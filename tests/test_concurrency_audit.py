"""The concurrency auditor (analysis/concurrency_audit.py) — both halves.

Half 1 (lock-discipline AST analysis): every finding kind fires on a
seeded-broken snippet and stays quiet on its fixed/waived twin; thread
discovery sees constructor spawns, Thread subclasses, and closure
producers; the `_THREAD_SHARED` declaration is enforced and
cross-checked; and the repo itself audits clean under the reference
contracts (the dogfood gate — the same scan `make concurrency-audit`
runs).

Half 2 (interleaving model checker): the faithful seqlock and
supervisor models PROVE their invariants over the full bounded
interleaving space, and all three seeded mutants are REFUTED by the
*intended* invariant with a concrete counterexample trace.

Pure stdlib — no jax anywhere in the module under test.
"""

import subprocess
import sys
import os

import pytest

from distributed_embeddings_tpu.analysis import concurrency_audit as ca

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _kinds(report):
    return {f.kind for f in report.findings}


# ================================================== Half 1: lock discipline


def test_drill_unguarded_shared_fires():
    rep = ca.audit_source(ca.DRILL_UNGUARDED_SRC, "<t>")
    assert "unguarded-shared" in _kinds(rep)
    # both the spawned loop and the caller-thread bump mutate _count
    f = next(f for f in rep.findings if f.kind == "unguarded-shared")
    assert "_count" in f.message


def test_guarded_twin_is_quiet():
    src = ca.DRILL_UNGUARDED_SRC.replace(
        "        while True:\n"
        "            self._count += 1",
        "        while True:\n"
        "            with self._lock:\n"
        "                self._count += 1"
    ).replace(
        "    def bump(self):\n"
        "        self._count += 1",
        "    def bump(self):\n"
        "        with self._lock:\n"
        "            self._count += 1")
    rep = ca.audit_source(src, "<t>")
    assert "unguarded-shared" not in _kinds(rep)


def test_thread_local_ok_waiver_silences():
    src = ca.DRILL_UNGUARDED_SRC.replace(
        "            self._count += 1",
        "            self._count += 1  # thread-local-ok: test waiver")
    # the caller-side bump() mutation is still unwaived -> still fires;
    # waive both sites and the finding disappears
    rep = ca.audit_source(src, "<t>")
    assert "unguarded-shared" in _kinds(rep)
    src = src.replace(
        "        self._count += 1",
        "        self._count += 1  # thread-local-ok: test waiver")
    rep = ca.audit_source(src, "<t>")
    assert "unguarded-shared" not in _kinds(rep)


def test_mutation_in_init_is_exempt():
    """Construction happens-before the spawn — __init__ writes are not
    cross-thread mutations."""
    src = ("import threading\n"
           "class W:\n"
           "    def __init__(self):\n"
           "        self._n = 0\n"
           "        threading.Thread(target=self._loop).start()\n"
           "    def _loop(self):\n"
           "        print(self._n)\n")
    rep = ca.audit_source(src, "<t>")
    assert "unguarded-shared" not in _kinds(rep)


def test_drill_lock_order_cycle_fires_and_waives():
    rep = ca.audit_source(ca.DRILL_CYCLE_SRC, "<t>")
    assert "lock-order-cycle" in _kinds(rep)
    assert rep.cycles  # the cycle itself is reported on the report too
    waived = ca.DRILL_CYCLE_SRC.replace(
        "            with self._a:",
        "            with self._a:  # lock-order-ok: test waiver")
    rep = ca.audit_source(waived, "<t>")
    assert "lock-order-cycle" not in _kinds(rep)


def test_consistent_order_is_quiet():
    src = ca.DRILL_CYCLE_SRC.replace(
        "    def ba(self):\n"
        "        with self._b:\n"
        "            with self._a:\n"
        "                pass\n",
        "    def ba(self):\n"
        "        with self._a:\n"
        "            with self._b:\n"
        "                pass\n")
    rep = ca.audit_source(src, "<t>")
    assert "lock-order-cycle" not in _kinds(rep)
    # the a->b edge is still recorded (order analysis ran)
    assert any(a.endswith("._a") and b.endswith("._b")
               for (a, b) in rep.lock_edges)


def test_rlock_self_reacquisition_is_not_a_cycle():
    """A locked caller calling a helper that re-acquires the same RLock
    is reentrant re-acquisition, not a deadlock — the serving.py
    _state_lock discipline."""
    src = ("import threading\n"
           "class S:\n"
           "    def __init__(self):\n"
           "        self._lock = threading.RLock()\n"
           "        self._n = 0\n"
           "    def _bump(self):\n"
           "        with self._lock:\n"
           "            self._n += 1\n"
           "    def submit(self):\n"
           "        with self._lock:\n"
           "            self._bump()\n")
    rep = ca.audit_source(src, "<t>")
    assert "lock-order-cycle" not in _kinds(rep)
    # the same shape on a plain Lock IS a self-deadlock
    rep = ca.audit_source(src.replace("RLock", "Lock"), "<t>")
    assert "lock-order-cycle" in _kinds(rep)


def test_drill_blocking_under_lock_fires_and_waives():
    rep = ca.audit_source(ca.DRILL_BLOCKING_SRC, "<t>")
    found = [f for f in rep.findings if f.kind == "blocking-under-lock"]
    assert found and "time.sleep" in found[0].message
    waived = ca.DRILL_BLOCKING_SRC.replace(
        "            time.sleep(0.1)",
        "            time.sleep(0.1)  # blocking-ok: test waiver")
    rep = ca.audit_source(waived, "<t>")
    assert "blocking-under-lock" not in _kinds(rep)


def test_blocking_bubbles_through_calls():
    """A locked caller invoking a method that blocks is the same hazard
    one hop removed — the interprocedural may-block pass."""
    src = ("import threading, time\n"
           "class P:\n"
           "    def __init__(self):\n"
           "        self._lock = threading.Lock()\n"
           "    def _slow(self):\n"
           "        time.sleep(1)\n"
           "    def poll(self):\n"
           "        with self._lock:\n"
           "            self._slow()\n")
    rep = ca.audit_source(src, "<t>")
    assert "blocking-under-lock" in _kinds(rep)


def test_timeout_bounded_calls_are_not_blocking():
    src = ("import threading\n"
           "class P:\n"
           "    def __init__(self):\n"
           "        self._lock = threading.Lock()\n"
           "        self._q = None\n"
           "    def poll(self):\n"
           "        with self._lock:\n"
           "            return self._q.get(timeout=0.1)\n")
    rep = ca.audit_source(src, "<t>")
    assert "blocking-under-lock" not in _kinds(rep)


def test_closure_producer_is_a_thread_of_control():
    """data.py idiom: a nested def handed to Thread(target=...) inside a
    method is its own thread of control, and instance attributes it
    mutates count as cross-thread."""
    src = ("import threading\n"
           "class DS:\n"
           "    def __init__(self):\n"
           "        self.n = 0\n"
           "    def run(self):\n"
           "        def producer():\n"
           "            self.n += 1\n"
           "        threading.Thread(target=producer).start()\n"
           "        self.n += 1\n")
    rep = ca.audit_source(src, "<t>")
    assert "unguarded-shared" in _kinds(rep)


def test_thread_shared_declaration_is_enforced():
    """A declared _THREAD_SHARED attr is held to the guard discipline
    even if discovery alone wouldn't see two mutating threads; a
    declared name that doesn't exist is contract drift."""
    src = ("import threading\n"
           "class D:\n"
           "    _THREAD_SHARED = ('_x',)\n"
           "    def __init__(self):\n"
           "        self._x = 0\n"
           "        threading.Thread(target=self._loop).start()\n"
           "    def _loop(self):\n"
           "        pass\n"
           "    def bump(self):\n"
           "        self._x += 1\n")
    rep = ca.audit_source(src, "<t>")
    assert "unguarded-shared" in _kinds(rep)
    ghost = src.replace("('_x',)", "('_x', '_ghost')")
    rep = ca.audit_source(ghost, "<t>")
    assert "contract-drift" in _kinds(rep)


def test_contract_drift_both_directions():
    src = ("import threading\n"
           "class W:\n"
           "    _THREAD_SHARED = ()\n"
           "    def start(self):\n"
           "        threading.Thread(target=self._loop).start()\n"
           "    def _loop(self):\n"
           "        pass\n")
    # spawning module with no contract at all -> drift
    rep = ca.audit_source(src, "<t>")
    assert "contract-drift" in _kinds(rep)
    # contract listing exactly the discovered thread -> clean
    c = ca.ConcurrencyContract(module="<t>", threads=("W._loop",))
    rep = ca.audit_source(src, "<t>", contract=c)
    assert "contract-drift" not in _kinds(rep)
    # contract naming a thread that no longer exists -> drift
    c = ca.ConcurrencyContract(module="<t>",
                               threads=("W._loop", "W._gone"))
    rep = ca.audit_source(src, "<t>", contract=c)
    assert "contract-drift" in _kinds(rep)


def test_watched_global_mutation_requires_module_lock():
    src = ("import threading\n"
           "_lock = threading.Lock()\n"
           "_counters = {}\n"
           "def bump(k):\n"
           "    _counters[k] = _counters.get(k, 0) + 1\n")
    c = ca.ConcurrencyContract(module="<t>", threads=(),
                               shared_globals=("_counters",))
    rep = ca.audit_source(src, "<t>", contract=c)
    assert "global-unguarded" in _kinds(rep)
    guarded = src.replace(
        "    _counters[k] = _counters.get(k, 0) + 1",
        "    with _lock:\n"
        "        _counters[k] = _counters.get(k, 0) + 1")
    rep = ca.audit_source(guarded, "<t>", contract=c)
    assert "global-unguarded" not in _kinds(rep)


def test_repo_audits_clean_under_reference_contracts():
    """Dogfood: the serving plane ships with zero unwaived findings and
    an acyclic lock-order graph (the make concurrency-audit gate)."""
    rep = ca.audit_repo()
    assert rep.findings == [], "\n".join(str(f) for f in rep.findings)
    assert rep.cycles == []
    # the contracted inventory is discovered, not asserted into being
    assert "parallel/supervisor.py" in rep.inventory
    assert "parallel/serving.py" in rep.inventory
    assert "utils/data.py" in rep.inventory
    assert rep.modules > 40


def test_report_round_trips_to_dict():
    rep = ca.audit_source(ca.DRILL_CYCLE_SRC, "<t>")
    d = rep.to_dict()
    assert d["modules"] == 1
    assert any(f["kind"] == "lock-order-cycle" for f in d["findings"])
    assert d["cycles"]


# ================================================ Half 2: model checking


def test_seqlock_faithful_proves():
    res = ca.prove(ca.seqlock_model())
    assert res.ok, str(res)
    assert res.states > 100 and res.transitions > res.states
    assert "PROVED" in str(res)


def test_seqlock_no_crc_mutant_refuted_by_torn_read():
    res = ca.refute(ca.seqlock_model("no_crc"))
    assert not res.ok
    assert res.violated == "no-torn-accept"
    assert res.trace  # a concrete interleaving, not just "violated"


def test_seqlock_stamps_swapped_mutant_refuted():
    res = ca.refute(ca.seqlock_model("stamps_swapped"))
    assert not res.ok
    assert res.violated == "stamp-honesty"


def test_supervisor_faithful_proves():
    res = ca.prove(ca.supervisor_model())
    assert res.ok, str(res)
    assert res.states > 1000


def test_supervisor_deadline_mutant_refuted():
    res = ca.refute(ca.supervisor_model("deadline_off_by_one"))
    assert not res.ok
    assert res.violated == "hang-detected-within-deadline"
    assert "hang" in res.trace


def test_unknown_mutant_rejected():
    with pytest.raises(ValueError):
        ca.seqlock_model("not_a_mutant")
    with pytest.raises(ValueError):
        ca.supervisor_model("not_a_mutant")


def test_explore_bounds_state_space():
    with pytest.raises(RuntimeError, match="exceeded"):
        ca.explore(ca.supervisor_model(), max_states=50)


def test_run_drills_all_green():
    assert ca.run_drills() == []


# ======================================================== the CLI gate


def test_cli_strict_green():
    """End-to-end: the exact invocation make concurrency-audit runs."""
    r = subprocess.run(
        [sys.executable, "tools/concurrency_audit.py", "--strict"],
        cwd=REPO, capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, r.stdout + r.stderr
    assert "concurrency_audit: OK" in r.stdout
    assert "PROVED" in r.stdout
    assert "refuted" in r.stdout.lower()

"""Optimized-HLO pass census (analysis/hlo_census.py) + the SGD dedup cut.

The acceptance contract of ISSUE 7: the compiled hybrid step's ops
attribute exactly to their ``obs.scope`` phases (dense / ragged /
row-sliced / MpInputs configs); the ``dedup`` phase compiles to ZERO row
ops under SparseSGD and to the pinned sort+segment-sum budget under the
stateful family on the dedup-regime shapes; seeded violations (an extra
gather pass, a float convert round-trip) are flagged by the declarative
PassBudget contracts; and an N-step SparseSGD trajectory is BITWISE
identical with and without the dedup pass (``DETPU_SGD_DEDUP=1``) on the
8-virtual-device mesh. Census runs compile abstractly (lower+compile,
nothing executes) under JAX_PLATFORMS=cpu (conftest); only the bitwise
equivalence test dispatches real steps.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh

from distributed_embeddings_tpu.analysis import (
    CensusError, PassBudget, census_of_text, census_step_fn,
    census_train_step, default_contracts)
from distributed_embeddings_tpu.parallel import (
    DistributedEmbedding, SparseAdagrad, SparseSGD, init_hybrid_state,
    make_hybrid_train_step)
from tools._profcommon import build_case

WORLD = 8
B = 16


@pytest.fixture(scope="module")
def mesh():
    devs = jax.devices()
    assert len(devs) >= WORLD, "conftest should force 8 CPU devices"
    return Mesh(np.array(devs[:WORLD]), ("data",))


def _census(config, opt, world, mesh=None, **kw):
    de, cats, batch_tree, dense_params, loss_fn = build_case(
        config, world, B)
    return census_train_step(
        de, loss_fn, optax.sgd(0.5), opt, cats, batch_tree, mesh=mesh,
        lr_schedule=0.3, dense_params=dense_params, **kw)


# --------------------------------------------------------------- the parser


HANDWRITTEN = """\
HloModule jit_step

%fused_computation.1 (p0: f32[64,8], p1: s32[16]) -> f32[16,8] {
  %p0 = f32[64,8]{1,0} parameter(0)
  %p1 = s32[16]{0} parameter(1)
  ROOT %gather.1 = f32[16,8]{1,0} gather(f32[64,8]{1,0} %p0, s32[16]{0} %p1), metadata={op_name="jit(step)/detpu/lookup_w8_d/detpu/packed_gather/gather"}
}

ENTRY %main (a: f32[64,8], ids: s32[16]) -> f32[64,8] {
  %a = f32[64,8]{1,0} parameter(0)
  %ids = s32[16]{0} parameter(1)
  %fusion.1 = f32[16,8]{1,0} fusion(f32[64,8]{1,0} %a, s32[16]{0} %ids), kind=kLoop, calls=%fused_computation.1, metadata={op_name="jit(step)/detpu/lookup_w8_d/detpu/packed_gather/gather"}
  %sort.1 = s32[16]{0} sort(s32[16]{0} %ids), dimensions={0}, metadata={op_name="jit(step)/detpu/sparse_apply_w8/detpu/dedup/sort"}
  %convert.1 = bf16[16,8]{1,0} convert(f32[16,8]{1,0} %fusion.1), metadata={op_name="jit(step)/detpu/sparse_apply_w8/convert_element_type"}
  %convert.2 = f32[16,8]{1,0} convert(bf16[16,8]{1,0} %convert.1), metadata={op_name="jit(step)/detpu/sparse_apply_w8/convert_element_type"}
  %all-to-all.1 = (f32[2,8]{1,0}, f32[2,8]{1,0}) all-to-all(f32[2,8]{1,0} %fusion.1, f32[2,8]{1,0} %fusion.1), metadata={op_name="jit(step)/detpu/out_all_to_all/all_to_all"}
  ROOT %while.1 = f32[64,8]{1,0} while(f32[64,8]{1,0} %a), condition=%cond, body=%body, metadata={op_name="jit(step)/detpu/sparse_apply_w8/scatter-add"}
}
"""


def test_parser_on_handwritten_hlo():
    """Pure text -> report: opcode normalization (while->scatter on the
    CPU lowering), tuple shapes, scope-path attribution, convert pairs,
    and the float round-trip metric — no compilation involved."""
    rep = census_of_text(HANDWRITTEN, label="hand", world=2)
    # the gather appears twice: once as the fused computation's body
    # instruction, once is the fusion wrapper (counted as fusion, not
    # gather)
    assert rep.passes("*packed_gather", "gather") == 1
    assert rep.phases["lookup_w8_d/packed_gather"].fusions == 1
    assert rep.passes("dedup", "sort") == 1
    assert rep.passes("sparse_apply_w8", "scatter") == 1  # the while
    assert rep.passes("out_all_to_all", "all_to_all") == 1
    sa = rep.phases["sparse_apply_w8"]
    assert sa.convert_pairs == {"f32->bf16": 1, "bf16->f32": 1}
    assert sa.roundtrips() == 1
    assert rep.passes("sparse_apply_*", "convert_roundtrip") == 1
    # contracts: the seeded round-trip and a dedup budget both fire
    rep.check([PassBudget("sparse_apply_*", "convert_roundtrip", 0),
               PassBudget("dedup", "sort", 0, reason="sgd")])
    assert len(rep.violations) == 2
    with pytest.raises(CensusError, match="pass budget"):
        rep.raise_on_violations()
    # renderings stay consistent with the dataclass
    md = rep.markdown()
    assert "| phase |" in md and "`dedup`" not in md  # leaf rides its path
    assert "lookup_w8_d/packed_gather" in md
    js = rep.to_json()
    assert js["ok"] is False
    assert js["phases"]["sparse_apply_w8/dedup"]["sort"] == 1


def test_min_passes_underrun_flagged():
    rep = census_of_text(HANDWRITTEN)
    rep.check([PassBudget("dedup", "gather", min_passes=1, max_passes=9)])
    assert any("underrun" in v for v in rep.violations)


def test_per_path_min_fires_when_phase_is_gone():
    # a renamed/dropped scope must trip a per_path min contract, not
    # vacuously match nothing and report ok
    rep = census_of_text(HANDWRITTEN)
    rep.check([PassBudget("no_such_phase", "sort", min_passes=1,
                          max_passes=9, per_path=True)])
    assert any("underrun" in v for v in rep.violations)


TPU_LAYOUT = """\
HloModule jit_step

ENTRY %main (a: f32[64,8], ids: s32[16]) -> f32[16,8] {
  %a = f32[64,8]{1,0:T(8,128)} parameter(0)
  %ids = s32[16]{0:T(256)} parameter(1)
  ROOT %gather.1 = f32[16,8]{1,0:T(8,128)S(1)} gather(f32[64,8]{1,0:T(8,128)} %a, s32[16]{0:T(256)} %ids), metadata={op_name="jit(step)/detpu/lookup_w8_d/detpu/packed_gather/gather"}
}
"""


def test_parser_on_tpu_layout_shapes():
    """Post-layout-assignment TPU HLO carries tiling/memory-space inside
    the layout braces (``{1,0:T(8,128)S(1)}``) — the parser must not
    silently skip those instruction lines (an unmatched line means the
    pass-budget gate passes vacuously on the real backend)."""
    rep = census_of_text(TPU_LAYOUT)
    assert rep.total_instructions == 3
    assert rep.passes("*packed_gather", "gather") == 1


def test_unparseable_module_fails_loudly():
    """census_step_fn must never return an empty census: zero parsed
    instructions means THIS backend's HLO text defeated the parser and
    every downstream budget would hold vacuously."""

    class _Fake:
        def lower(self, *a):
            return self

        def compile(self):
            return self

        def as_text(self):
            return "not hlo at all\n"

    with pytest.raises(CensusError, match="parsed 0 instructions"):
        census_step_fn(_Fake(), ())


def test_min_only_contract_is_floor_not_cap():
    # max_passes defaults to unbounded, so a floor-only contract guards a
    # pass's existence without also capping it
    rep = census_of_text(HANDWRITTEN)
    rep.check([PassBudget("dedup", "sort", min_passes=1)])
    assert not rep.violations


def test_min_greater_than_max_rejected():
    with pytest.raises(ValueError, match="can never hold"):
        PassBudget("dedup", "sort", max_passes=0, min_passes=1)


def test_gated_kinds_in_sync_with_compare_bench():
    # compare_bench must stay importable without jax, so it duplicates
    # the tuple; this is the sync the comments on both sides promise
    from tools import compare_bench

    from distributed_embeddings_tpu.analysis import hlo_census
    assert compare_bench.PHASE_GATE_KINDS == hlo_census.GATED_KINDS


# ------------------------------------------------- phase attribution (mesh)


@pytest.mark.parametrize("config", ["dense", "ragged", "row_sliced"])
def test_phase_attribution_8dev(config, mesh):
    """Every reference config compiles with its ops attributed to the
    expected scope paths: 3 all-to-all passes in their exchange phases,
    gathers confined to the lookup groups (<= 2 per group: the packed
    gather + its lane extract), forward and apply phases present."""
    rep = _census(config, SparseAdagrad(), WORLD, mesh=mesh)
    assert rep.ok, rep.violations
    assert rep.passes("id_all_to_all", "all_to_all") == 1
    assert rep.passes("out_all_to_all", "all_to_all") == 1
    assert rep.passes("grad_all_to_all", "all_to_all") == 1
    assert rep.passes("*", "all_to_all") == 3
    assert rep.passes("*lookup_*", "gather") >= 1
    assert any(p.startswith("embedding_forward") for p in rep.phases)
    assert any("sparse_apply" in p for p in rep.phases)
    rep.check([PassBudget("*lookup_*", "gather", max_passes=2,
                          per_path=True)])
    assert rep.ok, rep.violations


def test_mp_inputs_phase_attribution(mesh):
    """dp_input=False (MpInputs) skips the id exchange: the census shows
    0 id-exchange all-to-all passes and keeps the out/grad pair."""
    configs = [{"input_dim": 20 + 6 * i, "output_dim": 4,
                "combiner": ["sum", None, "mean"][i % 3]}
               for i in range(10)]
    de = DistributedEmbedding(configs, world_size=WORLD, dp_input=False)
    rng = np.random.default_rng(0)
    inputs = []
    for cfg in configs:
        hot = 1 if cfg["combiner"] is None else 3
        shape = (B,) if hot == 1 else (B, hot)
        inputs.append(rng.integers(0, cfg["input_dim"], size=shape
                                   ).astype(np.int32))
    mp = de.pack_mp_inputs(inputs)

    def loss_fn(dp, emb_outs, batch):
        n, y = batch
        x = jnp.concatenate([e.reshape(e.shape[0], -1) for e in emb_outs],
                            axis=1)
        return jnp.mean((x @ dp["w"] + n @ dp["v"] - y) ** 2)

    cols = sum(int(c["output_dim"]) for c in configs)
    dense_params = {"w": jax.ShapeDtypeStruct((cols, 1), jnp.float32),
                    "v": jax.ShapeDtypeStruct((3, 1), jnp.float32)}
    batch_tree = (jax.ShapeDtypeStruct((B, 3), jnp.float32),
                  jax.ShapeDtypeStruct((B, 1), jnp.float32))
    rep = census_train_step(de, loss_fn, optax.sgd(0.5), SparseAdagrad(),
                            mp, batch_tree, mesh=mesh,
                            dense_params=dense_params)
    assert rep.ok, rep.violations
    assert rep.passes("id_all_to_all", "all_to_all") == 0
    assert rep.passes("*", "all_to_all") == 2


# ----------------------------------------------------- the dedup pass budget


def test_sgd_dedup_budget_zero_8dev(mesh):
    """The pass cut, statically verified: on the dedup-regime shapes the
    SparseSGD build compiles a completely empty dedup phase (the default
    contracts enforce it; needs_dedup=False)."""
    rep = _census("bigvocab", SparseSGD(), WORLD, mesh=mesh)
    assert rep.ok, rep.violations
    for kind in ("sort", "scatter", "cumsum", "gather"):
        assert rep.passes("dedup", kind) == 0, kind
    assert not SparseSGD.needs_dedup


def test_adagrad_dedup_budget_unchanged_8dev(mesh):
    """The stateful family keeps its dedup pass on the same shapes —
    pinned exactly (1 sort + 2 segment-sum scatters per width group; one
    w8 group here), so a refactor that silently loses or duplicates the
    pass must update this number deliberately."""
    rep = _census("bigvocab", SparseAdagrad(), WORLD, mesh=mesh)
    assert rep.ok, rep.violations
    assert SparseAdagrad.needs_dedup
    assert rep.passes("dedup", "sort") == 1
    assert rep.passes("dedup", "scatter") == 2
    rep.check([PassBudget("dedup", "sort", max_passes=8, min_passes=1)])
    assert rep.ok, rep.violations


# ------------------------------------------------------- seeded violations


def test_seeded_extra_gather_pass_flagged():
    """A smuggled extra gather inside a lookup-group scope exceeds the
    <=2-per-group budget and fails --strict (the ISSUE drill)."""

    def step(slab, ids):
        with jax.named_scope("detpu/lookup_w8_d"):
            with jax.named_scope("detpu/packed_gather"):
                a = jnp.take(slab, ids, axis=0, mode="clip")
                b = jnp.take(slab, ids + 1, axis=0, mode="clip")
                c = jnp.take(slab, ids + 2, axis=0, mode="clip")
        return a.sum() + b.sum() + c.sum()

    rep = census_step_fn(
        jax.jit(step),
        (jax.ShapeDtypeStruct((100, 8), jnp.float32),
         jax.ShapeDtypeStruct((16,), jnp.int32)),
        label="seeded_gather",
        contracts=[PassBudget("*lookup_*", "gather", max_passes=2,
                              per_path=True)])
    assert not rep.ok
    assert any("gather" in v and "budget" in v for v in rep.violations), \
        rep.violations


def test_seeded_convert_roundtrip_flagged():
    """A float32 value squeezed through bf16 and back inside the apply
    phase is a silent-precision-loss hazard the census flags."""

    def step(x):
        with jax.named_scope("detpu/sparse_apply_w8"):
            y = x.astype(jnp.bfloat16).astype(jnp.float32)
            return (y * 2.0).sum()

    rep = census_step_fn(
        jax.jit(step), (jax.ShapeDtypeStruct((64, 8), jnp.float32),),
        label="seeded_roundtrip",
        contracts=[PassBudget("sparse_apply_*", "convert_roundtrip", 0)])
    assert rep.passes("sparse_apply_*", "convert_roundtrip") >= 1
    assert not rep.ok


# ---------------------------------------- the dedup-skip bitwise equivalence


def _grid(a, q=6):
    """Quantize onto the 2**-q grid so every update addition in the test
    is exact (no rounding anywhere => float addition re-associates freely
    => with/without dedup MUST be bitwise identical, not just close)."""
    return jnp.round(a * (1 << q)) / (1 << q)


def _bitwise_case(mesh, key=0):
    configs = [{"input_dim": 32, "output_dim": 8, "combiner": None}
               for _ in range(8)]
    de = DistributedEmbedding(configs, world_size=WORLD)

    def loss_fn(dp, emb_outs, batch):
        del batch
        x = jnp.concatenate([e.reshape(e.shape[0], -1) for e in emb_outs],
                            axis=1)
        # linear loss => cotangents are dp["w"] entries (grid values)
        return jnp.sum(x @ dp["w"]) * (2.0 ** -6)

    # dense side frozen (lr 0): w must stay on its coarse grid, or the
    # emb cotangents (= w * 2**-6) would gain mantissa bits every step
    # and the slab additions would start rounding — the exactness the
    # bitwise assertion rests on
    tx = optax.sgd(0.0)
    # duplicate-heavy ids: 16 draws from 8 distinct rows per table/step
    rng = np.random.default_rng(7)
    steps = [
        ([jnp.asarray(rng.integers(0, 8, size=(B,)), jnp.int32)
          for _ in configs],
         (jnp.zeros((B, 1), jnp.float32),))
        for _ in range(8)]
    w_np = rng.normal(size=(64, 1)).astype(np.float32)

    def fresh_state():
        # fresh arrays every run: the step donates its whole state, so a
        # buffer shared between the A and B runs would be deleted by A
        dense_params = {"w": _grid(jnp.asarray(w_np), q=3)}
        st = init_hybrid_state(de, SparseSGD(), dense_params, tx,
                               jax.random.key(key), mesh=mesh)
        return st._replace(emb_params=jax.tree.map(_grid, st.emb_params))

    return de, loss_fn, tx, steps, fresh_state


def test_sgd_trajectory_bitwise_equal_with_and_without_dedup(
        mesh, monkeypatch):
    """ISSUE 7 acceptance: 8 SparseSGD steps on the 8-device mesh, run
    with the dedup pass compiled OUT (default) and compiled IN
    (DETPU_SGD_DEDUP=1), end in bitwise-identical states. The data is
    engineered onto a power-of-two grid so every addition is exact —
    equality then proves the two programs apply the same updates to the
    same rows (any dropped/duplicated/misrouted id would break it), with
    no float-reassociation noise to hide behind."""
    de, loss_fn, tx, steps, fresh_state = _bitwise_case(mesh)

    def run():
        step = make_hybrid_train_step(de, loss_fn, tx, SparseSGD(),
                                      mesh=mesh, lr_schedule=0.5)
        state = fresh_state()
        for cats, batch in steps:
            _, state = step(state, cats, batch)
        return state

    monkeypatch.delenv("DETPU_SGD_DEDUP", raising=False)
    plain = run()
    monkeypatch.setenv("DETPU_SGD_DEDUP", "1")
    forced = run()

    for pa, pb in ((plain.emb_params, forced.emb_params),
                   (plain.dense_params, forced.dense_params)):
        la = jax.tree_util.tree_leaves_with_path(pa)
        lb = jax.tree_util.tree_leaves(pb)
        assert len(la) == len(lb)
        for (path, a), b in zip(la, lb):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b),
                err_msg=f"leaf {jax.tree_util.keystr(path)} diverged")


def test_sgd_dedup_escape_hatch_changes_the_program(mesh, monkeypatch):
    """The A/B knob must actually flip the compiled program: under
    DETPU_SGD_DEDUP=1 the SparseSGD step's dedup phase is non-empty
    (sort present), while the default build keeps it at zero (tested
    above). Static census only — nothing executes."""
    de, loss_fn, tx, _, fresh_state = _bitwise_case(mesh)
    state = jax.eval_shape(fresh_state)
    cats = [jax.ShapeDtypeStruct((B,), jnp.int32) for _ in range(8)]
    batch = (jax.ShapeDtypeStruct((B, 1), jnp.float32),)
    monkeypatch.setenv("DETPU_SGD_DEDUP", "1")
    rep = census_train_step(
        de, loss_fn, tx, SparseSGD(), cats, batch, mesh=mesh,
        lr_schedule=0.5, state=state, contracts=[],
        label="sgd_dedup_forced")
    assert rep.passes("dedup", "sort") >= 1
    # and default_contracts must NOT demand an empty dedup phase while
    # the hatch is set (the A/B build is a legitimate program)
    assert default_contracts(SparseSGD()) == []

"""Atomic, self-validating checkpoints (utils/checkpoint.py, round 6).

The reference has no torn-write story at all; here a checkpoint truncated
mid-write must be DETECTED (CRC manifest) and the previous valid
checkpoint restored, and a process killed inside the write path
(``DETPU_FAULT=die:checkpoint_write``) must leave the on-disk checkpoint
whole — the staging-swap commit means a reader never observes a partial
state at the checkpoint path.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_embeddings_tpu.parallel import (
    DistributedEmbedding, SparseAdagrad, init_hybrid_state)
from distributed_embeddings_tpu.utils import (
    previous_checkpoint_path, restore_train_state, runtime,
    save_train_state, verify_checkpoint)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tiny():
    configs = [{"input_dim": 12 + 3 * i, "output_dim": 4} for i in range(3)]
    de = DistributedEmbedding(configs, world_size=1)
    emb_opt = SparseAdagrad()
    dp = {"w": jnp.ones((12, 1), jnp.float32)}
    tx = optax.sgd(0.1)
    state = init_hybrid_state(de, emb_opt, dp, tx, jax.random.key(0))
    return de, emb_opt, dp, tx, state


def _bump(state, delta=1.0):
    return state._replace(
        emb_params=jax.tree.map(lambda a: a + delta, state.emb_params),
        step=state.step + 1)


def test_manifest_records_crcs_and_verifies(tmp_path):
    de, emb_opt, dp, tx, state = _tiny()
    path = str(tmp_path / "ckpt")
    save_train_state(path, de, state)
    meta = verify_checkpoint(path)  # must not raise
    files = meta["files"]
    assert "tables/table_000.npy" in files
    assert "dense.msgpack" in files
    assert all(isinstance(v, int) for v in files.values())
    # no stray staging dir after a successful commit
    assert not os.path.exists(path + ".staging")


def test_truncated_file_detected_no_fallback_raises(tmp_path):
    de, emb_opt, dp, tx, state = _tiny()
    path = str(tmp_path / "ckpt")
    save_train_state(path, de, state)
    victim = os.path.join(path, "tables", "table_001.npy")
    with open(victim, "r+b") as f:  # truncate mid-file: torn write
        f.truncate(os.path.getsize(victim) // 2)
    with pytest.raises(runtime.CheckpointCorrupt, match="table_001"):
        verify_checkpoint(path)
    with pytest.raises(runtime.CheckpointCorrupt):
        restore_train_state(path, de, emb_opt, dp, tx)  # no .prev exists


def test_torn_checkpoint_falls_back_to_previous_valid(tmp_path, caplog):
    """Acceptance: a checkpoint truncated mid-write is caught by CRC
    validation on load and the previous valid checkpoint is restored."""
    de, emb_opt, dp, tx, state = _tiny()
    path = str(tmp_path / "ckpt")
    save_train_state(path, de, state)  # v1
    v1_tables = [np.asarray(t) for t in de.get_weights(state.emb_params)]
    state2 = _bump(state)
    save_train_state(path, de, state2)  # v2; v1 parked at <path>.prev
    assert os.path.isdir(previous_checkpoint_path(path))

    # corrupt v2 (bit flip, not just truncation)
    victim = os.path.join(path, "tables", "table_000.npy")
    data = bytearray(open(victim, "rb").read())
    data[-1] ^= 0xFF
    with open(victim, "wb") as f:
        f.write(bytes(data))

    with caplog.at_level("WARNING"):
        restored = restore_train_state(path, de, emb_opt, dp, tx)
    assert any("falling back" in r.message for r in caplog.records)
    got = [np.asarray(t) for t in de.get_weights(restored.emb_params)]
    for a, b in zip(got, v1_tables):  # v1, NOT the torn v2
        np.testing.assert_array_equal(a, b)
    assert int(restored.step) == int(state.step)


def test_save_is_atomic_under_injected_death(tmp_path):
    """DETPU_FAULT=die:checkpoint_write kills the child inside the second
    save's write path; the committed checkpoint must still be v1, whole."""
    path = str(tmp_path / "ckpt")
    code = f"""
import os, sys
sys.path.insert(0, {_REPO!r})
import jax, optax, numpy as np, jax.numpy as jnp
jax.config.update('jax_platforms', 'cpu')
from distributed_embeddings_tpu.parallel import (
    DistributedEmbedding, SparseAdagrad, init_hybrid_state)
from distributed_embeddings_tpu.utils import save_train_state
configs = [{{"input_dim": 12 + 3 * i, "output_dim": 4}} for i in range(3)]
de = DistributedEmbedding(configs, world_size=1)
st = init_hybrid_state(de, SparseAdagrad(),
                       {{"w": jnp.ones((12, 1), jnp.float32)}},
                       optax.sgd(0.1), jax.random.key(0))
save_train_state({path!r}, de, st)
print("T0SUM", float(np.asarray(de.get_weights(st.emb_params)[0]).sum()))
os.environ["DETPU_FAULT"] = "die:checkpoint_write"
st2 = st._replace(emb_params=jax.tree.map(lambda a: a + 1.0, st.emb_params))
save_train_state({path!r}, de, st2)
print("UNREACHABLE")
"""
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 17, proc.stderr[-2000:]
    assert "UNREACHABLE" not in proc.stdout
    t0sum = float(proc.stdout.split("T0SUM", 1)[1].split()[0])

    verify_checkpoint(path)  # still whole
    de, emb_opt, dp, tx, _ = _tiny()
    restored = restore_train_state(path, de, emb_opt, dp, tx)
    got = float(np.asarray(de.get_weights(restored.emb_params)[0]).sum())
    assert got == pytest.approx(t0sum)  # v1 values, not the half-saved v2


def test_pre_crc_checkpoints_still_restore(tmp_path):
    """Old-format checkpoints (no ``files`` manifest) predate validation:
    they load with a debug note instead of failing."""
    de, emb_opt, dp, tx, state = _tiny()
    path = str(tmp_path / "ckpt")
    save_train_state(path, de, state)
    meta_path = os.path.join(path, "meta.json")
    with open(meta_path) as f:
        meta = json.load(f)
    del meta["files"]
    with open(meta_path, "w") as f:
        json.dump(meta, f)
    restored = restore_train_state(path, de, emb_opt, dp, tx)
    got = [np.asarray(t) for t in de.get_weights(restored.emb_params)]
    want = [np.asarray(t) for t in de.get_weights(state.emb_params)]
    for a, b in zip(got, want):
        np.testing.assert_array_equal(a, b)

"""Atomic, self-validating checkpoints (utils/checkpoint.py, round 6).

The reference has no torn-write story at all; here a checkpoint truncated
mid-write must be DETECTED (CRC manifest) and the previous valid
checkpoint restored, and a process killed inside the write path
(``DETPU_FAULT=die:checkpoint_write``) must leave the on-disk checkpoint
whole — the staging-swap commit means a reader never observes a partial
state at the checkpoint path.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_embeddings_tpu.parallel import (
    DistributedEmbedding, SparseAdagrad, init_hybrid_state)
from distributed_embeddings_tpu.utils import (
    previous_checkpoint_path, restore_train_state, ring_dir, ring_entries,
    rollback_candidates, runtime, save_train_state, verify_checkpoint)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tiny():
    configs = [{"input_dim": 12 + 3 * i, "output_dim": 4} for i in range(3)]
    de = DistributedEmbedding(configs, world_size=1)
    emb_opt = SparseAdagrad()
    dp = {"w": jnp.ones((12, 1), jnp.float32)}
    tx = optax.sgd(0.1)
    state = init_hybrid_state(de, emb_opt, dp, tx, jax.random.key(0))
    return de, emb_opt, dp, tx, state


def _bump(state, delta=1.0):
    return state._replace(
        emb_params=jax.tree.map(lambda a: a + delta, state.emb_params),
        step=state.step + 1)


def test_manifest_records_crcs_and_verifies(tmp_path):
    de, emb_opt, dp, tx, state = _tiny()
    path = str(tmp_path / "ckpt")
    save_train_state(path, de, state)
    meta = verify_checkpoint(path)  # must not raise
    files = meta["files"]
    assert "tables/table_000.npy" in files
    assert "dense.msgpack" in files
    assert all(isinstance(v, int) for v in files.values())
    # no stray staging dir after a successful commit
    assert not os.path.exists(path + ".staging")


def test_truncated_file_detected_no_fallback_raises(tmp_path):
    de, emb_opt, dp, tx, state = _tiny()
    path = str(tmp_path / "ckpt")
    save_train_state(path, de, state)
    victim = os.path.join(path, "tables", "table_001.npy")
    with open(victim, "r+b") as f:  # truncate mid-file: torn write
        f.truncate(os.path.getsize(victim) // 2)
    with pytest.raises(runtime.CheckpointCorrupt, match="table_001"):
        verify_checkpoint(path)
    with pytest.raises(runtime.CheckpointCorrupt):
        restore_train_state(path, de, emb_opt, dp, tx)  # no .prev exists


def test_torn_checkpoint_falls_back_to_previous_valid(tmp_path, caplog):
    """Acceptance: a checkpoint truncated mid-write is caught by CRC
    validation on load and the previous valid checkpoint is restored."""
    de, emb_opt, dp, tx, state = _tiny()
    path = str(tmp_path / "ckpt")
    save_train_state(path, de, state)  # v1
    v1_tables = [np.asarray(t) for t in de.get_weights(state.emb_params)]
    state2 = _bump(state)
    save_train_state(path, de, state2)  # v2; v1 parked at <path>.prev
    assert os.path.isdir(previous_checkpoint_path(path))

    # corrupt v2 (bit flip, not just truncation)
    victim = os.path.join(path, "tables", "table_000.npy")
    data = bytearray(open(victim, "rb").read())
    data[-1] ^= 0xFF
    with open(victim, "wb") as f:
        f.write(bytes(data))

    with caplog.at_level("WARNING"):
        restored = restore_train_state(path, de, emb_opt, dp, tx)
    assert any("falling back" in r.message for r in caplog.records)
    got = [np.asarray(t) for t in de.get_weights(restored.emb_params)]
    for a, b in zip(got, v1_tables):  # v1, NOT the torn v2
        np.testing.assert_array_equal(a, b)
    assert int(restored.step) == int(state.step)


def test_corrupt_ckpt_fault_is_caught_by_manifest(tmp_path, monkeypatch):
    """DETPU_FAULT=corrupt@ckpt flips bytes in a just-COMMITTED shard
    file — the manifest was written from the pristine bytes, so CRC
    validation must catch the divergence, and restore must fall back to
    the previous valid checkpoint."""
    de, emb_opt, dp, tx, state = _tiny()
    path = str(tmp_path / "ckpt")
    save_train_state(path, de, state)  # v1, clean
    v1_tables = [np.asarray(t) for t in de.get_weights(state.emb_params)]
    monkeypatch.setenv("DETPU_FAULT", "corrupt@ckpt")
    save_train_state(path, de, _bump(state))  # v2, corrupted post-commit
    monkeypatch.delenv("DETPU_FAULT")
    with pytest.raises(runtime.CheckpointCorrupt, match="CRC mismatch"):
        verify_checkpoint(path)
    restored = restore_train_state(path, de, emb_opt, dp, tx)  # .prev
    got = [np.asarray(t) for t in de.get_weights(restored.emb_params)]
    for a, b in zip(got, v1_tables):
        np.testing.assert_array_equal(a, b)
    assert int(restored.step) == int(state.step)


def test_driver_continues_past_corrupted_checkpoint(tmp_path, monkeypatch):
    """End-to-end: a resilient run whose LAST checkpoint was silently
    corrupted on disk must, on restart, detect the corruption, fall back
    to ``<path>.prev``, replay deterministically, and finish — no manual
    intervention."""
    import optax as _optax

    from distributed_embeddings_tpu.parallel import run_resilient
    from distributed_embeddings_tpu.parallel.trainer import (
        make_hybrid_train_step)

    de, emb_opt, dp, tx, state0 = _tiny()

    def loss_fn(dparams, outs, batch):
        x = sum(jnp.mean(o) for o in outs) * jnp.mean(dparams["w"])
        return (x - jnp.mean(batch)) ** 2

    step = make_hybrid_train_step(de, loss_fn, tx, emb_opt,
                                  with_metrics=False)

    def data(start):
        for i in range(start, 8):
            rng = np.random.default_rng(700 + i)
            cats = [jnp.asarray(rng.integers(0, 12 + 3 * t, 8), jnp.int32)
                    for t in range(3)]
            yield cats, jnp.asarray(rng.normal(size=(8,)), jnp.float32)

    ck = str(tmp_path / "ck")
    common = dict(de=de, checkpoint_dir=ck, checkpoint_every_steps=2,
                  resume=True, emb_optimizer=emb_opt, dense_tx=tx)
    # leg 1 (clean): checkpoints at 2 and 4 -> ck@4, .prev@4-cadence
    r1 = run_resilient(step, state0, data, until_step=4, **common)
    assert r1.step == 4
    # leg 2: the cadence save at step 6 lands corrupted on disk
    # (save_on_exit off so exactly ONE save corrupts — the clean step-4
    # checkpoint stays parked at .prev, as in a real bit-rot event)
    monkeypatch.setenv("DETPU_FAULT", "corrupt@ckpt")
    r2 = run_resilient(step, r1.state, data, until_step=6,
                       save_on_exit=False, **common)
    monkeypatch.delenv("DETPU_FAULT")
    assert r2.step == 6
    with pytest.raises(runtime.CheckpointCorrupt):
        verify_checkpoint(ck)
    # leg 3 (restart after the "bit rot"): falls back to .prev, replays,
    # and completes the run
    st3 = _fresh_state(de, emb_opt, tx)
    r3 = run_resilient(step, st3, data, **common)
    assert r3.step == 8 and not r3.preempted
    verify_checkpoint(ck)  # the final save is whole again
    # trajectory check: an uninterrupted run ends at the same state
    ref = run_resilient(step, _fresh_state(de, emb_opt, tx), data, de=de)
    got = de.get_weights(r3.state.emb_params)
    want = de.get_weights(ref.state.emb_params)
    for a, b in zip(got, want):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def _fresh_state(de, emb_opt, tx):
    # rebuilt from scratch (the step donates its inputs, so earlier legs'
    # buffers are deleted); same init key as _tiny -> same initial state
    return init_hybrid_state(de, emb_opt,
                             {"w": jnp.ones((12, 1), jnp.float32)}, tx,
                             jax.random.key(0))


def test_save_is_atomic_under_injected_death(tmp_path):
    """DETPU_FAULT=die:checkpoint_write kills the child inside the second
    save's write path; the committed checkpoint must still be v1, whole."""
    path = str(tmp_path / "ckpt")
    code = f"""
import os, sys
sys.path.insert(0, {_REPO!r})
import jax, optax, numpy as np, jax.numpy as jnp
jax.config.update('jax_platforms', 'cpu')
from distributed_embeddings_tpu.parallel import (
    DistributedEmbedding, SparseAdagrad, init_hybrid_state)
from distributed_embeddings_tpu.utils import save_train_state
configs = [{{"input_dim": 12 + 3 * i, "output_dim": 4}} for i in range(3)]
de = DistributedEmbedding(configs, world_size=1)
st = init_hybrid_state(de, SparseAdagrad(),
                       {{"w": jnp.ones((12, 1), jnp.float32)}},
                       optax.sgd(0.1), jax.random.key(0))
save_train_state({path!r}, de, st)
print("T0SUM", float(np.asarray(de.get_weights(st.emb_params)[0]).sum()))
os.environ["DETPU_FAULT"] = "die:checkpoint_write"
st2 = st._replace(emb_params=jax.tree.map(lambda a: a + 1.0, st.emb_params))
save_train_state({path!r}, de, st2)
print("UNREACHABLE")
"""
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 17, proc.stderr[-2000:]
    assert "UNREACHABLE" not in proc.stdout
    t0sum = float(proc.stdout.split("T0SUM", 1)[1].split()[0])

    verify_checkpoint(path)  # still whole
    de, emb_opt, dp, tx, _ = _tiny()
    restored = restore_train_state(path, de, emb_opt, dp, tx)
    got = float(np.asarray(de.get_weights(restored.emb_params)[0]).sum())
    assert got == pytest.approx(t0sum)  # v1 values, not the half-saved v2


# ------------------------------------------------------- checkpoint ring


def test_ring_retention_and_pruning(tmp_path):
    """keep_last_n keeps a ring of older generations beyond .prev: each
    save rotates the displaced .prev into <path>.ring/step_<n> and prunes
    to the newest keep_last_n entries; every retained generation stays
    CRC-whole and restorable."""
    de, emb_opt, dp, tx, state = _tiny()
    path = str(tmp_path / "ckpt")
    st, gen3 = state, None
    for i in range(6):  # saves at steps 0..5
        save_train_state(path, de, st, keep_last_n=2)
        if i == 3:
            gen3 = np.asarray(de.get_weights(st.emb_params)[0]).copy()
        st = _bump(st)
    assert json.load(open(os.path.join(path, "meta.json")))["step"] == 5
    entries = ring_entries(path)
    assert [s for s, _ in entries] == [3, 2]  # newest first, pruned
    for _, d in entries:
        verify_checkpoint(d)
    cands = rollback_candidates(path)
    assert [s for s, _ in cands] == [5, 4, 3, 2]
    assert cands[0][1] == path
    assert cands[1][1] == previous_checkpoint_path(path)
    # a ring entry restores like any checkpoint (step 3 generation)
    restored = restore_train_state(cands[2][1], de, emb_opt, dp, tx)
    assert int(restored.step) == 3
    got = np.asarray(de.get_weights(restored.emb_params)[0])
    np.testing.assert_array_equal(got, gen3)


def test_ring_disabled_keeps_flat_layout(tmp_path):
    """keep_last_n=0 (the library default) preserves the historical
    path + .prev layout: no ring directory appears."""
    de, emb_opt, dp, tx, state = _tiny()
    path = str(tmp_path / "ckpt")
    for _ in range(4):
        save_train_state(path, de, state)
        state = _bump(state)
    assert not os.path.exists(ring_dir(path))
    assert ring_entries(path) == []
    # candidates still enumerate the flat layout, newest first
    assert [s for s, _ in rollback_candidates(path)] == [3, 2]


def test_ring_skips_prering_checkpoints(tmp_path):
    """A .prev whose manifest predates step recording cannot be placed in
    the ring (its position is unknowable): it is dropped as before, and
    rollback_candidates sorts step-less generations last."""
    de, emb_opt, dp, tx, state = _tiny()
    path = str(tmp_path / "ckpt")
    save_train_state(path, de, state, keep_last_n=2)
    save_train_state(path, de, _bump(state), keep_last_n=2)
    # erase the step from .prev's manifest (simulate a pre-ring save)
    prev_meta = os.path.join(previous_checkpoint_path(path), "meta.json")
    meta = json.load(open(prev_meta))
    del meta["step"]
    with open(prev_meta, "w") as f:
        json.dump(meta, f)
    save_train_state(path, de, _bump(_bump(state)), keep_last_n=2)
    assert ring_entries(path) == []  # the step-less .prev was dropped
    assert [s for s, _ in rollback_candidates(path)] == [2, 1]


def test_checkpoint_mismatch_wrong_table_shape(tmp_path):
    """A whole, CRC-valid checkpoint restored into a model with a drifted
    table config must fail with CheckpointMismatch naming the table and
    both shapes — not a scatter-shape traceback from set_weights."""
    de, emb_opt, dp, tx, state = _tiny()
    path = str(tmp_path / "ckpt")
    save_train_state(path, de, state)
    configs = [{"input_dim": 12 + 3 * i, "output_dim": 4} for i in range(3)]
    configs[1]["input_dim"] = 99  # vocab drift on table 1
    de2 = DistributedEmbedding(configs, world_size=1)
    with pytest.raises(runtime.CheckpointMismatch,
                       match=r"table 1.*\(15, 4\).*\(99, 4\)"):
        restore_train_state(path, de2, emb_opt, dp, tx)


def test_checkpoint_mismatch_wrong_table_count(tmp_path):
    de, emb_opt, dp, tx, state = _tiny()
    path = str(tmp_path / "ckpt")
    save_train_state(path, de, state)
    de2 = DistributedEmbedding(
        [{"input_dim": 12 + 3 * i, "output_dim": 4} for i in range(2)],
        world_size=1)
    with pytest.raises(runtime.CheckpointMismatch, match="3 table"):
        restore_train_state(path, de2, emb_opt, dp, tx)


def test_checkpoint_mismatch_via_npy_headers(tmp_path):
    """Checkpoints predating the ``tables`` manifest entry still validate
    — shapes come from the .npy headers (an mmap open)."""
    de, emb_opt, dp, tx, state = _tiny()
    path = str(tmp_path / "ckpt")
    save_train_state(path, de, state)
    meta_path = os.path.join(path, "meta.json")
    with open(meta_path) as f:
        meta = json.load(f)
    del meta["tables"]
    with open(meta_path, "w") as f:
        json.dump(meta, f)
    configs = [{"input_dim": 12 + 3 * i, "output_dim": 4} for i in range(3)]
    configs[2]["output_dim"] = 8  # dim drift on table 2
    de2 = DistributedEmbedding(configs, world_size=1)
    with pytest.raises(runtime.CheckpointMismatch, match="table 2"):
        restore_train_state(path, de2, emb_opt, dp, tx)
    # and the matching model still restores fine without the entry
    de3 = DistributedEmbedding(
        [{"input_dim": 12 + 3 * i, "output_dim": 4} for i in range(3)],
        world_size=1)
    restore_train_state(path, de3, emb_opt, dp, tx)


# -------------------------------------- driver fault-point recovery matrix

# one resilient-driver run, 6 steps, checkpoint every 2: the recovery
# contract is that DETPU_FAULT=die:<point> at ANY driver/checkpoint fault
# point leaves on-disk state a restarted driver resumes from to the SAME
# final step and loss as an uninterrupted run
_DRIVER_CHILD = """
import sys
sys.path.insert(0, {repo!r})
import jax, optax, numpy as np, jax.numpy as jnp
jax.config.update('jax_platforms', 'cpu')
from distributed_embeddings_tpu.parallel import (
    DistributedEmbedding, SparseAdagrad, init_hybrid_state,
    make_hybrid_train_step, run_resilient)
configs = [{{"input_dim": 12 + 3 * i, "output_dim": 4}} for i in range(3)]
de = DistributedEmbedding(configs, world_size=1)
emb_opt = SparseAdagrad()
tx = optax.sgd(0.1)
state = init_hybrid_state(de, emb_opt,
                          {{"w": jnp.ones((12, 1), jnp.float32)}},
                          tx, jax.random.key(0))
def loss_fn(dp, outs, batch):
    x = sum(jnp.mean(o) for o in outs) * jnp.mean(dp["w"])
    return (x - jnp.mean(batch)) ** 2
def data(start):
    for i in range(start, 6):
        rng = np.random.default_rng(100 + i)
        cats = [jnp.asarray(rng.integers(0, c["input_dim"], 8), jnp.int32)
                for c in configs]
        yield cats, jnp.asarray(rng.normal(size=(8,)), jnp.float32)
step = make_hybrid_train_step(de, loss_fn, tx, emb_opt,
                              with_metrics=False, nan_guard=True)
r = run_resilient(step, state, data, de=de, checkpoint_dir={ckpt!r},
                  checkpoint_every_steps=2, resume=True,
                  emb_optimizer=emb_opt, dense_tx=tx,
                  exit_on_preempt=True)
print("FINAL", r.step)
"""

DRIVER_FAULT_POINTS = ("driver.step", "driver.save", "checkpoint_write",
                       "checkpoint_commit", "driver.final")


def _run_driver_child(ckpt, fault=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("DETPU_FAULT", None)
    if fault:
        env["DETPU_FAULT"] = fault
    code = _DRIVER_CHILD.format(repo=_REPO, ckpt=ckpt)
    return subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=300)


_REFERENCE_FINAL = {}


def _final_crcs(ckpt):
    """Content CRCs of a final checkpoint — tables, every optimizer
    component, dense.msgpack (step included): bitwise run equivalence."""
    with open(os.path.join(ckpt, "meta.json")) as f:
        return json.load(f)["files"]


def _reference_final(tmp_factory):
    """Uninterrupted run's final checkpoint CRCs, computed once."""
    if not _REFERENCE_FINAL:
        ckpt = os.path.join(str(tmp_factory.mktemp("ref")), "ck")
        proc = _run_driver_child(ckpt)
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert "FINAL 6" in proc.stdout, proc.stdout
        _REFERENCE_FINAL["crcs"] = _final_crcs(ckpt)
    return _REFERENCE_FINAL["crcs"]


@pytest.mark.parametrize("point", DRIVER_FAULT_POINTS)
def test_driver_die_at_fault_point_then_restart_recovers(
        tmp_path, tmp_path_factory, point):
    """DETPU_FAULT=die:<point> kills the child driver at that point; a
    restarted driver (resume=True) must end with a final checkpoint
    CRC-identical to the uninterrupted run's — no torn state, no lost or
    replayed batch."""
    ckpt = str(tmp_path / "ck")
    p1 = _run_driver_child(ckpt, fault=f"die:{point}")
    assert p1.returncode == 17, (point, p1.stderr[-2000:])
    p2 = _run_driver_child(ckpt)
    assert p2.returncode == 0, (point, p2.stderr[-2000:])
    assert "FINAL 6" in p2.stdout, (point, p2.stdout)
    assert _final_crcs(ckpt) == _reference_final(tmp_path_factory), point


def test_driver_die_at_resume_then_restart_recovers(
        tmp_path, tmp_path_factory):
    """The resume path itself is a fault point: preempt a run (checkpoint
    exists), die inside the next run's restore, then restart clean."""
    ckpt = str(tmp_path / "ck")
    p1 = _run_driver_child(ckpt, fault="preempt@2")
    from distributed_embeddings_tpu.parallel import PREEMPT_EXIT_CODE
    assert p1.returncode == PREEMPT_EXIT_CODE, p1.stderr[-2000:]
    assert os.path.exists(ckpt + ".resume.json")
    p2 = _run_driver_child(ckpt, fault="die:driver.resume")
    assert p2.returncode == 17, p2.stderr[-2000:]
    p3 = _run_driver_child(ckpt)
    assert p3.returncode == 0, p3.stderr[-2000:]
    assert "FINAL 6" in p3.stdout, p3.stdout
    assert _final_crcs(ckpt) == _reference_final(tmp_path_factory)


def test_pre_crc_checkpoints_still_restore(tmp_path):
    """Old-format checkpoints (no ``files`` manifest) predate validation:
    they load with a debug note instead of failing."""
    de, emb_opt, dp, tx, state = _tiny()
    path = str(tmp_path / "ckpt")
    save_train_state(path, de, state)
    meta_path = os.path.join(path, "meta.json")
    with open(meta_path) as f:
        meta = json.load(f)
    del meta["files"]
    with open(meta_path, "w") as f:
        json.dump(meta, f)
    restored = restore_train_state(path, de, emb_opt, dp, tx)
    got = [np.asarray(t) for t in de.get_weights(restored.emb_params)]
    want = [np.asarray(t) for t in de.get_weights(state.emb_params)]
    for a, b in zip(got, want):
        np.testing.assert_array_equal(a, b)

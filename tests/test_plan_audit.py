"""Plan-time capacity auditor (analysis/plan_audit.py, ISSUE 8).

Three layers of teeth:

* **mirror parity** — the jax-free pack/slab arithmetic must agree with
  ``ops/packed_slab.py`` and with a real ``DistributedEmbedding``'s
  layout for every reference configuration, and the byte totals must
  agree EXACTLY with ``analysis/memory.py``'s ``eval_shape`` accounting
  (the calibration contract ``tools/plan_audit.py --strict`` enforces);
* **measured validation** — the predicted per-step all-to-all payloads
  must equal the on-device ``*_a2a_bytes`` step metrics on the
  8-virtual-device mesh (the predictor is validated, not decorative);
* **contract drills** — a seeded over-HBM plan and a seeded past-cliff
  slab must each FAIL with a violation naming the rank / slab, and the
  real Criteo-1TB deployment plan (world=16, bf16, column-sliced) must
  pass, all without materializing a single array.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_embeddings_tpu.analysis import memory as dmem
from distributed_embeddings_tpu.analysis import plan_audit as pa
from distributed_embeddings_tpu.ops import packed_slab as ps
from distributed_embeddings_tpu.ops.embedding_lookup import Ragged
from distributed_embeddings_tpu.parallel import (
    DistributedEmbedding, SparseAdagrad, SparseAdam, SparseSGD,
    init_hybrid_state, make_hybrid_train_step)
from distributed_embeddings_tpu.parallel.strategy import DistEmbeddingStrategy
from tools._profcommon import (CRITEO1TB_BATCH, CRITEO1TB_COL_SLICE,
                               CRITEO1TB_DIM, CRITEO1TB_WORLD,
                               CRITEO_1TB_SIZES, build_case)

WORLD = 8

C1TB_CONFIGS = [{"input_dim": int(s), "output_dim": CRITEO1TB_DIM,
                 "combiner": None} for s in CRITEO_1TB_SIZES]


# ------------------------------------------------------ mirror parity


@pytest.mark.parametrize("width", [1, 2, 3, 4, 8, 16, 21, 32, 64, 127,
                                   128, 130, 256])
def test_pack_arithmetic_matches_packed_slab(width):
    """The jax-free mirrors cannot drift from ops/packed_slab.py."""
    assert pa._pack_factor(width) == ps.pack_factor(width)
    assert pa._phys_width(width) == ps.phys_width(width)
    for rows in (1, 7, 100, 1001):
        assert pa._align_rows(rows, width) == ps.align_rows(rows, width)
    assert pa.LANES == ps.LANES


@pytest.mark.parametrize("case", ["dense", "ragged", "row_sliced",
                                  "bigvocab", "criteo1tb"])
def test_slab_geometry_matches_distributed_embedding(case):
    """slab_geometry reproduces the layer's width grouping, row offsets
    and physical capacities exactly — for every shared reference case,
    including the real Criteo-1TB shapes (pure metadata, nothing
    materialized)."""
    world = CRITEO1TB_WORLD if case == "criteo1tb" else WORLD
    de, _, _, _, _ = build_case(case, world, 16)
    g = pa.slab_geometry(de.strategy)
    assert list(g.widths) == de.widths
    assert dict(g.phys_cap) == de.phys_cap
    assert dict(g.phys_w) == de.phys_w
    assert dict(g.rows_cap) == de.rows_cap
    assert [list(o) for o in g.row_offsets_list] == de.row_offsets_list


@pytest.mark.parametrize("opt,name", [(SparseSGD(), "sgd"),
                                      (SparseAdagrad(), "adagrad"),
                                      (SparseAdam(), "adam")])
def test_byte_model_matches_memory_eval_shape(opt, name):
    """The calibration contract: zero drift against
    analysis/memory.py's eval_shape accounting for every optimizer
    family (the state models price init() exactly)."""
    de, cats, _, _, _ = build_case("dense", WORLD, 16)
    rep = pa.audit_plan(de, 16, optimizer=opt, cat_inputs=cats)
    assert rep.optimizer == name
    mem = dmem.table_memory_report(de, opt)
    drift = pa.compare_with_memory(rep, mem)
    assert drift["max_abs_drift"] == 0.0, drift
    # per-rank division agrees with memory.py's new per-rank totals
    assert (rep.per_rank[0].alloc_param_bytes
            == mem["totals"]["param_bytes_allocated_per_rank"])
    assert (rep.per_rank[0].opt_state_bytes
            == mem["totals"]["opt_state_bytes_per_rank"])


def test_dtype_pricing_bf16_halves_param_bytes():
    de, cats, _, _, _ = build_case("dense", WORLD, 16)
    f32 = pa.audit_plan(de, 16, cat_inputs=cats, param_dtype="float32")
    bf16 = pa.audit_plan(de, 16, cat_inputs=cats, param_dtype=jnp.bfloat16)
    assert bf16.param_dtype == "bfloat16"
    assert (bf16.per_rank[0].alloc_param_bytes * 2
            == f32.per_rank[0].alloc_param_bytes)


# ------------------------------------------- measured a2a validation


def test_a2a_prediction_matches_step_metrics_on_mesh():
    """Predicted per-step exchange payloads equal the on-device
    ``*_a2a_bytes`` metrics exactly — dense + ragged mixed inputs on the
    8-virtual-device mesh."""
    mesh = jax.sharding.Mesh(np.array(jax.devices()), ("data",))
    configs = ([{"input_dim": 50, "output_dim": 16, "combiner": "sum"}]
               + [{"input_dim": 30 + i, "output_dim": 16}
                  for i in range(WORLD + 1)])
    de = DistributedEmbedding(configs, world_size=WORLD,
                              strategy="memory_balanced")
    tx = optax.sgd(0.01)
    emb_opt = SparseSGD()

    def loss_fn(dp, outs, batch):
        del batch
        return sum(jnp.mean(o.astype(jnp.float32) ** 2)
                   for o in outs) * dp["w"]

    state = init_hybrid_state(de, emb_opt, {"w": jnp.float32(0.5)}, tx,
                              jax.random.key(0), mesh=mesh)
    step = make_hybrid_train_step(de, loss_fn, tx, emb_opt, mesh=mesh,
                                  with_metrics=True)
    rng = np.random.default_rng(0)
    b, cap = 4, 8
    vals = np.concatenate([rng.integers(0, 50, cap).astype(np.int32)
                           for _ in range(WORLD)])
    splits = np.concatenate([np.arange(0, 2 * (b + 1), 2, dtype=np.int32)
                             for _ in range(WORLD)])
    rag = Ragged(values=jnp.asarray(vals), row_splits=jnp.asarray(splits))
    cats = [rag] + [jnp.asarray(rng.integers(0, 30, WORLD * b), jnp.int32)
                    for _ in range(WORLD + 1)]
    _, _, m = step(state, cats, None)

    rep = pa.audit_plan(de, WORLD * b, cat_inputs=cats, optimizer="sgd")
    assert rep.local_batch == b
    assert rep.id_a2a_bytes_per_step == int(np.asarray(m["id_a2a_bytes"])[0])
    assert rep.out_a2a_bytes_per_step == int(
        np.asarray(m["out_a2a_bytes"])[0])
    assert rep.grad_a2a_bytes_per_step == int(
        np.asarray(m["grad_a2a_bytes"])[0])
    # padding fraction is the same plan-derived figure the metric reports
    np.testing.assert_allclose(rep.out_pad_frac,
                               float(np.asarray(m["out_pad_frac"]).mean()),
                               atol=1e-6)


def test_mp_input_prices_zero_id_exchange():
    de, cats, _, _, _ = build_case("dense", WORLD, 16)
    dp = pa.audit_plan(de, 16, cat_inputs=cats, dp_input=True)
    mp = pa.audit_plan(de, 16, cat_inputs=cats, dp_input=False)
    assert dp.id_a2a_bytes_per_step > 0
    assert mp.id_a2a_bytes_per_step == 0
    assert mp.out_a2a_bytes_per_step == dp.out_a2a_bytes_per_step


# ------------------------------------------------- contract drills


def test_criteo1tb_deployment_plan_passes():
    """The north-star plan — real 26-table / ~188M-row vocab vector,
    world=16, bf16, the reference column-slice threshold — holds the
    default v5e contract: fits HBM, no slab past the cliff, every rank
    populated. Pure metadata; building the strategy at 188M rows costs
    microseconds and zero array bytes."""
    st = DistEmbeddingStrategy(C1TB_CONFIGS, CRITEO1TB_WORLD,
                               strategy="comm_balanced",
                               column_slice_threshold=CRITEO1TB_COL_SLICE)
    rep = pa.audit_plan(st, CRITEO1TB_BATCH, optimizer="sgd",
                        param_dtype="bfloat16", dp_input=False,
                        contract=pa.default_contract())
    assert rep.ok, rep.violations
    assert rep.n_sliced_tables >= CRITEO1TB_WORLD
    assert all(s.cliff != "past_cliff" for s in rep.slabs)
    # the whole point of the threshold: the ~40M-row tables split
    assert rep.n_sliced_tables > len(C1TB_CONFIGS)
    rep.raise_on_violations()  # no-op when clean


def test_seeded_over_hbm_plan_fails_naming_rank():
    """Criteo-1TB fp32 + Adam on 8 ranks (~57 GB/rank) must be rejected
    with the rank named."""
    st = DistEmbeddingStrategy(C1TB_CONFIGS, 8, strategy="memory_balanced")
    rep = pa.audit_plan(st, CRITEO1TB_BATCH, optimizer="adam",
                        param_dtype="float32",
                        contract=pa.default_contract())
    assert not rep.ok
    assert any(v.startswith("rank ") and "exceeds the per-rank HBM" in v
               for v in rep.violations), rep.violations
    with pytest.raises(pa.PlanAuditError, match="rank "):
        rep.raise_on_violations()


def test_online_snapshot_billing_and_seeded_over_hbm():
    """The online runtime's RCU double-buffer is contract-checked:
    ``online=True`` bills 2x params + 1x opt (frozen, shared) + 2x
    streaming state per rank as ``snapshot_bytes``, and a plan that
    fits offline can exceed HBM the moment serving runs beside
    training — rejected with the snapshot component named."""
    st = DistEmbeddingStrategy(C1TB_CONFIGS, CRITEO1TB_WORLD,
                               strategy="comm_balanced",
                               column_slice_threshold=CRITEO1TB_COL_SLICE)
    kw = dict(optimizer="adagrad", param_dtype="bfloat16",
              dp_input=False)
    off = pa.audit_plan(st, CRITEO1TB_BATCH,
                        contract=pa.default_contract(), **kw)
    assert off.ok, off.violations
    assert all(r.snapshot_bytes == 0 for r in off.per_rank)
    on = pa.audit_plan(st, CRITEO1TB_BATCH, online=True,
                       contract=pa.default_contract(), **kw)
    r0, o0 = on.per_rank[0], off.per_rank[0]
    # the publisher keeps exactly: published + in-flight params, one
    # frozen opt slab, two streaming-state copies (zero here)
    assert r0.snapshot_bytes == (2 * o0.alloc_param_bytes
                                 + o0.opt_state_bytes
                                 + 2 * o0.streaming_state_bytes)
    assert r0.total_bytes == o0.total_bytes + r0.snapshot_bytes
    # ~6.6 GB/rank offline fits v5e; +2x params +1x opt does not
    assert not on.ok
    assert any("online snapshots" in v and "exceeds the per-rank HBM" in v
               for v in on.violations), on.violations


def test_online_snapshot_bills_streaming_state_twice():
    cfgs = [{"input_dim": 4096 + 256, "output_dim": 16,
             "streaming": {"capacity": 4096, "buckets": 256}},
            {"input_dim": 1000, "output_dim": 16}]
    st = DistEmbeddingStrategy(cfgs, 2)

    class _S:  # duck-typed StreamingConfig (this module stays jax-free)
        depth, buckets = 3, 512

    off = pa.audit_plan(st, 16, streaming_config=_S)
    on = pa.audit_plan(st, 16, streaming_config=_S, online=True)
    o0, r0 = off.per_rank[0], on.per_rank[0]
    assert o0.streaming_state_bytes > 0
    assert r0.snapshot_bytes == (2 * o0.alloc_param_bytes
                                 + o0.opt_state_bytes
                                 + 2 * o0.streaming_state_bytes)


def test_isolated_serving_bills_shm_region():
    """``isolated=True`` prices the supervisor's double-buffered shm
    transport (utils/shm.py's exact region arithmetic over the global
    host-pickled payload) — host RAM, reported per rank but never
    counted against the HBM contract."""
    from distributed_embeddings_tpu.utils import shm

    cfgs = [{"input_dim": 4096 + 256, "output_dim": 16,
             "streaming": {"capacity": 4096, "buckets": 256}},
            {"input_dim": 1000, "output_dim": 16}]
    st = DistEmbeddingStrategy(cfgs, 2)

    class _S:  # duck-typed StreamingConfig
        depth, buckets = 3, 512

    off = pa.audit_plan(st, 16, streaming_config=_S)
    iso = pa.audit_plan(st, 16, streaming_config=_S, isolated=True)
    o0, r0 = off.per_rank[0], iso.per_rank[0]
    assert o0.shm_region_bytes == 0
    payload = 2 * (o0.alloc_param_bytes + o0.streaming_state_bytes)
    assert r0.shm_region_bytes == shm.region_bytes(
        shm.slack_capacity(payload))
    assert r0.shm_region_bytes > 2 * payload  # 2 buffers + slack + headers
    # host RAM, not HBM: totals and the contract are untouched
    assert r0.total_bytes == o0.total_bytes
    assert r0.hbm_frac == o0.hbm_frac
    assert "shm serving region" in iso.markdown()
    assert "shm serving region" not in off.markdown()


def test_seeded_past_cliff_slab_fails_naming_slab():
    """Criteo-1TB bf16 on 16 ranks WITHOUT column slicing stacks the
    ~40M-row tables into a ~9.5 GB apply slab — past the measured
    2.7→8.65 GB scatter cliff; must be rejected with the slab named."""
    st = DistEmbeddingStrategy(C1TB_CONFIGS, CRITEO1TB_WORLD,
                               strategy="comm_balanced")
    rep = pa.audit_plan(st, CRITEO1TB_BATCH, optimizer="sgd",
                        param_dtype="bfloat16", dp_input=False,
                        contract=pa.default_contract())
    assert any("slab w128" in v and "scatter cliff" in v
               for v in rep.violations), rep.violations


def test_empty_rank_flagged():
    st = DistEmbeddingStrategy([{"input_dim": 100, "output_dim": 8}] * 4, 6)
    rep = pa.audit_plan(st, 12, contract=pa.default_contract())
    assert any("own no table slice" in v for v in rep.violations)


def test_group_and_a2a_ceilings():
    de, cats, _, _, _ = build_case("dense", WORLD, 16)
    tight = pa.PlanContract(max_groups=1, max_a2a_bytes_per_step=1)
    rep = pa.audit_plan(de, 16, cat_inputs=cats, contract=tight)
    assert any("padded group shapes" in v for v in rep.violations)
    assert any("a2a payload" in v for v in rep.violations)


# ------------------------------------ spec audit, ranking, cost hook


def test_audit_plan_spec_matches_full_audit():
    """A bare plan_spec() dict (the checkpoint meta.json fingerprint)
    prices capacity identically to the full audit — the path that vets a
    checkpoint's plan before a restore."""
    st = DistEmbeddingStrategy(C1TB_CONFIGS, CRITEO1TB_WORLD,
                               strategy="comm_balanced",
                               column_slice_threshold=CRITEO1TB_COL_SLICE)
    full = pa.audit_plan(st, CRITEO1TB_BATCH, optimizer="adagrad",
                         param_dtype="bfloat16", dp_input=False)
    spec = pa.audit_plan_spec(st.plan_spec(), optimizer="adagrad",
                              param_dtype="bfloat16",
                              contract=pa.default_contract())
    assert spec.ok, spec.violations
    for a, b in zip(full.per_rank, spec.per_rank):
        assert a.alloc_param_bytes == b.alloc_param_bytes
        assert a.live_param_bytes == b.live_param_bytes
        assert a.opt_state_bytes == b.opt_state_bytes
    assert [ (s.width, s.rank_bytes) for s in full.slabs ] == \
           [ (s.width, s.rank_bytes) for s in spec.slabs ]


def test_rank_strategies_orders_fitting_plans_first():
    """The planner cost hook: a strategy whose plan violates the
    contract sorts after every fitting one; among fitting plans the
    lighter max-rank wins."""
    configs = [{"input_dim": 1000 * (i + 1), "output_dim": 16}
               for i in range(8)]
    ranked = pa.rank_strategies(configs, 4, 16,
                                contract=pa.PlanContract(
                                    max_rank_bytes=10**12))
    assert [n for n, _ in ranked][0] in ("memory_optimized",
                                         "memory_balanced",
                                         "comm_balanced")
    basic_rank = [n for n, _ in ranked].index("basic")
    best = ranked[0][1].max_rank_bytes
    assert ranked[basic_rank][1].max_rank_bytes >= best
    assert all(r.ok for _, r in ranked)


def test_strategy_predicted_cost_hook():
    st = DistEmbeddingStrategy([{"input_dim": 64, "output_dim": 8}] * 8, 4)
    rep = st.predicted_cost(16, optimizer="adagrad")
    assert isinstance(rep, pa.PlanReport)
    assert rep.world == 4 and rep.optimizer == "adagrad"
    assert rep.max_rank_bytes > 0


def test_encodings_from_inputs_errors():
    st = DistEmbeddingStrategy([{"input_dim": 64, "output_dim": 8}] * 8, 4)
    with pytest.raises(ValueError, match="not divisible"):
        pa.encodings_from_inputs(
            st, [jax.ShapeDtypeStruct((10,), jnp.int32)] * 8, 4)
    with pytest.raises(ValueError, match="not divisible"):
        pa.audit_plan(st, 10)
    with pytest.raises(ValueError, match="unknown optimizer"):
        pa.audit_plan(st, 16, optimizer="rmsprop")


def test_price_int8_serving_pricing_only():
    """The ISSUE-15 serving-table variant: int8 rows + per-row scales
    price at ~4x less HBM than fp32 (~2x vs bf16, minus the scale tax)
    and shrink the out-a2a payload by the same code/scale arithmetic —
    pricing only, nothing materializes, no jax touched."""
    st = DistEmbeddingStrategy(
        [{"input_dim": 10_000, "output_dim": 32}] * 8, 8)
    rec = pa.price_int8_serving(st, 64, param_dtype="float32")
    # fp32 dim-32: 128 B/row -> 36 B/row = 3.56x
    assert rec["table_bytes_ratio"] == pytest.approx(128 / 36)
    assert rec["int8_table_bytes_per_rank"] < rec["table_bytes_per_rank"]
    assert rec["int8_hbm_frac"] < rec["hbm_frac"]
    assert rec["out_a2a_bytes_per_step"] > 0
    assert rec["int8_out_a2a_bytes_per_step"] \
        < rec["out_a2a_bytes_per_step"]
    assert rec["out_a2a_ratio"] > 1.0
    # bf16 baseline halves the win but the variant still wins
    rec16 = pa.price_int8_serving(st, 64, param_dtype="bfloat16")
    assert 1.0 < rec16["table_bytes_ratio"] < rec["table_bytes_ratio"]
    # json-able (rides the bench serving section)
    import json
    json.dumps(rec)


def test_report_json_roundtrip():
    de, cats, _, _, _ = build_case("ragged", WORLD, 16)
    rep = pa.audit_plan(de, 16, cat_inputs=cats,
                        contract=pa.default_contract())
    import json
    doc = json.loads(pa.report_to_jsonl(rep))
    assert doc["world"] == WORLD
    assert len(doc["per_rank"]) == WORLD
    assert doc["violations"] == []
    assert "| rank |" in rep.markdown()

"""SPMD invariant auditor (analysis/audit.py) against the hybrid step.

The acceptance contract: the collective census is EXACT — one id
all-to-all + one output all-to-all forward, one cotangent all-to-all
backward per step on a multi-device mesh (dense, ragged, and row-sliced
configs), zero collectives on a single worker — and seeded violations
(an extra psum, an all_gather, an f64 leak, a host callback) are flagged.
Everything here is abstract tracing under JAX_PLATFORMS=cpu (conftest):
no TPU, no execution of the audited program.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_embeddings_tpu.analysis import (
    AuditError, audit_step_fn, audit_train_step, expected_collectives)
from distributed_embeddings_tpu.parallel import (
    DistributedEmbedding, SparseAdagrad, SparseSGD, init_hybrid_state,
    make_hybrid_train_step)
from tools.audit_step import build_case

WORLD = 8
B = 16

FULL_CENSUS = {"id_exchange_fwd": 1, "out_exchange_fwd": 1,
               "grad_exchange_bwd": 1}


@pytest.fixture(scope="module")
def mesh():
    devs = jax.devices()
    assert len(devs) >= WORLD, "conftest should force 8 CPU devices"
    return Mesh(np.array(devs[:WORLD]), ("data",))


def _audit(name, world, mesh=None, loss_fn=None, **kw):
    de, cats, batch_tree, dense_params, default_loss = build_case(
        name, world, B)
    return audit_train_step(
        de, loss_fn or default_loss, optax.sgd(0.5), SparseAdagrad(),
        cats, batch_tree, mesh=mesh, lr_schedule=0.3,
        dense_params=dense_params, **kw)


@pytest.mark.parametrize("config", ["dense", "ragged", "row_sliced"])
def test_census_exact_8dev(config, mesh):
    """Acceptance: exactly 2 forward + 1 backward all-to-all on an
    8-device mesh for dense, ragged, and row-sliced configs; no
    all_gather/reduce_scatter; every donation intact."""
    rep = _audit(config, WORLD, mesh=mesh)
    assert rep.ok, rep.violations
    assert rep.a2a_census() == FULL_CENSUS
    assert rep.collective_counts.get("all_gather", 0) == 0
    assert rep.collective_counts.get("reduce_scatter", 0) == 0
    assert rep.donation["dropped"] == 0
    assert rep.donation["donated"] == rep.donation["expected"]


@pytest.mark.parametrize("config", ["dense", "ragged"])
def test_census_single_worker(config):
    """world_size == 1 runs the plan executor without any exchange: the
    census must be empty (a collective here would mean the single-worker
    path touches a mesh axis that does not exist)."""
    rep = _audit(config, 1)
    assert rep.ok, rep.violations
    assert rep.a2a_census() == {}
    assert rep.collective_counts.get("psum", 0) == 0


def test_instrumented_step_same_census(mesh):
    """with_metrics=True adds on-device metrics but must not add any
    collective: the instrumented and bare steps share one exchange
    contract (otherwise DETPU_OBS=1 would change what it measures)."""
    rep = _audit("dense", WORLD, mesh=mesh, with_metrics=True)
    assert rep.ok, rep.violations
    assert rep.a2a_census() == FULL_CENSUS


def test_mp_input_skips_id_exchange(mesh):
    """dp_input=False (MpInputs) skips the id all-to-all: census is one
    forward (outputs) + one backward (cotangents)."""
    configs = [{"input_dim": 20 + 6 * i, "output_dim": 4,
                "combiner": ["sum", None, "mean"][i % 3]}
               for i in range(10)]
    de = DistributedEmbedding(configs, world_size=WORLD, dp_input=False)
    rng = np.random.default_rng(0)
    inputs = []
    for cfg in configs:
        hot = 1 if cfg["combiner"] is None else 3
        shape = (B,) if hot == 1 else (B, hot)
        inputs.append(rng.integers(0, cfg["input_dim"], size=shape
                                   ).astype(np.int32))
    mp = de.pack_mp_inputs(inputs)

    def loss_fn(dp, emb_outs, batch):
        n, y = batch
        x = jnp.concatenate([e.reshape(e.shape[0], -1) for e in emb_outs],
                            axis=1)
        return jnp.mean((x @ dp["w"] + n @ dp["v"] - y) ** 2)

    cols = sum(int(c["output_dim"]) for c in configs)
    dense_params = {"w": jax.ShapeDtypeStruct((cols, 1), jnp.float32),
                    "v": jax.ShapeDtypeStruct((3, 1), jnp.float32)}
    batch_tree = (jax.ShapeDtypeStruct((B, 3), jnp.float32),
                  jax.ShapeDtypeStruct((B, 1), jnp.float32))
    rep = audit_train_step(de, loss_fn, optax.sgd(0.5), SparseAdagrad(),
                           mp, batch_tree, mesh=mesh,
                           dense_params=dense_params)
    assert rep.ok, rep.violations
    assert rep.a2a_census() == {"out_exchange_fwd": 1,
                                "grad_exchange_bwd": 1}


def test_extra_psum_flagged(mesh):
    """A deliberately broken step — one extra psum smuggled into the loss
    — must fail the census (the ISSUE acceptance seeding)."""
    _, _, _, _, base_loss = build_case("dense", WORLD, B)

    def bad_loss(dp, emb_outs, batch):
        loss = base_loss(dp, emb_outs, batch)
        return loss + 0.0 * lax.psum(jnp.sum(emb_outs[0]), "data")

    rep = _audit("dense", WORLD, mesh=mesh, loss_fn=bad_loss)
    assert not rep.ok
    assert any("psum census" in v for v in rep.violations), rep.violations
    with pytest.raises(AuditError):
        rep.raise_on_violations()


def test_extra_all_gather_flagged(mesh):
    """An all_gather anywhere in the step is the paper's forbidden
    failure mode (a slab/batch-sized collective the layout exists to
    avoid) — flagged regardless of where it hides."""
    _, _, _, _, base_loss = build_case("dense", WORLD, B)

    def bad_loss(dp, emb_outs, batch):
        g = lax.all_gather(emb_outs[0], "data")
        return base_loss(dp, emb_outs, batch) + 0.0 * jnp.sum(g)

    rep = _audit("dense", WORLD, mesh=mesh, loss_fn=bad_loss)
    assert not rep.ok
    assert any("all_gather" in v for v in rep.violations), rep.violations


def test_dtype_leak_flagged():
    """An x64 leak (f64 value inside the step) is flagged. Seeded by
    tracing under enable_x64 with a loss that upcasts — without x64 the
    cast is a silent no-op, which is exactly why only the auditor can see
    the difference."""
    with jax.experimental.enable_x64():
        _, _, _, _, base_loss = build_case("dense", 1, B)

        def leaky_loss(dp, emb_outs, batch):
            return base_loss(dp, emb_outs, batch).astype(jnp.float64)

        rep = _audit("dense", 1, loss_fn=leaky_loss)
    assert not rep.ok
    assert any("f64" in v for v in rep.violations), rep.violations
    assert rep.dtype_leaks


def test_host_interop_flagged():
    """A host callback inside the jitted step (a device->host sync per
    step) is flagged by the host-interop audit."""

    def chatty_loss(dp, emb_outs, batch):
        loss = jnp.mean(emb_outs[0])
        jax.debug.callback(lambda x: None, loss)
        return loss

    rep = _audit("dense", 1, loss_fn=chatty_loss)
    assert not rep.ok
    assert any("host interop" in v for v in rep.violations), rep.violations
    assert rep.host_interop


def test_weak_scalar_arg_flagged():
    """A Python scalar riding the jitted signature is a recompile hazard
    (weak->strong flips retrace); the scan flags it."""
    f = jax.jit(lambda x, s: x * s)
    rep = audit_step_fn(f, (jax.ShapeDtypeStruct((4,), jnp.float32), 2.0),
                        check_donation=False)
    assert rep.recompile_hazards
    assert not rep.ok


def test_expected_collectives_shape():
    """The contract generator matches the layer's configuration."""
    configs = [{"input_dim": 32, "output_dim": 8} for _ in range(8)]
    de = DistributedEmbedding(configs, world_size=WORLD)
    exp = expected_collectives(de, nan_guard=True, n_dense_leaves=2)
    assert exp["all_to_all"] == 3
    assert exp["psum"] == 4  # loss + 2 dense leaves + nanguard
    assert exp["all_gather"] == 0
    de1 = DistributedEmbedding(configs, world_size=1)
    assert expected_collectives(de1, nan_guard=True,
                                n_dense_leaves=2)["all_to_all"] == 0


def test_step_runs_under_transfer_guard(mesh, transfer_guard_compiled):
    """Run-time twin of the static audit: a compiled hybrid step
    dispatched under jax.transfer_guard('disallow') performs no implicit
    host<->device transfer (fixture compiles outside the guard, then the
    steady-state dispatches run inside it)."""
    step, state, cats, batch = transfer_guard_compiled
    with jax.transfer_guard("disallow"):
        for _ in range(2):
            loss, state = step(state, cats, batch)
    assert np.isfinite(float(np.asarray(loss)))


@pytest.fixture
def transfer_guard_compiled(mesh):
    """A compiled (warmed-up) 8-device hybrid step with explicitly staged
    inputs — what a production steady state looks like."""
    configs = [{"input_dim": 24 + i, "output_dim": 4, "combiner": None}
               for i in range(8)]
    de = DistributedEmbedding(configs, world_size=WORLD)
    rng = np.random.default_rng(0)
    shard = NamedSharding(mesh, P("data"))
    cats = [jax.device_put(
        rng.integers(0, c["input_dim"], size=(B,)).astype(np.int32), shard)
        for c in configs]
    num = jax.device_put(rng.normal(size=(B, 3)).astype(np.float32), shard)
    y = jax.device_put(rng.normal(size=(B, 1)).astype(np.float32), shard)

    def loss_fn(dp, emb_outs, batch):
        n, yy = batch
        x = jnp.concatenate([e.reshape(e.shape[0], -1) for e in emb_outs],
                            axis=1)
        return jnp.mean((x @ dp["w"] + n @ dp["v"] - yy) ** 2)

    tx = optax.sgd(0.5)
    emb_opt = SparseSGD()
    dense_params = {"w": jnp.zeros((8 * 4, 1)), "v": jnp.zeros((3, 1))}
    state = init_hybrid_state(de, emb_opt, dense_params, tx,
                              jax.random.key(0), mesh=mesh)
    step = make_hybrid_train_step(de, loss_fn, tx, emb_opt, mesh=mesh,
                                  lr_schedule=0.1)
    # compile + first transfer of baked constants happens OUTSIDE the
    # guard; the guarded dispatches then prove the steady state clean
    loss, state = step(state, cats, (num, y))
    jax.block_until_ready(loss)
    return step, state, cats, (num, y)

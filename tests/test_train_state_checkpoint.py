"""Full train-state checkpoint/resume (utils/checkpoint.py) — beyond the
reference, which saves only embedding tables (SURVEY §5: "no optimizer-state
or step checkpointing").

The strong test: train K steps, save, restore into a FRESH DistributedEmbedding
and train K more — the trajectory must equal an uninterrupted 2K-step run
exactly (params, optimizer state, step counter all carried)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_embeddings_tpu.parallel import (
    DistributedEmbedding, SparseAdagrad, SparseAdam, SparseSGD,
    init_hybrid_state, make_hybrid_train_step)
from distributed_embeddings_tpu.utils import (
    restore_train_state, save_train_state)

WORLD = 8
B = 16


def _setup():
    # TWO width groups (4 and 16): multi-slab checkpoints must route each
    # optimizer-state component to the right width (a lexicographic-vs-
    # numeric wkey ordering bug once swapped Adam counts between groups)
    configs = [{"input_dim": 20 + 5 * i, "output_dim": 4 if i % 2 else 16,
                "combiner": ["sum", None, "mean"][i % 3]}
               for i in range(10)]
    de = DistributedEmbedding(configs, world_size=WORLD,
                              strategy="memory_balanced")
    mesh = Mesh(np.array(jax.devices()[:WORLD]), ("data",))
    return configs, de, mesh


def _data(rng, configs):
    cats = []
    for cfg in configs:
        shape = (B,) if cfg["combiner"] is None else (B, 3)
        cats.append(jnp.asarray(
            rng.integers(0, cfg["input_dim"], size=shape), jnp.int32))
    y = jnp.asarray(rng.normal(size=(B, 1)) * 0.1, jnp.float32)
    return cats, y


def _loss_fn(dp, emb_outs, batch):
    x = jnp.concatenate([e.reshape(e.shape[0], -1) for e in emb_outs],
                        axis=1)
    return jnp.mean((x @ dp["w"] - batch) ** 2)


@pytest.mark.parametrize("opt_name", ["sgd", "adagrad", "adam"])
def test_save_restore_resumes_exact_trajectory(tmp_path, opt_name):
    rng = np.random.default_rng(3)
    configs, de, mesh = _setup()
    emb_opt = {"sgd": SparseSGD(), "adagrad": SparseAdagrad(),
               "adam": SparseAdam()}[opt_name]
    tx = optax.sgd(0.4)
    cols = sum(c["output_dim"] for c in configs)
    dp = {"w": jnp.asarray(rng.normal(size=(cols, 1)) * 0.2, jnp.float32)}
    cats, y = _data(rng, configs)
    y_sh = jax.device_put(y, NamedSharding(mesh, P("data")))

    step = make_hybrid_train_step(de, _loss_fn, tx, emb_opt, mesh=mesh,
                                  lr_schedule=0.3)

    # uninterrupted 2K-step reference run
    ref = init_hybrid_state(de, emb_opt, jax.tree.map(jnp.copy, dp), tx,
                            jax.random.key(1), mesh=mesh)
    for _ in range(6):
        _, ref = step(ref, cats, y_sh)
    ref_tables = de.get_weights(ref.emb_params)

    # interrupted run: 3 steps, save, restore into a FRESH wrapper, 3 more
    st = init_hybrid_state(de, emb_opt, jax.tree.map(jnp.copy, dp), tx,
                           jax.random.key(1), mesh=mesh)
    for _ in range(3):
        _, st = step(st, cats, y_sh)
    ck = str(tmp_path / f"ck_{opt_name}")
    save_train_state(ck, de, st)

    de2 = DistributedEmbedding(configs, world_size=WORLD,
                               strategy="memory_balanced")
    st2 = restore_train_state(ck, de2, emb_opt,
                              jax.tree.map(jnp.zeros_like, dp), tx,
                              mesh=mesh)
    assert int(st2.step) == 3
    step2 = make_hybrid_train_step(de2, _loss_fn, tx, emb_opt, mesh=mesh,
                                   lr_schedule=0.3)
    for _ in range(3):
        _, st2 = step2(st2, cats, y_sh)

    got_tables = de2.get_weights(st2.emb_params)
    for t, (a, b) in enumerate(zip(ref_tables, got_tables)):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7,
                                   err_msg=f"table {t}")
    for k in ("w",):
        np.testing.assert_allclose(np.asarray(ref.dense_params[k]),
                                   np.asarray(st2.dense_params[k]),
                                   rtol=1e-6, atol=1e-7)
    assert int(st2.step) == 6


def test_restore_preserves_saved_dtypes(tmp_path):
    """bf16 tables + fp32 Adagrad accumulators restore with the SAME mixed
    dtypes by default — one forced dtype would silently alter the
    trajectory of a mixed-precision run (ADVICE r4)."""
    configs, de, mesh = _setup()
    emb_opt = SparseAdagrad()
    tx = optax.sgd(0.1)
    dp = {"w": jnp.zeros((sum(c["output_dim"] for c in configs), 1),
                         jnp.float32)}
    st = init_hybrid_state(de, emb_opt, dp, tx, jax.random.key(0),
                           mesh=mesh)
    # mixed precision: bf16 tables, fp32 accumulators
    st = st._replace(emb_params=jax.tree.map(
        lambda a: a.astype(jnp.bfloat16), st.emb_params))
    ck = str(tmp_path / "ck_mixed")
    save_train_state(ck, de, st)

    de2 = DistributedEmbedding(configs, world_size=WORLD,
                               strategy="memory_balanced")
    st2 = restore_train_state(ck, de2, emb_opt, dp, tx, mesh=mesh)
    assert all(v.dtype == jnp.bfloat16 for v in st2.emb_params.values())
    assert all(v.dtype == jnp.float32 for v in st2.emb_opt_state.values())
    # explicit per-component override still wins
    st3 = restore_train_state(ck, de2, emb_opt, dp, tx, mesh=mesh,
                              dtype={"tables": jnp.float32})
    assert all(v.dtype == jnp.float32 for v in st3.emb_params.values())
    assert all(v.dtype == jnp.float32 for v in st3.emb_opt_state.values())

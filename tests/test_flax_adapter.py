"""Flax ``nn.Module`` adapter: plain flax + optax training, no sparse trainer.

VERDICT r3 Missing #2: the reference's ``DistributedEmbedding`` is a Keras
layer composing with stock Keras loops (``dist_model_parallel.py:199-259``);
these tests prove the Flax adapter composes the same way — standard
``TrainState``/optax training through autodiff, single-device and under an
8-device ``shard_map``.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_embeddings_tpu.layers import DistributedEmbeddingLayer
from distributed_embeddings_tpu.ops.embedding_lookup import (
    embedding_lookup as lookup_fn)
from distributed_embeddings_tpu.ops.embedding_lookup import Ragged
from distributed_embeddings_tpu.parallel import (DistributedEmbedding,
                                                 resolve_dp_gradient)

WORLD = 8


def _configs(rng, n=6):
    out = []
    for i in range(n):
        out.append({"input_dim": int(rng.integers(8, 64)),
                    "output_dim": int(rng.integers(2, 10)),
                    "combiner": [None, "sum", "mean"][i % 3]})
    return out


def _inputs(rng, configs, b):
    cats = []
    for cfg in configs:
        if cfg["combiner"] is None:
            cats.append(jnp.asarray(
                rng.integers(0, cfg["input_dim"], size=(b,)), jnp.int32))
        else:
            cats.append(jnp.asarray(
                rng.integers(0, cfg["input_dim"], size=(b, 3)), jnp.int32))
    return cats


def test_single_device_forward_matches_oracle():
    rng = np.random.default_rng(0)
    configs = _configs(rng)
    de = DistributedEmbedding(configs, world_size=1)
    layer = DistributedEmbeddingLayer(de=de)
    cats = _inputs(rng, configs, b=16)
    vars_ = layer.init(jax.random.key(0), cats)
    outs = layer.apply(vars_, cats)
    tables = de.get_weights(vars_["params"]["slabs"])
    for t, (cfg, ids, out) in enumerate(zip(configs, cats, outs)):
        want = lookup_fn(
            jnp.asarray(tables[t]), ids, combiner=cfg["combiner"])
        np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)


def test_single_device_plain_optax_training_converges():
    rng = np.random.default_rng(1)
    configs = [{"input_dim": 32, "output_dim": 4, "combiner": "sum"},
               {"input_dim": 50, "output_dim": 6, "combiner": "mean"}]
    de = DistributedEmbedding(configs, world_size=1)

    class Model(nn.Module):
        de: DistributedEmbedding

        @nn.compact
        def __call__(self, cats):
            embs = DistributedEmbeddingLayer(de=self.de, name="emb")(cats)
            x = jnp.concatenate(embs, axis=1)
            return nn.Dense(1)(x)

    model = Model(de=de)
    b = 32
    cats = _inputs(rng, configs, b)
    y = jnp.asarray(rng.normal(size=(b, 1)) * 0.05, jnp.float32)
    vars_ = model.init(jax.random.key(0), cats)
    tx = optax.adam(3e-2)  # any optax transform — that's the point
    opt_state = tx.init(vars_)

    @jax.jit
    def step(vars_, opt_state):
        def loss_fn(v):
            return jnp.mean((model.apply(v, cats) - y) ** 2)
        loss, grads = jax.value_and_grad(loss_fn)(vars_)
        updates, opt_state = tx.update(grads, opt_state, vars_)
        return optax.apply_updates(vars_, updates), opt_state, loss

    losses = []
    for _ in range(60):
        vars_, opt_state, loss = step(vars_, opt_state)
        losses.append(float(loss))
    assert losses[-1] < 0.2 * losses[0], losses[:: len(losses) - 1]


def test_mesh_training_plain_optax():
    """8-device hybrid: adapter init outside shard_map, plain optax inside —
    no make_hybrid_train_step anywhere."""
    rng = np.random.default_rng(2)
    configs = [{"input_dim": 24 + 8 * i, "output_dim": 4,
                "combiner": "sum" if i % 2 else None}
               for i in range(WORLD + 2)]
    de = DistributedEmbedding(configs, world_size=WORLD)
    layer = DistributedEmbeddingLayer(de=de)
    mesh = Mesh(np.array(jax.devices()[:WORLD]), ("data",))

    b_local = 4
    B = WORLD * b_local
    cats = []
    for cfg in configs:
        hot = 1 if cfg["combiner"] is None else 2
        shape = (B,) if hot == 1 else (B, hot)
        cats.append(jnp.asarray(
            rng.integers(0, cfg["input_dim"], size=shape), jnp.int32))
    y = jnp.asarray(rng.normal(size=(B, 1)) * 0.05, jnp.float32)

    vars_ = layer.init(jax.random.key(0), cats)  # global stacked slabs
    w = jnp.zeros((sum(int(c["output_dim"]) for c in configs), 1))

    shard = NamedSharding(mesh, P("data"))
    repl = NamedSharding(mesh, P())
    slabs = jax.tree.map(lambda a: jax.device_put(a, shard),
                         vars_["params"]["slabs"])
    w = jax.device_put(w, repl)
    cats_sh = [jax.device_put(c, shard) for c in cats]
    y_sh = jax.device_put(y, shard)

    tx = optax.sgd(1.0)
    opt_state = jax.tree.map(lambda a: jax.device_put(a, shard)
                             if a.ndim else a, tx.init(slabs))

    def local_step(slabs, w, opt_state, cats, y):
        def loss_fn(sl, wv):
            outs = layer.apply({"params": {"slabs": sl}}, cats)
            x = jnp.concatenate(outs, axis=1)
            return jnp.mean((x @ wv - y) ** 2)

        loss, (gs, gw) = jax.value_and_grad(loss_fn, argnums=(0, 1))(
            slabs, w)
        # dp gradient for w (replicated): resolve via the library helper —
        # it absorbs the VMA-vs-legacy autodiff difference (newer jax
        # auto-psums the replicated-param gradient; pre-VMA jax returns the
        # per-device contribution) — then restore the summed-gradient
        # semantics this test's lr was tuned for. mp gradients local,
        # 1/world scale.
        gw = resolve_dp_gradient(gw, "data") * WORLD
        gs = jax.tree.map(lambda g: g / WORLD, gs)
        updates, opt_state = tx.update(gs, opt_state, slabs)
        slabs = optax.apply_updates(slabs, updates)
        w = w - 1.0 * gw
        return slabs, w, opt_state, jax.lax.pmean(loss, "data")

    step = jax.jit(jax.shard_map(
        local_step, mesh=mesh,
        in_specs=(P("data"), P(), P("data"), P("data"), P("data")),
        out_specs=(P("data"), P(), P("data"), P())))

    losses = []
    for _ in range(40):
        slabs, w, opt_state, loss = step(slabs, w, opt_state, cats_sh, y_sh)
        losses.append(float(loss))
    assert losses[-1] < 0.3 * losses[0], losses[:: len(losses) - 1]


def test_ragged_through_adapter():
    rng = np.random.default_rng(3)
    configs = [{"input_dim": 40, "output_dim": 5, "combiner": "mean"}]
    de = DistributedEmbedding(configs, world_size=1)
    layer = DistributedEmbeddingLayer(de=de)
    lens = rng.integers(0, 4, size=8)
    splits = np.concatenate([[0], np.cumsum(lens)]).astype(np.int32)
    vals = np.zeros(32, np.int32)
    vals[:splits[-1]] = rng.integers(0, 40, size=int(splits[-1]))
    rag = Ragged(values=jnp.asarray(vals), row_splits=jnp.asarray(splits))
    vars_ = layer.init(jax.random.key(0), [rag])
    out = layer.apply(vars_, [rag])[0]
    tab = de.get_weights(vars_["params"]["slabs"])[0]
    want = np.asarray(lookup_fn(
        jnp.asarray(tab), rag, combiner="mean"))
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-5, atol=1e-6)

"""Ragged (variable-hotness) features through the mp-input path.

VERDICT r2 missing #1: the reference's ``dp_input=False`` mode feeds per-rank
inputs straight to local layers, which accept ragged
(``dist_model_parallel.py:289-294`` + ``embedding.py:111-133``), so its mp
mode covers variable hotness. Here :meth:`DistributedEmbedding.pack_mp_inputs`
packs a *global-batch* ``Ragged`` into the plan's ``[values(cap), lengths(b)]``
block layout. Tests mirror ``test_dist_ragged.py``: forward parity vs the
single-process oracle across strategies and column slicing, then an SGD step
through the sparse trainer.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from distributed_embeddings_tpu.ops.embedding_lookup import Ragged
from distributed_embeddings_tpu.parallel import (
    DistributedEmbedding,
    SparseSGD,
    init_hybrid_state,
    make_hybrid_train_step,
)

from test_dist_ragged import (LOCAL_B, MAX_HOT, WORLD, make_mixed_inputs,
                              oracle_forward, ragged_model)


@pytest.fixture(scope="module")
def mesh():
    devs = jax.devices()
    assert len(devs) >= WORLD, "conftest should force 8 CPU devices"
    return Mesh(np.array(devs[:WORLD]), ("data",))


def to_global_inputs(configs, kinds, dist_inputs, shard_rows):
    """Rebuild the global-batch per-feature inputs (dense [WORLD*b, hot]
    arrays / one global Ragged per ragged feature) plus the ``hots`` entries
    pack_mp_inputs needs."""
    cap = LOCAL_B * MAX_HOT
    inputs, hots = [], []
    for i, kind in enumerate(kinds):
        if kind == "dense":
            inputs.append(np.asarray(dist_inputs[i]))
            hots.append(int(np.asarray(dist_inputs[i]).shape[1]))
        else:
            rows = [r for shard in shard_rows[i] for r in shard]
            inputs.append(Ragged.from_lists(rows, capacity=WORLD * cap))
            hots.append(("r", cap))  # tight per-shard capacity
    return inputs, hots


def mp_forward(de, mesh, flat, mp_in):
    def fwd(params, mpi):
        return tuple(de(params, mpi))

    return jax.jit(jax.shard_map(
        fwd, mesh=mesh,
        in_specs=(P("data"), P("data")),
        out_specs=P("data")))(flat, mp_in)


@pytest.mark.parametrize("strategy,column_slice_threshold,row_slice",
                         [("basic", None, None),
                          ("memory_balanced", None, None),
                          ("memory_balanced", 150, None),
                          # row slicing through the mp-input path, and both
                          # slicing modes at once under comm_balanced
                          ("memory_balanced", None, 200),
                          ("comm_balanced", 300, 150)])
def test_mp_ragged_forward_matches_oracle(mesh, strategy,
                                          column_slice_threshold, row_slice):
    rng = np.random.default_rng(41)
    configs, kinds = ragged_model(rng)
    de = DistributedEmbedding(configs, world_size=WORLD, strategy=strategy,
                              dp_input=False,
                              column_slice_threshold=column_slice_threshold,
                              row_slice=row_slice)
    if row_slice is not None:
        assert de.strategy.row_sliced_tables  # the mode actually engages
    flat = de.init(jax.random.key(0), mesh=mesh)
    tables = de.get_weights(flat)
    dist_inputs, shard_rows = make_mixed_inputs(rng, configs, kinds)
    inputs, hots = to_global_inputs(configs, kinds, dist_inputs, shard_rows)
    mp_in = de.pack_mp_inputs(inputs, mesh=mesh, hots=hots)

    expect = oracle_forward(tables, configs, kinds, dist_inputs, shard_rows)
    outs = mp_forward(de, mesh, flat, mp_in)
    assert len(outs) == len(expect)
    for o, e in zip(outs, expect):
        np.testing.assert_allclose(np.asarray(o), np.asarray(e),
                                   rtol=1e-5, atol=1e-6)


def test_mp_ragged_default_capacity(mesh):
    """Without an explicit ("r", cap) hots entry, packing falls back to the
    global capacity per shard — safe (padded) and oracle-equal."""
    rng = np.random.default_rng(59)
    configs, kinds = ragged_model(rng, num_tables=10)
    de = DistributedEmbedding(configs, world_size=WORLD, dp_input=False,
                              strategy="memory_balanced")
    flat = de.init(jax.random.key(1), mesh=mesh)
    tables = de.get_weights(flat)
    dist_inputs, shard_rows = make_mixed_inputs(rng, configs, kinds)
    inputs, _ = to_global_inputs(configs, kinds, dist_inputs, shard_rows)
    mp_in = de.pack_mp_inputs(inputs, mesh=mesh)  # hots inferred
    expect = oracle_forward(tables, configs, kinds, dist_inputs, shard_rows)
    outs = mp_forward(de, mesh, flat, mp_in)
    for o, e in zip(outs, expect):
        np.testing.assert_allclose(np.asarray(o), np.asarray(e),
                                   rtol=1e-5, atol=1e-6)


def test_mp_ragged_capacity_overflow_raises(mesh):
    rng = np.random.default_rng(61)
    configs = [{"input_dim": 50, "output_dim": 4, "combiner": "sum"},
               {"input_dim": 60, "output_dim": 4, "combiner": "sum"},
               {"input_dim": 70, "output_dim": 4, "combiner": "sum"},
               {"input_dim": 80, "output_dim": 4, "combiner": "sum"},
               {"input_dim": 90, "output_dim": 4, "combiner": "sum"},
               {"input_dim": 40, "output_dim": 4, "combiner": "sum"},
               {"input_dim": 30, "output_dim": 4, "combiner": "sum"},
               {"input_dim": 20, "output_dim": 4, "combiner": "sum"}]
    de = DistributedEmbedding(configs, world_size=WORLD, dp_input=False)
    rows = [[1, 2, 3]] * (WORLD * LOCAL_B)  # 3 ids per row, every shard
    rag = Ragged.from_lists(rows, capacity=3 * WORLD * LOCAL_B)
    dense = [np.zeros((WORLD * LOCAL_B, 1), np.int32)] * 7
    with pytest.raises(ValueError, match="capacity"):
        de.pack_mp_inputs([rag] + dense,
                          hots=[("r", 2)] + [1] * 7)  # cap 2 < 3*LOCAL_B


@pytest.mark.slow
def test_mp_ragged_sgd_step_matches_oracle(mesh):
    """One sparse-trainer SGD step with mp input incl. ragged features,
    trajectory-checked against the dense-autodiff oracle."""
    rng = np.random.default_rng(53)
    configs, kinds = ragged_model(rng)
    de = DistributedEmbedding(configs, world_size=WORLD, dp_input=False,
                              strategy="memory_balanced")
    tables0 = [rng.normal(size=(c["input_dim"], c["output_dim"])
                          ).astype(np.float32) for c in configs]
    flat = de.set_weights(tables0, mesh=mesh)
    dist_inputs, shard_rows = make_mixed_inputs(rng, configs, kinds)
    inputs, hots = to_global_inputs(configs, kinds, dist_inputs, shard_rows)
    mp_in = de.pack_mp_inputs(inputs, mesh=mesh, hots=hots)
    lr = 0.3

    emb_opt = SparseSGD()
    tx = optax.sgd(lr)
    total_w = sum(int(c["output_dim"]) for c in configs)
    dense_params = {"w": jnp.asarray(rng.normal(size=(total_w, 1)),
                                     jnp.float32)}

    def loss_fn(dp, emb_outs, batch):
        x = jnp.concatenate([o.reshape(o.shape[0], -1) for o in emb_outs],
                            axis=1)
        return jnp.mean((x @ dp["w"] - batch) ** 2)

    state = init_hybrid_state(de, emb_opt, dense_params, tx,
                              jax.random.key(1), mesh=mesh)
    state = state._replace(emb_params=flat, emb_opt_state=emb_opt.init(flat))
    step_fn = make_hybrid_train_step(de, loss_fn, tx, emb_opt, mesh=mesh,
                                     lr_schedule=lr)
    labels = jnp.asarray(rng.normal(size=(WORLD * LOCAL_B, 1)), jnp.float32)
    dense0 = jax.tree.map(np.asarray, dense_params)  # pre-donation snapshot
    _, state = step_fn(state, mp_in, labels)
    dist_tables = de.get_weights(state.emb_params)

    def ref_loss(tables, dp):
        outs = oracle_forward(tables, configs, kinds, dist_inputs, shard_rows)
        return loss_fn(dp, outs, labels)

    ref_grads, _ = jax.grad(ref_loss, argnums=(0, 1))(
        [jnp.asarray(t) for t in tables0], jax.tree.map(jnp.asarray, dense0))
    ref_tables = [t - lr * g for t, g in zip(tables0, ref_grads)]
    for a, b in zip(dist_tables, ref_tables):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)

"""Production observability plane (ISSUE 17): the mergeable quantile
sketch, the metrics registry + Prometheus rendering, the scrape
endpoint, and the crash flight recorder.

The sketch tests are the acceptance teeth for the serving migration:
every reported quantile must sit within the sketch's GUARANTEED
relative-error bound of the exact numpy reference, and merges must be
associative and commutative (per-rank sketches fold into one fleet view
in any order). The flight-recorder tests pin the black-box contract —
bounded rings, atomic CRC-stamped dump, tamper detection."""

import json
import math
import os
import threading
import urllib.request

import numpy as np
import pytest

from distributed_embeddings_tpu.utils import mplane, obs
from distributed_embeddings_tpu.utils.mplane import (
    FlightRecorder, MetricsRegistry, QuantileSketch)


@pytest.fixture(autouse=True)
def _isolate_recorder():
    mplane.uninstall_flight_recorder()
    yield
    mplane.uninstall_flight_recorder()


# ------------------------------------------------------------ the sketch


def _ref_quantile(vals, q):
    # the sketch ranks with rank = q * (count - 1): numpy's "linear"
    # interpolation on the same definition, then compare midpoints
    return float(np.quantile(np.asarray(vals, np.float64), q))


@pytest.mark.parametrize("dist", ["lognormal", "uniform", "exponential"])
def test_sketch_quantiles_within_relative_error(dist):
    rng = np.random.default_rng(7)
    vals = {
        "lognormal": rng.lognormal(1.0, 1.2, 8000),
        "uniform": rng.uniform(0.5, 500.0, 8000),
        "exponential": rng.exponential(20.0, 8000),
    }[dist]
    sk = QuantileSketch()
    for v in vals:
        sk.observe(float(v))
    for q in (0.01, 0.1, 0.5, 0.9, 0.95, 0.99, 0.999):
        ref = _ref_quantile(vals, q)
        got = sk.quantile(q)
        # the DDSketch guarantee is vs the sample at the rank the sketch
        # reads; numpy interpolates between ranks, so allow one extra
        # accuracy step of slack on top of the guaranteed bound
        assert got == pytest.approx(ref, rel=3 * sk.relative_accuracy), q


def test_sketch_exact_rank_guarantee():
    # against the EXACT order statistic (no interpolation) the bound is
    # the advertised relative_accuracy itself
    rng = np.random.default_rng(11)
    vals = np.sort(rng.lognormal(0.0, 2.0, 5001))
    sk = QuantileSketch()
    for v in vals:
        sk.observe(float(v))
    for q in (0.5, 0.9, 0.99):
        exact = float(vals[int(q * (len(vals) - 1))])
        assert abs(sk.quantile(q) - exact) <= \
            sk.relative_accuracy * exact * (1 + 1e-9)


def test_sketch_empty_and_edge_quantiles():
    sk = QuantileSketch()
    assert sk.quantile(0.5) is None
    assert sk.mean is None
    sk.observe(42.0)
    assert sk.quantile(0.0) == pytest.approx(42.0, rel=0.011)
    assert sk.quantile(1.0) == pytest.approx(42.0, rel=0.011)
    assert sk.mean == 42.0
    with pytest.raises(ValueError):
        sk.quantile(1.5)


def test_sketch_zero_and_negative_values():
    sk = QuantileSketch()
    for v in (0.0, -1.0, 0.0, 5.0):
        sk.observe(v)
    assert sk.count == 4
    assert sk.zero_count == 3
    assert sk.quantile(0.25) == 0.0
    assert sk.quantile(1.0) == pytest.approx(5.0, rel=0.011)


def test_sketch_merge_commutative_and_associative():
    rng = np.random.default_rng(3)
    parts = [rng.lognormal(0.0, 1.0, 700) for _ in range(3)]

    def build(vals):
        s = QuantileSketch()
        for v in vals:
            s.observe(float(v))
        return s

    a_bc = build(parts[0]).merge(build(parts[1]).merge(build(parts[2])))
    ab_c = build(parts[0]).merge(build(parts[1])).merge(build(parts[2]))
    c_ba = build(parts[2]).merge(build(parts[1])).merge(build(parts[0]))
    direct = build(np.concatenate(parts))
    # bucket-count addition: any association/order gives IDENTICAL state
    for other in (ab_c, c_ba, direct):
        assert a_bc.buckets == other.buckets
        assert a_bc.count == other.count
        assert a_bc.sum == pytest.approx(other.sum)
        for q in (0.5, 0.95, 0.99):
            assert a_bc.quantile(q) == other.quantile(q)


def test_sketch_merge_rejects_accuracy_mismatch():
    with pytest.raises(ValueError, match="accuracy"):
        QuantileSketch(0.01).merge(QuantileSketch(0.02))


def test_sketch_dict_roundtrip_preserves_merge():
    rng = np.random.default_rng(5)
    sk = QuantileSketch()
    for v in rng.exponential(3.0, 1000):
        sk.observe(float(v))
    back = QuantileSketch.from_dict(
        json.loads(json.dumps(sk.to_dict())))
    assert back.buckets == sk.buckets
    assert back.quantile(0.99) == sk.quantile(0.99)
    # and the deserialized sketch still merges
    back.merge(sk)
    assert back.count == 2 * sk.count


def test_sketch_collapse_bounds_memory_keeps_high_quantiles():
    rng = np.random.default_rng(9)
    vals = rng.lognormal(0.0, 3.0, 20000)  # many decades -> many buckets
    full = QuantileSketch()
    for v in vals:
        full.observe(float(v))
    assert len(full.buckets) > 512  # the data really needs a collapse
    sk = QuantileSketch(max_buckets=512)
    for v in vals:
        sk.observe(float(v))
    assert len(sk.buckets) <= 512
    # the collapse folds LOW buckets together: every quantile above the
    # collapsed floor — here p95/p99, the ones SLOs read — keeps the
    # guarantee; quantiles below the floor are the sacrificed ones
    for q in (0.95, 0.99, 0.999):
        ref = _ref_quantile(vals, q)
        assert sk.quantile(q) == pytest.approx(ref, rel=0.03), q


# ---------------------------------------------------------- the registry


def test_registry_golden_prometheus_rendering():
    reg = MetricsRegistry()
    reg.counter("detpu_requests_total", "served requests").inc(
        3, outcome="ok")
    reg.counter("detpu_requests_total").inc(1, outcome="shed")
    reg.gauge("detpu_level", "degradation rung").set(2)
    sk = reg.sketch("detpu_latency_ms", "end-to-end latency")
    for v in [10.0] * 99 + [100.0]:
        sk.observe(v)
    text = reg.render()
    lines = text.strip().splitlines()
    assert "# HELP detpu_latency_ms end-to-end latency" in lines
    assert "# TYPE detpu_latency_ms summary" in lines
    assert "# TYPE detpu_level gauge" in lines
    assert "# TYPE detpu_requests_total counter" in lines
    assert 'detpu_requests_total{outcome="ok"} 3' in lines
    assert 'detpu_requests_total{outcome="shed"} 1' in lines
    assert "detpu_level 2" in lines
    assert "detpu_latency_ms_count 100" in lines
    assert "detpu_latency_ms_sum 1090" in lines
    q50 = [ln for ln in lines if ln.startswith(
        'detpu_latency_ms{quantile="0.5"}')]
    assert len(q50) == 1
    assert float(q50[0].split()[-1]) == pytest.approx(10.0, rel=0.011)
    assert text.endswith("\n")


def test_registry_kind_collision_raises():
    reg = MetricsRegistry()
    reg.counter("detpu_x")
    with pytest.raises(TypeError, match="counter"):
        reg.gauge("detpu_x")
    with pytest.raises(TypeError):
        reg.sketch("detpu_x")


def test_registry_collector_pull_model_and_broken_collector():
    reg = MetricsRegistry()
    state = {"n": 0}

    def sync():
        state["n"] += 1
        reg.gauge("detpu_pull").set(state["n"])

    def broken():
        raise RuntimeError("adapter bug")

    reg.register_collector(sync)
    reg.register_collector(broken)
    assert "detpu_pull 1" in reg.render()
    assert "detpu_pull 2" in reg.render()  # re-pulled per scrape


def test_registry_export_file_atomic(tmp_path):
    reg = MetricsRegistry()
    reg.counter("detpu_total").inc(7)
    path = str(tmp_path / "metrics.prom")
    assert reg.export_file(path) == path
    with open(path) as f:
        assert f.read() == reg.render()
    assert not os.path.exists(path + ".tmp")


def test_registry_to_dict_mergeable_across_processes():
    # simulate two ranks exporting + a chief merging their sketches
    ranks = []
    for seed in (0, 1):
        reg = MetricsRegistry()
        sk = reg.sketch("detpu_lat_ms")
        for v in np.random.default_rng(seed).exponential(5.0, 500):
            sk.child().observe(float(v))
        ranks.append(json.loads(json.dumps(reg.to_dict())))
    merged = QuantileSketch.from_dict(
        ranks[0]["detpu_lat_ms"]["series"][0]["value"])
    merged.merge(QuantileSketch.from_dict(
        ranks[1]["detpu_lat_ms"]["series"][0]["value"]))
    assert merged.count == 1000


def test_sync_counters_and_step_metrics_adapters():
    reg = MetricsRegistry()
    mplane.sync_counters(reg, {"served": 10, "shed": 2, "bogus": "x"})
    mplane.sync_step_metrics(reg, {"loss": 0.5, "grad_norm": 1.25,
                                   "skip": None})
    text = reg.render()
    assert 'detpu_events_total{event="served"} 10' in text
    assert 'detpu_events_total{event="shed"} 2' in text
    assert "bogus" not in text  # unconvertible values skipped
    assert "detpu_step_loss 0.5" in text
    assert "detpu_step_grad_norm 1.25" in text
    # the mirror is idempotent (set_total, not inc): re-sync != double
    mplane.sync_counters(reg, {"served": 11})
    assert 'detpu_events_total{event="served"} 11' in reg.render()


def test_concurrent_observe_while_scrape():
    """The race the process-isolated serving driver hits: runtime
    threads observe (mutating sketch buckets AND creating labelled
    children) while the exporter's daemon thread renders. Pre-lock this
    died with ``dictionary changed size during iteration``; post-lock
    every observation must also still be accounted for (none torn)."""
    reg = MetricsRegistry()
    fam = reg.sketch("detpu_race_ms", "observe-while-scrape drill")
    writers, per_writer = 4, 1500
    errors = []
    stop = threading.Event()

    def writer(tid):
        try:
            rng = np.random.default_rng(tid)
            for i in range(per_writer):
                # rotating label sets force child creation mid-scrape
                fam.observe(float(rng.exponential(5.0)),
                            stage=f"s{tid}", shard=str(i % 7))
        except Exception as e:  # noqa: BLE001 - the assertion surface
            errors.append(e)

    def scraper():
        try:
            while not stop.is_set():
                reg.render()
                reg.to_dict()
        except Exception as e:  # noqa: BLE001 - the assertion surface
            errors.append(e)

    scrape = threading.Thread(target=scraper)
    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(writers)]
    scrape.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    scrape.join()
    assert errors == []
    assert sum(sk.count for _, sk in fam.items()) == writers * per_writer


def test_concurrent_observe_while_quantile_under_collapse():
    """Sketch-level: a tiny ``max_buckets`` forces :meth:`_collapse`
    (bucket-dict pops) to interleave with ``quantile`` iteration — the
    tightest version of the torn-read window."""
    sk = QuantileSketch(max_buckets=8)
    errors = []
    done = threading.Event()

    def writer(seed):
        try:
            rng = np.random.default_rng(seed)
            for _ in range(4000):
                sk.observe(float(rng.lognormal(mean=2.0, sigma=3.0)))
        except Exception as e:  # noqa: BLE001 - the assertion surface
            errors.append(e)

    def reader():
        try:
            while not done.is_set():
                sk.quantile(0.99)
                sk.to_dict()
        except Exception as e:  # noqa: BLE001 - the assertion surface
            errors.append(e)

    r = threading.Thread(target=reader)
    ws = [threading.Thread(target=writer, args=(s,)) for s in range(2)]
    r.start()
    for w in ws:
        w.start()
    for w in ws:
        w.join()
    done.set()
    r.join()
    assert errors == []
    assert sk.count == 2 * 4000
    assert len(sk.buckets) <= 8
    assert sk.quantile(0.5) is not None


# ---------------------------------------------------- the scrape endpoint


def test_http_exporter_scrape_roundtrip():
    reg = MetricsRegistry()
    reg.counter("detpu_scrapeme_total").inc(5)
    exp = mplane.start_http_exporter(reg, port=0)
    assert exp is not None and exp.port > 0
    try:
        with urllib.request.urlopen(exp.url(), timeout=10) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith("text/plain")
            body = resp.read().decode("utf-8")
        assert "detpu_scrapeme_total 5" in body
        # non-metrics paths 404 rather than leaking anything
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{exp.port}/secrets", timeout=10)
    finally:
        exp.stop()


def test_http_exporter_off_by_default(monkeypatch):
    monkeypatch.delenv(mplane.METRICS_PORT_ENV, raising=False)
    assert mplane.start_http_exporter(MetricsRegistry()) is None
    monkeypatch.setenv(mplane.METRICS_PORT_ENV, "not-a-port")
    assert mplane.start_http_exporter(MetricsRegistry()) is None


def test_http_exporter_env_port(monkeypatch):
    monkeypatch.setenv(mplane.METRICS_PORT_ENV, "0")
    reg = MetricsRegistry()
    reg.gauge("detpu_env_g").set(1)
    exp = mplane.start_http_exporter(reg)
    assert exp is not None
    try:
        with urllib.request.urlopen(exp.url(), timeout=10) as resp:
            assert b"detpu_env_g 1" in resp.read()
    finally:
        exp.stop()


# --------------------------------------------------- the flight recorder


def test_flight_recorder_ring_is_bounded(tmp_path):
    rec = FlightRecorder(str(tmp_path / "bb.json"), capacity=8)
    for i in range(50):
        rec.note_step(i, {"loss": float(i)})
        rec.note_event("tick", i=i)
    snap = rec.snapshot()
    assert len(snap["steps"]) == 8
    assert len(snap["events"]) == 8
    assert snap["steps"][-1]["step"] == 49
    assert snap["steps"][0]["step"] == 42  # oldest evicted


def test_flight_recorder_dump_and_verify(tmp_path):
    path = str(tmp_path / "run.blackbox.json")
    rec = FlightRecorder(path, capacity=4)
    rec.note_step(10, {"loss": 0.1})
    rec.note_event("training_rollback", restored_step=8)
    rec.note_stats({"latency_p99_ms": 12.5})
    out = rec.dump("nan_escalation", last_good_step=10,
                   unhealthy_tables=["table3"])
    assert out == path
    payload = mplane.verify_blackbox(path)
    assert payload["trigger"] == "nan_escalation"
    assert payload["context"]["unhealthy_tables"] == ["table3"]
    assert payload["steps"][0]["metrics"]["loss"] == 0.1
    assert payload["events"][0]["event"] == "training_rollback"
    assert payload["stats"][0]["stats"]["latency_p99_ms"] == 12.5
    assert not os.path.exists(path + ".tmp")  # atomic: no tmp debris


def test_flight_recorder_tamper_detected(tmp_path):
    path = str(tmp_path / "bb.json")
    rec = FlightRecorder(path, capacity=4)
    rec.note_step(1, {"loss": 1.0})
    rec.dump("preemption")
    doc = json.load(open(path))
    doc["payload"]["trigger"] = "nothing_happened"
    with open(path, "w") as f:
        json.dump(doc, f)
    with pytest.raises(ValueError, match="CRC"):
        mplane.verify_blackbox(path)


def test_flight_recorder_dump_never_raises(tmp_path):
    rec = FlightRecorder(str(tmp_path / "no" / "such" / "dir" / "bb.json"))
    rec.note_step(1, {})
    assert rec.dump("unhandled_crash") is None  # OSError swallowed


def test_flight_recorder_jsonable_coerces_device_payloads(tmp_path):
    path = str(tmp_path / "bb.json")
    rec = FlightRecorder(path)
    rec.note_step(0, {"arr": np.arange(3), "scalar": np.float32(1.5),
                      "weird": object()})
    rec.dump("unhandled_crash", err=ValueError("boom"))
    payload = mplane.verify_blackbox(path)
    m = payload["steps"][0]["metrics"]
    assert m["arr"] == [0, 1, 2]
    assert m["scalar"] == 1.5
    assert isinstance(m["weird"], str)
    assert "boom" in payload["context"]["err"]


def test_install_flight_recorder_idempotent_and_event_tap(tmp_path):
    path = str(tmp_path / "bb.json")
    rec = mplane.install_flight_recorder(path, capacity=16)
    assert rec is not None
    assert mplane.install_flight_recorder(path) is rec  # same path: kept
    # record_event flows into the ring through the tap
    obs.record_event("snapshot_published", version=3)
    events = rec.snapshot()["events"]
    assert any(e["event"] == "snapshot_published" and e["version"] == 3
               for e in events)
    # a new path REPLACES the recorder
    other = mplane.install_flight_recorder(str(tmp_path / "bb2.json"))
    assert other is not rec
    assert mplane.flight_recorder() is other


def test_install_flight_recorder_disabled_by_env(tmp_path, monkeypatch):
    monkeypatch.setenv(mplane.BLACKBOX_ENV, "0")
    assert mplane.install_flight_recorder(str(tmp_path / "bb.json")) is None
    assert mplane.flight_recorder() is None


def test_blackbox_ring_env_controls_capacity(tmp_path, monkeypatch):
    monkeypatch.setenv(mplane.BLACKBOX_RING_ENV, "3")
    rec = FlightRecorder(str(tmp_path / "bb.json"))
    assert rec.capacity == 3
    for i in range(9):
        rec.note_event("e", i=i)
    assert len(rec.snapshot()["events"]) == 3


# ------------------------------------------------- compare_bench gate


def test_compare_bench_obs_plane_gate():
    from tools import compare_bench as cb

    def rec(stats_us=120.0, scrape=1.5, dump=2.0, ok=1, rc=0):
        return {"metric": "x",
                "obs_plane": {"stats_wall_us": stats_us,
                              "scrape_ms": scrape, "dump_ms": dump,
                              "scrape_ok": ok,
                              "steady_state_recompiles": rc}}

    base = rec()
    assert cb.check_obs_plane(base, rec()) == 0
    # within the 100% cost ratchet
    assert cb.check_obs_plane(base, rec(stats_us=230.0)) == 0
    # beyond it: the plane's own read path got structurally slower
    assert cb.check_obs_plane(base, rec(stats_us=300.0)) == 1
    assert cb.check_obs_plane(base, rec(scrape=3.5)) == 1
    assert cb.check_obs_plane(base, rec(dump=4.5)) == 1
    # below the noise floor the ratchet is skipped: 3us -> 9us is timer
    # jitter, not a regression
    cheap = rec(stats_us=3.0)
    assert cb.check_obs_plane(cheap, rec(stats_us=9.0)) == 0
    # hard failures regardless of the baseline
    assert cb.check_obs_plane(base, rec(ok=0)) == 1
    assert cb.check_obs_plane(base, rec(rc=2)) == 1
    # missing section vs a baseline that has it fails; both-missing and
    # new-section-no-baseline pass (rounds legitimately add sections)
    assert cb.check_obs_plane(base, {"metric": "x"}) == 1
    assert cb.check_obs_plane({"metric": "x"}, {"metric": "x"}) == 0
    assert cb.check_obs_plane({"metric": "x"}, rec()) == 0

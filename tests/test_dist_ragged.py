"""Ragged (variable-hotness) inputs through the distributed path.

VERDICT r1 #9: the reference's variable-hotness kernel capability is
reachable from ``DistributedEmbedding`` through the ``Embedding`` layers it
owns; here the static-capacity CSR encoding travels inside the padded id
all-to-all as ``[values(cap), lengths(b)]`` blocks. Tests use the
single-process-reference pattern: dist-vs-oracle forward equality with mixed
ragged/dense features, then one SGD step both via shard_map autodiff and via
the sparse trainer, comparing updated weights.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from distributed_embeddings_tpu.ops import embedding_lookup
from distributed_embeddings_tpu.ops.embedding_lookup import Ragged
from distributed_embeddings_tpu.parallel import (
    DistributedEmbedding,
    SparseSGD,
    hybrid_value_and_grad,
    init_hybrid_state,
    make_hybrid_train_step,
)

WORLD = 8
LOCAL_B = 3
MAX_HOT = 4


@pytest.fixture(scope="module")
def mesh():
    devs = jax.devices()
    assert len(devs) >= WORLD, "conftest should force 8 CPU devices"
    return Mesh(np.array(devs[:WORLD]), ("data",))


def ragged_model(rng, num_tables=10):
    configs, kinds = [], []
    for i in range(num_tables):
        width = int(rng.integers(1, 9))
        rows = int(rng.integers(4, 100))
        ragged = bool(i % 2 == 0)
        combiner = (str(rng.choice(["sum", "mean"])) if ragged
                    else rng.choice([None, "sum", "mean"]))
        configs.append({"input_dim": rows, "output_dim": width,
                        "combiner": combiner})
        kinds.append("ragged" if ragged else "dense")
    return configs, kinds


def make_mixed_inputs(rng, configs, kinds):
    """Per-feature global inputs: ragged features as stacked per-shard
    static-capacity CSR (shard s owns leaf rows ``s*cap:(s+1)*cap`` /
    ``s*(b+1):(s+1)*(b+1)``), dense features as ``[WORLD*b, hot]``."""
    cap = LOCAL_B * MAX_HOT
    dist_inputs, shard_rows = [], []
    for cfg, kind in zip(configs, kinds):
        if kind == "dense":
            hot = int(rng.integers(1, 5)) if cfg["combiner"] else 1
            ids = rng.integers(0, cfg["input_dim"],
                               size=(WORLD * LOCAL_B, hot))
            dist_inputs.append(jnp.asarray(ids, jnp.int32))
            shard_rows.append(None)
            continue
        rows_per_shard = []
        vals_parts, split_parts = [], []
        for s in range(WORLD):
            rows = [list(rng.integers(0, cfg["input_dim"],
                                      size=int(rng.integers(0, MAX_HOT + 1))))
                    for _ in range(LOCAL_B)]
            rows_per_shard.append(rows)
            r = Ragged.from_lists(rows, capacity=cap)
            vals_parts.append(r.values)
            split_parts.append(r.row_splits)
        dist_inputs.append(Ragged(values=jnp.concatenate(vals_parts),
                                  row_splits=jnp.concatenate(split_parts)))
        shard_rows.append(rows_per_shard)
    return dist_inputs, shard_rows


def oracle_forward(tables, configs, kinds, dist_inputs, shard_rows):
    cap = LOCAL_B * MAX_HOT
    outs = []
    for i, (cfg, kind) in enumerate(zip(configs, kinds)):
        t = jnp.asarray(tables[i])
        if kind == "dense":
            o = embedding_lookup(t, dist_inputs[i], combiner=cfg["combiner"])
            outs.append(o.reshape(o.shape[0], -1))
            continue
        shard_outs = [
            embedding_lookup(t, Ragged.from_lists(rows, capacity=cap),
                             combiner=cfg["combiner"])
            for rows in shard_rows[i]]
        outs.append(jnp.concatenate(shard_outs, axis=0))
    return outs


def dist_forward(de, mesh, flat, dist_inputs):
    def fwd(params, inps):
        return tuple(de(params, list(inps)))

    return jax.jit(jax.shard_map(
        fwd, mesh=mesh,
        in_specs=(P("data"), P("data")),
        out_specs=P("data")))(flat, tuple(dist_inputs))


@pytest.mark.parametrize("strategy,column_slice_threshold",
                         [("basic", None), ("memory_balanced", None),
                          ("memory_balanced", 150)])
def test_ragged_forward_matches_oracle(mesh, strategy, column_slice_threshold):
    rng = np.random.default_rng(41)
    configs, kinds = ragged_model(rng)
    de = DistributedEmbedding(configs, world_size=WORLD, strategy=strategy,
                              column_slice_threshold=column_slice_threshold)
    flat = de.init(jax.random.key(0), mesh=mesh)
    tables = de.get_weights(flat)
    dist_inputs, shard_rows = make_mixed_inputs(rng, configs, kinds)

    expect = oracle_forward(tables, configs, kinds, dist_inputs, shard_rows)
    outs = dist_forward(de, mesh, flat, dist_inputs)
    assert len(outs) == len(expect)
    for o, e in zip(outs, expect):
        np.testing.assert_allclose(np.asarray(o), np.asarray(e),
                                   rtol=1e-5, atol=1e-6)


def test_ragged_world1_matches_oracle():
    rng = np.random.default_rng(43)
    configs, kinds = ragged_model(rng, num_tables=6)
    de = DistributedEmbedding(configs, world_size=1)
    flat = de.init(jax.random.key(2))
    tables = de.get_weights(flat)
    dist_inputs, shard_rows = make_mixed_inputs(rng, configs, kinds)
    # world1: single "shard" holding everything — rebuild at global batch
    cap = LOCAL_B * MAX_HOT
    flat_inputs = []
    for i, kind in enumerate(kinds):
        if kind == "dense":
            flat_inputs.append(dist_inputs[i])
        else:
            rows = [r for shard in shard_rows[i] for r in shard]
            flat_inputs.append(Ragged.from_lists(rows, capacity=WORLD * cap))
    outs = de(flat, flat_inputs)
    expect = oracle_forward(tables, configs, kinds, dist_inputs, shard_rows)
    for o, e in zip(outs, expect):
        # world1 preserves original output rank (reference call semantics);
        # the oracle is flattened to the distributed layout
        o = np.asarray(o).reshape(np.asarray(o).shape[0], -1)
        np.testing.assert_allclose(o, np.asarray(e), rtol=1e-5, atol=1e-6)


def test_ragged_sgd_step_matches_oracle(mesh):
    """Autodiff backward through the ragged exchange (hybrid_value_and_grad)."""
    rng = np.random.default_rng(47)
    configs, kinds = ragged_model(rng)
    de = DistributedEmbedding(configs, world_size=WORLD,
                              strategy="memory_balanced")
    tables0 = [rng.normal(size=(c["input_dim"], c["output_dim"])
                          ).astype(np.float32) for c in configs]
    flat = de.set_weights(tables0, mesh=mesh)
    dist_inputs, shard_rows = make_mixed_inputs(rng, configs, kinds)
    lr = 0.5

    def local_loss(params, inps):
        outs = de(params, list(inps))
        return sum(jnp.mean(o ** 2) for o in outs)

    def step(params, inps):
        _, grads = hybrid_value_and_grad(
            local_loss, mp_mask=True, axis_name="data")(params, inps)
        return jax.tree.map(lambda p, g: p - lr * g, params, grads)

    new_flat = jax.jit(jax.shard_map(
        step, mesh=mesh, in_specs=(P("data"), P("data")),
        out_specs=P("data")))(flat, tuple(dist_inputs))
    dist_tables = de.get_weights(new_flat)

    def ref_loss(tables):
        outs = oracle_forward(tables, configs, kinds, dist_inputs, shard_rows)
        return sum(jnp.mean(o ** 2) for o in outs)

    ref_grads = jax.grad(ref_loss)([jnp.asarray(t) for t in tables0])
    ref_tables = [t - lr * g for t, g in zip(tables0, ref_grads)]
    for a, b in zip(dist_tables, ref_tables):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.slow
@pytest.mark.parametrize("column_slice_threshold", [None, 150])
def test_ragged_sparse_trainer_step_matches_oracle(mesh,
                                                   column_slice_threshold):
    """The manual IndexedSlices-style backward (sparse_apply_gradients) with
    ragged features — including through column-sliced tables — trajectory-
    checked against a dense-autodiff oracle."""
    rng = np.random.default_rng(53)
    configs, kinds = ragged_model(rng)
    de = DistributedEmbedding(configs, world_size=WORLD,
                              strategy="memory_balanced",
                              column_slice_threshold=column_slice_threshold)
    tables0 = [rng.normal(size=(c["input_dim"], c["output_dim"])
                          ).astype(np.float32) for c in configs]
    flat = de.set_weights(tables0, mesh=mesh)
    dist_inputs, shard_rows = make_mixed_inputs(rng, configs, kinds)
    lr = 0.3

    emb_opt = SparseSGD()
    tx = optax.sgd(lr)
    total_w = sum(int(c["output_dim"]) for c in configs)
    dense_params = {"w": jnp.asarray(rng.normal(size=(total_w, 1)),
                                     jnp.float32)}

    def loss_fn(dp, emb_outs, batch):
        x = jnp.concatenate([o.reshape(o.shape[0], -1) for o in emb_outs],
                            axis=1)
        pred = x @ dp["w"]
        return jnp.mean((pred - batch) ** 2)

    state = init_hybrid_state(de, emb_opt, dense_params, tx,
                              jax.random.key(1), mesh=mesh)
    state = state._replace(emb_params=flat,
                           emb_opt_state=emb_opt.init(flat))
    step_fn = make_hybrid_train_step(de, loss_fn, tx, emb_opt, mesh=mesh,
                                     lr_schedule=lr)
    labels = jnp.asarray(rng.normal(size=(WORLD * LOCAL_B, 1)), jnp.float32)
    dense0 = jax.tree.map(np.asarray, dense_params)  # pre-donation snapshot
    _, state = step_fn(state, tuple(dist_inputs), labels)
    dist_tables = de.get_weights(state.emb_params)

    def ref_loss(tables, dp):
        outs = oracle_forward(tables, configs, kinds, dist_inputs, shard_rows)
        return loss_fn(dp, outs, labels)

    ref_grads, dense_grads = jax.grad(ref_loss, argnums=(0, 1))(
        [jnp.asarray(t) for t in tables0],
        jax.tree.map(jnp.asarray, dense0))
    ref_tables = [t - lr * g for t, g in zip(tables0, ref_grads)]
    for a, b in zip(dist_tables, ref_tables):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_sparse_coo_through_distributed_wrapper(mesh):
    """SparseIds (COO) inputs convert to CSR on entry and match the ragged
    oracle — the wrapper accepts everything the op layer does (beyond the
    reference, whose distributed path is dense-only)."""
    from distributed_embeddings_tpu.ops.embedding_lookup import SparseIds

    rng = np.random.default_rng(71)
    configs, kinds = ragged_model(rng)
    de = DistributedEmbedding(configs, world_size=WORLD,
                              strategy="memory_balanced")
    flat = de.init(jax.random.key(0), mesh=mesh)
    tables = de.get_weights(flat)
    dist_inputs, shard_rows = make_mixed_inputs(rng, configs, kinds)

    # re-encode every ragged feature as per-shard COO stacked like the
    # Ragged convention ([WORLD*cap] values / [WORLD*(b+1)] splits)
    cap = LOCAL_B * MAX_HOT
    coo_inputs = []
    for i, (inp, kind) in enumerate(zip(dist_inputs, kinds)):
        if kind == "dense":
            coo_inputs.append(inp)
            continue
        idx_parts, val_parts = [], []
        for s in range(WORLD):
            rows = shard_rows[i][s]
            ind = np.full((cap, 2), LOCAL_B, np.int32)  # pad rows >= batch
            vals = np.zeros(cap, np.int32)
            k = 0
            for rr, ids in enumerate(rows):
                for v in ids:
                    ind[k] = (rr, k)
                    vals[k] = v
                    k += 1
            idx_parts.append(ind)
            val_parts.append(vals)
        coo_inputs.append(SparseIds(
            indices=jnp.asarray(np.concatenate(idx_parts)),
            values=jnp.asarray(np.concatenate(val_parts)),
            dense_shape=(LOCAL_B, MAX_HOT)))

    def fwd(params, inps):
        return tuple(de(params, list(inps)))

    # SparseIds shards: indices [WORLD*cap, 2] / values [WORLD*cap] split
    # along dim 0 by the mesh axis
    outs = jax.jit(jax.shard_map(
        fwd, mesh=mesh, in_specs=(P("data"), P("data")),
        out_specs=P("data")))(flat, tuple(coo_inputs))
    expect = oracle_forward(tables, configs, kinds, dist_inputs, shard_rows)
    for o, e in zip(outs, expect):
        np.testing.assert_allclose(np.asarray(o), np.asarray(e),
                                   rtol=1e-5, atol=1e-6)

"""Self-healing training driver (ISSUE 3): on-device non-finite guard,
invalid-input policies, and preemption-safe resume.

The acceptance contracts under test:

* with the guard on (``DETPU_NANGUARD``, default), an engineered NaN/Inf
  batch leaves params AND optimizer state bitwise-unchanged, advances the
  step counter, and flags ``skipped_steps`` — single- and multi-device;
* K consecutive non-finite losses escalate with the last good step named;
* each invalid-id policy (``clamp`` / ``drop`` / ``raise``) behaves as
  documented and the violation count surfaces as ``invalid_id_count``;
* a run preempted mid-training (``DETPU_FAULT=preempt@<step>`` — a real
  self-SIGTERM) and resumed produces a final checkpoint CRC-identical to
  the uninterrupted run's (tables, optimizer components, dense, step).
"""

import json
import math
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh

from distributed_embeddings_tpu.ops.embedding_lookup import Ragged
from distributed_embeddings_tpu.parallel import (
    DistributedEmbedding, SparseAdagrad, SparseAdam, SparseSGD,
    init_hybrid_state, make_hybrid_train_loop, make_hybrid_train_step,
    run_resilient)
from distributed_embeddings_tpu.utils import (
    fast_forward, runtime, save_train_state)

WORLD = 8
CONFIGS = [{"input_dim": 20 + 3 * i, "output_dim": 4} for i in range(10)]


def _loss_fn(dp, outs, batch):
    return (sum(jnp.mean(o) for o in outs) * dp["w"]
            - jnp.mean(batch)) ** 2


def _build(world=1, emb_opt=None, dense_tx=None, nan_guard=None,
           with_metrics=True, **de_kw):
    de = DistributedEmbedding(CONFIGS, world_size=world, **de_kw)
    emb_opt = emb_opt or SparseAdagrad()
    tx = dense_tx or optax.sgd(0.1)
    mesh = (Mesh(np.array(jax.devices()[:world]), ("data",))
            if world > 1 else None)
    state = init_hybrid_state(de, emb_opt, {"w": jnp.float32(0.5)}, tx,
                              jax.random.key(0), mesh=mesh)
    step = make_hybrid_train_step(de, _loss_fn, tx, emb_opt, mesh=mesh,
                                  with_metrics=with_metrics,
                                  nan_guard=nan_guard)
    return de, tx, emb_opt, state, step


def _batch(seed, nan=False):
    rng = np.random.default_rng(seed)
    cats = [jnp.asarray(rng.integers(0, c["input_dim"], 16), jnp.int32)
            for c in CONFIGS]
    y = jnp.asarray(rng.normal(size=(16,)), jnp.float32)
    if nan:
        y = y.at[3].set(jnp.nan)
    return cats, y


def _snap(state):
    return jax.tree.map(lambda a: np.asarray(a).copy(), state._asdict())


def _assert_state_equal(a, b, keys=("emb_params", "emb_opt_state",
                                    "dense_params", "dense_opt_state")):
    for k in keys:
        jax.tree.map(lambda x, y: np.testing.assert_array_equal(x, y),
                     a[k], b[k])


# ------------------------------------------------- on-device non-finite guard


@pytest.mark.parametrize("world", [1, WORLD])
def test_nanguard_skip_is_bitwise_noop(world):
    """Acceptance: an injected non-finite batch leaves params/opt state
    bitwise-unchanged, increments ``skipped_steps``, advances ``step``."""
    de, tx, emb_opt, state, step = _build(world=world, nan_guard=True)
    cats, y = _batch(0)
    loss, state, m = step(state, cats, y)  # one healthy step first
    assert int(np.asarray(m["skipped_steps"]).max()) == 0
    before = _snap(state)
    cats2, ynan = _batch(1, nan=True)
    loss2, state2, m2 = step(state, cats2, ynan)
    assert not math.isfinite(float(np.asarray(loss2).reshape(-1)[0]))
    sk = np.asarray(m2["skipped_steps"])
    assert (sk == 1).all(), sk  # every rank skips in lockstep
    _assert_state_equal(before, _snap(state2))
    assert int(np.asarray(state2.step)) == int(before["step"]) + 1


def test_nanguard_protects_adam_aux_state():
    """SparseAdam carries a non-slab step count (and optax.adam its own):
    the skip must hold those bitwise too, not just the slabs."""
    de, tx, emb_opt, state, step = _build(
        emb_opt=SparseAdam(), dense_tx=optax.adam(0.1), nan_guard=True)
    cats, y = _batch(0)
    _, state, _ = step(state, cats, y)
    before = _snap(state)
    _, state2, m2 = step(state, *_batch(1, nan=True))
    assert int(np.asarray(m2["skipped_steps"]).max()) == 1
    _assert_state_equal(before, _snap(state2))


def test_nanguard_off_env_propagates(monkeypatch):
    """DETPU_NANGUARD=0 builds the unguarded step: the NaN reaches the
    params (the historical behavior, and the proof the guard is load-
    bearing)."""
    monkeypatch.setenv("DETPU_NANGUARD", "0")
    de, tx, emb_opt, state, step = _build(nan_guard=None,
                                          with_metrics=False)
    cats, ynan = _batch(0, nan=True)
    loss, state2 = step(state, cats, ynan)
    assert not math.isfinite(float(loss))
    dense = np.asarray(state2.dense_params["w"])
    assert not np.isfinite(dense).all()


def test_nanguard_in_scanned_loop_skips_only_poisoned_step():
    """Inside ``make_hybrid_train_loop``'s scan a poisoned step k skips
    itself; steps k+1.. train from the untouched state."""
    de = DistributedEmbedding(CONFIGS, world_size=1)
    emb_opt, tx = SparseAdagrad(), optax.sgd(0.1)
    state = init_hybrid_state(de, emb_opt, {"w": jnp.float32(0.5)}, tx,
                              jax.random.key(0))
    loop = make_hybrid_train_loop(de, _loss_fn, tx, emb_opt,
                                  with_metrics=True, nan_guard=True)
    K = 3
    rng = np.random.default_rng(0)
    cat_stacks = [jnp.asarray(rng.integers(0, c["input_dim"], (K, 16)),
                              jnp.int32) for c in CONFIGS]
    y = jnp.asarray(rng.normal(size=(K, 16)), jnp.float32)
    y = y.at[1, 2].set(jnp.inf)  # poison the middle scanned step
    losses, state2, m = loop(state, cat_stacks, y)
    losses = np.asarray(losses)
    assert math.isfinite(losses[0]) and math.isfinite(losses[2])
    assert not math.isfinite(losses[1])
    sk = np.asarray(m["skipped_steps"]).reshape(K)
    assert sk.tolist() == [0, 1, 0]
    assert int(np.asarray(state2.step)) == K


# --------------------------------------------------- invalid-input policies


def test_invalid_policy_validation():
    with pytest.raises(ValueError, match="invalid_id_policy"):
        DistributedEmbedding(CONFIGS, world_size=1,
                             invalid_id_policy="ignore")


def test_clamp_policy_counts_and_defined_reads():
    """Default policy: defined (clamped) forward reads, dropped backward,
    and the violation surfaces in ``invalid_id_count``."""
    de, tx, emb_opt, state, step = _build()
    cats, y = _batch(0)
    cats[2] = cats[2].at[0].set(-7)
    cats[5] = cats[5].at[1].set(10 ** 6)
    before_t2 = de.get_table(state.emb_params, 2)
    loss, state2, m = step(state, cats, y)
    assert math.isfinite(float(loss))
    assert int(np.asarray(m["invalid_id_count"]).sum()) == 2
    # dropped backward: the clamp target (row 0 of table 2) trained nothing
    # from the bad id beyond what the batch's legitimate ids did — checked
    # indirectly by the forward being finite; the bitwise-drop semantics
    # are covered by the op-layer tests
    del before_t2


def test_drop_policy_reads_zero_rows():
    configs = [{"input_dim": 10, "output_dim": 4}]
    de = DistributedEmbedding(configs, world_size=1,
                              invalid_id_policy="drop")
    assert de.masked_reads
    params = de.init(jax.random.key(0))
    out = np.asarray(de(params, [jnp.asarray([0, 3, -2, 11], jnp.int32)])[0])
    assert (out[2] == 0).all() and (out[3] == 0).all()
    assert (out[0] != 0).any()


def test_raise_policy_eager_forward_and_pack():
    configs = [{"input_dim": 10, "output_dim": 4}]
    de = DistributedEmbedding(configs, world_size=1,
                              invalid_id_policy="raise")
    params = de.init(jax.random.key(0))
    ok = de(params, [jnp.asarray([0, 9], jnp.int32)])[0]
    assert np.isfinite(np.asarray(ok)).all()
    with pytest.raises(runtime.InvalidInputError, match="outside"):
        de(params, [jnp.asarray([0, 3, -2, 11], jnp.int32)])
    de2 = DistributedEmbedding(
        [{"input_dim": 10, "output_dim": 4} for _ in range(2)],
        world_size=2, dp_input=False, invalid_id_policy="raise")
    with pytest.raises(runtime.InvalidInputError, match="outside"):
        de2.pack_mp_inputs([np.array([1, -3]), np.array([2, 4])])
    # a packed MpInputs batch was validated at pack time: the driver's
    # per-batch re-check must skip it, not crash on len(MpInputs)
    packed = de2.pack_mp_inputs([np.array([1, 3]), np.array([2, 4])])
    assert de2.check_inputs(packed) is None


def test_check_inputs_counts_and_overflow():
    configs = [{"input_dim": 10, "output_dim": 4, "combiner": "sum"}]
    de = DistributedEmbedding(configs, world_size=1)
    rag = Ragged(values=jnp.asarray([1, 2, 3, 4], jnp.int32),
                 row_splits=jnp.asarray([0, 3, 6], jnp.int32))  # claims 6>4
    assert de.check_inputs([rag]) == 2  # 6 - 4 overflowed
    de_r = DistributedEmbedding(configs, world_size=1,
                                ragged_overflow_raise=True)
    with pytest.raises(runtime.InvalidInputError, match="capacity"):
        de_r.check_inputs([rag])


def test_check_inputs_ignores_sparse_padding():
    """SparseIds padding (rows >= dense_shape[0]) carries arbitrary
    values by contract — a healthy padded batch must pass 'raise'."""
    from distributed_embeddings_tpu.ops.embedding_lookup import SparseIds

    configs = [{"input_dim": 10, "output_dim": 4, "combiner": "sum"}]
    de = DistributedEmbedding(configs, world_size=1,
                              invalid_id_policy="raise")
    sp = SparseIds(
        indices=jnp.asarray([[0, 0], [1, 0], [4, 0], [4, 1]], jnp.int32),
        values=jnp.asarray([3, 7, -1, 99], jnp.int32),  # padding garbage
        dense_shape=(4, 2))
    assert de.check_inputs([sp]) == 0
    bad = SparseIds(
        indices=jnp.asarray([[0, 0], [1, 0], [4, 0], [4, 1]], jnp.int32),
        values=jnp.asarray([3, -2, -1, 99], jnp.int32),  # live row 1 bad
        dense_shape=(4, 2))
    with pytest.raises(runtime.InvalidInputError, match="1 id"):
        de.check_inputs([bad])


def test_run_resilient_escalates_ragged_overflow():
    configs = [{"input_dim": 50, "output_dim": 4, "combiner": "sum"}]
    de = DistributedEmbedding(configs, world_size=1,
                              ragged_overflow_raise=True)
    emb_opt, tx = SparseSGD(), optax.sgd(0.1)
    state = init_hybrid_state(de, emb_opt, {"w": jnp.float32(0.5)}, tx,
                              jax.random.key(0))
    step = make_hybrid_train_step(de, _loss_fn, tx, emb_opt,
                                  with_metrics=True)

    def data(start):
        rag = Ragged(values=jnp.asarray(np.arange(8), jnp.int32),
                     row_splits=jnp.asarray([0, 3, 6, 9, 12], jnp.int32))
        yield [rag], jnp.ones((4,), jnp.float32)

    with pytest.raises(runtime.InvalidInputError):
        run_resilient(step, state, data, de=de)


# --------------------------------------------------------- resilient driver


def _driver_data(start, n=10):
    for i in range(start, n):
        yield _batch(1000 + i)


def test_preempt_resume_crc_identical(tmp_path, monkeypatch):
    """Acceptance: a run self-SIGTERM'd via ``DETPU_FAULT=preempt@4`` and
    resumed reaches the same final step with a checkpoint CRC-identical
    (every table, optimizer component, dense.msgpack incl. step) to the
    uninterrupted run's."""
    de, tx, emb_opt, state, step = _build(with_metrics=False)
    ref = run_resilient(step, state, _driver_data, de=de)
    assert ref.step == 10 and ref.stop_reason == "exhausted"
    save_train_state(str(tmp_path / "ref"), de, ref.state)

    ckpt = str(tmp_path / "ck")
    de2, tx2, emb_opt2, state2, step2 = _build(with_metrics=False)
    monkeypatch.setenv(runtime.FAULT_ENV, "preempt@4")
    r1 = run_resilient(step2, state2, _driver_data, de=de2,
                       checkpoint_dir=ckpt, emb_optimizer=emb_opt2,
                       dense_tx=tx2)
    assert r1.preempted and r1.stop_reason == "preempted"
    assert r1.step == 5  # the in-flight step FINISHED before the exit
    sentinel = json.load(open(ckpt + ".resume.json"))
    assert sentinel["step"] == 5 and sentinel["reason"] == "preempted"

    monkeypatch.delenv(runtime.FAULT_ENV)
    de3, tx3, emb_opt3, state3, step3 = _build(with_metrics=False)
    r2 = run_resilient(step3, state3, _driver_data, de=de3,
                       checkpoint_dir=ckpt, emb_optimizer=emb_opt3,
                       dense_tx=tx3)
    assert r2.step == 10 and not r2.preempted
    assert r2.last_loss == ref.last_loss
    assert not os.path.exists(ckpt + ".resume.json")  # cleared on success
    crc_ref = json.load(open(tmp_path / "ref" / "meta.json"))["files"]
    crc_new = json.load(open(os.path.join(ckpt, "meta.json")))["files"]
    assert crc_ref == crc_new


def test_escalation_names_last_good_step(tmp_path):
    de, tx, emb_opt, state, step = _build(with_metrics=False)
    ckpt = str(tmp_path / "ck")

    def data(start):
        for i in range(start, 10):
            yield _batch(i, nan=(i >= 2))

    with pytest.raises(runtime.NonFiniteLossError,
                       match="last good step: 1"):
        run_resilient(step, state, data, de=de, checkpoint_dir=ckpt,
                      escalate_after=3, save_on_exit=False)
    # the escalation checkpointed the (guard-clean) state first
    meta = json.load(open(os.path.join(ckpt, "meta.json")))
    assert meta["num_tables"] == len(CONFIGS)


def test_escalation_keys_on_guard_verdict_not_just_loss():
    """The guard can skip on non-finite GRADIENT energy with a finite
    loss; when the step is instrumented, the driver must count those
    skips from the on-device ``skipped_steps`` flag."""
    class FakeState:
        step = 0

    def fake_step(state, cat_inputs, batch):
        # finite loss, but the guard flagged the step as skipped
        return (np.float32(0.5), FakeState(),
                {"skipped_steps": np.array([1], np.int32),
                 "id_overflow": np.array([0], np.int32)})

    def data(start):
        for i in range(start, 10):
            yield None, None

    with pytest.raises(runtime.NonFiniteLossError,
                       match="last good step: -1"):
        run_resilient(fake_step, FakeState(), data, de=None,
                      escalate_after=2, metrics_interval=0)


def test_until_step_and_periodic_cadence(tmp_path):
    de, tx, emb_opt, state, step = _build(with_metrics=False)
    ckpt = str(tmp_path / "ck")
    r = run_resilient(step, state, _driver_data, de=de,
                      checkpoint_dir=ckpt, checkpoint_every_steps=2,
                      until_step=5)
    assert r.step == 5 and r.stop_reason == "until_step"
    # saves at steps 2, 4 (cadence) + final = 3
    assert r.checkpoints_saved == 3


def test_on_step_stop_and_step_numbers():
    de, tx, emb_opt, state, step = _build(with_metrics=False)
    seen = []

    def on_step(s, loss, metrics, st):
        seen.append(s)
        assert math.isfinite(loss)
        return s == 3

    r = run_resilient(step, state, _driver_data, de=de, on_step=on_step)
    assert seen == [0, 1, 2, 3]
    assert r.stop_reason == "on_step" and r.step == 4


# --------------------------------------------- rollback-and-replay recovery


def _stream(n, bad=(), drop=()):
    """Deterministic stream factory: positions in ``bad`` yield a NaN'd
    batch; positions in ``drop`` are removed entirely (the clean
    equivalent a recovered run must match bit for bit)."""
    def data(start):
        idx = [i for i in range(n) if i not in drop]
        for i in idx[start:]:
            yield _batch(2000 + i, nan=(i in bad))
    return data


def _mesh(world):
    return (Mesh(np.array(jax.devices()[:world]), ("data",))
            if world > 1 else None)


@pytest.mark.parametrize("world", [1, WORLD])
def test_rollback_quarantine_crc_identical(tmp_path, world):
    """Acceptance: a run that hits an injected bad batch rolls back to a
    ring checkpoint, quarantines it (ledger + events), finishes, and its
    final checkpoint is CRC-identical to an uninterrupted run trained on
    the same stream with that batch skipped — single-process AND the
    8-virtual-device mesh."""
    from distributed_embeddings_tpu.utils import obs

    obs.drain_events()  # test isolation: only THIS run's events below
    de, tx, emb_opt, state, step = _build(world=world, nan_guard=True)
    ck = str(tmp_path / "ck")
    r = run_resilient(step, state, _stream(10, bad={5}), de=de,
                      checkpoint_dir=ck, checkpoint_every_steps=2,
                      resume=True, emb_optimizer=emb_opt, dense_tx=tx,
                      mesh=_mesh(world), escalate_after=1, keep_last_n=2)
    assert r.step == 9 and r.stop_reason == "exhausted"
    assert r.rollbacks == 1 and r.quarantined == (5,)
    assert r.rollback_time_s > 0
    # the ledger survives on disk beside the checkpoint
    ledger = json.load(open(ck + ".quarantine.json"))
    assert ledger["quarantined"] == [5] and ledger["rollbacks"] == 1
    # recovery recorded through obs.record_event (tentpole contract)
    assert obs.drain_events("training_rollback")
    assert obs.drain_events("batch_quarantined")
    assert obs.drain_events("training_recovered")

    de2, tx2, emb_opt2, state2, step2 = _build(world=world, nan_guard=True)
    ref = str(tmp_path / "ref")
    r2 = run_resilient(step2, state2, _stream(10, drop={5}), de=de2,
                       checkpoint_dir=ref, checkpoint_every_steps=2,
                       resume=True, emb_optimizer=emb_opt2, dense_tx=tx2,
                       mesh=_mesh(world), keep_last_n=2)
    assert r2.step == 9 and r2.rollbacks == 0
    crc = json.load(open(os.path.join(ck, "meta.json")))["files"]
    crc_ref = json.load(open(os.path.join(ref, "meta.json")))["files"]
    assert crc == crc_ref


def test_rollback_budget_exhaustion_attaches_ledger(tmp_path):
    """A stream poisoned past the retry budget must still fire the old
    terminal NonFiniteLossError — now with the quarantine ledger
    attached (message + attributes)."""
    de, tx, emb_opt, state, step = _build(nan_guard=True)
    ck = str(tmp_path / "ck")
    with pytest.raises(runtime.NonFiniteLossError,
                       match="could not recover: rollback budget "
                             "exhausted") as ei:
        run_resilient(step, state, _stream(10, bad=set(range(4, 10))),
                      de=de, checkpoint_dir=ck, checkpoint_every_steps=2,
                      resume=True, emb_optimizer=emb_opt, dense_tx=tx,
                      escalate_after=2, keep_last_n=2, rollback_max=1,
                      quarantine_max=4)
    assert "Quarantine ledger" in str(ei.value)
    assert ei.value.quarantined == (4, 5)  # the first window, bisected
    assert ei.value.rollbacks == 1
    # terminal escalation still parks the clean state first
    meta = json.load(open(os.path.join(ck, "meta.json")))
    assert meta["num_tables"] == len(CONFIGS)


def test_quarantine_budget_exhaustion(tmp_path):
    """DETPU_QUARANTINE_MAX bounds how much history the recovery may
    rewrite: one slot means the second poisoned batch in the window is
    terminal."""
    de, tx, emb_opt, state, step = _build(nan_guard=True)
    ck = str(tmp_path / "ck")
    with pytest.raises(runtime.NonFiniteLossError,
                       match="poisoned beyond the quarantine budget"):
        run_resilient(step, state, _stream(10, bad={4, 5}), de=de,
                      checkpoint_dir=ck, checkpoint_every_steps=2,
                      resume=True, emb_optimizer=emb_opt, dense_tx=tx,
                      escalate_after=2, keep_last_n=2, quarantine_max=1)


def test_rollback_without_checkpoint_dir_is_terminal():
    """No checkpoint ring -> the escalation stays terminal (the
    pre-recovery behavior), with the failure reason named."""
    de, tx, emb_opt, state, step = _build(nan_guard=True)
    with pytest.raises(runtime.NonFiniteLossError,
                       match="no checkpoint_dir to roll back to"):
        run_resilient(step, state, _stream(8, bad={2, 3, 4}), de=de,
                      escalate_after=3)


def test_nanguard_off_disables_rollback(tmp_path, monkeypatch):
    """DETPU_NANGUARD=0: a replayed window cannot be trusted (updates
    were not guarded), so recovery refuses and the escalation is
    terminal with the poisoned-state warning intact."""
    monkeypatch.setenv("DETPU_NANGUARD", "0")
    de, tx, emb_opt, state, step = _build(nan_guard=False,
                                          with_metrics=False)
    ck = str(tmp_path / "ck")
    with pytest.raises(runtime.NonFiniteLossError,
                       match="DETPU_NANGUARD=0"):
        run_resilient(step, state, _stream(8, bad={2, 3, 4}), de=de,
                      checkpoint_dir=ck, checkpoint_every_steps=2,
                      resume=True, emb_optimizer=emb_opt, dense_tx=tx,
                      escalate_after=3, keep_last_n=2)


def test_recovery_resume_preserves_ledger(tmp_path, monkeypatch):
    """A run preempted AFTER a recovery must resume with the quarantine
    ledger honored: the poisoned batch is never re-fed and the rollback
    budget is not refreshed."""
    de, tx, emb_opt, state, step = _build(nan_guard=True)
    ck = str(tmp_path / "ck")
    r1 = run_resilient(step, state, _stream(12, bad={3}), de=de,
                       checkpoint_dir=ck, checkpoint_every_steps=2,
                       resume=True, emb_optimizer=emb_opt, dense_tx=tx,
                       escalate_after=1, keep_last_n=2, until_step=6)
    assert r1.quarantined == (3,) and r1.step == 6
    de2, tx2, emb_opt2, state2, step2 = _build(nan_guard=True)
    r2 = run_resilient(step2, state2, _stream(12, bad={3}), de=de2,
                       checkpoint_dir=ck, checkpoint_every_steps=2,
                       resume=True, emb_optimizer=emb_opt2, dense_tx=tx2,
                       escalate_after=1, keep_last_n=2)
    assert r2.step == 11 and r2.rollbacks == 1  # ledger, not a re-rollback
    assert r2.quarantined == (3,)
    # clean-equivalent reference
    de3, tx3, emb_opt3, state3, step3 = _build(nan_guard=True)
    ref = str(tmp_path / "ref")
    r3 = run_resilient(step3, state3, _stream(12, drop={3}), de=de3,
                       checkpoint_dir=ref, checkpoint_every_steps=2,
                       resume=True, emb_optimizer=emb_opt3, dense_tx=tx3,
                       keep_last_n=2)
    crc = json.load(open(os.path.join(ck, "meta.json")))["files"]
    crc_ref = json.load(open(os.path.join(ref, "meta.json")))["files"]
    assert crc == crc_ref


def test_rollback_refuses_foreign_lineage_checkpoints(tmp_path):
    """A fresh run (resume=False) over a dead run's checkpoints must
    never roll back into them: every save is stamped with a run-lineage
    id, and candidates from another lineage are refused — the escalation
    is terminal instead of silently splicing foreign parameters."""
    de, tx, emb_opt, state, step = _build(nan_guard=True)
    ck = str(tmp_path / "ck")
    r1 = run_resilient(step, state, _stream(6), de=de, checkpoint_dir=ck,
                       checkpoint_every_steps=2, resume=True,
                       emb_optimizer=emb_opt, dense_tx=tx, keep_last_n=2)
    assert r1.step == 6  # run A left generations behind
    de2, tx2, emb_opt2, state2, step2 = _build(nan_guard=True)
    with pytest.raises(runtime.NonFiniteLossError,
                       match="no healthy checkpoint generation"):
        run_resilient(step2, state2, _stream(6, bad={0, 1, 2}), de=de2,
                      checkpoint_dir=ck, checkpoint_every_steps=2,
                      resume=False, emb_optimizer=emb_opt2, dense_tx=tx2,
                      escalate_after=3, keep_last_n=2)


def test_fresh_run_clears_stale_ledger(tmp_path):
    """resume=False in a dirty directory must DELETE a previous run's
    quarantine ledger — otherwise this run's own later resume would
    inherit stale skip positions and a spent rollback budget."""
    from distributed_embeddings_tpu.parallel import quarantine_ledger_path

    de, tx, emb_opt, state, step = _build(nan_guard=True)
    ck = str(tmp_path / "ck")
    r1 = run_resilient(step, state, _stream(8, bad={3}), de=de,
                       checkpoint_dir=ck, checkpoint_every_steps=2,
                       resume=True, emb_optimizer=emb_opt, dense_tx=tx,
                       escalate_after=1, keep_last_n=2)
    assert r1.quarantined == (3,)
    assert os.path.isfile(quarantine_ledger_path(ck))
    de2, tx2, emb_opt2, state2, step2 = _build(nan_guard=True)
    r2 = run_resilient(step2, state2, _stream(8), de=de2,
                       checkpoint_dir=ck, checkpoint_every_steps=2,
                       resume=False, emb_optimizer=emb_opt2, dense_tx=tx2,
                       keep_last_n=2)
    assert r2.step == 8 and r2.quarantined == ()  # pos 3 fed normally
    assert not os.path.isfile(quarantine_ledger_path(ck))


def test_sentinels_name_unhealthy_table():
    """Per-table health sentinels: a NaN entering through ONE table's
    cotangent names exactly that table — in the metrics, the contract
    check, and obs.unhealthy_tables."""
    from distributed_embeddings_tpu.utils import obs

    de = DistributedEmbedding(CONFIGS, world_size=1)
    emb_opt, tx = SparseAdagrad(), optax.sgd(0.1)
    state = init_hybrid_state(de, emb_opt, {"w": jnp.float32(0.5)}, tx,
                              jax.random.key(0))

    def loss_fn(dp, outs, batch):
        # per-table coefficients: poisoning batch[:, t] NaNs only
        # table t's cotangent
        return sum(batch[:, i].mean() * jnp.mean(o)
                   for i, o in enumerate(outs)) * dp["w"]

    step = make_hybrid_train_step(de, loss_fn, tx, emb_opt,
                                  with_metrics=True, nan_guard=True)
    rng = np.random.default_rng(0)
    cats = [jnp.asarray(rng.integers(0, c["input_dim"], 16), jnp.int32)
            for c in CONFIGS]
    y = jnp.asarray(rng.normal(size=(16, len(CONFIGS))), jnp.float32)
    loss, state, m = step(state, cats, y.at[0, 2].set(jnp.nan))
    assert int(np.asarray(m["skipped_steps"]).max()) == 1
    nf = np.asarray(m["table_nonfinite"]).reshape(-1, len(CONFIGS))
    assert (nf.sum(axis=0) > 0).tolist() == [
        i == 2 for i in range(len(CONFIGS))]
    assert obs.unhealthy_tables(m) == [2]
    violations = obs.TableHealthContract().check(m)
    assert len(violations) == 1 and violations[0].startswith("table 2:")
    # healthy batch: clean bill, and a magnitude contract can also fire
    loss2, state, m2 = step(state, cats, y)
    assert obs.unhealthy_tables(m2) == []
    tight = obs.TableHealthContract(max_grad_norm=1e-12)
    assert len(tight.check(m2)) == len(CONFIGS)


def test_nan_fault_injection_quarantines(tmp_path, monkeypatch):
    """DETPU_FAULT=nan@<step> poisons the batch in-flight: the guard
    skips organically and the recovery quarantines exactly that stream
    position."""
    de, tx, emb_opt, state, step = _build(nan_guard=True)
    ck = str(tmp_path / "ck")
    monkeypatch.setenv(runtime.FAULT_ENV, "nan@3")
    r = run_resilient(step, state, _stream(8), de=de, checkpoint_dir=ck,
                      checkpoint_every_steps=2, resume=True,
                      emb_optimizer=emb_opt, dense_tx=tx,
                      escalate_after=1, keep_last_n=2)
    assert r.quarantined == (3,) and r.rollbacks == 1
    assert r.step == 7  # 8 batches minus the quarantined one


def test_badbatch_fault_injection_counts_invalid(monkeypatch):
    """DETPU_FAULT=badbatch@<step> corrupts the categorical ids: under
    the default clamp policy the run survives and the violation surfaces
    in invalid_id_count; under 'raise' it escalates."""
    de, tx, emb_opt, state, step = _build(nan_guard=True)
    monkeypatch.setenv(runtime.FAULT_ENV, "badbatch@1")
    seen = {}

    def on_step(s, loss, metrics, st):
        seen[s] = int(np.asarray(metrics["invalid_id_count"]).sum())
        return False

    r = run_resilient(step, state, _stream(4), de=de, on_step=on_step,
                      metrics_interval=0)
    assert r.step == 4 and seen[1] > 0 and seen[0] == 0 and seen[2] == 0

    de2, tx2, emb_opt2, state2, step2 = _build(
        nan_guard=True, invalid_id_policy="raise")
    with pytest.raises(runtime.InvalidInputError):
        run_resilient(step2, state2, _stream(4), de=de2,
                      metrics_interval=0)


def test_nan_badbatch_fault_parsing(monkeypatch):
    monkeypatch.setenv(runtime.FAULT_ENV, "nan@5,badbatch@7,raise:x:1")
    assert runtime.nan_steps() == (5,)
    assert runtime.badbatch_steps() == (7,)
    # the @-entries must not confuse the mode:point parser
    assert ("raise", "x", "1") in runtime._fault_specs()
    monkeypatch.setenv(runtime.FAULT_ENV, "nan@2,nan@9")
    assert runtime.nan_steps() == (2, 9)
    monkeypatch.setenv(runtime.FAULT_ENV, "nan@oops")
    assert runtime.nan_steps() == ()
    monkeypatch.delenv(runtime.FAULT_ENV)
    assert runtime.nan_steps() == () and runtime.badbatch_steps() == ()


def test_oovflood_fault_parsing(monkeypatch):
    monkeypatch.setenv(runtime.FAULT_ENV, "oovflood@3,nan@5,raise:x:1")
    assert runtime.oovflood_steps() == (3,)
    assert runtime.nan_steps() == (5,)
    # the @-entry must not confuse the mode:point parser
    assert ("raise", "x", "1") in runtime._fault_specs()
    monkeypatch.setenv(runtime.FAULT_ENV, "oovflood@2, oovflood@9 ")
    assert runtime.oovflood_steps() == (2, 9)
    monkeypatch.setenv(runtime.FAULT_ENV, "oovflood@nope")
    assert runtime.oovflood_steps() == ()
    monkeypatch.delenv(runtime.FAULT_ENV)
    assert runtime.oovflood_steps() == ()


def test_burst_fault_parsing(monkeypatch):
    monkeypatch.setenv(runtime.FAULT_ENV, "burst@2,oovflood@3,raise:x:1")
    assert runtime.burst_steps() == (2,)
    assert runtime.oovflood_steps() == (3,)
    # the @-entry must not confuse the mode:point parser
    assert ("raise", "x", "1") in runtime._fault_specs()
    monkeypatch.setenv(runtime.FAULT_ENV, "burst@1, burst@4 ")
    assert runtime.burst_steps() == (1, 4)
    monkeypatch.setenv(runtime.FAULT_ENV, "burst@soon")
    assert runtime.burst_steps() == ()
    monkeypatch.delenv(runtime.FAULT_ENV)
    assert runtime.burst_steps() == ()


def test_oovflood_injects_fresh_ids(monkeypatch):
    """The oovflood drill swaps a batch's integer leaves for a burst of
    never-before-seen ids — distinct within the burst, deterministic per
    stream position, and int-dtype-preserving."""
    from distributed_embeddings_tpu.parallel import resilient as res

    cats = [np.arange(8, dtype=np.int32),
            np.zeros((4, 2), np.int64),
            np.ones((3,), np.float32)]  # non-integer leaf untouched
    out = res._oovflood_ids(cats, spos=3)
    assert out[0].dtype == np.int32 and out[1].dtype == np.int64
    flood = np.concatenate([out[0].reshape(-1), out[1].reshape(-1)])
    assert len(set(flood.tolist())) == flood.size  # all distinct
    assert flood.min() >= 1_000_000_000  # far past any sane vocab
    assert np.array_equal(out[2], cats[2])
    out2 = res._oovflood_ids(cats, spos=3)
    assert np.array_equal(out[0], out2[0])  # deterministic per position
    out3 = res._oovflood_ids(cats, spos=4)
    assert not np.array_equal(out[0], out3[0])  # fresh per position


# ----------------------------------------------------- fast_forward / misc


def test_fast_forward_forms():
    calls = []

    def factory(start):
        calls.append(start)
        return iter(range(start, 6))

    assert list(fast_forward(factory, 2)) == [2, 3, 4, 5]
    assert calls == [2]

    class Seekable:
        def iter_from(self, start):
            return iter(range(start, 6))

    assert list(fast_forward(Seekable(), 3)) == [3, 4, 5]
    assert list(fast_forward(range(6), 4)) == [4, 5]
    assert list(fast_forward(range(6), 0)) == [0, 1, 2, 3, 4, 5]
    with pytest.raises(ValueError):
        fast_forward(range(6), -1)


def test_preempt_step_parsing(monkeypatch):
    monkeypatch.setenv(runtime.FAULT_ENV, "preempt@7")
    assert runtime.preempt_step() == 7
    monkeypatch.setenv(runtime.FAULT_ENV,
                       "raise:backend:1, preempt@3 ,slow:x")
    assert runtime.preempt_step() == 3
    # the preempt entry must not confuse the mode:point parser
    assert ("raise", "backend", "1") in runtime._fault_specs()
    monkeypatch.setenv(runtime.FAULT_ENV, "preempt@nope")
    assert runtime.preempt_step() is None
    monkeypatch.delenv(runtime.FAULT_ENV)
    assert runtime.preempt_step() is None


# ------------------------------------------------- the crash flight recorder


@pytest.fixture()
def _blackbox_isolation():
    from distributed_embeddings_tpu.utils import mplane
    mplane.uninstall_flight_recorder()
    yield
    mplane.uninstall_flight_recorder()


def test_rollback_exhaustion_dumps_blackbox(tmp_path, _blackbox_isolation):
    """A terminal escalation leaves a CRC-intact post-mortem beside the
    checkpoint naming the trigger, with the recovery events ringed in
    (the tentpole's black-box contract)."""
    from distributed_embeddings_tpu.parallel import resilient as rz
    from distributed_embeddings_tpu.utils import mplane

    de, tx, emb_opt, state, step = _build(nan_guard=True)
    ck = str(tmp_path / "ck")
    with pytest.raises(runtime.NonFiniteLossError):
        run_resilient(step, state, _stream(10, bad=set(range(4, 10))),
                      de=de, checkpoint_dir=ck, checkpoint_every_steps=2,
                      resume=True, emb_optimizer=emb_opt, dense_tx=tx,
                      escalate_after=2, keep_last_n=2, rollback_max=1,
                      quarantine_max=4)
    path = rz.blackbox_path(ck)
    payload = mplane.verify_blackbox(path)
    assert payload["trigger"] == "rollback_exhaustion"
    assert payload["context"]["rollbacks"] == 1
    assert payload["context"]["quarantined"] == [4, 5]
    # the recovery events rode the obs tap into the ring
    kinds = {e["event"] for e in payload["events"]}
    assert "training_rollback" in kinds
    assert "batch_quarantined" in kinds


def test_quarantine_exhaustion_dumps_blackbox(tmp_path,
                                              _blackbox_isolation):
    from distributed_embeddings_tpu.parallel import resilient as rz
    from distributed_embeddings_tpu.utils import mplane

    de, tx, emb_opt, state, step = _build(nan_guard=True)
    ck = str(tmp_path / "ck")
    with pytest.raises(runtime.NonFiniteLossError,
                       match="quarantine budget"):
        run_resilient(step, state, _stream(10, bad={4, 5}), de=de,
                      checkpoint_dir=ck, checkpoint_every_steps=2,
                      resume=True, emb_optimizer=emb_opt, dense_tx=tx,
                      escalate_after=2, keep_last_n=2, quarantine_max=1)
    payload = mplane.verify_blackbox(rz.blackbox_path(ck))
    assert payload["trigger"] == "quarantine_exhaustion"


def test_preemption_dumps_blackbox(tmp_path, monkeypatch,
                                   _blackbox_isolation):
    from distributed_embeddings_tpu.parallel import resilient as rz
    from distributed_embeddings_tpu.utils import mplane

    de, tx, emb_opt, state, step = _build(with_metrics=False)
    ck = str(tmp_path / "ck")
    monkeypatch.setenv(runtime.FAULT_ENV, "preempt@3")
    r = run_resilient(step, state, _driver_data, de=de,
                      checkpoint_dir=ck, emb_optimizer=emb_opt,
                      dense_tx=tx, exit_on_preempt=False)
    monkeypatch.delenv(runtime.FAULT_ENV)
    assert r.preempted
    payload = mplane.verify_blackbox(rz.blackbox_path(ck))
    assert payload["trigger"] == "preemption"
    assert payload["context"]["step"] == r.step


def test_unhandled_crash_dumps_blackbox(tmp_path, _blackbox_isolation):
    """ANY exception escaping the train loop leaves a post-mortem with
    the ringed step metrics and the error named — the last line of
    defense."""
    from distributed_embeddings_tpu.parallel import resilient as rz
    from distributed_embeddings_tpu.utils import mplane

    de, tx, emb_opt, state, step = _build()
    ck = str(tmp_path / "ck")

    def data(start):
        for i in range(start, 10):
            if i == 3:
                raise RuntimeError("disk on fire")
            yield _batch(i)

    with pytest.raises(RuntimeError, match="disk on fire"):
        run_resilient(step, state, data, de=de, checkpoint_dir=ck,
                      metrics_interval=1, save_on_exit=False)
    payload = mplane.verify_blackbox(rz.blackbox_path(ck))
    assert payload["trigger"] == "unhandled_crash"
    assert "disk on fire" in payload["context"]["error"]
    assert payload["context"]["error_type"] == "RuntimeError"
    # metrics_interval=1: the ring holds the pre-crash step summaries
    assert [s["step"] for s in payload["steps"]] == [0, 1, 2]


def test_blackbox_disabled_by_env(tmp_path, monkeypatch,
                                  _blackbox_isolation):
    from distributed_embeddings_tpu.parallel import resilient as rz
    from distributed_embeddings_tpu.utils import mplane

    monkeypatch.setenv(mplane.BLACKBOX_ENV, "0")
    de, tx, emb_opt, state, step = _build(with_metrics=False)
    ck = str(tmp_path / "ck")

    def data(start):
        for i in range(start, 5):
            if i == 2:
                raise RuntimeError("quiet crash")
            yield _batch(i)

    with pytest.raises(RuntimeError):
        run_resilient(step, state, data, de=de, checkpoint_dir=ck,
                      save_on_exit=False)
    assert not os.path.exists(rz.blackbox_path(ck))

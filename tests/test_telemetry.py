"""Embedding telemetry observatory (ISSUE 5): jit-carried hot-row
sketches, per-rank load accounting, and static memory accounting.

The acceptance teeth: engineered Zipfian inputs on the 8-device CPU mesh
must surface PLANTED heavy hitters in the per-table top-k and a
known-imbalanced sharding in the per-rank load accumulators; training
outcomes must be bitwise-identical with telemetry on vs off; the
telemetry must be genuinely jit-carried (no host callbacks in the
audited jaxpr, zero steady-state recompiles); and the memory report must
shape-check on the reference configs without backend execution.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh

from distributed_embeddings_tpu.analysis import (audit_step_fn,
                                                 memory as dmem,
                                                 telemetry as tel)
from distributed_embeddings_tpu.ops.embedding_lookup import Ragged
from distributed_embeddings_tpu.parallel import (
    DistributedEmbedding, SparseAdagrad, SparseSGD, init_hybrid_state,
    make_hybrid_train_loop, make_hybrid_train_step, run_resilient)
from distributed_embeddings_tpu.utils import obs, power_law_ids

WORLD = 8
CFG = tel.TelemetryConfig(depth=4, buckets=512, topk=8, candidates=32)


@pytest.fixture
def mesh():
    devs = jax.devices()
    assert len(devs) >= WORLD, "conftest must expose 8 cpu devices"
    return Mesh(np.array(devs[:WORLD]), ("data",))


def _loss_fn(dp, outs, batch):
    del batch
    x = jnp.concatenate([o.reshape(o.shape[0], -1) for o in outs], axis=1)
    return jnp.mean((x @ dp["w"]) ** 2)


def _setup(mesh, configs, telemetry=CFG, **step_kw):
    de = DistributedEmbedding(configs, world_size=WORLD)
    tx = optax.sgd(0.01)
    emb_opt = SparseSGD()
    cols = sum(int(c["output_dim"]) for c in configs)
    dense_params = {"w": jnp.full((cols, 1), 0.1, jnp.float32)}
    state = init_hybrid_state(de, emb_opt, dense_params, tx,
                              jax.random.key(0), mesh=mesh)
    step = make_hybrid_train_step(de, _loss_fn, tx, emb_opt, mesh=mesh,
                                  nan_guard=False, telemetry=telemetry,
                                  **step_kw)
    return de, state, step


# ------------------------------------------------------------- sketch math


def test_cms_exact_at_low_load():
    # far below collision load, the min-over-depth estimate is exact
    cms = jnp.zeros((4, 512), jnp.int32)
    ids = jnp.asarray([3, 3, 3, 17, 17, 99], jnp.int32)
    live = jnp.ones((6,), bool)
    cms = tel.cms_update(cms, ids, live)
    est = tel.cms_query(cms, jnp.asarray([3, 17, 99, 42], jnp.int32))
    assert est.tolist() == [3, 2, 1, 0]


def test_cms_masked_positions_add_nothing():
    cms = jnp.zeros((2, 64), jnp.int32)
    ids = jnp.asarray([5, 5, 5], jnp.int32)
    cms = tel.cms_update(cms, ids, jnp.asarray([True, False, True]))
    assert int(tel.cms_query(cms, jnp.asarray([5], jnp.int32))[0]) == 2


def test_cms_never_undercounts():
    # overload a tiny sketch: estimates may inflate but never shrink
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 1000, 5000).astype(np.int32)
    cms = tel.cms_update(jnp.zeros((2, 32), jnp.int32),
                         jnp.asarray(ids), jnp.ones((5000,), bool))
    true = np.bincount(ids, minlength=1000)
    est = np.asarray(tel.cms_query(cms, jnp.arange(1000, dtype=jnp.int32)))
    assert (est >= true).all()


def test_record_ids_topk_tracks_heavy_hitter():
    wstate = {
        "cms": jnp.zeros((4, 512), jnp.int32),
        "topk_ids": jnp.full((4,), tel.TOPK_EMPTY, jnp.int32),
        "topk_est": jnp.zeros((4,), jnp.int32),
        "ids": jnp.zeros((1,), jnp.float32),
    }
    rng = np.random.default_rng(1)
    for _ in range(3):
        ids = rng.integers(0, 400, 128).astype(np.int32)
        ids[:40] = 7  # ~31% heavy hitter
        wstate = tel.record_ids(wstate, jnp.asarray(ids),
                                jnp.ones((128,), bool), CFG)
    top = np.asarray(wstate["topk_ids"])
    est = np.asarray(wstate["topk_est"])
    assert top[0] == 7  # slot 0 is the best estimate
    assert est[0] >= 120  # >= the true count (CMS never undercounts)
    assert float(wstate["ids"][0]) == 3 * 128


# ------------------------------------------ acceptance: planted hot rows


def test_zipf_planted_hot_rows_recovered_8dev(mesh):
    configs = [{"input_dim": 500, "output_dim": 8} for _ in range(8)]
    de, state, step = _setup(mesh, configs)
    telem = tel.init_telemetry(de, CFG, mesh=mesh)
    rng = np.random.default_rng(0)
    batch = 64
    planted = {0: 7, 3: 123, 6: 499}
    for _ in range(6):
        cats = []
        for t in range(8):
            ids = power_law_ids(rng, 500, (batch,)).astype(np.int32)
            if t in planted:
                ids[rng.permutation(batch)[:batch // 4]] = planted[t]
            cats.append(jnp.asarray(ids))
        _, state, telem = step(state, cats, None, telem)
    hot = tel.hot_rows(de, telem)
    for tid, row in planted.items():
        rows = [r for r, _ in hot[tid]]
        assert row in rows, (tid, row, hot[tid])
    # the planted row dominates its table's ranking
    assert hot[0][0][0] == 7
    # load accounting: 6 steps x 8 tables x 64 ids, uniformly routed
    lb = tel.load_balance(telem)
    assert lb["steps"] == 6
    np.testing.assert_allclose(sum(lb["per_rank_ids"]), 6 * 8 * batch)
    assert lb["imbalance_ratio"] == pytest.approx(1.0)


def test_imbalanced_sharding_shows_in_per_rank_histogram(mesh):
    # table 7 is ragged with ~10x the ids of every 1-hot dense table;
    # under the basic placement its owning rank routes ~10x the load
    configs = [{"input_dim": 300, "output_dim": 8,
                "combiner": "sum" if i == 7 else None}
               for i in range(8)]
    de, state, step = _setup(mesh, configs)
    telem = tel.init_telemetry(de, CFG, mesh=mesh)
    rng = np.random.default_rng(0)
    batch, hot = 64, 10
    local_b = batch // WORLD
    cap = local_b * hot
    for _ in range(3):
        cats = []
        for t in range(8):
            if t == 7:
                vals = power_law_ids(rng, 300, (WORLD * cap,))
                splits = np.tile(
                    np.arange(local_b + 1, dtype=np.int32) * hot, WORLD)
                cats.append(Ragged(values=jnp.asarray(vals, jnp.int32),
                                   row_splits=jnp.asarray(splits)))
            else:
                cats.append(jnp.asarray(
                    power_law_ids(rng, 300, (batch,)), jnp.int32))
        _, state, telem = step(state, cats, None, telem)
    lb = tel.load_balance(telem)
    loads = lb["per_rank_ids"]
    # 7 ranks at 3*64 ids, one at 3*640
    assert max(loads) == pytest.approx(3 * batch * hot)
    assert sorted(loads)[-2] == pytest.approx(3 * batch)
    assert lb["imbalance_ratio"] > 4.0


# -------------------------------------- acceptance: bitwise-identical


def test_training_bitwise_identical_with_telemetry_on_vs_off(mesh):
    configs = [{"input_dim": 200, "output_dim": 8} for _ in range(8)]
    rng = np.random.default_rng(3)
    batches = [[jnp.asarray(rng.integers(0, 200, 32), jnp.int32)
                for _ in range(8)] for _ in range(3)]

    def run(telemetry):
        de, state, step = _setup(mesh, configs, telemetry=telemetry)
        telem = (tel.init_telemetry(de, CFG, mesh=mesh)
                 if telemetry else None)
        for cats in batches:
            if telemetry:
                loss, state, telem = step(state, cats, None, telem)
            else:
                loss, state = step(state, cats, None)
        return loss, state

    loss_off, state_off = run(False)
    loss_on, state_on = run(CFG)
    assert np.asarray(loss_off).tobytes() == np.asarray(loss_on).tobytes()
    for a, b in zip(jax.tree_util.tree_leaves(state_off),
                    jax.tree_util.tree_leaves(state_on)):
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes()


# ----------------------------- acceptance: jit-carried, no host interop


def test_no_host_interop_and_contract_census(mesh):
    configs = [{"input_dim": 100, "output_dim": 8} for _ in range(8)]
    de, state, step = _setup(mesh, configs, with_metrics=True)
    telem = tel.init_telemetry(de, CFG, mesh=mesh)
    cats = [jax.ShapeDtypeStruct((32,), jnp.int32) for _ in range(8)]
    abs_of = lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype)  # noqa: E731
    rep = audit_step_fn(
        step, (jax.tree.map(abs_of, state), cats, None,
               jax.tree.map(abs_of, telem)),
        world=WORLD, label="telemetry_step")
    assert rep.host_interop == []
    # telemetry adds NO collectives: the exchange census stays 2fwd+1bwd
    assert rep.a2a_census() == {"id_exchange_fwd": 1,
                                "out_exchange_fwd": 1,
                                "grad_exchange_bwd": 1}
    assert not rep.dtype_leaks


def test_zero_steady_state_recompiles(mesh):
    configs = [{"input_dim": 100, "output_dim": 8} for _ in range(8)]
    de, state, step = _setup(mesh, configs)
    telem = tel.init_telemetry(de, CFG, mesh=mesh)
    rng = np.random.default_rng(0)

    def batch():
        return [jnp.asarray(rng.integers(0, 100, 32), jnp.int32)
                for _ in range(8)]

    obs.install_compile_listener()
    # two warmup steps: the first returns state/telemetry re-laid-out by
    # the out_specs (replicated leaves pick up mesh shardings), so the
    # SECOND call is the first with steady-state input layouts
    for _ in range(2):
        _, state, telem = step(state, batch(), None, telem)
    jax.block_until_ready(state.step)
    before = obs.counters().get("recompiles", 0)
    for _ in range(3):
        _, state, telem = step(state, batch(), None, telem)
    jax.block_until_ready(state.step)
    assert obs.counters().get("recompiles", 0) - before == 0


def test_scan_loop_carries_one_telemetry_state(mesh):
    configs = [{"input_dim": 100, "output_dim": 8} for _ in range(8)]
    de = DistributedEmbedding(configs, world_size=WORLD)
    tx = optax.sgd(0.01)
    emb_opt = SparseSGD()
    dense_params = {"w": jnp.full((64, 1), 0.1, jnp.float32)}
    state = init_hybrid_state(de, emb_opt, dense_params, tx,
                              jax.random.key(0), mesh=mesh)
    loop = make_hybrid_train_loop(de, _loss_fn, tx, emb_opt, mesh=mesh,
                                  nan_guard=False, telemetry=CFG)
    rng = np.random.default_rng(0)
    K, batch = 4, 32
    cat_stacks = [jnp.asarray(rng.integers(0, 100, (K, batch)), jnp.int32)
                  for _ in range(8)]
    telem = tel.init_telemetry(de, CFG, mesh=mesh)
    losses, state, telem = loop(state, cat_stacks, None, telem)
    assert losses.shape == (K,)
    lb = tel.load_balance(telem)
    assert lb["steps"] == K
    np.testing.assert_allclose(sum(lb["per_rank_ids"]), K * 8 * batch)


# ------------------------------------------------- resilient-driver flush


def test_resilient_flushes_telemetry_alongside_checkpoints(mesh, tmp_path):
    import json

    configs = [{"input_dim": 100, "output_dim": 8} for _ in range(8)]
    de, state, step = _setup(mesh, configs)
    telem = tel.init_telemetry(de, CFG, mesh=mesh)
    rng = np.random.default_rng(0)

    def data(start):
        for _ in range(start, 4):
            yield ([jnp.asarray(rng.integers(0, 100, 32), jnp.int32)
                    for _ in range(8)], None)

    ckpt = os.path.join(str(tmp_path), "ckpt")
    res = run_resilient(step, state, data, de=de, checkpoint_dir=ckpt,
                        resume=False, telemetry_state=telem)
    assert res.steps_run == 4
    tpath = ckpt + ".telemetry.json"
    assert os.path.isfile(tpath)
    with open(tpath, encoding="utf-8") as f:
        summary = json.load(f)
    assert summary["steps"] == 4
    assert len(summary["per_rank_ids"]) == WORLD
    lb = tel.load_balance(res.telemetry)
    assert lb["steps"] == 4


def test_resilient_resume_continues_telemetry(mesh, tmp_path):
    # the documented durability contract: an interrupted+resumed run's
    # telemetry CONTINUES the accumulation (state restored from the
    # .state.npz sidecar), it does not restart from zero
    import json

    configs = [{"input_dim": 100, "output_dim": 8} for _ in range(8)]
    de, state0, step = _setup(mesh, configs)
    rng = np.random.default_rng(0)

    def data(start):
        for _ in range(start, 6):
            yield ([jnp.asarray(rng.integers(0, 100, 32), jnp.int32)
                    for _ in range(8)], None)

    ckpt = os.path.join(str(tmp_path), "ckpt")
    emb_opt, tx = SparseSGD(), optax.sgd(0.01)
    first = run_resilient(
        step, state0, data, de=de, checkpoint_dir=ckpt,
        emb_optimizer=emb_opt, dense_tx=tx, mesh=mesh, until_step=3,
        telemetry_state=tel.init_telemetry(de, CFG, mesh=mesh))
    assert tel.load_balance(first.telemetry)["steps"] == 3
    # second invocation: fresh telemetry template, resume restores both
    # the train state AND the telemetry accumulation
    second = run_resilient(
        step, state0, data, de=de, checkpoint_dir=ckpt,
        emb_optimizer=emb_opt, dense_tx=tx, mesh=mesh,
        telemetry_state=tel.init_telemetry(de, CFG, mesh=mesh))
    assert int(second.step) == 6
    assert tel.load_balance(second.telemetry)["steps"] == 6
    with open(ckpt + ".telemetry.json", encoding="utf-8") as f:
        assert json.load(f)["steps"] == 6


def test_restore_telemetry_state_rejects_drift(tmp_path):
    configs = [{"input_dim": 64, "output_dim": 8} for _ in range(8)]
    de = DistributedEmbedding(configs, world_size=WORLD)
    state = tel.init_telemetry(de, CFG)
    path = str(tmp_path / "t.npz")
    tel.save_telemetry_state(path, state)
    other = tel.init_telemetry(
        de, tel.TelemetryConfig(depth=2, buckets=64, topk=4, candidates=8))
    got = tel.restore_telemetry_state(path, other)
    # mismatched geometry: the fresh template comes back unchanged
    assert got is other
    same = tel.restore_telemetry_state(path, tel.init_telemetry(de, CFG))
    for a, b in zip(jax.tree_util.tree_leaves(same),
                    jax.tree_util.tree_leaves(state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# -------------------------------------------------- memory accounting


def _memory_case(name):
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from tools.audit_step import build_case

    return build_case(name, WORLD, 16)


@pytest.mark.parametrize("config", ["dense", "ragged", "row_sliced"])
def test_memory_report_shapes(config, mesh):
    de, cats, batch_tree, dense_params, loss_fn = _memory_case(config)
    rep = dmem.step_memory_report(
        de, loss_fn, optax.sgd(0.5), SparseAdagrad(), cats, batch_tree,
        mesh=mesh, dense_params=dense_params)
    layout = rep["layout"]
    assert len(layout["tables"]) == len(de.strategy.global_configs)
    for t in layout["tables"]:
        assert t["param_bytes"] == t["rows"] * t["width"] * 4
        assert t["slices"] >= 1 and t["ranks"]
    assert set(layout["slabs"]) == {f"w{w}" for w in de.widths}
    for slab in layout["slabs"].values():
        assert slab["param_bytes"] >= slab["live_bytes"] > 0
        # SparseAdagrad: one accumulator slab per param slab
        assert slab["opt_state_bytes"] == slab["param_bytes"]
    tot = layout["totals"]
    assert tot["param_bytes_allocated"] >= tot["param_bytes_live"]
    assert 0.0 <= tot["padding_frac"] < 1.0
    assert len(layout["per_rank"]) == WORLD
    assert sum(r["live_param_bytes"] for r in layout["per_rank"]) \
        == tot["param_bytes_live"]
    comp = rep["compiled"]
    assert comp["error"] is None, comp
    assert comp["argument_bytes"] > 0
    assert comp["peak_bytes_est"] > 0
    assert comp["flops"] and comp["flops"] > 0
    traffic = rep["per_table_traffic"]
    assert {t["table_id"] for t in traffic} \
        == set(range(len(de.strategy.global_configs)))
    for t in traffic:
        assert t["est_hbm_bytes_per_step"] > 0
        assert t["est_flops_per_step"] > 0


def test_table_memory_report_row_sliced_accounting():
    # a row-sliced table's slices must sum to the full table bytes
    de, *_ = _memory_case("row_sliced")
    rep = dmem.table_memory_report(de, SparseSGD())
    sliced = [t for t in rep["tables"] if t["row_sliced"]]
    assert sliced, "row_sliced case must row-slice something"
    for t in sliced:
        assert t["slices"] > 1
    # SparseSGD carries no slab state: zero bytes, not None
    assert rep["totals"]["opt_state_bytes"] == 0
    assert rep["totals"]["opt_state_error"] is None


def test_compiled_step_report_requires_jit_wrapper():
    rep = dmem.compiled_step_report(lambda x: x, (jnp.zeros((2,)),))
    assert "lower" in rep["error"]


def test_resolve_config_contract():
    assert tel.resolve_config(False) is None
    assert tel.resolve_config(CFG) is CFG
    got = tel.resolve_config(True)
    assert isinstance(got, tel.TelemetryConfig)
    with pytest.raises(TypeError):
        tel.resolve_config(3)
    # explicit opt-in: None is OFF even with the env var set (an env
    # default would change the call arity under 3-arg call sites)
    os.environ["DETPU_TELEMETRY"] = "1"
    try:
        assert tel.resolve_config(None) is None
    finally:
        os.environ.pop("DETPU_TELEMETRY", None)

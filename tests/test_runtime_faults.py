"""Fault-tolerance layer (utils/runtime.py) under injected faults — all
CPU-only, subprocess-based where the failure mode is a hang or a death.

Scenarios (ISSUE r6): probe timeout on a hung backend; require_devices
falling back to the forced-CPU mesh; dryrun_multichip completing via the
CPU child while the backend hangs; bootstrap retry-then-succeed,
retry-then-raise (cluster expected) and silent single-host degradation;
crash-surviving JSONL section records; bench killed mid-run keeping every
completed section.
"""

import json
import os
import subprocess
import sys
import time

import pytest

from distributed_embeddings_tpu.parallel import bootstrap
from distributed_embeddings_tpu.utils import runtime

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv(runtime.FAULT_ENV, raising=False)
    runtime.reset_fault_counts()
    yield
    runtime.reset_fault_counts()


# ------------------------------------------------------- fault_point/retry


def test_fault_point_modes(monkeypatch):
    runtime.fault_point("nothing_set")  # no env: no-op

    monkeypatch.setenv(runtime.FAULT_ENV, "raise:ckpt")
    with pytest.raises(runtime.FaultInjected):
        runtime.fault_point("ckpt")
    runtime.fault_point("other_point")  # non-matching point passes

    # budgeted raise: first 2 calls fail, third passes
    runtime.reset_fault_counts()
    monkeypatch.setenv(runtime.FAULT_ENV, "raise:join:2")
    for _ in range(2):
        with pytest.raises(runtime.FaultInjected):
            runtime.fault_point("join")
    runtime.fault_point("join")

    monkeypatch.setenv(runtime.FAULT_ENV, "slow:io:0.05")
    t0 = time.monotonic()
    runtime.fault_point("io")
    assert time.monotonic() - t0 >= 0.05


def test_die_and_hang_at_parsing(monkeypatch):
    """die@/hang@ positional drills parse like the other @-style specs,
    combine with them, and never leak into the mode:point spec list (a
    die@5 must not warn as a malformed die:<point> entry)."""
    assert runtime.die_steps() == ()
    assert runtime.hang_steps() == ()
    monkeypatch.setenv(runtime.FAULT_ENV, "die@5")
    assert runtime.die_steps() == (5,)
    monkeypatch.setenv(runtime.FAULT_ENV, "hang@3,hang@9")
    assert runtime.hang_steps() == (3, 9)
    monkeypatch.setenv(runtime.FAULT_ENV,
                       "oovflood@2,die@4,burst@1,hang@7,corrupt@ckpt")
    assert runtime.die_steps() == (4,)
    assert runtime.hang_steps() == (7,)
    assert runtime.oovflood_steps() == (2,)
    assert runtime.burst_steps() == (1,)
    assert runtime._fault_specs() == []  # all skipped, none malformed
    # malformed positions warn and drop (like nan@/burst@)
    monkeypatch.setenv(runtime.FAULT_ENV, "die@notanint,die@2")
    assert runtime.die_steps() == (2,)
    # the mode:point grammar is untouched: hang:point still parses as a
    # fault_point spec, not a positional drill
    monkeypatch.setenv(runtime.FAULT_ENV, "hang:backend:60,hang@4")
    assert runtime._fault_specs() == [("hang", "backend", "60")]
    assert runtime.hang_steps() == (4,)


def test_retry_succeeds_after_transient_failures():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise RuntimeError("transient")
        return "ok"

    assert runtime.retry(flaky, max_attempts=5, base_delay_s=0.01) == "ok"
    assert calls["n"] == 3


def test_retry_attempt_budget_reraises():
    def always():
        raise ValueError("permanent")

    with pytest.raises(ValueError):
        runtime.retry(always, max_attempts=2, base_delay_s=0.01)


def test_retry_deadline_raises_deadline_exceeded():
    def always():
        raise ValueError("permanent")

    with pytest.raises(runtime.DeadlineExceeded):
        runtime.retry(always, deadline_s=0.05, base_delay_s=0.05)


def test_deadline_interrupts_sleep():
    t0 = time.monotonic()
    with pytest.raises(runtime.DeadlineExceeded):
        with runtime.deadline(0.2, label="nap"):
            time.sleep(30)
    assert time.monotonic() - t0 < 5


# ------------------------------------------------------------------- probe


def test_probe_backend_cpu_reports_devices():
    probe = runtime.probe_backend(timeout_s=120, platform="cpu")
    assert probe.ok, probe
    assert probe.platform == "cpu"
    assert probe.device_count >= 1


def test_probe_backend_hang_times_out(monkeypatch):
    monkeypatch.setenv(runtime.FAULT_ENV, "hang:backend:60")
    probe = runtime.probe_backend(timeout_s=2)
    assert not probe.ok
    assert "timed out" in probe.error
    assert probe.elapsed_s < 30


def test_require_devices_falls_back_to_forced_cpu_mesh(monkeypatch):
    monkeypatch.setenv(runtime.FAULT_ENV, "hang:backend:60")
    spec = runtime.require_devices(4, timeout_s=2)
    assert spec.forced_cpu and spec.device_count == 4
    env = spec.child_env()
    assert env["JAX_PLATFORMS"] == "cpu"
    assert "--xla_force_host_platform_device_count=4" in env["XLA_FLAGS"]


def test_dryrun_multichip_completes_via_cpu_child_with_hung_backend(
        monkeypatch):
    """Acceptance: with DETPU_FAULT=hang:backend the dryrun still completes
    inside its deadline — the parent probes (times out fast), never touches
    its own backend, and spawns the forced-CPU child."""
    monkeypatch.setenv(runtime.FAULT_ENV, "hang:backend:120")
    monkeypatch.syspath_prepend(_REPO)
    import __graft_entry__ as g

    t0 = time.monotonic()
    g.dryrun_multichip(2, probe_timeout_s=3, child_timeout_s=420)
    assert time.monotonic() - t0 < 425


# --------------------------------------------------------------- bootstrap


def test_bootstrap_single_host_degrades_silently(monkeypatch):
    for var in ("SLURM_NTASKS", "OMPI_COMM_WORLD_SIZE", "PMI_SIZE",
                "JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS",
                "MEGASCALE_COORDINATOR_ADDRESS", "TPU_WORKER_HOSTNAMES"):
        monkeypatch.delenv(var, raising=False)

    def broken(*a):
        raise RuntimeError("no cluster here")

    monkeypatch.setattr(bootstrap, "_join_runtime", broken)
    assert bootstrap.initialize() is False


def test_bootstrap_retries_then_succeeds(monkeypatch):
    monkeypatch.setenv("SLURM_NTASKS", "2")  # cluster expected
    calls = {"n": 0}

    def flaky(*a):
        calls["n"] += 1
        if calls["n"] < 3:
            raise RuntimeError("coordinator warming up")

    monkeypatch.setattr(bootstrap, "_join_runtime", flaky)
    assert bootstrap.initialize(retries=3) is True
    assert calls["n"] == 3


def test_bootstrap_cluster_expected_raises_after_retries(monkeypatch):
    monkeypatch.setenv("SLURM_NTASKS", "2")
    calls = {"n": 0}

    def dead(*a):
        calls["n"] += 1
        raise RuntimeError("connection refused")

    monkeypatch.setattr(bootstrap, "_join_runtime", dead)
    with pytest.raises(runtime.CoordinatorUnreachable):
        bootstrap.initialize(retries=1)
    assert calls["n"] == 2


def test_bootstrap_slow_coordinator_hits_deadline(monkeypatch):
    """DETPU_FAULT=slow:coordinator + a short per-attempt timeout_s: every
    attempt times out inside fault_point (before any real jax.distributed
    call) and a cluster-expected job raises CoordinatorUnreachable."""
    monkeypatch.setenv("SLURM_NTASKS", "2")
    monkeypatch.setenv(runtime.FAULT_ENV, "slow:coordinator:30")
    t0 = time.monotonic()
    with pytest.raises(runtime.CoordinatorUnreachable):
        bootstrap.initialize(timeout_s=0.3, retries=1)
    assert time.monotonic() - t0 < 20


# ------------------------------------------- crash-surviving section records


def test_section_recorder_survives_process_death(tmp_path):
    side = str(tmp_path / "sections.jsonl")
    code = (
        f"import os, sys; sys.path.insert(0, {_REPO!r})\n"
        "from distributed_embeddings_tpu.utils import runtime\n"
        f"rec = runtime.SectionRecorder({side!r})\n"
        "runtime.run_section(rec, 'alpha', lambda: 1.5)\n"
        "os.environ[runtime.FAULT_ENV] = 'die:beta'\n"
        "runtime.run_section(rec, 'beta', lambda: 2.5)\n"
        "rec.record('never_reached', ok=True)\n")
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 17, proc.stderr[-2000:]  # die:* exit code
    recs = runtime.SectionRecorder.load(side)
    assert [r["section"] for r in recs] == ["alpha"]
    assert recs[0]["ok"] and recs[0]["value"] == 1.5
    # a torn trailing line (killed mid-write) must not break parsing
    with open(side, "a", encoding="utf-8") as f:
        f.write('{"section": "torn", "ok"')
    recs = runtime.SectionRecorder.load(side)
    assert [r["section"] for r in recs] == ["alpha"]


def test_run_section_records_failure_and_returns_default(tmp_path):
    rec = runtime.SectionRecorder(str(tmp_path / "s.jsonl"))

    def boom():
        raise RuntimeError("nope")

    out = runtime.run_section(rec, "bad", boom, default="dflt", retries=1)
    assert out == "dflt"
    recs = runtime.SectionRecorder.load(rec.path)
    assert recs[0]["section"] == "bad" and recs[0]["ok"] is False
    assert recs[0]["attempts"] == 2


def test_bench_killed_mid_run_leaves_parseable_sidecar(tmp_path):
    """Acceptance: bench.py killed mid-run (die:bench.bf16, the second
    section) leaves a parseable JSONL sidecar containing the probe and
    every completed section (fp32)."""
    side = str(tmp_path / "bench.partial.jsonl")
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "DETPU_BENCH_SMOKE": "1",
        "DETPU_BENCH_SIDECAR": side,
        "DETPU_FAULT": "die:bench.bf16",
        "PYTHONPATH": _REPO,
    })
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "bench.py")],
        env=env, cwd=_REPO, capture_output=True, text=True, timeout=420)
    assert proc.returncode == 17, (proc.stdout, proc.stderr[-2000:])
    recs = runtime.SectionRecorder.load(side)
    by_name = {r["section"]: r for r in recs}
    assert by_name["probe"]["ok"] is True
    assert by_name["bench.fp32"]["ok"] is True
    assert by_name["bench.fp32"]["value"] > 0
    assert "final" not in by_name  # killed before completion


def test_bench_backend_unavailable_emits_parseable_error_record(tmp_path,
                                                                monkeypatch):
    """A stalled tunnel must yield one parseable JSON line (error field),
    not an rc=124 hang with an empty tail."""
    side = str(tmp_path / "bench.partial.jsonl")
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "DETPU_BENCH_SMOKE": "1",
        "DETPU_BENCH_SIDECAR": side,
        "DETPU_FAULT": "hang:backend:120",
        "DETPU_PROBE_TIMEOUT_S": "3",
        "PYTHONPATH": _REPO,
    })
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "bench.py")],
        env=env, cwd=_REPO, capture_output=True, text=True, timeout=180)
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert "backend unavailable" in out["error"]
    assert out["value"] == 0.0
    recs = runtime.SectionRecorder.load(side)
    assert recs and recs[0]["section"] == "probe"
    assert recs[0]["ok"] is False

"""Sparse embedding update path vs dense-autodiff + optax oracle.

The manual backward (reverse all-to-all + per-row scatter updates) must
produce exactly the training trajectory that full autodiff through the tables
with a dense optax optimizer would — the reference asserts the same by
comparing post-SGD weights of its distributed and single-process models
(``dist_model_parallel_test.py:162-171``)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from distributed_embeddings_tpu.ops import embedding_lookup
from distributed_embeddings_tpu.parallel import (
    DistributedEmbedding,
    HybridTrainState,
    SparseAdagrad,
    SparseAdam,
    SparseMomentum,
    SparseSGD,
    init_hybrid_state,
    make_hybrid_train_step,
)

WORLD = 8


def setup_model(rng, num_tables=10, world=WORLD, column_slice_threshold=None,
                dp_input=True, vocab_max=60):
    configs = []
    for _ in range(num_tables):
        configs.append({
            "input_dim": int(rng.integers(8, vocab_max)),
            "output_dim": int(rng.integers(2, 7)),
            "combiner": rng.choice([None, "sum", "mean"]),
        })
    de = DistributedEmbedding(configs, world_size=world,
                              strategy="memory_balanced",
                              column_slice_threshold=column_slice_threshold,
                              dp_input=dp_input)
    tables = [rng.normal(size=(c["input_dim"], c["output_dim"])
                         ).astype(np.float32) for c in configs]
    return configs, de, tables


def make_batch(rng, configs, batch):
    cats, total_w = [], 0
    for c in configs:
        hot = int(rng.integers(1, 4)) if c["combiner"] else 1
        cats.append(jnp.asarray(
            rng.integers(0, c["input_dim"], size=(batch, hot)), jnp.int32))
        total_w += c["output_dim"] * (1 if c["combiner"] else hot)
    labels = jnp.asarray(rng.normal(size=(batch, 1)), jnp.float32)
    return cats, labels, total_w


def dense_loss(dense_params, emb_outs, batch):
    labels = batch
    h = jnp.concatenate([o.reshape(o.shape[0], -1) for o in emb_outs], axis=1)
    pred = h @ dense_params["w"]
    return jnp.mean((pred - labels) ** 2)


def oracle_trajectory(configs, tables0, dense0, cats, labels, emb_tx, steps,
                      lr):
    """Single-device full-autodiff trajectory with optax on the tables."""
    params = {"tables": [jnp.asarray(t) for t in tables0],
              "dense": dict(dense0)}
    tx = optax.multi_transform(
        {"emb": emb_tx, "dense": optax.sgd(0.1)},
        {"tables": "emb", "dense": "dense"})
    state = tx.init(params)

    def loss_fn(p):
        outs = []
        for inp, cfg, t in zip(cats, configs, p["tables"]):
            o = embedding_lookup(t, inp, combiner=cfg["combiner"])
            outs.append(o.reshape(o.shape[0], -1))
        h = jnp.concatenate(outs, axis=1)
        pred = h @ p["dense"]["w"]
        return jnp.mean((pred - labels) ** 2)

    for _ in range(steps):
        grads = jax.grad(loss_fn)(params)
        updates, state = tx.update(grads, state, params)
        params = optax.apply_updates(params, updates)
    return params


@pytest.mark.parametrize("opt_name", ["sgd", "adagrad"])
@pytest.mark.parametrize(
    "world", [1, pytest.param(WORLD, marks=pytest.mark.slow)])
def test_sparse_trainer_matches_dense_optax(opt_name, world):
    rng = np.random.default_rng(42)
    cst = 300 if world > 1 else None
    configs, de, tables0 = setup_model(rng, world=world,
                                       column_slice_threshold=cst)
    mesh = (Mesh(np.array(jax.devices()[:world]), ("data",))
            if world > 1 else None)
    lr = 0.3
    if opt_name == "sgd":
        emb_opt, emb_tx = SparseSGD(), optax.sgd(lr)
    else:
        emb_opt, emb_tx = SparseAdagrad(), optax.adagrad(lr)

    B = 16 * world
    cats, labels, total_w = make_batch(rng, configs, B)
    dense0_np = rng.normal(size=(total_w, 1)).astype(np.float32) * 0.3
    # the train step donates its state buffers; give each consumer a fresh copy
    dense0 = {"w": jnp.asarray(dense0_np)}

    flat = de.set_weights(tables0, mesh=mesh)
    state = HybridTrainState(
        emb_params=flat,
        emb_opt_state=emb_opt.init(flat),
        dense_params=dense0,
        dense_opt_state=optax.sgd(0.1).init(dense0),
        step=jnp.zeros((), jnp.int32))

    step_fn = make_hybrid_train_step(
        de, dense_loss, optax.sgd(0.1), emb_opt, mesh=mesh, lr_schedule=lr)

    losses = []
    for _ in range(3):
        loss, state = step_fn(state, cats, labels)
        losses.append(float(loss))

    oracle = oracle_trajectory(configs, tables0, {"w": jnp.asarray(dense0_np)},
                               cats, labels, emb_tx, steps=3, lr=lr)
    got_tables = de.get_weights(state.emb_params)
    for got, want in zip(got_tables, oracle["tables"]):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(state.dense_params["w"]),
                               np.asarray(oracle["dense"]["w"]),
                               rtol=2e-4, atol=1e-5)
    assert losses[-1] < losses[0]


def make_covering_batch(rng, configs, batch):
    """Like make_batch, but every table row is touched every step (first hot
    column cycles through the whole vocab) — the regime where lazy
    momentum/Adam trajectories equal dense optax exactly (see
    parallel/optimizers.py module docstring)."""
    cats, total_w = [], 0
    for c in configs:
        v = c["input_dim"]
        assert v <= batch, "covering batch needs vocab <= batch"
        hot = int(rng.integers(1, 4)) if c["combiner"] else 1
        ids = rng.integers(0, v, size=(batch, hot))
        ids[:, 0] = np.arange(batch) % v
        cats.append(jnp.asarray(ids, jnp.int32))
        total_w += c["output_dim"] * (1 if c["combiner"] else hot)
    labels = jnp.asarray(rng.normal(size=(batch, 1)), jnp.float32)
    return cats, labels, total_w


@pytest.mark.parametrize("opt_name", ["momentum", "nesterov", "adam"])
@pytest.mark.parametrize(
    "world", [1, pytest.param(WORLD, marks=pytest.mark.slow)])
def test_sparse_momentum_adam_match_dense_optax(opt_name, world):
    """Stateful-moment optimizers (VERDICT r2 missing #2): trajectory equality
    vs dense optax when every row is touched every step."""
    rng = np.random.default_rng(44)
    B = 16 * world if world > 1 else 64
    # covering batches need vocab <= batch (vocab_max < min B)
    configs, de, tables0 = setup_model(rng, world=world, vocab_max=48)
    mesh = (Mesh(np.array(jax.devices()[:world]), ("data",))
            if world > 1 else None)
    lr = 0.1
    if opt_name == "momentum":
        emb_opt, emb_tx = SparseMomentum(0.9), optax.sgd(lr, momentum=0.9)
    elif opt_name == "nesterov":
        emb_opt = SparseMomentum(0.9, nesterov=True)
        emb_tx = optax.sgd(lr, momentum=0.9, nesterov=True)
    else:
        emb_opt, emb_tx = SparseAdam(), optax.adam(lr)

    cats, labels, total_w = make_covering_batch(rng, configs, B)
    dense0_np = rng.normal(size=(total_w, 1)).astype(np.float32) * 0.3
    dense0 = {"w": jnp.asarray(dense0_np)}

    flat = de.set_weights(tables0, mesh=mesh)
    state = HybridTrainState(
        emb_params=flat,
        emb_opt_state=emb_opt.init(flat),
        dense_params=dense0,
        dense_opt_state=optax.sgd(0.1).init(dense0),
        step=jnp.zeros((), jnp.int32))
    step_fn = make_hybrid_train_step(
        de, dense_loss, optax.sgd(0.1), emb_opt, mesh=mesh, lr_schedule=lr)

    for _ in range(3):
        _, state = step_fn(state, cats, labels)

    oracle = oracle_trajectory(configs, tables0, {"w": jnp.asarray(dense0_np)},
                               cats, labels, emb_tx, steps=3, lr=lr)
    for got, want in zip(de.get_weights(state.emb_params), oracle["tables"]):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=1e-5)


@pytest.mark.parametrize("opt_name", ["momentum", "adam"])
def test_lazy_moments_skip_untouched_rows(opt_name):
    """Lazy semantics: a step that touches only row 0 must leave every other
    row's params AND state untouched (dense optax would decay-and-apply
    momentum to all rows)."""
    configs = [{"input_dim": 8, "output_dim": 4, "combiner": "sum"}]
    de = DistributedEmbedding(configs, world_size=1)
    emb_opt = (SparseMomentum(0.9) if opt_name == "momentum" else SparseAdam())
    rng = np.random.default_rng(7)
    t0 = rng.normal(size=(8, 4)).astype(np.float32)
    flat = de.set_weights([t0])
    opt_state = emb_opt.init(flat)

    # step 1: touch every row (builds nonzero momentum everywhere)
    all_rows = jnp.arange(8, dtype=jnp.int32)[:, None]
    outs, res = de.forward_with_residuals(de.local_view(flat), [all_rows])
    flat, opt_state = de.sparse_apply_gradients(
        de.local_view(flat), de.local_view(opt_state), res,
        [jnp.ones_like(outs[0])], emb_opt, 0.1, scale=1.0)
    after1 = de.get_weights(de.stacked_view(flat))[0]

    # step 2: touch only row 0
    one_row = jnp.zeros((8, 1), jnp.int32)
    outs, res = de.forward_with_residuals(flat, [one_row])
    flat, opt_state = de.sparse_apply_gradients(
        flat, opt_state, res, [jnp.ones_like(outs[0])], emb_opt, 0.1,
        scale=1.0)
    after2 = de.get_weights(de.stacked_view(flat))[0]

    assert not np.allclose(after2[0], after1[0])  # row 0 moved
    np.testing.assert_array_equal(after2[1:], after1[1:])  # rest frozen


@pytest.mark.slow
def test_sparse_trainer_mp_input_matches_dense_optax():
    """The manual sparse backward under model-parallel input (dp_input=False):
    the reverse output all-to-all + scatter updates must still reproduce the
    dense-autodiff optax trajectory when the id exchange never ran."""
    rng = np.random.default_rng(43)
    configs, de, tables0 = setup_model(rng, world=WORLD,
                                       column_slice_threshold=300,
                                       dp_input=False)
    mesh = Mesh(np.array(jax.devices()[:WORLD]), ("data",))
    lr = 0.3
    emb_opt, emb_tx = SparseAdagrad(), optax.adagrad(lr)

    B = 16 * WORLD
    cats, labels, total_w = make_batch(rng, configs, B)
    mp_in = de.pack_mp_inputs(cats, mesh=mesh)
    dense0_np = rng.normal(size=(total_w, 1)).astype(np.float32) * 0.3
    dense0 = {"w": jnp.asarray(dense0_np)}

    flat = de.set_weights(tables0, mesh=mesh)
    state = HybridTrainState(
        emb_params=flat,
        emb_opt_state=emb_opt.init(flat),
        dense_params=dense0,
        dense_opt_state=optax.sgd(0.1).init(dense0),
        step=jnp.zeros((), jnp.int32))
    step_fn = make_hybrid_train_step(
        de, dense_loss, optax.sgd(0.1), emb_opt, mesh=mesh, lr_schedule=lr)

    losses = []
    for _ in range(3):
        loss, state = step_fn(state, mp_in, labels)
        losses.append(float(loss))

    oracle = oracle_trajectory(configs, tables0, {"w": jnp.asarray(dense0_np)},
                               cats, labels, emb_tx, steps=3, lr=lr)
    for got, want in zip(de.get_weights(state.emb_params), oracle["tables"]):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=1e-5)
    assert losses[-1] < losses[0]

"""Native data-runtime tests: C library vs numpy fallbacks."""

import os
import tempfile

import numpy as np
import pytest

from distributed_embeddings_tpu.utils import native
from distributed_embeddings_tpu.utils.data import (
    RawBinaryDataset, get_categorical_feature_type)

needs_native = pytest.mark.skipif(not native.have_native(),
                                  reason="cc/libdetpu_dataio.so not built")


@needs_native
def test_power_law_ids_distribution():
    ids = native.native_power_law_ids(seed=1, alpha=1.05, vocab=100000,
                                      shape=(50000,))
    assert ids.min() >= 0 and ids.max() < 100000
    # power law: low ids dominate
    assert (ids < 100).mean() > 0.3
    # deterministic per seed
    ids2 = native.native_power_law_ids(seed=1, alpha=1.05, vocab=100000,
                                       shape=(50000,))
    np.testing.assert_array_equal(ids, ids2)


@needs_native
def test_row_to_split_matches_numpy():
    rng = np.random.default_rng(0)
    rows = np.sort(rng.integers(0, 10, size=40))
    got = native.native_row_to_split(rows, 10)
    want = np.searchsorted(rows, np.arange(11), side="left")
    np.testing.assert_array_equal(got, want)


def make_criteo_dir(tmp, n, sizes, num_numerical, split="train"):
    d = os.path.join(tmp, split)
    os.makedirs(d, exist_ok=True)
    rng = np.random.default_rng(0)
    labels = rng.integers(0, 2, size=n).astype(np.bool_)
    labels.tofile(os.path.join(d, "label.bin"))
    numerical = rng.normal(size=(n, num_numerical)).astype(np.float16)
    numerical.tofile(os.path.join(d, "numerical.bin"))
    cats = []
    for i, s in enumerate(sizes):
        dt = get_categorical_feature_type(s)
        c = rng.integers(0, s, size=n).astype(dt)
        c.tofile(os.path.join(d, f"cat_{i}.bin"))
        cats.append(c)
    return d, labels, numerical, cats


@needs_native
def test_native_criteo_reader_matches_memmap():
    sizes = [100, 40000, 3]
    with tempfile.TemporaryDirectory() as tmp:
        d, labels, numerical, cats = make_criteo_dir(tmp, 64, sizes, 5)
        reader = native.NativeCriteoReader(d, [0, 1, 2], sizes, 5)
        assert reader.num_samples == 64
        num, cs, lab = reader.read(16, 16)
        np.testing.assert_allclose(num, numerical[16:32].astype(np.float32))
        np.testing.assert_array_equal(lab[:, 0], labels[16:32].astype(np.float32))
        for got, want in zip(cs, cats):
            np.testing.assert_array_equal(got, want[16:32].astype(np.int32))
        reader.close()

        # python reader agrees
        ds = RawBinaryDataset(tmp, batch_size=16, numerical_features=5,
                              categorical_features=[0, 1, 2],
                              categorical_feature_sizes=sizes)
        n2, c2, l2 = ds[1]
        np.testing.assert_allclose(n2, num)
        np.testing.assert_array_equal(l2, lab)
        for a, b in zip(c2, cs):
            np.testing.assert_array_equal(a, b)

"""Observability layer (ISSUE 2): on-device step metrics, ragged
capacity-overflow counters, process counters/recompile listener, the
metrics sidecar, and `utils.metrics.binary_auc` edge cases.

The overflow tests are the acceptance teeth: a ragged batch engineered to
claim more ids than its static capacity must report a NONZERO truncation
count instead of passing silently (the failure mode the ISSUE motivation
names)."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_embeddings_tpu.ops.embedding_lookup import Ragged
from distributed_embeddings_tpu.parallel import (
    DistributedEmbedding, SparseRows, SparseSGD, init_hybrid_state,
    make_hybrid_train_loop, make_hybrid_train_step, sparse_grad_metrics)
from distributed_embeddings_tpu.utils import metrics as umetrics
from distributed_embeddings_tpu.utils import obs, runtime

WORLD = 8


# ------------------------------------------------------ binary_auc edges


def _auc_pairwise(labels, preds):
    """O(P*N) literal definition: P(score_pos > score_neg) + 0.5 ties."""
    labels = np.asarray(labels).reshape(-1)
    preds = np.asarray(preds).reshape(-1)
    pos = preds[labels > 0.5]
    neg = preds[labels <= 0.5]
    if len(pos) == 0 or len(neg) == 0:
        return float("nan")
    wins = (pos[:, None] > neg[None, :]).sum()
    ties = (pos[:, None] == neg[None, :]).sum()
    return float((wins + 0.5 * ties) / (len(pos) * len(neg)))


def test_binary_auc_matches_pairwise_reference():
    rng = np.random.default_rng(0)
    labels = (rng.random(500) < 0.3).astype(np.float32)
    preds = rng.normal(size=500)
    np.testing.assert_allclose(umetrics.binary_auc(labels, preds),
                               _auc_pairwise(labels, preds), atol=1e-12)


def test_binary_auc_tied_scores():
    # heavy ties (quantized scores): the rank statistic must average tied
    # ranks, matching the 0.5-credit pairwise definition
    rng = np.random.default_rng(1)
    labels = (rng.random(400) < 0.5).astype(np.float32)
    preds = rng.integers(0, 4, size=400).astype(np.float64)  # 4 levels
    np.testing.assert_allclose(umetrics.binary_auc(labels, preds),
                               _auc_pairwise(labels, preds), atol=1e-12)


def test_binary_auc_all_tied_is_half():
    labels = np.array([0, 1, 0, 1, 1], np.float32)
    preds = np.full(5, 0.7)
    np.testing.assert_allclose(umetrics.binary_auc(labels, preds), 0.5,
                               atol=1e-12)


def test_binary_auc_single_class_is_nan():
    preds = np.array([0.1, 0.9, 0.5])
    assert np.isnan(umetrics.binary_auc(np.ones(3), preds))
    assert np.isnan(umetrics.binary_auc(np.zeros(3), preds))


def test_binary_auc_empty_batch_is_nan():
    assert np.isnan(umetrics.binary_auc(np.zeros(0), np.zeros(0)))


def test_binary_auc_perfect_and_inverted():
    labels = np.array([0, 0, 1, 1], np.float32)
    np.testing.assert_allclose(
        umetrics.binary_auc(labels, np.array([0.1, 0.2, 0.8, 0.9])), 1.0)
    np.testing.assert_allclose(
        umetrics.binary_auc(labels, np.array([0.9, 0.8, 0.2, 0.1])), 0.0)


# ----------------------------------------------- step metrics, world == 1


def _loss_fn_factory():
    def loss_fn(dp, outs, batch):
        del batch
        return sum(jnp.mean(o.astype(jnp.float32) ** 2) for o in outs) \
            * dp["w"]
    return loss_fn


def _single_worker_setup(combiner="sum"):
    configs = [{"input_dim": 50, "output_dim": 8, "combiner": combiner},
               {"input_dim": 40, "output_dim": 8}]
    de = DistributedEmbedding(configs, world_size=1)
    tx = optax.sgd(0.01)
    emb_opt = SparseSGD()
    state = init_hybrid_state(de, emb_opt, {"w": jnp.float32(0.5)}, tx,
                              jax.random.key(0))
    step = make_hybrid_train_step(de, _loss_fn_factory(), tx, emb_opt,
                                  with_metrics=True)
    return de, state, step


def test_single_worker_metrics_schema_and_counts():
    de, state, step = _single_worker_setup()
    rng = np.random.default_rng(0)
    rag = Ragged(values=jnp.asarray(rng.integers(0, 50, 12), jnp.int32),
                 row_splits=jnp.asarray([0, 3, 6, 9, 12], jnp.int32))
    dense_ids = jnp.asarray(rng.integers(0, 40, 4), jnp.int32)
    loss, state, m = step(state, [rag, dense_ids], None)
    assert set(m) == set(obs.STEP_METRIC_KEYS)
    assert int(m["ids_routed"][0]) == 12 + 4
    assert int(m["id_overflow"][0]) == 0
    # single worker: nothing leaves the chip
    assert float(m["id_a2a_bytes"][0]) == 0.0
    assert float(m["out_a2a_bytes"][0]) == 0.0
    assert float(m["loss"][0]) == pytest.approx(float(loss))
    assert int(m["step"][0]) == 0
    assert float(m["emb_grad_norm"][0]) > 0
    # every value JSON-serializes (the sidecar contract)
    assert obs._selftest_json_roundtrip(m)


def test_single_worker_overflow_counter_nonzero():
    """A ragged batch whose row lengths claim more ids than the static
    capacity holds must report the truncated count, not pass silently."""
    de, state, step = _single_worker_setup()
    rng = np.random.default_rng(0)
    cap = 8
    # lengths claim 3 ids per row * 4 rows = 12 > cap = 8 -> 4 truncated
    rag = Ragged(values=jnp.asarray(rng.integers(0, 50, cap), jnp.int32),
                 row_splits=jnp.asarray([0, 3, 6, 9, 12], jnp.int32))
    dense_ids = jnp.asarray(rng.integers(0, 40, 4), jnp.int32)
    _, _, m = step(state, [rag, dense_ids], None)
    assert int(m["id_overflow"][0]) == 4
    # routed counts clamp at capacity: 8 ragged + 4 dense
    assert int(m["ids_routed"][0]) == cap + 4


def test_metrics_disabled_keeps_two_tuple_contract():
    configs = [{"input_dim": 40, "output_dim": 8}]
    de = DistributedEmbedding(configs, world_size=1)
    tx = optax.sgd(0.01)
    emb_opt = SparseSGD()
    state = init_hybrid_state(de, emb_opt, {"w": jnp.float32(0.5)}, tx,
                              jax.random.key(0))
    step = make_hybrid_train_step(de, _loss_fn_factory(), tx, emb_opt,
                                  with_metrics=False)
    out = step(state, [jnp.zeros((4,), jnp.int32)], None)
    assert len(out) == 2


def test_env_flag_enables_metrics(monkeypatch):
    monkeypatch.setenv(obs.OBS_ENV, "1")
    assert obs.metrics_enabled()
    de, state, _ = _single_worker_setup()
    # with_metrics=None follows the env
    step = make_hybrid_train_step(de, _loss_fn_factory(), optax.sgd(0.01),
                                  SparseSGD())
    rng = np.random.default_rng(0)
    rag = Ragged(values=jnp.asarray(rng.integers(0, 50, 12), jnp.int32),
                 row_splits=jnp.asarray([0, 3, 6, 9, 12], jnp.int32))
    out = step(state, [rag, jnp.zeros((4,), jnp.int32)], None)
    assert len(out) == 3
    monkeypatch.setenv(obs.OBS_ENV, "0")
    assert not obs.metrics_enabled()


def test_train_loop_stacks_metrics_over_steps():
    de, state, _ = _single_worker_setup(combiner=None)
    # dense-only inputs for an easy [K, ...] stack
    loop = make_hybrid_train_loop(de, _loss_fn_factory(), optax.sgd(0.01),
                                  SparseSGD(), with_metrics=True)
    K, b = 3, 4
    rng = np.random.default_rng(0)
    cats = [jnp.asarray(rng.integers(0, 50, (K, b)), jnp.int32),
            jnp.asarray(rng.integers(0, 40, (K, b)), jnp.int32)]
    losses, state, m = loop(state, cats, None)
    assert losses.shape == (K,)
    assert m["ids_routed"].shape == (K, 1)
    np.testing.assert_array_equal(np.asarray(m["step"]).reshape(-1),
                                  [0, 1, 2])


# ----------------------------------------------- step metrics, world == 8


def _dist_setup():
    mesh = jax.sharding.Mesh(np.array(jax.devices()), ("data",))
    configs = ([{"input_dim": 50, "output_dim": 16, "combiner": "sum"}]
               + [{"input_dim": 30 + i, "output_dim": 16}
                  for i in range(WORLD + 1)])
    de = DistributedEmbedding(configs, world_size=WORLD,
                              strategy="memory_balanced")
    tx = optax.sgd(0.01)
    emb_opt = SparseSGD()
    state = init_hybrid_state(de, emb_opt, {"w": jnp.float32(0.5)}, tx,
                              jax.random.key(0), mesh=mesh)
    step = make_hybrid_train_step(de, _loss_fn_factory(), tx, emb_opt,
                                  mesh=mesh, with_metrics=True)
    return de, state, step


def _stacked_ragged(rng, cap, b, lens_per_shard):
    """Per-shard CSR blocks stacked in the distributed Ragged convention:
    values [WORLD*cap], row_splits [WORLD*(b+1)]; ``lens_per_shard[s]`` is
    shard s's uniform per-row length."""
    vals, splits = [], []
    for s in range(WORLD):
        vals.append(rng.integers(0, 50, cap).astype(np.int32))
        ln = lens_per_shard[s]
        splits.append(np.arange(0, ln * (b + 1), ln, dtype=np.int32))
    return Ragged(values=jnp.asarray(np.concatenate(vals)),
                  row_splits=jnp.asarray(np.concatenate(splits)))


def test_distributed_overflow_is_per_rank():
    de, state, step = _dist_setup()
    rng = np.random.default_rng(0)
    b, cap = 4, 8
    # shard 0 claims 3*4=12 > cap=8 (4 truncated); others claim 2*4=8 (fit)
    rag = _stacked_ragged(rng, cap, b, [3] + [2] * (WORLD - 1))
    cats = [rag] + [jnp.asarray(rng.integers(0, 30, WORLD * b), jnp.int32)
                    for _ in range(WORLD + 1)]
    _, _, m = step(state, cats, None)
    overflow = np.asarray(m["id_overflow"])
    assert overflow.shape == (WORLD,)
    assert overflow.sum() == 4
    # the overflow lands on the rank OWNING the ragged table, localizing
    # the truncation to a placement, not just a boolean
    assert (overflow > 0).sum() == 1
    # exchange byte metrics are nonzero on a real mesh and identical
    # across ranks (uniform padded layout)
    ida2a = np.asarray(m["id_a2a_bytes"])
    assert (ida2a > 0).all() and len(set(ida2a.tolist())) == 1
    assert (np.asarray(m["out_a2a_bytes"]) > 0).all()
    # per-rank routed counts sum to >= the dense id volume
    assert np.asarray(m["ids_routed"]).sum() > 0


def test_distributed_healthy_batch_zero_overflow():
    de, state, step = _dist_setup()
    rng = np.random.default_rng(0)
    b, cap = 4, 8
    rag = _stacked_ragged(rng, cap, b, [2] * WORLD)
    cats = [rag] + [jnp.asarray(rng.integers(0, 30, WORLD * b), jnp.int32)
                    for _ in range(WORLD + 1)]
    _, _, m = step(state, cats, None)
    assert np.asarray(m["id_overflow"]).sum() == 0


# ------------------------------------------------- counters and listeners


def test_counters_inc_and_reset():
    obs.reset_counters()
    assert obs.counter_inc("x") == 1
    assert obs.counter_inc("x", 4) == 5
    assert obs.counters() == {"x": 5}
    obs.reset_counters()
    assert obs.counters() == {}


def test_retry_increments_counter():
    obs.reset_counters()
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise ValueError("transient")
        return "ok"

    assert runtime.retry(flaky, max_attempts=5, base_delay_s=0.01,
                         max_delay_s=0.02, describe="obs test") == "ok"
    assert obs.counters()["runtime_retries"] == 2
    assert obs.counters()["runtime_retries.obs_test"] == 2


def test_fault_point_increments_counter(monkeypatch):
    obs.reset_counters()
    runtime.reset_fault_counts()
    monkeypatch.setenv(runtime.FAULT_ENV, "raise:obs_probe:1")
    with pytest.raises(runtime.FaultInjected):
        runtime.fault_point("obs_probe")
    assert obs.counters()["fault_injections"] == 1
    assert obs.counters()["fault_injections.obs_probe"] == 1


def test_compile_listener_counts_fresh_compiles():
    assert obs.install_compile_listener()
    obs.reset_counters()
    shape = (17,)  # unlikely to be cached from another test

    @jax.jit
    def f(x):
        return x * 2 + 1

    f(jnp.zeros(shape)).block_until_ready()
    first = obs.counters().get("recompiles", 0)
    assert first >= 1
    f(jnp.ones(shape)).block_until_ready()  # cache hit: no new compile
    assert obs.counters().get("recompiles", 0) == first


# -------------------------------------------------------- metrics logger


def test_metrics_logger_roundtrip(tmp_path):
    path = str(tmp_path / "metrics.jsonl")
    log = obs.MetricsLogger(path)
    m = {"ids_routed": jnp.asarray([7], jnp.int32),
         "id_overflow": np.asarray([0])}
    log.log_step(m, step=3, variant="test")
    obs.reset_counters()
    obs.counter_inc("recompiles", 2)
    log.log_counters(final=True)
    recs = obs.MetricsLogger.load(path)
    assert [r["section"] for r in recs] == ["step_metrics", "counters"]
    assert recs[0]["step"] == 3 and recs[0]["variant"] == "test"
    assert recs[0]["metrics"]["ids_routed"] == [7]
    assert recs[1]["counters"]["recompiles"] == 2
    # every line is independently parseable JSON (fsynced JSONL contract)
    with open(path, encoding="utf-8") as f:
        for line in f:
            json.loads(line)


def test_metrics_logger_tolerates_torn_tail(tmp_path):
    path = str(tmp_path / "metrics.jsonl")
    log = obs.MetricsLogger(path)
    log.log_step({"ids_routed": [1]}, step=0)
    with open(path, "a", encoding="utf-8") as f:
        f.write('{"section": "step_metrics", "metr')  # killed mid-write
    recs = obs.MetricsLogger.load(path)
    assert len(recs) == 1 and recs[0]["step"] == 0


def test_summarize_reduces_per_rank_vectors():
    m = {"ids_routed": np.asarray([4, 6]),
         "id_overflow": np.asarray([0, 3]),
         "id_a2a_bytes": np.asarray([10.0, 10.0]),
         "out_pad_frac": np.asarray([0.25, 0.5]),
         "loss": np.asarray([1.5, 1.5])}
    s = obs.summarize(m)
    assert s["ids_routed"] == 10.0
    assert s["id_overflow"] == 3.0
    assert s["id_a2a_bytes"] == 20.0
    assert s["out_pad_frac"] == 0.5
    assert s["loss"] == 1.5


def test_summarize_percentiles_of_per_rank_vectors():
    # 8-rank vector: p50/p95 ride alongside the scalar aggregation
    ids = np.asarray([10.0, 10, 10, 10, 10, 10, 10, 94])
    s = obs.summarize({"ids_routed": ids, "loss": np.asarray([1.0])})
    assert s["ids_routed"] == float(ids.sum())
    assert s["ids_routed_p50"] == pytest.approx(np.percentile(ids, 50))
    assert s["ids_routed_p95"] == pytest.approx(np.percentile(ids, 95))
    # scalar ([1]-shaped) metrics carry no percentile keys
    assert "loss_p50" not in s and "loss_p95" not in s


def test_metrics_logger_rotation_caps_growth(tmp_path):
    path = str(tmp_path / "metrics.jsonl")
    cap = 2000
    log = obs.MetricsLogger(path, max_bytes=cap)
    for s in range(60):
        log.log_step({"ids_routed": list(range(8))}, step=s)
    # the live file stays bounded by ~one record past the cap, and the
    # rotated generation holds the earlier records
    assert os.path.getsize(path) <= cap + 200
    assert os.path.exists(path + ".1")
    live = obs.MetricsLogger.load(path)
    rotated = obs.MetricsLogger.load(path + ".1")
    assert live and rotated
    # one generation kept: the retained tail is contiguous, ordered,
    # and ends at the newest record
    steps = [r["step"] for r in rotated + live]
    assert steps == list(range(steps[0], 60))


def test_metrics_logger_unbounded_by_default(tmp_path):
    path = str(tmp_path / "metrics.jsonl")
    log = obs.MetricsLogger(path)  # DETPU_OBS_MAX_BYTES default 0
    for s in range(30):
        log.log_counters(step=s)
    assert not os.path.exists(path + ".1")
    assert len(obs.MetricsLogger.load(path)) == 30


def test_metrics_logger_n_generation_rotation(tmp_path):
    # max_files=3: .1/.2/.3 ride behind the live file (the checkpoint
    # ring idiom); the record stream across ALL generations is the
    # contiguous, ordered tail of everything logged
    path = str(tmp_path / "metrics.jsonl")
    cap = 1000
    log = obs.MetricsLogger(path, max_bytes=cap, max_files=3)
    for s in range(120):
        log.log_step({"ids_routed": list(range(8))}, step=s)
    gens = [p for p in (f"{path}.{i}" for i in range(1, 5))
            if os.path.exists(p)]
    assert gens == [f"{path}.{i}" for i in (1, 2, 3)]  # never a .4
    assert os.path.getsize(path) <= cap + 200
    recs = []
    for p in reversed(gens):  # .3 oldest ... .1 newest rotated
        recs.extend(obs.MetricsLogger.load(p))
    recs.extend(obs.MetricsLogger.load(path))
    steps = [r["step"] for r in recs]
    assert steps == list(range(steps[0], 120))


def test_metrics_logger_rotation_drops_oldest_generation(tmp_path):
    # with max_files=1 every rotation REPLACES .1 — the oldest records
    # fall off instead of a .2 appearing
    path = str(tmp_path / "metrics.jsonl")
    log = obs.MetricsLogger(path, max_bytes=500, max_files=1)
    for s in range(80):
        log.log_step({"ids_routed": list(range(8))}, step=s)
    assert os.path.exists(path + ".1")
    assert not os.path.exists(path + ".2")
    tail = [r["step"] for r in obs.MetricsLogger.load(path + ".1")
            + obs.MetricsLogger.load(path)]
    assert tail == list(range(tail[0], 80))
    assert tail[0] > 0  # something WAS dropped


def test_metrics_logger_max_files_env_default(tmp_path, monkeypatch):
    monkeypatch.setenv("DETPU_OBS_MAX_FILES", "4")
    log = obs.MetricsLogger(str(tmp_path / "m.jsonl"), max_bytes=100)
    assert log.max_files == 4
    monkeypatch.delenv("DETPU_OBS_MAX_FILES")
    log = obs.MetricsLogger(str(tmp_path / "m2.jsonl"), max_bytes=100)
    assert log.max_files == 2  # registry default


# ------------------------------------------------- sparse_optax metrics


def test_sparse_grad_metrics_counts_live_rows():
    vocab = 10
    g = SparseRows(ids=jnp.asarray([0, 3, vocab, vocab], jnp.int32),
                   rows=jnp.asarray([[3.0, 4.0], [0.0, 0.0],
                                     [9.0, 9.0], [9.0, 9.0]]),
                   vocab=vocab)
    out = sparse_grad_metrics([g])
    assert int(out["touched_rows"][0]) == 2  # the two in-vocab rows
    # pad rows' values are excluded from the norm: |(3,4)| = 5
    assert float(out["sparse_grad_norm"][0]) == pytest.approx(5.0)


# ------------------------------------------------------- tracing helpers


def test_scope_and_profile_trace_noop(tmp_path, monkeypatch):
    with obs.scope("unit_test"):
        pass  # named_scope outside a trace is a no-op context
    monkeypatch.delenv(obs.PROFILE_DIR_ENV, raising=False)
    with obs.profile_trace("nothing"):
        pass  # disabled: transparent
    d = str(tmp_path / "prof")
    monkeypatch.setenv(obs.PROFILE_DIR_ENV, d)
    with obs.profile_trace("lbl"):
        jnp.zeros((2,)).block_until_ready()
    # a capture directory was created for the label
    assert os.path.isdir(os.path.join(d, "lbl"))

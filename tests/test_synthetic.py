"""Synthetic model zoo tests (reference:
``examples/benchmarks/synthetic_models/``)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh

from distributed_embeddings_tpu.models import (
    InputGenerator,
    build_synthetic,
    expand_embedding_configs,
    synthetic_models_v3,
)
from distributed_embeddings_tpu.models.synthetic import average_pool_1d
from distributed_embeddings_tpu.parallel import (
    SparseAdagrad,
    init_hybrid_state,
    make_hybrid_train_step,
)


def test_zoo_scales_match_reference():
    """Table counts from the reference README: 55..2002 tables."""
    expected = {"tiny": 55, "small": 107, "medium": 311, "large": 612,
                "jumbo": 1022, "colossal": 2002}
    for name, count in expected.items():
        cfgs, table_map, hotness = expand_embedding_configs(
            synthetic_models_v3[name])
        assert len(cfgs) == count, name
        assert len(table_map) == len(hotness)


def test_expand_shared_tables():
    cfgs, table_map, hotness = expand_embedding_configs(
        synthetic_models_v3["tiny"])
    # first group: 1 table shared by inputs of hotness 1 and 10
    assert table_map[0] == table_map[1] == 0
    assert hotness[0] == 1 and hotness[1] == 10


def test_average_pool_1d():
    x = jnp.asarray(np.arange(12, dtype=np.float32).reshape(2, 6))
    out = average_pool_1d(x, 4)
    # windows [0..3] avg, [4,5] avg over true count 2
    np.testing.assert_allclose(out, [[1.5, 4.5], [7.5, 10.5]])


@pytest.mark.slow
def test_tiny_trains_on_mesh():
    world = 8
    mesh = Mesh(np.array(jax.devices()[:world]), ("data",))
    model_cfg = synthetic_models_v3["tiny"]
    de, dense, hotness = build_synthetic(model_cfg, world, row_cap=1000,
                                         column_slice_threshold=8000)
    B = world * 4
    gen = InputGenerator(model_cfg, B, alpha=1.05, num_batches=2,
                         row_cap=1000)
    num0, cats0, labels0 = gen[0]
    out_widths = [int(de.strategy.global_configs[t]["output_dim"])
                  for t in de.strategy.input_table_map]
    dense_params = dense.init(
        jax.random.key(0), num0[:2],
        [jnp.zeros((2, w), jnp.float32) for w in out_widths])

    emb_opt = SparseAdagrad()
    tx = optax.adagrad(0.05)

    def loss_fn(dp, emb_outs, batch):
        n, y = batch
        return jnp.mean((dense.apply(dp, n, emb_outs) - y) ** 2)

    state = init_hybrid_state(de, emb_opt, dense_params, tx,
                              jax.random.key(1), mesh=mesh)
    step_fn = make_hybrid_train_step(de, loss_fn, tx, emb_opt, mesh=mesh,
                                     lr_schedule=0.05)
    losses = []
    for i in range(6):
        num, cats, labels = gen[i]
        loss, state = step_fn(state, cats, (num, labels))
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]

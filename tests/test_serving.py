"""Deadline-bounded serving runtime: coalescing, the padded-batch
ladder, deadline scheduling, overload admission control, graceful
degradation, and the read-only streaming serve path.

The semantics under test (``parallel/serving.py``):

* variable-size requests coalesce FIFO into the smallest ladder rung
  that holds them; padding rows are inert and the sliced-back
  predictions are bitwise the direct forward's;
* the scheduler flushes on max_batch OR max_wait_ms, propagates
  per-request deadlines (early flush to make them, typed ``Expired``
  past them), and the degradation ladder first shrinks the batching
  delay, then sheds lowest-priority requests with typed ``Overloaded``
  — queue growth is bounded by construction;
* a warmed ladder never recompiles, whatever request-size mix arrives;
* streaming tables serve READ-ONLY: cold/evicted ids resolve to their
  shared bucket rows, admitted ids to their slots (agreeing with the
  rows the train path writes), and the slot map/sketch are
  bitwise-unchanged by any amount of serving.
"""

import json

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from distributed_embeddings_tpu.parallel import (
    DistributedEmbedding, Expired, Overloaded, Request, ServeConfig,
    Served, ServingRuntime, SparseSGD, StreamingConfig,
    init_hybrid_state, init_streaming, make_hybrid_eval_step,
    make_hybrid_train_step)
from distributed_embeddings_tpu.parallel import serving as sv
from distributed_embeddings_tpu.parallel import streaming as smod
from distributed_embeddings_tpu.utils import mplane, obs


class ManualClock:
    """Injectable clock: tests own time, so wait/deadline semantics are
    deterministic (no wall-clock sleeps anywhere)."""

    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t


def _pred_fn(dp, outs, batch):
    p = sum(jnp.sum(o, -1) for o in outs)
    if batch is not None:
        p = p + jnp.sum(batch, -1)
    return p


def _build(configs=None, world=1, mesh=None, **cfg_kw):
    configs = configs or [{"input_dim": 100, "output_dim": 4},
                          {"input_dim": 50, "output_dim": 4}]
    de = DistributedEmbedding(configs, world_size=world)
    tx = optax.sgd(0.1)
    state = init_hybrid_state(de, SparseSGD(), {"w": jnp.ones((4, 1))},
                              tx, jax.random.key(0), mesh=mesh)
    clock = ManualClock()
    cfg_kw.setdefault("max_batch", 16)
    cfg_kw.setdefault("max_wait_ms", 5)
    cfg_kw.setdefault("deadline_ms", 1000)
    cfg_kw.setdefault("max_queue", 64)
    rt = ServingRuntime(de, _pred_fn, state, mesh=mesh,
                        config=ServeConfig(**cfg_kw), clock=clock)
    return de, state, rt, clock


def _tmpl(n_inputs=2, numerical=3):
    return ([np.zeros(2, np.int32) for _ in range(n_inputs)],
            np.zeros((2, numerical), np.float32))


def _req(rng, de_sizes=(100, 50), n=3, numerical=3, **kw):
    return sv.synthetic_request(rng, list(de_sizes), n,
                                numerical=numerical, **kw)


# ------------------------------------------------------------- the ladder


def test_default_ladder_is_pow2_world_multiples():
    assert sv.resolve_rungs(ServeConfig(max_batch=64), world=1) \
        == (8, 16, 32, 64)
    # the top rung rounds DOWN to a world multiple: the ladder must
    # never exceed the configured max_batch (admission and the
    # max_queue validation bind against it)
    assert sv.resolve_rungs(ServeConfig(max_batch=100), world=8) \
        == (8, 16, 32, 64, 96)
    # a max_batch below the pow2 floor is its own single rung
    assert sv.resolve_rungs(ServeConfig(max_batch=4), world=1) == (4,)
    # ...but never below one world row
    assert sv.resolve_rungs(ServeConfig(max_batch=4, max_queue=8),
                            world=8) == (8,)


def test_explicit_rungs_validated():
    assert sv.resolve_rungs(
        ServeConfig(rungs=(16, 64)), world=8) == (16, 64)
    with pytest.raises(ValueError, match="ascending"):
        sv.resolve_rungs(ServeConfig(rungs=(64, 16)), world=1)
    with pytest.raises(ValueError, match="multiple of world"):
        sv.resolve_rungs(ServeConfig(rungs=(12,)), world=8)


def test_config_validation():
    with pytest.raises(ValueError, match="shed_frac"):
        ServeConfig(shed_frac=0.0)
    with pytest.raises(ValueError, match="max_queue"):
        ServeConfig(max_batch=64, max_queue=32)


# -------------------------------------------------- coalescing + packing


def test_coalesced_predictions_match_direct_forward():
    de, state, rt, clock = _build()
    rt.warmup(_tmpl())
    rng = np.random.default_rng(0)
    r1, r2 = _req(rng, n=3), _req(rng, n=5)
    assert rt.submit(r1, now=0.0) is None
    assert rt.submit(r2, now=0.0) is None
    assert rt.poll(now=0.0) == []          # neither full nor timed out
    clock.t = 0.006
    res = rt.poll(now=0.006)
    served = {r.rid: r for r in res if isinstance(r, Served)}
    assert len(served) == 2 and all(r.rung == 8 for r in served.values())
    for req in (r1, r2):
        direct = _pred_fn(None, de(state.emb_params,
                                   [jnp.asarray(c) for c in req.cats]),
                          jnp.asarray(req.batch))
        np.testing.assert_array_equal(
            np.asarray(served[req.rid].predictions), np.asarray(direct))
    s = rt.stats()
    assert s["flushes"] == 1 and s["pad_fraction"] == 0.0
    assert s["served_samples"] == 8


def test_multihot_and_ragged_inputs_pack():
    configs = [{"input_dim": 100, "output_dim": 4},
               {"input_dim": 60, "output_dim": 4, "combiner": "sum"},
               {"input_dim": 40, "output_dim": 4, "combiner": "sum"}]
    de, state, rt, clock = _build(configs, ragged_hotness=3)
    tmpl = ([np.zeros(2, np.int32), np.zeros((2, 2), np.int32),
             [[1], [2, 3]]], np.zeros((2, 3), np.float32))
    rt.warmup(tmpl)
    req = Request(
        cats=[np.asarray([5, 6, 7], np.int32),
              np.asarray([[1, 2], [3, 4], [5, 6]], np.int32),
              [[10, 11], [], [12, 13, 14, 15]]],  # last row clips to 3
        batch=np.ones((3, 3), np.float32))
    assert rt.submit(req, now=0.0) is None
    clock.t = 0.01
    res = rt.poll(now=0.01)
    (served,) = [r for r in res if isinstance(r, Served)]
    from distributed_embeddings_tpu.ops.embedding_lookup import Ragged
    rag = Ragged(values=jnp.asarray([10, 11, 12, 13, 14, 0, 0, 0, 0],
                                    jnp.int32),
                 row_splits=jnp.asarray([0, 2, 2, 5], jnp.int32))
    direct = _pred_fn(None, de(state.emb_params,
                               [jnp.asarray(req.cats[0]),
                                jnp.asarray(req.cats[1]), rag]),
                      jnp.ones((3, 3), jnp.float32))
    np.testing.assert_array_equal(np.asarray(served.predictions),
                                  np.asarray(direct))
    assert rt.stats()["ragged_clipped"] == 1


def test_request_validation():
    de, state, rt, clock = _build()
    rt.warmup(_tmpl())
    with pytest.raises(ValueError, match="categorical inputs"):
        rt.submit(Request(cats=[np.zeros(2, np.int32)]), now=0.0)
    with pytest.raises(ValueError, match="largest rung"):
        rt.submit(Request(cats=[np.zeros(99, np.int32),
                                np.zeros(99, np.int32)],
                          batch=np.zeros((99, 3), np.float32)), now=0.0)
    with pytest.raises(ValueError, match="empty"):
        rt.submit(Request(cats=[np.zeros(0, np.int32),
                                np.zeros(0, np.int32)],
                          batch=np.zeros((0, 3), np.float32)), now=0.0)
    with pytest.raises(ValueError, match="samples"):
        rt.submit(Request(cats=[np.zeros(2, np.int32),
                                np.zeros(3, np.int32)],
                          batch=np.zeros((2, 3), np.float32)), now=0.0)
    # a malformed BATCH is rejected at submit, while nothing is queued —
    # failing at pack time would crash the flush and lose every healthy
    # request coalesced with it
    with pytest.raises(ValueError, match="batch spec"):
        rt.submit(Request(cats=[np.zeros(2, np.int32),
                                np.zeros(2, np.int32)],
                          batch=np.zeros((2, 5), np.float32)), now=0.0)
    with pytest.raises(ValueError, match="batch spec"):
        rt.submit(Request(cats=[np.zeros(2, np.int32),
                                np.zeros(2, np.int32)]), now=0.0)
    assert rt.queued_samples == 0


# ------------------------------------------------- the deadline scheduler


def test_flush_on_max_wait():
    de, state, rt, clock = _build(max_wait_ms=5)
    rt.warmup(_tmpl())
    rng = np.random.default_rng(1)
    rt.submit(_req(rng, n=2), now=0.0)
    assert rt.poll(now=0.004) == []
    clock.t = 0.005
    res = rt.poll(now=0.005)
    assert [type(r) for r in res] == [Served]
    assert res[0].latency_ms == pytest.approx(5.0)


def test_flush_on_full_rung():
    de, state, rt, clock = _build(max_batch=16)
    rt.warmup(_tmpl())
    rng = np.random.default_rng(2)
    for _ in range(4):
        rt.submit(_req(rng, n=4), now=0.0)
    res = rt.poll(now=0.0)   # 16 queued = the largest rung: no waiting
    assert sum(isinstance(r, Served) for r in res) == 4
    assert rt.stats()["rung_flushes"] == {"16": 1}


def test_deadline_propagation_flushes_early():
    # huge max_wait: only the deadline can force this flush
    de, state, rt, clock = _build(max_wait_ms=10_000)
    rt.warmup(_tmpl())
    rng = np.random.default_rng(3)
    req = _req(rng, n=2)
    req.deadline_ms = 20.0
    rt.submit(req, now=0.0)
    assert rt.poll(now=0.010) == []
    res = rt.poll(now=0.020)   # t + est >= deadline -> flush now
    assert [type(r) for r in res] == [Served]
    assert not res[0].deadline_missed


def test_expired_requests_drop_typed():
    de, state, rt, clock = _build(max_wait_ms=10_000)
    rt.warmup(_tmpl())
    rng = np.random.default_rng(4)
    req = _req(rng, n=2)
    req.deadline_ms = 5.0
    rt.submit(req, now=0.0)
    clock.t = 0.05
    res = rt.poll(now=0.05)
    assert [type(r) for r in res] == [Expired]
    assert res[0].deadline_ms == 5.0
    s = rt.stats()
    assert s["expired"] == 1 and s["deadline_missed"] == 1
    assert s["served"] == 0 and rt.queued_samples == 0


def test_late_completion_marks_deadline_missed():
    de, state, rt, clock = _build(max_wait_ms=5)

    class SlowClock(ManualClock):
        def __call__(self):
            self.t += 0.02   # every clock read advances 20ms
            return self.t

    rt._clock = SlowClock()
    rt.warmup(_tmpl())
    rng = np.random.default_rng(5)
    req = _req(rng, n=2)
    # deadline chosen so the request does NOT expire before the flush
    # (submit reads t=0.02s -> deadline 0.05s; poll reads 0.04s < 0.05)
    # but the flush's completion read (0.08s) lands past it
    req.deadline_ms = 30.0
    rt.submit(req)
    res = rt.poll()
    served = [r for r in res if isinstance(r, Served)]
    assert len(served) == 1   # flushed, not expired
    assert served[0].deadline_missed
    assert rt.stats()["deadline_missed"] == 1


# ---------------------------------------------- overload admission control


def test_overload_sheds_typed_and_recovers():
    obs.drain_events()
    de, state, rt, clock = _build(max_batch=8, max_queue=16,
                                  shed_frac=0.5, max_wait_ms=10_000)
    rt.warmup(_tmpl())
    rng = np.random.default_rng(6)
    rejections = []
    for _ in range(12):
        r = rt.submit(_req(rng, n=2), now=0.0)
        if r is not None:
            rejections.append(r)
    # 16-sample queue: 4 fit below the 8-sample shed line... queue fills
    # to the cap, everything past it is typed, queue NEVER exceeds cap
    assert rt.queued_samples <= 16
    assert rejections and all(isinstance(r, Overloaded)
                              for r in rejections)
    assert {r.reason for r in rejections} <= {"load_shed", "queue_full"}
    assert rt.level == 2
    deg = obs.drain_events("serve_degraded")
    assert deg and deg[-1]["level"] == 2
    # drain: the ladder must walk back down and say so
    res = rt.flush(now=0.0)
    assert sum(isinstance(r, Served) for r in res) > 0
    assert rt.level == 0
    rec = obs.drain_events("serve_recovered")
    assert rec and rec[-1]["level"] == 0
    s = rt.stats()
    assert s["shed"] == len(rejections) and s["degraded"] >= 1
    assert s["recovered"] >= 1


def test_priority_survives_shed_level():
    de, state, rt, clock = _build(max_batch=8, max_queue=32,
                                  shed_frac=0.25, max_wait_ms=10_000)
    rt.warmup(_tmpl())
    rng = np.random.default_rng(7)
    while rt.queued_samples < 8:   # climb past the shed line
        assert rt.submit(_req(rng, n=2), now=0.0) is None
    assert rt.level == 2
    lo = rt.submit(_req(rng, n=2), now=0.0)
    assert isinstance(lo, Overloaded) and lo.reason == "load_shed"
    hi = _req(rng, n=2)
    hi.priority = 1
    assert rt.submit(hi, now=0.0) is None   # high priority still admitted
    full = _req(rng, n=2)
    full.priority = 99
    while rt.submit(full, now=0.0) is None:  # ...until the hard cap
        full = _req(rng, n=2)
        full.priority = 99
    rej = rt.submit(full, now=0.0)
    assert isinstance(rej, Overloaded) and rej.reason == "queue_full"


def test_pressure_level_shrinks_batching_delay():
    de, state, rt, clock = _build(max_batch=8, max_queue=64,
                                  max_wait_ms=10_000)
    rt.warmup(_tmpl())
    rng = np.random.default_rng(8)
    for _ in range(4):
        rt.submit(_req(rng, n=2), now=0.0)
    # 8 queued >= largest rung -> level 1: flush NOW despite max_wait
    assert rt.level == 1
    res = rt.poll(now=0.0)
    assert sum(isinstance(r, Served) for r in res) == 4


def test_flush_failure_answers_typed(monkeypatch):
    """A flush that raises (injected fault, transient backend error)
    answers its coalesced requests with typed Failed instead of the
    exception escaping poll() and losing them — and the loop keeps
    serving afterwards."""
    from distributed_embeddings_tpu.utils import runtime as rmod

    de, state, rt, clock = _build()
    rt.warmup(_tmpl())
    rmod.reset_fault_counts()
    monkeypatch.setenv(rmod.FAULT_ENV, "raise:serve_step:1")
    rng = np.random.default_rng(11)
    rt.submit(_req(rng, n=2), now=0.0)
    clock.t = 0.01
    res = rt.poll(now=0.01)
    assert [type(r) for r in res] == [sv.Failed]
    assert "FaultInjected" in res[0].reason
    assert rt.stats()["failed"] == 1 and rt.queued_samples == 0
    deg = obs.counters().get("event_serve_flush_error", 0)
    assert deg >= 1
    # the fault budget is spent: service continues normally
    rt.submit(_req(rng, n=2), now=0.02)
    clock.t = 0.03
    res = rt.poll(now=0.03)
    assert [type(r) for r in res] == [Served]


# ------------------------------------------------------ recompile hygiene


def test_mixed_sizes_never_recompile_after_warmup():
    de, state, rt, clock = _build(max_batch=32)
    rt.warmup(_tmpl())
    assert rt.warmup_compiles >= len(rt.rungs)
    rng = np.random.default_rng(9)
    for i in range(10):
        rt.submit(_req(rng, n=1 + (i * 3) % 7), now=clock.t)
        clock.t += 0.01
        rt.poll(now=clock.t)
    clock.t += 1.0
    rt.poll(now=clock.t)
    s = rt.stats()
    assert s["served"] == 10
    assert s["steady_state_recompiles"] == 0
    assert len(s["rung_flushes"]) >= 1


# ----------------------------------------------------------- the auditor


def test_audit_serve_program_world1_has_no_collectives():
    de, state, rt, clock = _build()
    rt.warmup(_tmpl())
    rep = sv.audit_serve_program(rt)
    assert rep.violations == []
    assert rep.collective_counts.get("all_to_all", 0) == 0
    assert rep.collective_counts.get("psum", 0) == 0


@pytest.fixture
def mesh8():
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()[:8]), ("data",))


def _world8_configs():
    return ([{"input_dim": 64, "output_dim": 8},
             {"input_dim": 32 + 8, "output_dim": 8,
              "streaming": {"capacity": 32, "buckets": 8}}]
            + [{"input_dim": 24 + i, "output_dim": 4} for i in range(6)])


def test_audit_serve_program_world8_forward_contract(mesh8):
    de, state, rt, clock = _build(
        [{"input_dim": 50 + i, "output_dim": 4} for i in range(8)],
        world=8, mesh=mesh8, max_batch=16)
    rt.warmup(_tmpl(n_inputs=8))
    rep = sv.audit_serve_program(rt)
    assert rep.violations == []
    # forward-only: id + out exchange, NO grad exchange, NO psum
    assert rep.a2a_census() == {"id_exchange_fwd": 1,
                                "out_exchange_fwd": 1}
    assert rep.collective_counts.get("psum", 0) == 0
    assert rep.host_interop == []


# ------------------------------------- read-only streaming serve (world 8)


def test_streaming_serve_world8_read_only_and_remap_agreement(mesh8):
    """The satellite-4 battery: at world 8, (a) serving leaves the slot
    map/sketch bitwise-unchanged, (b) cold ids resolve to shared bucket
    rows (two ids sharing a bucket serve identical embeddings), (c) the
    serve remap agrees with the train path — an id the TRAIN step
    admitted serves from its slot (diverging from its bucket-mate), and
    a later train update to that slot is visible to eval."""
    configs = _world8_configs()
    de = DistributedEmbedding(configs, world_size=8)
    scfg = StreamingConfig(admit_min_count=2, evict_margin=1, depth=2,
                           buckets=128)
    tx = optax.sgd(0.05)
    state = init_hybrid_state(de, SparseSGD(), {"w": jnp.ones((4, 1))},
                              tx, jax.random.key(0), mesh=mesh8)
    sstate = init_streaming(de, scfg, mesh=mesh8)

    def loss_fn(dp, outs, b):
        return (sum(jnp.mean(o) for o in outs) * jnp.mean(dp["w"])
                + jnp.mean(b))

    step = make_hybrid_train_step(de, loss_fn, tx, SparseSGD(),
                                  mesh=mesh8, dynamic=scfg,
                                  with_metrics=True, nan_guard=False)
    B = 16
    zeros = [jnp.zeros((B,), jnp.int32) if i != 1 else None
             for i in range(8)]

    def cats_with(ext_id):
        return [jnp.full((B,), ext_id, jnp.int32) if i == 1 else z
                for i, z in enumerate(zeros)]

    hot = 987_654_321
    b_t = jnp.zeros((B,), jnp.float32)
    m = None
    for _ in range(3):
        _, state, m, sstate = step(state, cats_with(hot), b_t, sstate)
    assert float(np.asarray(m["stream_hit_ids"]).sum()) > 0  # admitted

    # two COLD external ids engineered to share a hash bucket, one in a
    # different bucket — computed BEFORE warmup (the eager hash mixes
    # compile tiny programs that must not count as steady-state serves)
    tid = jnp.asarray(1, jnp.int32)
    nb = 8
    base = 111_111
    cands = jnp.arange(base, base + 4096, dtype=jnp.int32)
    buckets = np.asarray(smod._mix(cands, tid, smod._H_BUCKET)
                         % np.uint32(nb))
    cold_a = base
    cold_b = base + int(np.nonzero(buckets[1:] == buckets[0])[0][0]) + 1
    cold_c = base + int(np.nonzero(buckets[1:] != buckets[0])[0][0]) + 1

    clock = ManualClock()
    rt = ServingRuntime(
        de, _pred_fn, state, mesh=mesh8,
        config=ServeConfig(max_batch=16, max_wait_ms=2,
                           deadline_ms=1000, max_queue=64),
        streaming=(scfg, sstate), clock=clock)
    rt.warmup(_tmpl(n_inputs=8))
    before = jax.tree.map(np.asarray, rt.streaming_state)

    def serve_one(ext_id):
        req = Request(cats=[np.full((8,), ext_id, np.int32) if i == 1
                            else np.zeros((8,), np.int32)
                            for i in range(8)],
                      batch=np.zeros((8, 3), np.float32))
        rt.submit(req, now=clock.t)
        clock.t += 0.01
        res = rt.poll(now=clock.t)
        (r,) = [x for x in res if isinstance(x, Served)]
        return np.asarray(r.predictions)

    pa, pb, pc, ph = (serve_one(cold_a), serve_one(cold_b),
                      serve_one(cold_c), serve_one(hot))
    # (b) cold ids SHARE their bucket row: same bucket -> same serving
    np.testing.assert_array_equal(pa, pb)
    # the admitted id reads its own (zero-init, trained) slot row, not
    # the bucket row its cold self would have used
    assert not np.array_equal(ph, pa) or not np.array_equal(ph, pc)
    # (a) serving mutated NOTHING
    after = jax.tree.map(np.asarray, rt.streaming_state)
    for x, y in zip(jax.tree.leaves(before), jax.tree.leaves(after)):
        np.testing.assert_array_equal(x, y)
    assert rt.stats()["steady_state_recompiles"] == 0

    # (c) eval-vs-train remap agreement: the serving runtime's answer is
    # bitwise the plain eval step's for the same inputs...
    ev = make_hybrid_eval_step(de, _pred_fn, mesh=mesh8, dynamic=scfg)
    direct = np.asarray(ev(
        state, [jnp.full((8,), hot, jnp.int32) if i == 1
                else jnp.zeros((8,), jnp.int32) for i in range(8)],
        jnp.zeros((8, 3), jnp.float32), sstate))
    np.testing.assert_array_equal(ph, direct)
    # ...and a train update to the admitted slot is what eval sees next
    _, state2, _, sstate2 = step(state, cats_with(hot),
                                 jnp.ones((B,), jnp.float32), sstate)
    rt.state, rt.streaming_state = state2, sstate2
    ph2 = serve_one(hot)
    assert not np.array_equal(ph2, ph)


# ------------------------------------------------------------- the driver


def test_drive_applies_burst_positions():
    de, state, rt, clock = _build(max_batch=32, max_queue=2048,
                                  deadline_ms=60_000)
    import time as _time

    rt._clock = _time.monotonic   # drive runs in real time
    rt.warmup(_tmpl())
    rng = np.random.default_rng(10)

    def make_request(i):
        return _req(rng, n=1)

    res_plain = sv.drive(rt, make_request, qps=100, duration_s=0.5,
                         burst_positions=())
    n_plain = len(res_plain)
    res_burst = sv.drive(rt, make_request, qps=100, duration_s=0.5,
                         burst_positions=(0,), burst_x=4.0)
    # second 0 spans the whole 0.5s stream: ~4x the arrivals
    assert len(res_burst) > 2 * n_plain
    assert rt.stats()["steady_state_recompiles"] == 0


def test_realtime_driver_concurrent_with_publisher():
    """ISSUE 18: the wall-clock driver runs on its OWN thread while the
    'trainer' (this thread) keeps publishing snapshots — freshness_p95_s
    must come out of true concurrency, every request must come back
    typed (none lost, none hung), and the publish/flush race must never
    produce a torn read (the RCU contract under an actual second
    thread)."""
    import time as _time

    de, state, rt, clock = _build(max_batch=32, max_queue=4096,
                                  deadline_ms=60_000, max_wait_ms=2)
    rt._clock = _time.monotonic   # the driver runs in real time
    rt.warmup(_tmpl())
    rt.install_snapshot(state, version=1, train_step=0)
    rng = np.random.default_rng(11)
    drv = sv.RealtimeDriver(rt, lambda i: _req(rng, n=1), qps=300,
                            duration_s=None, burst_positions=(),
                            drain_s=30.0)
    drv.start()
    t0, v = _time.monotonic(), 1
    while _time.monotonic() - t0 < 0.6:
        v += 1
        rt.install_snapshot(state, version=v, train_step=v)
        rt.note_train_step(v)
        _time.sleep(0.02)
    drv.stop()
    drv.join(timeout=60)
    results = drv.results()
    assert drv.submitted > 0
    # conservation across threads: every submitted rid answered once
    assert sorted(r.rid for r in results) == list(range(drv.submitted))
    served = [r for r in results if isinstance(r, sv.Served)]
    assert served and {r.version for r in served} != {1}  # saw republishes
    st = rt.stats()
    assert st["freshness_p95_s"] is not None
    assert st["freshness_p95_s"] >= 0.0
    assert st["steady_state_recompiles"] == 0


def test_unavailable_is_typed_and_ranked_below_stale():
    """The outage response: carries its provenance, renders a status
    like every other typed result, and is NOT a Served."""
    u = sv.Unavailable(rid=7, latency_ms=0.0, reason="worker_down",
                       outage_s=1.5, restarts=2)
    assert u.status == "unavailable"
    assert not isinstance(u, sv.Served)
    assert (u.reason, u.outage_s, u.restarts) == ("worker_down", 1.5, 2)


def test_compare_bench_serving_gate():
    from tools import compare_bench as cb

    base = {"metric": "x",
            "serving": {"latency_p95_ms": 10.0,
                        "steady_state_recompiles": 0}}

    def cand(p95=10.0, rc=0):
        return {"metric": "x",
                "serving": {"latency_p95_ms": p95,
                            "steady_state_recompiles": rc}}

    assert cb.check_serving(base, cand()) == 0
    assert cb.check_serving(base, cand(p95=10.9)) == 0   # within 10%
    assert cb.check_serving(base, cand(p95=11.5)) == 1   # p95 ratchet
    assert cb.check_serving(base, cand(rc=2)) == 1       # recompiles
    # missing section vs a baseline that has it fails; both-missing and
    # new-section-no-baseline pass (rounds legitimately add sections)
    assert cb.check_serving(base, {"metric": "x"}) == 1
    assert cb.check_serving({"metric": "x"}, {"metric": "x"}) == 0
    assert cb.check_serving({"metric": "x"}, cand()) == 0


def test_stats_surface():
    de, state, rt, clock = _build()
    rt.warmup(_tmpl())
    s = rt.stats()
    for k in ("served", "shed", "deadline_missed", "pad_fraction",
              "queue_depth_p95", "latency_p99_ms", "level_name",
              "steady_state_recompiles", "warmup_compiles",
              "latency_stages_ms", "p99_dominant_stage"):
        assert k in s
    assert s["level_name"] == "healthy"


# ---------------------------------------------- observability plane views


class TickClock(ManualClock):
    """Monotone clock that advances a hair on every read, so the flush
    timestamps (t0/t_pack/t_disp/t_dev/t1) are strictly increasing and
    every decomposition span is nonzero."""

    def __call__(self) -> float:
        self.t += 1e-4
        return self.t


def _build_ticking(**cfg_kw):
    de, state, rt, clock = _build(**cfg_kw)
    tick = TickClock()
    tick.t = clock.t
    rt._clock = tick
    return de, state, rt, tick


def _drive_obs(rt, clock, rng, rounds=40):
    lats = []
    for i in range(rounds):
        assert rt.submit(_req(rng, n=2)) is None
        # varied queue waits, all past max_wait_ms so every round
        # flushes exactly its own request (counts stay exact)
        clock.t += 0.006 + 0.0015 * (i % 9)
        for r in rt.poll():
            assert isinstance(r, Served)
            lats.append(r.latency_ms)
    return lats


def test_served_spans_sum_exactly_to_latency():
    de, state, rt, clock = _build_ticking()
    rt.warmup(_tmpl())
    rng = np.random.default_rng(0)
    seen = 0
    for i in range(6):
        assert rt.submit(_req(rng, n=2)) is None
        clock.t += 0.007
        for r in rt.poll():
            assert isinstance(r, Served)
            assert set(r.spans) == {"queue_wait_ms", "coalesce_ms",
                                    "dispatch_ms", "device_compute_ms",
                                    "reply_slice_ms"}
            # the five stages are a PARTITION of the request's life:
            # they sum to the end-to-end latency by construction
            assert sum(r.spans.values()) == pytest.approx(
                r.latency_ms, rel=1e-9)
            assert all(v >= 0.0 for v in r.spans.values())
            assert r.spans["queue_wait_ms"] > 0
            seen += 1
    assert seen == 6


def test_stats_sketch_percentiles_match_numpy_reference():
    # the serving battery's pin: sketch-backed stats() percentiles sit
    # within the sketch's guaranteed relative error of the numpy
    # reference over the SAME samples (method="lower" = the exact order
    # statistic at the sketch's rank definition, q * (count - 1))
    de, state, rt, clock = _build_ticking()
    rt.warmup(_tmpl())
    rng = np.random.default_rng(2)
    lats = _drive_obs(rt, clock, rng, rounds=60)
    assert len(lats) == 60
    s = rt.stats()
    arr = np.asarray(lats, np.float64)
    for key, q in (("latency_p50_ms", 50), ("latency_p95_ms", 95),
                   ("latency_p99_ms", 99)):
        ref = float(np.percentile(arr, q, method="lower"))
        assert s[key] == pytest.approx(ref, rel=0.011), key


def test_stage_decomposition_accounts_for_total_latency():
    de, state, rt, clock = _build_ticking()
    rt.warmup(_tmpl())
    rng = np.random.default_rng(3)
    lats = _drive_obs(rt, clock, rng, rounds=30)
    s = rt.stats()
    stages = s["latency_stages_ms"]
    assert set(stages) == set(sv.STAGES)
    for st in stages.values():
        assert st["count"] == len(lats)
        assert {"p50", "p95", "p99", "mean", "sum"} <= set(st)
    # per-request spans partition the latency, so the per-stage sketch
    # SUMS add up to the total served latency (exactly — sums are not
    # bucketed)
    total = sum(st["sum"] for st in stages.values())
    assert total == pytest.approx(sum(lats), rel=1e-9)
    assert s["p99_dominant_stage"] in stages
    # with these injected waits the queue dominates the tail
    assert s["p99_dominant_stage"] == "queue_wait"


def test_serving_registry_prometheus_surface():
    de, state, rt, clock = _build_ticking()
    rt.warmup(_tmpl())
    rng = np.random.default_rng(4)
    _drive_obs(rt, clock, rng, rounds=10)
    text = rt.metrics.render()
    assert "# TYPE detpu_serve_latency_ms summary" in text
    assert "detpu_serve_latency_ms_count 10" in text
    assert 'detpu_serve_stage_ms{stage="queue_wait",quantile="0.99"}' \
        in text
    assert 'detpu_serve_total{outcome="served"} 10' in text
    assert "detpu_serve_level 0" in text
    assert "detpu_serve_steady_state_recompiles 0" in text
    # the registry snapshot round-trips through JSON in mergeable form
    doc = json.loads(json.dumps(rt.metrics.to_dict()))
    lat = doc["detpu_serve_latency_ms"]["series"][0]["value"]
    assert mplane.QuantileSketch.from_dict(lat).count == 10

"""Exchange-plan scaling invariants at zoo scale (no compilation).

The round-3 executor's headline property — HLO size independent of
world x tables — rests on the plan layout: few groups, rank-uniform
offsets, bounded padding. These tests pin those properties at the scales
the reference publishes (tiny -> colossal, ``config_v3.py:30-133`` there)
so a layout regression is caught in milliseconds, not in a 78-second
colossal compile.
"""

import numpy as np
import pytest

from distributed_embeddings_tpu.models import (build_synthetic,
                                               synthetic_models_v3)

WORLD = 8


def build_plan(scale, strategy="memory_balanced"):
    de, _, hots = build_synthetic(synthetic_models_v3[scale], WORLD,
                                  strategy=strategy, row_cap=1000)
    encs = [("d", h) for h in hots]
    return de, de._get_plan(encs, 64)


@pytest.mark.parametrize("scale", ["tiny", "small", "medium", "large",
                                   "jumbo", "colossal"])
def test_group_count_stays_small(scale):
    """Heavy HLO is O(#groups): the zoo's group count must stay O(10)
    regardless of table count (colossal: 2002 tables)."""
    de, plan = build_plan(scale)
    assert len(plan.groups) <= 12, (scale, len(plan.groups))
    assert len(plan.instances) == sum(
        len(ids) for ids in de.strategy.input_ids_list)


def test_layout_partitions_exactly():
    """Group regions tile the block and the output row with no gaps or
    overlaps, and every live instance stays inside its group region."""
    de, plan = build_plan("colossal")
    goff = col = 0
    for g in plan.groups:
        assert g.goff == goff and g.col == col, (g, goff, col)
        goff += g.n * g.blen
        col += g.n * g.width
    assert plan.l_max == max(goff, 1) and plan.s_max == max(col, 1)
    for inst in plan.instances:
        g = plan.groups[inst.group]
        assert inst.slot0 + inst.num_slots <= g.n


def test_plan_tensors_match_strategy():
    """Per-slot plan rows/roffs agree with the strategy's local configs."""
    de, plan = build_plan("medium")
    for r in range(WORLD):
        seen = 0
        for inst in plan.instances:
            if inst.rank != r:
                continue
            g = plan.groups[inst.group]
            m = de.strategy.local_map_list[r][
                de.strategy.input_ids_list[r].index(inst.input_id)]
            cfg = de.strategy.local_configs_list[r][m]
            assert g.width == int(cfg["output_dim"])
            for k in range(inst.num_slots):
                assert plan.rows[inst.group][r, inst.slot0 + k] == int(
                    cfg["input_dim"])
                assert plan.valid[inst.group][r, inst.slot0 + k] == 1.0
            seen += 1
        assert seen == len(de.strategy.input_ids_list[r])


@pytest.mark.parametrize("strategy", ["memory_balanced", "comm_balanced"])
def test_padding_within_bounds(strategy):
    """Output-exchange padding of the balanced strategies stays below the
    measured bounds of docs/perf_tpu.md (regression guard, +5pt slack)."""
    bounds = {"tiny": 0.25, "small": 0.20, "medium": 0.19}
    for scale, bound in bounds.items():
        de, plan = build_plan(scale, strategy=strategy)
        live = np.zeros(WORLD)
        for inst in plan.instances:
            live[inst.rank] += plan.out_width(inst)
        waste = 1 - live.mean() / plan.s_max
        assert waste <= bound, (strategy, scale, waste)

"""Seqlock shared-memory snapshot transport (ISSUE 18 tentpole piece 1).

The contract under test: a reader gets a BITWISE-consistent snapshot or
``None`` — never a torn one. Torn-read detection is pinned by forging
exactly the states a racing writer produces (begin stamp without end
stamp; payload bytes changed after the CRC was computed) and asserting
the reader refuses them, then recovers on the next clean publish. The
cross-process pin against in-process ``install_snapshot`` lives in
``tests/test_supervisor.py`` (needs jax); this file is pure transport.
"""

import struct

import pytest

from distributed_embeddings_tpu.utils import shm


def _mk(capacity=4096):
    region = shm.SnapshotShm.create(capacity)
    return region


def test_roundtrip_payload_and_metadata():
    with _mk() as region:
        payload = b"\x00\x01snapshot-bytes\xff" * 7
        seq = region.publish_bytes(payload, version=3, train_step=12,
                                   wall_ts=123.5)
        assert seq == 1
        snap = region.read_latest()
        assert snap is not None
        assert snap.payload == payload
        assert (snap.seq, snap.version, snap.train_step, snap.wall_ts) == \
            (1, 3, 12, 123.5)
        region.unlink()


def test_read_before_any_publish_is_none():
    with _mk() as region:
        assert region.read_latest() is None
        assert region.latest_seq() == 0
        region.unlink()


def test_latest_wins_and_buffers_alternate():
    with _mk() as region:
        for v in range(1, 6):
            region.publish_bytes(f"snap-{v}".encode(), version=v,
                                 train_step=v * 2, wall_ts=float(v))
        snap = region.read_latest()
        assert snap.payload == b"snap-5"
        assert snap.version == 5 and snap.seq == 5
        region.unlink()


def test_attach_reads_what_create_published():
    region = _mk()
    try:
        region.publish_bytes(b"cross-handle", version=9, train_step=1,
                             wall_ts=0.25)
        reader = shm.SnapshotShm.attach(region.name)
        try:
            snap = reader.read_latest()
            assert snap is not None and snap.payload == b"cross-handle"
            assert reader.capacity == region.capacity
        finally:
            reader.close()
    finally:
        region.unlink()


def test_attach_rejects_foreign_region():
    from multiprocessing import shared_memory

    raw = shared_memory.SharedMemory(create=True, size=256)
    try:
        with pytest.raises(ValueError, match="not a snapshot region"):
            shm.SnapshotShm.attach(raw.name)
    finally:
        raw.close()
        raw.unlink()


def test_mid_write_stamps_refuse_the_read():
    """Forge the writer-mid-publish state: begin stamp advanced, end
    stamp stale. Every retry re-reads ``latest`` and must give up with
    ``None`` — the caller keeps its previous snapshot."""
    with _mk() as region:
        region.publish_bytes(b"good", version=1, train_step=1, wall_ts=1.0)
        off = region._buf_off(1)
        # seq_begin := 99 while seq_end stays 1 -> mismatch
        struct.pack_into("<Q", region._shm.buf, off, 99)
        assert region.read_latest(retries=4) is None
        region.unlink()


def test_crc_catches_payload_torn_after_stamps():
    """Both stamps valid but a payload byte changed after the CRC was
    computed — the interleaving stamps alone cannot see."""
    with _mk() as region:
        region.publish_bytes(b"consistent-bytes", version=1, train_step=1,
                             wall_ts=1.0)
        data_off = region._buf_off(1) + shm.BUFHDR_SIZE
        region._shm.buf[data_off] ^= 0xFF
        assert region.read_latest(retries=4) is None
        region.unlink()


def test_recovery_after_fresh_publish():
    """A corrupted buffer is left behind the moment the writer publishes
    again: the new sequence lands in the OTHER buffer and reads clean."""
    with _mk() as region:
        region.publish_bytes(b"old", version=1, train_step=1, wall_ts=1.0)
        data_off = region._buf_off(1) + shm.BUFHDR_SIZE
        region._shm.buf[data_off] ^= 0xFF
        assert region.read_latest(retries=2) is None
        region.publish_bytes(b"new", version=2, train_step=2, wall_ts=2.0)
        snap = region.read_latest()
        assert snap is not None and snap.payload == b"new"
        assert snap.version == 2
        region.unlink()


def test_oversized_payload_raises_with_sizing_hint():
    with _mk(capacity=64) as region:
        with pytest.raises(ValueError, match="slack_capacity"):
            region.publish_bytes(b"x" * 65, version=1, train_step=1,
                                 wall_ts=1.0)
        region.unlink()


def test_region_bytes_and_slack_sizing(monkeypatch):
    assert shm.region_bytes(100) == \
        shm.HEADER_SIZE + 2 * (shm.BUFHDR_SIZE + 100)
    assert shm.slack_capacity(1000) == 1250  # default slack 1.25
    monkeypatch.setenv(shm.SLACK_ENV, "2.0")
    assert shm.slack_capacity(1000) == 2000
    monkeypatch.setenv(shm.SLACK_ENV, "0.5")
    with pytest.raises(ValueError, match="must be >= 1.0"):
        shm.slack_capacity(1000)


def test_writer_seq_monotone_across_reattach():
    """A writer handle rebuilt over an existing region (crash-resume)
    continues the sequence instead of restarting at 1 — readers key
    staleness off monotone seqs."""
    region = _mk()
    try:
        region.publish_bytes(b"a", version=1, train_step=1, wall_ts=1.0)
        region.publish_bytes(b"b", version=2, train_step=2, wall_ts=2.0)
        rewriter = shm.SnapshotShm.attach(region.name)
        try:
            seq = rewriter.publish_bytes(b"c", version=3, train_step=3,
                                         wall_ts=3.0)
            assert seq == 3
            assert region.read_latest().payload == b"c"
        finally:
            rewriter.close()
    finally:
        region.unlink()

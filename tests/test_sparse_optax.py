"""Op-layer sparse gradients + optax bridge (parallel/sparse_optax.py).

Reference behavior being matched: the registered lookup gradient returns
IndexedSlices even on one device (embedding_lookup_ops.py:105-122), so any
optimizer updates only touched rows. Tests:

* gradient parity: sparse (unique_ids, unique_grad) scattered dense equals
  plain autodiff through embedding_lookup, for dense/ragged/sparse inputs,
  all combiners, shared tables;
* trajectory parity vs dense optax when every row is touched (sgd/adagrad/
  momentum/adam numerics);
* trajectory parity vs the hybrid trainer path (the same lazy semantics);
* O(touched-rows) memory: a jitted train step over a table whose dense
  gradient would dominate memory compiles with temporaries a small
  fraction of the table size.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_embeddings_tpu.ops import Ragged, SparseIds, embedding_lookup
from distributed_embeddings_tpu.parallel import (
    DistributedEmbedding, SparseAdagrad, SparseRows, SparseSGD,
    apply_sparse_updates, init_hybrid_state, make_hybrid_train_step,
    sparse_rows_adagrad, sparse_rows_adam, sparse_rows_momentum,
    sparse_rows_sgd, sparse_value_and_grad, unique_ids_static)


def _scatter_dense(sg: SparseRows) -> np.ndarray:
    out = np.zeros((sg.vocab, sg.rows.shape[1]), np.float32)
    ids = np.asarray(sg.ids)
    rows = np.asarray(sg.rows, np.float32)
    for k in range(len(ids)):
        if ids[k] < sg.vocab:
            out[ids[k]] += rows[k]
    return out


def test_unique_ids_static_roundtrip():
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, 13, size=57), jnp.int32)
    uids, inv = unique_ids_static(ids, 13)
    assert uids.shape[0] == min(57, 14)
    np.testing.assert_array_equal(np.asarray(uids)[np.asarray(inv)],
                                  np.asarray(ids))
    u = np.asarray(uids)
    valid = u[u < 13]
    np.testing.assert_array_equal(valid, np.unique(np.asarray(ids)))


@pytest.mark.parametrize("combiner", [None, "sum", "mean"])
def test_grad_parity_dense_inputs(combiner):
    rng = np.random.default_rng(1)
    vocab, w, b = 40, 8, 16
    table = jnp.asarray(rng.normal(size=(vocab, w)), jnp.float32)
    shape = (b,) if combiner is None else (b, 3)
    ids = jnp.asarray(rng.integers(0, vocab, size=shape), jnp.int32)
    tgt = jnp.asarray(rng.normal(size=(b, w)), jnp.float32)

    def loss_fn(dp, outs, t):
        return jnp.mean((outs[0] * dp["s"] - t) ** 2)

    dp = {"s": jnp.float32(1.3)}
    f = sparse_value_and_grad(loss_fn, combiners=[combiner])
    loss, (dgrads, sgrads) = f(dp, [table], [ids], tgt)

    def ref(dpp, tbl):
        return loss_fn(dpp, [embedding_lookup(tbl, ids, combiner=combiner)],
                       tgt)

    rloss, (rdg, rtg) = jax.value_and_grad(ref, argnums=(0, 1))(dp, table)
    np.testing.assert_allclose(float(loss), float(rloss), rtol=1e-6)
    np.testing.assert_allclose(float(dgrads["s"]), float(rdg["s"]),
                               rtol=1e-5)
    np.testing.assert_allclose(_scatter_dense(sgrads[0]), np.asarray(rtg),
                               rtol=1e-5, atol=1e-6)


def test_grad_parity_ragged_sparse_and_shared_table():
    rng = np.random.default_rng(2)
    vocab, w, b = 30, 4, 8
    table = jnp.asarray(rng.normal(size=(vocab, w)), jnp.float32)
    ragged = Ragged.from_lists(
        [list(rng.integers(0, vocab, size=rng.integers(1, 5)))
         for _ in range(b)], capacity=40)
    rows = np.sort(rng.integers(0, b, size=12))
    coo = SparseIds(
        indices=jnp.asarray(np.stack([rows, np.arange(12) % 3], 1),
                            jnp.int32),
        values=jnp.asarray(rng.integers(0, vocab, size=12), jnp.int32),
        dense_shape=(b, 3))
    tgt = jnp.asarray(rng.normal(size=(b, w)), jnp.float32)

    def loss_fn(dp, outs, t):
        del dp
        return jnp.mean((outs[0] + 2.0 * outs[1] - t) ** 2)

    # two inputs SHARING one table: joint dedup, one SparseRows out
    f = sparse_value_and_grad(loss_fn, combiners=["mean"],
                              input_table_map=[0, 0])
    loss, (_, sgrads) = f({}, [table], [ragged, coo], tgt)
    assert len(sgrads) == 1

    def ref(tbl):
        return loss_fn({}, [embedding_lookup(tbl, ragged, combiner="mean"),
                            embedding_lookup(tbl, coo, combiner="mean")],
                       tgt)

    rloss, rtg = jax.value_and_grad(ref)(table)
    np.testing.assert_allclose(float(loss), float(rloss), rtol=1e-6)
    np.testing.assert_allclose(_scatter_dense(sgrads[0]), np.asarray(rtg),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("kind", ["sgd", "adagrad", "momentum", "adam"])
def test_trajectory_matches_dense_optax_when_all_rows_touched(kind):
    """With every row touched every step, lazy == dense semantics and the
    sparse transforms must reproduce optax trajectories exactly."""
    rng = np.random.default_rng(3)
    vocab, w, b = 6, 4, 24  # b >> vocab: all rows touched w.h.p.
    table0 = jnp.asarray(rng.normal(size=(vocab, w)), jnp.float32)
    sched = lambda step: 0.1 / (1.0 + 0.1 * step)
    tx, ref_tx = {
        "sgd": (sparse_rows_sgd(sched), optax.sgd(sched)),
        "adagrad": (sparse_rows_adagrad(sched),
                    optax.adagrad(sched, initial_accumulator_value=0.1,
                                  eps=1e-7)),
        "momentum": (sparse_rows_momentum(sched, momentum=0.8),
                     optax.sgd(sched, momentum=0.8)),
        "adam": (sparse_rows_adam(sched), optax.adam(sched)),
    }[kind]

    def loss_fn(dp, outs, t):
        del dp
        return jnp.mean((outs[0] - t) ** 2)

    f = sparse_value_and_grad(loss_fn, combiners=["sum"])

    table = table0
    state = tx.init([table])
    rtable = table0
    rstate = ref_tx.init(rtable)
    for step in range(5):
        ids = jnp.asarray(
            np.concatenate([np.arange(vocab),
                            rng.integers(0, vocab, size=b - vocab)]
                           ).reshape(b // 2, 2), jnp.int32)
        tgt = jnp.asarray(rng.normal(size=(b // 2, w)), jnp.float32)
        _, (_, sgrads) = f({}, [table], [ids], tgt)
        upd, state = tx.update(sgrads, state, [table])
        [table] = apply_sparse_updates([table], upd)

        def ref(tbl):
            return loss_fn({}, [embedding_lookup(tbl, ids, combiner="sum")],
                           tgt)

        rg = jax.grad(ref)(rtable)
        rupd, rstate = ref_tx.update(rg, rstate, rtable)
        rtable = optax.apply_updates(rtable, rupd)
        np.testing.assert_allclose(np.asarray(table), np.asarray(rtable),
                                   rtol=2e-5, atol=1e-6,
                                   err_msg=f"{kind} step {step}")


@pytest.mark.parametrize("kind", ["sgd", "adagrad"])
def test_trajectory_matches_hybrid_path(kind):
    """The optax route and the hybrid trainer route implement the SAME
    sparse semantics: identical configs + data must give identical tables
    (VERDICT r4 #4 parity criterion)."""
    rng = np.random.default_rng(4)
    configs = [{"input_dim": 25 + 3 * i, "output_dim": 8, "combiner": "sum"}
               for i in range(4)]
    lr = 0.2
    de = DistributedEmbedding(configs, world_size=1)
    emb_opt = {"sgd": SparseSGD(), "adagrad": SparseAdagrad()}[kind]
    # dense side: a fixed linear readout, plain SGD both routes
    cols = sum(c["output_dim"] for c in configs)
    dp0 = {"w": jnp.asarray(rng.normal(size=(cols, 1)) * 0.3, jnp.float32)}
    dtx = optax.sgd(lr)

    def loss_fn(dp, outs, y):
        x = jnp.concatenate([o.reshape(o.shape[0], -1) for o in outs], 1)
        return jnp.mean((x @ dp["w"] - y) ** 2)

    # --- hybrid route
    state = init_hybrid_state(de, emb_opt, jax.tree.map(jnp.copy, dp0), dtx,
                              jax.random.key(7))
    step_fn = make_hybrid_train_step(de, loss_fn, dtx, emb_opt,
                                     lr_schedule=lr)
    # --- optax route, seeded with the SAME initial tables
    tables = [jnp.asarray(t) for t in de.get_weights(state.emb_params)]
    tx = {"sgd": sparse_rows_sgd(lr), "adagrad": sparse_rows_adagrad(lr)}[
        kind]
    est = tx.init(tables)
    dp = jax.tree.map(jnp.copy, dp0)
    dst = dtx.init(dp)
    f = sparse_value_and_grad(loss_fn,
                              combiners=[c["combiner"] for c in configs])

    b = 16
    for _ in range(4):
        cats = [jnp.asarray(rng.integers(0, c["input_dim"], size=(b, 2)),
                            jnp.int32) for c in configs]
        y = jnp.asarray(rng.normal(size=(b, 1)), jnp.float32)
        _, state = step_fn(state, cats, y)
        _, (dg, sg) = f(dp, tables, cats, y)
        du, dst = dtx.update(dg, dst, dp)
        dp = optax.apply_updates(dp, du)
        su, est = tx.update(sg, est, tables)
        tables = apply_sparse_updates(tables, su)

    hyb = de.get_weights(state.emb_params)
    for t, (a, b_) in enumerate(zip(hyb, tables)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-5, atol=1e-6,
                                   err_msg=f"table {t}")
    np.testing.assert_allclose(np.asarray(state.dense_params["w"]),
                               np.asarray(dp["w"]), rtol=1e-5, atol=1e-6)


def test_tuple_structured_params_tree():
    """Structural tuples in the params tree must NOT be confused with the
    transforms' internal per-leaf result packing (a tuple-as-leaf unpack
    once returned optimizer state as the update)."""
    w = jnp.ones((3, 2), jnp.float32)
    b = jnp.ones((2,), jnp.float32)
    params = {"dense": (w, b)}
    grads = {"dense": (jnp.full_like(w, 0.5), jnp.full_like(b, 0.5))}
    for tx, ref_tx in [
            (sparse_rows_adagrad(0.1),
             optax.adagrad(0.1, initial_accumulator_value=0.1, eps=1e-7)),
            (sparse_rows_momentum(0.1, momentum=0.9),
             optax.sgd(0.1, momentum=0.9)),
            (sparse_rows_adam(0.1), optax.adam(0.1))]:
        st = tx.init(params)
        upd, _ = tx.update(grads, st, params)
        rst = ref_tx.init(params)
        rupd, _ = ref_tx.update(grads, rst, params)
        jax.tree.map(
            lambda a, b_: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b_), rtol=1e-6, atol=1e-7),
            upd, rupd)


def test_out_of_range_ids_train_nothing_and_stay_sorted():
    """Ids >= vocab must not break the sorted-uids invariant and must not
    touch any row (forward reads the clipped last row, like the op
    layer)."""
    vocab, w = 10, 4
    table = jnp.asarray(np.arange(vocab * w).reshape(vocab, w), jnp.float32)
    ids = jnp.asarray([[3, vocab + 7], [vocab + 200, 3]], jnp.int32)

    def loss_fn(dp, outs, t):
        del dp
        return jnp.sum(outs[0] * t)

    f = sparse_value_and_grad(loss_fn, combiners=["sum"])
    tgt = jnp.ones((2, w), jnp.float32)
    loss, (_, sgrads) = f({}, [table], [ids], tgt)
    u = np.asarray(sgrads[0].ids)
    assert (np.diff(u) >= 0).all(), u  # ascending incl. sentinel tail
    # forward parity with the direct op-layer lookup (clip semantics)
    direct = embedding_lookup(table, ids, combiner="sum")
    np.testing.assert_allclose(
        float(loss), float(jnp.sum(direct * tgt)), rtol=1e-6)
    # applying the gradient changes only row 3 and (clip target) row 9
    # must NOT be trained by the bad ids: grads for ids >= vocab drop
    tx = sparse_rows_sgd(1.0)
    st = tx.init([table])
    upd, _ = tx.update(sgrads, st, [table])
    [newt] = apply_sparse_updates([table], upd)
    changed = np.where(
        np.any(np.asarray(newt) != np.asarray(table), axis=1))[0]
    np.testing.assert_array_equal(changed, [3])


def test_step_memory_is_touched_rows_not_vocab():
    """A big-table step's temporaries must be O(touched rows): a dense
    gradient would add >= one table-size (16 MB here) of transients."""
    vocab, w, b = 1_000_000, 4, 512
    table = jnp.zeros((vocab, w), jnp.float32)
    tx = sparse_rows_adagrad(0.1)

    def loss_fn(dp, outs, y):
        del dp
        return jnp.mean((outs[0] - y) ** 2)

    f = sparse_value_and_grad(loss_fn, combiners=["sum"])

    def step(tbl, est, ids, y):
        _, (_, sg) = f({}, [tbl], [ids], y)
        upd, est = tx.update(sg, est, [tbl])
        [tbl] = apply_sparse_updates([tbl], upd)
        return tbl, est

    ids = jnp.zeros((b, 2), jnp.int32)
    y = jnp.zeros((b, w), jnp.float32)
    est = tx.init([table])
    compiled = (jax.jit(step, donate_argnums=(0, 1))
                .lower(table, est, ids, y).compile())
    mem = compiled.memory_analysis()
    table_bytes = vocab * w * 4
    # params + acc live in/out (donated); temporaries must stay far below
    # one dense gradient
    assert mem.temp_size_in_bytes < table_bytes // 4, (
        mem.temp_size_in_bytes, table_bytes)


def test_weighted_ragged_and_sparse_parity():
    """Per-id weights must survive the unique-rows remap: forward AND
    gradient parity with the direct weighted lookup (ADVICE r5 medium —
    ``_remap`` once dropped the ``weights`` field and weighted inputs
    silently computed an unweighted forward/gradient)."""
    rng = np.random.default_rng(11)
    vocab, w, b = 30, 4, 8
    table = jnp.asarray(rng.normal(size=(vocab, w)), jnp.float32)
    rows = [list(rng.integers(0, vocab, size=rng.integers(1, 5)))
            for _ in range(b)]
    wts = [[float(x) for x in rng.uniform(0.5, 2.0, size=len(r))]
           for r in rows]
    ragged = Ragged.from_lists(rows, capacity=40, weights=wts)
    nnz = 12
    srows = np.sort(rng.integers(0, b, size=nnz))
    coo = SparseIds(
        indices=jnp.asarray(np.stack([srows, np.arange(nnz) % 3], 1),
                            jnp.int32),
        values=jnp.asarray(rng.integers(0, vocab, size=nnz), jnp.int32),
        dense_shape=(b, 3),
        weights=jnp.asarray(rng.uniform(0.5, 2.0, size=nnz), jnp.float32))
    tgt = jnp.asarray(rng.normal(size=(b, w)), jnp.float32)

    for combiner in ["sum", "mean"]:
        for inp in [ragged, coo]:
            def loss_fn(dp, outs, t):
                del dp
                return jnp.mean((outs[0] - t) ** 2)

            f = sparse_value_and_grad(loss_fn, combiners=[combiner])
            loss, (_, sgrads) = f({}, [table], [inp], tgt)

            def ref(tbl):
                return loss_fn(
                    {}, [embedding_lookup(tbl, inp, combiner=combiner)],
                    tgt)

            rloss, rtg = jax.value_and_grad(ref)(table)
            np.testing.assert_allclose(float(loss), float(rloss), rtol=1e-6,
                                       err_msg=f"{combiner}/{type(inp)}")
            np.testing.assert_allclose(
                _scatter_dense(sgrads[0]), np.asarray(rtg),
                rtol=1e-5, atol=1e-6, err_msg=f"{combiner}/{type(inp)}")


def test_negative_ids_train_row_zero_not_tail():
    """Negative ids clamp to 0 on BOTH sides (ADVICE r5 low): the forward
    reads row 0 (op-layer clip) and the update trains row 0 — never a
    tail row via JAX's negative-index scatter normalization."""
    vocab, w = 10, 4
    table = jnp.asarray(np.arange(vocab * w).reshape(vocab, w), jnp.float32)
    ids = jnp.asarray([[3, -1], [-7, 3]], jnp.int32)

    def loss_fn(dp, outs, t):
        del dp
        return jnp.sum(outs[0] * t)

    f = sparse_value_and_grad(loss_fn, combiners=["sum"])
    tgt = jnp.ones((2, w), jnp.float32)
    loss, (_, sgrads) = f({}, [table], [ids], tgt)
    u = np.asarray(sgrads[0].ids)
    assert (u >= 0).all(), u  # no negative id may reach the scatters
    assert (np.diff(u) >= 0).all(), u
    # forward parity with the direct op-layer lookup (clip-to-0 read)
    direct = embedding_lookup(table, ids, combiner="sum")
    np.testing.assert_allclose(
        float(loss), float(jnp.sum(direct * tgt)), rtol=1e-6)
    tx = sparse_rows_sgd(1.0)
    st = tx.init([table])
    upd, _ = tx.update(sgrads, st, [table])
    [newt] = apply_sparse_updates([table], upd)
    changed = np.where(
        np.any(np.asarray(newt) != np.asarray(table), axis=1))[0]
    # rows 0 (the clamped negatives) and 3 train; the tail must not
    np.testing.assert_array_equal(changed, [0, 3])


# ------------------------------------------- the dedup-skip (SGD) pass cut


def test_dedup_false_forward_and_grads_match():
    """sparse_value_and_grad(dedup=False) skips the unique_ids_static
    sort pass: the forward loss is BITWISE the dedup=True value (a gather
    of a gather of the same clamped ids) and the scattered-dense gradient
    matches; the rows come back unique=False carrying the raw clamped
    stream."""
    rng = np.random.default_rng(11)
    vocab, w, b = 12, 8, 16  # small vocab => guaranteed duplicates
    table = jnp.asarray(rng.normal(size=(vocab, w)), jnp.float32)
    ids = jnp.asarray(rng.integers(0, vocab, size=(b, 3)), jnp.int32)
    tgt = jnp.asarray(rng.normal(size=(b, w)), jnp.float32)

    def loss_fn(dp, outs, t):
        return jnp.mean((outs[0] * dp["s"] - t) ** 2)

    dp = {"s": jnp.float32(1.3)}
    f_dd = sparse_value_and_grad(loss_fn, combiners=["sum"], dedup=True)
    f_nd = sparse_value_and_grad(loss_fn, combiners=["sum"], dedup=False)
    loss_dd, (_, sg_dd) = f_dd(dp, [table], [ids], tgt)
    loss_nd, (dg_nd, sg_nd) = f_nd(dp, [table], [ids], tgt)
    np.testing.assert_array_equal(np.asarray(loss_dd), np.asarray(loss_nd))
    assert sg_dd[0].unique and not sg_nd[0].unique
    assert sg_nd[0].ids.shape[0] == b * 3  # the raw stream, no unique pass
    np.testing.assert_allclose(_scatter_dense(sg_nd[0]),
                               _scatter_dense(sg_dd[0]),
                               rtol=1e-5, atol=1e-6)
    # the linear transform + apply path accepts non-unique rows
    tx = sparse_rows_sgd(0.5)
    upd, _ = tx.update(sg_nd, tx.init([table]), [table])
    assert not upd[0].unique  # the flag must survive the transform
    [t_nd] = apply_sparse_updates([table], upd)
    upd_dd, _ = tx.update(sg_dd, tx.init([table]), [table])
    [t_dd] = apply_sparse_updates([table], upd_dd)
    np.testing.assert_allclose(np.asarray(t_nd), np.asarray(t_dd),
                               rtol=1e-5, atol=1e-6)


def test_dedup_false_stateful_transforms_refuse():
    """Stateful (read-modify-write) transforms must reject unique=False
    rows at trace time instead of silently reading stale state for the
    second occurrence of a duplicated id."""
    table = jnp.zeros((8, 4), jnp.float32)
    rows = SparseRows(ids=jnp.asarray([3, 3, 1], jnp.int32),
                      rows=jnp.ones((3, 4), jnp.float32), vocab=8,
                      unique=False)
    for name, tx in [("adagrad", sparse_rows_adagrad(0.1)),
                     ("momentum", sparse_rows_momentum(0.1)),
                     ("adam", sparse_rows_adam(0.1))]:
        with pytest.raises(ValueError, match="requires unique"):
            tx.update([rows], tx.init([table]), [table])


def test_dedup_false_env_hatch_forces_dedup_back(monkeypatch):
    """DETPU_SGD_DEDUP=1 (the A/B escape hatch) overrides dedup=False at
    build time: the returned rows are sorted-unique again."""
    monkeypatch.setenv("DETPU_SGD_DEDUP", "1")
    table = jnp.asarray(np.arange(40.0).reshape(10, 4), jnp.float32)
    ids = jnp.asarray([3, 3, 7], jnp.int32)

    def loss_fn(dp, outs, *a):
        del dp, a
        return jnp.sum(outs[0])

    f = sparse_value_and_grad(loss_fn, combiners=[None], dedup=False)
    _, (_, sg) = f({}, [table], [ids])
    assert sg[0].unique
    u = np.asarray(sg[0].ids)
    assert (np.diff(u) >= 0).all()
    monkeypatch.delenv("DETPU_SGD_DEDUP")
    f2 = sparse_value_and_grad(loss_fn, combiners=[None], dedup=False)
    _, (_, sg2) = f2({}, [table], [ids])
    assert not sg2[0].unique


# ------------------------------------------------ ROADMAP 1 diagnostic:
# SparseSGD vs an equivalent dense one-hot-matmul SGD model


@pytest.mark.parametrize("combiner,hot", [(None, 1), ("mean", 3),
                                          ("sum", 3)])
def test_hybrid_sparse_sgd_matches_dense_onehot_sgd(combiner, hot):
    """The lr-coupling half of the ROADMAP 1 question, isolated.

    The planted-signal task's embedding half does not learn under
    SparseSGD (VERDICT "Missing #2"); the cross-world test (PR 6) ruled
    out a 1/world mp grad-scale defect. The two remaining suspects were
    (a) an lr coupling hiding in the sparse pipeline — SparseSGD's
    effective step differing from plain SGD at the same lr — and
    (b) init scale / task conditioning. This test settles (a): the FULL
    hybrid path (packed slab layout, lane-packed gather/scatter, plan
    executor, sparse backward) must produce, step for step, the same
    trajectory as a dense model in which the lookup is written as
    ``one_hot(ids) @ table`` and BOTH halves are trained by plain
    ``optax.sgd`` at the same lr — duplicates in the batch included
    (they scatter-add on one side and accumulate through the matmul
    transpose on the other).

    Verdict (recorded in ROADMAP item 1): this test passes — the sparse
    path IS plain SGD, at exactly the declared lr, for sum/mean/no
    combiner. The remaining suspect for the planted-task failure is
    init scale / task conditioning, not the optimizer.
    """
    rng = np.random.default_rng(7)
    vocab, w, b, lr, steps = 12, 4, 16, 0.5, 8
    shape = (b,) if combiner is None else (b, hot)
    id_steps = [jnp.asarray(rng.integers(0, vocab, size=shape), jnp.int32)
                for _ in range(steps)]
    tgt_steps = [jnp.asarray(rng.normal(size=(b, 1)), jnp.float32)
                 for _ in range(steps)]

    # --- hybrid path: SparseSGD through make_hybrid_train_step, world 1
    de = DistributedEmbedding(
        [{"input_dim": vocab, "output_dim": w, "combiner": combiner}],
        world_size=1)
    emb_opt = SparseSGD()
    tx = optax.sgd(lr)
    # host-side init shared by both models: the hybrid step DONATES its
    # state, so each side must get its own device buffer
    proj0 = rng.normal(size=(w, 1)).astype(np.float32)

    def loss_fn(dp, outs, batch):
        o = outs[0]
        if combiner is None and o.ndim == 3:  # [b, 1, w] rank-preserved
            o = o.reshape(o.shape[0], -1)
        return jnp.mean((o @ dp["proj"] - batch) ** 2)

    state = init_hybrid_state(de, emb_opt, {"proj": jnp.asarray(proj0)},
                              tx, jax.random.key(3))
    step = make_hybrid_train_step(de, loss_fn, tx, emb_opt,
                                  lr_schedule=lr, nan_guard=False,
                                  with_metrics=False)

    # --- dense twin: identical init, lookup as one_hot @ table, plain
    # optax.sgd over BOTH the table and the projection
    table0 = np.asarray(de.get_weights(state.emb_params)[0])
    dense_params = {"table": jnp.asarray(table0),
                    "proj": jnp.asarray(proj0)}
    dtx = optax.sgd(lr)
    dopt = dtx.init(dense_params)

    def dense_loss(p, ids, y):
        oh = jax.nn.one_hot(ids, vocab, dtype=jnp.float32)
        gathered = oh @ p["table"]            # [b(, hot), w]
        if combiner == "mean":
            gathered = gathered.mean(axis=1)
        elif combiner == "sum":
            gathered = gathered.sum(axis=1)
        return jnp.mean((gathered @ p["proj"] - y) ** 2)

    @jax.jit
    def dense_step(p, o, ids, y):
        loss, g = jax.value_and_grad(dense_loss)(p, ids, y)
        upd, o = dtx.update(g, o, p)
        return loss, optax.apply_updates(p, upd), o

    for k in range(steps):
        loss_h, state = step(state, [id_steps[k]], tgt_steps[k])
        loss_d, dense_params, dopt = dense_step(dense_params, dopt,
                                                id_steps[k], tgt_steps[k])
        np.testing.assert_allclose(float(loss_h), float(loss_d),
                                   rtol=1e-5,
                                   err_msg=f"loss diverged at step {k}")
        [table_h] = de.get_weights(state.emb_params)
        np.testing.assert_allclose(
            np.asarray(table_h), np.asarray(dense_params["table"]),
            rtol=1e-4, atol=1e-6,
            err_msg=f"table trajectory diverged at step {k} — an lr "
                    "coupling in the sparse path")
        np.testing.assert_allclose(
            np.asarray(state.dense_params["proj"]),
            np.asarray(dense_params["proj"]), rtol=1e-4, atol=1e-6)
    # the run must have actually trained the table (a frozen embedding
    # half matching a frozen twin would vacuously pass)
    assert float(np.abs(table0 - np.asarray(table_h)).max()) > 1e-3

"""DLRM example script smoke: mid-training eval cadence + AUC early stop
(VERDICT r3 Missing #3) driven end-to-end through ``examples/dlrm/main.py``
on an 8-virtual-device CPU mesh (via the script's DETPU_FORCE_CPU_DEVICES
test hook)."""

import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SCRIPT = os.path.join(_REPO, "examples", "dlrm", "main.py")


def _run(tmp_path, extra):
    env = dict(os.environ)
    env["DETPU_FORCE_CPU_DEVICES"] = "8"
    env["PYTHONPATH"] = os.pathsep.join(
        [_REPO] + env.get("PYTHONPATH", "").split(os.pathsep))
    cmd = [
        sys.executable, _SCRIPT,
        "--batch_size", "64",
        "--table_sizes", ",".join(["50"] * 10),
        "--embedding_dim", "8",
        "--bottom_mlp_dims", "16,8",
        "--top_mlp_dims", "16,1",
        "--num_numerical_features", "4",
        "--learning_rate", "0.1",
        "--checkpoint_out", str(tmp_path / "ckpt"),
    ] + extra
    proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=600)
    assert proc.returncode == 0, proc.stderr[-3000:]
    return proc.stdout


def test_eval_interval_and_early_stop(tmp_path):
    out = _run(tmp_path, [
        "--num_batches", "8",
        "--eval_interval", "3",
        "--eval_batches", "2",
        "--auc_threshold", "0.0",  # any AUC satisfies: must stop at step 3
    ])
    assert "eval step: 3 AUC:" in out, out
    assert "threshold 0.0 reached at step 3" in out, out
    # early stop means the end-of-training eval must NOT run
    assert "Evaluation completed" not in out, out


@pytest.mark.slow
def test_final_eval_and_checkpoint(tmp_path):
    out = _run(tmp_path, [
        "--num_batches", "4",
        "--eval_interval", "0",
        "--eval_batches", "2",
    ])
    assert "Evaluation completed, AUC:" in out, out
    assert "saved 10 tables" in out, out


def _write_dataset(root, n, sizes, numf):
    """Tiny Criteo raw-binary dataset (reader layout, utils/data.py)."""
    import json

    import numpy as np

    rng = np.random.default_rng(0)
    for split, rows in (("train", n), ("test", n // 2)):
        d = root / split
        d.mkdir(parents=True, exist_ok=True)
        (rng.random(rows) < 0.3).astype(np.bool_).tofile(d / "label.bin")
        rng.normal(size=(rows, numf)).astype(np.float16).tofile(
            d / "numerical.bin")
        from distributed_embeddings_tpu.utils.data import (
            get_categorical_feature_type)
        for i, s in enumerate(sizes):
            rng.integers(0, s, size=rows).astype(
                get_categorical_feature_type(s)).tofile(d / f"cat_{i}.bin")
    (root / "model_size.json").write_text(
        json.dumps({f"c{i}": s - 1 for i, s in enumerate(sizes)}))


@pytest.mark.slow
def test_save_restore_resumes_data_stream(tmp_path):
    """--restore_state continues the dataset at the checkpointed step with
    globally numbered steps; resuming a COMPLETED run trains nothing
    extra (ADVICE r4 / r5 review findings)."""
    sizes = [50] * 10
    _write_dataset(tmp_path / "ds", 64 * 6, sizes, 4)
    common = ["--dataset_path", str(tmp_path / "ds"),
              "--eval_batches", "0", "--eval_interval", "0"]
    out1 = _run(tmp_path, common + [
        "--save_state", str(tmp_path / "state")])
    assert "saved full train state" in out1, out1
    out2 = _run(tmp_path, common + [
        "--restore_state", str(tmp_path / "state"),
        "--save_state", str(tmp_path / "state2")])
    assert "restored train state at step 6" in out2, out2
    # the 6-batch epoch was finished: the resumed run must yield NO new
    # training steps (an empty stream, not a silent extra epoch) — the
    # loop's per-step loss line never fires on an empty stream
    assert " loss:" not in out2, out2
    # and the re-saved state's step counter must still be 6
    from flax import serialization
    import numpy as np
    blob = (tmp_path / "state2" / "dense.msgpack").read_bytes()
    assert int(np.asarray(
        serialization.msgpack_restore(blob)["step"])) == 6

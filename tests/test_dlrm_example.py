"""DLRM example script smoke: mid-training eval cadence + AUC early stop
(VERDICT r3 Missing #3) driven end-to-end through ``examples/dlrm/main.py``
on an 8-virtual-device CPU mesh (via the script's DETPU_FORCE_CPU_DEVICES
test hook)."""

import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SCRIPT = os.path.join(_REPO, "examples", "dlrm", "main.py")


def _run(tmp_path, extra):
    env = dict(os.environ)
    env["DETPU_FORCE_CPU_DEVICES"] = "8"
    env["PYTHONPATH"] = os.pathsep.join(
        [_REPO] + env.get("PYTHONPATH", "").split(os.pathsep))
    cmd = [
        sys.executable, _SCRIPT,
        "--batch_size", "64",
        "--table_sizes", ",".join(["50"] * 10),
        "--embedding_dim", "8",
        "--bottom_mlp_dims", "16,8",
        "--top_mlp_dims", "16,1",
        "--num_numerical_features", "4",
        "--learning_rate", "0.1",
        "--checkpoint_out", str(tmp_path / "ckpt"),
    ] + extra
    proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=600)
    assert proc.returncode == 0, proc.stderr[-3000:]
    return proc.stdout


def test_eval_interval_and_early_stop(tmp_path):
    out = _run(tmp_path, [
        "--num_batches", "8",
        "--eval_interval", "3",
        "--eval_batches", "2",
        "--auc_threshold", "0.0",  # any AUC satisfies: must stop at step 3
    ])
    assert "eval step: 3 AUC:" in out, out
    assert "threshold 0.0 reached at step 3" in out, out
    # early stop means the end-of-training eval must NOT run
    assert "Evaluation completed" not in out, out


@pytest.mark.slow
def test_final_eval_and_checkpoint(tmp_path):
    out = _run(tmp_path, [
        "--num_batches", "4",
        "--eval_interval", "0",
        "--eval_batches", "2",
    ])
    assert "Evaluation completed, AUC:" in out, out
    assert "saved 10 tables" in out, out

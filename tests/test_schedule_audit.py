"""Schedule-graph auditor: HLO DAG parsing, cost/critical-path model,
serialized/overlappable classification, contracts, and the StepSchedule
declaration check.

Two layers:

* handwritten-HLO units for the operand extraction the PR 7 census
  never needed — through fusions (``calls=``), tuple-shaped operands,
  while-lowered scatters (``body=``/``condition=``), and the
  post-layout TPU shape spellings (``{1,0:T(8,128)}`` — the PR 7
  regression class) — plus cycle-detection and root-finding sanity;
* the real compiled hybrid step on the 8-virtual-device CPU mesh: the
  id / out / grad all-to-alls report as SERIALIZED on the critical path
  (the documented baseline), and a seeded overlap-declaring
  StepSchedule against the serialized program fails.
"""

import json

import numpy as np
import optax
import pytest

from distributed_embeddings_tpu.analysis import schedule_audit as sa
from distributed_embeddings_tpu.parallel import SparseAdagrad
from distributed_embeddings_tpu.parallel.schedule import (
    PHASE_APPLY, PHASE_DENSE, PHASE_GRAD_EXCHANGE, PHASE_ID_EXCHANGE,
    PHASE_LOOKUP, PHASE_OUT_EXCHANGE, PhaseDecl, ScheduleError,
    StepSchedule, default_schedule)

# --------------------------------------------------- handwritten modules

HLO_FUSION_TUPLE = """\
HloModule test, entry_computation_layout={(f32[16,8])->f32[16,8]}

%fused_computation (param_0: f32[16,8]) -> f32[16,8] {
  %param_0 = f32[16,8]{1,0} parameter(0)
  ROOT %neg = f32[16,8]{1,0} negate(f32[16,8]{1,0} %param_0), metadata={op_name="jit(f)/detpu/lookup_w8_d/neg"}
}

ENTRY %main (p0: f32[16,8], p1: s32[4]) -> f32[16,8] {
  %p0 = f32[16,8]{1,0:T(8,128)} parameter(0)
  %p1 = s32[4]{0} parameter(1)
  %fusion = f32[16,8]{1,0} fusion(f32[16,8]{1,0:T(8,128)} %p0), kind=kLoop, calls=%fused_computation
  %tup = (f32[16,8]{1,0}, s32[4]{0}) tuple(f32[16,8]{1,0} %fusion, s32[4]{0} %p1)
  %gte = f32[16,8]{1,0} get-tuple-element((f32[16,8]{1,0}, s32[4]{0}) %tup), index=0
  ROOT %add = f32[16,8]{1,0} add(f32[16,8]{1,0} %gte, f32[16,8]{1,0} %fusion), metadata={op_name="jit(f)/detpu/lookup_w8_d/add"}
}
"""

HLO_WHILE_SCATTER = """\
HloModule scat

%wbody (p: (s32[], f32[8,4])) -> (s32[], f32[8,4]) {
  %p = (s32[], f32[8,4]{1,0}) parameter(0), metadata={op_name="jit(f)/detpu/sparse_apply/detpu/sparse_apply_w4/scatter-add"}
  %i = s32[] get-tuple-element((s32[], f32[8,4]{1,0}) %p), index=0
  %buf = f32[8,4]{1,0} get-tuple-element((s32[], f32[8,4]{1,0}) %p), index=1
  ROOT %out = (s32[], f32[8,4]{1,0}) tuple(s32[] %i, f32[8,4]{1,0} %buf)
}

%wcond (p: (s32[], f32[8,4])) -> pred[] {
  %p = (s32[], f32[8,4]{1,0}) parameter(0)
  %i2 = s32[] get-tuple-element((s32[], f32[8,4]{1,0}) %p), index=0
  %n = s32[] constant(8)
  ROOT %lt = pred[] compare(s32[] %i2, s32[] %n), direction=LT
}

ENTRY %main (a: f32[8,4]) -> f32[8,4] {
  %a = f32[8,4]{1,0:T(8,128)S(1)} parameter(0)
  %z = s32[] constant(0)
  %init = (s32[], f32[8,4]{1,0}) tuple(s32[] %z, f32[8,4]{1,0} %a)
  %w = (s32[], f32[8,4]{1,0}) while((s32[], f32[8,4]{1,0}) %init), condition=%wcond, body=%wbody, backend_config={"known_trip_count":{"n":"8"}}
  ROOT %res = f32[8,4]{1,0} get-tuple-element((s32[], f32[8,4]{1,0}) %w), index=1
}
"""

HLO_CYCLE = """\
HloModule cyc

ENTRY %main (p: f32[4]) -> f32[4] {
  %p = f32[4]{0} parameter(0)
  %a = f32[4]{0} add(f32[4]{0} %p, f32[4]{0} %b)
  ROOT %b = f32[4]{0} add(f32[4]{0} %a, f32[4]{0} %p)
}
"""


def _overlap_module(world_payload_cols: int, with_big_compute: bool) -> str:
    """An all-reduce plus (optionally) a big INDEPENDENT multiply: the
    classification fixture."""
    big = (
        '  %big = f32[1000,100]{1,0} multiply(f32[1000,100]{1,0} %q, '
        'f32[1000,100]{1,0} %q), metadata={op_name="jit(f)/detpu/'
        'dense_forward_backward/mul"}\n')
    consume = "f32[1000,100]{1,0} %big" if with_big_compute \
        else "f32[1000,100]{1,0} %q"
    return (
        "HloModule ov\n\n"
        "ENTRY %main (p: f32[64], q: f32[1000,100]) -> "
        "(f32[64], f32[1000,100]) {\n"
        "  %p = f32[64]{0} parameter(0)\n"
        "  %q = f32[1000,100]{1,0} parameter(1)\n"
        f"  %coll = f32[{world_payload_cols}]{{0}} all-reduce("
        f"f32[{world_payload_cols}]{{0}} %p), "
        'metadata={op_name="jit(f)/detpu/id_all_to_all/all_reduce"}\n'
        + (big if with_big_compute else "")
        + f"  ROOT %t = (f32[64]{{0}}, f32[1000,100]{{1,0}}) tuple("
        f"f32[64]{{0}} %coll, {consume})\n"
        "}\n")


# ------------------------------------------------------------ parser units


def test_operand_extraction_through_fusion_and_tuples():
    comps = sa.parse_hlo_module(HLO_FUSION_TUPLE)
    entry = sa.entry_computation(comps)
    by = entry.by_name()
    assert by["fusion"].operands == ("p0",)
    assert by["fusion"].called == ("fused_computation",)
    # tuple-shaped operand: the gte consumes the 2-element tuple
    assert by["gte"].operands == ("tup",)
    assert by["tup"].operands == ("fusion", "p1")
    # two operands, one repeated name each resolves
    assert by["add"].operands == ("gte", "fusion")
    assert by["add"].is_root
    # the non-entry computation parsed too (phase resolution reads it)
    assert "fused_computation" in comps
    # post-layout TPU tile spelling did not break shape/operand parsing
    assert "T(8,128)" in by["p0"].shape


def test_fusion_phase_falls_back_to_called_computation():
    comps = sa.parse_hlo_module(HLO_FUSION_TUPLE)
    entry = sa.entry_computation(comps)
    fusion = entry.by_name()["fusion"]
    assert fusion.phase == ""  # no op_name on the fusion instruction
    assert sa._resolve_phase(fusion, comps) == "lookup_w8_d"


def test_while_lowered_scatter_parses_and_attributes():
    comps = sa.parse_hlo_module(HLO_WHILE_SCATTER)
    entry = sa.entry_computation(comps)
    by = entry.by_name()
    w = by["w"]
    assert w.op == "while"
    assert w.operands == ("init",)
    assert set(w.called) == {"wcond", "wbody"}
    # no op_name on the while itself: phase resolves from the BODY's
    # scatter-add scope (majority vote over called computations)
    assert sa._resolve_phase(w, comps) == "sparse_apply/sparse_apply_w4"
    # S(1) memory-space spelling parsed
    assert "S(1)" in by["a"].shape
    g = sa.ScheduleGraph(comps, world=1)
    # while consumes init which consumes the param: a real chain
    order = g.topo_order()
    idx = {g.nodes[i].instr.name: order.index(i)
           for i in range(len(g.nodes))}
    assert idx["init"] < idx["w"] < idx["res"]


def test_cycle_detection_raises():
    g = sa.ScheduleGraph(sa.parse_hlo_module(HLO_CYCLE), world=1)
    with pytest.raises(sa.ScheduleGraphError, match="cycle"):
        g.topo_order()


def test_root_finding_sanity():
    g = sa.ScheduleGraph(sa.parse_hlo_module(HLO_FUSION_TUPLE), world=1)
    roots = g.roots()
    names = {g.nodes[i].instr.name for i in roots}
    assert "add" in names  # the ROOT instruction is a sink
    # every non-sink feeds something
    assert all(g.succs[i] == [] for i in roots)


def test_audit_text_rejects_garbage():
    with pytest.raises(sa.ScheduleGraphError):
        sa.audit_text("not hlo at all")


# ------------------------------------------------- cost + classification


def test_collective_payload_uses_off_chip_fraction():
    g = sa.ScheduleGraph(sa.parse_hlo_module(
        _overlap_module(64, False)), world=8)
    coll = next(n for n in g.nodes if n.is_collective)
    # operand f32[64] = 256 B; off-chip 7/8 -> 224 B
    assert coll.payload_bytes == 224
    assert coll.cost_ns == pytest.approx(
        224 / sa.CHIP_SPECS["v5e"].ici_eff_gbps)
    # world=1: nothing leaves the chip
    g1 = sa.ScheduleGraph(sa.parse_hlo_module(
        _overlap_module(64, False)), world=1)
    assert next(n for n in g1.nodes if n.is_collective).payload_bytes == 0


def test_classification_overlappable_vs_serialized():
    rep = sa.audit_text(_overlap_module(64, True), world=8)
    (c,) = rep.collectives
    # the big multiply is independent of the all-reduce: overlappable
    assert c.classification == "overlappable"
    assert c.independent_compute_ns > c.cost_ns
    assert rep.serialized_collective_fraction == 0.0

    rep2 = sa.audit_text(_overlap_module(64, False), world=8)
    (c2,) = rep2.collectives
    # nothing independent (parameters are trivial): serialized
    assert c2.classification == "serialized"
    assert c2.independent_compute_ns == 0.0
    assert rep2.serialized_collective_fraction == 1.0


def test_trivial_ops_cost_nothing():
    g = sa.ScheduleGraph(sa.parse_hlo_module(HLO_FUSION_TUPLE), world=1)
    by = {n.instr.name: n for n in g.nodes}
    assert by["p0"].cost_ns == 0.0 and by["p0"].is_trivial
    assert by["tup"].cost_ns == 0.0
    assert by["add"].cost_ns > 0.0


def test_critical_path_longest_chain():
    rep = sa.audit_text(_overlap_module(64, True), world=8)
    # the heaviest chain is q -> big -> tuple, not the tiny collective
    phases = [p for p, _ in rep.critical_path_phases]
    assert any("dense_forward_backward" in p for p in phases)
    assert rep.critical_path_ns > 0
    assert rep.critical_path_bytes > 0


# ----------------------------------------------------- contracts + report


def test_contract_expect_validated():
    with pytest.raises(ValueError, match="expect"):
        sa.ScheduleContract("x", expect="maybe")


def test_contracts_fire_on_mismatch_and_absence():
    rep = sa.audit_text(_overlap_module(64, True), world=8)
    rep.check([sa.ScheduleContract("id_all_to_all",
                                   expect="serialized")])
    assert any("is overlappable, expected serialized" in v
               for v in rep.violations)
    rep2 = sa.audit_text(_overlap_module(64, True), world=8)
    rep2.check([sa.ScheduleContract("no_such_phase")])
    assert any("expected >= 1" in v for v in rep2.violations)
    rep3 = sa.audit_text(_overlap_module(64, True), world=8)
    rep3.check([sa.ScheduleContract("id_all_to_all",
                                    expect="overlappable")])
    assert rep3.ok


def test_report_json_and_markdown_roundtrip():
    rep = sa.audit_text(_overlap_module(64, True), world=8)
    d = json.loads(json.dumps(rep.to_json()))
    assert d["serialized_collective_fraction"] == 0.0
    assert d["collectives"][0]["classification"] == "overlappable"
    md = rep.markdown()
    assert "overlappable" in md and "critical path" in md
    s = rep.summary()
    assert set(s) >= {"serialized_collective_fraction",
                      "critical_path_bytes", "violations"}


# -------------------------------------------------- StepSchedule semantics


def test_default_schedule_validates_and_is_serialized():
    sched = default_schedule()
    assert [p.name for p in sched.collectives()] == [
        PHASE_ID_EXCHANGE, PHASE_OUT_EXCHANGE, PHASE_GRAD_EXCHANGE]
    assert sched.declared_overlaps() == ()
    assert sched.depends_on(PHASE_APPLY, PHASE_ID_EXCHANGE)


def test_schedule_rejects_duplicates_undeclared_cycles_and_self_overlap():
    with pytest.raises(ScheduleError, match="duplicate"):
        StepSchedule("d", (PhaseDecl("a"), PhaseDecl("a")))
    with pytest.raises(ScheduleError, match="undeclared"):
        StepSchedule("d", (PhaseDecl("a", after=("ghost",)),))
    with pytest.raises(ScheduleError, match="cycle"):
        StepSchedule("d", (PhaseDecl("a", after=("b",)),
                           PhaseDecl("b", after=("a",))))
    with pytest.raises(ScheduleError, match="overlap itself"):
        StepSchedule("d", (PhaseDecl("a", overlaps=("a",)),))
    with pytest.raises(ScheduleError, match="cannot overlap"):
        # b depends on a THROUGH c, yet claims to overlap it
        StepSchedule("d", (PhaseDecl("a"),
                           PhaseDecl("c", after=("a",)),
                           PhaseDecl("b", after=("c",),
                                     overlaps=("a",))))
    with pytest.raises(ScheduleError, match="kind"):
        PhaseDecl("a", kind="junk")


def test_schedule_declaration_check_against_compiled_graph():
    rep = sa.audit_text(_overlap_module(64, False), world=8)
    honest = StepSchedule("honest", (
        PhaseDecl("id_all_to_all", kind="collective"),))
    rep.check_against_schedule(honest)
    assert rep.ok
    lying = StepSchedule("lying", (
        PhaseDecl("id_all_to_all", kind="collective",
                  overlaps=("dense",)),
        PhaseDecl("dense", kind="compute")))
    rep.check_against_schedule(lying)
    assert any("does not exist in what XLA emitted" in v
               for v in rep.violations)
    # a declared collective phase the program no longer has
    rep2 = sa.audit_text(_overlap_module(64, False), world=8)
    rep2.check_against_schedule(StepSchedule("gone", (
        PhaseDecl("vanished_exchange", kind="collective"),)))
    assert any("matches no compiled collective" in v
               for v in rep2.violations)


# --------------------------------------------- the real compiled step


@pytest.fixture(scope="module")
def real_step_report():
    from tools._profcommon import build_case

    import jax
    from jax.sharding import Mesh

    de, cats, batch_tree, dense_params, loss_fn = build_case(
        "dense", 8, 256)
    mesh = Mesh(np.array(jax.devices()[:8]), ("data",))
    rep = sa.audit_train_step(
        de, loss_fn, optax.sgd(0.5), SparseAdagrad(), cats, batch_tree,
        mesh=mesh, lr_schedule=0.3, dense_params=dense_params,
        with_metrics=False, nan_guard=True, label="test/dense8")
    return de, rep


def test_real_step_baseline_serialized_a2a_chain(real_step_report):
    """The acceptance baseline: the unpipelined step's id / out / grad
    all-to-alls are serialized ON the critical path, and the report is
    contract-clean against the layer's own (serialized) schedule."""
    de, rep = real_step_report
    assert rep.ok, rep.violations
    a2a = {c.phase_leaf: c for c in rep.collectives
           if c.op == "all-to-all"}
    assert set(a2a) == {"id_all_to_all", "out_all_to_all",
                        "grad_all_to_all"}
    for c in a2a.values():
        assert c.classification == "serialized", c
        assert c.on_critical_path, c
        assert c.payload_bytes > 0
    assert rep.serialized_collective_fraction > 0.9
    # the schedule phases the orchestrator declares all compiled in
    assert de.schedule.phase(PHASE_ID_EXCHANGE).kind == "collective"
    path_phases = " ".join(p for p, _ in rep.critical_path_phases)
    assert "id_all_to_all" in path_phases
    assert "lookup_" in path_phases
    assert PHASE_OUT_EXCHANGE in path_phases
    assert "grad_all_to_all" in path_phases


def test_real_step_fake_overlap_schedule_fails(real_step_report):
    """The seeded drill of the acceptance criteria: a StepSchedule
    CLAIMING the id exchange overlaps dense compute, checked against
    the real serialized program, must produce violations."""
    de, rep = real_step_report
    fake = StepSchedule("fake-pipelined", (
        PhaseDecl(PHASE_ID_EXCHANGE, kind="collective",
                  overlaps=(PHASE_DENSE,)),
        PhaseDecl(PHASE_LOOKUP, kind="compute",
                  after=(PHASE_ID_EXCHANGE,)),
        PhaseDecl(PHASE_DENSE, kind="compute")))
    import dataclasses as dc
    fresh = dc.replace(rep, violations=[])
    fresh.check_against_schedule(fake)
    assert any("SERIALIZES collective" in v for v in fresh.violations)
    with pytest.raises(sa.ScheduleGraphError, match="schedule audit"):
        fresh.raise_on_violations()


def test_real_step_graph_is_acyclic_with_roots(real_step_report):
    de, rep = real_step_report
    assert rep.nodes > 50 and rep.edges > rep.nodes // 2
    # report built => topo_order succeeded (cycle-free) and roots exist
    assert rep.critical_path_ns > 0


def test_overlap_claim_verified_against_declared_partner():
    """A claim must be certified against the DECLARED partner's
    independent compute, not any independent chain: the module has a big
    independent `dense_forward_backward` phase, so claiming overlap with
    it passes — but claiming overlap with `lookup_*` (which has no
    independent compute here) must fail even though the collective's
    GLOBAL classification is overlappable."""
    rep = sa.audit_text(_overlap_module(64, True), world=8)
    (c,) = rep.collectives
    assert c.classification == "overlappable"
    assert c.independent_matching(("dense_forward_backward",)) > 0
    assert c.independent_matching(("lookup_*",)) == 0.0
    honest = StepSchedule("honest-claim", (
        PhaseDecl("id_all_to_all", kind="collective",
                  overlaps=("dense_forward_backward",)),
        PhaseDecl("dense_forward_backward", kind="compute")))
    rep.check_against_schedule(honest)
    assert rep.ok, rep.violations
    lying = StepSchedule("wrong-partner", (
        PhaseDecl("id_all_to_all", kind="collective",
                  overlaps=("lookup_*",)),
        PhaseDecl("lookup_*", kind="compute")))
    rep2 = sa.audit_text(_overlap_module(64, True), world=8)
    rep2.check_against_schedule(lying)
    assert any("SERIALIZES collective" in v for v in rep2.violations)

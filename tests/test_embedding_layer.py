"""Embedding module semantics tests, mirroring the reference's layer tests
(``distributed_embeddings/python/layers/embedding_test.py``): hand-computed
outputs for 1D/2D/3D dense × {None, sum, mean}, ragged and sparse inputs, and
weight-update equality against a plain dense-gather formulation under the same
optimizer."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_embeddings_tpu.layers import ConcatEmbedding, Embedding
from distributed_embeddings_tpu.ops import Ragged, SparseIds


def build(input_dim=6, output_dim=2, combiner=None):
    layer = Embedding(input_dim=input_dim, output_dim=output_dim,
                      combiner=combiner)
    table = np.arange(input_dim * output_dim, dtype=np.float32).reshape(
        input_dim, output_dim)
    params = {"params": {"embeddings": jnp.asarray(table)}}
    return layer, params, table


def test_1d_no_combiner():
    layer, params, table = build()
    out = layer.apply(params, jnp.array([0, 3, 5]))
    np.testing.assert_allclose(out, table[[0, 3, 5]])


def test_1d_with_combiner_raises():
    layer, params, _ = build(combiner="sum")
    with pytest.raises(ValueError):
        layer.apply(params, jnp.array([0, 1]))


@pytest.mark.parametrize("combiner,reduce_fn", [
    (None, None), ("sum", np.sum), ("mean", np.mean)])
def test_2d_dense(combiner, reduce_fn):
    layer, params, table = build(combiner=combiner)
    ids = np.array([[0, 1], [2, 3], [4, 5]])
    out = layer.apply(params, jnp.asarray(ids))
    expect = table[ids]
    if reduce_fn is not None:
        expect = reduce_fn(expect, axis=1)
    np.testing.assert_allclose(out, expect, rtol=1e-6)


@pytest.mark.parametrize("combiner,reduce_fn", [
    (None, None), ("sum", np.sum), ("mean", np.mean)])
def test_3d_dense(combiner, reduce_fn):
    layer, params, table = build(combiner=combiner)
    ids = np.array([[[0, 1], [2, 3]], [[4, 5], [0, 5]]])
    out = layer.apply(params, jnp.asarray(ids))
    expect = table[ids]
    if reduce_fn is not None:
        expect = reduce_fn(expect, axis=-2)
    np.testing.assert_allclose(out, expect, rtol=1e-6)
    assert out.shape == expect.shape


@pytest.mark.parametrize("combiner,reduce_fn", [("sum", np.sum), ("mean", np.mean)])
def test_ragged(combiner, reduce_fn):
    layer, params, table = build(combiner=combiner)
    rows = [[0, 1, 2], [3], [4, 5]]
    out = layer.apply(params, Ragged.from_lists(rows))
    expect = np.stack([reduce_fn(table[r], axis=0) for r in rows])
    np.testing.assert_allclose(out, expect, rtol=1e-6)


def test_sparse():
    layer, params, table = build(combiner="sum")
    indices = jnp.array([[0, 0], [0, 1], [1, 0], [2, 0], [2, 1], [2, 2]])
    values = jnp.array([0, 1, 3, 2, 4, 5])
    out = layer.apply(params, SparseIds(indices=indices, values=values,
                                        dense_shape=(3, 3)))
    expect = np.stack([table[[0, 1]].sum(0), table[3], table[[2, 4, 5]].sum(0)])
    np.testing.assert_allclose(out, expect, rtol=1e-6)


def test_int_cast():
    layer, params, table = build()
    out = layer.apply(params, jnp.array([0.0, 2.0]))
    np.testing.assert_allclose(out, table[[0, 2]])


def test_adagrad_update_matches_dense_formulation():
    """The reference compares Adagrad updates of its layer vs
    ``tf.keras.layers.Embedding`` (``embedding_test.py``); here: fused
    sum-combiner layer vs explicit gather+sum, same optax Adagrad."""
    rng = np.random.default_rng(0)
    vocab, width = 12, 4
    ids = jnp.asarray(rng.integers(0, vocab, size=(5, 3)))
    init_table = jnp.asarray(rng.normal(size=(vocab, width)), jnp.float32)

    layer = Embedding(input_dim=vocab, output_dim=width, combiner="sum")
    params_a = {"params": {"embeddings": init_table}}
    params_b = {"params": {"embeddings": init_table}}

    def loss_fused(p):
        return jnp.sum(layer.apply(p, ids) ** 2)

    def loss_dense(p):
        g = jnp.take(p["params"]["embeddings"], ids, axis=0)
        return jnp.sum(jnp.sum(g, axis=1) ** 2)

    tx = optax.adagrad(0.1)
    for loss_fn, params in ((loss_fused, params_a), (loss_dense, params_b)):
        state = tx.init(params)
        for _ in range(3):
            grads = jax.grad(loss_fn)(params)
            updates, state = tx.update(grads, state)
            params = optax.apply_updates(params, updates)
        if loss_fn is loss_fused:
            final_a = params
        else:
            final_b = params
    np.testing.assert_allclose(final_a["params"]["embeddings"],
                               final_b["params"]["embeddings"], rtol=1e-5)


def test_concat_embedding():
    sizes = (3, 4, 2)
    layer = ConcatEmbedding(feature_sizes=sizes, embedding_width=2)
    total = sum(sizes)
    table = np.arange(total * 2, dtype=np.float32).reshape(total, 2)
    params = {"params": {"embeddings": jnp.asarray(table)}}
    ids = jnp.array([[1, 0, 1], [2, 3, 0]])
    out = layer.apply(params, ids)
    expect = np.stack([table[[1, 3 + 0, 7 + 1]], table[[2, 3 + 3, 7 + 0]]])
    np.testing.assert_allclose(out, expect)


def test_from_config_strips_keras_keys():
    cfg = {"input_dim": 5, "output_dim": 3, "combiner": "sum",
           "mask_zero": True, "input_length": 4, "name": "emb"}
    layer = Embedding.from_config(cfg)
    assert layer.input_dim == 5 and layer.combiner == "sum"

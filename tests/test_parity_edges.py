"""Round-5 parity edges (VERDICT r4 Missing #3-#5).

* N-D (>2-D) dense inputs through the distributed path — the reference
  flattens arbitrary dense inputs through its exchange and lets the local
  layer reduce the trailing dim (``dist_model_parallel.py:273-288``);
  distributed outputs are ``[batch, -1]`` flats, single-worker outputs
  keep the local layer's rank.
* Per-id weights plumbed through ``layers.Embedding`` and the distributed
  executor (the CUDA kernel's optional ``weights`` input,
  ``embedding_lookup_kernels.cu:52-55``): weighted ``Ragged``/``SparseIds``
  features ride the id exchange as bitcast payloads; ``'mean'`` divides by
  the id count (kernel semantics, ``.cu:220-222``).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from distributed_embeddings_tpu.layers import Embedding
from distributed_embeddings_tpu.ops import (Ragged, SparseIds,
                                            embedding_lookup)
from distributed_embeddings_tpu.parallel import (
    DistributedEmbedding, SparseSGD, init_hybrid_state,
    make_hybrid_train_step)

WORLD = 8
B = 2  # local batch


@pytest.fixture(scope="module")
def mesh():
    devs = jax.devices()
    assert len(devs) >= WORLD
    return Mesh(np.array(devs[:WORLD]), ("data",))


def _dist_forward(de, mesh, flat, inputs):
    def fwd(params, inps):
        return tuple(de(params, list(inps)))

    return jax.jit(jax.shard_map(
        fwd, mesh=mesh, in_specs=(P("data"), P("data")),
        out_specs=P("data")))(flat, tuple(inputs))


# --------------------------------------------------------------- N-D dense


_ND_SHAPE_TAILS = [(3, 2), (2, 2, 3), (2, 3), (4,)] * 2


def _nd_model():
    base = [
        {"input_dim": 37, "output_dim": 8, "combiner": "sum"},   # 3-D
        {"input_dim": 23, "output_dim": 4, "combiner": "mean"},  # 4-D
        {"input_dim": 51, "output_dim": 8, "combiner": None},    # 3-D
        {"input_dim": 40, "output_dim": 4, "combiner": "sum"},   # 2-D
    ]
    return [dict(c, input_dim=c["input_dim"] + 10 * (k // 4))
            for k, c in enumerate(base * 2)]  # >= WORLD tables


def _nd_inputs(rng, configs, batch=WORLD * B):
    return [jnp.asarray(
        rng.integers(0, c["input_dim"], size=(batch,) + tail), jnp.int32)
        for c, tail in zip(configs, _ND_SHAPE_TAILS)]


def _nd_oracle(tables, configs, inputs):
    """Reference distributed layout: combiner reduces the LAST dim, output
    flattens to [batch, -1] (``dist_model_parallel.py:288,308``)."""
    outs = []
    for t, cfg, ids in zip(tables, configs, inputs):
        t = jnp.asarray(t)
        if cfg["combiner"]:
            flat = ids.reshape(-1, ids.shape[-1])
            o = embedding_lookup(t, flat, combiner=cfg["combiner"])
        else:
            o = embedding_lookup(t, ids)
        outs.append(o.reshape(ids.shape[0], -1))
    return outs


@pytest.mark.parametrize("strategy", ["basic", "memory_balanced"])
def test_nd_dense_distributed_forward(mesh, strategy):
    rng = np.random.default_rng(7)
    configs = _nd_model()
    de = DistributedEmbedding(configs, world_size=WORLD, strategy=strategy)
    flat = de.init(jax.random.key(0), mesh=mesh)
    tables = de.get_weights(flat)
    inputs = _nd_inputs(rng, configs)
    expect = _nd_oracle(tables, configs, inputs)
    outs = _dist_forward(de, mesh, flat, inputs)
    for i, (a, b) in enumerate(zip(outs, expect)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6, err_msg=f"out {i}")


def test_nd_dense_mp_input_forward(mesh):
    rng = np.random.default_rng(8)
    configs = _nd_model()
    de = DistributedEmbedding(configs, world_size=WORLD, dp_input=False)
    flat = de.init(jax.random.key(0), mesh=mesh)
    tables = de.get_weights(flat)
    inputs = _nd_inputs(rng, configs)
    packed = de.pack_mp_inputs([np.asarray(x) for x in inputs], mesh=mesh)
    expect = _nd_oracle(tables, configs, inputs)

    def fwd(params, mp):
        return tuple(de(params, mp))

    outs = jax.jit(jax.shard_map(
        fwd, mesh=mesh, in_specs=(P("data"), P("data")),
        out_specs=P("data")))(flat, packed)
    for i, (a, b) in enumerate(zip(outs, expect)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6, err_msg=f"out {i}")


def test_nd_dense_single_worker_keeps_rank():
    """world_size == 1 matches the reference's local `call`: N-D outputs
    keep their rank (combiner drops the last dim, no-combiner appends w)."""
    rng = np.random.default_rng(9)
    configs = _nd_model()
    de = DistributedEmbedding(configs, world_size=1)
    params = de.init(jax.random.key(0))
    tables = de.get_weights(params)
    inputs = _nd_inputs(rng, configs, batch=4)
    outs = de(params, inputs)
    assert outs[0].shape == (4, 3, 8)        # sum over last dim
    assert outs[1].shape == (4, 2, 2, 4)     # mean over last dim
    assert outs[2].shape == (4, 2, 3, 8)     # no combiner: + width dim
    assert outs[3].shape == (4, 4)           # 2-D + combiner
    for t, cfg, ids, o in zip(tables, configs, inputs, outs):
        t = jnp.asarray(t)
        if cfg["combiner"]:
            ref = embedding_lookup(
                t, ids.reshape(-1, ids.shape[-1]), combiner=cfg["combiner"]
            ).reshape(o.shape)
        else:
            ref = embedding_lookup(t, ids)
        np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)


def test_nd_dense_train_step_matches_oracle(mesh):
    """One hybrid SGD step with a 3-D input equals the full-batch oracle
    update (the reference suite's updated-weights comparison pattern)."""
    rng = np.random.default_rng(10)
    configs = _nd_model()
    de = DistributedEmbedding(configs, world_size=WORLD)
    lr = 0.3
    emb_opt = SparseSGD()
    import optax
    dp0 = {"s": jnp.float32(1.0)}
    tx = optax.sgd(lr)

    def loss_fn(dp, outs, y):
        x = jnp.concatenate([o.reshape(o.shape[0], -1) for o in outs], 1)
        return jnp.mean(x * dp["s"]) + 0.0 * y.sum()

    state = init_hybrid_state(de, emb_opt, jax.tree.map(jnp.copy, dp0),
                              tx, jax.random.key(0), mesh=mesh)
    tables0 = [jnp.asarray(t) for t in de.get_weights(state.emb_params)]
    step = make_hybrid_train_step(de, loss_fn, tx, emb_opt, mesh=mesh,
                                  lr_schedule=lr)
    inputs = _nd_inputs(rng, configs)
    y = jax.device_put(jnp.zeros((WORLD * B, 1), jnp.float32),
                       jax.sharding.NamedSharding(mesh, P("data")))
    _, state = step(state, inputs, y)
    got = de.get_weights(state.emb_params)

    def oracle_loss(tabs):
        outs = _nd_oracle(tabs, configs, inputs)
        return loss_fn(dp0, outs, np.zeros(1))

    grads = jax.grad(oracle_loss)(tables0)
    for i, (t0, g, gt) in enumerate(zip(tables0, grads, got)):
        np.testing.assert_allclose(np.asarray(t0 - lr * g), np.asarray(gt),
                                   rtol=1e-5, atol=1e-6, err_msg=f"table {i}")


# ---------------------------------------------------------- per-id weights


def test_op_layer_and_module_weights():
    rng = np.random.default_rng(11)
    vocab, w, b = 19, 4, 6
    table = jnp.asarray(rng.normal(size=(vocab, w)), jnp.float32)
    rows = [list(rng.integers(0, vocab, size=rng.integers(1, 4)))
            for _ in range(b)]
    wts = [list(rng.random(len(r)).astype(np.float32)) for r in rows]
    rag = Ragged.from_lists(rows, capacity=16, weights=wts)

    # manual oracle: weighted sum / count for mean (kernel semantics)
    def oracle(comb):
        out = np.zeros((b, w), np.float32)
        for i, (r, ws) in enumerate(zip(rows, wts)):
            for rid, wt in zip(r, ws):
                out[i] += wt * np.asarray(table)[rid]
            if comb == "mean" and r:
                out[i] /= len(r)
        return out

    for comb in ("sum", "mean"):
        got = embedding_lookup(table, rag, combiner=comb)
        np.testing.assert_allclose(np.asarray(got), oracle(comb),
                                   rtol=1e-5, atol=1e-6)
        # the Flax layer plumbs the same weights (field or argument)
        mod = Embedding(input_dim=vocab, output_dim=w, combiner=comb)
        out2 = mod.apply({"params": {"embeddings": table}}, rag)
        np.testing.assert_allclose(np.asarray(out2), oracle(comb),
                                   rtol=1e-5, atol=1e-6)
    # dense 2-D ids + explicit weights argument
    ids = jnp.asarray(rng.integers(0, vocab, size=(b, 3)), jnp.int32)
    dw = jnp.asarray(rng.random((b, 3)), jnp.float32)
    mod = Embedding(input_dim=vocab, output_dim=w, combiner="sum")
    got = mod.apply({"params": {"embeddings": table}}, ids, weights=dw)
    ref = jnp.einsum("bh,bhw->bw", dw, jnp.take(table, ids, axis=0))
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def _weighted_model(rng, num_tables=8):
    configs, feats = [], []
    cap = B * 4
    for i in range(num_tables):
        width = int(rng.integers(1, 9))
        rows = int(rng.integers(6, 60))
        comb = "mean" if i % 2 else "sum"
        configs.append({"input_dim": rows, "output_dim": width,
                        "combiner": comb})
    return configs, cap


def _weighted_inputs(rng, configs, cap):
    """Global weighted-ragged inputs as stacked per-shard CSR blocks."""
    inputs, per_shard = [], []
    for cfg in configs:
        vals, splits, wts, shards = [], [], [], []
        for s in range(WORLD):
            rows = [list(rng.integers(0, cfg["input_dim"],
                                      size=int(rng.integers(0, 5))))
                    for _ in range(B)]
            ws = [list(rng.random(len(r)).astype(np.float32)) for r in rows]
            r = Ragged.from_lists(rows, capacity=cap, weights=ws)
            vals.append(r.values)
            splits.append(r.row_splits)
            wts.append(r.weights)
            shards.append((rows, ws))
        inputs.append(Ragged(values=jnp.concatenate(vals),
                             row_splits=jnp.concatenate(splits),
                             weights=jnp.concatenate(wts)))
        per_shard.append(shards)
    return inputs, per_shard


def _weighted_oracle(tables, configs, per_shard, cap):
    outs = []
    for t, cfg, shards in zip(tables, configs, per_shard):
        t = jnp.asarray(t)
        parts = [embedding_lookup(
            t, Ragged.from_lists(rows, capacity=cap, weights=ws),
            combiner=cfg["combiner"]) for rows, ws in shards]
        outs.append(jnp.concatenate(parts, axis=0))
    return outs


def test_weighted_ragged_distributed_forward(mesh):
    rng = np.random.default_rng(12)
    configs, cap = _weighted_model(rng)
    de = DistributedEmbedding(configs, world_size=WORLD,
                              strategy="memory_balanced")
    flat = de.init(jax.random.key(1), mesh=mesh)
    tables = de.get_weights(flat)
    inputs, per_shard = _weighted_inputs(rng, configs, cap)
    expect = _weighted_oracle(tables, configs, per_shard, cap)
    outs = _dist_forward(de, mesh, flat, inputs)
    for i, (a, b) in enumerate(zip(outs, expect)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6, err_msg=f"out {i}")


def test_weighted_ragged_mp_input_forward(mesh):
    rng = np.random.default_rng(13)
    configs, cap = _weighted_model(rng, num_tables=8)
    de = DistributedEmbedding(configs, world_size=WORLD, dp_input=False)
    flat = de.init(jax.random.key(1), mesh=mesh)
    tables = de.get_weights(flat)

    # one GLOBAL-batch ragged per feature (pack_mp_inputs contract)
    inputs, glob_rows = [], []
    for cfg in configs:
        rows = [list(rng.integers(0, cfg["input_dim"],
                                  size=int(rng.integers(0, 5))))
                for _ in range(WORLD * B)]
        ws = [list(rng.random(len(r)).astype(np.float32)) for r in rows]
        inputs.append(Ragged.from_lists(rows, capacity=WORLD * B * 4,
                                        weights=ws))
        glob_rows.append((rows, ws))
    packed = de.pack_mp_inputs(inputs, mesh=mesh,
                               hots=[("rw", cap)] * len(configs))

    def fwd(params, mp):
        return tuple(de(params, mp))

    outs = jax.jit(jax.shard_map(
        fwd, mesh=mesh, in_specs=(P("data"), P("data")),
        out_specs=P("data")))(flat, packed)
    for i, cfg in enumerate(configs):
        rows, ws = glob_rows[i]
        ref = embedding_lookup(
            jnp.asarray(tables[i]),
            Ragged.from_lists(rows, capacity=WORLD * B * 8, weights=ws),
            combiner=cfg["combiner"])
        np.testing.assert_allclose(np.asarray(outs[i]), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6, err_msg=f"out {i}")


def test_weighted_ragged_train_step_matches_oracle(mesh):
    """Weights scale the backward too: one hybrid SGD step equals the
    oracle update through the weighted op-layer autodiff."""
    rng = np.random.default_rng(14)
    configs, cap = _weighted_model(rng, num_tables=8)
    de = DistributedEmbedding(configs, world_size=WORLD)
    lr = 0.25
    import optax
    emb_opt = SparseSGD()
    dp0 = {"s": jnp.float32(1.0)}
    tx = optax.sgd(lr)

    def loss_fn(dp, outs, y):
        x = jnp.concatenate([o.reshape(o.shape[0], -1) for o in outs], 1)
        return jnp.mean(x * dp["s"]) + 0.0 * y.sum()

    state = init_hybrid_state(de, emb_opt, jax.tree.map(jnp.copy, dp0),
                              tx, jax.random.key(2), mesh=mesh)
    tables0 = [jnp.asarray(t) for t in de.get_weights(state.emb_params)]
    step = make_hybrid_train_step(de, loss_fn, tx, emb_opt, mesh=mesh,
                                  lr_schedule=lr)
    inputs, per_shard = _weighted_inputs(rng, configs, cap)
    y = jax.device_put(jnp.zeros((WORLD * B, 1), jnp.float32),
                       jax.sharding.NamedSharding(mesh, P("data")))
    _, state = step(state, inputs, y)
    got = de.get_weights(state.emb_params)

    def oracle_loss(tabs):
        outs = _weighted_oracle(tabs, configs, per_shard, cap)
        return loss_fn(dp0, outs, np.zeros(1))

    grads = jax.grad(oracle_loss)(tables0)
    for i, (t0, g, gt) in enumerate(zip(tables0, grads, got)):
        np.testing.assert_allclose(np.asarray(t0 - lr * g), np.asarray(gt),
                                   rtol=1e-5, atol=1e-6, err_msg=f"table {i}")


def test_weighted_sparse_ids_roundtrip():
    """SparseIds carries weights through the COO->CSR conversion (op layer
    and executor entry)."""
    rng = np.random.default_rng(15)
    vocab, w, b = 21, 4, 5
    table = jnp.asarray(rng.normal(size=(vocab, w)), jnp.float32)
    rows = np.sort(rng.integers(0, b, size=9))
    coo = SparseIds(
        indices=jnp.asarray(np.stack([rows, np.arange(9) % 3], 1),
                            jnp.int32),
        values=jnp.asarray(rng.integers(0, vocab, size=9), jnp.int32),
        dense_shape=(b, 3),
        weights=jnp.asarray(rng.random(9), jnp.float32))
    got = embedding_lookup(table, coo, combiner="sum")
    ref = np.zeros((b, w), np.float32)
    for r, v, wt in zip(rows, np.asarray(coo.values),
                        np.asarray(coo.weights)):
        ref[r] += wt * np.asarray(table)[v]
    np.testing.assert_allclose(np.asarray(got), ref, rtol=1e-5, atol=1e-6)

"""Process-isolated serving (ISSUE 18): snapshot payload fidelity, the
supervision config, outage semantics, and one real spawned worker.

The crash/hang/restart chaos drill runs out-of-band in
``tools/check_isolation.py`` (= ``make check-isolation``); here we pin
the pieces that make it deterministic: the shm payload reconstructs the
in-process ``install_snapshot`` state BITWISE, an unstarted/downed
supervisor answers typed ``Unavailable`` instead of hanging callers,
and a real spawn-context worker serves a stream end to end with
request conservation."""

import os

import numpy as np
import jax
import pytest

from distributed_embeddings_tpu.parallel import serving as sv
from distributed_embeddings_tpu.parallel import supervisor as sup
from distributed_embeddings_tpu.utils import mplane

from tools import isolation_common as ic


# ------------------------------------------------ payload <-> state pin


def test_snapshot_payload_reconstructs_state_bitwise():
    built = ic.build(world=1)
    state, stream = built["state"], built["streaming"][1]
    payload = sup.snapshot_payload(state, stream)
    state2, stream2, step = sup.install_payload(payload, state, stream)
    assert step == int(np.asarray(state.step))
    ref = jax.tree.leaves((state.emb_params, state.dense_params))
    got = jax.tree.leaves((state2.emb_params, state2.dense_params))
    assert len(ref) == len(got)
    for a, b in zip(ref, got):
        a, b = np.asarray(a), np.asarray(b)
        assert a.dtype == b.dtype and np.array_equal(a, b)
    for a, b in zip(jax.tree.leaves(stream), jax.tree.leaves(stream2)):
        a, b = np.asarray(a), np.asarray(b)
        assert a.dtype == b.dtype and np.array_equal(a, b)


def test_payload_install_matches_in_process_install_snapshot():
    """The cross-boundary pin: a runtime fed the RECONSTRUCTED state
    must answer bitwise-identically to one fed the original via plain
    ``install_snapshot`` — and installing the reconstruction must not
    retrace the compiled ladder (device_put onto template shardings)."""
    built = ic.build(world=1)
    state, (scfg, sstate) = built["state"], built["streaming"]

    def mk_rt():
        rt = sv.ServingRuntime(built["de"], built["pred_fn"], state,
                               config=built["config"],
                               streaming=(scfg, sstate))
        rt.warmup(built["template"])
        return rt

    rt_direct, rt_shm = mk_rt(), mk_rt()
    stream_copy = jax.tree.map(lambda x: np.asarray(x), sstate)
    rt_direct.install_snapshot(state, stream_copy, version=1, train_step=0)
    payload = sup.snapshot_payload(state, sstate)
    state2, stream2, _ = sup.install_payload(payload, state, sstate)
    rt_shm.install_snapshot(state2, stream2, version=1, train_step=0)

    make_request = ic.make_request_fn(seed=5)
    for i in range(4):
        for rt in (rt_direct, rt_shm):
            assert rt.submit(make_request(i)) is None
    a = {r.rid: r for r in rt_direct.flush()}
    b = {r.rid: r for r in rt_shm.flush()}
    assert set(a) == set(b) and a
    for rid in a:
        assert isinstance(a[rid], sv.Served)
        assert np.array_equal(np.asarray(a[rid].predictions),
                              np.asarray(b[rid].predictions))
    assert rt_shm.steady_recompiles() == 0


def test_install_payload_rejects_mismatched_template():
    built = ic.build(world=1)
    state, stream = built["state"], built["streaming"][1]
    payload = sup.snapshot_payload(state, stream)
    with pytest.raises(ValueError, match="streaming"):
        sup.install_payload(payload, state, None)


# --------------------------------------------------------------- config


def test_supervise_config_env_defaults(monkeypatch):
    cfg = sup.SuperviseConfig()
    assert cfg.heartbeat_s == 0.25 and cfg.deadline_s == 5.0
    assert cfg.max_restarts == 3
    monkeypatch.setenv(sup.MAX_RESTARTS_ENV, "7")
    monkeypatch.setenv(sup.HEARTBEAT_ENV, "0.5")
    cfg = sup.SuperviseConfig()
    assert cfg.max_restarts == 7 and cfg.heartbeat_s == 0.5


def test_supervise_config_rejects_unbeatable_deadline():
    with pytest.raises(ValueError, match="deadline"):
        sup.SuperviseConfig(heartbeat_s=2.0, deadline_s=1.0)


# ------------------------------------------------------ outage semantics


def test_unstarted_supervisor_answers_typed_unavailable():
    s = sup.Supervisor("tools.isolation_common:worker_factory",
                       {"world": 1})
    try:
        make_request = ic.make_request_fn()
        rej = s.submit(make_request(0))
        assert isinstance(rej, sv.Unavailable)
        assert rej.status == "unavailable"
        assert rej.reason == "never_started" and rej.rid == 0
        rej2 = s.submit(make_request(1))
        assert rej2.rid == 1            # rids stay monotone while down
        assert s.queued_samples == 0    # nothing hung, nothing lost
        st = s.stats(sync=False)
        assert st["supervisor"]["worker_alive"] is False
        assert st["supervisor"]["unavailable"] == 2
    finally:
        s.close()


# ------------------------------------------------- compare_bench gate


def test_compare_bench_isolated_serving_gate():
    from tools import compare_bench as cb

    def rec(crashes=1, restarts=1, budget=1, conserved=1, rc=0,
            inp99=8.0, oop99=14.0, rtfs=20.0):
        return {"isolated_serving": {
            "crashes": crashes, "restarts": restarts,
            "budget_ok": budget, "conserved": conserved,
            "steady_state_recompiles": rc,
            "inproc_p99_ms": inp99, "oop_p99_ms": oop99,
            "restart_to_first_served_ms": rtfs}}

    base = rec()
    assert cb.check_isolated_serving(base, rec()) == 0
    assert cb.check_isolated_serving(base, rec(crashes=0)) == 1
    assert cb.check_isolated_serving(base, rec(restarts=0)) == 1
    assert cb.check_isolated_serving(base, rec(budget=0)) == 1
    assert cb.check_isolated_serving(base, rec(conserved=0)) == 1
    assert cb.check_isolated_serving(base, rec(rc=2)) == 1
    # boundary overhead: 5x floor + 10ms slack
    assert cb.check_isolated_serving(base, rec(oop99=49.0)) == 0
    assert cb.check_isolated_serving(base, rec(oop99=51.0)) == 1
    assert cb.check_isolated_serving(base, rec(rtfs=40_000.0)) == 1
    # missing section vs a baseline that has it fails; both-missing and
    # new-section-no-baseline pass
    assert cb.check_isolated_serving(base, {}) == 1
    assert cb.check_isolated_serving({}, {}) == 0
    assert cb.check_isolated_serving({}, rec()) == 0


# ----------------------------------------------------- one real worker


def test_supervised_worker_end_to_end(tmp_path):
    """Spawn a real world-1 worker, publish a snapshot through shared
    memory, drive a request stream via the wall-clock driver, and pin
    request conservation + the supervisor stats block. (Crash/restart
    chaos is ``make check-isolation``'s job.)"""
    s = sup.Supervisor(
        "tools.isolation_common:worker_factory", {"world": 1},
        config=sup.SuperviseConfig(
            blackbox_path=str(tmp_path / "sup.blackbox.json"),
            env={"JAX_PLATFORMS": "cpu", "DETPU_FAULT": "",
                 "DETPU_METRICS_PORT": ""}))
    try:
        s.start()
        assert s._warm and s.stats(sync=False)["supervisor"]["worker_alive"]
        built = ic.build(world=1)
        s.install_snapshot(built["state"], built["streaming"][1],
                           version=1, train_step=0)
        s.note_train_step(1)
        drv = sv.RealtimeDriver(s, ic.make_request_fn(seed=2), qps=60,
                                duration_s=0.5, burst_positions=(),
                                drain_s=60.0)
        drv.start()
        drv.join(timeout=120)
        results = drv.results()
        assert drv.submitted > 0
        assert sorted(r.rid for r in results) == list(range(drv.submitted))
        served = [r for r in results if isinstance(r, sv.Served)]
        assert served, [type(r).__name__ for r in results]
        assert all(r.version == 1 for r in served)
        st = s.stats()
        assert st["served"] >= len(served) - 1
        assert st["steady_state_recompiles"] == 0
        block = st["supervisor"]
        assert block["restarts"] == 0 and block["worker_alive"]
        assert block["shm_region_bytes"] > 0
        assert block["shm_publish_p95_ms"] is not None
        # monotone versioning enforced supervisor-side too
        with pytest.raises(ValueError, match="monotonic"):
            s.install_snapshot(built["state"], built["streaming"][1],
                               version=1, train_step=2)
    finally:
        s.close()
    assert not os.path.exists(str(tmp_path / "sup.blackbox.json"))

"""flax_training example smoke: all three modes run end-to-end and learn.

Covers the ecosystem-composability surface (VERDICT r3 Missing #2 plus the
r5 sparse-optax route): plain flax+optax, the 8-device mesh variant, and
O(touched-rows) sparse training under plain optax.
"""

import os
import re
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SCRIPT = os.path.join(_REPO, "examples", "flax_training", "main.py")


def _run(extra):
    env = dict(os.environ)
    env["DETPU_FORCE_CPU_DEVICES"] = "8"
    env["PYTHONPATH"] = os.pathsep.join(
        [_REPO] + env.get("PYTHONPATH", "").split(os.pathsep))
    proc = subprocess.run([sys.executable, _SCRIPT] + extra, env=env,
                          capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr[-3000:]
    return proc.stdout


def _final_loss(out):
    m = re.search(r"final loss ([0-9.]+)", out)
    assert m, out
    return float(m.group(1))


@pytest.mark.slow
@pytest.mark.parametrize("mode,factor", [
    ([], 0.5),           # Adam converges fast
    (["--mesh"], 0.9),   # 100 plain-SGD steps: modest but monotone drop
    (["--sparse"], 0.5),
])
def test_example_modes_run_and_learn(mode, factor):
    out = _run(mode)
    m = re.search(r"step +0 loss ([0-9.]+)", out)
    assert m, out
    assert _final_loss(out) < float(m.group(1)) * factor, out

"""Distributed integration tests on an 8-virtual-device CPU mesh.

Single-process-reference pattern from the reference suite
(``distributed_embeddings/python/layers/dist_model_parallel_test.py:29-171``):
build a full non-distributed model and a distributed one from the same weights,
assert forward outputs equal, then apply one SGD step to both and compare
updated weights — avoiding direct comparison of sharded gradients.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_embeddings_tpu.ops import embedding_lookup
from distributed_embeddings_tpu.parallel import (
    DistributedEmbedding,
    hybrid_value_and_grad,
)

WORLD = 8


@pytest.fixture(scope="module")
def mesh():
    devs = jax.devices()
    assert len(devs) >= WORLD, "conftest should force 8 CPU devices"
    return Mesh(np.array(devs[:WORLD]), ("data",))


def random_model(rng, num_tables=12, num_inputs=None, shared=False,
                 multihot=True):
    """Random table configs + input map + global inputs, reference-style
    randomized shapes (``dist_model_parallel_test.py:96-114``)."""
    configs = []
    for _ in range(num_tables):
        width = int(rng.integers(1, 9))
        rows = int(rng.integers(4, 100))
        combiner = rng.choice([None, "sum", "mean"]) if multihot else None
        configs.append({"input_dim": rows, "output_dim": width,
                        "combiner": combiner})
    if shared:
        num_inputs = num_inputs or num_tables + 2
        input_table_map = list(rng.integers(0, num_tables, size=num_inputs))
        # ensure every table has at least one input
        for t in range(num_tables):
            if t not in input_table_map:
                input_table_map[rng.integers(0, num_inputs)] = t
        input_table_map = sorted(input_table_map)
    else:
        input_table_map = list(range(num_tables))
    return configs, input_table_map


def make_inputs(rng, configs, input_table_map, global_batch,
                multihot_nocombiner=False):
    """Random inputs. ``multihot_nocombiner`` draws hotness>1 for
    combiner-less tables too — valid only without column slicing (sliced
    no-combiner outputs are slice-major flattened, matching the reference's
    [batch, -1] reshape, so they differ from the unsliced oracle layout)."""
    inputs = []
    for i in input_table_map:
        cfg = configs[i]
        if cfg["combiner"] or multihot_nocombiner:
            hot = int(rng.integers(1, 5))
        else:
            hot = 1
        ids = rng.integers(0, cfg["input_dim"], size=(global_batch, hot))
        inputs.append(jnp.asarray(ids, jnp.int32))
    return inputs


def reference_forward(tables, configs, input_table_map, inputs):
    """Full-batch single-device oracle, flattened to the distributed layout."""
    outs = []
    for inp, t in zip(inputs, input_table_map):
        cfg = configs[t]
        if cfg["combiner"]:
            o = embedding_lookup(jnp.asarray(tables[t]), inp,
                                 combiner=cfg["combiner"])
        else:
            o = embedding_lookup(jnp.asarray(tables[t]), inp)
        outs.append(o.reshape(o.shape[0], -1))
    return outs


def dist_forward_fn(de, mesh, n_inputs):
    def fwd(params, *inps):
        return tuple(de(params, list(inps)))

    return jax.jit(jax.shard_map(
        fwd, mesh=mesh,
        in_specs=(P("data"),) + (P("data"),) * n_inputs,
        out_specs=P("data")))


SEEDS = {"basic": 101, "memory_balanced": 202, "memory_optimized": 303,
         "comm_balanced": 404}


@pytest.mark.parametrize("strategy", ["basic", "memory_balanced", "comm_balanced",
                                      "memory_optimized"])
@pytest.mark.parametrize("column_slice_threshold", [None, 150])
def test_forward_matches_reference(mesh, strategy, column_slice_threshold):
    rng = np.random.default_rng(SEEDS[strategy])
    configs, input_table_map = random_model(rng)
    de = DistributedEmbedding(configs, world_size=WORLD, strategy=strategy,
                              column_slice_threshold=column_slice_threshold,
                              input_table_map=input_table_map)
    flat = de.init(jax.random.key(0), mesh=mesh)
    tables = de.get_weights(flat)

    inputs = make_inputs(rng, configs, input_table_map, global_batch=WORLD * 4,
                         multihot_nocombiner=column_slice_threshold is None)
    expect = reference_forward(tables, configs, input_table_map, inputs)

    outs = dist_forward_fn(de, mesh, len(inputs))(flat, *inputs)
    assert len(outs) == len(expect)
    for o, e in zip(outs, expect):
        np.testing.assert_allclose(np.asarray(o), np.asarray(e),
                                   rtol=1e-5, atol=1e-6)


def test_set_weights_roundtrip(mesh):
    rng = np.random.default_rng(7)
    configs, input_table_map = random_model(rng, num_tables=9)
    de = DistributedEmbedding(configs, world_size=WORLD,
                              strategy="memory_balanced",
                              column_slice_threshold=120,
                              input_table_map=input_table_map)
    tables = [rng.normal(size=(c["input_dim"], c["output_dim"])
                         ).astype(np.float32) for c in configs]
    flat = de.set_weights(tables, mesh=mesh)
    back = de.get_weights(flat)
    for a, b in zip(tables, back):
        np.testing.assert_array_equal(a, b)


def test_shared_table_inputs_forward(mesh):
    rng = np.random.default_rng(11)
    configs, input_table_map = random_model(rng, num_tables=10, shared=True)
    de = DistributedEmbedding(configs, world_size=WORLD,
                              input_table_map=input_table_map)
    flat = de.init(jax.random.key(1), mesh=mesh)
    tables = de.get_weights(flat)
    inputs = make_inputs(rng, configs, input_table_map, global_batch=WORLD * 2)
    expect = reference_forward(tables, configs, input_table_map, inputs)
    outs = dist_forward_fn(de, mesh, len(inputs))(flat, *inputs)
    for o, e in zip(outs, expect):
        np.testing.assert_allclose(np.asarray(o), np.asarray(e),
                                   rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("strategy", ["basic", "memory_optimized"])
def test_sgd_step_matches_reference(mesh, strategy):
    """One SGD step on both models from identical weights; compare updated
    weights (reference ``dist_model_parallel_test.py:162-171``)."""
    rng = np.random.default_rng(13)
    configs, input_table_map = random_model(rng, num_tables=10, multihot=True)
    de = DistributedEmbedding(configs, world_size=WORLD, strategy=strategy,
                              column_slice_threshold=200,
                              input_table_map=input_table_map)
    tables0 = [rng.normal(size=(c["input_dim"], c["output_dim"])
                          ).astype(np.float32) for c in configs]
    flat = de.set_weights(tables0, mesh=mesh)
    inputs = make_inputs(rng, configs, input_table_map, global_batch=WORLD * 4)
    lr = 0.5

    # --- distributed step -------------------------------------------------
    def local_loss(params, *inps):
        outs = de(params, list(inps))
        return sum(jnp.mean(o ** 2) for o in outs)

    def step(params, *inps):
        loss, grads = hybrid_value_and_grad(
            local_loss, mp_mask=True, axis_name="data")(params, *inps)
        return jax.tree.map(lambda p, g: p - lr * g, params, grads)

    new_flat = jax.jit(jax.shard_map(
        step, mesh=mesh,
        in_specs=(P("data"),) + (P("data"),) * len(inputs),
        out_specs=P("data")))(flat, *inputs)
    dist_tables = de.get_weights(new_flat)

    # --- single-device reference step -------------------------------------
    def ref_loss(tables):
        outs = reference_forward(tables, configs, input_table_map, inputs)
        return sum(jnp.mean(o ** 2) for o in outs)

    ref_grads = jax.grad(ref_loss)([jnp.asarray(t) for t in tables0])
    ref_tables = [t - lr * g for t, g in zip(tables0, ref_grads)]

    for a, b in zip(dist_tables, ref_tables):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_column_slice_dup_worker(mesh):
    """A rank can hold two slices of the same table
    (reference ``test_column_slice_dup_worker``, ``:277-287``): 8 tables on 8
    ranks with aggressive slicing forces duplicate-table ranks."""
    rng = np.random.default_rng(17)
    configs = [{"input_dim": 64, "output_dim": 8, "combiner": None}
               for _ in range(8)]
    de = DistributedEmbedding(configs, world_size=WORLD,
                              column_slice_threshold=16)
    tables = [rng.normal(size=(64, 8)).astype(np.float32) for _ in range(8)]
    flat = de.set_weights(tables, mesh=mesh)
    np.testing.assert_array_equal(np.asarray(de.get_weights(flat)[3]),
                                  tables[3])
    inputs = [jnp.asarray(rng.integers(0, 64, size=(WORLD * 2, 1)), jnp.int32)
              for _ in range(8)]
    outs = dist_forward_fn(de, mesh, 8)(flat, *inputs)
    expect = reference_forward(tables, configs, list(range(8)), inputs)
    for o, e in zip(outs, expect):
        np.testing.assert_allclose(np.asarray(o), np.asarray(e),
                                   rtol=1e-5, atol=1e-6)


def test_rank_with_no_inputs(mesh):
    """A table with no mapped input leaves its rank with nothing to route:
    branch outputs must still type-match across ranks."""
    rng = np.random.default_rng(23)
    configs = [{"input_dim": 16, "output_dim": 4, "combiner": None}
               for _ in range(9)]
    # inputs only reference tables 0..7; table 8's owner routes no inputs
    input_table_map = list(range(8))
    de = DistributedEmbedding(configs, world_size=WORLD,
                              input_table_map=input_table_map)
    flat = de.init(jax.random.key(5), mesh=mesh)
    tables = de.get_weights(flat)
    inputs = [jnp.asarray(rng.integers(0, 16, size=(WORLD * 2, 1)), jnp.int32)
              for _ in range(8)]
    outs = dist_forward_fn(de, mesh, 8)(flat, *inputs)
    expect = reference_forward(tables, configs, input_table_map, inputs)
    for o, e in zip(outs, expect):
        np.testing.assert_allclose(np.asarray(o), np.asarray(e),
                                   rtol=1e-5, atol=1e-6)


def dist_forward_mp_fn(de, mesh):
    """Forward for model-parallel input: the MpInputs pytree shards over the
    mesh axis (its packed [dest, src, l_max] leading dim)."""
    def fwd(params, mp_in):
        return tuple(de(params, mp_in))

    return jax.jit(jax.shard_map(
        fwd, mesh=mesh, in_specs=(P("data"), P("data")),
        out_specs=P("data")))


@pytest.mark.parametrize("strategy", ["basic", "memory_balanced", "comm_balanced",
                                      "memory_optimized"])
@pytest.mark.parametrize("column_slice_threshold", [None, 150])
def test_mp_input_forward_matches_reference(mesh, strategy,
                                            column_slice_threshold):
    """dp_input=False forward parity (reference
    ``dist_model_parallel_test.py:129-134``: the mp-input mode of every
    strategy)."""
    rng = np.random.default_rng(SEEDS[strategy] + 1)
    configs, input_table_map = random_model(rng)
    de = DistributedEmbedding(configs, world_size=WORLD, strategy=strategy,
                              column_slice_threshold=column_slice_threshold,
                              input_table_map=input_table_map, dp_input=False)
    flat = de.init(jax.random.key(0), mesh=mesh)
    tables = de.get_weights(flat)

    inputs = make_inputs(rng, configs, input_table_map, global_batch=WORLD * 4,
                         multihot_nocombiner=column_slice_threshold is None)
    expect = reference_forward(tables, configs, input_table_map, inputs)

    mp_in = de.pack_mp_inputs(inputs, mesh=mesh)
    outs = dist_forward_mp_fn(de, mesh)(flat, mp_in)
    assert len(outs) == len(expect)
    for o, e in zip(outs, expect):
        np.testing.assert_allclose(np.asarray(o), np.asarray(e),
                                   rtol=1e-5, atol=1e-6)


def test_mp_input_sgd_step_matches_reference(mesh):
    """One SGD step under mp input equals the single-device oracle step
    (reference ``dist_model_parallel_test.py:199-215``)."""
    rng = np.random.default_rng(29)
    configs, input_table_map = random_model(rng, num_tables=10)
    de = DistributedEmbedding(configs, world_size=WORLD,
                              strategy="memory_balanced",
                              column_slice_threshold=200,
                              input_table_map=input_table_map, dp_input=False)
    tables0 = [rng.normal(size=(c["input_dim"], c["output_dim"])
                          ).astype(np.float32) for c in configs]
    flat = de.set_weights(tables0, mesh=mesh)
    inputs = make_inputs(rng, configs, input_table_map, global_batch=WORLD * 4)
    mp_in = de.pack_mp_inputs(inputs, mesh=mesh)
    lr = 0.5

    def local_loss(params, mp_in_):
        outs = de(params, mp_in_)
        return sum(jnp.mean(o ** 2) for o in outs)

    def step(params, mp_in_):
        loss, grads = hybrid_value_and_grad(
            local_loss, mp_mask=True, axis_name="data")(params, mp_in_)
        return jax.tree.map(lambda p, g: p - lr * g, params, grads)

    new_flat = jax.jit(jax.shard_map(
        step, mesh=mesh, in_specs=(P("data"), P("data")),
        out_specs=P("data")))(flat, mp_in)
    dist_tables = de.get_weights(new_flat)

    def ref_loss(tables):
        outs = reference_forward(tables, configs, input_table_map, inputs)
        return sum(jnp.mean(o ** 2) for o in outs)

    ref_grads = jax.grad(ref_loss)([jnp.asarray(t) for t in tables0])
    ref_tables = [t - lr * g for t, g in zip(tables0, ref_grads)]
    for a, b in zip(dist_tables, ref_tables):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("dp_input", [True, False])
def test_bf16_compute_dtype_forward(mesh, dp_input):
    """compute_dtype=bf16: outputs come back bf16 (cast before the mp→dp
    exchange, reference ``dist_model_parallel.py:300``) and match the fp32
    oracle within bf16 tolerance."""
    rng = np.random.default_rng(31)
    configs, input_table_map = random_model(rng, num_tables=10)
    de = DistributedEmbedding(configs, world_size=WORLD,
                              strategy="memory_balanced",
                              input_table_map=input_table_map,
                              dp_input=dp_input,
                              compute_dtype=jnp.bfloat16)
    flat = de.init(jax.random.key(0), mesh=mesh)
    tables = de.get_weights(flat)
    inputs = make_inputs(rng, configs, input_table_map, global_batch=WORLD * 4)
    expect = reference_forward(tables, configs, input_table_map, inputs)

    if dp_input:
        outs = dist_forward_fn(de, mesh, len(inputs))(flat, *inputs)
    else:
        outs = dist_forward_mp_fn(de, mesh)(flat,
                                            de.pack_mp_inputs(inputs,
                                                              mesh=mesh))
    for o, e in zip(outs, expect):
        assert o.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(o, np.float32), np.asarray(e),
                                   rtol=2e-2, atol=2e-2)


def test_bf16_single_worker_cast():
    configs = [{"input_dim": 10, "output_dim": 4, "combiner": "sum"}]
    de = DistributedEmbedding(configs, world_size=1,
                              compute_dtype=jnp.bfloat16)
    flat = de.init(jax.random.key(0))
    outs = de(flat, [jnp.asarray([[1, 2], [3, 4]], jnp.int32)])
    assert outs[0].dtype == jnp.bfloat16


def test_world_size_one_passthrough():
    configs = [{"input_dim": 10, "output_dim": 4, "combiner": "sum"},
               {"input_dim": 8, "output_dim": 2, "combiner": None}]
    de = DistributedEmbedding(configs, world_size=1)
    flat = de.init(jax.random.key(0))
    tables = de.get_weights(flat)
    ids0 = jnp.asarray([[1, 2], [3, 4]], jnp.int32)
    ids1 = jnp.asarray([[5], [0]], jnp.int32)
    outs = de(flat, [ids0, ids1])
    np.testing.assert_allclose(
        outs[0], embedding_lookup(jnp.asarray(tables[0]), ids0, combiner="sum"),
        rtol=1e-6)
    np.testing.assert_allclose(
        outs[1], embedding_lookup(jnp.asarray(tables[1]), ids1), rtol=1e-6)

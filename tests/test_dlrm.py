"""DLRM model tests (reference: ``examples/dlrm/``)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from distributed_embeddings_tpu.models import (
    DLRM,
    DLRMConfig,
    dot_interact,
)
from distributed_embeddings_tpu.models.dlrm import DLRMDense, bce_with_logits
from distributed_embeddings_tpu.models.schedules import warmup_poly_decay_schedule
from distributed_embeddings_tpu.parallel import (
    DistributedEmbedding,
    SparseSGD,
    HybridTrainState,
    make_hybrid_train_step,
)
from distributed_embeddings_tpu.utils import binary_auc


def small_config(tables=6, dim=8):
    return DLRMConfig(table_sizes=[50 + 7 * i for i in range(tables)],
                      embedding_dim=dim,
                      num_numerical_features=4,
                      bottom_mlp_dims=[16, dim],
                      top_mlp_dims=[32, 16, 1])


def test_dot_interact_matches_numpy():
    rng = np.random.default_rng(0)
    B, F, D = 4, 5, 3
    embs = [jnp.asarray(rng.normal(size=(B, D)), jnp.float32)
            for _ in range(F - 1)]
    bot = jnp.asarray(rng.normal(size=(B, D)), jnp.float32)
    out = dot_interact(embs, bot)
    feats = np.stack([np.asarray(bot)] + [np.asarray(e) for e in embs], 1)
    gram = feats @ feats.transpose(0, 2, 1)
    want = []
    for b in range(B):
        low = [gram[b, i, j] for i in range(F) for j in range(i)]
        want.append(np.concatenate([low, feats[b, 0]]))
    np.testing.assert_allclose(out, np.stack(want), rtol=1e-5)
    assert out.shape == (B, F * (F - 1) // 2 + D)


def test_dlrm_forward_and_local_train():
    cfg = small_config()
    model = DLRM(cfg)
    params = model.init(jax.random.key(0))
    rng = np.random.default_rng(1)
    B = 32
    num = jnp.asarray(rng.normal(size=(B, 4)), jnp.float32)
    cats = [jnp.asarray(rng.integers(0, s, size=(B,)), jnp.int32)
            for s in cfg.table_sizes]
    logits = model.apply(params, num, cats)
    assert logits.shape == (B, 1)
    labels = jnp.asarray(rng.integers(0, 2, size=(B, 1)), jnp.float32)
    loss = bce_with_logits(logits, labels)
    assert np.isfinite(float(loss))


@pytest.mark.parametrize(
    "world", [1, pytest.param(8, marks=pytest.mark.slow)])
def test_dlrm_hybrid_training_loss_decreases(world):
    cfg = small_config(tables=10)  # >= world ranks (reference constraint)
    mesh = (Mesh(np.array(jax.devices()[:world]), ("data",))
            if world > 1 else None)
    de = DistributedEmbedding(cfg.embedding_configs(), world_size=world,
                              strategy="memory_balanced")
    dense = DLRMDense(cfg)
    rng = np.random.default_rng(2)
    B = 16 * world
    num = jnp.asarray(rng.normal(size=(B, 4)), jnp.float32)
    cats = [jnp.asarray(rng.integers(0, s, size=(B,)), jnp.int32)
            for s in cfg.table_sizes]
    labels = jnp.asarray(rng.integers(0, 2, size=(B, 1)), jnp.float32)

    dense_params = dense.init(
        jax.random.key(3), num[:2],
        [jnp.zeros((2, cfg.embedding_dim), jnp.float32)
         for _ in cfg.table_sizes])

    def loss_fn(dp, emb_outs, batch):
        n, y = batch
        logits = dense.apply(dp, n, emb_outs)
        return bce_with_logits(logits, y)

    emb_opt = SparseSGD()
    tx = optax.sgd(0.05)
    flat = de.init(jax.random.key(4), mesh=mesh)
    state = HybridTrainState(
        emb_params=flat,
        emb_opt_state=emb_opt.init(flat),
        dense_params=dense_params,
        dense_opt_state=tx.init(dense_params),
        step=jnp.zeros((), jnp.int32))
    step_fn = make_hybrid_train_step(de, loss_fn, tx, emb_opt, mesh=mesh,
                                     lr_schedule=0.05)
    losses = []
    for _ in range(20):
        loss, state = step_fn(state, cats, (num, labels))
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()


@pytest.mark.slow
@pytest.mark.parametrize("dp_input", [True, False])
def test_dlrm_mesh_eval_matches_single_device(dp_input):
    """Distributed eval (shard_map forward + reassembled global predictions)
    equals a single-device forward from the same weights; AUC computes on the
    gathered predictions (the reference's allgather eval,
    ``examples/dlrm/main.py:230-243``)."""
    from distributed_embeddings_tpu.parallel import make_hybrid_eval_step

    world = 8
    cfg = small_config(tables=10)
    mesh = Mesh(np.array(jax.devices()[:world]), ("data",))
    de = DistributedEmbedding(cfg.embedding_configs(), world_size=world,
                              strategy="memory_balanced", dp_input=dp_input)
    dense = DLRMDense(cfg)
    rng = np.random.default_rng(5)
    B = 16 * world
    num = jnp.asarray(rng.normal(size=(B, 4)), jnp.float32)
    cats = [jnp.asarray(rng.integers(0, s, size=(B,)), jnp.int32)
            for s in cfg.table_sizes]
    labels = rng.integers(0, 2, size=(B,))

    flat = de.init(jax.random.key(6), mesh=mesh)
    tables = de.get_weights(flat)
    dense_params = dense.init(
        jax.random.key(7), num[:2],
        [jnp.zeros((2, cfg.embedding_dim), jnp.float32)
         for _ in cfg.table_sizes])
    state = HybridTrainState(
        emb_params=flat, emb_opt_state=(), dense_params=dense_params,
        dense_opt_state=(), step=jnp.zeros((), jnp.int32))

    eval_fn = make_hybrid_eval_step(
        de, lambda dp, outs, n: jax.nn.sigmoid(dense.apply(dp, n, outs)),
        mesh=mesh)
    cats_in = de.pack_mp_inputs(cats, mesh=mesh) if not dp_input else cats
    preds = np.asarray(eval_fn(state, cats_in, num))

    de1 = DistributedEmbedding(cfg.embedding_configs(), world_size=1)
    flat1 = de1.set_weights(tables)
    outs1 = de1(flat1, cats)
    want = np.asarray(jax.nn.sigmoid(dense.apply(dense_params, num, outs1)))
    np.testing.assert_allclose(preds, want, rtol=1e-5, atol=1e-6)

    auc = binary_auc(labels, preds[:, 0])
    assert 0.0 <= auc <= 1.0


@pytest.mark.slow
def test_dlrm_bf16_hybrid_training_loss_decreases():
    """Full bf16-compute hybrid step (bf16 MLPs + bf16 embedding exchange,
    fp32 master weights) trains stably — the reference's AMP configuration
    (``examples/dlrm/README.md:8``) on TPU."""
    world = 8
    cfg = small_config(tables=10)
    cfg.compute_dtype = jnp.bfloat16
    mesh = Mesh(np.array(jax.devices()[:world]), ("data",))
    de = DistributedEmbedding(cfg.embedding_configs(), world_size=world,
                              strategy="memory_balanced",
                              compute_dtype=jnp.bfloat16)
    dense = DLRMDense(cfg)
    rng = np.random.default_rng(9)
    B = 16 * world
    num = jnp.asarray(rng.normal(size=(B, 4)), jnp.float32)
    cats = [jnp.asarray(rng.integers(0, s, size=(B,)), jnp.int32)
            for s in cfg.table_sizes]
    labels = jnp.asarray(rng.integers(0, 2, size=(B, 1)), jnp.float32)

    dense_params = dense.init(
        jax.random.key(3), num[:2],
        [jnp.zeros((2, cfg.embedding_dim), jnp.float32)
         for _ in cfg.table_sizes])
    # master weights stay fp32 under bf16 compute
    assert all(p.dtype == jnp.float32
               for p in jax.tree.leaves(dense_params))

    def loss_fn(dp, emb_outs, batch):
        n, y = batch
        assert all(o.dtype == jnp.bfloat16 for o in emb_outs)
        return bce_with_logits(dense.apply(dp, n, emb_outs), y)

    emb_opt = SparseSGD()
    tx = optax.sgd(0.05)
    flat = de.init(jax.random.key(4), mesh=mesh)
    assert all(p.dtype == jnp.float32 for p in jax.tree.leaves(flat))
    state = HybridTrainState(
        emb_params=flat,
        emb_opt_state=emb_opt.init(flat),
        dense_params=dense_params,
        dense_opt_state=tx.init(dense_params),
        step=jnp.zeros((), jnp.int32))
    step_fn = make_hybrid_train_step(de, loss_fn, tx, emb_opt, mesh=mesh,
                                     lr_schedule=0.05)
    losses = []
    for _ in range(20):
        loss, state = step_fn(state, cats, (num, labels))
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()
    assert all(p.dtype == jnp.float32
               for p in jax.tree.leaves(state.emb_params))


def test_lr_schedule_phases():
    sched = warmup_poly_decay_schedule(24.0, warmup_steps=10,
                                       decay_start_step=20, decay_steps=10)
    assert float(sched(0)) == pytest.approx(0.0, abs=1e-5)
    assert float(sched(5)) == pytest.approx(12.0, rel=1e-5)
    assert float(sched(15)) == pytest.approx(24.0)
    assert float(sched(25)) == pytest.approx(24.0 * 0.25, rel=1e-5)
    assert float(sched(40)) == pytest.approx(0.0, abs=1e-6)


def test_binary_auc():
    labels = np.array([0, 0, 1, 1])
    # perfect ranking
    assert binary_auc(labels, np.array([0.1, 0.2, 0.8, 0.9])) == 1.0
    # 1 of 4 (pos, neg) pairs correctly ordered
    assert binary_auc(labels, np.array([0.9, 0.2, 0.8, 0.1])) == 0.25
    # known partial
    assert binary_auc(labels, np.array([0.3, 0.6, 0.5, 0.9])) == 0.75

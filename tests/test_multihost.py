"""Multi-host smoke: 2 processes x 4 virtual CPU devices, one jax.distributed
runtime.

The reference's distributed tests run under ``horovodrun -np N`` on one box
(``dist_model_parallel_test.py:85-89``); the TPU-native analogue is a local
``jax.distributed`` cluster. Each process initializes only its addressable
shards, runs one hybrid train step over the global 8-device mesh, and
reassembles full tables with ``get_weights`` from *non-addressable* shards —
the masked-psum chunked-allgather path. Both processes must see identical
tables, and the run must match a single-process oracle on the same seed.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_WORKER = r"""
import json, sys

import os
pid = int(sys.argv[1])
port = sys.argv[2]
nproc = int(sys.argv[3])
# 8 global devices regardless of process count (2x4 or 1x8)
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + f" --xla_force_host_platform_device_count={8 // nproc}")
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

from distributed_embeddings_tpu.parallel import bootstrap

if nproc > 1:
    did = bootstrap.initialize(coordinator_address=f"127.0.0.1:{port}",
                               num_processes=nproc, process_id=pid)
    assert did, "bootstrap.initialize() did not run"
    assert not bootstrap.initialize(), "second initialize() must be a no-op"
assert bootstrap.process_count() == nproc
assert bootstrap.world() == 8, jax.devices()
assert bootstrap.broadcast_seed(1234 + 77 * bootstrap.process_index()) == 1234

import jax.numpy as jnp
import optax
from distributed_embeddings_tpu.parallel import (
    DistributedEmbedding, SparseSGD, init_hybrid_state, make_hybrid_train_step)

mesh = bootstrap.global_mesh()
cfgs = [{"input_dim": 48 + 8 * i, "output_dim": 8 if i % 2 else 16}
        for i in range(10)]
de = DistributedEmbedding(cfgs, world_size=8, strategy="memory_balanced")

GB = 32  # global batch
rng = np.random.default_rng(0)  # same on every process
cats_np = [rng.integers(0, c["input_dim"], size=(GB,)).astype(np.int32)
           for c in cfgs]
num_np = rng.normal(size=(GB, 4)).astype(np.float32)
lab_np = rng.integers(0, 2, size=(GB, 1)).astype(np.float32)

import flax.linen as nn
class Head(nn.Module):
    @nn.compact
    def __call__(self, num, embs):
        x = jnp.concatenate(
            [e.reshape(e.shape[0], -1) for e in embs] + [num], axis=1)
        return nn.Dense(1)(x)
head = Head()
dense_params = head.init(
    jax.random.key(0), jnp.asarray(num_np[:2]),
    [jnp.zeros((2, c["output_dim"])) for c in cfgs])

def loss_fn(dp, emb_outs, batch):
    n, y = batch
    return jnp.mean((head.apply(dp, n, emb_outs) - y) ** 2)

tx = optax.sgd(0.1)
emb_opt = SparseSGD()
state = init_hybrid_state(de, emb_opt, dense_params, tx,
                          jax.random.key(1), mesh=mesh)
step = make_hybrid_train_step(de, loss_fn, tx, emb_opt, mesh=mesh,
                              lr_schedule=0.1)

# each process feeds its local rows; shard_batch assembles the global arrays
lo, hi = (GB // nproc) * pid, (GB // nproc) * (pid + 1)
cats = [bootstrap.shard_batch(mesh, c[lo:hi]) for c in cats_np]
batch = bootstrap.shard_batch(mesh, (num_np[lo:hi], lab_np[lo:hi]))

for _ in range(3):
    loss, state = step(state, cats, batch)

tables = de.get_weights(state.emb_params, chunk_elems=256)
digest = [float(np.asarray(t, np.float64).sum()) for t in tables]

# cross-process serialized reload (reference use_lock broadcast_object
# parity): every process takes a barrier-gated turn; must neither deadlock
# nor corrupt the values
params2 = de.set_weights(tables, mesh=mesh, use_lock=True, chunk_elems=256)
tables2 = de.get_weights(params2, chunk_elems=256)
digest2 = [float(np.asarray(t, np.float64).sum()) for t in tables2]
print("RESULT " + json.dumps({
    "pid": pid, "loss": float(loss), "digest": digest,
    "digest2": digest2}))
"""


def _run_cluster(nproc, port):
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
        + env.get("PYTHONPATH", "").split(os.pathsep))
    procs = [subprocess.Popen(
        [sys.executable, "-c", _WORKER, str(i), str(port), str(nproc)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for i in range(nproc)]
    results = []
    for p in procs:
        out, err = p.communicate(timeout=600)
        assert p.returncode == 0, f"worker failed:\n{err[-4000:]}"
        line = [ln for ln in out.splitlines() if ln.startswith("RESULT ")][-1]
        results.append(json.loads(line[len("RESULT "):]))
    return results


@pytest.mark.slow
def test_two_process_train_and_checkpoint():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    res = _run_cluster(2, port)

    # both processes agree on loss and on the reassembled tables
    assert res[0]["loss"] == pytest.approx(res[1]["loss"], rel=1e-6)
    np.testing.assert_allclose(res[0]["digest"], res[1]["digest"], rtol=1e-6)
    # the lock-serialized reload round-trips on both processes
    for r in res:
        np.testing.assert_allclose(r["digest2"], r["digest"], rtol=1e-6)

    # and the 2-process run matches a single-process oracle bit-for-bit
    # (same seeds, same global batch, same mesh size)
    oracle = _run_cluster(1, 0)[0]
    assert oracle["loss"] == pytest.approx(res[0]["loss"], rel=1e-5)
    np.testing.assert_allclose(oracle["digest"], res[0]["digest"], rtol=1e-5)

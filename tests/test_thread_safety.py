"""Regression tests for the races the concurrency auditor flagged.

Each test here pins a specific dogfood fix from the lock-discipline
audit (analysis/concurrency_audit.py) at runtime — the static gate
proves the guard EXISTS, these prove it does what the finding said was
broken without it:

* ServingRuntime outcome counters were bare dict ``+=`` (a lost-update
  read-modify-write) bumped from the driver, trainer, and exporter
  threads — now ``_count()`` under the state RLock;
* ``install_snapshot``'s version check-then-act and the published-triple
  swap raced concurrent publishers — now one atom under the lock;
* Supervisor caller-side mutators (``note_train_step`` /
  ``set_freshness_slo``) write fields the monitor thread reads on the
  crash path — now locked, with the queue put outside the lock (the
  blocking-under-lock rule);
* ``obs.install_compile_listener``'s idempotence flag was an unlocked
  check-then-act: two racing callers could both register, double-
  counting every recompile forever — now under ``_compile_lock``.

All hammer tests use barriers so every thread is actually in the
critical region together; counts are exact, not statistical.
"""

import threading

import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_embeddings_tpu.parallel import (
    DistributedEmbedding, ServeConfig, ServingRuntime, SparseSGD,
    init_hybrid_state)
from distributed_embeddings_tpu.parallel.supervisor import Supervisor
from distributed_embeddings_tpu.utils import obs

import jax


def _pred_fn(dp, outs, batch):
    p = sum(jnp.sum(o, -1) for o in outs)
    if batch is not None:
        p = p + jnp.sum(batch, -1)
    return p


@pytest.fixture(scope="module")
def runtime_factory():
    """One cheap world-1 embedding/state pair shared by the module; each
    test gets a fresh runtime over it (counters start at zero)."""
    configs = [{"input_dim": 40, "output_dim": 4}]
    de = DistributedEmbedding(configs, world_size=1)
    tx = optax.sgd(0.1)
    state = init_hybrid_state(de, SparseSGD(), {"w": jnp.ones((4, 1))},
                              tx, jax.random.key(0))

    def make():
        return state, ServingRuntime(
            de, _pred_fn, state, config=ServeConfig(max_batch=8))

    return make


def _hammer(n_threads, fn):
    """Run fn(i) on n_threads, all released together; re-raise the
    first worker exception."""
    barrier = threading.Barrier(n_threads)
    errors = []

    def work(i):
        barrier.wait()
        try:
            fn(i)
        except BaseException as e:  # noqa: BLE001 - surfaced below
            errors.append(e)

    ts = [threading.Thread(target=work, args=(i,))
          for i in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert not any(t.is_alive() for t in ts)
    return errors


def test_serving_counters_lose_no_increments(runtime_factory):
    """8 threads x 2000 bumps must land exactly — the lost-update
    regression (unguarded `self._counts[k] += 1`)."""
    _, rt = runtime_factory()
    per = 2000

    def bump(i):
        for _ in range(per):
            rt._count("served")
            rt._count("served_samples", 3)

    assert _hammer(8, bump) == []
    assert rt._counts["served"] == 8 * per
    assert rt._counts["served_samples"] == 8 * per * 3


def test_install_snapshot_version_check_is_atomic(runtime_factory):
    """8 publishers racing the SAME version: exactly one wins, the rest
    get the monotonicity ValueError — without the lock the check-then-
    act admits several and the installed count drifts."""
    state, rt = runtime_factory()
    wins, losses = [], []

    def publish(i):
        try:
            rt.install_snapshot(state, version=1, train_step=0, now=0.0)
            wins.append(i)
        except ValueError:
            losses.append(i)

    assert _hammer(8, publish) == []
    assert len(wins) == 1 and len(losses) == 7
    assert rt._counts["snapshots_installed"] == 1
    assert rt._published[2][0] == 1


def test_publisher_vs_freshness_notes_stay_consistent(runtime_factory):
    """One thread publishes monotone snapshots while another advances
    the trainer's step note: no exception, the final freshness view is
    the newest of both writers (not a torn mix)."""
    state, rt = runtime_factory()
    n = 200

    def run(i):
        if i == 0:
            for v in range(1, n + 1):
                rt.install_snapshot(state, version=v, train_step=v,
                                    now=float(v))
        else:
            for s in range(1, n + 1):
                rt.note_train_step(s, now=float(s))

    assert _hammer(2, run) == []
    assert rt._counts["snapshots_installed"] == n
    assert rt._published[2][:2] == (n, n)
    # latest_train_step is whichever writer ran last — but never behind
    # the installed snapshot's step and never past n
    assert n == rt._latest_train_step


def test_supervisor_caller_mutators_are_locked():
    """note_train_step / set_freshness_slo from many caller threads:
    every message reaches the send queue (the worker's view) and the
    retained fields (what a restart re-pushes) hold a written value."""
    sup = Supervisor("tools.isolation_common:worker_factory")
    try:
        per = 200

        def drive(i):
            for s in range(per):
                sup.note_train_step(i * per + s)
                sup.set_freshness_slo(steps=float(i), seconds=None)

        assert _hammer(4, drive) == []
        msgs = []
        while not sup._send_q.empty():
            msgs.append(sup._send_q.get_nowait())
        assert len(msgs) == 4 * per * 2  # nothing lost, nothing doubled
        assert sup._last_train_step in {i * per + (per - 1)
                                        for i in range(4)}
        assert sup._slo in {(float(i), None) for i in range(4)}
    finally:
        sup.close()


def test_compile_listener_registers_exactly_once(monkeypatch):
    """16 racing installers, one registration — the check-then-act now
    holds _compile_lock, so recompiles can never double-count."""
    jm = pytest.importorskip("jax.monitoring")
    registered = []
    monkeypatch.setattr(jm, "register_event_duration_secs_listener",
                        registered.append)
    monkeypatch.setattr(obs, "_compile_listener_installed", False)

    results = []
    assert _hammer(
        16, lambda i: results.append(obs.install_compile_listener())) == []
    assert results == [True] * 16
    assert len(registered) == 1
    assert obs._compile_listener_installed

"""Elastic topology resume: plan-aware checkpoint re-sharding
(utils/checkpoint.py codec + tools/reshard.py + the resilient driver's
mesh-shrink path) and the telemetry_balanced planner.

The format invariant under test: checkpoints hold full LOGICAL tables, so
a rewrite across world sizes (8->4, 4->8) and plan kinds (table-parallel
<-> row-sliced <-> column-sliced) is byte-identical on the table data and
A -> B -> A round-trips bit for bit — params AND sparse-optimizer state
(SparseAdam exercises slab components plus the plan-dependent aux step
counts).

Also here: the cross-world-size SGD equivalence probe (ROADMAP item 1
diagnostic) — the sparse path's 1/world mp-gradient scale convention must
make world=1 and world=8 produce matching updates, or every elastic-resume
equivalence claim is void.
"""

import filecmp
import glob
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_embeddings_tpu.parallel import (
    DistributedEmbedding, HybridTrainState, SparseAdam, SparseSGD,
    init_hybrid_state, make_hybrid_train_step, run_resilient)
from distributed_embeddings_tpu.parallel.strategy import (
    DistEmbeddingStrategy, plans_equal)
from distributed_embeddings_tpu.utils import (
    obs, restore_train_state, runtime, save_train_state)
from distributed_embeddings_tpu.utils.checkpoint import reshard_checkpoint
from distributed_embeddings_tpu.analysis.telemetry import (
    table_loads_from_summary)

WORLD = 8
B = 16
CONFIGS = [{"input_dim": 20 + 4 * i, "output_dim": 4 if i % 2 else 16}
           for i in range(8)]
COLS = sum(c["output_dim"] for c in CONFIGS)


def _data(seed):
    rng = np.random.default_rng(seed)
    cats = [jnp.asarray(rng.integers(0, c["input_dim"], size=(B,)),
                        jnp.int32) for c in CONFIGS]
    y = jnp.asarray(rng.normal(size=(B, 1)) * 0.1, jnp.float32)
    return cats, y


def _loss_fn(dp, emb_outs, batch):
    x = jnp.concatenate([e.reshape(e.shape[0], -1) for e in emb_outs],
                        axis=1)
    return jnp.mean((x @ dp["w"] - batch) ** 2)


def _dp():
    return {"w": jnp.full((COLS, 1), 0.1, jnp.float32)}


@pytest.fixture(scope="module")
def mesh8():
    return Mesh(np.array(jax.devices()[:WORLD]), ("data",))


@pytest.fixture(scope="module")
def mesh4():
    return Mesh(np.array(jax.devices()[:4]), ("data",))


@pytest.fixture(scope="module")
def de4():
    return DistributedEmbedding(CONFIGS, world_size=4, strategy="basic")


@pytest.fixture(scope="module")
def step4(de4, mesh4):
    return make_hybrid_train_step(de4, _loss_fn, optax.sgd(0.1),
                                  SparseAdam(), mesh=mesh4,
                                  lr_schedule=0.3, with_metrics=False)


@pytest.fixture(scope="module")
def ck8(tmp_path_factory, mesh8):
    """A checkpoint written on the 8-rank topology after 2 Adam steps,
    plus the logical tables it holds (the cross-plan ground truth)."""
    de = DistributedEmbedding(CONFIGS, world_size=WORLD,
                              strategy="memory_balanced")
    emb_opt, tx = SparseAdam(), optax.sgd(0.1)
    st = init_hybrid_state(de, emb_opt, _dp(), tx, jax.random.key(1),
                           mesh=mesh8)
    step = make_hybrid_train_step(de, _loss_fn, tx, emb_opt, mesh=mesh8,
                                  lr_schedule=0.3, with_metrics=False)
    cats, y = _data(0)
    y8 = jax.device_put(y, NamedSharding(mesh8, P("data")))
    for _ in range(2):
        _, st = step(st, cats, y8)
    path = str(tmp_path_factory.mktemp("elastic") / "ck8")
    save_train_state(path, de, st)
    tables = de.get_weights(st.emb_params)
    return {"path": path, "de": de, "tables": tables,
            "emb_opt": emb_opt, "tx": tx}


def _tables_equal(a, b):
    return all(np.array_equal(x, y) for x, y in zip(a, b))


# ------------------------------------------------------ mismatch policies


def test_plan_recorded_and_default_mismatch_raises(ck8, de4, mesh4):
    with open(os.path.join(ck8["path"], "meta.json")) as f:
        meta = json.load(f)
    assert meta["plan"]["world_size"] == WORLD
    assert plans_equal(meta["plan"], ck8["de"].strategy.plan_spec())
    with pytest.raises(runtime.CheckpointMismatch, match="reshard"):
        restore_train_state(ck8["path"], de4, ck8["emb_opt"], _dp(),
                            ck8["tx"], mesh=mesh4)


def test_same_plan_restore_unaffected(ck8, mesh8):
    """A matching topology restores under the strict default — the
    elastic machinery must not tax the common path."""
    de2 = DistributedEmbedding(CONFIGS, world_size=WORLD,
                               strategy="memory_balanced")
    st = restore_train_state(ck8["path"], de2, ck8["emb_opt"], _dp(),
                             ck8["tx"], mesh=mesh8)
    assert _tables_equal(ck8["tables"], de2.get_weights(st.emb_params))


def test_online_reshard_8_to_4(ck8, de4, mesh4, step4):
    obs.drain_events()  # isolate
    st = restore_train_state(ck8["path"], de4, ck8["emb_opt"], _dp(),
                             ck8["tx"], mesh=mesh4, on_mismatch="reshard")
    assert int(st.step) == 2
    assert _tables_equal(ck8["tables"], de4.get_weights(st.emb_params))
    # degradation recorded: old plan, new plan, per-rank byte deltas
    (ev,) = obs.drain_events("checkpoint_reshard")
    assert ev["diff"]["world_size"] == [8, 4]
    assert len(ev["diff"]["per_rank_byte_deltas"]) == 4
    assert ev["old_plan"]["world_size"] == 8
    assert ev["new_plan"]["world_size"] == 4
    # the re-sharded optimizer state must be USABLE, not just shaped:
    # one more Adam step on the shrunken mesh
    cats, y = _data(0)
    y4 = jax.device_put(y, NamedSharding(mesh4, P("data")))
    _, st = step4(st, cats, y4)
    assert int(st.step) == 3


def test_online_reshard_to_column_sliced_rekeys_aux(ck8, mesh8):
    """Column slicing changes the WIDTH SET (w16 tables split into w8
    slices), so Adam's per-width aux step counts have no saved twin —
    the codec rebuilds them from the saved consensus."""
    de_cs = DistributedEmbedding(CONFIGS, world_size=WORLD,
                                 strategy="basic",
                                 column_slice_threshold=300)
    assert de_cs.widths != ck8["de"].widths  # the premise
    st = restore_train_state(ck8["path"], de_cs, ck8["emb_opt"], _dp(),
                             ck8["tx"], mesh=mesh8, on_mismatch="reshard")
    assert _tables_equal(ck8["tables"], de_cs.get_weights(st.emb_params))
    for wkey, (_, _, count) in st.emb_opt_state.items():
        np.testing.assert_array_equal(
            np.asarray(count).reshape(-1), 2.0,
            err_msg=f"Adam step count lost across re-key ({wkey})")
    obs.drain_events("checkpoint_reshard")


# ------------------------------------------------- offline codec round trip


def test_offline_roundtrip_bitwise(ck8, tmp_path):
    """A(8, memory_balanced) -> B(4, row-sliced) -> A'(original plan):
    every table and optimizer-state array byte-identical, plan manifest
    restored."""
    ckb = str(tmp_path / "ckB")
    cka2 = str(tmp_path / "ckA2")
    target_b = DistEmbeddingStrategy(CONFIGS, 4, strategy="basic",
                                     row_slice_threshold=120)
    diff = reshard_checkpoint(ck8["path"], ckb, target_b)
    assert diff["world_size"] == [8, 4]
    reshard_checkpoint(ckb, cka2, ck8["de"])  # accepts a DistributedEmbedding
    for f in sorted(glob.glob(os.path.join(ck8["path"], "tables", "*.npy"))
                    + glob.glob(os.path.join(ck8["path"], "emb_opt", "*",
                                             "*.npy"))
                    + [os.path.join(ck8["path"], "dense.msgpack")]):
        f2 = f.replace(ck8["path"], cka2)
        assert filecmp.cmp(f, f2, shallow=False), f
    with open(os.path.join(cka2, "meta.json")) as f:
        meta2 = json.load(f)
    assert plans_equal(meta2["plan"], ck8["de"].strategy.plan_spec())
    # aux arrays (npz re-written, not byte-copied) equal at the array level
    with np.load(os.path.join(ck8["path"], "emb_opt", "state2.npz")) as a, \
            np.load(os.path.join(cka2, "emb_opt", "state2.npz")) as b:
        assert sorted(a.files) == sorted(b.files)
        for k in a.files:
            np.testing.assert_array_equal(a[k], b[k])


def test_offline_reshard_restores_cleanly(ck8, de4, mesh4, tmp_path):
    """4->8 grow: a checkpoint re-sharded offline restores under the
    strict default policy (its plan now MATCHES), and the grow direction
    reproduces the same logical tables."""
    ck4 = str(tmp_path / "ck4")
    reshard_checkpoint(ck8["path"], ck4,
                       DistEmbeddingStrategy(CONFIGS, 4, strategy="basic"))
    st = restore_train_state(ck4, de4, ck8["emb_opt"], _dp(), ck8["tx"],
                             mesh=mesh4)  # on_mismatch default: no raise
    assert _tables_equal(ck8["tables"], de4.get_weights(st.emb_params))


def test_offline_dry_run_writes_nothing(ck8, tmp_path):
    dst = str(tmp_path / "never")
    diff = reshard_checkpoint(
        ck8["path"], dst, DistEmbeddingStrategy(CONFIGS, 4),
        dry_run=True)
    assert not os.path.exists(dst)
    assert diff["world_size"] == [8, 4]
    assert diff["per_rank_bytes_new"] and diff["per_rank_byte_deltas"]


def test_reshard_rejects_more_ranks_than_tables(ck8, tmp_path):
    """A 16-rank plan over 8 tables would write a checkpoint no
    DistributedEmbedding could ever load (fewer tables than mesh
    positions is unsupported) — reject it up front."""
    with pytest.raises(ValueError, match="fewer tables"):
        reshard_checkpoint(ck8["path"], str(tmp_path / "x"),
                           DistEmbeddingStrategy(CONFIGS, 16))


def test_reshard_rejects_wrong_model(ck8, tmp_path):
    other = [{"input_dim": 10, "output_dim": 4} for _ in range(3)]
    with pytest.raises(runtime.CheckpointMismatch, match="never the model"):
        reshard_checkpoint(ck8["path"], str(tmp_path / "x"),
                           DistEmbeddingStrategy(other, 2))


def test_reshard_cli(ck8, tmp_path, capsys):
    from tools import reshard as cli

    dst = str(tmp_path / "cli_out")
    assert cli.main([ck8["path"], dst, "--world-size", "4",
                     "--dry-run"]) == 0
    assert not os.path.exists(dst)
    assert cli.main([ck8["path"], dst, "--world-size", "4",
                     "--strategy", "memory_balanced"]) == 0
    out = capsys.readouterr().out
    assert "world 8 -> 4" in out
    assert os.path.isfile(os.path.join(dst, "meta.json"))
    # corrupt source -> clean nonzero exit, no traceback
    bad = str(tmp_path / "bad_src")
    os.makedirs(bad)
    assert cli.main([bad, str(tmp_path / "y"), "--world-size", "2"]) == 1


# ------------------------------------------------ telemetry-driven plans


def test_telemetry_balanced_planner_spreads_hot_tables():
    loads = [1000.0, 1.0, 1.0, 990.0, 1.0, 1.0, 1.0, 980.0]
    s = DistEmbeddingStrategy(CONFIGS, 4, strategy="telemetry_balanced",
                              table_loads=loads)
    per_rank = [sum(loads[t] for t in tids) for tids in s.table_ids_list]
    imbalance = max(per_rank) / (sum(per_rank) / 4)
    base = DistEmbeddingStrategy(CONFIGS, 4, strategy="basic")
    base_rank = [sum(loads[t] for t in tids) for tids in base.table_ids_list]
    base_imb = max(base_rank) / (sum(base_rank) / 4)
    # the three hot tables land on three different ranks
    owners = {r for r, tids in enumerate(s.table_ids_list)
              for t in tids if t in (0, 3, 7)}
    assert len(owners) == 3
    assert imbalance < base_imb
    with pytest.raises(ValueError, match="table_loads"):
        DistEmbeddingStrategy(CONFIGS, 4, strategy="telemetry_balanced")


def test_table_loads_from_summary_and_cli_feed(ck8, tmp_path, capsys):
    summary = {"tables": [
        {"table_id": 0, "top_rows": [[1, 500], [2, 300]]},
        {"table_id": 5, "top_rows": [[0, 900]]},
    ]}
    loads = table_loads_from_summary(summary, len(CONFIGS))
    assert loads[0] == 800.0 and loads[5] == 900.0
    assert sum(loads) == 1700.0
    tel = str(tmp_path / "tel.json")
    with open(tel, "w") as f:
        json.dump(summary, f)
    from tools import reshard as cli

    dst = str(tmp_path / "bal")
    assert cli.main([ck8["path"], dst, "--world-size", "4",
                     "--strategy", "telemetry_balanced",
                     "--telemetry", tel]) == 0
    with open(os.path.join(dst, "meta.json")) as f:
        plan = json.load(f)["plan"]
    assert plan["strategy"] == "telemetry_balanced"
    # the two hot tables must sit on different ranks
    owners = {r for r, tids in enumerate(plan["table_ids_list"])
              for t in tids if t in (0, 5)}
    assert len(owners) == 2
    # missing summary is a usage error, not a stack trace
    assert cli.main([ck8["path"], str(tmp_path / "z"), "--world-size", "4",
                     "--strategy", "telemetry_balanced"]) == 2
    capsys.readouterr()


# --------------------------------------- cross-world trajectory equivalence


def test_sgd_cross_world_equivalence(mesh8):
    """ROADMAP item 1 diagnostic: the suspected 1/world mp-gradient scale
    defect. Same tables, same GLOBAL batches, SparseSGD: world=1 and
    world=8 must produce matching updates — the sparse path's 1/world
    pre-scale (``sparse_apply_gradients``) exactly cancels the
    world-times-larger local-mean cotangents under the pmean-averaged
    loss convention. A failure here would invalidate every cross-topology
    resume equivalence this suite claims."""
    rng = np.random.default_rng(11)
    tables0 = [np.asarray(rng.normal(size=(c["input_dim"],
                                           c["output_dim"])) * 0.1,
                          np.float32) for c in CONFIGS]

    def run(world, mesh):
        de = DistributedEmbedding(CONFIGS, world_size=world)
        emb_opt, tx = SparseSGD(), optax.sgd(0.2)
        emb_params = de.set_weights([t.copy() for t in tables0], mesh=mesh)
        dp = _dp()
        st = HybridTrainState(
            emb_params=emb_params, emb_opt_state=emb_opt.init(emb_params),
            dense_params=dp, dense_opt_state=tx.init(dp),
            step=jnp.zeros((), jnp.int32))
        step = make_hybrid_train_step(de, _loss_fn, tx, emb_opt, mesh=mesh,
                                      lr_schedule=0.3, with_metrics=False)
        for i in range(3):
            cats, y = _data(100 + i)
            if mesh is not None:
                y = jax.device_put(y, NamedSharding(mesh, P("data")))
            _, st = step(st, cats, y)
        return de.get_weights(st.emb_params), np.asarray(
            st.dense_params["w"])

    t1, w1 = run(1, None)
    t8, w8 = run(8, mesh8)
    np.testing.assert_allclose(w1, w8, rtol=1e-5, atol=1e-7)
    for i, (a, b) in enumerate(zip(t1, t8)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7,
                                   err_msg=f"table {i}: world=1 vs "
                                           "world=8 SGD updates diverge")


def test_preempt_resume_smaller_mesh_matches_uninterrupted(
        ck8, de4, mesh4, mesh8, step4, tmp_path, monkeypatch):
    """The mesh-shrink acceptance path, in process: preempt an 8-rank
    resilient run at step 2 (real self-SIGTERM), auto-resume it on 4
    ranks (driver re-shards in place, logs the degradation), and require
    the final LOGICAL state to match the uninterrupted 8-rank run."""
    de8, emb_opt, tx = ck8["de"], SparseSGD(), optax.sgd(0.1)
    step8 = make_hybrid_train_step(de8, _loss_fn, tx, emb_opt, mesh=mesh8,
                                   lr_schedule=0.2, with_metrics=False)
    N = 6

    def data_for(mesh):
        def factory(start):
            for i in range(start, N):
                cats, y = _data(200 + i)
                if mesh is not None:
                    y = jax.device_put(y, NamedSharding(mesh, P("data")))
                yield cats, y
        return factory

    def init8():
        return init_hybrid_state(de8, emb_opt, _dp(), tx,
                                 jax.random.key(3), mesh=mesh8)

    # uninterrupted 8-rank reference
    ref = init8()
    for item in data_for(mesh8)(0):
        _, ref = step8(ref, *item)
    ref_tables = de8.get_weights(ref.emb_params)

    ck = str(tmp_path / "shrink")
    monkeypatch.setenv("DETPU_FAULT", "preempt@2")
    r1 = run_resilient(step8, init8(), data_for(mesh8), de=de8,
                       checkpoint_dir=ck, checkpoint_every_steps=2,
                       resume=True, emb_optimizer=emb_opt, dense_tx=tx,
                       mesh=mesh8)
    monkeypatch.delenv("DETPU_FAULT")
    assert r1.preempted and r1.stop_reason == "preempted"
    assert os.path.exists(ck + ".resume.json")

    # resume on the SHRUNKEN mesh — no manual intervention, just a
    # 4-rank de/mesh; the sgd step4 fixture is Adam, so build SGD's
    logger = obs.MetricsLogger(str(tmp_path / "m.jsonl"))
    step4s = make_hybrid_train_step(de4, _loss_fn, tx, emb_opt, mesh=mesh4,
                                    lr_schedule=0.2, with_metrics=False)
    st4 = init_hybrid_state(de4, emb_opt, _dp(), tx, jax.random.key(4),
                            mesh=mesh4)
    r2 = run_resilient(step4s, st4, data_for(mesh4), de=de4,
                       checkpoint_dir=ck, checkpoint_every_steps=2,
                       resume=True, emb_optimizer=emb_opt, dense_tx=tx,
                       mesh=mesh4, metrics_logger=logger)
    assert r2.step == N and not r2.preempted
    assert r2.steps_run == N - r1.step  # no batch replayed or skipped
    got_tables = de4.get_weights(r2.state.emb_params)
    for i, (a, b) in enumerate(zip(ref_tables, got_tables)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6,
                                   err_msg=f"table {i}")
    # the degradation is in the metrics log
    recs = obs.MetricsLogger.load(str(tmp_path / "m.jsonl"))
    reshard = [r for r in recs if r.get("section") == "checkpoint_reshard"]
    assert reshard and reshard[0]["diff"]["world_size"] == [8, 4]

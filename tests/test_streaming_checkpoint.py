"""Dynamic-table state is part of the recoverable trajectory: checkpoint
round-trips (bitwise), offline re-shard round-trips (8→4→8), the
generalized aux rewind, and rollback-after-eviction CRC-identity on the
8-virtual-device mesh.

The satellite contracts under test:

* ``save_train_state(aux_states=)`` persists the slot map + sketch
  CRC-manifested inside the checkpoint; ``load_aux_state`` +
  ``streaming.decode_state`` reproduce the carried state bitwise;
* ``tools/reshard.py``'s codec moves the (plan-agnostic) aux file
  byte-identically: 8→4→8 restores bitwise;
* rollback-and-replay rewinds the slot map with the ring exactly like
  the params (the "other jit-carried aux state is silently kept" fix):
  a streaming run that hits a NaN storm AFTER evictions recovers to a
  final checkpoint CRC-identical to the stream-minus-poison run.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from jax.sharding import Mesh

from distributed_embeddings_tpu.parallel import (
    DistributedEmbedding, SparseAdagrad, StreamingConfig,
    init_hybrid_state, init_streaming, make_hybrid_train_step,
    run_resilient)
from distributed_embeddings_tpu.parallel import streaming as smod
from distributed_embeddings_tpu.utils.checkpoint import (
    load_aux_state, reshard_checkpoint, restore_train_state,
    save_train_state, verify_checkpoint)


SCFG = StreamingConfig(admit_min_count=2, evict_margin=1, depth=2,
                       buckets=64)


def _configs(n_static=7, dim=8):
    cfgs = [{"input_dim": 24 + 3 * i, "output_dim": dim}
            for i in range(n_static)]
    cfgs.append({"input_dim": 64 + 8, "output_dim": dim,
                 "streaming": {"capacity": 64, "buckets": 8}})
    return cfgs


def _build(world, mesh=None, seed=0):
    cfgs = _configs()
    de = DistributedEmbedding(cfgs, world_size=world)
    emb_opt = SparseAdagrad()
    tx = optax.sgd(0.1)
    state = init_hybrid_state(de, emb_opt,
                              {"w": jnp.ones((4, 1), jnp.float32)}, tx,
                              jax.random.key(seed), mesh=mesh)

    def loss_fn(dp, outs, batch):
        return sum(batch[:, i % 2].mean() * jnp.mean(o)
                   for i, o in enumerate(outs)) * jnp.mean(dp["w"])

    step = make_hybrid_train_step(de, loss_fn, tx, emb_opt, mesh=mesh,
                                  with_metrics=True, nan_guard=True,
                                  dynamic=SCFG)
    return de, emb_opt, tx, state, step


def _batch(de, i, world):
    rng = np.random.default_rng(300 + i)
    B = 2 * world
    cats = []
    for cfg in de.strategy.global_configs:
        if cfg.get("streaming"):
            cats.append(jnp.asarray(
                rng.integers(i, i + 5, B) * 13 + 10**7, jnp.int32))
        else:
            cats.append(jnp.asarray(
                rng.integers(0, cfg["input_dim"], B), jnp.int32))
    return cats, jnp.asarray(rng.normal(size=(B, 2)), jnp.float32)


def _run_steps(de, state, step, sstate, n, world, start=0):
    for i in range(start, start + n):
        cats, b = _batch(de, i, world)
        _, state, _, sstate = step(state, cats, b, sstate)
    return state, sstate


def _bitwise(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(la, lb))


def test_save_restore_roundtrip_bitwise(tmp_path):
    de, emb_opt, tx, state, step = _build(1)
    sstate = init_streaming(de, SCFG)
    state, sstate = _run_steps(de, state, step, sstate, 5, 1)
    ck = str(tmp_path / "ck")
    enc = smod.encode_state(de, sstate)
    save_train_state(ck, de, state, aux_states={"streaming": enc})
    meta = verify_checkpoint(ck)  # aux file is CRC-manifested
    assert "aux/streaming.npz" in meta["files"]
    assert meta["aux_states"] == ["streaming"]
    restored = restore_train_state(ck, de, emb_opt, state.dense_params,
                                   tx)
    dec = smod.decode_state(de, init_streaming(de, SCFG),
                            load_aux_state(ck, "streaming"))
    assert _bitwise(sstate, dec)
    # logical content is bitwise: a re-save of the restored state (slab
    # alignment padding differs in memory, never in a checkpoint)
    # reproduces every file CRC
    ck2 = str(tmp_path / "ck2")
    save_train_state(ck2, de, restored,
                     aux_states={"streaming": smod.encode_state(de, dec)})
    assert (verify_checkpoint(ck)["files"]
            == verify_checkpoint(ck2)["files"])


def test_missing_aux_decodes_to_pristine_state(tmp_path):
    de, emb_opt, tx, state, step = _build(1)
    sstate = init_streaming(de, SCFG)
    state, sstate = _run_steps(de, state, step, sstate, 3, 1)
    ck = str(tmp_path / "ck")
    save_train_state(ck, de, state)  # pre-streaming-era checkpoint
    assert load_aux_state(ck, "streaming") is None
    dec = smod.decode_state(de, sstate, None)
    assert _bitwise(dec, smod.fresh_like(sstate))


def test_torn_head_resumes_aux_from_prev_generation(tmp_path):
    """When the head checkpoint is torn and restore falls back to
    ``<dir>.prev``, the streaming aux must come from the SAME (.prev)
    generation the params did — loading the newer head's slot map onto
    older tables would splice two trajectories."""
    de, emb_opt, tx, state, step = _build(1)
    sstate = init_streaming(de, SCFG)
    ck = str(tmp_path / "ck")
    # two generations with DIFFERENT slot-map contents
    state, sstate = _run_steps(de, state, step, sstate, 2, 1)
    save_train_state(ck, de, state,
                     aux_states={"streaming": smod.encode_state(
                         de, sstate)})
    prev_enc = smod.encode_state(de, sstate)
    state, sstate = _run_steps(de, state, step, sstate, 3, 1, start=2)
    save_train_state(ck, de, state,
                     aux_states={"streaming": smod.encode_state(
                         de, sstate)})
    # tear the head's first table shard (CRC catches it)
    target = os.path.join(ck, "tables", "table_000.npy")
    with open(target, "r+b") as f:
        f.seek(os.path.getsize(target) // 2)
        byte = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([byte[0] ^ 0xFF]))

    def data(start):
        return iter(())  # restore only; no further steps

    r = run_resilient(step, _build(1)[3], data, de=de, checkpoint_dir=ck,
                      resume=True, emb_optimizer=emb_opt, dense_tx=tx,
                      streaming_state=init_streaming(de, SCFG),
                      save_on_exit=False, metrics_interval=0)
    assert r.step == 2  # params came from .prev (step 2), not the head
    prev_dec = smod.decode_state(de, init_streaming(de, SCFG), prev_enc)
    assert _bitwise(r.streaming, prev_dec)


def test_reshard_8_4_8_roundtrip_bitwise(tmp_path):
    mesh = Mesh(np.array(jax.devices()[:8]), ("data",))
    de8, emb_opt, tx, state, step = _build(8, mesh=mesh)
    sstate = init_streaming(de8, SCFG, mesh=mesh)
    state, sstate = _run_steps(de8, state, step, sstate, 4, 8)
    ck8 = str(tmp_path / "ck8")
    save_train_state(ck8, de8, state,
                     aux_states={"streaming": smod.encode_state(
                         de8, sstate)})

    de4 = DistributedEmbedding(_configs(), world_size=4)
    ck4 = str(tmp_path / "ck4")
    reshard_checkpoint(ck8, ck4, de4)
    # the aux file is plan-agnostic: byte-identical through the rewrite
    assert (verify_checkpoint(ck8)["files"]["aux/streaming.npz"]
            == verify_checkpoint(ck4)["files"]["aux/streaming.npz"])
    # restoring at world 4: slot maps carry over, per-rank sketch resets
    dec4 = smod.decode_state(de4, init_streaming(de4, SCFG),
                             load_aux_state(ck4, "streaming"))
    occ8 = smod.occupancy(de8, sstate)
    occ4 = smod.occupancy(de4, dec4)
    assert [t["occupied"] for t in occ4["tables"]] \
        == [t["occupied"] for t in occ8["tables"]]
    assert occ4["steps"] == 0  # world changed: counters/sketch warm up

    ck8b = str(tmp_path / "ck8b")
    reshard_checkpoint(ck4, ck8b, de8)
    dec8 = smod.decode_state(de8, init_streaming(de8, SCFG, mesh=mesh),
                             load_aux_state(ck8b, "streaming"))
    # back on the original topology the FULL state (sketch included)
    # reproduces bitwise
    assert _bitwise(sstate, dec8)
    restored = restore_train_state(ck8b, de8, emb_opt,
                                   state.dense_params, tx, mesh=mesh)
    # logical content bitwise: re-saving the restored state reproduces
    # the original manifest (in-memory slab padding legitimately differs)
    ck8c = str(tmp_path / "ck8c")
    save_train_state(ck8c, de8, restored,
                     aux_states={"streaming": smod.encode_state(
                         de8, dec8)})
    assert (verify_checkpoint(ck8)["files"]
            == verify_checkpoint(ck8c)["files"])


def test_rollback_after_eviction_crc_identity(tmp_path):
    """The mesh NaN-storm drill with a live slot map: the chaos run
    rolls back to a ring checkpoint (rewinding the slot map from the
    SAME candidate — the generalized aux rewind), quarantines the
    poison, and ends CRC-identical (aux/streaming.npz included) to the
    clean run trained on the stream with the poisoned batch removed."""
    mesh = Mesh(np.array(jax.devices()[:8]), ("data",))
    BAD, STEPS = 5, 10

    def child(ckpt, drop=(), poison=None):
        de, emb_opt, tx, state, step = _build(8, mesh=mesh)
        sstate = init_streaming(de, SCFG, mesh=mesh)

        def data(start):
            idx = [i for i in range(STEPS) if i not in drop]
            for i in idx[start:]:
                cats, b = _batch(de, i, 8)
                if poison is not None and i == poison:
                    b = b.at[0, 0].set(np.nan)
                yield cats, b

        r = run_resilient(step, state, data, de=de, checkpoint_dir=ckpt,
                          checkpoint_every_steps=2, resume=True,
                          emb_optimizer=emb_opt, dense_tx=tx, mesh=mesh,
                          streaming_state=sstate, escalate_after=1,
                          keep_last_n=2, metrics_interval=0)
        return r

    chaos = str(tmp_path / "chaos")
    r1 = child(chaos, poison=BAD)
    assert r1.rollbacks == 1 and r1.quarantined == (BAD,)
    assert r1.step == STEPS - 1
    occ = smod.occupancy(_build(8, mesh=mesh)[0], r1.streaming)
    assert occ["admitted"] > 0  # the drill exercised a live slot map

    clean = str(tmp_path / "clean")
    r2 = child(clean, drop=(BAD,))
    assert r2.step == STEPS - 1

    def crcs(ck):
        with open(os.path.join(ck, "meta.json"), encoding="utf-8") as f:
            return json.load(f)["files"]

    assert crcs(chaos) == crcs(clean), (
        "recovered streaming run is not trajectory-exact vs the "
        "stream-minus-poison run (slot map or params diverged)")

"""Direct unit tests of the lane-packed slab layout helpers.

The distributed suite exercises packing indirectly through oracles; these
pin the layout contract itself, including odd widths whose pack leaves dead
lanes (w=3 → p=42, 126/128 lanes used) and w >= 128 passthrough.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_embeddings_tpu.ops import packed_slab as ps


@pytest.mark.parametrize("width", [1, 3, 8, 16, 48, 64, 127, 128, 256])
def test_geometry(width):
    p = ps.pack_factor(width)
    assert p == (1 if width >= 128 else 128 // width)
    assert ps.phys_width(width) == (width if p == 1 else 128)
    rows = 1000
    ra = ps.align_rows(rows, width)
    assert ra % p == 0 and ra >= rows and ra - rows < p
    pr, pw = ps.packed_shape(ra, width)
    assert pr * p == ra and pw == ps.phys_width(width)


@pytest.mark.parametrize("width", [3, 16, 48, 128])
def test_pack_unpack_roundtrip(width):
    rng = np.random.default_rng(0)
    p = ps.pack_factor(width)
    n = 6 * p
    chunk = rng.normal(size=(n, width)).astype(np.float32)
    packed = ps.pack_rows_np(chunk, width)
    assert packed.shape == (n // p, ps.phys_width(width))
    np.testing.assert_array_equal(ps.unpack_rows_np(packed, width), chunk)
    # device-side pack agrees with the host pack
    np.testing.assert_array_equal(
        np.asarray(ps.pack_rows(jnp.asarray(chunk), width)), packed)


@pytest.mark.parametrize("width", [3, 16, 48, 128])
def test_packed_gather_matches_unpacked(width):
    rng = np.random.default_rng(1)
    p = ps.pack_factor(width)
    rows = ps.align_rows(500, width)
    logical = rng.normal(size=(rows, width)).astype(np.float32)
    slab = jnp.asarray(ps.pack_rows_np(logical, width))
    ids = jnp.asarray(rng.integers(0, 500, size=(257,)), jnp.int32)
    out = ps.packed_gather(slab, ids, width)
    np.testing.assert_array_equal(np.asarray(out), logical[np.asarray(ids)])
    # 2-D id shapes keep their shape
    ids2 = ids[:256].reshape(64, 4)
    out2 = ps.packed_gather(slab, ids2, width)
    assert out2.shape == (64, 4, width)
    np.testing.assert_array_equal(
        np.asarray(out2), logical[np.asarray(ids2)])


@pytest.mark.parametrize("width", [3, 16, 128])
def test_expand_update_rows_scatter_equivalence(width):
    """Scatter-add of lane-expanded rows == logical scatter-add, including
    duplicate logical ids and the OOB sentinel."""
    rng = np.random.default_rng(2)
    p = ps.pack_factor(width)
    rows = ps.align_rows(96, width)
    logical0 = rng.normal(size=(rows, width)).astype(np.float32)
    slab = jnp.asarray(ps.pack_rows_np(logical0, width))

    n = 300
    ids = rng.integers(0, 96, size=(n,))
    ids[::17] = rows  # sentinel: dropped
    ids = jnp.asarray(ids, jnp.int32)
    vals = jnp.asarray(rng.normal(size=(n, width)), jnp.float32)

    phys_ids, pvals = ps.expand_update_rows(vals, ids, width)
    assert pvals.shape[1] == ps.phys_width(width)
    new_slab = slab.at[phys_ids].add(pvals, mode="drop")

    want = logical0.copy()
    for i, idv in enumerate(np.asarray(ids)):
        if idv < rows:
            want[idv] += np.asarray(vals)[i]
    got = ps.unpack_rows_np(np.asarray(new_slab), width)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)

"""Planner unit tests (reference behavior:
``dist_model_parallel.py:25-196``)."""

import pytest

from distributed_embeddings_tpu.parallel.strategy import (
    DistEmbeddingStrategy,
    apply_strategy,
    maybe_slice_table_column,
)


def cfg(rows, width):
    return {"input_dim": rows, "output_dim": width}


def test_no_slice_below_threshold():
    assert maybe_slice_table_column(cfg(10, 4), 100, 8) == [cfg(10, 4)]
    assert maybe_slice_table_column(cfg(10, 4), None, 8) == [cfg(10, 4)]


def test_power_of_two_slicing_with_remainder():
    # 1000x10 = 10000 elements, threshold 3000 -> 4 slices, capped by nothing
    slices = maybe_slice_table_column(cfg(1000, 10), 3000, 8)
    assert [s["output_dim"] for s in slices] == [3, 3, 2, 2]
    assert all(s["input_dim"] == 1000 for s in slices)


def test_slice_caps():
    # would want 8 slices but width is 4 -> capped at 4
    slices = maybe_slice_table_column(cfg(1000, 4), 600, 8)
    assert len(slices) == 4
    # capped by world size
    slices = maybe_slice_table_column(cfg(1000, 8), 600, 2)
    assert len(slices) == 2


def test_basic_round_robin():
    sliced = [[cfg(10, 2)] for _ in range(5)]
    ids = apply_strategy("basic", 2, sliced)
    assert ids == [[0, 2, 4], [1, 3]]


def test_memory_balanced_snake():
    sizes = [100, 90, 80, 70, 60, 50, 40, 30]
    sliced = [[cfg(s, 1)] for s in sizes]
    ids = apply_strategy("memory_balanced", 2, sliced)
    # table counts even, byte loads close
    assert sorted(len(r) for r in ids) == [4, 4]
    loads = [sum(sizes[t] for t in r) for r in ids]
    assert abs(loads[0] - loads[1]) <= 20
    assert sorted(ids[0] + ids[1]) == list(range(8))


def test_memory_optimized_greedy():
    sizes = [100, 1, 1, 1, 1, 1]
    sliced = [[cfg(s, 1)] for s in sizes]
    ids = apply_strategy("memory_optimized", 2, sliced)
    loads = sorted(sum(sizes[t] for t in r) for r in ids)
    assert loads == [5, 100]


def test_unknown_strategy_raises():
    with pytest.raises(ValueError):
        DistEmbeddingStrategy([cfg(4, 2)], 1, strategy="bogus")


def test_world_one_passthrough():
    s = DistEmbeddingStrategy([cfg(4, 2), cfg(6, 3)], 1)
    assert s.table_ids_list == [[0, 1]]
    assert s.local_input_table_map == [0, 1]
    assert s.rev_global_input_ids == [0, 1]
    assert s.widths_list_flat == [2, 3]


def test_global_view_consistency():
    configs = [cfg(100, 8), cfg(50, 4), cfg(80, 8), cfg(10, 2), cfg(60, 4)]
    s = DistEmbeddingStrategy(configs, 2, strategy="basic")
    # every table placed exactly once
    placed = sorted(t for r in s.table_ids_list for t in r)
    assert placed == list(range(5))
    # every input routed exactly once, reorder is a permutation
    routed = sorted(i for r in s.input_ids_list for i in r)
    assert routed == list(range(5))
    assert sorted(s.rev_global_input_ids) == list(range(5))
    # widths in worker order match the routed inputs' table widths
    flat_inputs = [i for r in s.input_ids_list for i in r]
    assert s.widths_list_flat == [
        configs[s.input_table_map[i]]["output_dim"] for i in flat_inputs]


def test_shared_table_inputs():
    # inputs 0,1 -> table 0; input 2 -> table 1
    s = DistEmbeddingStrategy([cfg(10, 4), cfg(20, 8)], 2,
                              input_table_map=[0, 0, 1])
    routed = sorted(i for r in s.input_ids_list for i in r)
    assert routed == [0, 1, 2]
    # the rank owning table 0 sees both inputs with the same local table
    for rank_ids, rank_map in zip(s.input_ids_list, s.local_map_list):
        if 0 in rank_ids:
            assert 1 in rank_ids
            m0 = rank_map[rank_ids.index(0)]
            assert rank_map[rank_ids.index(1)] == m0


def test_column_slice_out_ranges_collapse():
    # table 0 sliced in 2; ranges expressed in progressive-collapse coordinates
    configs = [cfg(100, 8), cfg(10, 2), cfg(10, 2)]
    s = DistEmbeddingStrategy(configs, 2, column_slice_threshold=400)
    assert s.sliced_out_ranges == [[0, 2]]
    # four outputs before collapse: two slices of input 0 + inputs 1,2
    assert len(s.rev_global_input_ids) == 4
    # reordered outputs are sorted by input id: first two belong to input 0
    flat_inputs = [i for r in s.input_ids_list for i in r]
    reordered = [flat_inputs[i] for i in s.rev_global_input_ids]
    assert reordered == sorted(flat_inputs) == [0, 0, 1, 2]


def test_column_slice_widths_sum():
    configs = [cfg(1000, 9)]
    s = DistEmbeddingStrategy(configs, 4, column_slice_threshold=3000)
    widths = [c["output_dim"] for c in
              (s.local_configs_list[0] + s.local_configs_list[1] +
               s.local_configs_list[2] + s.local_configs_list[3])]
    assert sum(widths) == 9 and len(widths) == 4


def test_comm_balanced_class_counts():
    """Per-(width, inputs) class counts differ by at most 1 across ranks,
    and bytes stay balanced."""
    import numpy as np
    rng = np.random.default_rng(0)
    configs = []
    # three width classes with skewed sizes (memory_optimized would bunch
    # the small ones onto few ranks)
    for _ in range(9):
        configs.append(cfg(int(rng.integers(10, 20)), 8))
    for _ in range(10):
        configs.append(cfg(int(rng.integers(1000, 2000)), 16))
    for _ in range(5):
        configs.append(cfg(int(rng.integers(100000, 200000)), 32))
    sliced = [[c] for c in configs]
    world = 4
    ids = apply_strategy("comm_balanced", world, sliced,
                         input_table_map=list(range(len(configs))))
    assert sorted(t for r in ids for t in r) == list(range(len(configs)))
    widths = [c["output_dim"] for c in configs]
    for w in (8, 16, 32):
        counts = [sum(1 for t in r if widths[t] == w) for r in ids]
        assert max(counts) - min(counts) <= 1, (w, counts)
    loads = [sum(configs[t]["input_dim"] * configs[t]["output_dim"]
                 for t in r) for r in ids]
    assert max(loads) < 2.2 * min(loads)


def test_comm_balanced_shared_tables_classed_apart():
    """Tables with different input multiplicity form separate classes (the
    hotness proxy), each balanced on its own."""
    configs = [cfg(100, 8) for _ in range(8)]
    # tables 0..3 each serve two inputs; 4..7 one input
    itm = [0, 0, 1, 1, 2, 2, 3, 3, 4, 5, 6, 7]
    sliced = [[c] for c in configs]
    ids = apply_strategy("comm_balanced", 4, sliced, input_table_map=itm)
    for r in ids:
        shared = sum(1 for t in r if t < 4)
        single = sum(1 for t in r if t >= 4)
        assert shared == 1 and single == 1, ids


def test_comm_balanced_end_to_end_parity():
    """comm_balanced produces a valid plan: routing maps stay consistent."""
    configs = [cfg(50 + i, [4, 8, 16][i % 3]) for i in range(10)]
    s = DistEmbeddingStrategy(configs, 4, strategy="comm_balanced")
    routed = sorted(i for r in s.input_ids_list for i in r)
    assert routed == list(range(10))
    assert sorted(s.rev_global_input_ids) == list(range(10))


# --------------------------------------- extreme shapes (ISSUE 8): the
# planner is pure host metadata — 188M-row tables must plan in
# milliseconds without materializing any array


# the real Criteo-1TB vocab vector, single-sourced so these tests can
# never drift from what the capacity auditor and bench price
from tools._profcommon import CRITEO_1TB_SIZES as C1TB_188M  # noqa: E402


def _check_plan_valid(st, n_tables):
    """Structural invariants every plan must hold: every table placed on
    at least one rank, per-rank maps aligned, spec JSON-able."""
    placed = sorted({t for rank in st.table_ids_list for t in rank})
    assert placed == list(range(n_tables))
    # every rank's routing views are mutually aligned
    for r in range(st.world_size):
        assert len(st.local_configs_list[r]) == len(st.table_ids_list[r])
        assert len(st.input_ids_list[r]) == len(st.local_map_list[r])
        for m in st.local_map_list[r]:
            assert 0 <= m < len(st.local_configs_list[r])
    # the fingerprint is valid JSON with consistent per-rank elements
    import json
    spec = json.loads(json.dumps(st.plan_spec()))
    assert spec["world_size"] == st.world_size
    assert len(spec["local_tables"]) == st.world_size
    for r, entries in enumerate(spec["local_tables"]):
        total = sum(rows * width for _t, rows, width, _rb, _cs in entries)
        assert total == spec["per_rank_elements"][r]
    # global element conservation: slices partition every table
    total_elems = sum(spec["per_rank_elements"])
    return total_elems


@pytest.mark.parametrize("strategy", ["basic", "memory_balanced",
                                      "memory_optimized", "comm_balanced",
                                      "telemetry_balanced"])
def test_planners_at_188m_row_shapes(strategy):
    """Every planner produces a valid plan at the real Criteo-1TB row
    counts (~188M rows, 26 tables, world 16) — instantly and without
    arrays."""
    configs = [cfg(s, 128) for s in C1TB_188M]
    kw = {}
    if strategy == "telemetry_balanced":
        kw["table_loads"] = [float(s) for s in C1TB_188M]
    st = DistEmbeddingStrategy(configs, 16, strategy=strategy, **kw)
    total = _check_plan_valid(st, len(configs))
    assert total == sum(s * 128 for s in C1TB_188M)


def test_telemetry_balanced_without_loads_raises_cleanly():
    with pytest.raises(ValueError, match="table_loads"):
        DistEmbeddingStrategy([cfg(s, 128) for s in C1TB_188M], 16,
                              strategy="telemetry_balanced")


def test_world_equals_tables_boundary():
    """world == #tables: every rank owns exactly one table, for every
    planner, at 188M-row scale."""
    configs = [cfg(s, 128) for s in C1TB_188M]
    world = len(configs)
    for strategy in ("basic", "memory_balanced", "memory_optimized",
                     "comm_balanced"):
        st = DistEmbeddingStrategy(configs, world, strategy=strategy)
        assert all(len(r) == 1 for r in st.table_ids_list), strategy
        _check_plan_valid(st, len(configs))


def test_column_slice_threshold_at_188m_shapes():
    """Column slicing at real shapes: the >1.4e9-element tables split
    4-way (power of 2), slices partition the width exactly, and the
    sliced-out ranges reassemble in input order."""
    configs = [cfg(s, 128) for s in C1TB_188M]
    st = DistEmbeddingStrategy(configs, 16, strategy="comm_balanced",
                               column_slice_threshold=1_400_000_000)
    big = [t for t, s in enumerate(C1TB_188M) if s * 128 > 1_400_000_000]
    sliced, _ranges, _rranges, _rows = st.create_sliced_configs(
        16, 1_400_000_000, st.input_table_map)
    for t in big:
        assert len(sliced[t]) == 4, (t, len(sliced[t]))
        assert sum(c["output_dim"] for c in sliced[t]) == 128
        assert all(c["input_dim"] == C1TB_188M[t] for c in sliced[t])
    for t in range(len(configs)):
        if t not in big:
            assert len(sliced[t]) == 1
    _check_plan_valid(st, len(configs))
    # ranges cover exactly the sliced inputs, in ascending input order
    starts = [s for s, _ in st.sliced_out_ranges]
    assert starts == sorted(starts)
    assert len(st.sliced_out_ranges) == len(big)


def test_column_slice_precedence_over_row_slice_at_scale():
    """A table split by the column threshold is NOT row-sliced even when
    it also exceeds the row threshold (the two thresholds express one
    capacity constraint; doubly-sliced tables have no exchange
    layout)."""
    configs = [cfg(s, 128) for s in C1TB_188M]
    st = DistEmbeddingStrategy(configs, 16,
                               column_slice_threshold=1_400_000_000,
                               row_slice_threshold=1_000_000_000)
    big_col = {t for t, s in enumerate(C1TB_188M)
               if s * 128 > 1_400_000_000}
    # row-sliced tables are exactly those over the ROW threshold but
    # under the column one
    for t in st.row_sliced_tables:
        assert t not in big_col
        assert C1TB_188M[t] * 128 > 1_000_000_000
    _check_plan_valid(st, len(configs))

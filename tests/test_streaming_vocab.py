"""Streaming-vocab (dynamic-table) mode: admission, eviction, graceful
degradation, guard interplay, metrics, and recompile hygiene.

The semantics under test (``parallel/streaming.py`` + the
``DistributedEmbedding(streaming=...)`` remap):

* external ids from an unbounded space serve out of a fixed slab:
  below the frequency gate they share hash-bucket rows, past it they
  claim direct-mapped slots (zeroed at claim), and claims on occupied
  slots only evict colder occupants (approximate LFU);
* every transition is jit-carried, deterministic, and guard-gated — a
  nan-guard-skipped step leaves slot map, sketch, counters AND slabs
  bitwise-unchanged;
* slot-map churn never retraces the compiled step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_embeddings_tpu.parallel import (
    DistributedEmbedding, SparseAdagrad, SparseSGD, StreamingConfig,
    init_hybrid_state, init_streaming, make_hybrid_eval_step,
    make_hybrid_train_step)
from distributed_embeddings_tpu.parallel import streaming as smod
from distributed_embeddings_tpu.utils import obs


def _build(configs, world=1, mesh=None, cfg=None, opt=None, **step_kw):
    de = DistributedEmbedding(configs, world_size=world)
    emb_opt = opt or SparseSGD()
    tx = optax.sgd(0.1)
    state = init_hybrid_state(de, emb_opt,
                              {"w": jnp.ones((4, 1), jnp.float32)}, tx,
                              jax.random.key(0), mesh=mesh)

    def loss_fn(dp, outs, batch):
        return (sum(jnp.mean(o) for o in outs) * jnp.mean(dp["w"])
                + jnp.mean(batch))

    step = make_hybrid_train_step(de, loss_fn, tx, emb_opt, mesh=mesh,
                                  dynamic=cfg, **step_kw)
    return de, state, step


def _stream_cfg(**kw):
    base = dict(admit_min_count=2, evict_margin=1, depth=2, buckets=64)
    base.update(kw)
    return StreamingConfig(**base)


STATIC = {"input_dim": 32, "output_dim": 4}


def streaming_table(capacity=16, buckets=4):
    return {"input_dim": capacity + buckets, "output_dim": 4,
            "streaming": {"capacity": capacity, "buckets": buckets}}


# ------------------------------------------------------------- validation


def test_streaming_config_must_match_input_dim():
    with pytest.raises(ValueError, match="capacity"):
        DistributedEmbedding(
            [STATIC, {"input_dim": 99, "output_dim": 4,
                      "streaming": {"capacity": 16, "buckets": 4}}],
            world_size=1)


def test_streaming_rejects_sliced_tables():
    big = {"input_dim": 4096 + 64, "output_dim": 8,
           "streaming": {"capacity": 4096, "buckets": 64}}
    small = {"input_dim": 32, "output_dim": 8}
    with pytest.raises(NotImplementedError, match="sliced"):
        DistributedEmbedding([small, dict(small), big], world_size=2,
                             column_slice_threshold=8192)
    with pytest.raises(NotImplementedError, match="sliced"):
        DistributedEmbedding([small, dict(small), big], world_size=2,
                             row_slice=8192)


def test_dynamic_arg_requires_a_streaming_table():
    de, state, step = _build([STATIC, dict(STATIC)], cfg=None)
    with pytest.raises(ValueError, match="init_streaming"):
        init_streaming(de, _stream_cfg())


def test_resolve_config_rejects_junk():
    with pytest.raises(TypeError):
        smod.resolve_config("yes")


# ------------------------------------------------- admission and eviction


def test_cold_ids_share_buckets_then_admit():
    cfg = _stream_cfg(admit_min_count=3)
    de, state, step = _build([STATIC, streaming_table()], cfg=cfg,
                             with_metrics=True, nan_guard=False)
    sstate = init_streaming(de, cfg)
    ext = jnp.full((8,), 7_654_321, jnp.int32)  # one hot external id
    cats = [jnp.zeros((8,), jnp.int32), ext]
    batch = jnp.zeros((8,), jnp.float32)
    # step 1: est jumps to 8 >= 3 -> admitted immediately, but SERVED
    # from the bucket this step (the slot zeroes at commit)
    _, state, m, sstate = step(state, cats, batch, sstate)
    assert float(m["stream_admitted"][0]) == 1
    assert float(m["stream_bucket_ids"][0]) == 8
    assert float(m["stream_hit_ids"][0]) == 0
    # step 2: the id hits its slot
    _, state, m, sstate = step(state, cats, batch, sstate)
    assert float(m["stream_admitted"][0]) == 0
    assert float(m["stream_hit_ids"][0]) == 8
    occ = smod.occupancy(de, sstate)
    assert occ["admitted"] == 1 and occ["tables"][0]["occupied"] == 1


def test_below_gate_ids_stay_in_buckets():
    cfg = _stream_cfg(admit_min_count=100)
    de, state, step = _build([STATIC, streaming_table()], cfg=cfg,
                             with_metrics=True, nan_guard=False)
    sstate = init_streaming(de, cfg)
    rng = np.random.default_rng(0)
    for i in range(4):
        cats = [jnp.zeros((8,), jnp.int32),
                jnp.asarray(rng.integers(0, 1000, 8) + 10**6, jnp.int32)]
        _, state, m, sstate = step(state, cats,
                                   jnp.zeros((8,), jnp.float32), sstate)
        assert float(m["stream_admitted"][0]) == 0
        assert float(m["stream_bucket_ids"][0]) == 8
    assert smod.occupancy(de, sstate)["tables"][0]["occupied"] == 0


def test_lfu_eviction_hot_id_displaces_cold_occupant():
    # capacity=1: every external id direct-maps to the single slot, so
    # the hash collision is guaranteed and eviction is forced
    cfg = _stream_cfg(admit_min_count=1, evict_margin=1)
    de, state, step = _build([STATIC, streaming_table(capacity=1,
                                                      buckets=4)],
                             cfg=cfg, with_metrics=True, nan_guard=False)
    sstate = init_streaming(de, cfg)
    zeros = jnp.zeros((8,), jnp.float32)
    a = jnp.full((8,), 111, jnp.int32)
    b = jnp.full((8,), 999, jnp.int32)
    # id A claims the slot (freq est 8)
    _, state, m, sstate = step(state, [jnp.zeros((8,), jnp.int32), a],
                               zeros, sstate)
    assert float(m["stream_admitted"][0]) == 1
    # id B arrives once: est 8 < A's 8 + margin -> NO eviction
    _, state, m, sstate = step(state, [jnp.zeros((8,), jnp.int32), b],
                               zeros, sstate)
    assert float(m["stream_evicted"][0]) == 0
    # id B again: est 16 >= 8 + 1 -> evicts A
    _, state, m, sstate = step(state, [jnp.zeros((8,), jnp.int32), b],
                               zeros, sstate)
    assert float(m["stream_evicted"][0]) == 1
    # A degrades back to its bucket; B hits the slot
    both = jnp.concatenate([a[:4], b[:4]])
    _, state, m, sstate = step(state, [jnp.zeros((8,), jnp.int32), both],
                               zeros, sstate)
    assert float(m["stream_hit_ids"][0]) == 4
    assert float(m["stream_bucket_ids"][0]) == 4


def test_admitted_row_zeroes_then_trains():
    cfg = _stream_cfg(admit_min_count=1)
    de, state, step = _build([STATIC, streaming_table()], cfg=cfg,
                             with_metrics=False, nan_guard=False)
    sstate = init_streaming(de, cfg)
    ext = jnp.full((8,), 42_424_242, jnp.int32)
    cats = [jnp.zeros((8,), jnp.int32), ext]
    batch = jnp.zeros((8,), jnp.float32)
    _, state, sstate = step(state, cats, batch, sstate)
    # locate the claimed slot and check its row is exactly zero
    wkey = f"w{4}"
    fp = np.asarray(sstate[wkey]["slot_fp"][0])
    claimed = np.nonzero(fp >= 0)[0]
    assert claimed.size == 1
    row = np.asarray(state.emb_params[wkey]).reshape(
        -1, de.phys_w[4])  # packed rows
    from distributed_embeddings_tpu.ops import packed_slab as ps
    logical = ps.unpack_rows_np(
        np.asarray(state.emb_params[wkey][0]), 4)
    assert np.all(logical[claimed[0]] == 0.0)
    # next step the id reads the zeroed slot and its gradient trains it
    _, state, sstate = step(state, cats, batch, sstate)
    logical2 = ps.unpack_rows_np(
        np.asarray(state.emb_params[wkey][0]), 4)
    assert not np.all(logical2[claimed[0]] == 0.0)


def test_duplicate_claims_are_deterministic():
    # two DIFFERENT hot ids colliding on the single slot in the SAME
    # batch: the winner must be tie-broken deterministically
    cfg = _stream_cfg(admit_min_count=1)

    def run():
        de, state, step = _build([STATIC, streaming_table(capacity=1,
                                                          buckets=2)],
                                 cfg=cfg, with_metrics=False,
                                 nan_guard=False)
        sstate = init_streaming(de, cfg)
        ext = jnp.asarray([5, 9] * 4, jnp.int32) + 10**7
        _, state, sstate = step(
            state, [jnp.zeros((8,), jnp.int32), ext],
            jnp.zeros((8,), jnp.float32), sstate)
        return (np.asarray(sstate["w4"]["slot_fp"]),
                np.asarray(sstate["w4"]["slot_freq"]))
    a, b = run(), run()
    assert np.array_equal(a[0], b[0]) and np.array_equal(a[1], b[1])


# --------------------------------------------------- guard + degradation


def test_nan_guard_skip_leaves_streaming_state_and_slabs_bitwise():
    cfg = _stream_cfg(admit_min_count=1)
    de, state, step = _build([STATIC, streaming_table()], cfg=cfg,
                             opt=SparseAdagrad(), with_metrics=True,
                             nan_guard=True)
    sstate = init_streaming(de, cfg)
    good = jnp.zeros((8,), jnp.float32)
    cats = [jnp.zeros((8,), jnp.int32),
            jnp.full((8,), 123, jnp.int32)]
    _, state, m, sstate = step(state, cats, good, sstate)
    before = jax.tree.map(np.asarray,
                          (state.emb_params, state.emb_opt_state, sstate))
    # poisoned batch with NOVEL ids: transitions must be fully gated
    cats2 = [jnp.zeros((8,), jnp.int32),
             jnp.full((8,), 987_654, jnp.int32)]
    _, state, m, sstate = step(state, cats2,
                               jnp.full((8,), np.nan, jnp.float32),
                               sstate)
    assert float(m["skipped_steps"][0]) == 1
    assert float(m["stream_admitted"][0]) == 0
    after = jax.tree.map(np.asarray,
                         (state.emb_params, state.emb_opt_state, sstate))
    for x, y in zip(jax.tree.leaves(before), jax.tree.leaves(after)):
        assert np.array_equal(x, y)


def test_oov_flood_degrades_gracefully():
    # a burst of never-seen ids must neither crash nor evict the hot set
    cfg = _stream_cfg(admit_min_count=3, evict_margin=2)
    de, state, step = _build([STATIC, streaming_table(capacity=8,
                                                      buckets=4)],
                             cfg=cfg, with_metrics=True, nan_guard=False)
    sstate = init_streaming(de, cfg)
    zeros = jnp.zeros((8,), jnp.float32)
    hot = jnp.full((8,), 777, jnp.int32)
    for _ in range(3):  # establish the hot id
        _, state, m, sstate = step(
            state, [jnp.zeros((8,), jnp.int32), hot], zeros, sstate)
    occupied = smod.occupancy(de, sstate)["tables"][0]["occupied"]
    assert occupied == 1
    flood = jnp.asarray(np.arange(8) + 2_000_000_000 - 8, jnp.int32)
    _, state, m, sstate = step(
        state, [jnp.zeros((8,), jnp.int32), flood], zeros, sstate)
    occ = smod.occupancy(de, sstate)
    assert occ["evicted"] == 0  # one-shot ids never beat the gate
    # the hot id still hits its slot afterwards
    _, state, m, sstate = step(
        state, [jnp.zeros((8,), jnp.int32), hot], zeros, sstate)
    assert float(m["stream_hit_ids"][0]) == 8


def test_eval_step_is_read_only():
    cfg = _stream_cfg(admit_min_count=1)
    de, state, step = _build([STATIC, streaming_table()], cfg=cfg,
                             with_metrics=False, nan_guard=False)
    sstate = init_streaming(de, cfg)
    cats = [jnp.zeros((8,), jnp.int32), jnp.full((8,), 31337, jnp.int32)]
    _, state, sstate = step(state, cats, jnp.zeros((8,), jnp.float32),
                            sstate)
    ev = make_hybrid_eval_step(
        de, lambda dp, outs, b: sum(jnp.mean(o, -1) for o in outs),
        dynamic=cfg)
    before = jax.tree.map(np.asarray, sstate)
    novel = [jnp.zeros((8,), jnp.int32),
             jnp.full((8,), 999_999, jnp.int32)]
    preds = ev(state, novel, None, sstate)
    assert np.isfinite(np.asarray(preds)).all()
    for x, y in zip(jax.tree.leaves(before),
                    jax.tree.leaves(jax.tree.map(np.asarray, sstate))):
        assert np.array_equal(x, y)


def test_ragged_streaming_input():
    # multi-hot ragged features route through the same remap: values
    # remap, lengths/padding stay byte-identical, dead positions inert
    from distributed_embeddings_tpu.ops.embedding_lookup import Ragged

    cfg = _stream_cfg(admit_min_count=1)
    configs = [STATIC,
               {"input_dim": 16 + 4, "output_dim": 4, "combiner": "sum",
                "streaming": {"capacity": 16, "buckets": 4}}]
    de = DistributedEmbedding(configs, world_size=1)
    emb_opt = SparseSGD()
    tx = optax.sgd(0.1)
    state = init_hybrid_state(de, emb_opt,
                              {"w": jnp.ones((4, 1), jnp.float32)}, tx,
                              jax.random.key(0))

    def loss_fn(dp, outs, batch):
        return (sum(jnp.mean(o) for o in outs) * jnp.mean(dp["w"])
                + jnp.mean(batch))

    step = make_hybrid_train_step(de, loss_fn, tx, emb_opt,
                                  with_metrics=True, nan_guard=False,
                                  dynamic=cfg)
    sstate = init_streaming(de, cfg)
    rag = Ragged(values=jnp.asarray([11, 11, 11, 22, 22, 0, 0, 0],
                                    jnp.int32) + 10**6,
                 row_splits=jnp.asarray([0, 3, 5, 5, 5], jnp.int32))
    cats = [jnp.zeros((4,), jnp.int32), rag]
    batch = jnp.zeros((4,), jnp.float32)
    loss, state, m, sstate = step(state, cats, batch, sstate)
    assert np.isfinite(float(loss))
    # only the 5 LIVE ragged positions count (padding is inert)
    assert float(m["stream_bucket_ids"][0]) == 5
    assert float(m["stream_admitted"][0]) == 2  # ids 11+1e6 and 22+1e6
    loss, state, m, sstate = step(state, cats, batch, sstate)
    assert float(m["stream_hit_ids"][0]) == 5


# ------------------------------------------------------ recompile hygiene


def test_slot_map_churn_does_not_retrace():
    cfg = _stream_cfg(admit_min_count=1)
    de, state, step = _build([STATIC, streaming_table(capacity=8,
                                                      buckets=4)],
                             cfg=cfg, with_metrics=True, nan_guard=True)
    sstate = init_streaming(de, cfg)
    obs.install_compile_listener()
    rng = np.random.default_rng(3)

    def one(i):
        cats = [jnp.asarray(rng.integers(0, 32, 8), jnp.int32),
                jnp.asarray(rng.integers(0, 10**6, 8), jnp.int32)]
        return step(state, cats, jnp.zeros((8,), jnp.float32), sstate)

    _, state, m, sstate = one(0)  # warmup compile
    c0 = obs.counters().get("recompiles", 0)
    for i in range(4):  # heavy admission/eviction churn
        _, state, m, sstate = one(i + 1)
    jax.block_until_ready(jax.tree.leaves(sstate))
    assert obs.counters().get("recompiles", 0) - c0 == 0


def test_train_loop_carries_streaming_state():
    from distributed_embeddings_tpu.parallel import make_hybrid_train_loop

    cfg = _stream_cfg(admit_min_count=2)
    configs = [STATIC, streaming_table()]
    de = DistributedEmbedding(configs, world_size=1)
    emb_opt = SparseSGD()
    tx = optax.sgd(0.1)
    state = init_hybrid_state(de, emb_opt,
                              {"w": jnp.ones((4, 1), jnp.float32)}, tx,
                              jax.random.key(0))

    def loss_fn(dp, outs, batch):
        return sum(jnp.mean(o) for o in outs) * jnp.mean(dp["w"])

    loop = make_hybrid_train_loop(de, loss_fn, tx, emb_opt,
                                  with_metrics=True, nan_guard=True,
                                  dynamic=cfg)
    sstate = init_streaming(de, cfg)
    K = 4
    cat_stacks = [jnp.zeros((K, 8), jnp.int32),
                  jnp.broadcast_to(jnp.full((8,), 55_555, jnp.int32),
                                   (K, 8))]
    batch_stacks = jnp.zeros((K, 8), jnp.float32)
    losses, state, metrics, sstate = loop(state, cat_stacks,
                                          batch_stacks, sstate)
    assert losses.shape == (K,)
    adm = np.asarray(metrics["stream_admitted"]).reshape(K)
    hits = np.asarray(metrics["stream_hit_ids"]).reshape(K)
    # the id admits on the first scanned step and hits from the second —
    # ONE carried slot map across the whole compiled dispatch
    assert adm[0] == 1 and adm[1:].sum() == 0
    assert hits[0] == 0 and all(hits[1:] == 8)
    occ = smod.occupancy(de, sstate)
    assert occ["steps"] == K and occ["admitted"] == 1


# ------------------------------------------------------------- 8-dev mesh


def test_streaming_on_mesh_with_telemetry_combined():
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:8]), ("data",))
    cfg = _stream_cfg(admit_min_count=2)
    configs = [{"input_dim": 24 + 3 * i, "output_dim": 8}
               for i in range(7)]
    configs.append({"input_dim": 64 + 8, "output_dim": 8,
                    "streaming": {"capacity": 64, "buckets": 8}})
    de = DistributedEmbedding(configs, world_size=8)
    emb_opt = SparseSGD()
    tx = optax.sgd(0.1)
    state = init_hybrid_state(de, emb_opt,
                              {"w": jnp.ones((8, 1), jnp.float32)}, tx,
                              jax.random.key(0), mesh=mesh)

    def loss_fn(dp, outs, batch):
        return sum(jnp.mean(o) for o in outs) * jnp.mean(dp["w"])

    from distributed_embeddings_tpu.analysis import telemetry as tel
    tcfg = tel.TelemetryConfig(depth=2, buckets=128, topk=8,
                               candidates=16)
    step = make_hybrid_train_step(de, loss_fn, tx, emb_opt, mesh=mesh,
                                  with_metrics=True, nan_guard=True,
                                  telemetry=tcfg, dynamic=cfg)
    telem = tel.init_telemetry(de, tcfg, mesh=mesh)
    sstate = init_streaming(de, cfg, mesh=mesh)
    rng = np.random.default_rng(5)
    B = 16
    for i in range(3):
        cats = [jnp.asarray(rng.integers(0, c["input_dim"], B), jnp.int32)
                for c in configs[:7]]
        cats.append(jnp.asarray(rng.integers(0, 40, B) + 10**7,
                                jnp.int32))
        loss, state, metrics, telem, sstate = step(
            state, cats, jnp.zeros((B,), jnp.float32), telem, sstate)
    assert np.isfinite(float(loss))
    assert float(np.asarray(metrics["stream_admitted"]).sum()) > 0
    for k in obs.STREAMING_METRIC_KEYS:
        assert np.asarray(metrics[k]).shape == (8,)
    occ = smod.occupancy(de, sstate)
    assert occ["admitted"] > 0
    assert occ["tables"][0]["table_id"] == 7


# ------------------------------------------------- admission moment hygiene


@pytest.mark.parametrize("opt_cls", [SparseAdagrad, "adam", "momentum"])
def test_admitted_slot_moments_reset_to_fresh_init(opt_cls):
    """ROADMAP 5(b): a claimed slot's slab-shaped optimizer state must
    reset to the optimizer's fresh-init value in the same commit scatter
    that zeroes the row — an admitted id's moments start exactly like a
    freshly initialized table's, never as the evictee's leftovers."""
    from distributed_embeddings_tpu.parallel import (SparseAdam,
                                                     SparseMomentum)
    from distributed_embeddings_tpu.ops import packed_slab as ps

    if opt_cls == "adam":
        opt = SparseAdam()
    elif opt_cls == "momentum":
        opt = SparseMomentum()
    else:
        opt = opt_cls()
    cfg = _stream_cfg(admit_min_count=2)
    de, state, step = _build([STATIC, streaming_table()], cfg=cfg,
                             opt=opt, with_metrics=False, nan_guard=False)
    sstate = init_streaming(de, cfg)
    wkey = "w4"
    ext = jnp.full((8,), 42_424_242, jnp.int32)
    cats = [jnp.zeros((8,), jnp.int32), ext]
    batch = jnp.zeros((8,), jnp.float32)
    # pre-dirty every slab-shaped moment with a sentinel the fresh-init
    # value can never equal: without the reset, the claimed slot would
    # keep the sentinel (the evictee's-leftovers bug this test pins)
    SENT = 7.5
    slab_shape = np.asarray(state.emb_params[wkey][0]).shape

    def dirty(leaf):
        if np.asarray(leaf).shape[1:] == slab_shape:
            return jnp.full_like(leaf, SENT)
        return leaf
    state = state._replace(
        emb_opt_state=jax.tree.map(dirty, state.emb_opt_state))
    # one batch of 8 occurrences pushes the sketch past the gate: the
    # slot is claimed and the commit scatter must reset row AND moments
    _, state, sstate = step(state, cats, batch, sstate)
    fp = np.asarray(sstate[wkey]["slot_fp"][0])
    claimed = np.nonzero(fp >= 0)[0]
    assert claimed.size == 1
    row = int(claimed[0])

    fill = float(getattr(opt, "fresh_row_fill", 0.0))
    leaves = jax.tree.leaves(state.emb_opt_state[wkey])
    slab_leaves = [lf for lf in leaves
                   if np.asarray(lf[0]).shape == slab_shape]
    assert slab_leaves, "optimizer carries no slab-shaped state?"
    for leaf in slab_leaves:
        logical = ps.unpack_rows_np(np.asarray(leaf[0]), 4)
        np.testing.assert_array_equal(
            logical[row], np.full((4,), fill, logical.dtype))
        # a neighbouring slot row nobody touched keeps the sentinel —
        # the reset is row-targeted, not a slab-wide wipe (slots start
        # after the static table's 32 rows; capacity 16)
        untouched = 32 + ((row - 32 + 1) % 16)
        assert np.all(logical[untouched] == SENT)
    # the param row itself is zero (the pre-existing contract)
    logical_p = ps.unpack_rows_np(np.asarray(state.emb_params[wkey][0]), 4)
    assert np.all(logical_p[row] == 0.0)
    # step 3: the admitted id now trains its slot — moments move OFF the
    # fresh value (proves the reset didn't just freeze the row)
    _, state, sstate = step(state, cats, batch, sstate)
    moved = False
    for leaf in jax.tree.leaves(state.emb_opt_state[wkey]):
        if np.asarray(leaf[0]).shape != slab_shape:
            continue
        logical = ps.unpack_rows_np(np.asarray(leaf[0]), 4)
        if not np.all(logical[row] == fill):
            moved = True
    assert moved


def test_moment_reset_is_guard_gated():
    """A nan-guard-skipped step must leave the optimizer moments (like
    everything else) bitwise-unchanged even when an admission was
    staged in the same step."""
    cfg = _stream_cfg(admit_min_count=1)
    opt = SparseAdagrad()
    de, state, step = _build([STATIC, streaming_table()], cfg=cfg,
                             opt=opt, with_metrics=False, nan_guard=True)
    sstate = init_streaming(de, cfg)
    ext = jnp.full((8,), 77_777_777, jnp.int32)
    cats = [jnp.zeros((8,), jnp.int32), ext]
    before_opt = jax.tree.map(np.asarray, state.emb_opt_state)
    before_fp = np.asarray(sstate["w4"]["slot_fp"])
    # poisoned batch: the guard must skip the whole step, moment reset
    # included
    bad = jnp.full((8,), np.nan, jnp.float32)
    _, state, sstate = step(state, cats, bad, sstate)
    after_opt = jax.tree.map(np.asarray, state.emb_opt_state)
    jax.tree.map(np.testing.assert_array_equal, before_opt, after_opt)
    np.testing.assert_array_equal(before_fp,
                                  np.asarray(sstate["w4"]["slot_fp"]))

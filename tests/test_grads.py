"""Hybrid gradient glue tests (reference: tape/broadcast patches,
``dist_model_parallel.py:509-567``)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from distributed_embeddings_tpu.parallel import (
    broadcast_variables,
    hybrid_gradients,
    split_mp_dp,
)

WORLD = 8


def get_mesh():
    return Mesh(np.array(jax.devices()[:WORLD]), ("data",))


def test_split_mp_dp_prefix_mask():
    tree = {"emb": jnp.ones(3), "dense": {"w": jnp.ones(2), "b": jnp.ones(1)}}
    mp, dp = split_mp_dp(tree, {"emb": True, "dense": False})
    assert mp["emb"] is not None and mp["dense"]["w"] is None
    assert dp["emb"] is None and dp["dense"]["b"] is not None


def test_hybrid_gradients_semantics():
    mesh = get_mesh()

    def f(grads):
        return hybrid_gradients(grads, {"mp": True, "dp": False}, "data")

    # per-device grads: mp leaf gets /W, dp leaf gets pmean
    mp_in = jnp.arange(WORLD, dtype=jnp.float32).reshape(WORLD, 1)
    dp_in = jnp.arange(WORLD, dtype=jnp.float32).reshape(WORLD, 1)
    out = jax.jit(jax.shard_map(
        f, mesh=mesh,
        in_specs=({"mp": P("data"), "dp": P("data")},),
        out_specs={"mp": P("data"), "dp": P("data")}))(
            {"mp": mp_in, "dp": dp_in})
    np.testing.assert_allclose(out["mp"][:, 0], np.arange(WORLD) / WORLD)
    np.testing.assert_allclose(out["dp"][:, 0],
                               np.full(WORLD, np.arange(WORLD).mean()))


def test_broadcast_variables_root_wins():
    mesh = get_mesh()

    def f(params):
        return broadcast_variables(params, {"mp": True, "dp": False}, "data",
                                   root_rank=2)

    mp_in = jnp.arange(WORLD, dtype=jnp.float32).reshape(WORLD, 1)
    dp_in = 10.0 * jnp.arange(WORLD, dtype=jnp.float32).reshape(WORLD, 1)
    out = jax.jit(jax.shard_map(
        f, mesh=mesh,
        in_specs=({"mp": P("data"), "dp": P("data")},),
        out_specs={"mp": P("data"), "dp": P("data")}))(
            {"mp": mp_in, "dp": dp_in})
    # mp untouched (stays different per rank), dp all equal to root's value
    np.testing.assert_allclose(out["mp"][:, 0], np.arange(WORLD))
    np.testing.assert_allclose(out["dp"][:, 0], np.full(WORLD, 20.0))

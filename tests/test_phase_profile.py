"""Measured phase-time observatory tests.

Three layers, mirroring the module split:

* handwritten-trace parser tests (``utils/traceparse.py``): gzipped
  Chrome-trace fixtures with fused events, events missing ``op_name``
  metadata, and multi-device streams — so the parser is pinned
  independently of the live profiler format;
* the live capture path: the until-now-untested ``DETPU_PROFILE_DIR``
  round trip (``obs.profile_trace`` -> trace directory -> parser
  recovers the ``detpu/`` phase names), :func:`profile_steps` on a tiny
  jitted step, and the opt-in guarantee (a profiled step's outputs are
  bitwise the unprofiled step's);
* calibration/agreement units plus the ``tools/compare_bench.py``
  gates (``check_phase_profile``, the cross-backend refusal) — no jax.
"""

import gzip
import json
import os
import types

import pytest

from distributed_embeddings_tpu.utils import obs, traceparse
from distributed_embeddings_tpu.analysis import phase_profile as pp

DATA = os.path.join(os.path.dirname(__file__), "data")
MINI = os.path.join(DATA, "mini.trace.json.gz")


def _ev(name, ts, dur, pid=1, tid=1, **args):
    e = {"ph": "X", "pid": pid, "tid": tid, "name": name,
         "ts": float(ts), "dur": float(dur)}
    if args:
        e["args"] = args
    return e


def _doc(*events):
    return {"traceEvents": list(events)}


# ------------------------------------------------------------ parser units


def test_mini_fixture_roundtrip():
    """The checked-in miniature trace parses to hand-computable numbers
    (this is the fixture the no-jax obs_report selftest also pins)."""
    events = traceparse.parse_events(traceparse.load_trace(MINI))
    assert len(events) == 8          # the $python host frame is dropped
    m = traceparse.measure_events(events)
    assert {"embedding_forward/id_all_to_all",
            "embedding_forward/lookup_w8_d/packed_gather",
            "sparse_apply/sparse_apply_w8"} <= set(m["phase_ms"])
    assert m["a2a_union_ms"] == pytest.approx(0.11)
    assert m["measured_serialized_fraction"] == pytest.approx(
        85.0 / 110.0, abs=1e-4)
    # dot.4 carries op metadata (no detpu scope) -> resolved-unscoped;
    # copy.3 carries nothing -> unresolved
    assert m["events_resolved"] == 7
    unresolved = [e for e in events if not e.resolved]
    assert [e.name for e in unresolved] == ["copy.3"]


def test_fused_event_and_missing_opname():
    doc = _doc(
        _ev("fusion.7", 0, 50,
            long_name="jit(f)/detpu/sparse_apply/detpu/dedup/sort"),
        _ev("custom-call.2", 50, 10),                  # no metadata
        _ev("reduce.1", 60, 10, op_name="jit(f)/reduce_sum"),
        _ev("$file.py:1 frame", 0, 100),               # host: dropped
        _ev("ThreadpoolListener::Record", 0, 1),       # host: dropped
    )
    events = traceparse.parse_events(doc)
    assert [e.name for e in events] == ["fusion.7", "custom-call.2",
                                        "reduce.1"]
    assert events[0].phase == "sparse_apply/dedup"
    assert events[0].resolved
    assert not events[1].resolved and events[1].phase == ""
    assert events[2].resolved and events[2].phase == ""


def test_bare_name_resolver_join():
    """CPU-style events (bare instruction names) join through a
    resolver, including the ``.clone`` fallback and the ``hlo_op``
    arg."""
    table = {"dot.4": "embedding_forward/lookup_w8_d",
             "my_fusion": "sparse_apply/sparse_apply_w8"}
    doc = _doc(
        _ev("dot.4", 0, 10),
        _ev("my_fusion.clone", 10, 10),
        _ev("call", 20, 10, hlo_op="dot.4"),
        _ev("nonesuch.9", 30, 10),
    )

    def resolver(name):
        if name.endswith(".clone"):
            name = name[:-6]
        return table.get(name)

    events = traceparse.parse_events(doc, resolver=resolver)
    assert [e.phase for e in events] == [
        "embedding_forward/lookup_w8_d", "sparse_apply/sparse_apply_w8",
        "embedding_forward/lookup_w8_d", ""]
    assert [e.resolved for e in events] == [True, True, True, False]


def test_multi_device_streams_union_and_concurrency():
    """Two device lanes running the same phases concurrently: summed
    durations double, the wall union does not."""
    op = "jit(s)/detpu/sparse_apply/scatter"
    doc = _doc(_ev("scatter.1", 0, 100, pid=1, op_name=op),
               _ev("scatter.1", 20, 100, pid=2, op_name=op))
    m = traceparse.measure_events(traceparse.parse_events(doc))
    assert m["phase_ms"]["sparse_apply"] == pytest.approx(0.2)
    assert m["wall_ms"] == pytest.approx(0.12)
    assert m["concurrency"] == pytest.approx(0.2 / 0.12, abs=1e-3)


def test_trace_files_layouts(tmp_path):
    """Both capture layouts parse: the plugins/profile nesting with a
    gz file, and a bare .trace.json handed directly."""
    doc = _doc(_ev("add.1", 0, 10, op_name="jit(f)/detpu/nanguard/add"))
    nested = tmp_path / "cap" / "plugins" / "profile" / "r1"
    nested.mkdir(parents=True)
    with gzip.open(nested / "host.trace.json.gz", "wb") as f:
        f.write(json.dumps(doc).encode())
    plain = tmp_path / "solo.trace.json"
    plain.write_text(json.dumps(doc))

    ev_dir = traceparse.parse_capture(str(tmp_path / "cap"))
    ev_file = traceparse.parse_capture(str(plain))
    assert len(ev_dir) == len(ev_file) == 1
    assert ev_dir[0].phase == "nanguard"


def test_interval_math():
    merge = traceparse.merge_intervals
    assert merge([(0, 10), (10, 20), (30, 40)]) == [(0, 20), (30, 40)]
    assert merge([(5, 15), (0, 30)]) == [(0, 30)]
    assert traceparse.intersect_total([(0, 10), (20, 30)],
                                      [(5, 25)]) == pytest.approx(10)
    assert traceparse.intersect_total([], [(0, 1)]) == 0.0


def test_group_of():
    g = traceparse.group_of
    assert g("embedding_forward/id_all_to_all") == "exchange"
    assert g("sparse_apply/grad_all_to_all") == "exchange"
    assert g("embedding_forward/lookup_w4_d/packed_gather") == "lookup"
    assert g("sparse_apply/sparse_apply_w4") == "apply"
    assert g("sparse_apply/sparse_apply_w4/dedup") == "apply"
    assert g("dense_forward_backward") == "dense"
    assert g("dense_update") == "dense"
    assert g("streaming_commit") == "streaming"
    assert g("") == "other"
    assert g("nanguard") == "other"


def test_independent_spans_decide_classification():
    """The DAG-aware hook: with no independent spans a fully-shadowed
    exchange still classifies serialized; with generous independent
    spans it classifies overlapped."""
    a2a = "embedding_forward/id_all_to_all"
    doc = _doc(
        _ev("all-to-all.1", 0, 100,
            op_name=f"jit(s)/detpu/embedding_forward/detpu/"
                    f"id_all_to_all/a2a"),
        # concurrent compute that is DAG-DEPENDENT (another device's
        # gather feeding its own exchange): must not count as hiding
        _ev("gather.1", 0, 100,
            op_name="jit(s)/detpu/embedding_forward/detpu/"
                    "lookup_w4_d/gather"),
    )
    events = traceparse.parse_events(doc)
    m_dep = traceparse.measure_events(events,
                                      independent_spans={a2a: []})
    assert m_dep["collectives"][0]["classification"] == "serialized"
    assert m_dep["measured_serialized_fraction"] == pytest.approx(1.0)
    m_ind = traceparse.measure_events(
        events, independent_spans={a2a: [(0.0, 100.0)]})
    assert m_ind["collectives"][0]["classification"] == "overlapped"
    assert m_ind["measured_serialized_fraction"] == pytest.approx(0.0)
    # the naive fallback (no spans dict) over-credits: documented upper
    # bound — the gather's concurrency counts
    m_naive = traceparse.measure_events(events)
    assert m_naive["collectives"][0]["classification"] == "overlapped"


# -------------------------------------------------- live capture round trip


@pytest.fixture
def cpu_jit_fn():
    import jax
    import jax.numpy as jnp

    def f(x, y):
        with obs.scope("embedding_forward"):
            with obs.scope("id_all_to_all"):
                a = x @ y
        with obs.scope("sparse_apply"):
            b = jnp.sin(a) + jnp.cos(a)
        return b.sum()

    jf = jax.jit(f)
    x = jnp.ones((128, 128))
    jf(x, x).block_until_ready()
    return jf, x


def test_detpu_profile_dir_roundtrip(cpu_jit_fn, tmp_path, monkeypatch):
    """Satellite 1: the until-now-untested ``DETPU_PROFILE_DIR`` capture
    path in utils/obs.py — capture a tiny jitted step on CPU through
    ``obs.profile_trace``, assert the trace directory exists, and the
    parser recovers the known ``detpu/`` phase names."""
    jf, x = cpu_jit_fn
    cap = tmp_path / "cap"
    monkeypatch.setenv("DETPU_PROFILE_DIR", str(cap))
    with obs.profile_trace("roundtrip"):
        jf(x, x).block_until_ready()
    root = cap / "roundtrip"
    assert root.is_dir()
    files = traceparse.trace_files(str(root))
    assert files, "profile_trace produced no .trace.json[.gz] capture"
    events = traceparse.parse_capture(str(root))
    phases = {e.phase for e in events if e.phase}
    # CPU events carry bare instruction names; join them against the
    # compiled module's own op_name text
    if not phases:
        txt = jf.lower(x, x).compile().as_text()
        index = pp.HloPhaseIndex(txt)
        events = traceparse.parse_capture(str(root),
                                          resolver=index.resolve)
        phases = {e.phase for e in events if e.phase}
    assert any(p.startswith("embedding_forward") for p in phases), phases
    assert any(p.startswith("sparse_apply") for p in phases), phases


def test_profile_steps_and_bitwise_opt_in(cpu_jit_fn, tmp_path):
    """:func:`profile_steps` reduces live captures to a PhaseProfile
    with per-step spread — and profiling is strictly opt-in: the
    profiled step's outputs are bitwise the unprofiled step's."""
    import jax
    import numpy as np

    jf, x = cpu_jit_fn
    txt = jf.lower(x, x).compile().as_text()
    index = pp.HloPhaseIndex(txt)
    out = {}

    def run_one():
        out["y"] = jf(x, x)
        float(out["y"])

    prof = pp.profile_steps(run_one, steps=2, index=index,
                            profile_dir=str(tmp_path / "keep"),
                            label="tiny")
    assert prof.steps == 2
    assert prof.step_wall_ms["p50"] > 0
    assert prof.capture_s is not None and prof.parse_s is not None
    assert any(p.startswith("embedding_forward")
               for p in prof.phase_ms), prof.phase_ms
    # explicit profile_dir keeps the captures (the
    # DETPU_PHASE_PROFILE_DIR contract)
    assert traceparse.trace_files(str(tmp_path / "keep"))
    # opt-in: same inputs with the profiler off -> bitwise-equal output
    y_prof = np.asarray(out["y"])
    y_plain = np.asarray(jf(x, x))
    assert y_plain.tobytes() == y_prof.tobytes()
    json.dumps(prof.to_json())     # must round-trip
    assert "tiny" in prof.markdown()


# --------------------------------------------- calibration and agreement


def _fake_sched(phase_cost_ns, collectives):
    return types.SimpleNamespace(
        phase_cost_ns=phase_cost_ns,
        collectives=[types.SimpleNamespace(phase=p, classification=c)
                     for p, c in collectives])


def _profile_with(phase_ms, collectives=()):
    measures = [{
        "events": 10, "events_resolved": 10,
        "wall_ms": sum(phase_ms.values()), "busy_ms": 0.0,
        "concurrency": 1.0, "phase_ms": dict(phase_ms),
        "group_ms": {g: 0.0 for g in traceparse.GROUPS},
        "a2a_union_ms": 0.0, "a2a_frac": 0.0,
        "collectives": [
            {"phase": p, "union_ms": 1.0, "hidden_ms": 0.0,
             "hidden_frac": h,
             "classification": ("overlapped" if h >= 0.5
                                else "serialized")}
            for p, h in collectives],
        "measured_serialized_fraction": None,
        "overlap_min_frac": 0.5,
    }]
    return pp.PhaseProfile.from_steps(measures, label="t", world=1,
                                      backend="cpu")


def test_calibrate_flags_relative_drift():
    """A uniform backend-speed factor cancels; only RELATIVE mispricing
    flags."""
    prof = _profile_with({"a": 100.0, "b": 10.0, "c": 1.0})
    # modeled costs exactly 1000x cheaper across the board -> no drift
    sched = _fake_sched({"a": 100.0 * 1e3, "b": 10.0 * 1e3,
                         "c": 1.0 * 1e3}, [])
    rep = pp.calibrate(prof, sched, drift_max=2.0)
    assert rep.ok and rep.scale == pytest.approx(1000.0)
    # phase b now modeled 10x too cheap relative to the others
    sched = _fake_sched({"a": 100.0 * 1e3, "b": 1.0 * 1e3,
                         "c": 1.0 * 1e3}, [])
    rep = pp.calibrate(prof, sched, drift_max=2.0)
    assert not rep.ok
    assert any("'b'" in f for f in rep.flagged)
    assert not any("'a'" in f for f in rep.flagged)
    json.dumps(rep.to_json())
    assert "DRIFT" in rep.markdown()


def test_calibrate_ignores_trace_noise_phases():
    """Phases below the share floor never flag (ratio noise on a 0.1%
    phase is not mispricing)."""
    prof = _profile_with({"big": 1000.0, "tiny": 0.1})
    sched = _fake_sched({"big": 1000.0 * 1e3, "tiny": 0.0001 * 1e3}, [])
    rep = pp.calibrate(prof, sched, drift_max=2.0)
    assert rep.ok
    tiny = next(r for r in rep.rows if r.phase == "tiny")
    assert tiny.normalized is not None and not tiny.flagged


def test_check_agreement_semantics():
    ida = "embedding_forward/id_all_to_all"
    outa = "embedding_forward/out_all_to_all"
    # modeled serialized + measured serialized -> agreement
    prof = _profile_with({}, collectives=[(ida, 0.1)])
    sched = _fake_sched({}, [(ida, "serialized")])
    assert pp.check_agreement(prof, sched) == []
    # modeled serialized + measured overlapped -> violation
    prof = _profile_with({}, collectives=[(ida, 0.9)])
    assert any("modeled SERIALIZED" in v
               for v in pp.check_agreement(prof, sched))
    # modeled overlappable may measure either way
    sched = _fake_sched({}, [(ida, "overlappable")])
    prof = _profile_with({}, collectives=[(ida, 0.1)])
    assert pp.check_agreement(prof, sched) == []
    # modeled exchange never measured -> violation; psum collectives
    # (non-exchange phases) are ignored entirely
    sched = _fake_sched({}, [(ida, "serialized"), (outa, "serialized"),
                             ("nanguard", "serialized"),
                             ("", "serialized")])
    prof = _profile_with({}, collectives=[(ida, 0.1)])
    vs = pp.check_agreement(prof, sched)
    assert any(outa in v for v in vs)
    assert not any("nanguard" in v for v in vs)
    # measured exchange the model never saw -> violation
    sched = _fake_sched({}, [(ida, "serialized")])
    prof = _profile_with({}, collectives=[(ida, 0.1), (outa, 0.1)])
    assert any("not a collective of the modeled" in v
               for v in pp.check_agreement(prof, sched))


# ------------------------------------------------- compare_bench gates


def test_check_phase_profile_gate():
    from tools import compare_bench as cb

    base = {"phase_profile": {"measured_serialized_fraction": 0.2,
                              "violations": []}}
    ok = {"phase_profile": {"measured_serialized_fraction": 0.25,
                            "violations": []}}
    regress = {"phase_profile": {"measured_serialized_fraction": 0.6,
                                 "violations": []}}
    broken = {"phase_profile": {"measured_serialized_fraction": 0.2,
                                "violations": ["agreement: ..."]}}
    assert cb.check_phase_profile(base, ok) == 0
    assert cb.check_phase_profile(base, regress) == 1
    assert cb.check_phase_profile(base, broken) == 1
    # missing section while the baseline has one -> fail; both missing ok
    assert cb.check_phase_profile(base, {}) == 1
    assert cb.check_phase_profile({}, {}) == 0
    # first record carrying the section: absolute checks only
    assert cb.check_phase_profile({}, ok) == 0


def test_check_env_backend_refusal():
    from tools import compare_bench as cb

    cpu = {"backend": "cpu", "device_count": 1}
    tpu = {"backend": "tpu", "device_count": 16}
    assert cb.check_env(cpu, dict(cpu)) == 0
    assert cb.check_env(cpu, tpu) == 2          # backend AND count differ
    assert cb.check_env(cpu, tpu, allow_mismatch=True) == 0
    # env-block fallback for records predating the top-level stamp
    old = {"env": {"backend": "tpu", "device_count": 16}}
    assert cb.check_env(old, tpu) == 0
    assert cb.check_env(old, cpu) == 2
    # unstamped records keep comparing (pre-PR-2)
    assert cb.check_env({}, tpu) == 0

"""Online learning runtime: RCU snapshot publication, the freshness
SLO, and bounded staleness under chaos (``parallel/online.py`` +
the serving runtime's snapshot side).

The semantics under test:

* staleness arithmetic — per-response ``staleness_steps`` /
  ``staleness_s`` and the ``freshness_p95_*`` stats measure the
  installed snapshot against the latest completed train step and the
  flush clock, deterministically under an injected clock;
* publication consistency — versions are strictly monotone (a
  regression raises), a streaming runtime refuses a snapshot without
  its matching streaming-state copy, and on the 8-virtual-device mesh
  a flush interleaved with a publisher observes exactly ONE whole
  version (bitwise the plain eval step's answer for that version's
  state — never a mid-publish mix);
* the freshness rung — when publication falls behind the step SLO the
  server sheds low-priority load with typed
  ``Overloaded(reason="stale_snapshot")``, rides the existing
  degradation ladder (level 2, ``snapshot_lagging`` event), and
  recovers the moment a fresh snapshot installs;
* rollback composition — when training rewinds under the published
  view, ``maybe_publish`` republishes the ring-candidate state at once
  with the version still advancing;
* chaos composition — the combined ``DETPU_FAULT=oovflood@P,burst@P``
  drill (a traffic spike of never-seen ids while serving) admits
  streaming ids, sheds only typed, recovers post-burst, and keeps 0
  steady-state recompiles; preemption mid-serve checkpoints a
  consistent (training state, published version) pair that auto-resume
  continues monotonically.
"""

import json
import os
import time

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from distributed_embeddings_tpu.parallel import (
    DistributedEmbedding, OnlineConfig, OnlineRuntime, Overloaded,
    ServeConfig, Served, ServingRuntime, SnapshotPublisher, SparseAdagrad,
    SparseSGD, StreamingConfig, init_hybrid_state, init_streaming,
    make_hybrid_eval_step, make_hybrid_train_step, online_sidecar_path)
from distributed_embeddings_tpu.parallel import online as om
from distributed_embeddings_tpu.parallel import serving as sv
from distributed_embeddings_tpu.parallel import streaming as smod
from distributed_embeddings_tpu.utils import obs, runtime


class ManualClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t


def _pred_fn(dp, outs, batch):
    p = sum(jnp.sum(o, -1) for o in outs)
    if batch is not None:
        p = p + jnp.sum(batch, -1)
    return p


def _build(configs=None, world=1, mesh=None, **cfg_kw):
    configs = configs or [{"input_dim": 100, "output_dim": 4},
                          {"input_dim": 50, "output_dim": 4}]
    de = DistributedEmbedding(configs, world_size=world)
    tx = optax.sgd(0.1)
    state = init_hybrid_state(de, SparseSGD(), {"w": jnp.ones((4, 1))},
                              tx, jax.random.key(0), mesh=mesh)
    clock = ManualClock()
    cfg_kw.setdefault("max_batch", 16)
    cfg_kw.setdefault("max_wait_ms", 5)
    cfg_kw.setdefault("deadline_ms", 1000)
    cfg_kw.setdefault("max_queue", 64)
    rt = ServingRuntime(de, _pred_fn, state, mesh=mesh,
                        config=ServeConfig(**cfg_kw), clock=clock)
    return de, state, rt, clock


def _tmpl(n_inputs=2, numerical=3):
    return ([np.zeros(2, np.int32) for _ in range(n_inputs)],
            np.zeros((2, numerical), np.float32))


def _req(rng, de_sizes=(100, 50), n=3, numerical=3, **kw):
    return sv.synthetic_request(rng, list(de_sizes), n,
                                numerical=numerical, **kw)


# ------------------------------------------------- staleness arithmetic


def test_staleness_arithmetic_and_served_stamps():
    de, state, rt, clock = _build()
    rt.warmup(_tmpl())
    rt.install_snapshot(state, version=1, train_step=10,
                        published_t=0.0, now=0.0)
    s = rt.stats()
    assert s["snapshot_version"] == 1
    assert s["snapshot_train_step"] == 10
    assert not rt.freshness_stale
    # training advances 3 steps past the snapshot
    rt.note_train_step(13, now=1.0)
    rng = np.random.default_rng(0)
    assert rt.submit(_req(rng, n=2), now=1.0) is None
    clock.t = 1.5
    (r,) = rt.poll(now=1.5)
    assert isinstance(r, Served)
    assert r.version == 1
    assert r.staleness_steps == 3.0
    # seconds-staleness is measured at flush completion vs published_t
    assert r.staleness_s == pytest.approx(1.5)
    s = rt.stats()
    # stats() percentiles now come from mergeable log-bucketed sketches:
    # exact to within the sketch's guaranteed relative error (1%)
    assert s["freshness_p95_steps"] == pytest.approx(3.0, rel=0.011)
    assert s["freshness_p95_s"] == pytest.approx(1.5, rel=0.011)
    assert s["snapshots_installed"] == 1


def test_stats_freshness_none_before_any_snapshot_serve():
    de, state, rt, clock = _build()
    rt.warmup(_tmpl())
    s = rt.stats()
    assert s["freshness_p95_steps"] is None
    assert s["freshness_p95_s"] is None
    assert s["snapshot_version"] is None


def test_version_monotonicity_enforced():
    de, state, rt, clock = _build()
    rt.install_snapshot(state, version=3, train_step=1, now=0.0)
    with pytest.raises(ValueError, match="monotonic"):
        rt.install_snapshot(state, version=3, train_step=2, now=0.0)
    with pytest.raises(ValueError, match="monotonic"):
        rt.install_snapshot(state, version=2, train_step=2, now=0.0)
    rt.install_snapshot(state, version=4, train_step=2, now=0.0)
    assert rt.stats()["snapshot_version"] == 4


def test_streaming_runtime_requires_streaming_state_copy():
    configs = [{"input_dim": 20, "output_dim": 4},
               {"input_dim": 32 + 8, "output_dim": 4,
                "streaming": {"capacity": 32, "buckets": 8}}]
    de = DistributedEmbedding(configs, world_size=1)
    scfg = StreamingConfig(admit_min_count=2, evict_margin=1, depth=2,
                           buckets=64)
    tx = optax.sgd(0.1)
    state = init_hybrid_state(de, SparseSGD(), {"w": jnp.ones((4, 1))},
                              tx, jax.random.key(0))
    sstate = init_streaming(de, scfg)
    rt = ServingRuntime(de, _pred_fn, state, streaming=(scfg, sstate),
                        clock=ManualClock())
    with pytest.raises(ValueError, match="streaming_state"):
        rt.install_snapshot(state, version=1, train_step=0, now=0.0)
    rt.install_snapshot(state, sstate, version=1, train_step=0, now=0.0)


# ------------------------------------------------- the freshness rung


def test_freshness_rung_sheds_typed_and_recovers():
    obs.drain_events()
    de, state, rt, clock = _build()
    rt.warmup(_tmpl())
    rt.set_freshness_slo(max_steps=2)
    rt.install_snapshot(state, version=1, train_step=0, now=0.0)
    assert not rt.freshness_stale
    # within SLO: 2 steps behind is the boundary, still fresh
    rt.note_train_step(2, now=0.0)
    assert not rt.freshness_stale
    # past it: the rung engages
    rt.note_train_step(3, now=0.0)
    assert rt.freshness_stale
    lag = obs.drain_events("snapshot_lagging")
    assert lag and lag[-1]["lag_steps"] == 3
    assert rt.level == 2
    rng = np.random.default_rng(1)
    rej = rt.submit(_req(rng, n=2), now=0.0)
    assert isinstance(rej, Overloaded) and rej.reason == "stale_snapshot"
    hi = _req(rng, n=2)
    hi.priority = 1
    assert rt.submit(hi, now=0.0) is None  # high priority still admitted
    # a fresh publication recovers the rung immediately
    rt.install_snapshot(state, version=2, train_step=3, now=0.0)
    assert not rt.freshness_stale and rt.level == 0
    assert rt.submit(_req(rng, n=2), now=0.0) is None
    s = rt.stats()
    assert s["stale_shed"] == 1
    assert s["freshness_stale"] is False
    assert obs.drain_events("snapshot_published")


def test_freshness_wall_clock_slo():
    de, state, rt, clock = _build()
    rt.warmup(_tmpl())
    rt.set_freshness_slo(max_steps=0, max_s=10.0)  # 0 = steps unchecked
    rt.install_snapshot(state, version=1, train_step=0, now=0.0)
    rt.note_train_step(100, now=5.0)   # steps don't matter here
    assert not rt.freshness_stale
    clock.t = 11.0
    rt.poll(now=11.0)                  # poll refreshes wall-clock age
    assert rt.freshness_stale


# ------------------------------------------------ publisher semantics


def test_publisher_cadence_sidecar_and_rollback_rewind(tmp_path):
    obs.drain_events()
    de, state, rt, clock = _build()
    rt.warmup(_tmpl())
    side = online_sidecar_path(str(tmp_path / "ck"))
    pub = SnapshotPublisher(
        rt, config=OnlineConfig(publish_every_steps=3,
                                freshness_max_steps=8),
        sidecar_path=side, clock=clock)
    st = lambda k: state._replace(step=jnp.asarray(k, jnp.int32))
    assert pub.maybe_publish(st(0)) is not None        # first: always
    assert pub.maybe_publish(st(2)) is None            # off-cadence
    assert rt.stats()["snapshot_train_step"] == 0      # ...not installed
    snap = pub.maybe_publish(st(3))                    # cadence hit
    assert snap is not None and snap.version == 2
    assert json.load(open(side))["train_step"] == 3
    # rollback: training rewound under the published view -> immediate
    # republish, version still advancing while train_step goes BACK
    back = pub.maybe_publish(st(1))
    assert back is not None and back.version == 3 and back.train_step == 1
    assert obs.drain_events("snapshot_rewound")
    assert rt.stats()["snapshot_version"] == 3
    assert rt.stats()["snapshot_train_step"] == 1
    assert json.load(open(side)) ["version"] == 3


def test_publisher_resume_continues_version_counter(tmp_path):
    de, state, rt, clock = _build()
    rt.warmup(_tmpl())
    side = online_sidecar_path(str(tmp_path / "ck"))
    pub = SnapshotPublisher(rt, sidecar_path=side, clock=clock)
    pub.publish(state, train_step=4)
    pub.publish(state, train_step=5)
    assert json.load(open(side))["version"] == 2
    # "resume": a new publisher (fresh process) over the same sidecar
    de2, state2, rt2, clock2 = _build()
    pub2 = SnapshotPublisher(rt2, sidecar_path=side, resume=True,
                             clock=clock2)
    snap = pub2.publish(state2, train_step=6)
    assert snap.version == 3                  # monotone across the resume
    # resume=False starts a fresh lineage and deletes the stale record
    pub3 = SnapshotPublisher(rt2, sidecar_path=side, resume=False,
                             clock=clock2)
    assert not os.path.exists(side)
    assert pub3.version == 0


def test_published_buffers_are_real_copies():
    """Donation safety: the published view must survive the training
    step donating the source buffers — distinct device buffers, equal
    values."""
    de, state, rt, clock = _build()
    pub = SnapshotPublisher(rt, clock=clock)
    snap = pub.publish(state, train_step=0)
    src = jax.tree.leaves(state.emb_params)
    dst = jax.tree.leaves(snap.state.emb_params)
    for a, b in zip(src, dst):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert a.unsafe_buffer_pointer() != b.unsafe_buffer_pointer()


# --------------------------------------- no torn reads (8-device mesh)


@pytest.fixture
def mesh8():
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()[:8]), ("data",))


def test_no_torn_reads_under_interleaved_publication_mesh8(mesh8):
    """RCU on the real mesh: requests queued BEFORE a publish flush
    against the version installed at flush time — the whole new
    version, bitwise the plain eval step's answer for that state, never
    a mid-publish mix of old and new tables."""
    configs = [{"input_dim": 50 + i, "output_dim": 4} for i in range(8)]
    de, state, rt, clock = _build(configs, world=8, mesh=mesh8,
                                  max_batch=16)
    pub = SnapshotPublisher(rt, clock=clock)
    # two visibly different table generations, same shapes/shardings
    state_a = state
    state_b = state._replace(
        emb_params=jax.tree.map(lambda a: a + jnp.asarray(1, a.dtype),
                                state.emb_params),
        step=jnp.asarray(7, jnp.int32))
    ev = make_hybrid_eval_step(de, _pred_fn, mesh=mesh8)
    # one-time compiles (publisher cloners, the reference eval step)
    # land BEFORE the warmup baseline — the steady-state window then
    # covers the interleaved publish/serve sequence itself
    pub.warm(state_a)
    ev(state_a, [jnp.zeros(8, jnp.int32) for _ in range(8)],
       jnp.zeros((8, 3), jnp.float32))
    rt.warmup(_tmpl(n_inputs=8))
    rng = np.random.default_rng(3)

    def serve_one(req):
        rt.submit(req, now=clock.t)
        clock.t += 0.01
        res = rt.poll(now=clock.t)
        (r,) = [x for x in res if isinstance(x, Served)]
        return r

    def direct(st, req):
        return np.asarray(ev(st, [jnp.asarray(c) for c in req.cats],
                             jnp.asarray(req.batch)))

    pub.publish(state_a, train_step=0)
    r1q = _req(rng, de_sizes=[50 + i for i in range(8)], n=8)
    r1 = serve_one(r1q)
    assert r1.version == 1
    np.testing.assert_array_equal(np.asarray(r1.predictions),
                                  direct(state_a, r1q))

    # interleave: queue a request, THEN publish, THEN flush — the flush
    # must observe v2 whole (read-once discipline)
    r2q = _req(rng, de_sizes=[50 + i for i in range(8)], n=8)
    assert rt.submit(r2q, now=clock.t) is None
    pub.publish(state_b)
    clock.t += 0.01
    res = rt.poll(now=clock.t)
    (r2,) = [x for x in res if isinstance(x, Served)]
    assert r2.version == 2
    np.testing.assert_array_equal(np.asarray(r2.predictions),
                                  direct(state_b, r2q))
    # bitwise distinguishable generations: a torn read could not match
    assert not np.array_equal(np.asarray(r2.predictions),
                              direct(state_a, r2q))
    assert rt.stats()["steady_state_recompiles"] == 0


# ------------------------------------------------ chaos composition


def _online_setup(mesh=None, world=1):
    configs = [{"input_dim": 20, "output_dim": 4},
               {"input_dim": 32 + 8, "output_dim": 4,
                "streaming": {"capacity": 32, "buckets": 8}}]
    de = DistributedEmbedding(configs, world_size=world)
    scfg = StreamingConfig(admit_min_count=2, evict_margin=1, depth=2,
                           buckets=256)
    emb_opt = SparseAdagrad()
    tx = optax.sgd(0.05)
    state = init_hybrid_state(de, emb_opt,
                              {"w": jnp.ones((4, 1), jnp.float32)},
                              tx, jax.random.key(0), mesh=mesh)
    sstate = init_streaming(de, scfg, mesh=mesh)

    def loss_fn(dp, outs, batch):
        return sum(batch[:, i].mean() * jnp.mean(o)
                   for i, o in enumerate(outs)) * jnp.mean(dp["w"])

    step = make_hybrid_train_step(de, loss_fn, tx, emb_opt, mesh=mesh,
                                  with_metrics=True, nan_guard=True,
                                  dynamic=scfg)

    def make_batch(i):
        rng = np.random.default_rng(900 + i)
        cats = [jnp.asarray(rng.integers(0, 20, 8), jnp.int32),
                jnp.asarray(rng.integers(i, i + 6, 8) * 7 + 10_000_000,
                            jnp.int32)]
        return cats, jnp.asarray(rng.normal(size=(8, 2)), jnp.float32)

    return de, scfg, emb_opt, tx, state, sstate, step, make_batch


def test_combined_chaos_oovflood_and_burst_while_serving(monkeypatch):
    """The joint drill: at step 2 the training stream floods with
    never-seen ids (oovflood@) while at step 3 serve traffic spikes 8x
    (burst@). Wanted: streaming admissions happen, every refusal is
    typed, the ladder recovers after the burst, staleness stays within
    the SLO, and nothing retraces."""
    monkeypatch.setenv(runtime.FAULT_ENV, "oovflood@2,burst@3")
    de, scfg, emb_opt, tx, state, sstate, step, make_batch = \
        _online_setup()
    rt = ServingRuntime(de, _pred_fn, state,
                        config=ServeConfig(max_batch=16, max_wait_ms=0,
                                           deadline_ms=10_000,
                                           max_queue=16),
                        streaming=(scfg, sstate))
    rng = np.random.default_rng(7)

    STEPS = 8
    def data(start):
        for i in range(start, STEPS):
            yield make_batch(i)

    online = OnlineRuntime(
        rt, config=OnlineConfig(publish_every_steps=2,
                                freshness_max_steps=4))
    res = online.run(step, state, data, de=de,
                     warmup_template=_tmpl(numerical=2),
                     make_request=lambda i: _req(rng, (20, 40), n=2,
                                                 numerical=2),
                     requests_per_step=2, burst_x=8.0,
                     streaming_state=sstate, emb_optimizer=emb_opt,
                     dense_tx=tx, metrics_interval=0)
    assert res.train.step == STEPS and not res.train.preempted
    # oovflood absorbed into the admission machinery: admissions happened
    occ = smod.occupancy(de, res.train.streaming)
    assert int(occ["admitted"]) > 0
    served = [r for r in res.serve_results if isinstance(r, Served)]
    others = [r for r in res.serve_results if not isinstance(r, Served)]
    assert served, "no request was ever served"
    # typed sheds only: the burst overflow came back as Overloaded, not
    # exceptions or losses
    assert others and all(isinstance(r, Overloaded) for r in others)
    assert {r.reason for r in others} <= {"queue_full", "load_shed"}
    # post-burst recovery: the ladder walked back down
    assert rt.level == 0
    s = res.serve_stats
    assert s["steady_state_recompiles"] == 0
    assert s["freshness_p95_steps"] is not None
    assert s["freshness_p95_steps"] <= 4 * 1.011  # sketch rel-error slack
    # every served answer observed a whole published version
    assert all(r.version >= 1 for r in served)
    vs = [r.version for r in served]
    assert vs == sorted(vs)  # versions only ever move forward


def test_realtime_mode_wall_clock_freshness():
    """ISSUE 18 tentpole: ``realtime_qps`` hands the serve plane its own
    thread of control. Arrivals land on wall-clock time against the
    live publisher WHILE training runs, the pump only publishes, and
    ``freshness_p95_s`` measures true concurrent staleness. Request
    conservation: every driver submission comes back typed exactly
    once, and nothing retraces."""
    de, scfg, emb_opt, tx, state, sstate, step, make_batch = \
        _online_setup()
    rt = ServingRuntime(de, _pred_fn, state,
                        config=ServeConfig(max_batch=16, max_wait_ms=2,
                                           deadline_ms=10_000,
                                           max_queue=256),
                        streaming=(scfg, sstate))
    rng = np.random.default_rng(11)

    STEPS = 8
    def data(start):
        for i in range(start, STEPS):
            time.sleep(0.03)  # hold the stream open: wall-clock arrivals
            yield make_batch(i)

    online = OnlineRuntime(rt, config=OnlineConfig(publish_every_steps=2))
    res = online.run(step, state, data, de=de,
                     warmup_template=_tmpl(numerical=2),
                     make_request=lambda i: _req(rng, (20, 40), n=2,
                                                 numerical=2),
                     realtime_qps=150.0, realtime_drain_s=60.0,
                     streaming_state=sstate, emb_optimizer=emb_opt,
                     dense_tx=tx, metrics_interval=0)
    assert res.train.step == STEPS and not res.train.preempted
    served = [r for r in res.serve_results if isinstance(r, Served)]
    assert served, "driver produced no served responses"
    # conservation: runtime rids are contiguous and every submission
    # came back exactly once (no losses, no duplicates through the
    # concurrent submit/poll/install interleaving)
    rids = sorted(r.rid for r in res.serve_results)
    assert rids == list(range(len(rids)))
    assert all(r.version >= 1 for r in served)
    s = res.serve_stats
    assert s["steady_state_recompiles"] == 0
    # wall-clock freshness, measured by the open-loop driver's flushes
    assert s["freshness_p95_s"] is not None and s["freshness_p95_s"] > 0
    assert res.published_version >= 1


def test_realtime_mode_argument_validation():
    online = OnlineRuntime(object())  # serving untouched before validation
    with pytest.raises(ValueError, match="ONE load mode"):
        online.run(None, None, None, de=None,
                   make_request=lambda i: None, requests_per_step=2,
                   realtime_qps=10.0)
    with pytest.raises(ValueError, match="make_request"):
        online.run(None, None, None, de=None, realtime_qps=10.0)
    with pytest.raises(ValueError, match="positive"):
        online.run(None, None, None, de=None,
                   make_request=lambda i: None, realtime_qps=0.0)


def test_preempt_mid_serve_then_resume_consistent_pair(tmp_path,
                                                       monkeypatch):
    """Preemption mid-serve: the SIGTERM checkpointed training state and
    the sidecar's published version form a consistent pair (published
    step never ahead of the saved step), and auto-resume continues the
    version lineage monotonically from the restored state."""
    ckpt = str(tmp_path / "ck")
    STEPS = 10
    rng = np.random.default_rng(11)

    def run_once(faults):
        # a fresh process each time: new de/state/step templates, a new
        # serving runtime — only the checkpoint + sidecar carry over
        de, scfg, emb_opt, tx, state, sstate, step, make_batch = \
            _online_setup()

        def data(start):
            for i in range(start, STEPS):
                yield make_batch(i)

        rt = ServingRuntime(de, _pred_fn, state,
                            config=ServeConfig(max_batch=16,
                                               max_wait_ms=0,
                                               deadline_ms=10_000,
                                               max_queue=64),
                            streaming=(scfg, sstate))
        if faults:
            monkeypatch.setenv(runtime.FAULT_ENV, faults)
        else:
            monkeypatch.delenv(runtime.FAULT_ENV, raising=False)
        online = OnlineRuntime(
            rt, config=OnlineConfig(publish_every_steps=2,
                                    freshness_max_steps=4),
            checkpoint_dir=ckpt)
        return online.run(
            step, state, data, de=de,
            warmup_template=_tmpl(numerical=2),
            make_request=lambda i: _req(rng, (20, 40), n=2, numerical=2),
            requests_per_step=2, streaming_state=sstate,
            emb_optimizer=emb_opt, dense_tx=tx,
            checkpoint_every_steps=2, metrics_interval=0)

    r1 = run_once("preempt@4")
    assert r1.train.preempted
    side = json.load(open(online_sidecar_path(ckpt)))
    saved_step = json.load(
        open(os.path.join(ckpt, "meta.json")))["step"]
    # the consistent pair: the published view never leads the checkpoint
    assert side["version"] == r1.published_version >= 1
    assert side["train_step"] <= saved_step

    r2 = run_once(None)
    assert r2.train.step == STEPS and not r2.train.preempted
    # versions continue, never restart, across the preemption boundary
    assert r2.published_version > r1.published_version
    served2 = [r for r in r2.serve_results if isinstance(r, Served)]
    assert served2
    assert min(r.version for r in served2) > r1.published_version
    # the first resumed publication is the RESTORED state, not the init
    # template the process started from
    assert all(r.staleness_steps is not None for r in served2)
    assert json.load(open(online_sidecar_path(ckpt)))["train_step"] \
        == r2.train.step


def test_compare_bench_online_gate():
    from tools import compare_bench as cb

    def rec(p95=10.0, rc=0, fresh=2.0, slo=4, delta=0.0):
        return {"metric": "x",
                "online": {"latency_p95_ms": p95,
                           "steady_state_recompiles": rc,
                           "freshness_p95_steps": fresh,
                           "freshness_slo_steps": slo,
                           "auc_delta_vs_replay": delta}}

    base = rec()
    assert cb.check_online(base, rec()) == 0
    assert cb.check_online(base, rec(p95=10.9)) == 0      # within 10%
    assert cb.check_online(base, rec(p95=11.5)) == 1      # p95 ratchet
    assert cb.check_online(base, rec(rc=1)) == 1          # recompiles
    assert cb.check_online(base, rec(fresh=5.0)) == 1     # SLO breach
    assert cb.check_online(base, rec(delta=0.01)) == 1    # AUC drifted
    assert cb.check_online(base, rec(delta=-0.01)) == 1   # either sign
    # missing section vs a baseline that has it fails; both-missing and
    # new-section-no-baseline pass (rounds legitimately add sections)
    assert cb.check_online(base, {"metric": "x"}) == 1
    assert cb.check_online({"metric": "x"}, {"metric": "x"}) == 0
    assert cb.check_online({"metric": "x"}, rec()) == 0


# ------------------------------------------- freshness-breach post-mortem


def test_freshness_breach_dumps_blackbox(tmp_path):
    """The stale TRANSITION parks a CRC-intact black box naming the
    lagging version — the serving runtime's leg of the flight-recorder
    contract."""
    from distributed_embeddings_tpu.utils import mplane

    mplane.uninstall_flight_recorder()
    try:
        de, state, rt, clock = _build()
        rt.warmup(_tmpl())
        path = str(tmp_path / "serve.blackbox.json")
        rec = mplane.install_flight_recorder(path, capacity=8)
        assert rec is not None
        rt.set_freshness_slo(max_steps=2)
        rt.install_snapshot(state, version=1, train_step=0, now=0.0)
        rt.note_train_step(3, now=4.5)
        assert rt.freshness_stale
        payload = mplane.verify_blackbox(path)
        assert payload["trigger"] == "freshness_breach"
        assert payload["context"]["version"] == 1
        assert payload["context"]["lag_steps"] == 3
        # a stats() snapshot rode along (captured AT the breach, i.e.
        # the last pre-breach view), and the snapshot_lagging event
        # reached the ring through the obs tap
        assert payload["stats"][-1]["source"] == "serving"
        assert payload["stats"][-1]["stats"]["snapshot_version"] == 1
        assert any(e["event"] == "snapshot_lagging"
                   for e in payload["events"])
    finally:
        mplane.uninstall_flight_recorder()

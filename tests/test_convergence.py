"""End-to-end learning evidence (VERDICT r4 Missing #1).

The reference's published quality is Criteo AUC 0.80248 via its eval loop
(``examples/dlrm/README.md:7``, ``examples/dlrm/main.py:223-243``). These
slow tests train DLRM through the FULL hybrid path — DistributedEmbedding
over the 8-device mesh, sparse embedding optimizer (SparseAdam), LR
schedule, AUC eval — on a planted-signal task (``models/learnable.py``,
shared driver with the bench's ``convergence`` capture) and assert the AUC
rises well above chance, and that bf16 tables track the fp32 trajectory.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from distributed_embeddings_tpu.models.learnable import (
    LearnableClicks, train_dlrm_convergence)
from distributed_embeddings_tpu.models.schedules import (
    warmup_poly_decay_schedule)

WORLD = 8


def _train(param_dtype, seed=0, steps=240):
    task = LearnableClicks([200] * 8, num_numerical=4, seed=123, scale=1.2)
    mesh = Mesh(np.array(jax.devices()[:WORLD]), ("data",))
    sched = warmup_poly_decay_schedule(0.01, warmup_steps=20,
                                       decay_start_step=180, decay_steps=60)
    return train_dlrm_convergence(
        task, world_size=WORLD, mesh=mesh, steps=steps, batch=1024,
        embedding_dim=8, lr_schedule=sched, param_dtype=param_dtype,
        eval_n=8192, seed=seed)


@pytest.mark.slow
def test_dlrm_learns_planted_signal():
    auc0, mid, auc1 = _train(jnp.float32)
    # untrained ~ chance; trained near the task's ~0.888 Bayes ceiling
    # (well above the 0.636 numerical-only ceiling: the sparse embedding
    # path itself demonstrably learns), rising through training
    assert 0.45 < auc0 < 0.58, auc0
    assert auc1 > 0.82, (auc0, mid, auc1)
    assert auc1 > mid > auc0, (auc0, mid, auc1)


@pytest.mark.slow
def test_bf16_tables_track_fp32_quality():
    """The benched bf16-tables precision is evidence-backed: its trained
    quality tracks fp32 on the same task/seed within a small bound."""
    _, _, auc_fp32 = _train(jnp.float32, seed=11)
    _, _, auc_bf16 = _train(jnp.bfloat16, seed=11)
    assert auc_bf16 > 0.82, auc_bf16
    assert abs(auc_fp32 - auc_bf16) < 0.03, (auc_fp32, auc_bf16)

"""The detlint rule framework (tools/detlint/) and the env-var registry.

Two layers: each rule fires on a seeded violation and stays quiet on the
clean twin (rule unit tests over parsed snippets), and the repo itself is
lint-clean (the dogfood gate — the same invocation `make lint` runs).
No jax involved anywhere here; detlint is pure AST.
"""

import ast
import subprocess
import sys

import pytest

from distributed_embeddings_tpu.utils import envvars
from tools import detlint
from tools.detlint.rules import (bare_except, donated_aux, eager_backend,
                                 env_registry, hardcoded_capacity,
                                 host_fetch, module_scope_jax, named_scope,
                                 spawn_context, thread_shared,
                                 unsized_unique)

CTX = {"repo": detlint.REPO}
PARALLEL = "distributed_embeddings_tpu/parallel/x.py"


def _check(rule, src, path=PARALLEL):
    return rule.check(ast.parse(src), path, src, dict(CTX))


# ------------------------------------------------------------ rule units


def test_bare_except_fires_and_clean():
    assert _check(bare_except, "try:\n    pass\nexcept:\n    pass\n")
    assert not _check(bare_except,
                      "try:\n    pass\nexcept Exception:\n    pass\n")


def test_eager_backend_module_scope_vs_annotated():
    bad = "import jax\nn = jax.device_count()\n"
    assert _check(eager_backend, bad, path="bench.py")
    in_fn = ("import jax\n"
             "def f():\n"
             "    return jax.device_count()\n")
    assert _check(eager_backend, in_fn, path="bench.py")
    ok = ("import jax\n"
          "def f():\n"
          "    return jax.device_count()  # backend-ok: probe-cleared\n")
    assert not _check(eager_backend, ok, path="bench.py")


def test_env_registry_literal_and_constant_resolution():
    assert _check(env_registry,
                  'import os\nv = os.environ.get("DETPU_NOT_A_KNOB")\n')
    assert _check(env_registry,
                  'import os\nX = "DETPU_NOT_A_KNOB"\nv = os.environ[X]\n')
    assert _check(env_registry,
                  'import os\nv = os.getenv("DETPU_NOT_A_KNOB")\n')
    # registered names, writes, and non-DETPU names all pass
    assert not _check(env_registry,
                      'import os\nv = os.environ.get("DETPU_OBS")\n')
    assert not _check(env_registry,
                      'import os\nos.environ["DETPU_NOT_A_KNOB"] = "1"\n')
    assert not _check(env_registry,
                      'import os\nv = os.environ.get("HOME")\n')


def test_host_fetch_rule():
    assert _check(host_fetch, "def f(x):\n    return x.item()\n")
    assert _check(host_fetch,
                  "import jax\ndef f(x):\n    return jax.device_get(x)\n")
    assert not _check(host_fetch,
                      "def f(x):\n    return x.item()  # host-ok: driver\n")
    # .item(key) (dict-style with args) is not an array readback
    assert not _check(host_fetch, "def f(d):\n    return d.item(3)\n")


def test_named_scope_rule():
    bad = ("from jax import lax\n"
           "def f(x):\n"
           "    return lax.all_to_all(x, 'data', 0, 0)\n")
    assert _check(named_scope, bad)
    ok = ("from jax import lax\n"
          "def f(x):\n"
          "    with obs.scope('id_all_to_all'):\n"
          "        return lax.all_to_all(x, 'data', 0, 0)\n")
    assert not _check(named_scope, ok)


def test_unsized_unique_rule():
    """The seeded-violation drill: jnp.unique/nonzero without size= in
    package code fires; size=, the unsized-ok marker, host-side numpy,
    and out-of-package paths stay quiet."""
    path = "distributed_embeddings_tpu/analysis/x.py"
    bad = ("import jax.numpy as jnp\n"
           "def f(ids):\n"
           "    return jnp.unique(ids)\n")
    assert _check(unsized_unique, bad, path=path)
    assert _check(unsized_unique,
                  "import jax\n"
                  "def f(x):\n"
                  "    return jax.numpy.nonzero(x)\n", path=path)
    ok = ("import jax.numpy as jnp\n"
          "def f(ids):\n"
          "    return jnp.unique(ids, size=32, fill_value=0)\n")
    assert not _check(unsized_unique, ok, path=path)
    annotated = ("import jax.numpy as jnp\n"
                 "def f(ids):\n"
                 "    return jnp.unique(ids)  # unsized-ok: eager tooling\n")
    assert not _check(unsized_unique, annotated, path=path)
    # host-side numpy is a different module
    assert not _check(unsized_unique,
                      "import numpy as np\n"
                      "def f(x):\n"
                      "    return np.unique(x)\n", path=path)
    # the rule is scoped to package code only (the runner's SCOPE filter)
    assert detlint._matches(path, unsized_unique.SCOPE)
    assert not detlint._matches("tools/x.py", unsized_unique.SCOPE)


def test_hardcoded_capacity_rule():
    """The seeded drills: a capacity-named constant and a byte-scale
    literal in package code fire; the marker, small literals, hex hash
    constants, and the registry module itself stay quiet."""
    path = "distributed_embeddings_tpu/parallel/x.py"
    # seeded capacity constant (any magnitude) fires
    assert _check(hardcoded_capacity, "V5E_HBM_GB = 16\n", path=path)
    # seeded byte-scale literal fires
    assert _check(hardcoded_capacity,
                  "LIMIT = 17179869184\n", path=path)
    assert _check(hardcoded_capacity,
                  "def f():\n    return 2.7e9\n", path=path)
    # the marker escapes both triggers
    assert not _check(
        hardcoded_capacity,
        "V5E_HBM_GB = 16  # capacity-ok: doc example\n", path=path)
    assert not _check(
        hardcoded_capacity,
        "VOCAB = 2000000000  # capacity-ok: model size\n", path=path)
    # small non-capacity constants and hex bit patterns stay quiet
    assert not _check(hardcoded_capacity, "CHUNK = 128 * 1024 * 1024\n",
                      path=path)
    assert not _check(hardcoded_capacity, "MASK = 0xFFFFFFFFFF\n",
                      path=path)
    # the registry module is the one legitimate home (EXCLUDE'd)
    assert detlint._matches(
        "distributed_embeddings_tpu/analysis/plan_audit.py",
        hardcoded_capacity.EXCLUDE)
    assert not detlint._matches("bench.py", hardcoded_capacity.SCOPE)


def test_module_scope_jax_rule():
    path = "distributed_embeddings_tpu/utils/obs.py"
    assert _check(module_scope_jax, "import jax\n", path=path)
    assert _check(module_scope_jax, "from jax import lax\n", path=path)
    assert not _check(module_scope_jax,
                      "def f():\n    import jax\n    return jax\n",
                      path=path)


# ------------------------------------------------------- framework pieces


def test_donated_aux_registry_resolves():
    reg = donated_aux.registered_aux(detlint.REPO, dict(CTX))
    # the two aux kinds the step builders thread today, in signature
    # order (telemetry first, then streaming — the _with_aux_signature
    # contract)
    assert reg == [("telemetry", "telem"), ("streaming", "stream")]


def test_donated_aux_wrong_order_and_undeclared_drills():
    # seeded wrong-order drill: streaming threaded BEFORE telemetry —
    # jit donation indices and the resilient rewind would then address
    # the wrong buffer
    bad_order = ("def step(state, cat_inputs, batch, stream, telem):\n"
                 "    pass\n")
    found = _check(donated_aux, bad_order)
    assert found and "out of registry order" in found[0].message
    # seeded undeclared drill: a new aux kind threaded without being
    # registered first
    undeclared = ("def step(state, cat_inputs, batch, telem, sched):\n"
                  "    pass\n")
    found = _check(donated_aux, undeclared)
    assert found and "undeclared aux arg 'sched'" in found[0].message


def test_donated_aux_clean_twins():
    for ok in (
        "def step(state, cat_inputs, batch, telem, stream):\n    pass\n",
        "def step(state, cat_inputs, batch, telem):\n    pass\n",
        "def loop(state, cat_stacks, batch_stacks, stream):\n    pass\n",
        # the packed-tuple internal form is exempt (not a jit boundary)
        "def core(state, cat_inputs, batch, aux):\n    pass\n",
        # no trailing aux at all
        "def step(state, cat_inputs, batch):\n    pass\n",
        # not a step-builder signature
        "def f(a, b, c, d):\n    pass\n",
    ):
        assert not _check(donated_aux, ok), ok


def test_spawn_context_rule():
    """Seeded drill: default-context multiprocessing in package code
    fires; the spawn idiom, process-free submodules, and the spawn-ok
    waiver stay quiet."""
    # raw-module factories = default (fork) context
    assert _check(spawn_context,
                  "import multiprocessing\n"
                  "p = multiprocessing.Process(target=f)\n")
    assert _check(spawn_context,
                  "import multiprocessing as mp\n"
                  "pool = mp.Pool(4)\n")
    # importing the factory binds the default context at the import
    assert _check(spawn_context, "from multiprocessing import Process\n")
    assert _check(spawn_context, "from multiprocessing.pool import Pool\n")
    # asking for fork (or the platform default) by name
    assert _check(spawn_context,
                  "import multiprocessing\n"
                  'ctx = multiprocessing.get_context("fork")\n')
    assert _check(spawn_context,
                  "import multiprocessing\n"
                  "ctx = multiprocessing.get_context()\n")
    assert _check(spawn_context,
                  "from multiprocessing import set_start_method\n"
                  'set_start_method("forkserver")\n')
    # the blessed idiom: explicit spawn, factories off the spawn context
    ok = ("import multiprocessing\n"
          '_SPAWN = multiprocessing.get_context("spawn")\n'
          "p = _SPAWN.Process(target=f)\n")
    assert not _check(spawn_context, ok)
    assert not _check(spawn_context,
                      "from multiprocessing import get_context\n"
                      'ctx = get_context(method="spawn")\n')
    # process-free corners start nothing
    assert not _check(spawn_context,
                      "from multiprocessing import shared_memory\n"
                      "from multiprocessing.connection import Client\n"
                      "from multiprocessing import resource_tracker\n")
    # the waiver
    assert not _check(spawn_context,
                      "import multiprocessing\n"
                      "p = multiprocessing.Process(target=f)"
                      "  # spawn-ok: no jax in this process\n")
    # out of scope (scoping is the runner's job): tests may fork freely
    assert not detlint._matches("tests/test_shm.py", spawn_context.SCOPE)
    assert detlint._matches(
        "distributed_embeddings_tpu/parallel/supervisor.py",
        spawn_context.SCOPE)


def test_thread_shared_rule():
    """Seeded drill: a thread-spawning class without a _THREAD_SHARED
    declaration fires; the declared twin, the empty-tuple declaration,
    the waiver, spawn-free classes, and module-level spawns stay quiet."""
    spawning = ("import threading\n"
                "class Driver:\n"
                "    def start(self):\n"
                "        threading.Thread(target=self._run).start()\n")
    found = _check(thread_shared, spawning)
    assert found and "_THREAD_SHARED" in found[0].message
    # Thread subclasses spawn themselves — same obligation
    assert _check(thread_shared,
                  "from threading import Thread\n"
                  "class W(Thread):\n"
                  "    def run(self):\n"
                  "        pass\n")
    # a non-tuple declaration is its own finding (the auditor parses it)
    bad_decl = ("import threading\n"
                "class Driver:\n"
                "    _THREAD_SHARED = ['_x']\n"
                "    def start(self):\n"
                "        threading.Thread(target=self._run).start()\n")
    found = _check(thread_shared, bad_decl)
    assert found and "literal tuple" in found[0].message
    # the declared twin (non-empty and empty both count)
    assert not _check(thread_shared,
                      "import threading\n"
                      "class Driver:\n"
                      '    _THREAD_SHARED = ("_results",)\n'
                      "    def start(self):\n"
                      "        threading.Thread(target=self._run).start()\n")
    assert not _check(thread_shared,
                      "import threading\n"
                      "class Driver:\n"
                      "    _THREAD_SHARED = ()\n"
                      "    def start(self):\n"
                      "        threading.Thread(target=self._run).start()\n")
    # the waiver on the spawn line
    assert not _check(thread_shared,
                      "import threading\n"
                      "class Driver:\n"
                      "    def start(self):\n"
                      "        threading.Thread(target=f).start()"
                      "  # thread-shared-ok: script helper\n")
    # spawn-free classes and module-level spawns carry no obligation
    assert not _check(thread_shared,
                      "import threading\n"
                      "class Plain:\n"
                      "    pass\n"
                      "threading.Thread(target=f).start()\n")
    # scoped to the package; tests/tools may spawn undeclared
    assert detlint._matches(
        "distributed_embeddings_tpu/parallel/serving.py",
        thread_shared.SCOPE)
    assert not detlint._matches("tests/test_shm.py", thread_shared.SCOPE)
    assert not detlint._matches("tools/x.py", thread_shared.SCOPE)


def test_discover_rules_finds_all():
    rules = detlint.discover_rules()
    assert {"bare-except", "eager-backend", "env-registry",
            "hardcoded-capacity", "host-fetch", "module-scope-jax",
            "named-scope-exchange", "spawn-context", "thread-shared",
            "unsized-unique"} <= set(rules)


def test_unknown_rule_name_raises():
    with pytest.raises(ValueError, match="unknown detlint rule"):
        detlint.run(rule_names=["no-such-rule"])


def test_repo_is_lint_clean():
    """Dogfood: the tree ships with zero findings (the make lint gate)."""
    findings = detlint.run()
    assert findings == [], "\n".join(str(f) for f in findings)


def test_cli_clean_and_seeded(tmp_path):
    """End-to-end CLI: clean repo exits 0; a seeded unregistered env read
    (written under a real checked path inside a scratch repo copy is
    overkill — a direct rule-scoped file list does it) exits 1."""
    r = subprocess.run([sys.executable, "-m", "tools.detlint"],
                       cwd=detlint.REPO, capture_output=True, text=True,
                       timeout=120)
    assert r.returncode == 0, r.stderr
    assert "OK" in r.stdout


def test_registry_roundtrip():
    """The AST-extracted registry matches the imported module's view, and
    the runtime helpers enforce membership."""
    names = env_registry.registered_names(detlint.REPO)
    assert names == set(envvars.registered())
    assert "DETPU_OBS" in names and "DETPU_FAULT" in names
    with pytest.raises(KeyError, match="not a registered"):
        envvars.get("DETPU_NOT_A_KNOB")
    with pytest.raises(KeyError):
        envvars.enabled("DETPU_NOT_A_KNOB")


def test_envvars_semantics(monkeypatch):
    monkeypatch.delenv("DETPU_NANGUARD", raising=False)
    assert envvars.enabled("DETPU_NANGUARD")  # declared default "1"
    monkeypatch.setenv("DETPU_NANGUARD", "0")
    assert not envvars.enabled("DETPU_NANGUARD")
    monkeypatch.setenv("DETPU_NANGUARD_K", "7")
    assert envvars.get_int("DETPU_NANGUARD_K", 3) == 7
    monkeypatch.setenv("DETPU_NANGUARD_K", "bogus")
    assert envvars.get_int("DETPU_NANGUARD_K", 3) == 3
    monkeypatch.setenv("DETPU_PROBE_TIMEOUT_S", "2.5")
    assert envvars.get_float("DETPU_PROBE_TIMEOUT_S") == 2.5


def test_legacy_shim_still_green():
    """tools/check_no_eager_backend.py (kept for make verify mid-
    transition) delegates to the detlint rule and stays green."""
    r = subprocess.run(
        [sys.executable, "tools/check_no_eager_backend.py"],
        cwd=detlint.REPO, capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    assert "OK" in r.stdout

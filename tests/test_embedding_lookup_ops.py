"""Golden tests for the functional lookup op layer.

Mirrors the reference's op test strategy
(``distributed_embeddings/python/ops/embedding_lookup_ops_test.py``): generate
random multi-hot batches with no empty rows, compare the fused ragged/sparse
paths against a dense gather + reduce oracle, and check gradients agree.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_embeddings_tpu.ops import (
    Ragged,
    SparseIds,
    combiner_grad_values,
    dedup_sparse_grad,
    embedding_lookup,
    row_to_split,
)


def make_ragged_case(rng, batch, vocab, max_hot, capacity=None):
    """Random ragged batch with hotness in [1, max_hot] (no empty rows,
    matching the reference generator at ``embedding_lookup_ops_test.py:25-33``)."""
    hots = rng.integers(1, max_hot + 1, size=batch)
    rows = [list(rng.integers(0, vocab, size=h)) for h in hots]
    return rows, Ragged.from_lists(rows, capacity=capacity)


def oracle(params, rows, combiner):
    outs = []
    for r in rows:
        emb = np.asarray(params)[np.asarray(r)]
        outs.append(emb.sum(0) if combiner == "sum" else emb.mean(0))
    return np.stack(outs)


@pytest.mark.parametrize("combiner", ["sum", "mean"])
def test_ragged_matches_oracle(combiner):
    rng = np.random.default_rng(0)
    params = jnp.asarray(rng.normal(size=(50, 8)), jnp.float32)
    rows, ragged = make_ragged_case(rng, batch=16, vocab=50, max_hot=7)
    out = embedding_lookup(params, ragged, combiner=combiner)
    np.testing.assert_allclose(out, oracle(params, rows, combiner), rtol=1e-6)


@pytest.mark.parametrize("combiner", ["sum", "mean"])
def test_ragged_padding_ignored(combiner):
    rng = np.random.default_rng(1)
    params = jnp.asarray(rng.normal(size=(30, 4)), jnp.float32)
    rows, _ = make_ragged_case(rng, batch=8, vocab=30, max_hot=5)
    exact = Ragged.from_lists(rows)
    padded = Ragged.from_lists(rows, capacity=exact.capacity + 13)
    # poison the padding with in-range ids: must not change the result
    padded = padded.replace(
        values=padded.values.at[exact.capacity:].set(7))
    np.testing.assert_allclose(
        embedding_lookup(params, padded, combiner=combiner),
        embedding_lookup(params, exact, combiner=combiner), rtol=1e-6)


def test_empty_rows_give_zero_sum():
    params = jnp.ones((10, 4), jnp.float32)
    ragged = Ragged(values=jnp.array([1, 2], jnp.int32),
                    row_splits=jnp.array([0, 0, 2, 2], jnp.int32))
    out = embedding_lookup(params, ragged, combiner="sum")
    np.testing.assert_allclose(out, [[0] * 4, [2] * 4, [0] * 4])
    out = embedding_lookup(params, ragged, combiner="mean")
    np.testing.assert_allclose(out, [[0] * 4, [1] * 4, [0] * 4])


@pytest.mark.parametrize("combiner", [None, "sum", "mean"])
def test_dense_2d(combiner):
    rng = np.random.default_rng(2)
    params = jnp.asarray(rng.normal(size=(20, 4)), jnp.float32)
    ids = jnp.asarray(rng.integers(0, 20, size=(6, 3)))
    out = embedding_lookup(params, ids, combiner=combiner)
    dense = np.asarray(params)[np.asarray(ids)]
    if combiner is None:
        np.testing.assert_allclose(out, dense)
    else:
        np.testing.assert_allclose(
            out, dense.sum(1) if combiner == "sum" else dense.mean(1), rtol=1e-6)


def test_dense_hotness_one_squeeze():
    params = jnp.asarray(np.arange(12, dtype=np.float32).reshape(6, 2))
    ids = jnp.array([[3], [0], [5]])
    out = embedding_lookup(params, ids, combiner="sum")
    np.testing.assert_allclose(out, np.asarray(params)[[3, 0, 5]])


def test_row_to_split_and_sparse_path():
    rng = np.random.default_rng(3)
    params = jnp.asarray(rng.normal(size=(40, 8)), jnp.float32)
    rows, ragged = make_ragged_case(rng, batch=10, vocab=40, max_hot=4)
    coo_rows = np.repeat(np.arange(10), [len(r) for r in rows])
    cols = np.concatenate([np.arange(len(r)) for r in rows])
    indices = jnp.asarray(np.stack([coo_rows, cols], 1))
    splits = row_to_split(indices, 10)
    np.testing.assert_array_equal(splits, ragged.row_splits)

    sparse = SparseIds(indices=indices,
                       values=ragged.values[: indices.shape[0]],
                       dense_shape=(10, 4))
    np.testing.assert_allclose(
        embedding_lookup(params, sparse, combiner="mean"),
        oracle(params, rows, "mean"), rtol=1e-6)


def test_dense_weights_applied_at_any_hotness():
    params = jnp.asarray(np.arange(10, dtype=np.float32).reshape(5, 2))
    w2 = embedding_lookup(params, jnp.array([[1, 2]]), combiner="sum",
                          weights=jnp.array([[2.0, 3.0]]))
    np.testing.assert_allclose(w2, 2 * np.asarray(params)[1:2] + 3 * np.asarray(params)[2:3])
    # hotness 1 must honor weights too (not take the squeeze fast path)
    w1 = embedding_lookup(params, jnp.array([[3]]), combiner="sum",
                          weights=jnp.array([[4.0]]))
    np.testing.assert_allclose(w1, 4 * np.asarray(params)[3:4])


@pytest.mark.parametrize("combiner", ["sum", "mean"])
def test_ragged_grad_matches_dense_oracle(combiner):
    """Grad-equivalence, the reference's trick at ``embedding_test.py:133-181``:
    autodiff through the fused path must equal autodiff through the oracle."""
    rng = np.random.default_rng(4)
    params = jnp.asarray(rng.normal(size=(25, 4)), jnp.float32)
    hot = 3  # uniform hotness so the dense oracle applies
    ids = rng.integers(0, 25, size=(8, hot))
    ragged = Ragged.from_lists([list(r) for r in ids])

    def fused(p):
        return jnp.sum(embedding_lookup(p, ragged, combiner=combiner) ** 2)

    def dense(p):
        g = jnp.take(p, jnp.asarray(ids), axis=0)
        red = jnp.sum(g, 1) if combiner == "sum" else jnp.mean(g, 1)
        return jnp.sum(red ** 2)

    np.testing.assert_allclose(jax.grad(fused)(params), jax.grad(dense)(params),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("combiner", ["sum", "mean"])
def test_manual_sparse_grad_matches_autodiff(combiner):
    """combiner_grad_values + dedup_sparse_grad must reproduce the dense
    parameter gradient when scattered — the (unique_ids, unique_grad)
    IndexedSlices contract of the reference backward."""
    rng = np.random.default_rng(5)
    vocab, width = 30, 4
    params = jnp.asarray(rng.normal(size=(vocab, width)), jnp.float32)
    rows, ragged = make_ragged_case(rng, batch=12, vocab=vocab, max_hot=5,
                                    capacity=80)

    def loss(p):
        return jnp.sum(embedding_lookup(p, ragged, combiner=combiner) ** 2)

    auto = jax.grad(loss)(params)

    out = embedding_lookup(params, ragged, combiner=combiner)
    out_grad = 2 * out
    vals = combiner_grad_values(out_grad, ragged.row_splits, ragged.capacity,
                                combiner)
    uids, ugrads = dedup_sparse_grad(ragged.values, vals, pad_id=vocab,
                                     valid=jnp.arange(80) < ragged.row_splits[-1])
    manual = jnp.zeros_like(params).at[uids].add(ugrads, mode="drop")
    np.testing.assert_allclose(manual, auto, rtol=1e-5, atol=1e-6)
    # unique ids really are unique (excluding pad)
    uids_np = np.asarray(uids)
    real = uids_np[uids_np < vocab]
    assert len(real) == len(set(real))

"""Cross-process request tracing (``utils/reqtrace.py`` + its serving
and supervisor integration).

The contracts under test:

* every terminal outcome — ``Served``, ``Expired``, ``Overloaded``,
  ``Failed``, ``Unavailable`` — carries a ``spans`` partition whose
  values sum to its ``latency_ms`` within ``SPAN_SUM_TOL_MS``, and
  books a trace with the same invariant;
* retention is tail-based and DETERMINISTIC: unhealthy outcomes always
  retain, the rest by a seeded hash of the trace id (no wall clock, no
  ``random``) or the latency top decile — two buffers with the same
  seed retain identical sets;
* the retained ring is bounded: a 10x burst past capacity evicts
  oldest-first and never grows the ring;
* the Chrome export round-trips through the jax-free
  ``utils/traceparse.parse_request_traces`` reader (gzip included) and
  stays out of the device-event parser's way;
* the flight recorder's black box carries the trace ring under its CRC:
  a tampered ``traces`` entry fails ``verify_blackbox``;
* the metrics federation primitives (``merge_registry_docs`` /
  ``render_doc`` / ``add_federated``) merge sketches, sum counters, and
  render one scrape document without duplicating metadata.
"""

import gzip
import json
import os
import zlib

import numpy as np
import jax.numpy as jnp
import pytest

from distributed_embeddings_tpu.parallel import (
    Expired, Failed, Overloaded, Served, Unavailable)
from distributed_embeddings_tpu.parallel import serving as sv
from distributed_embeddings_tpu.utils import mplane, obs, reqtrace, traceparse

from tests.test_serving import _build, _req, _tmpl


def _buf(**kw):
    kw.setdefault("capacity", 64)
    kw.setdefault("sample", 1.0)
    kw.setdefault("seed", 0)
    kw.setdefault("enabled", True)
    return reqtrace.TraceBuffer(**kw)


def _finish_one(buf, rid, outcome="served", latency_ms=5.0, t0=100.0,
                stages=None, **attrs):
    buf.begin(rid, t0)
    return buf.finish(rid, outcome, latency_ms, t0 + latency_ms / 1e3,
                      stages or {"queue_wait": latency_ms}, **attrs)


# ---------------------------------------------------- retention policy


def test_unhealthy_outcomes_always_retained():
    buf = _buf(sample=0.0)   # sampling would drop EVERY healthy trace
    for i, outcome in enumerate(
            ("expired", "failed", "overloaded", "unavailable")):
        tr = _finish_one(buf, i, outcome=outcome)
        assert tr is not None and tr["retained_because"] == "outcome"
    assert _finish_one(buf, 99, outcome="served") is None
    st = buf.stats()
    assert st["retained"] == 4 and st["sampled_out"] == 1


def test_top_decile_retention_overrides_sampling():
    thresh = {"v": None}
    buf = _buf(sample=0.0, top_fn=lambda: thresh["v"])
    assert _finish_one(buf, 0, latency_ms=50.0) is None  # cold: sampled
    thresh["v"] = 10.0
    tr = _finish_one(buf, 1, latency_ms=50.0)
    assert tr is not None and tr["retained_because"] == "top_decile"
    assert _finish_one(buf, 2, latency_ms=5.0) is None   # under threshold


def test_sampling_is_seeded_and_deterministic():
    def retained_ids(seed):
        buf = _buf(capacity=1024, sample=0.35, seed=seed)
        for i in range(200):
            _finish_one(buf, i)
        return [t["trace_id"] for t in buf.snapshot()]

    a, b = retained_ids(7), retained_ids(7)
    assert a == b and 0 < len(a) < 200
    assert retained_ids(8) != a
    # the decision is a pure function of (seed, trace_id) — crc32, no
    # wall clock, no random module
    tid = reqtrace.TraceBuffer(seed=7).mint(3)
    expect = (zlib.crc32(f"7:{tid}".encode()) & 0xFFFFFFFF) / 2.0 ** 32
    assert reqtrace.hash01(7, tid) == expect


def test_ring_bounded_under_10x_burst():
    buf = _buf(capacity=16)
    for i in range(160):
        _finish_one(buf, i)
    snap = buf.snapshot()
    assert len(snap) == 16
    st = buf.stats()
    assert st["retained"] == 16 and st["evicted"] == 144
    # oldest evicted, newest kept
    assert [t["rid"] for t in snap] == list(range(144, 160))


# ------------------------------------------- post-hoc marks and drains


def test_append_event_annotate_and_exactly_once_drain():
    buf = _buf()
    tr = _finish_one(buf, 0, outcome="unavailable")
    assert buf.append_event(tr["trace_id"], "worker_restarted", t=101.0)
    assert buf.annotate(tr["trace_id"], restart_crossed=True)
    assert not buf.append_event("t-missing", "x")
    assert not buf.annotate("t-missing", x=1)
    got = buf.drain_new()
    assert [t["trace_id"] for t in got] == [tr["trace_id"]]
    assert got[0]["attrs"]["restart_crossed"]
    assert buf.drain_new() == []          # cursor advanced
    _finish_one(buf, 1, outcome="failed")
    assert len(buf.drain_new()) == 1      # only the new one


def test_disabled_buffer_noops():
    buf = _buf(enabled=False)
    assert buf.begin(0, 1.0) is None
    assert buf.finish(0, "failed", 1.0, 1.0, {"queue_wait": 1.0}) is None
    assert buf.snapshot() == [] and not buf.stats()["enabled"]


# -------------------------------------------- Chrome export round trip


def test_chrome_export_roundtrip_and_namespace(tmp_path):
    buf = _buf()
    _finish_one(buf, 0, latency_ms=4.0,
                stages={"queue_wait": 1.0, "coalesce": 0.5,
                        "dispatch": 0.5, "device_compute": 1.5,
                        "reply_slice": 0.5},
                flush=3, coalesced=2, flush_t0=100.0005)
    tr = _finish_one(buf, 1, outcome="unavailable", latency_ms=20.0)
    buf.append_event(tr["trace_id"], "worker_restarted", t=101.0)
    buf.annotate(tr["trace_id"], restart_crossed=True)

    for name in ("req.trace.json", "req.trace.json.gz"):
        path = os.path.join(tmp_path, name)
        buf.export(path)
        opener = gzip.open if name.endswith(".gz") else open
        with opener(path, "rb") as f:
            doc = json.loads(f.read().decode())
        names = {e["name"] for e in doc["traceEvents"]}
        assert obs.REQ_EVENT_PREFIX + "served" in names
        assert obs.REQ_EVENT_PREFIX + "stage/device_compute" in names
        assert obs.REQ_EVENT_PREFIX + "mark/worker_restarted" in names
        assert obs.REQ_EVENT_PREFIX + "flush" in names

        parsed = {t["trace_id"]: t
                  for t in traceparse.parse_request_traces(path)}
        assert len(parsed) == 2
        served = next(t for t in parsed.values()
                      if t["outcome"] == "served")
        assert abs(sum(served["stages_ms"].values())
                   - served["latency_ms"]) <= reqtrace.SPAN_SUM_TOL_MS
        crossed = parsed[tr["trace_id"]]
        assert crossed["attrs"]["restart_crossed"]
        assert any(e["name"] == "worker_restarted"
                   for e in crossed["events"])
        # the request namespace stays OUT of the device-event parser
        assert traceparse.parse_events(doc) == []


# ----------------------------------- every terminal outcome has spans


def _spans_sum_ok(res):
    assert res.spans, f"{type(res).__name__} carries no spans"
    assert abs(sum(res.spans.values()) - res.latency_ms) \
        <= reqtrace.SPAN_SUM_TOL_MS


def _retain_all(rt):
    rt.traces = reqtrace.TraceBuffer(
        capacity=64, sample=1.0, seed=0, enabled=True, process="serve",
        top_fn=rt._trace_top_decile)


def test_served_spans_partition_and_trace(monkeypatch):
    de, state, rt, clock = _build()
    _retain_all(rt)
    rt.warmup(_tmpl())
    rng = np.random.default_rng(0)
    rt.submit(_req(rng, n=3))
    res = rt.flush()
    assert len(res) == 1 and isinstance(res[0], Served)
    _spans_sum_ok(res[0])
    assert set(res[0].spans) == {f"{s}_ms" for s in sv.STAGES}
    (tr,) = rt.traces.snapshot()
    assert tr["outcome"] == "served"
    assert set(tr["stages_ms"]) == set(sv.STAGES)
    assert abs(sum(tr["stages_ms"].values()) - tr["latency_ms"]) \
        <= reqtrace.SPAN_SUM_TOL_MS
    assert tr["attrs"]["coalesced"] == 1


def test_expired_overloaded_failed_spans():
    de, state, rt, clock = _build(max_batch=8, max_queue=8)
    _retain_all(rt)
    rt.warmup(_tmpl())
    rng = np.random.default_rng(1)

    # Expired: the deadline passes before any flush
    tight = _req(rng, n=2)
    tight.deadline_ms = 5.0
    rt.submit(tight)
    clock.t += 1.0
    expired = [r for r in rt.poll() if isinstance(r, Expired)]
    assert len(expired) == 1
    _spans_sum_ok(expired[0])
    assert expired[0].spans == {"queue_wait_ms": expired[0].latency_ms}

    # Overloaded: flood past max_queue
    shed = []
    for _ in range(24):
        r = rt.submit(_req(rng, n=2))
        if isinstance(r, Overloaded):
            shed.append(r)
    assert shed
    _spans_sum_ok(shed[0])
    rt.flush()

    # Failed: the flush itself raises -> typed Failed, spans intact
    def boom(reqs, rung):
        raise RuntimeError("boom")
    rt._run_flush = boom
    rt.submit(_req(rng, n=2))
    clock.t += 1.0
    failed = [r for r in rt.flush() if isinstance(r, Failed)]
    assert len(failed) == 1 and "boom" in failed[0].reason
    _spans_sum_ok(failed[0])

    by_outcome = {t["outcome"] for t in rt.traces.snapshot()}
    assert {"expired", "overloaded", "failed"} <= by_outcome
    for t in rt.traces.snapshot():
        assert abs(sum(t["stages_ms"].values()) - t["latency_ms"]) \
            <= reqtrace.SPAN_SUM_TOL_MS


def test_unavailable_spans_from_unstarted_supervisor():
    from distributed_embeddings_tpu.parallel import Supervisor

    sup = Supervisor("tools.isolation_common:worker_factory")
    try:
        res = sup.submit(sv.Request(cats=[np.zeros(1, np.int32)]))
        assert isinstance(res, Unavailable)
        _spans_sum_ok(res)
        (tr,) = sup.traces.snapshot()
        assert tr["outcome"] == "unavailable"
        assert tr["retained_because"] == "outcome"
        assert sup._outage_trace == tr["trace_id"]
    finally:
        sup._listener.close()


def test_stats_unhealthy_view_and_exemplars():
    de, state, rt, clock = _build()
    _retain_all(rt)
    rt.warmup(_tmpl())
    rng = np.random.default_rng(2)
    tight = _req(rng, n=2)
    tight.deadline_ms = 5.0
    rt.submit(tight)
    clock.t += 1.0
    rt.poll()
    rt.submit(_req(rng, n=2))
    rt.flush()
    st = rt.stats()
    # the plain per-stage view keeps EXACTLY the five healthy children
    # (check_obsplane's stage-ratio gate sums them against served p99)
    assert set(st["latency_stages_ms"]) == set(sv.STAGES)
    assert "expired" in st["latency_stages_unhealthy_ms"]
    assert st["latency_stages_unhealthy_ms"]["expired"]["count"] == 1
    assert st["trace"]["retained"] == len(rt.traces.snapshot())
    exemplars = st["p99_exemplars"]
    assert exemplars and all(
        {"trace_id", "outcome", "latency_ms", "dominant_stage"}
        <= set(e) for e in exemplars)
    # exemplars rank by latency, slowest first
    lats = [e["latency_ms"] for e in exemplars]
    assert lats == sorted(lats, reverse=True)


# --------------------------------------------- flight-recorder blackbox


def test_blackbox_carries_traces_under_crc(tmp_path):
    path = os.path.join(tmp_path, "bb.blackbox.json")
    rec = mplane.FlightRecorder(path)
    buf = _buf()
    _finish_one(buf, 0, outcome="failed", latency_ms=7.0)
    for tr in buf.drain_new():
        rec.note_trace(tr)
    rec.dump("test", reason="trace_ring")
    payload = mplane.verify_blackbox(path)
    assert payload["traces"] and \
        payload["traces"][0]["outcome"] == "failed"

    # tampering with a trace breaks the CRC: the ring is COVERED, not
    # appended outside the envelope
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    doc["payload"]["traces"][0]["latency_ms"] = 1e9
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    with pytest.raises(ValueError):
        mplane.verify_blackbox(path)


# ------------------------------------------------- metrics federation


def _doc_with(counter=None, sketch_vals=(), gauge=None):
    reg = mplane.MetricsRegistry()
    if counter is not None:
        reg.counter("detpu_test_total", "t").inc(counter)
    if sketch_vals:
        fam = reg.sketch("detpu_test_ms", "t")
        for v in sketch_vals:
            fam.observe(v)
    if gauge is not None:
        reg.gauge("detpu_test_g", "t").set(gauge)
    return reg.to_dict()


def test_merge_registry_docs_sums_and_merges():
    a = _doc_with(counter=2.0, sketch_vals=(1.0, 2.0), gauge=5.0)
    b = _doc_with(counter=3.0, sketch_vals=(3.0,), gauge=9.0)
    a_json = json.dumps(a, sort_keys=True)
    merged = mplane.merge_registry_docs([a, b])
    assert json.dumps(a, sort_keys=True) == a_json   # inputs untouched

    (cnt,) = merged["detpu_test_total"]["series"]
    assert cnt["value"] == 5.0
    (summ,) = merged["detpu_test_ms"]["series"]
    sk = mplane.QuantileSketch.from_dict(summ["value"])
    assert sk.count == 3
    (g,) = merged["detpu_test_g"]["series"]
    assert g["value"] == 9.0                          # gauge: last wins


def test_render_doc_skips_duplicate_metadata():
    doc = _doc_with(counter=1.0, sketch_vals=(2.0,))
    text = mplane.render_doc(doc)
    assert "# HELP detpu_test_total" in text
    assert "detpu_test_ms_count 1" in text
    skipped = mplane.render_doc(doc, skip_meta_for={"detpu_test_total"})
    assert "# HELP detpu_test_total" not in skipped
    assert "detpu_test_total 1" in skipped


def test_add_federated_serves_one_merged_view():
    sup = mplane.MetricsRegistry()
    sup.counter("detpu_supervisor_total", "s").inc()
    worker_doc = _doc_with(counter=4.0, sketch_vals=(1.0, 5.0))
    sup.add_federated(lambda: worker_doc)
    text = sup.render()
    assert "detpu_supervisor_total 1" in text
    assert "detpu_test_total 4" in text
    assert "detpu_test_ms_count 2" in text
    # a failing source degrades to the registry's own families
    sup.add_federated(lambda: (_ for _ in ()).throw(RuntimeError("x")))
    assert "detpu_supervisor_total 1" in sup.render()

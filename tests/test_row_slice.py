"""Row slicing: the mode the reference declares but never implements
(``dist_model_parallel.py:225,233-234``) — implemented here (VERDICT r3
stretch). A row-sliced table's vocab splits into ranges placed like
independent tables; each slice serves only in-range ids (zero rows outside)
and slice outputs sum.

Tests: forward oracle parity (dense 1-hot / multi-hot sum+mean / ragged over
a row-sliced table), full-train-step parity sliced vs UNsliced from identical
weights, checkpoint roundtrip through the row-range slice plan, and the
masked_reads debug contract."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_embeddings_tpu.ops.embedding_lookup import Ragged
from distributed_embeddings_tpu.parallel import (
    DistributedEmbedding, SparseSGD, make_hybrid_train_step, HybridTrainState)
from distributed_embeddings_tpu.parallel.strategy import maybe_slice_table_row

WORLD = 8
B = 16  # global batch


@pytest.fixture(scope="module")
def mesh():
    return Mesh(np.array(jax.devices()[:WORLD]), ("data",))


def _configs():
    # table 0 is big -> row-sliced into 4 ranges of 25 rows; the rest plain
    return [
        {"input_dim": 100, "output_dim": 8, "combiner": None},
        {"input_dim": 30, "output_dim": 8, "combiner": "sum"},
        {"input_dim": 100, "output_dim": 8, "combiner": "mean"},
        {"input_dim": 40, "output_dim": 8, "combiner": None},
        {"input_dim": 26, "output_dim": 8, "combiner": "sum"},
        {"input_dim": 100, "output_dim": 4, "combiner": "sum"},
        {"input_dim": 22, "output_dim": 8, "combiner": None},
        {"input_dim": 24, "output_dim": 8, "combiner": None},
    ]


ROW_THR = 100 * 8 // 4 + 1  # tables with 100 rows split into 4 row slices


def _tables(rng, configs):
    return [rng.normal(size=(c["input_dim"], c["output_dim"])
                       ).astype(np.float32) for c in configs]


def _make_inputs(rng, configs):
    cats, oracle = [], []
    for cfg in configs:
        if cfg["combiner"] is None:
            ids = rng.integers(0, cfg["input_dim"], size=(B,))
            cats.append(jnp.asarray(ids, jnp.int32))
            oracle.append(("d1", ids))
        elif cfg["input_dim"] == 100 and cfg["combiner"] == "mean":
            # ragged over the row-sliced mean table
            lens = rng.integers(0, 4, size=B)
            splits = np.concatenate([[0], np.cumsum(lens)]).astype(np.int32)
            cap = 4 * (B // WORLD) * WORLD
            vals = np.zeros(cap, np.int32)
            vals[:splits[-1]] = rng.integers(0, 100, size=int(splits[-1]))
            # flat per-shard CSR blocks (shard s: rows s*cap/W, splits local)
            per = B // WORLD
            v_parts, s_parts = [], []
            for s in range(WORLD):
                lo, hi = splits[s * per], splits[(s + 1) * per]
                seg = np.zeros(cap // WORLD, np.int32)
                seg[:hi - lo] = vals[lo:hi]
                v_parts.append(seg)
                s_parts.append((splits[s * per:(s + 1) * per + 1]
                                - lo).astype(np.int32))
            cats.append(Ragged(values=jnp.asarray(np.concatenate(v_parts)),
                               row_splits=jnp.asarray(
                                   np.concatenate(s_parts))))
            oracle.append(("r", (vals, splits)))
        else:
            hot = 3
            ids = rng.integers(0, cfg["input_dim"], size=(B, hot))
            cats.append(jnp.asarray(ids, jnp.int32))
            oracle.append(("dh", ids))
    return cats, oracle


def _oracle_outs(tables, configs, oracle):
    outs = []
    for cfg, tab, (kind, data) in zip(configs, tables, oracle):
        if kind == "d1":
            outs.append(tab[data])
        elif kind == "dh":
            red = tab[data].sum(axis=1)
            if cfg["combiner"] == "mean":
                red = red / data.shape[1]
            outs.append(red)
        else:
            vals, splits = data
            o = np.zeros((B, tab.shape[1]), np.float32)
            for i in range(B):
                seg = vals[splits[i]:splits[i + 1]]
                if len(seg):
                    o[i] = tab[seg].sum(0) / (
                        len(seg) if cfg["combiner"] == "mean" else 1)
            outs.append(o)
    return outs


def _dist_forward(de, params, cats, mesh):
    n = len(cats)

    def f(p, *cs):
        return [o.astype(jnp.float32) for o in de(p, list(cs))]

    sm = jax.shard_map(
        f, mesh=mesh, in_specs=(P("data"),) + (P("data"),) * n,
        out_specs=P("data"))
    return sm(params, *cats)


def test_maybe_slice_table_row_geometry():
    cfg = {"input_dim": 103, "output_dim": 8}
    slices = maybe_slice_table_row(cfg, 103 * 8 // 4 + 1, 8)
    assert len(slices) == 4
    assert [s["input_dim"] for s in slices] == [26, 26, 26, 25]
    assert [s["_row_base"] for s in slices] == [0, 26, 52, 78]
    assert maybe_slice_table_row(cfg, None, 8) == [dict(cfg)]


def test_row_sliced_forward_matches_oracle(mesh):
    rng = np.random.default_rng(0)
    configs = _configs()
    de = DistributedEmbedding(configs, world_size=WORLD,
                              strategy="memory_balanced", row_slice=ROW_THR)
    assert de.strategy.row_sliced_tables  # the big tables actually split
    tables = _tables(rng, configs)
    params = de.set_weights(tables, mesh=mesh)
    cats, oracle = _make_inputs(rng, configs)
    outs = _dist_forward(de, params, cats, mesh)
    want = _oracle_outs(tables, configs, oracle)
    for t, (o, w_) in enumerate(zip(outs, want)):
        np.testing.assert_allclose(np.asarray(o), w_, rtol=1e-5, atol=1e-5,
                                   err_msg=f"table {t}")


def test_row_sliced_train_step_matches_unsliced(mesh):
    rng = np.random.default_rng(1)
    configs = _configs()
    tables = _tables(rng, configs)
    cats, _ = _make_inputs(rng, configs)
    y = jnp.asarray(rng.normal(size=(B, 1)) * 0.1, jnp.float32)
    cols = sum(c["output_dim"] for c in configs)
    wvec = jnp.asarray(rng.normal(size=(cols, 1)) * 0.3, jnp.float32)

    def run(row_slice):
        de = DistributedEmbedding(configs, world_size=WORLD,
                                  strategy="memory_balanced",
                                  row_slice=row_slice)
        params = de.set_weights(tables, mesh=mesh)
        emb_opt = SparseSGD()
        tx = optax.sgd(0.5)
        dp = {"w": jnp.array(wvec)}

        def loss_fn(dpar, outs, batch):
            x = jnp.concatenate(
                [o.reshape(o.shape[0], -1) for o in outs], axis=1)
            return jnp.mean((x @ dpar["w"] - batch) ** 2)

        state = HybridTrainState(
            emb_params=params, emb_opt_state=emb_opt.init(params),
            dense_params=dp, dense_opt_state=tx.init(dp),
            step=jnp.zeros((), jnp.int32))
        step = make_hybrid_train_step(de, loss_fn, tx, emb_opt, mesh=mesh,
                                      lr_schedule=0.3)
        y_sh = jax.device_put(y, NamedSharding(mesh, P("data")))
        loss, state = step(state, cats, y_sh)
        return float(loss), de.get_weights(state.emb_params)

    loss_a, tabs_a = run(None)
    loss_b, tabs_b = run(ROW_THR)
    assert abs(loss_a - loss_b) < 1e-5
    for t, (ta, tb) in enumerate(zip(tabs_a, tabs_b)):
        np.testing.assert_allclose(ta, tb, rtol=1e-5, atol=1e-6,
                                   err_msg=f"table {t}")


def test_row_sliced_checkpoint_roundtrip(mesh):
    rng = np.random.default_rng(2)
    configs = _configs()
    de = DistributedEmbedding(configs, world_size=WORLD,
                              strategy="basic", row_slice=ROW_THR)
    tables = _tables(rng, configs)
    params = de.set_weights(tables, mesh=mesh)
    back = de.get_weights(params)
    for t, (a, b) in enumerate(zip(tables, back)):
        np.testing.assert_array_equal(a, b, err_msg=f"table {t}")


def test_masked_reads_zero_out_of_range(mesh):
    configs = [{"input_dim": 16 + i, "output_dim": 4, "combiner": None}
               for i in range(WORLD)]
    rng = np.random.default_rng(3)
    tables = _tables(rng, configs)
    ids = [jnp.asarray(rng.integers(0, c["input_dim"], size=(B,)), jnp.int32)
           for c in configs]
    bad = np.asarray(ids[0]).copy()
    bad[::3] = 10_000  # way out of range
    ids[0] = jnp.asarray(bad)

    de = DistributedEmbedding(configs, world_size=WORLD, masked_reads=True)
    params = de.set_weights(tables, mesh=mesh)
    outs = _dist_forward(de, params, ids, mesh)
    out0 = np.asarray(outs[0])
    assert np.all(out0[::3] == 0.0)  # bad ids read zero rows
    good = np.asarray(ids[0])[1::3]
    np.testing.assert_allclose(out0[1::3], tables[0][good], rtol=1e-6)

"""The RawBinaryDataset prefetch producer's lifecycle contract.

The producer is a per-iteration daemon thread feeding a bounded queue
(utils/data.py _iter_range). Three things must hold or long-running
drivers leak:

* a consumer that ABANDONS the generator mid-epoch (break / GC / driver
  crash) must stop the producer promptly — the stop event, not queue
  starvation, ends it;
* repeated iterations must not accumulate orphaned daemon threads;
* a producer-side exception (truncated file, transient IO error) must
  surface in the CONSUMER as that exception, not hang the consumer on
  an empty queue.

The concurrency auditor's discovery side sees this thread too
(RawBinaryDataset._iter_range:producer in the utils/data.py contract);
these tests pin the runtime behavior the contract describes.
"""

import threading
import time

import numpy as np
import pytest

from distributed_embeddings_tpu.utils.data import RawBinaryDataset


N_ROWS = 64
BATCH = 4


@pytest.fixture
def dataset_dir(tmp_path):
    """A tiny but real split-binary layout (memmaps need files)."""
    train = tmp_path / "train"
    train.mkdir()
    rng = np.random.default_rng(0)
    (train / "label.bin").write_bytes(
        (rng.random(N_ROWS) < 0.5).astype(np.bool_).tobytes())
    (train / "numerical.bin").write_bytes(
        rng.random((N_ROWS, 2)).astype(np.float16).tobytes())
    (train / "cat_0.bin").write_bytes(
        rng.integers(0, 100, N_ROWS).astype(np.int8).tobytes())
    return str(tmp_path)


def _make(dataset_dir, **kw):
    kw.setdefault("batch_size", BATCH)
    kw.setdefault("numerical_features", 2)
    kw.setdefault("categorical_features", [0])
    kw.setdefault("categorical_feature_sizes", [100])
    kw.setdefault("prefetch_depth", 4)
    return RawBinaryDataset(dataset_dir, **kw)


def _producer_threads():
    return [t for t in threading.enumerate() if t.name.startswith("Thread-")
            and t.daemon and t.is_alive()]


def _wait_for(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return pred()


def test_full_epoch_matches_direct_reads(dataset_dir):
    """Baseline: the threaded path yields exactly the direct reads."""
    ds = _make(dataset_dir)
    got = list(ds)
    assert len(got) == len(ds) == N_ROWS // BATCH
    for i, (num, cats, lab) in enumerate(got):
        dnum, dcats, dlab = ds[i]
        np.testing.assert_array_equal(num, dnum)
        np.testing.assert_array_equal(lab, dlab)
        for c, dc in zip(cats, dcats):
            np.testing.assert_array_equal(c, dc)


def test_abandoned_consumer_stops_producer(dataset_dir):
    """Closing the generator after one batch must end the producer via
    the stop event, even though the bounded queue is full and it would
    otherwise block on put() forever."""
    # depth 2 << 16 batches: the producer is certainly parked on a full
    # queue when the consumer walks away
    ds = _make(dataset_dir, prefetch_depth=2)
    before = set(id(t) for t in _producer_threads())
    it = iter(ds)
    next(it)
    spawned = [t for t in _producer_threads() if id(t) not in before]
    assert len(spawned) == 1
    it.close()  # generator finally -> stop.set()
    assert _wait_for(lambda: not spawned[0].is_alive()), \
        "producer still alive after consumer abandoned the iterator"


def test_no_thread_growth_across_repeated_iterations(dataset_dir):
    """Partial epochs in a loop (the realtime driver's shape) must not
    accumulate daemon threads."""
    ds = _make(dataset_dir, prefetch_depth=2)
    baseline = threading.active_count()
    for _ in range(10):
        it = iter(ds)
        next(it)
        it.close()
    assert _wait_for(lambda: threading.active_count() <= baseline), (
        f"thread growth: {threading.active_count()} alive vs "
        f"baseline {baseline}: {threading.enumerate()}")


def test_producer_exception_surfaces_to_consumer(dataset_dir):
    """A mid-epoch read failure must raise in the consumer, not strand
    it on q.get()."""
    ds = _make(dataset_dir)
    real_read = ds._read

    def flaky(idx):
        if idx == 3:
            raise OSError("simulated truncated read")
        return real_read(idx)

    ds._read = flaky
    it = iter(ds)
    got = [next(it) for _ in range(3)]
    assert len(got) == 3
    with pytest.raises(OSError, match="simulated truncated read"):
        next(it)
    # and the producer is gone afterwards
    assert _wait_for(
        lambda: all(not t.is_alive() or not t.name.startswith("Thread-")
                    for t in threading.enumerate()
                    if t.daemon and t.name.startswith("Thread-")))


def test_unthreaded_path_when_depth_too_small(dataset_dir):
    """prefetch_depth <= 1 takes the synchronous path — no thread at
    all (the auditor's inventory only lists the threaded producer)."""
    ds = _make(dataset_dir, prefetch_depth=1)
    before = threading.active_count()
    assert len(list(ds)) == N_ROWS // BATCH
    assert threading.active_count() == before

"""Pipelined hybrid step: schedule declarations, microbatch slicing, and
trajectory equivalence against the serialized baseline.

The K-microbatch software-pipelined step (``parallel/schedule.py::
pipelined_schedule`` + ``parallel/trainer.py::_pipelined_local_step``)
promises three things, each pinned here:

* **K=1 is the serialized program, bitwise** — ``pipelined_schedule(1)``
  degenerates to the serialized schedule and the traced step is
  byte-identical;
* **K>1 is trajectory-equivalent** — losses and final parameters match
  the serialized step within float-accumulation-order tolerance across
  the PR 12 A/B matrix configurations (dense / ragged / row-sliced /
  streaming+telemetry, world 1 and 8, SGD/Adagrad/Adam, metrics on and
  off), with the discrete state (streaming slot maps, admission
  sketches, telemetry sketches, metric counters) BITWISE equal — the
  staging concatenation must reproduce the serialized decisions exactly;
* **the declared overlaps exist** — the schedule auditor certifies the
  pipelined program's DAG independence and the serialized fraction
  collapses (the ROADMAP item 2 acceptance).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh

from distributed_embeddings_tpu.ops.embedding_lookup import Ragged
from distributed_embeddings_tpu.parallel import (
    DistributedEmbedding,
    SparseAdagrad,
    SparseAdam,
    SparseSGD,
    init_hybrid_state,
    make_hybrid_train_step,
)
from distributed_embeddings_tpu.parallel import schedule as schedule_mod
from distributed_embeddings_tpu.parallel.schedule import (
    PHASE_DENSE,
    PHASE_GRAD_EXCHANGE,
    PHASE_ID_EXCHANGE,
    ScheduleError,
    default_schedule,
    pipelined_schedule,
    resolve_schedule,
    streaming_schedule,
)
from distributed_embeddings_tpu.utils import envvars

WORLD = 8


# ------------------------------------------------------------- schedules


def test_pipelined_schedule_declares_per_microbatch_phases():
    sched = pipelined_schedule(2)
    assert sched.microbatches == 2
    names = [p.name for p in sched.phases]
    assert "id_all_to_all_mb0" in names and "id_all_to_all_mb1" in names
    assert "sparse_apply*" in names
    # every collective declares an overlap with the OTHER microbatch's
    # lookup/dense chain
    for p in sched.phases:
        if p.kind == "collective":
            assert p.overlaps, p.name
            assert all("_mb" in q for q in p.overlaps)


def test_pipelined_schedule_k1_is_serialized_baseline():
    assert pipelined_schedule(1).name == default_schedule().name
    assert pipelined_schedule(1).microbatches == 1
    assert (pipelined_schedule(1, streaming=True).name
            == streaming_schedule().name)


def test_pipelined_schedule_env_default(monkeypatch):
    monkeypatch.setenv("DETPU_MICROBATCH", "4")
    assert pipelined_schedule().microbatches == 4
    monkeypatch.setenv("DETPU_MICROBATCH", "1")
    assert pipelined_schedule().microbatches == 1
    monkeypatch.setenv("DETPU_MICROBATCH", "0")
    with pytest.raises(ScheduleError):
        pipelined_schedule()


def test_resolve_schedule_forms():
    assert resolve_schedule(None).name == "serialized-v1"
    assert resolve_schedule("serialized",
                            streaming=True).name == "streaming-serialized-v1"
    sched = pipelined_schedule(2)
    assert resolve_schedule(sched) is sched
    with pytest.raises(ScheduleError):
        resolve_schedule("bogus")


def test_streaming_schedule_declares_admit_overlap():
    sched = streaming_schedule()
    by = sched.by_name()
    assert by["out_all_to_all"].overlaps == ("streaming_admit_*",)
    assert by["grad_all_to_all"].overlaps == ("streaming_admit_*",)
    assert sched.microbatches == 1


def test_mb_phase_glob_suffix():
    assert schedule_mod.mb_phase("lookup_*", 0) == "lookup_*_mb0"
    assert schedule_mod.mb_phase(PHASE_ID_EXCHANGE, 3) == "id_all_to_all_mb3"
    import fnmatch
    assert fnmatch.fnmatchcase("lookup_w8_d_mb0", "lookup_*_mb0")
    assert not fnmatch.fnmatchcase("lookup_w8_d_mb10", "lookup_*_mb1")


def test_microbatch_knobs_registered():
    reg = envvars.registered()
    # default 2: asking for schedule="pipelined" without pinning K must
    # actually build a pipeline (the serialized baseline is the DEFAULT
    # schedule, not a pipelined_schedule degenerate)
    assert reg["DETPU_MICROBATCH"].default == "2"
    assert "DETPU_MICROBATCH_BENCH" in reg


def test_schedule_pipelined_string_actually_pipelines(monkeypatch):
    monkeypatch.delenv("DETPU_MICROBATCH", raising=False)
    configs = [{"input_dim": 32, "output_dim": 4, "combiner": "sum"}
               for _ in range(8)]
    de = DistributedEmbedding(configs, world_size=WORLD,
                              schedule="pipelined")
    assert de.schedule.microbatches == 2
    # and the plain default stays serialized regardless of the env knob
    monkeypatch.setenv("DETPU_MICROBATCH", "4")
    de2 = DistributedEmbedding(configs, world_size=WORLD)
    assert de2.schedule.microbatches == 1


def test_expected_collectives_scale_with_microbatches():
    from distributed_embeddings_tpu.analysis import expected_collectives

    configs = [{"input_dim": 32, "output_dim": 4, "combiner": "sum"}
               for _ in range(8)]
    de = DistributedEmbedding(configs, world_size=WORLD,
                              schedule=pipelined_schedule(2))
    exp = expected_collectives(de, nan_guard=True, n_dense_leaves=2)
    assert exp["all_to_all_roles"] == {"id_exchange_fwd": 2,
                                       "out_exchange_fwd": 2,
                                       "grad_exchange_bwd": 2}
    # the psum census is K-invariant: accumulate locally, resolve once
    assert exp["psum"] == 1 + 2 + 1


# ------------------------------------------------------ microbatch slicing


def test_microbatch_inputs_ragged_slices_rows_exactly():
    from distributed_embeddings_tpu.parallel.trainer import (
        _microbatch_inputs)

    splits = jnp.asarray([0, 2, 3, 3, 6], jnp.int32)
    values = jnp.asarray([10, 11, 20, 30, 31, 32, 0, 0], jnp.int32)
    r = Ragged(values=values, row_splits=splits)
    dense = jnp.arange(4, dtype=jnp.int32)
    batch = jnp.arange(8, dtype=jnp.float32).reshape(4, 2)
    mbs = _microbatch_inputs([r, dense], batch, 2)
    assert len(mbs) == 2
    (r0, d0), b0 = mbs[0]
    (r1, d1), b1 = mbs[1]
    np.testing.assert_array_equal(r0.row_splits, [0, 2, 3])
    np.testing.assert_array_equal(r0.values[:3], [10, 11, 20])
    np.testing.assert_array_equal(r1.row_splits, [0, 0, 3])
    np.testing.assert_array_equal(r1.values[:3], [30, 31, 32])
    np.testing.assert_array_equal(d0, [0, 1])
    np.testing.assert_array_equal(d1, [2, 3])
    np.testing.assert_array_equal(b1, batch[2:])
    with pytest.raises(ValueError):
        _microbatch_inputs([dense], batch, 3)


# --------------------------------------------------------- the A/B harness


def _build_case(name, world, rng):
    """One A/B matrix configuration: ``(de_kwargs, configs, streaming)``."""
    if name == "dense":
        configs = [{"input_dim": 20 + 6 * i, "output_dim": 4,
                    "combiner": ["sum", None, "mean"][i % 3]}
                   for i in range(10)]
        return {}, configs, False
    if name == "ragged":
        configs = [{"input_dim": 40 + 7 * i, "output_dim": 8,
                    "combiner": "sum" if i % 2 else "mean"}
                   for i in range(8)]
        return {}, configs, False
    if name == "row_sliced":
        configs = [{"input_dim": 100 if i % 3 == 0 else 20 + i,
                    "output_dim": 8,
                    "combiner": [None, "sum", "mean"][i % 3]}
                   for i in range(9)]
        return {"row_slice": 100 * 8 // 4 + 1}, configs, False
    if name == "streaming":
        configs = [{"input_dim": 20 + 6 * i, "output_dim": 4,
                    "combiner": ["sum", None, "mean"][i % 3]}
                   for i in range(9)]
        configs.append({"input_dim": 512 + 64, "output_dim": 4,
                        "combiner": "sum",
                        "streaming": {"capacity": 512, "buckets": 64}})
        return {}, configs, True
    raise ValueError(name)


def _make_inputs(rng, configs, batch, world, ragged):
    local_b = batch // max(world, 1)
    cats = []
    for cfg in configs:
        if ragged:
            vals_all, splits_all = [], []
            cap = local_b * 4
            for _ in range(max(world, 1)):
                hots = rng.integers(0, 5, size=local_b)
                splits = np.zeros(local_b + 1, np.int32)
                np.cumsum(hots, out=splits[1:])
                vals = np.zeros(cap, np.int32)
                nnz = int(splits[-1])
                vals[:nnz] = rng.integers(0, cfg["input_dim"], size=nnz)
                vals_all.append(vals)
                splits_all.append(splits)
            cats.append(Ragged(values=jnp.asarray(np.concatenate(vals_all)),
                               row_splits=jnp.asarray(
                                   np.concatenate(splits_all))))
            continue
        hot = 1 if cfg["combiner"] is None else 3
        shape = (batch,) if hot == 1 else (batch, hot)
        hi = (16 * cfg["streaming"]["capacity"] if "streaming" in cfg
              else cfg["input_dim"])
        cats.append(jnp.asarray(rng.integers(0, hi, size=shape), jnp.int32))
    n = jnp.asarray(rng.normal(size=(batch, 13)), jnp.float32)
    y = jnp.asarray(rng.normal(size=(batch, 1)), jnp.float32)
    cols = sum(c["output_dim"] for c in configs)
    dp = {"w": jnp.asarray(rng.normal(size=(cols, 1)), jnp.float32) * 0.1,
          "v": jnp.asarray(rng.normal(size=(13, 1)), jnp.float32) * 0.1}
    return cats, (n, y), dp


def _loss_fn(dp, emb_outs, b):
    n, y = b
    x = jnp.concatenate([e.reshape(e.shape[0], -1) for e in emb_outs],
                        axis=1)
    return jnp.mean((x @ dp["w"] + n @ dp["v"] - y) ** 2)


def _opt(name):
    return {"sgd": SparseSGD, "adagrad": SparseAdagrad,
            "adam": SparseAdam}[name]()


def _run(name, world, opt_name, metrics, sched, steps=3, batch=64,
         telemetry=False):
    from distributed_embeddings_tpu.analysis import telemetry as tel
    from distributed_embeddings_tpu.parallel import (StreamingConfig,
                                                     init_streaming)
    from distributed_embeddings_tpu.analysis.telemetry import init_telemetry

    kwargs, configs, streaming = _build_case(name, world,
                                             np.random.default_rng(0))
    de = DistributedEmbedding(configs, world_size=world, schedule=sched,
                              **kwargs)
    mesh = (Mesh(np.array(jax.devices()[:world]), ("data",))
            if world > 1 else None)
    rng = np.random.default_rng(7)
    cats, bt, dp = _make_inputs(rng, configs, batch, world,
                                ragged=(name == "ragged"))
    tx = optax.sgd(0.5)
    opt = _opt(opt_name)
    scfg = StreamingConfig(admit_min_count=1) if streaming else None
    tcfg = tel.TelemetryConfig() if telemetry else None
    state = init_hybrid_state(de, opt, dp, tx, jax.random.key(0),
                              mesh=mesh)
    step = make_hybrid_train_step(
        de, _loss_fn, tx, opt, mesh=mesh, lr_schedule=0.3,
        with_metrics=metrics, nan_guard=True,
        telemetry=tcfg if tcfg else False,
        dynamic=scfg if scfg else False)
    aux = []
    if tcfg:
        aux.append(init_telemetry(de, tcfg, mesh=mesh))
    if scfg:
        aux.append(init_streaming(de, scfg, mesh=mesh))
    losses = []
    last_metrics = None
    for _ in range(steps):
        out = step(state, cats, bt, *aux)
        loss, state = out[0], out[1]
        rest = list(out[2:])
        if metrics:
            last_metrics = rest.pop(0)
        aux = rest
        losses.append(float(loss))
    return losses, state, aux, last_metrics


def _assert_equivalent(name, world, opt_name, metrics, telemetry=False,
                       steps=3):
    l0, s0, aux0, m0 = _run(name, world, opt_name, metrics, None,
                            steps=steps, telemetry=telemetry)
    l2, s2, aux2, m2 = _run(name, world, opt_name, metrics,
                            pipelined_schedule(
                                2, streaming=(name == "streaming")),
                            steps=steps, telemetry=telemetry)
    np.testing.assert_allclose(l0, l2, rtol=2e-5, atol=2e-6)
    for a, b in zip(jax.tree_util.tree_leaves(s0.emb_params),
                    jax.tree_util.tree_leaves(s2.emb_params)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=2e-5, atol=2e-6)
    # the discrete aux state (slot maps, sketches, counters) must be
    # BITWISE equal: the pipelined staging reproduces the serialized
    # decisions exactly, not approximately
    for a, b in zip(jax.tree_util.tree_leaves(aux0),
                    jax.tree_util.tree_leaves(aux2)):
        if jnp.issubdtype(a.dtype, jnp.integer):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        else:
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6, atol=1e-6)
    if metrics:
        for k in ("ids_routed", "invalid_id_count", "id_overflow",
                  "skipped_steps"):
            np.testing.assert_array_equal(np.asarray(m0[k]),
                                          np.asarray(m2[k]))


# ------------------------------------------- the PR 12 six-config matrix
# Each configuration pairs with a distinct (world, optimizer, metrics)
# assignment so the set covers world 1 and 8, all three optimizer
# families, and metrics on/off without the full 48-way product; the
# cross combinations ride the slow tier.

def test_ab_dense_world8_adagrad_metrics_on():
    _assert_equivalent("dense", WORLD, "adagrad", True)


def test_ab_ragged_world1_sgd_metrics_off():
    _assert_equivalent("ragged", 1, "sgd", False)


def test_ab_row_sliced_world8_adam_metrics_off():
    _assert_equivalent("row_sliced", WORLD, "adam", False)


def test_ab_streaming_world8_adagrad_metrics_on_with_telemetry():
    _assert_equivalent("streaming", WORLD, "adagrad", True,
                       telemetry=True)


@pytest.mark.parametrize("name,world,opt_name,metrics,telemetry", [
    ("dense", 1, "adam", False, False),
    ("dense", WORLD, "sgd", False, False),
    ("ragged", WORLD, "adagrad", True, False),
    ("row_sliced", 1, "adagrad", True, False),
    ("streaming", 1, "sgd", False, False),
    ("streaming", WORLD, "adam", False, True),
])
def test_ab_matrix_cross(name, world, opt_name, metrics, telemetry):
    _assert_equivalent(name, world, opt_name, metrics,
                       telemetry=telemetry)


# ------------------------------------------------------- exact arithmetic


def test_grad_accumulation_order_exact_bitwise():
    """With exactly-representable values (integer embeddings and
    cotangents, power-of-two batch and K), the K=2 step must reproduce
    the serialized step BITWISE — duplicate ids crossing the microbatch
    boundary land in the merged per-width stream and the single scatter
    accumulates the same per-row total regardless of segment order."""
    configs = [{"input_dim": 16, "output_dim": 4, "combiner": "sum"}
               for _ in range(2)]

    def int_init(key, shape, dtype):
        del key
        return (jnp.arange(np.prod(shape), dtype=jnp.float32)
                .reshape(shape) % 8).astype(dtype)

    for c in configs:
        c["embeddings_initializer"] = int_init

    def run(sched):
        de = DistributedEmbedding(configs, world_size=1, schedule=sched)
        # duplicate ids straddling the microbatch boundary
        cats = [jnp.asarray([[1, 1], [2, 3], [1, 2], [3, 3]], jnp.int32),
                jnp.asarray([[0, 5], [5, 5], [5, 0], [2, 2]], jnp.int32)]
        y = jnp.asarray([[1.0], [-2.0], [4.0], [-8.0]], jnp.float32)
        n = jnp.zeros((4, 13), jnp.float32)
        dp = {"w": jnp.ones((8, 1), jnp.float32),
              "v": jnp.zeros((13, 1), jnp.float32)}
        tx = optax.sgd(0.0)  # dense frozen: the sparse path is the test
        opt = SparseSGD()
        state = init_hybrid_state(de, opt, dp, tx, jax.random.key(0))
        step = make_hybrid_train_step(de, _loss_fn, tx, opt,
                                      lr_schedule=0.5,
                                      with_metrics=False, nan_guard=False)
        for _ in range(2):
            loss, state = step(state, cats, (n, y))
        return loss, state

    l0, s0 = run(None)
    l2, s2 = run(pipelined_schedule(2))
    assert float(l0) == float(l2)
    for a, b in zip(jax.tree_util.tree_leaves(s0.emb_params),
                    jax.tree_util.tree_leaves(s2.emb_params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ------------------------------------------------------------ K=1 bitwise


def test_k1_pipelined_step_bitwise_identical_to_serialized():
    configs = [{"input_dim": 24 + i, "output_dim": 4, "combiner": "sum"}
               for i in range(2)]

    def lower_text(sched):
        de = DistributedEmbedding(configs, world_size=1, schedule=sched)
        cats = [jax.ShapeDtypeStruct((8, 2), jnp.int32) for _ in configs]
        bt = (jax.ShapeDtypeStruct((8, 13), jnp.float32),
              jax.ShapeDtypeStruct((8, 1), jnp.float32))
        dp = {"w": jax.ShapeDtypeStruct((8, 1), jnp.float32),
              "v": jax.ShapeDtypeStruct((13, 1), jnp.float32)}
        tx = optax.sgd(0.1)
        opt = SparseSGD()
        state = jax.eval_shape(
            lambda k, d: init_hybrid_state(de, opt, d, tx, k),
            jax.random.key(0), dp)
        step = make_hybrid_train_step(de, _loss_fn, tx, opt,
                                      lr_schedule=0.1,
                                      with_metrics=False, nan_guard=True)
        return step.lower(state, cats, bt).as_text()

    assert lower_text(pipelined_schedule(1)) == lower_text(None)


# --------------------------------------------- schedule-audit acceptance


def test_pipelined_schedule_certifies_and_fraction_collapses():
    """The ROADMAP item 2 acceptance, in-suite: the compiled K=2 program
    must contain every declared overlap (declaration check), classify
    every declaring exchange overlappable, and collapse the modeled
    serialized fraction from the ~0.99 baseline to <= 0.7."""
    import sys
    sys.path.insert(0, __import__("os").path.dirname(
        __import__("os").path.dirname(__import__("os").path.abspath(
            __file__))))
    from tools._profcommon import build_case
    from distributed_embeddings_tpu.analysis import schedule_audit as sa

    de, cats, batch_tree, dense_params, loss_fn = build_case(
        "pipelined", WORLD, 256)
    mesh = Mesh(np.array(jax.devices()[:WORLD]), ("data",))
    rep = sa.audit_train_step(
        de, loss_fn, optax.sgd(0.5), SparseAdagrad(), cats, batch_tree,
        mesh=mesh, lr_schedule=0.3, dense_params=dense_params,
        contracts=sa.declared_overlap_contracts(de.schedule),
        label="pipelined-acceptance")
    assert rep.ok, rep.violations
    assert rep.serialized_collective_fraction <= 0.7
    a2a_phases = {c.phase_leaf for c in rep.collectives
                  if "all_to_all" in c.phase_leaf}
    assert {f"{r}_mb{k}" for r in ("id_all_to_all", "out_all_to_all",
                                   "grad_all_to_all")
            for k in range(2)} <= a2a_phases


def test_pipelined_fake_overlap_still_rejected():
    """A pipelined-SHAPED schedule declared against the SERIALIZED
    program must fail the declaration check: _mb phases match nothing
    compiled, which is itself the lie the auditor reports."""
    from distributed_embeddings_tpu.analysis import schedule_audit as sa
    from tools._profcommon import build_case

    de, cats, batch_tree, dense_params, loss_fn = build_case(
        "dense", WORLD, 256)
    mesh = Mesh(np.array(jax.devices()[:WORLD]), ("data",))
    rep = sa.audit_train_step(
        de, loss_fn, optax.sgd(0.5), SparseAdagrad(), cats, batch_tree,
        mesh=mesh, lr_schedule=0.3, dense_params=dense_params,
        schedule=pipelined_schedule(2), contracts=[],
        label="fake-pipelined")
    assert not rep.ok
    assert any("matches no compiled collective" in v
               for v in rep.violations)


# ------------------------------------------------------------ guard rails


def test_pipelined_rejects_mp_input():
    configs = [{"input_dim": 32, "output_dim": 4, "combiner": "sum"}
               for _ in range(8)]
    de = DistributedEmbedding(configs, world_size=WORLD, dp_input=False,
                              schedule=pipelined_schedule(2))
    mesh = Mesh(np.array(jax.devices()[:WORLD]), ("data",))
    step = make_hybrid_train_step(de, _loss_fn, optax.sgd(0.1),
                                  SparseSGD(), mesh=mesh)
    packed = de.pack_mp_inputs(
        [np.zeros((16, 3), np.int32) for _ in configs], mesh=mesh)
    bt = (jnp.zeros((16, 13), jnp.float32), jnp.zeros((16, 1),
                                                      jnp.float32))
    dp = {"w": jnp.zeros((32, 1), jnp.float32),
          "v": jnp.zeros((13, 1), jnp.float32)}
    state = init_hybrid_state(de, SparseSGD(), dp, optax.sgd(0.1),
                              jax.random.key(0), mesh=mesh)
    with pytest.raises(NotImplementedError, match="pipelined"):
        step(state, packed, bt)

"""make_hybrid_train_loop: K scanned steps == K individual steps.

The loop driver exists to amortize per-dispatch host overhead (measured
~25 ms/step through the bench tunnel); its contract is exact per-step
equivalence with make_hybrid_train_step — same gradients, same optimizer
updates, same step counter — which these tests assert by trajectory
comparison from a shared init.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_embeddings_tpu.parallel import (
    DistributedEmbedding, SparseAdagrad, SparseSGD, init_hybrid_state,
    make_hybrid_train_loop, make_hybrid_train_step)

WORLD = 8
K = 3


def _model(world):
    configs = [{"input_dim": 20 + 6 * i, "output_dim": 4,
                "combiner": ["sum", None, "mean"][i % 3]}
               for i in range(10)]
    return DistributedEmbedding(configs, world_size=world), configs


def _data(rng, configs, b, k):
    cats, stacks = [], []
    for cfg in configs:
        hot = 1 if cfg["combiner"] is None else 3
        shape = (k, b) if hot == 1 else (k, b, hot)
        arr = rng.integers(0, cfg["input_dim"], size=shape)
        stacks.append(jnp.asarray(arr, jnp.int32))
        cats.append([jnp.asarray(arr[i], jnp.int32) for i in range(k)])
    num = jnp.asarray(rng.normal(size=(k, b, 3)), jnp.float32)
    y = jnp.asarray(rng.normal(size=(k, b, 1)) * 0.1, jnp.float32)
    return cats, stacks, num, y


def _loss_fn(dp, emb_outs, batch):
    n, y = batch
    x = jnp.concatenate([e.reshape(e.shape[0], -1) for e in emb_outs],
                        axis=1)
    pred = x @ dp["w"] + n @ dp["v"]
    return jnp.mean((pred - y) ** 2)


def _dense_params(configs):
    # every input is [b, w] here: no-combiner tables get 1-hot 1-D inputs,
    # combiner tables reduce their 3-hot inputs
    cols = sum(int(c["output_dim"]) for c in configs)
    return {"w": jnp.zeros((cols, 1)), "v": jnp.zeros((3, 1))}


@pytest.mark.parametrize("world", [1, WORLD])
def test_loop_matches_individual_steps(world):
    rng = np.random.default_rng(0)
    de, configs = _model(world)
    b = 16  # global batch
    cats, stacks, num, y = _data(rng, configs, b, K)
    tx = optax.sgd(0.5)
    emb_opt = SparseAdagrad()
    mesh = (Mesh(np.array(jax.devices()[:world]), ("data",))
            if world > 1 else None)
    dp = _dense_params(configs)

    # each state gets its own dense-param copies: the steps donate their
    # state, and a shared array would be deleted under the other state
    state_a = init_hybrid_state(de, emb_opt, jax.tree.map(jnp.copy, dp), tx,
                                jax.random.key(1), mesh=mesh)
    state_b = init_hybrid_state(de, emb_opt, jax.tree.map(jnp.copy, dp), tx,
                                jax.random.key(1), mesh=mesh)

    step = make_hybrid_train_step(de, _loss_fn, tx, emb_opt, mesh=mesh,
                                  lr_schedule=0.3)
    loop = make_hybrid_train_loop(de, _loss_fn, tx, emb_opt, mesh=mesh,
                                  lr_schedule=0.3)

    if mesh is not None:
        shard = NamedSharding(mesh, P(None, "data"))
        stacks = [jax.device_put(s, shard) for s in stacks]
        num = jax.device_put(num, shard)
        y = jax.device_put(y, shard)

    losses_step = []
    for i in range(K):
        loss, state_a = step(state_a, [s[i] for s in stacks],
                             (num[i], y[i]))
        losses_step.append(float(loss))

    losses_loop, state_b = loop(state_b, stacks, (num, y))
    np.testing.assert_allclose(np.asarray(losses_loop),
                               np.asarray(losses_step), rtol=1e-5)
    assert int(state_b.step) == K
    for k in state_a.emb_params:
        np.testing.assert_allclose(
            np.asarray(state_a.emb_params[k]),
            np.asarray(state_b.emb_params[k]), rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(
            np.asarray(state_a.emb_opt_state[k]),
            np.asarray(state_b.emb_opt_state[k]), rtol=1e-5, atol=1e-6)
    for k in ("w", "v"):
        np.testing.assert_allclose(
            np.asarray(state_a.dense_params[k]),
            np.asarray(state_b.dense_params[k]), rtol=1e-5, atol=1e-6)

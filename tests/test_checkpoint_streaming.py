"""Scale-safe checkpointing: streaming set/get must keep transient host
memory bounded by the chunk size, not the model size.

The reference engineered its checkpoint paths around exactly this:
``set_weights`` scatter-updates in ~128M-element chunks to dodge
copy-on-write OOM (``dist_model_parallel.py:362-380``) and ``get_weights``
chunks its allgathers below 2^31 elements (``:426-447``). Here a subprocess
builds a half-GiB model on an 8-virtual-device CPU mesh with a small chunk
size and asserts peak-RSS growth stays near one model copy per phase —
a staging-array implementation (the pre-round-2 code materialized the full
``[world, rows_cap, w]`` on host) fails the bound.

A subprocess keeps the RSS accounting clean: ``ru_maxrss`` is a process-
lifetime high-water mark, so it must start from a known baseline.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

# 8 equal tables, 1M rows x 16 wide fp32 = 64 MiB each, 512 MiB total.
_NUM_TABLES = 8
_ROWS = 1_000_000
_WIDTH = 16
_MODEL_BYTES = _NUM_TABLES * _ROWS * _WIDTH * 4

_SCRIPT = r"""
import gc, json, resource, sys

import jax
# env vars alone don't stick when a sitecustomize pre-registers the TPU
# plugin; force the platform the way tests/conftest.py does
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp

from distributed_embeddings_tpu.parallel import DistributedEmbedding

assert len(jax.devices()) == 8, jax.devices()

NUM_TABLES, ROWS, WIDTH = %(num_tables)d, %(rows)d, %(width)d
CHUNK_ELEMS = 1 << 20          # 4 MiB fp32 chunks — far below one table

def peak_mib():
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0

mesh = jax.sharding.Mesh(np.array(jax.devices()), ("data",))
de = DistributedEmbedding(
    [{"input_dim": ROWS, "output_dim": WIDTH} for _ in range(NUM_TABLES)],
    world_size=len(jax.devices()))

rng = np.random.default_rng(0)
sources = [rng.normal(size=(ROWS, WIDTH)).astype(np.float32)
           for _ in range(NUM_TABLES)]

# keep only fingerprints of the sources so the measured get_weights phase
# is the first full reassembly (a full-get "probe" would bake a naive
# implementation's host copy into the high-water mark and hide it)
sums = [float(s.sum(dtype=np.float64)) for s in sources]
sample_rows = [np.array(s[::ROWS // 7]) for s in sources]

peak0 = peak_mib()
params = de.set_weights(sources, mesh=mesh, chunk_elems=CHUNK_ELEMS)
jax.block_until_ready(list(params.values()))
peak_set = peak_mib()

del sources
gc.collect()
peak_mid = peak_mib()

tables = de.get_weights(params, chunk_elems=CHUNK_ELEMS)
peak_get = peak_mib()

ok = all(
    abs(float(t.sum(dtype=np.float64)) - s) < 1e-3
    and np.array_equal(t[::ROWS // 7], rows)
    for t, s, rows in zip(tables, sums, sample_rows))

print(json.dumps({
    "ok": bool(ok),
    "peak0_mib": peak0,
    "set_delta_mib": peak_set - peak0,
    "get_delta_mib": peak_get - peak_mid,
}))
"""


@pytest.mark.slow
def test_streaming_checkpoint_rss_bounded(tmp_path):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8")
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
        + env.get("PYTHONPATH", "").split(os.pathsep))
    script = _SCRIPT % {"num_tables": _NUM_TABLES, "rows": _ROWS,
                        "width": _WIDTH}
    out = subprocess.run([sys.executable, "-c", script], env=env,
                         capture_output=True, text=True, timeout=1200)
    assert out.returncode == 0, out.stderr[-4000:]
    stats = json.loads(out.stdout.strip().splitlines()[-1])
    model_mib = _MODEL_BYTES / 2**20

    assert stats["ok"], "roundtrip mismatch"
    # set_weights: +1 model on (CPU-backend) devices plus chunk transients.
    # The old staging-array path adds another full host model (>= 2x).
    assert stats["set_delta_mib"] < 1.5 * model_mib, stats
    # get_weights after a same-size probe already peaked: the streamed
    # reassembly only re-fills an output-sized buffer (already inside the
    # high-water mark); a whole-model device_get would add ~1 model.
    assert stats["get_delta_mib"] < 0.5 * model_mib, stats


def test_all_ranks_false_and_use_lock():
    """Reference-parity checkpoint modes: get_weights(all_ranks=False)
    returns tables only on process 0 (single-process here, so it returns
    them) and set_weights(use_lock=True) serializes via the file lock."""
    import numpy as np
    from distributed_embeddings_tpu.parallel import DistributedEmbedding

    import jax
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:8]), ("data",))
    rng = np.random.default_rng(0)
    configs = [{"input_dim": 24 + i, "output_dim": 8} for i in range(8)]
    de = DistributedEmbedding(configs, world_size=8)
    tables = [rng.normal(size=(c["input_dim"], 8)).astype(np.float32)
              for c in configs]
    params = de.set_weights(tables, mesh=mesh, use_lock=True)
    back = de.get_weights(params, all_ranks=False)
    assert back is not None  # this process IS process 0
    for a, b in zip(tables, back):
        np.testing.assert_array_equal(a, b)


def test_optimizer_state_checkpoints_through_same_path():
    """Beyond the reference (it has no optimizer-state checkpointing, SURVEY
    §5): Adagrad accumulator slabs are the same width-keyed dict shape as
    params, so get_weights/set_weights reassemble and redistribute them
    unchanged — per-table accumulator roundtrip."""
    import jax
    import numpy as np
    from distributed_embeddings_tpu.parallel import (
        DistributedEmbedding, SparseAdagrad)

    mesh = jax.sharding.Mesh(np.array(jax.devices()[:8]), ("data",))
    rng = np.random.default_rng(1)
    configs = [{"input_dim": 20 + 3 * i, "output_dim": 8} for i in range(8)]
    de = DistributedEmbedding(configs, world_size=8)
    tables = [rng.normal(size=(c["input_dim"], 8)).astype(np.float32)
              for c in configs]
    params = de.set_weights(tables, mesh=mesh)
    accum = SparseAdagrad(initial_accumulator_value=0.25).init(params)
    acc_tables = de.get_weights(accum)
    for c, a in zip(configs, acc_tables):
        assert a.shape == (c["input_dim"], 8)
        np.testing.assert_allclose(a, 0.25)
    # redistribute and read back: exact
    accum2 = de.set_weights(acc_tables, mesh=mesh)
    for a, b in zip(acc_tables, de.get_weights(accum2)):
        np.testing.assert_array_equal(a, b)

"""Test harness: force an 8-virtual-device CPU platform.

The reference test suite needs real GPUs under ``horovodrun -np N``
(``distributed_embeddings/python/layers/dist_model_parallel_test.py:85-89``);
here multi-device tests run anywhere via XLA's host-platform device count —
a capability called out in SURVEY.md §4 as worth having from day 1.

Must run before the first JAX backend initialization. The container's
sitecustomize may have already *registered* a TPU plugin at interpreter start;
switching ``jax_platforms`` to cpu before any backend is touched still works.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
# a user-level DETPU_OBS=1 would flip every env-defaulted train step to the
# instrumented 3-tuple return and break the suite's 2-tuple call sites —
# the suite opts in explicitly (with_metrics=True) where it tests metrics.
# DETPU_TELEMETRY likewise changes the step arity (telemetry state in/out).
# Popped here (before any test imports), so subprocess tests inherit the
# sanitized environment too.
os.environ.pop("DETPU_OBS", None)
os.environ.pop("DETPU_TELEMETRY", None)

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running / memory-heavy tests")


import pytest  # noqa: E402 - after the backend-forcing block above


@pytest.fixture
def transfer_guard():
    """Opt-in: fail the test on any IMPLICIT host<->device transfer inside
    it (``jax.transfer_guard("disallow")``). Trainer / dist-embedding step
    tests use this to prove the jitted step never smuggles a hidden
    device->host readback or a per-step host constant upload — the same
    property the step auditor checks statically (analysis/audit.py), here
    enforced at run time. Explicit transfers (``jax.device_put``, committed
    input staging, ``np.asarray`` readbacks the test itself does) stay
    allowed."""
    with jax.transfer_guard("disallow"):
        yield

"""Training a DistributedEmbedding through plain Flax + optax.

The ecosystem-composability counterpart of the reference's Keras packaging
(its ``DistributedEmbedding`` is a ``tf.keras.layers.Layer`` dropped into a
stock ``model.fit``-style loop, ``dist_model_parallel.py:199-259``): here
:class:`~distributed_embeddings_tpu.layers.DistributedEmbeddingLayer` makes
the sharded tables a normal Flax parameter, so the whole model trains with
``flax.training.train_state`` + any optax transform — no sparse trainer, no
custom step builder.

This is the right tool when tables are modest (autodiff produces dense slab
gradients, so each step reads+writes whole slabs); for huge tables use
``parallel.make_hybrid_train_step`` with the sparse optimizers — the SAME
layer and parameter pytree, so you can switch without converting anything.

Run (any backend):
    python examples/flax_training/main.py
Mesh (8 virtual CPU devices):
    DETPU_FORCE_CPU_DEVICES=8 python examples/flax_training/main.py --mesh
Sparse optax (O(touched-rows) updates on one big table):
    python examples/flax_training/main.py --sparse
"""

import os
import sys

if os.environ.get("DETPU_FORCE_CPU_DEVICES"):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count="
        + os.environ["DETPU_FORCE_CPU_DEVICES"])

import flax.linen as nn
import jax

if os.environ.get("DETPU_FORCE_CPU_DEVICES"):
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np
import optax
from flax.training import train_state

from distributed_embeddings_tpu.layers import DistributedEmbeddingLayer
from distributed_embeddings_tpu.parallel import DistributedEmbedding

TABLE_SIZES = [1000, 5000, 20000, 800, 12000, 300, 9000, 2500]
EMBED_DIM = 16
BATCH = 256


class RecModel(nn.Module):
    """Embeddings -> concat -> 2-layer MLP; everything standard Flax."""

    de: DistributedEmbedding

    @nn.compact
    def __call__(self, cats):
        embs = DistributedEmbeddingLayer(de=self.de, name="embeddings")(cats)
        x = jnp.concatenate(embs, axis=-1)
        x = nn.relu(nn.Dense(64)(x))
        return nn.Dense(1)(x)


def sparse_optax_demo():
    """Third mode (``--sparse``): O(touched-rows) training of one BIG
    table under plain optax via ``parallel.sparse_optax`` — the reference
    op layer's IndexedSlices gradient (``embedding_lookup_ops.py:105-122``)
    without the hybrid trainer. Only the looked-up rows of the table and
    the Adagrad accumulator are read or written each step."""
    from distributed_embeddings_tpu.parallel import (
        apply_sparse_updates, sparse_rows_adagrad, sparse_value_and_grad)

    vocab, width, batch = 2_000_000, 32, 4096
    table = jnp.zeros((vocab, width), jnp.float32)
    dense = {"w": jnp.full((width, 1), 0.3, jnp.float32)}
    tx_dense = optax.adam(1e-2)
    tx_rows = sparse_rows_adagrad(1.0)

    def loss_fn(dp, outs, y):
        return jnp.mean((outs[0] @ dp["w"] - y) ** 2)

    f = sparse_value_and_grad(loss_fn, combiners=["sum"])

    import functools

    # donation is what lets the row scatters update the table and the
    # accumulator in place — without it every step copies both slabs
    @functools.partial(jax.jit, donate_argnums=(0, 2, 3))
    def step(table, dense, d_state, r_state, ids, y):
        loss, (dg, sg) = f(dense, [table], [ids], y)
        du, d_state = tx_dense.update(dg, d_state, dense)
        dense = optax.apply_updates(dense, du)
        ru, r_state = tx_rows.update(sg, r_state, [table])
        [table] = apply_sparse_updates([table], ru)
        return table, dense, d_state, r_state, loss

    d_state = tx_dense.init(dense)
    r_state = tx_rows.init([table])
    rng = np.random.default_rng(0)
    loss = None
    for i in range(60):
        ids = jnp.asarray(rng.integers(0, 50_000, size=(batch, 2)),
                          jnp.int32)
        y = jnp.ones((batch, 1), jnp.float32)
        table, dense, d_state, r_state, loss = step(
            table, dense, d_state, r_state, ids, y)
        if i % 20 == 0:
            print(f"step {i:3d} loss {float(loss):.4f}")
    print(f"final loss {float(loss):.4f}  (table {vocab:,} x {width}; "
          f"each step touches <= {batch * 2:,} rows)")


def main():
    if "--sparse" in sys.argv:
        return sparse_optax_demo()
    mesh_mode = "--mesh" in sys.argv
    world = len(jax.devices()) if mesh_mode else 1
    de = DistributedEmbedding(
        [{"input_dim": s, "output_dim": EMBED_DIM, "combiner": "sum"}
         for s in TABLE_SIZES],
        world_size=world, strategy="memory_balanced")
    model = RecModel(de=de)

    rng = np.random.default_rng(0)
    cats = [jnp.asarray(rng.integers(0, s, size=(BATCH, 4)), jnp.int32)
            for s in TABLE_SIZES]
    labels = jnp.asarray(rng.normal(size=(BATCH, 1)) * 0.1, jnp.float32)

    variables = model.init(jax.random.key(0), cats)
    ts = train_state.TrainState.create(
        apply_fn=model.apply, params=variables["params"],
        tx=optax.adam(1e-2))  # stock optax — that's the point

    if world == 1:
        @jax.jit
        def step(ts, cats, labels):
            def loss_fn(p):
                pred = ts.apply_fn({"params": p}, cats)
                return jnp.mean((pred - labels) ** 2)
            loss, grads = jax.value_and_grad(loss_fn)(ts.params)
            return ts.apply_gradients(grads=grads), loss

        for i in range(100):
            ts, loss = step(ts, cats, labels)
            if i % 20 == 0:
                print(f"step {i:3d} loss {float(loss):.6f}")
        print(f"final loss {float(loss):.6f}")
        return

    # mesh mode: same model — the slab params shard over the axis and the
    # executor runs inside shard_map; dense params stay replicated. Kept
    # stateless (SGD) for brevity; tests/test_flax_adapter.py shows the
    # same pattern with sharded optimizer state.
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    mesh = Mesh(np.array(jax.devices()), ("data",))
    shard = NamedSharding(mesh, P("data"))
    repl = NamedSharding(mesh, P())

    params = {
        "embeddings": jax.tree.map(lambda a: jax.device_put(a, shard),
                                   ts.params["embeddings"]),
        "Dense_0": jax.tree.map(lambda a: jax.device_put(a, repl),
                                ts.params["Dense_0"]),
        "Dense_1": jax.tree.map(lambda a: jax.device_put(a, repl),
                                ts.params["Dense_1"]),
    }
    lr = 0.05

    def local_step(params, cats, labels):
        def loss_fn(p):
            pred = model.apply({"params": p}, cats)
            return jnp.mean((pred - labels) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        # dense grads: average over shards; slab grads: local, 1/world
        params = {
            "embeddings": jax.tree.map(
                lambda p, g: p - lr * g / world,
                params["embeddings"], grads["embeddings"]),
            "Dense_0": jax.tree.map(
                lambda p, g: p - lr * jax.lax.pmean(g, "data"),
                params["Dense_0"], grads["Dense_0"]),
            "Dense_1": jax.tree.map(
                lambda p, g: p - lr * jax.lax.pmean(g, "data"),
                params["Dense_1"], grads["Dense_1"]),
        }
        return params, jax.lax.pmean(loss, "data")

    pspec = {"embeddings": P("data"), "Dense_0": P(), "Dense_1": P()}
    step = jax.jit(jax.shard_map(
        local_step, mesh=mesh,
        in_specs=(pspec, P("data"), P("data")),
        out_specs=(pspec, P())))

    cats_sh = [jax.device_put(c, shard) for c in cats]
    labels_sh = jax.device_put(labels, shard)
    for i in range(100):
        params, loss = step(params, cats_sh, labels_sh)
        if i % 20 == 0:
            print(f"step {i:3d} loss {float(loss):.6f}")
    print(f"final loss {float(loss):.6f}")


if __name__ == "__main__":
    main()

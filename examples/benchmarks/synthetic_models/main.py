"""Synthetic model benchmark driver.

TPU port of the reference driver
(``examples/benchmarks/synthetic_models/main.py:54-155``): builds a zoo model
(``--model tiny..colossal``), trains with the hybrid-parallel step, reports
mean iteration time. A collective-synced loss read closes each timing window
like the reference's allreduced-loss print (``main.py:123,138-144``).

Run (single chip):        python main.py --model tiny --row_cap 1000000
Run (8-dev CPU dry run):  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
                          python main.py --model tiny --row_cap 100000 --batch_size 1024
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from absl import app, flags

from distributed_embeddings_tpu.models import (
    InputGenerator, build_synthetic, synthetic_models_v3)
from distributed_embeddings_tpu.parallel import (
    SparseAdagrad, SparseSGD, init_hybrid_state, make_hybrid_train_step,
    run_resilient)

FLAGS = flags.FLAGS
flags.DEFINE_string("model", "tiny", "model scale from the zoo")
flags.DEFINE_integer("batch_size", 65536, "global batch size")
flags.DEFINE_float("alpha", 1.05, "power-law exponent; 0 = uniform ids")
flags.DEFINE_integer("num_steps", 100, "timed steps")
flags.DEFINE_string("optimizer", "adagrad", "sgd | adagrad (embedding side)")
flags.DEFINE_integer("column_slice_threshold", None, "max elements per slice")
flags.DEFINE_integer("row_cap", None,
                     "clip table vocab (zoo tables reach 2B rows)")
flags.DEFINE_float("learning_rate", 0.01, "learning rate")
flags.DEFINE_string("checkpoint_dir", None,
                    "drive the run through the self-healing driver "
                    "(parallel.resilient.run_resilient) with atomic "
                    "train-state checkpoints in this directory; SIGTERM "
                    "mid-run checkpoints and exits with the resume "
                    "sentinel instead of losing the run")
flags.DEFINE_bool("resume", False,
                  "auto-resume from --checkpoint_dir when a valid "
                  "checkpoint exists (preemption requeue)")
flags.DEFINE_integer("checkpoint_every_steps", 0,
                     "periodic checkpoint cadence for --checkpoint_dir "
                     "(0 = only at exit/preemption)")
flags.DEFINE_integer("keep_last_n", None,
                     "checkpoint-ring size beyond <dir> and <dir>.prev "
                     "(rollback-and-replay recovery candidates); default "
                     "DETPU_CKPT_RING (2)")
flags.DEFINE_integer("rollback_max", None,
                     "NaN-escalation rollback budget before the terminal "
                     "NonFiniteLossError; default DETPU_ROLLBACK_MAX (2)")

_GEN_BATCHES = 4  # distinct pre-generated batches, cycled


def main(_):
    model_config = synthetic_models_v3[FLAGS.model]
    devices = jax.devices()
    world = len(devices)
    mesh = (jax.sharding.Mesh(np.array(devices), ("data",))
            if world > 1 else None)
    de, dense, hotness = build_synthetic(
        model_config, world,
        column_slice_threshold=FLAGS.column_slice_threshold,
        row_cap=FLAGS.row_cap)
    print(de.strategy.describe())

    gen = InputGenerator(model_config, FLAGS.batch_size, alpha=FLAGS.alpha,
                         num_batches=_GEN_BATCHES, row_cap=FLAGS.row_cap)
    num0, cats0, _ = gen[0]
    out_widths = [
        int(de.strategy.global_configs[t]["output_dim"])
        for t in de.strategy.input_table_map]
    dense_params = dense.init(
        jax.random.key(0), num0[:2],
        [jnp.zeros((2, w), jnp.float32) for w in out_widths])

    emb_opt = SparseSGD() if FLAGS.optimizer == "sgd" else SparseAdagrad()
    tx = (optax.sgd(FLAGS.learning_rate) if FLAGS.optimizer == "sgd"
          else optax.adagrad(FLAGS.learning_rate))

    def loss_fn(dp, emb_outs, batch):
        n, y = batch
        pred = dense.apply(dp, n, emb_outs)
        return jnp.mean((pred - y) ** 2)

    state = init_hybrid_state(de, emb_opt, dense_params, tx,
                              jax.random.key(1), mesh=mesh)
    # telemetry pinned off: this benchmark times the raw step (use the
    # dlrm example or DETPU_TELEMETRY with your own loop for hot-row
    # telemetry)
    step_fn = make_hybrid_train_step(de, loss_fn, tx, emb_opt, mesh=mesh,
                                     lr_schedule=FLAGS.learning_rate,
                                     with_metrics=False, telemetry=False)

    if FLAGS.checkpoint_dir:
        # self-healing path: checkpointed, preemption-safe, resumable —
        # the deterministic batch cycle makes an interrupted+resumed run
        # reproduce the uninterrupted trajectory
        def data(start):
            for i in range(start, FLAGS.num_steps):
                num, cats, labels = gen[i % _GEN_BATCHES]
                yield cats, (num, labels)

        t0 = time.perf_counter()
        res = run_resilient(
            step_fn, state, data, de=de,
            checkpoint_dir=FLAGS.checkpoint_dir,
            checkpoint_every_steps=FLAGS.checkpoint_every_steps,
            keep_last_n=FLAGS.keep_last_n,
            rollback_max=FLAGS.rollback_max,
            resume=FLAGS.resume, emb_optimizer=emb_opt, dense_tx=tx,
            mesh=mesh, exit_on_preempt=True)
        dt = (time.perf_counter() - t0) / max(res.steps_run, 1)
        print(f"{model_config.name}: {dt * 1e3:.3f} ms/iter over "
              f"{res.steps_run} resilient step(s) to step {res.step} "
              f"({res.checkpoints_saved} checkpoint(s)), final loss "
              f"{res.last_loss:.5f}" if res.last_loss is not None else
              f"{model_config.name}: resumed past the end (step {res.step})")
        return

    # compile + warmup; float() readback drains the pipeline — on remote
    # tunnels block_until_ready can be a no-op (docs/perf_tpu.md Methodology)
    num, cats, labels = gen[0]
    loss, state = step_fn(state, cats, (num, labels))
    print(f"{model_config.name}: compiled; warmup loss {float(loss):.5f}")

    t0 = time.perf_counter()
    for i in range(FLAGS.num_steps):
        num, cats, labels = gen[i]
        loss, state = step_fn(state, cats, (num, labels))
    # readback forces the whole threaded-state chain before the timer stops
    # (the reference stops on an allreduced-loss print the same way,
    # synthetic_models/main.py:123,138-144 there)
    final_loss = float(loss)
    dt = (time.perf_counter() - t0) / FLAGS.num_steps
    print(f"{model_config.name}: {dt * 1e3:.3f} ms/iter "
          f"({FLAGS.batch_size / dt:,.0f} samples/s) on {world} device(s), "
          f"final loss {final_loss:.5f}")


if __name__ == "__main__":
    app.run(main)

"""Single-op embedding lookup microbenchmark.

TPU port of the reference microbenchmark
(``examples/benchmarks/benchmark.py:23-98``): times forward, forward+backward
and forward+backward+SGD of the fused ragged variable-hotness lookup against
the unfused dense gather+reduce formulation.

Timing discipline (see ``docs/perf_tpu.md`` Methodology): loops chain each
iteration's output into the next call's input — remote-device tunnels can
both no-op ``block_until_ready`` and short-circuit identical dispatches —
and force completion with a value readback before stopping the clock.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
from absl import app, flags

from distributed_embeddings_tpu.ops import Ragged, embedding_lookup

FLAGS = flags.FLAGS
flags.DEFINE_integer("batch_size", 65536, "batch size")
flags.DEFINE_integer("vocab", 1000000, "table rows")
flags.DEFINE_integer("width", 128, "embedding width")
flags.DEFINE_integer("hotness", 10, "average ids per sample")
flags.DEFINE_integer("iters", 50, "timed iterations")


def timeit(step, params, *args, iters):
    """``step(params, *args) -> params_like`` timed with params threading
    (data-dependent chain) and a readback-forced stop."""
    out = step(params, *args)
    float(jnp.sum(out[:1]))  # drain pipeline
    t0 = time.perf_counter()
    for _ in range(iters):
        out = step(out, *args)
    float(jnp.sum(out[:1]))  # force completion of the whole chain
    return (time.perf_counter() - t0) / iters * 1e3


def main(_):
    b, v, w, h = FLAGS.batch_size, FLAGS.vocab, FLAGS.width, FLAGS.hotness
    rng = np.random.default_rng(0)
    params = jnp.asarray(rng.normal(size=(v, w)), jnp.float32)
    # variable hotness in [1, 2h-1], mean h (reference generates variable rows)
    hots = rng.integers(1, 2 * h, size=b)
    total = int(hots.sum())
    values = jnp.asarray(rng.integers(0, v, size=total), jnp.int32)
    splits = jnp.asarray(np.concatenate([[0], np.cumsum(hots)]), jnp.int32)
    ragged = Ragged(values=values, row_splits=splits)
    dense_ids = jnp.asarray(rng.integers(0, v, size=(b, h)), jnp.int32)

    # forward: fold a hair of the output back into params to chain iterations
    fwd = jax.jit(lambda p, r: p.at[0, 0].add(
        1e-30 * jnp.sum(embedding_lookup(p, r, combiner="sum")[0])),
        donate_argnums=0)
    print(f"ragged fwd:           {timeit(fwd, params + 0, ragged, iters=FLAGS.iters):8.3f} ms")
    print(f"dense  fwd:           {timeit(fwd, params + 0, dense_ids, iters=FLAGS.iters):8.3f} ms")

    grad = jax.jit(lambda p, r: p - 1e-30 * jax.grad(
        lambda q: embedding_lookup(q, r, combiner="sum").sum())(p),
        donate_argnums=0)
    print(f"ragged fwd+bwd:       {timeit(grad, params + 0, ragged, iters=FLAGS.iters):8.3f} ms")

    sgd = jax.jit(lambda p, r: p - 0.01 * jax.grad(
        lambda q: embedding_lookup(q, r, combiner="sum").sum())(p),
        donate_argnums=0)
    print(f"ragged fwd+bwd+sgd:   {timeit(sgd, params + 0, ragged, iters=FLAGS.iters):8.3f} ms")


if __name__ == "__main__":
    app.run(main)

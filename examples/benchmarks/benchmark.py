"""Single-op embedding lookup microbenchmark.

TPU port of the reference microbenchmark
(``examples/benchmarks/benchmark.py:23-98``): times forward, forward+backward
and forward+backward+SGD of the fused ragged variable-hotness lookup against
the unfused dense gather+reduce formulation.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
from absl import app, flags

from distributed_embeddings_tpu.ops import Ragged, embedding_lookup

FLAGS = flags.FLAGS
flags.DEFINE_integer("batch_size", 65536, "batch size")
flags.DEFINE_integer("vocab", 1000000, "table rows")
flags.DEFINE_integer("width", 128, "embedding width")
flags.DEFINE_integer("hotness", 10, "average ids per sample")
flags.DEFINE_integer("iters", 50, "timed iterations")


def timeit(fn, *args, iters):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters * 1e3


def main(_):
    b, v, w, h = FLAGS.batch_size, FLAGS.vocab, FLAGS.width, FLAGS.hotness
    rng = np.random.default_rng(0)
    params = jnp.asarray(rng.normal(size=(v, w)), jnp.float32)
    # variable hotness in [1, 2h-1], mean h (reference generates variable rows)
    hots = rng.integers(1, 2 * h, size=b)
    total = int(hots.sum())
    values = jnp.asarray(rng.integers(0, v, size=total), jnp.int32)
    splits = jnp.asarray(np.concatenate([[0], np.cumsum(hots)]), jnp.int32)
    ragged = Ragged(values=values, row_splits=splits)
    dense_ids = jnp.asarray(rng.integers(0, v, size=(b, h)), jnp.int32)

    fwd = jax.jit(lambda p, r: embedding_lookup(p, r, combiner="sum"))
    print(f"ragged fwd:           {timeit(fwd, params, ragged, iters=FLAGS.iters):8.3f} ms")

    dfwd = jax.jit(lambda p, i: embedding_lookup(p, i, combiner="sum"))
    print(f"dense  fwd:           {timeit(dfwd, params, dense_ids, iters=FLAGS.iters):8.3f} ms")

    grad = jax.jit(jax.grad(lambda p, r: embedding_lookup(p, r, combiner="sum").sum()))
    print(f"ragged fwd+bwd:       {timeit(grad, params, ragged, iters=FLAGS.iters):8.3f} ms")

    sgd = jax.jit(lambda p, r: p - 0.01 * jax.grad(
        lambda q: embedding_lookup(q, r, combiner="sum").sum())(p),
        donate_argnums=0)
    p2 = jnp.array(params)
    out = sgd(p2, ragged)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(FLAGS.iters):
        out = sgd(out, ragged)
    jax.block_until_ready(out)
    print(f"ragged fwd+bwd+sgd:   {(time.perf_counter()-t0)/FLAGS.iters*1e3:8.3f} ms")


if __name__ == "__main__":
    app.run(main)

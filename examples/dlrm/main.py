"""DLRM training example.

TPU port of the reference example (``examples/dlrm/main.py``): MLPerf-config
DLRM trained with hybrid parallelism — table-model-parallel embeddings over
the device mesh, data-parallel MLPs — on the Criteo raw-binary dataset (or
synthetic data when no ``--dataset_path`` is given). SGD with the MLPerf
warmup + polynomial-decay schedule, AUC evaluation, and a global embedding
checkpoint dump at the end.

Single chip:    python main.py --num_batches 100
CPU mesh dry:   JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
                python main.py --num_batches 20 --batch_size 1024 --table_sizes 1000 ...
"""

import json
import os
import sys

# test hook: run the example on N virtual CPU devices (the smoke test drives
# the full script this way; a TPU run never sets this)
if os.environ.get("DETPU_FORCE_CPU_DEVICES"):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count="
        + os.environ["DETPU_FORCE_CPU_DEVICES"])

import jax

if os.environ.get("DETPU_FORCE_CPU_DEVICES"):
    jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
import optax
from absl import app, flags

from distributed_embeddings_tpu.models.dlrm import (
    DLRMConfig, DLRMDense, bce_with_logits)
from distributed_embeddings_tpu.models.schedules import (
    warmup_poly_decay_schedule)
from distributed_embeddings_tpu.analysis import telemetry
from distributed_embeddings_tpu.parallel import (
    DistributedEmbedding, SparseSGD, bootstrap, init_hybrid_state,
    make_hybrid_eval_step, make_hybrid_train_step, run_resilient)
from distributed_embeddings_tpu.utils import (
    RawBinaryDataset, binary_auc, obs, power_law_ids)

FLAGS = flags.FLAGS
flags.DEFINE_string("dataset_path", None,
                    "Criteo split-binary root (with model_size.json)")
flags.DEFINE_float("learning_rate", 24, "base learning rate")
flags.DEFINE_integer("batch_size", 64 * 1024, "global batch size")
flags.DEFINE_list("top_mlp_dims", ["1024", "1024", "512", "256", "1"],
                  "top MLP sizes")
flags.DEFINE_list("bottom_mlp_dims", ["512", "256", "128"],
                  "bottom MLP sizes")
flags.DEFINE_integer("num_numerical_features", 13, "dense feature count")
flags.DEFINE_integer("num_batches", 340,
                     "synthetic batches when no dataset is given")
flags.DEFINE_list("table_sizes", [str(x) for x in 26 * [1000]],
                  "vocab size per table for the synthetic dataset")
flags.DEFINE_integer("embedding_dim", 128, "embedding width")
flags.DEFINE_string("dist_strategy", "memory_balanced",
                    "table placement strategy")
flags.DEFINE_integer("column_slice_threshold", None,
                     "max elements per table slice")
flags.DEFINE_string("checkpoint_out", "/tmp/embedding_weights",
                    "np.savez path for final global embedding weights")
flags.DEFINE_bool("dp_input", False,
                  "feed data-parallel id shards through the dp->mp exchange; "
                  "False (default, like the reference example) feeds "
                  "model-parallel input, skipping the id all-to-all")
flags.DEFINE_integer("eval_interval", 0,
                     "evaluate every N training steps (0 = only at the end)")
flags.DEFINE_float("auc_threshold", None,
                   "stop training early once a mid-training evaluation "
                   "reaches this AUC (MLPerf-style convergence target)")
flags.DEFINE_integer("eval_batches", 4,
                     "synthetic evaluation batches when no dataset is given "
                     "(a real dataset evaluates its full validation split)")
flags.DEFINE_string("save_state", None,
                    "directory for a FULL train-state checkpoint (tables + "
                    "sparse-optimizer state + dense + step; resumable via "
                    "utils.restore_train_state) in addition to the "
                    "reference-style embedding-weights dump")
flags.DEFINE_string("restore_state", None,
                    "resume from a --save_state checkpoint directory "
                    "(restores tables, sparse-optimizer state, dense "
                    "params/optimizer and the step counter; a torn "
                    "checkpoint falls back to <dir>.prev automatically)")
flags.DEFINE_bool("resume", False,
                  "auto-resume from --save_state when a valid checkpoint "
                  "(or its .prev fallback) exists there — the "
                  "preemption-requeue form of --restore_state (the run a "
                  "SIGTERM checkpointed continues where it left off, no "
                  "batch replayed or skipped)")
flags.DEFINE_integer("checkpoint_interval", 0,
                     "checkpoint the full train state to --save_state "
                     "every N steps (0 = only at exit/preemption)")
flags.DEFINE_float("checkpoint_time_s", 0,
                   "also checkpoint when this much wall-clock passed "
                   "since the last save (bounds work lost to preemption; "
                   "0 = disabled)")
flags.DEFINE_integer("keep_last_n", None,
                     "checkpoint-ring size: how many generations beyond "
                     "--save_state and its .prev stay restorable (the "
                     "rollback-and-replay recovery's supply of known-good "
                     "states); default DETPU_CKPT_RING (2)")
flags.DEFINE_integer("rollback_max", None,
                     "rollback-and-replay attempts on a NaN escalation "
                     "before NonFiniteLossError turns terminal; default "
                     "DETPU_ROLLBACK_MAX (2)")
flags.DEFINE_integer("quarantine_max", None,
                     "total batches the recovery may quarantine before "
                     "declaring the stream poisoned; default "
                     "DETPU_QUARANTINE_MAX (8)")
flags.DEFINE_float("bootstrap_timeout_s", None,
                   "per-attempt deadline for the multi-host runtime join "
                   "(None = jax defaults); a slow coordinator is retried "
                   "with backoff instead of hanging the pod")
flags.DEFINE_integer("bootstrap_retries", 2,
                     "join retry budget before a cluster-expected job "
                     "fails with CoordinatorUnreachable")
flags.DEFINE_string("metrics_out", None,
                    "step-metrics JSONL sidecar path (observability layer); "
                    "default <checkpoint_out>.metrics.jsonl when DETPU_OBS=1 "
                    "is set, disabled otherwise")
flags.DEFINE_integer("metrics_interval", 100,
                     "log a step-metrics record every N training steps "
                     "(only when metrics are enabled)")
flags.DEFINE_enum("plan_audit", "off", ["off", "warn", "strict"],
                  "plan-time capacity preflight (analysis.plan_audit): "
                  "price the placement plan — per-rank HBM, per-step "
                  "all-to-all payloads, apply-slab scatter-cliff exposure "
                  "— BEFORE any table is materialized, against the "
                  "--plan_audit_chip contract. 'warn' prints the report "
                  "and any violations; 'strict' additionally refuses to "
                  "start a plan that violates its contract (exit 2) — the "
                  "capacity gate you run before touching a pod")
flags.DEFINE_string("plan_audit_chip", "v5e",
                    "capacity-registry chip the preflight contract binds "
                    "to (see analysis.plan_audit.CHIP_SPECS)")
flags.DEFINE_float("serve_qps", 0,
                   "after training, serve a Zipfian request stream from "
                   "the trained model at this rate through the "
                   "deadline-bounded ServingRuntime (parallel/serving.py) "
                   "and print p50/p95/p99 + shed/pad stats — the "
                   "inference half of the example (0 = off; "
                   "single-process runs only)")
flags.DEFINE_float("serve_seconds", 5,
                   "duration of the --serve_qps stream")
flags.DEFINE_enum("param_dtype", "float32", ["float32", "bfloat16"],
                  "embedding table (slab) dtype. bfloat16 halves per-rank "
                  "HBM and a2a activation payloads — the dtype the "
                  "Criteo-1TB v5e-16 deployment plan is audited at; the "
                  "plan-audit preflight prices whichever is selected")


def synthetic_batches(cfg, num_batches, batch_size, seed=0):
    rng = np.random.default_rng(seed)
    for _ in range(num_batches):
        num = jnp.asarray(rng.normal(size=(batch_size,
                                           cfg.num_numerical_features)),
                          jnp.float32)
        cats = [jnp.asarray(power_law_ids(rng, s, (batch_size,)), jnp.int32)
                for s in cfg.table_sizes]
        labels = jnp.asarray(rng.integers(0, 2, size=(batch_size, 1)),
                             jnp.float32)
        yield num, cats, labels


def main(_):
    # multi-host bootstrap (the reference's hvd.init, main.py:152-157 there):
    # no-op on a single host; on a pod every host runs this same script.
    # Deadline-bounded + retried (utils.runtime): a slow coordinator gets
    # retried, an unreachable one fails loudly instead of hanging forever
    bootstrap.initialize(timeout_s=FLAGS.bootstrap_timeout_s,
                         retries=FLAGS.bootstrap_retries)
    is_chief = bootstrap.process_index() == 0

    # observability (all off unless the env/flags ask): live-profiler
    # server, recompile counter, step-metrics sidecar
    obs.maybe_start_server()
    with_metrics = obs.metrics_enabled() or FLAGS.metrics_out is not None
    metrics_log = None
    if with_metrics:
        obs.install_compile_listener()
        if is_chief:
            metrics_log = obs.MetricsLogger(
                FLAGS.metrics_out
                or FLAGS.checkpoint_out + ".metrics.jsonl")

    table_sizes = [int(s) for s in FLAGS.table_sizes]
    if FLAGS.dataset_path is not None:
        with open(os.path.join(FLAGS.dataset_path, "model_size.json"),
                  encoding="utf-8") as f:
            table_sizes = [s + 1 for s in json.load(f).values()]

    cfg = DLRMConfig(
        table_sizes=table_sizes,
        embedding_dim=FLAGS.embedding_dim,
        num_numerical_features=FLAGS.num_numerical_features,
        bottom_mlp_dims=[int(d) for d in FLAGS.bottom_mlp_dims],
        top_mlp_dims=[int(d) for d in FLAGS.top_mlp_dims])

    devices = jax.devices()
    world = len(devices)
    mesh = (jax.sharding.Mesh(np.array(devices), ("data",))
            if world > 1 else None)
    # mp input only means anything on a real mesh
    use_mp_input = (not FLAGS.dp_input) and world > 1
    de = DistributedEmbedding(cfg.embedding_configs(),
                              world_size=world,
                              strategy=FLAGS.dist_strategy,
                              dp_input=not use_mp_input,
                              column_slice_threshold=FLAGS.column_slice_threshold)
    dense = DLRMDense(cfg)
    if is_chief:
        print(de.strategy.describe())

    if FLAGS.plan_audit != "off":
        # the capacity gate, BEFORE anything is materialized: the same
        # backend-free model `tools/plan_audit.py --strict` enforces in
        # make verify, here bound to this run's actual plan/batch/input
        # mode. A plan that cannot fit (or holds a past-cliff apply
        # slab) fails in milliseconds instead of OOMing a pod.
        from distributed_embeddings_tpu.analysis import plan_audit as pa
        report = pa.audit_plan(
            de, FLAGS.batch_size, optimizer="sgd",
            param_dtype=FLAGS.param_dtype,
            dp_input=not use_mp_input, chip=FLAGS.plan_audit_chip,
            label="dlrm_preflight",
            contract=pa.default_contract(FLAGS.plan_audit_chip))
        if is_chief:
            print(report.markdown())
        if not report.ok and FLAGS.plan_audit == "strict":
            print(f"plan_audit: {len(report.violations)} capacity "
                  "contract violation(s); refusing to start (use "
                  "--plan_audit=warn to proceed anyway)", file=sys.stderr)
            sys.exit(2)

    dense_params = dense.init(
        jax.random.key(0),
        jnp.zeros((2, cfg.num_numerical_features), jnp.float32),
        [jnp.zeros((2, cfg.embedding_dim), jnp.float32)
         for _ in table_sizes])

    emb_opt = SparseSGD()
    sched = warmup_poly_decay_schedule(
        FLAGS.learning_rate, warmup_steps=8000,
        decay_start_step=48000, decay_steps=24000)
    # the same schedule drives both sides: optax natively for the dense
    # params, lr_schedule for the sparse embedding updates
    tx = optax.sgd(sched)

    def loss_fn(dp, emb_outs, batch):
        n, y = batch
        return bce_with_logits(dense.apply(dp, n, emb_outs), y)

    if FLAGS.restore_state:
        from distributed_embeddings_tpu.utils import (envvars,
                                                      restore_train_state)
        state = restore_train_state(
            FLAGS.restore_state, de, emb_opt, dense_params, tx, mesh=mesh,
            # elastic by default, like run_resilient: a checkpoint from a
            # different world size/plan re-shards in place (DETPU_ON_
            # MISMATCH=error restores the strict behavior)
            on_mismatch=envvars.get("DETPU_ON_MISMATCH"))
        if is_chief:
            print("restored train state at step", int(state.step),
                  "from", FLAGS.restore_state)
    else:
        state = init_hybrid_state(de, emb_opt, dense_params, tx,
                                  jax.random.key(1), mesh=mesh,
                                  dtype=jnp.dtype(FLAGS.param_dtype))
    # DETPU_TELEMETRY=1: build the step with jit-carried access
    # telemetry (hot-row sketches + per-rank loads); the resilient
    # driver threads the state and flushes <save_state>.telemetry.json
    # alongside each checkpoint. Step arity changes with it, so the
    # step build and the carried state are decided TOGETHER here.
    with_telemetry = telemetry.telemetry_enabled()
    step_fn = make_hybrid_train_step(de, loss_fn, tx, emb_opt, mesh=mesh,
                                     lr_schedule=sched,
                                     with_metrics=with_metrics,
                                     telemetry=with_telemetry)
    telem = (telemetry.init_telemetry(de, mesh=mesh) if with_telemetry
             else None)

    nproc = bootstrap.process_count()
    pid = bootstrap.process_index()
    if FLAGS.batch_size % world:
        # world = process_count * local_devices; the len//nproc slicing below
        # would silently drop the remainder of every global batch — fail
        # loudly instead (ADVICE r2)
        raise ValueError(
            f"--batch_size {FLAGS.batch_size} must be divisible by the "
            f"global device count {world} ({nproc} processes)")

    def prep_cats(cats):
        """Global per-feature id arrays -> the executor's input format."""
        if use_mp_input:
            # multi-host correct: each process materializes only its blocks
            return de.pack_mp_inputs(cats, mesh=mesh)
        if nproc > 1:
            # dp input on a pod: every process holds the same global batch
            # (synthetic: seeded identically; Criteo: full-file readers) and
            # contributes its rows of it
            def local_rows(c):
                c = np.asarray(c)
                return c[(len(c) // nproc) * pid:(len(c) // nproc) * (pid + 1)]
            return [bootstrap.shard_batch(mesh, local_rows(c)) for c in cats]
        return [jnp.asarray(c) for c in cats]

    def prep_batch(num, labels):
        """Dense features/labels -> per-device data-parallel shards."""
        if nproc > 1:
            lb = num.shape[0] // nproc
            return bootstrap.shard_batch(
                mesh, (np.asarray(num)[lb * pid:lb * (pid + 1)],
                       np.asarray(labels)[lb * pid:lb * (pid + 1)]))
        return jnp.asarray(num), jnp.asarray(labels)

    def data_source(start):
        """Batch stream positioned at absolute step ``start`` (the
        resilient driver's resume contract: no batch replayed or
        skipped), already prepped into ``(cat_inputs, batch)`` pairs."""
        if FLAGS.dataset_path is not None:
            # mp input reads full global batches per feature and packs
            # them per-rank; on a multi-host launch each process would
            # restrict categorical_features to its local ranks' tables
            # (reference main.py:166-176). start_batch positions the
            # memmap readers directly — no replay cost.
            ds = RawBinaryDataset(
                data_path=FLAGS.dataset_path, batch_size=FLAGS.batch_size,
                numerical_features=FLAGS.num_numerical_features,
                categorical_features=list(range(len(table_sizes))),
                categorical_feature_sizes=table_sizes,
                drop_last_batch=True, dp_input=not use_mp_input,
                start_batch=start)
            it = ((jnp.asarray(n), cs, jnp.asarray(y)) for n, cs, y in ds)
        else:
            import itertools
            # seeded generation is deterministic: skipping the first
            # ``start`` batches reproduces the uninterrupted stream
            it = itertools.islice(
                synthetic_batches(cfg, FLAGS.num_batches,
                                  FLAGS.batch_size), start, None)
        for num, cats, labels in it:
            yield prep_cats(cats), prep_batch(num, labels)

    if FLAGS.dataset_path is not None:
        eval_data = RawBinaryDataset(
            data_path=FLAGS.dataset_path, batch_size=FLAGS.batch_size,
            numerical_features=FLAGS.num_numerical_features,
            categorical_features=list(range(len(table_sizes))),
            categorical_feature_sizes=table_sizes,
            drop_last_batch=True, valid=True, dp_input=not use_mp_input)
    else:
        # a fixed held-out synthetic set so mid-training eval is meaningful
        eval_data = (list(synthetic_batches(cfg, FLAGS.eval_batches,
                                            FLAGS.batch_size, seed=1))
                     if FLAGS.eval_batches else None)

    eval_fn = make_hybrid_eval_step(
        de, lambda dp, outs, n: jax.nn.sigmoid(dense.apply(dp, n, outs)),
        mesh=mesh)

    def evaluate(state):
        """Full pass over the eval split -> global AUC (the reference's
        allgather eval, ``examples/dlrm/main.py:230-243`` there)."""
        all_preds, all_labels = [], []
        for num, cats, labels in eval_data:
            num_in = (prep_batch(num, labels)[0] if nproc > 1
                      else jnp.asarray(num))
            preds = eval_fn(state, prep_cats(cats), num_in)
            # process-spanning predictions gather to every host
            all_preds.append(bootstrap.to_host(preds))
            all_labels.append(np.asarray(labels))
        return binary_auc(np.concatenate(all_labels),
                          np.concatenate(all_preds))

    # flag-driven mid-training eval cadence with an MLPerf-style AUC stop
    # target (VERDICT r3 Missing #3), hosted in the resilient driver's
    # per-step callback; resume numbers steps globally so logging/eval
    # cadence stays aligned with the uninterrupted run

    def on_step(step, loss, metrics, cur_state):
        del metrics  # the driver already handles the metrics sidecar
        if step % 1000 == 0 and is_chief:
            print("step:", step, " loss:", float(loss))
        if (FLAGS.eval_interval and eval_data is not None and step
                and step % FLAGS.eval_interval == 0):
            auc = evaluate(cur_state)
            if is_chief:
                print(f"eval step: {step} AUC: {auc}")
            if FLAGS.auc_threshold is not None and auc >= FLAGS.auc_threshold:
                if is_chief:
                    print(f"AUC threshold {FLAGS.auc_threshold} reached at "
                          f"step {step}, stopping")
                return True
        return False

    # The self-healing driver: periodic/wall-clock checkpoints to
    # --save_state (a keep_last_n ring of generations), SIGTERM/SIGINT ->
    # finish step + checkpoint + exit 83 (resume sentinel beside the
    # checkpoint dir), --resume auto-restores and fast-forwards the data
    # stream, and K consecutive non-finite losses roll back to the newest
    # healthy ring entry, quarantine the poisoned batch window (per-table
    # sentinels naming the unhealthy table), and continue — terminal
    # NonFiniteLossError only after the rollback budget.
    result = run_resilient(
        step_fn, state, data_source, de=de,
        checkpoint_dir=FLAGS.save_state,
        checkpoint_every_steps=FLAGS.checkpoint_interval,
        checkpoint_every_s=FLAGS.checkpoint_time_s,
        keep_last_n=FLAGS.keep_last_n,
        rollback_max=FLAGS.rollback_max,
        quarantine_max=FLAGS.quarantine_max,
        resume=FLAGS.resume,
        emb_optimizer=emb_opt, dense_tx=tx, mesh=mesh,
        metrics_logger=metrics_log,
        metrics_interval=FLAGS.metrics_interval,
        on_step=on_step,
        telemetry_state=telem,
        # exit code 83 asserts "checkpointed, requeue me" — only true when
        # a checkpoint dir exists; without one a SIGTERM just ends the
        # loop and the script finishes gracefully (weights dump below)
        exit_on_preempt=FLAGS.save_state is not None,
        save_on_exit=FLAGS.save_state is not None,
        is_chief=is_chief)
    state = result.state

    # an "on_step" stop is exactly the AUC-threshold early stop — the
    # end-of-training eval is skipped like the pre-driver loop did
    if eval_data is not None and result.stop_reason != "on_step":
        auc = evaluate(state)
        if is_chief:
            print(f"Evaluation completed, AUC: {auc}")

    if FLAGS.serve_qps > 0 and nproc == 1 and use_mp_input:
        print("serving epilogue skipped: the ServingRuntime coalesces "
              "data-parallel requests — rerun with --dp_input")
    elif FLAGS.serve_qps > 0 and nproc == 1:
        # inference epilogue: the deadline-bounded serving runtime over
        # the JUST-TRAINED state — variable-size Zipfian requests
        # coalesce into the padded-batch ladder (warmed up front, zero
        # steady-state recompiles), overload sheds typed
        from distributed_embeddings_tpu.parallel import (ServeConfig,
                                                         ServingRuntime)
        from distributed_embeddings_tpu.parallel import serving as sv

        rt = ServingRuntime(
            de, lambda dp, outs, n: jax.nn.sigmoid(
                dense.apply(dp, n, outs))[:, 0],
            state, mesh=mesh, config=ServeConfig())
        srng = np.random.default_rng(2)
        tmpl = sv.synthetic_request(
            srng, table_sizes, 2,
            numerical=FLAGS.num_numerical_features)
        rt.warmup((tmpl.cats, tmpl.batch))
        sv.drive(rt, lambda i: sv.synthetic_request(
                     srng, table_sizes, int(srng.integers(1, 9)),
                     numerical=FLAGS.num_numerical_features),
                 FLAGS.serve_qps, FLAGS.serve_seconds)
        s = rt.stats()
        print(f"serving: {s['served']} served at {FLAGS.serve_qps:.0f} "
              f"QPS target — p50/p95/p99 = {s['latency_p50_ms']:.1f}/"
              f"{s['latency_p95_ms']:.1f}/{s['latency_p99_ms']:.1f} ms, "
              f"shed={s['shed']}, deadline_missed={s['deadline_missed']}, "
              f"pad={s['pad_fraction']:.2f}, "
              f"recompiles={s['steady_state_recompiles']}")

    # every process participates in the chunked gather; rank 0 writes
    # (reference main.py:246-248 there)
    weights = de.get_weights(state.emb_params)
    if is_chief:
        np.savez(FLAGS.checkpoint_out, *weights)
        print("saved", len(weights), "tables to", FLAGS.checkpoint_out)
    if FLAGS.save_state and is_chief:
        # the driver's save_on_exit already wrote it, atomically
        print("saved full train state to", FLAGS.save_state)
    if metrics_log is not None:
        # final process-counter snapshot: recompiles, runtime retries,
        # fault injections — the "why was this run slow/odd" record
        metrics_log.log_counters(final=True)


if __name__ == "__main__":
    app.run(main)

#!/usr/bin/env python
"""Audit the compiled hybrid step's schedule graph on a CPU mesh.

The jaxpr auditor checks which collectives we ask for, the HLO census
counts what XLA emits; this gate sees the DEPENDENCY STRUCTURE between
them. It builds the shared reference configurations
(``tools/_profcommon.build_case`` — the same shapes every static gate
uses, plus the ``streaming`` dynamic-vocab case and the real Criteo-1TB
vector), compiles each hybrid train step abstractly, parses the
optimized HLO into the full dependency DAG
(:mod:`distributed_embeddings_tpu.analysis.schedule_audit`), prices it
under the v5e cost model, and enforces:

* the **baseline contracts** — the id / out / grad all-to-alls exist,
  sit on the modeled critical path, and are SERIALIZED against dense
  compute (today's unpipelined step, the documented starting line the
  pipelined step has to beat);
* the layer's declared :class:`StepSchedule` — every overlap a schedule
  claims must exist in the compiled DAG;
* a **seeded drill**: a fake overlap-declaring schedule (claiming the
  id exchange hides under dense compute) is checked against the real
  serialized program and MUST fail — if the auditor ever lets that lie
  through, this gate fails itself.

Nothing executes on any backend — ``lower().compile()`` only.

    python tools/schedule_audit.py --strict          # make verify's gate
    python tools/schedule_audit.py --json report.json --config dense
    python tools/schedule_audit.py --markdown        # per-case tables

Exit codes: 0 clean; 1 violations found or drill not caught (only with
``--strict``); 2 usable-environment failure.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

try:  # imported as tools.schedule_audit (tests)
    from tools._profcommon import build_case, cpu_mesh, force_cpu  # noqa: F401
except ImportError:  # run as a script: tools/ itself is sys.path[0]
    from _profcommon import build_case, cpu_mesh, force_cpu  # noqa: F401

#: (case, world, global batch, optimizer) sweep. Batches are large
#: enough that the a2a payloads dominate the toy dense-update branch —
#: at production shapes they dominate by orders of magnitude, and the
#: serialized-baseline classification must not flip on the audit shapes.
CASES = (
    ("dense", 8, 256, "adagrad"),
    ("pipelined", 8, 256, "adagrad"),
    ("ragged", 8, 256, "adagrad"),
    ("row_sliced", 8, 256, "adagrad"),
    ("bigvocab", 8, 256, "sgd"),
    ("streaming", 8, 256, "adagrad"),
    ("criteo1tb", 16, 4096, "adagrad"),
)


def audit_case(name: str, world: int, batch: int, opt_name: str):
    """Audit one (config, optimizer) pair against the baseline."""
    import optax

    from distributed_embeddings_tpu.analysis import schedule_audit as sa
    from distributed_embeddings_tpu.parallel import (SparseAdagrad,
                                                     SparseSGD,
                                                     StreamingConfig)

    opt = SparseSGD() if opt_name == "sgd" else SparseAdagrad()
    de, cats, batch_tree, dense_params, loss_fn = build_case(
        name, world, batch)
    dynamic = StreamingConfig() if name == "streaming" else None
    contracts = None  # baseline_contracts(): all three a2as serialized
    if name == "pipelined":
        # the K=2 software-pipelined step: every declared microbatch
        # overlap must EXIST in the compiled DAG (the declaration check
        # runs via de.schedule) AND every declaring exchange must
        # classify overlappable — the ROADMAP item 2 acceptance this
        # gate certifies
        contracts = sa.declared_overlap_contracts(de.schedule)
    elif name == "streaming":
        # the auditor's first real finding: the staged slot-map/sketch
        # transitions branch off the received ids and are consumed only
        # at commit — a genuine independent compute chain next to the
        # activation/cotangent exchanges. The id exchange stays
        # serialized (everything downstream depends on it).
        why = ("streaming admission staging (slot-map/sketch "
               "transitions) is independent of this exchange — the "
               "overlap candidate a pipelined step can exploit")
        contracts = [
            sa.ScheduleContract("id_all_to_all", expect="serialized",
                                on_critical_path=True,
                                reason="unpipelined baseline"),
            sa.ScheduleContract("out_all_to_all", expect="overlappable",
                                reason=why),
            sa.ScheduleContract("grad_all_to_all", expect="overlappable",
                                reason=why),
        ]
    return sa.audit_train_step(
        de, loss_fn, optax.sgd(0.5), opt, cats, batch_tree,
        mesh=cpu_mesh(world), lr_schedule=0.3, dynamic=dynamic,
        dense_params=dense_params, contracts=contracts,
        label=f"{name}/world{world}/{opt_name}")


def seeded_drill(world: int, batch: int) -> int:
    """The self-check: a schedule CLAIMING the id exchange overlaps the
    dense compute, audited against the real (serialized) program, must
    produce violations. Returns 0 when the drill fired, 1 when the fake
    overlap slipped through."""
    import optax

    from distributed_embeddings_tpu.analysis import schedule_audit as sa
    from distributed_embeddings_tpu.parallel import SparseAdagrad
    from distributed_embeddings_tpu.parallel.schedule import (
        PHASE_APPLY, PHASE_DENSE, PHASE_GRAD_EXCHANGE, PHASE_ID_EXCHANGE,
        PHASE_LOOKUP, PHASE_OUT_EXCHANGE, PhaseDecl, StepSchedule)

    # a "pipelined" schedule nobody implemented: microbatch k+1's id
    # exchange supposedly hides under microbatch k's dense compute, so
    # no `after` chain ties them and the overlap claim is declarable
    fake = StepSchedule(
        name="fake-pipelined-drill",
        phases=(
            PhaseDecl(PHASE_ID_EXCHANGE, kind="collective",
                      overlaps=(PHASE_DENSE,)),
            PhaseDecl(PHASE_LOOKUP, kind="compute",
                      after=(PHASE_ID_EXCHANGE,)),
            PhaseDecl(PHASE_OUT_EXCHANGE, kind="collective",
                      after=(PHASE_LOOKUP,)),
            PhaseDecl(PHASE_DENSE, kind="compute"),
            PhaseDecl(PHASE_GRAD_EXCHANGE, kind="collective",
                      after=(PHASE_DENSE,)),
            PhaseDecl(PHASE_APPLY, kind="compute",
                      after=(PHASE_GRAD_EXCHANGE,)),
        ))
    de, cats, batch_tree, dense_params, loss_fn = build_case(
        "dense", world, batch)
    rep = sa.audit_train_step(
        de, loss_fn, optax.sgd(0.5), SparseAdagrad(), cats, batch_tree,
        mesh=cpu_mesh(world), lr_schedule=0.3, dense_params=dense_params,
        schedule=fake, contracts=[], label="drill/fake-overlap")
    if rep.ok:
        print("schedule_audit: DRILL FAILED — the fake overlap-declaring "
              "schedule passed against the serialized program; the "
              "overlap check is not checking", file=sys.stderr)
        return 1
    print("schedule_audit: drill OK (fake overlap-declaring schedule "
          f"rejected: {rep.violations[0]})")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--config",
                    choices=("dense", "pipelined", "ragged", "row_sliced",
                             "bigvocab", "streaming", "criteo1tb", "all"),
                    default="all")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 on any violation (the make verify gate)")
    ap.add_argument("--markdown", action="store_true",
                    help="print each case's collective table")
    ap.add_argument("--json", metavar="PATH",
                    help="dump the full reports as JSON (- for stdout)")
    ap.add_argument("--no-drill", action="store_true",
                    help="skip the seeded fake-overlap drill")
    args = ap.parse_args(argv)

    cases = [c for c in CASES
             if args.config == "all" or c[0] == args.config]
    force_cpu(max(c[1] for c in cases))
    sys.path.insert(0, REPO)

    reports = []
    failed = 0
    for name, world, batch, opt_name in cases:
        try:
            rep = audit_case(name, world, batch, opt_name)
        except Exception as e:  # noqa: BLE001 - report, then fail the gate
            print(f"schedule_audit: {name}/{opt_name}: audit errored: {e}",
                  file=sys.stderr)
            return 2
        reports.append(rep)
        status = "OK" if rep.ok else "FAIL"
        n_ser = sum(c.classification == "serialized"
                    for c in rep.collectives)
        print(f"schedule_audit: {rep.label}: {status} "
              f"nodes={rep.nodes} edges={rep.edges} "
              f"collectives={len(rep.collectives)} "
              f"serialized={n_ser} "
              f"frac={rep.serialized_collective_fraction:.3f} "
              f"critical_path={rep.critical_path_ns / 1e3:.1f}us")
        if args.markdown:
            print(rep.markdown())
        for v in rep.violations:
            print(f"schedule_audit:   violation: {v}", file=sys.stderr)
            failed += 1
    if not args.no_drill:
        failed += seeded_drill(8, 256)
    if args.json:
        payload = json.dumps([r.to_json() for r in reports], indent=2)
        if args.json == "-":
            print(payload)
        else:
            with open(args.json, "w", encoding="utf-8") as fh:
                fh.write(payload + "\n")
    if failed and args.strict:
        print(f"schedule_audit: {failed} violation(s)", file=sys.stderr)
        return 1
    if not failed:
        print(f"schedule_audit: OK ({len(reports)} case(s) certify the "
              "serialized baseline; drill caught the fake overlap)"
              if not args.no_drill else
              f"schedule_audit: OK ({len(reports)} case(s))")
    return 0


if __name__ == "__main__":
    sys.exit(main())

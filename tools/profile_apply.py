"""Bisect the DLRM dense-variant sparse apply (66 ms measured in the step
phase split): how much is the unavoidable SGD scatter, how much is glue
(grad assembly / broadcast / concat / cast)?

Usage: python tools/profile_apply.py
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

CAP_SIZES = [min(s, 2_000_000) for s in [
    1460, 583, 10131227, 2202608, 305, 24, 12517, 633, 3, 93145, 5683,
    8351593, 3194, 27, 14992, 5461306, 10, 5652, 2173, 4, 7046547, 18, 15,
    286181, 105, 142572]]
B = 65536
N = 26
W = 128


def readback(x):
    return float(jnp.asarray(x).reshape(-1)[0])


def slope_donate(make_fn, args, iters_hi=3):
    f1 = jax.jit(make_fn(1), donate_argnums=(0,))
    fh = jax.jit(make_fn(iters_hi), donate_argnums=(0,))

    state = {"args": args}

    def run(f):
        s, sl = f(*state["args"])
        state["args"] = (sl,) + state["args"][1:]
        return readback(s)

    run(f1); run(fh)
    t0 = time.perf_counter(); run(f1); t1 = time.perf_counter()
    run(fh); t2 = time.perf_counter()
    return ((t2 - t1) - (t1 - t0)) / (iters_hi - 1) * 1e3


def main():
    rng = np.random.default_rng(0)
    rows_total = sum(CAP_SIZES)
    offs = np.concatenate([[0], np.cumsum(CAP_SIZES)[:-1]]).astype(np.int64)
    ids_np = np.zeros((N, B), np.int64)
    for i, s in enumerate(CAP_SIZES):
        u = rng.random(B)
        ids_np[i] = np.minimum((u ** 3 * s).astype(np.int64), s - 1) + offs[i]
    ids = jnp.asarray(ids_np.reshape(-1).astype(np.int32))  # [N*B]

    def fresh_slab():  # each phase donates (and so deletes) its slab
        return jnp.zeros((rows_total, W), jnp.float32) + 0.5

    vals_bf16 = jnp.zeros((N * B, W), jnp.bfloat16) + 1e-3

    # (a) raw scatter, fp32 updates
    def mk_a(k):
        def f(sl, ids_, v):
            s = jnp.float32(0)
            for _ in range(k):
                sl = sl.at[ids_].add(v.astype(jnp.float32) * (1.0 + s * 0))
                s = s + sl[0, 0]
            return s, sl
        return f
    print(f"raw SGD scatter ({N*B} rows): "
          f"{slope_donate(mk_a, (fresh_slab(), ids, vals_bf16)):.1f} ms", flush=True)

    # (b) scatter from per-feature grad slices [N, B, W] bf16 with the
    # backward's broadcast/transpose/concat glue in front
    grad = jnp.zeros((B, N * W), jnp.bfloat16) + 1e-3  # mp_grad row layout

    def mk_b(k):
        def f(sl, ids_, g):
            s = jnp.float32(0)
            for _ in range(k):
                gsl = g.reshape(1, B, N, W).transpose(0, 2, 1, 3)
                vals = gsl.reshape(-1, W).astype(jnp.float32)
                sl = sl.at[ids_].add(vals * (1.0 + s * 0))
                s = s + sl[0, 0]
            return s, sl
        return f
    print("scatter + transpose/cast glue: "
          f"{slope_donate(mk_b, (fresh_slab(), ids, grad)):.1f} ms", flush=True)

    # (c) sorted-scatter comparison (pre-sorted ids, same payload)
    order = np.argsort(ids_np.reshape(-1), kind="stable")
    ids_s = jnp.asarray(ids_np.reshape(-1)[order].astype(np.int32))

    def mk_c(k):
        def f(sl, ids_, v):
            s = jnp.float32(0)
            for _ in range(k):
                sl = sl.at[ids_].add(v.astype(jnp.float32) * (1.0 + s * 0),
                                     indices_are_sorted=True)
                s = s + sl[0, 0]
            return s, sl
        return f
    print("pre-sorted scatter: "
          f"{slope_donate(mk_c, (fresh_slab(), ids_s, vals_bf16)):.1f} ms",
          flush=True)


if __name__ == "__main__":
    main()

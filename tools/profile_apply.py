"""Bisect the DLRM dense-variant sparse apply (66 ms measured in the step
phase split): how much is the unavoidable SGD scatter, how much is glue
(grad assembly / broadcast / concat / cast)?

Usage: python tools/profile_apply.py
"""

import jax
import jax.numpy as jnp
import numpy as np

import _profcommon as pc
from _profcommon import slope_donate

CAP_SIZES = pc.CAP_SIZES
B = 65536
N = 26
W = 128


def main():
    rng = np.random.default_rng(0)
    rows_total = sum(CAP_SIZES)
    offs = np.concatenate([[0], np.cumsum(CAP_SIZES)[:-1]]).astype(np.int64)
    ids_np = np.zeros((N, B), np.int64)
    for i, s in enumerate(CAP_SIZES):
        u = rng.random(B)
        ids_np[i] = np.minimum((u ** 3 * s).astype(np.int64), s - 1) + offs[i]
    ids = jnp.asarray(ids_np.reshape(-1).astype(np.int32))  # [N*B]

    def fresh_slab():  # each phase donates (and so deletes) its slab
        return jnp.zeros((rows_total, W), jnp.float32) + 0.5

    vals_bf16 = jnp.zeros((N * B, W), jnp.bfloat16) + 1e-3

    # (a) raw scatter, fp32 updates
    def mk_a(k):
        def f(sl, ids_, v):
            s = jnp.float32(0)
            for _ in range(k):
                sl = sl.at[ids_].add(v.astype(jnp.float32) * (1.0 + s * 0))
                s = s + sl[0, 0]
            return s, sl
        return f
    print(f"raw SGD scatter ({N*B} rows): "
          f"{slope_donate(mk_a, (fresh_slab(), ids, vals_bf16)):.1f} ms", flush=True)

    # (b) scatter from per-feature grad slices [N, B, W] bf16 with the
    # backward's broadcast/transpose/concat glue in front
    grad = jnp.zeros((B, N * W), jnp.bfloat16) + 1e-3  # mp_grad row layout

    def mk_b(k):
        def f(sl, ids_, g):
            s = jnp.float32(0)
            for _ in range(k):
                gsl = g.reshape(1, B, N, W).transpose(0, 2, 1, 3)
                vals = gsl.reshape(-1, W).astype(jnp.float32)
                sl = sl.at[ids_].add(vals * (1.0 + s * 0))
                s = s + sl[0, 0]
            return s, sl
        return f
    print("scatter + transpose/cast glue: "
          f"{slope_donate(mk_b, (fresh_slab(), ids, grad)):.1f} ms", flush=True)

    # (c) sorted-scatter comparison (pre-sorted ids, same payload)
    order = np.argsort(ids_np.reshape(-1), kind="stable")
    ids_s = jnp.asarray(ids_np.reshape(-1)[order].astype(np.int32))

    def mk_c(k):
        def f(sl, ids_, v):
            s = jnp.float32(0)
            for _ in range(k):
                sl = sl.at[ids_].add(v.astype(jnp.float32) * (1.0 + s * 0),
                                     indices_are_sorted=True)
                s = s + sl[0, 0]
            return s, sl
        return f
    print("pre-sorted scatter: "
          f"{slope_donate(mk_c, (fresh_slab(), ids_s, vals_bf16)):.1f} ms",
          flush=True)


if __name__ == "__main__":
    pc.ensure_backend()  # probe-first: a stalled tunnel must not hang us
    main()

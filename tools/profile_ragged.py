"""Phase profile of the multi-hot ragged DLRM step (VERDICT r3 Weak #2).

Times each phase of the ragged path at the bench's exact shapes
(batch 16384, 26 features, hotness 1..30 mean 15.5, capped Criteo-Kaggle
vocabs, fp32 params / bf16 compute) with the readback-forced in-jit
repetition-slope methodology from docs/perf_tpu.md. All large buffers are
jit *arguments* (a captured constant would re-upload GBs per compile
through the device tunnel).

Usage: python tools/profile_ragged.py [phase ...]
"""

import sys

import jax
import jax.numpy as jnp
import numpy as np

import _profcommon as pc
from _profcommon import readback, slope, slope_donate

CAP_SIZES = pc.CAP_SIZES
B = 16384
N = 26
HOT_MEAN = 15
W = 128


def main(phases):
    rng = np.random.default_rng(0)
    rows_total = sum(CAP_SIZES)
    print(f"slab rows={rows_total} ({rows_total*W*4/1e9:.1f} GB fp32)",
          flush=True)

    draws = []
    for s in CAP_SIZES:
        hots = rng.integers(1, 2 * HOT_MEAN + 1, size=B)
        splits = np.zeros(B + 1, np.int64)
        np.cumsum(hots, out=splits[1:])
        draws.append((s, splits))
    cap = max(int(sp[-1]) for _, sp in draws)
    print(f"cap={cap} total_rows={N*cap}", flush=True)

    vals_np = np.zeros((N, cap), np.int32)
    lens_np = np.zeros((N, B), np.int32)
    offs = np.zeros(N, np.int64)
    o = 0
    for i, (s, splits) in enumerate(draws):
        nnz = int(splits[-1])
        u = rng.random(nnz)
        vals_np[i, :nnz] = np.minimum((u ** 3 * s).astype(np.int64), s - 1)
        lens_np[i] = np.diff(splits)
        offs[i] = o
        o += s

    dev_lens = jnp.asarray(lens_np)
    grows = jnp.asarray(vals_np) + jnp.asarray(
        offs.astype(np.int32))[:, None]  # [N, cap] global rows
    need_slab = {"gather", "opt_scatter", "opt_scatter_sorted", None}
    slab = (jnp.zeros((rows_total, W), jnp.float32) + 0.5
            if (not phases or set(phases) & need_slab) else None)

    def seg_ss(lens):
        zero = jnp.zeros((N, 1), lens.dtype)
        splits = jnp.concatenate([zero, jnp.cumsum(lens, axis=1)], axis=1)
        return jax.vmap(lambda sp: jnp.searchsorted(
            sp, jnp.arange(cap, dtype=sp.dtype), side="right") - 1)(splits)

    def want(p):
        return not phases or p in phases

    if want("seg_ss"):
        def mk(k):
            def f(lens):
                s = jnp.int32(0)
                for _ in range(k):
                    seg = seg_ss(lens)
                    s = s + seg[0, 0] + seg[-1, -1]
                    lens = lens + (s - s)
                return s
            return f
        print(f"seg searchsorted: {slope(mk, (dev_lens,)):.1f} ms",
              flush=True)

    if want("gather"):
        def mk(k):
            def f(sl, ids):
                s = jnp.float32(0)
                for _ in range(k):
                    g = jnp.take(sl, ids.reshape(-1), axis=0, mode="clip")
                    s = s + g[0, 0] + g[-1, -1]
                    ids = ids + jnp.int32(s - s)
                return s
            return f
        print(f"fwd gather ({N*cap} rows): {slope(mk, (slab, grows)):.1f} ms",
              flush=True)

    if want("combine_sc") or want("combine_cs") or want("bwd_take"):
        seg = seg_ss(dev_lens)
        sidx = (jnp.arange(N)[:, None] * (B + 1) + seg)
        gath = jnp.zeros((N, cap, W), jnp.float32) + 0.5
        zero = jnp.zeros((N, 1), dev_lens.dtype)
        splits = jnp.concatenate(
            [zero, jnp.cumsum(dev_lens, axis=1)], axis=1).astype(jnp.int32)

    if want("combine_sc"):
        def mk(k):
            def f(g, si):
                s = jnp.float32(0)
                for _ in range(k):
                    buf = jnp.zeros((N * (B + 1), W), g.dtype)
                    buf = buf.at[si.reshape(-1)].add(
                        g.reshape(-1, W), indices_are_sorted=True)
                    red = buf.reshape(N, B + 1, W)[:, :B, :]
                    s = s + red[0, 0, 0] + red[-1, -1, -1]
                    g = g + (s - s)
                return s
            return f
        print(f"combine scatter-add fp32: {slope(mk, (gath, sidx)):.1f} ms",
              flush=True)

    if want("combine_cs"):
        def mk(k):
            def f(g, sp):
                s = jnp.float32(0)
                for _ in range(k):
                    pref = jnp.cumsum(g, axis=1)  # [N, cap, W]
                    pz = jnp.concatenate(
                        [jnp.zeros((N, 1, W), pref.dtype), pref], axis=1)
                    hi = jnp.take_along_axis(pz, sp[:, 1:, None], axis=1)
                    lo = jnp.take_along_axis(pz, sp[:, :-1, None], axis=1)
                    red = hi - lo
                    s = s + red[0, 0, 0] + red[-1, -1, -1]
                    g = g + (s - s)
                return s
            return f
        print(f"combine cumsum-prefix fp32: {slope(mk, (gath, splits)):.1f} "
              "ms", flush=True)

    if want("bwd_take"):
        grad = jnp.zeros((N, B, W), jnp.bfloat16) + 0.25

        def mk(k):
            def f(g, si):
                s = jnp.float32(0)
                for _ in range(k):
                    gpad = jnp.concatenate(
                        [g, jnp.zeros((N, 1, W), g.dtype)], axis=1)
                    vals = jnp.take(gpad.reshape(-1, W), si.reshape(-1),
                                    axis=0)
                    s = s + vals[0, 0].astype(jnp.float32)
                    g = g + (s - s).astype(g.dtype)
                return s
            return f
        print(f"bwd grad take bf16: {slope(mk, (grad, sidx)):.1f} ms",
              flush=True)

    if want("opt_scatter"):
        upd = jnp.zeros((N * cap, W), jnp.float32) + 1e-4

        def mk(k):
            def f(sl, ids, u):
                s = jnp.float32(0)
                for _ in range(k):
                    sl = sl.at[ids.reshape(-1)].add(u)
                    s = s + sl[0, 0]
                return s, sl
            return f
        print(f"opt scatter ({N*cap} rows, unsorted): "
              f"{slope_donate(mk, (slab, grows, upd)):.1f} ms", flush=True)

    if want("opt_scatter_sorted"):
        sflat = jnp.asarray(np.sort(np.asarray(grows).reshape(-1)))
        upd = jnp.zeros((N * cap, W), jnp.float32) + 1e-4

        def mk(k):
            def f(sl, ids, u):
                s = jnp.float32(0)
                for _ in range(k):
                    sl = sl.at[ids].add(u, indices_are_sorted=True)
                    s = s + sl[0, 0]
                return s, sl
            return f
        print("opt scatter sorted: "
              f"{slope_donate(mk, (slab, sflat, upd)):.1f} ms", flush=True)


if __name__ == "__main__":
    pc.ensure_backend()  # probe-first: a stalled tunnel must not hang us
    main(sys.argv[1:])

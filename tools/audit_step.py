#!/usr/bin/env python
"""Audit the hybrid train step's SPMD invariants on a CPU mesh.

Builds reference DistributedEmbedding configurations (dense, ragged,
row-sliced — the same shapes the tier-1 tests pin), traces the hybrid
train step abstractly on an N-virtual-device CPU mesh, and prints each
:class:`~distributed_embeddings_tpu.analysis.AuditReport`: the collective
census checked against the 2-forward + 1-backward all-to-all contract,
dtype/host-interop/donation audits, and recompile hazards. Nothing
executes on any backend — ``jax.make_jaxpr`` + ``jit(...).lower()`` only.

    python tools/audit_step.py --strict          # make verify's gate
    python tools/audit_step.py --json report.json --config ragged

Exit codes: 0 clean; 1 violations found (only with ``--strict``);
2 usable-environment failure.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _force_cpu(devices: int) -> None:
    """Must run before the first jax import: the auditor is a pure static
    tool and must never touch (or wait on) an accelerator backend."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={devices}")
    # an inherited DETPU_OBS=1 / DETPU_TELEMETRY=1 would flip the audited
    # step to an instrumented variant; audit the shapes explicitly instead
    os.environ.pop("DETPU_OBS", None)
    os.environ.pop("DETPU_TELEMETRY", None)


def build_case(name: str, world: int, batch: int):
    """One reference configuration: ``(de, cat_inputs, batch_tree,
    dense_params, loss_fn)`` with abstract (ShapeDtypeStruct) inputs."""
    import jax
    import jax.numpy as jnp

    from distributed_embeddings_tpu.ops.embedding_lookup import Ragged
    from distributed_embeddings_tpu.parallel import DistributedEmbedding

    def loss_fn(dp, emb_outs, b):
        n, y = b
        x = jnp.concatenate([e.reshape(e.shape[0], -1) for e in emb_outs],
                            axis=1)
        return jnp.mean((x @ dp["w"] + n @ dp["v"] - y) ** 2)

    if name == "dense":
        configs = [{"input_dim": 20 + 6 * i, "output_dim": 4,
                    "combiner": ["sum", None, "mean"][i % 3]}
                   for i in range(10)]
        de = DistributedEmbedding(configs, world_size=world)
        cats = []
        for cfg in configs:
            hot = 1 if cfg["combiner"] is None else 3
            shape = (batch,) if hot == 1 else (batch, hot)
            cats.append(jax.ShapeDtypeStruct(shape, jnp.int32))
    elif name == "ragged":
        configs = [{"input_dim": 40 + 7 * i, "output_dim": 8,
                    "combiner": "sum" if i % 2 else "mean"}
                   for i in range(8)]
        de = DistributedEmbedding(configs, world_size=world)
        local_b = batch // max(world, 1)
        cap = local_b * 4
        cats = [Ragged(values=jax.ShapeDtypeStruct((world * cap,),
                                                   jnp.int32),
                       row_splits=jax.ShapeDtypeStruct(
                           (world * (local_b + 1),), jnp.int32))
                for _ in configs]
    elif name == "row_sliced":
        configs = [
            {"input_dim": 100, "output_dim": 8, "combiner": None},
            {"input_dim": 30, "output_dim": 8, "combiner": "sum"},
            {"input_dim": 100, "output_dim": 8, "combiner": "mean"},
            {"input_dim": 40, "output_dim": 8, "combiner": None},
            {"input_dim": 26, "output_dim": 8, "combiner": "sum"},
            {"input_dim": 100, "output_dim": 4, "combiner": "sum"},
            {"input_dim": 22, "output_dim": 8, "combiner": None},
            {"input_dim": 24, "output_dim": 8, "combiner": None},
        ]
        # the 100-row tables split into 4 row-range slices
        de = DistributedEmbedding(configs, world_size=world,
                                  row_slice=100 * 8 // 4 + 1)
        cats = []
        for cfg in configs:
            hot = 1 if cfg["combiner"] is None else 3
            shape = (batch,) if hot == 1 else (batch, hot)
            cats.append(jax.ShapeDtypeStruct(shape, jnp.int32))
    else:
        raise ValueError(f"unknown config {name!r}")

    cols = sum(int(c["output_dim"]) for c in configs)
    dense_params = {"w": jax.ShapeDtypeStruct((cols, 1), jnp.float32),
                    "v": jax.ShapeDtypeStruct((3, 1), jnp.float32)}
    batch_tree = (jax.ShapeDtypeStruct((batch, 3), jnp.float32),
                  jax.ShapeDtypeStruct((batch, 1), jnp.float32))
    return de, cats, batch_tree, dense_params, loss_fn


def audit_case(name: str, world: int, batch: int, with_metrics: bool,
               with_telemetry: bool = False):
    import jax
    import numpy as np
    import optax
    from jax.sharding import Mesh

    from distributed_embeddings_tpu.analysis import audit_train_step
    from distributed_embeddings_tpu.parallel import SparseAdagrad

    de, cats, batch_tree, dense_params, loss_fn = build_case(
        name, world, batch)
    mesh = None
    if world > 1:
        devs = jax.devices()  # backend-ok: JAX_PLATFORMS=cpu forced above
        if len(devs) < world:
            raise RuntimeError(
                f"host platform exposes {len(devs)} devices < {world}")
        mesh = Mesh(np.array(devs[:world]), ("data",))
    suffix = "/telemetry" if with_telemetry else ""
    return audit_train_step(
        de, loss_fn, optax.sgd(0.5), SparseAdagrad(), cats, batch_tree,
        mesh=mesh, lr_schedule=0.3, with_metrics=with_metrics,
        telemetry=with_telemetry,
        dense_params=dense_params, label=f"{name}/world{world}{suffix}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--config", choices=("dense", "ragged", "row_sliced",
                                         "all"), default="all")
    ap.add_argument("--world", type=int, default=8,
                    help="mesh positions (CPU virtual devices; default 8)")
    ap.add_argument("--batch", type=int, default=16, help="global batch")
    ap.add_argument("--with-metrics", action="store_true",
                    help="audit the instrumented (DETPU_OBS) step variant")
    ap.add_argument("--with-telemetry", action="store_true",
                    help="audit ONLY the telemetry-instrumented "
                         "(DETPU_TELEMETRY) step variants")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 on any violation (the make verify gate)")
    ap.add_argument("--json", metavar="PATH",
                    help="dump the full reports as JSON (- for stdout)")
    args = ap.parse_args(argv)

    _force_cpu(max(args.world, 1))
    sys.path.insert(0, REPO)

    names = (["dense", "ragged", "row_sliced"] if args.config == "all"
             else [args.config])
    # (config, telemetry?) cases: --with-telemetry audits only the
    # telemetry-instrumented variants; the default "all" sweep ALSO
    # audits one telemetry case so the verify gate covers the carried
    # state (same census, donation grown by the telemetry leaves)
    cases = [(n, args.with_telemetry) for n in names]
    if args.config == "all" and not args.with_telemetry:
        cases.append(("dense", True))
    reports = []
    failed = 0
    for name, with_tel in cases:
        try:
            rep = audit_case(name, args.world, args.batch,
                             args.with_metrics, with_telemetry=with_tel)
        except Exception as e:  # noqa: BLE001 - report, then fail the gate
            print(f"audit_step: {name}: audit errored: {e}",
                  file=sys.stderr)
            return 2
        reports.append(rep)
        census = rep.a2a_census()
        status = "OK" if rep.ok else "FAIL"
        print(f"audit_step: {rep.label}: {status} a2a={census} "
              f"psum={rep.collective_counts.get('psum', 0)} "
              f"donated={rep.donation.get('donated')}/"
              f"{rep.donation.get('expected')}")
        for v in rep.violations:
            print(f"audit_step:   violation: {v}", file=sys.stderr)
            failed += 1
    if args.json:
        payload = json.dumps([r.to_json() for r in reports], indent=2)
        if args.json == "-":
            print(payload)
        else:
            with open(args.json, "w", encoding="utf-8") as fh:
                fh.write(payload + "\n")
    if failed and args.strict:
        print(f"audit_step: {failed} violation(s)", file=sys.stderr)
        return 1
    if not failed:
        print(f"audit_step: OK ({len(reports)} configuration(s) hold the "
              "SPMD communication contract)")
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Audit the hybrid train step's SPMD invariants on a CPU mesh.

Builds reference DistributedEmbedding configurations (dense, ragged,
row-sliced — the same shapes the tier-1 tests pin), traces the hybrid
train step abstractly on an N-virtual-device CPU mesh, and prints each
:class:`~distributed_embeddings_tpu.analysis.AuditReport`: the collective
census checked against the 2-forward + 1-backward all-to-all contract,
dtype/host-interop/donation audits, and recompile hazards. Nothing
executes on any backend — ``jax.make_jaxpr`` + ``jit(...).lower()`` only.

    python tools/audit_step.py --strict          # make verify's gate
    python tools/audit_step.py --json report.json --config ragged

Exit codes: 0 clean; 1 violations found (only with ``--strict``);
2 usable-environment failure.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# reference configurations + CPU pinning live in tools/_profcommon.py
# (shared with tools/hlo_audit.py and the profile tools so the audited
# shapes AND the audited program — which env knobs are stripped — cannot
# drift); build_case re-exported because tests and docs address it here
try:  # imported as the tools.audit_step module (tests, tooling)
    from tools._profcommon import build_case, cpu_mesh, force_cpu  # noqa: F401
except ImportError:  # run as a script: tools/ itself is sys.path[0]
    from _profcommon import build_case, cpu_mesh, force_cpu  # noqa: F401


def audit_case(name: str, world: int, batch: int, with_metrics: bool,
               with_telemetry: bool = False):
    import optax

    from distributed_embeddings_tpu.analysis import audit_train_step
    from distributed_embeddings_tpu.parallel import SparseAdagrad

    de, cats, batch_tree, dense_params, loss_fn = build_case(
        name, world, batch)
    mesh = cpu_mesh(world)
    suffix = "/telemetry" if with_telemetry else ""
    return audit_train_step(
        de, loss_fn, optax.sgd(0.5), SparseAdagrad(), cats, batch_tree,
        mesh=mesh, lr_schedule=0.3, with_metrics=with_metrics,
        telemetry=with_telemetry,
        dense_params=dense_params, label=f"{name}/world{world}{suffix}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--config", choices=("dense", "pipelined", "ragged",
                                         "row_sliced", "all"),
                    default="all")
    ap.add_argument("--world", type=int, default=8,
                    help="mesh positions (CPU virtual devices; default 8)")
    ap.add_argument("--batch", type=int, default=16, help="global batch")
    ap.add_argument("--with-metrics", action="store_true",
                    help="audit the instrumented (DETPU_OBS) step variant")
    ap.add_argument("--with-telemetry", action="store_true",
                    help="audit ONLY the telemetry-instrumented "
                         "(DETPU_TELEMETRY) step variants")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 on any violation (the make verify gate)")
    ap.add_argument("--json", metavar="PATH",
                    help="dump the full reports as JSON (- for stdout)")
    args = ap.parse_args(argv)

    force_cpu(max(args.world, 1))
    sys.path.insert(0, REPO)

    # "pipelined" audits the K=2 microbatched step: the a2a census must
    # show exactly K of each exchange role while the psum count stays
    # K-invariant (expected_collectives reads de.schedule.microbatches)
    names = (["dense", "pipelined", "ragged", "row_sliced"]
             if args.config == "all" else [args.config])
    # (config, telemetry?) cases: --with-telemetry audits only the
    # telemetry-instrumented variants; the default "all" sweep ALSO
    # audits one telemetry case so the verify gate covers the carried
    # state (same census, donation grown by the telemetry leaves)
    cases = [(n, args.with_telemetry) for n in names]
    if args.config == "all" and not args.with_telemetry:
        cases.append(("dense", True))
    reports = []
    failed = 0
    for name, with_tel in cases:
        if name == "pipelined" and (args.batch // max(args.world, 1)) % 2:
            print(f"audit_step: pipelined: skipped — per-device batch "
                  f"{args.batch // max(args.world, 1)} does not divide "
                  "into the case's K=2 microbatches (pick --batch "
                  "divisible by 2*world)")
            continue
        try:
            rep = audit_case(name, args.world, args.batch,
                             args.with_metrics, with_telemetry=with_tel)
        except Exception as e:  # noqa: BLE001 - report, then fail the gate
            print(f"audit_step: {name}: audit errored: {e}",
                  file=sys.stderr)
            return 2
        reports.append(rep)
        census = rep.a2a_census()
        status = "OK" if rep.ok else "FAIL"
        print(f"audit_step: {rep.label}: {status} a2a={census} "
              f"psum={rep.collective_counts.get('psum', 0)} "
              f"donated={rep.donation.get('donated')}/"
              f"{rep.donation.get('expected')}")
        for v in rep.violations:
            print(f"audit_step:   violation: {v}", file=sys.stderr)
            failed += 1
    if args.json:
        payload = json.dumps([r.to_json() for r in reports], indent=2)
        if args.json == "-":
            print(payload)
        else:
            with open(args.json, "w", encoding="utf-8") as fh:
                fh.write(payload + "\n")
    if failed and args.strict:
        print(f"audit_step: {failed} violation(s)", file=sys.stderr)
        return 1
    if not failed:
        print(f"audit_step: OK ({len(reports)} configuration(s) hold the "
              "SPMD communication contract)")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Shared model factory for the process-isolation drill + tests.

Both sides of the process boundary — the trainer child in
``tools/check_isolation.py`` / ``bench.py`` and the spawned
:mod:`~distributed_embeddings_tpu.parallel.supervisor` serving worker —
must build the SAME model at the SAME world size (the snapshot payload
is the flattened parameter leaves; slab shapes carry the world dim), so
the build lives in ONE importable place and the worker references it by
name: ``"tools.isolation_common:worker_factory"`` (spawn children
inherit ``sys.path``, so anything the parent can import, the worker
can).
"""

from __future__ import annotations

#: static-table vocab sizes; with the streaming table appended the model
#: has 8 tables — one per mesh position at the drill's world=8 (the
#: planner refuses fewer tables than mesh positions)
SIZES = [2000, 1500, 1000, 800, 600, 500, 400]


def build(world: int = 8, seed: int = 0):
    """The isolation-drill model: three static tables + one streaming
    table (so snapshots carry BOTH param and streaming leaves across
    the boundary), a sigmoid head, and a synthetic request template.

    Returns a dict with everything either side needs; the worker
    factory below narrows it to the ``ServingWorker`` surface."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh

    from distributed_embeddings_tpu.parallel import (
        DistributedEmbedding, ServeConfig, SparseSGD, StreamingConfig,
        init_hybrid_state, init_streaming)
    from distributed_embeddings_tpu.parallel import serving as sv

    mesh = (Mesh(np.array(jax.devices()[:world]),  # backend-ok: drill child
                 ("data",))
            if world > 1 else None)
    sizes = list(SIZES)
    configs = ([{"input_dim": v, "output_dim": 8} for v in sizes]
               + [{"input_dim": 64 + 16, "output_dim": 8,
                   "streaming": {"capacity": 64, "buckets": 16}}])
    de = DistributedEmbedding(configs, world_size=world)
    scfg = StreamingConfig(admit_min_count=2, evict_margin=1, depth=2,
                           buckets=256)
    tx = optax.sgd(0.05)
    state = init_hybrid_state(
        de, SparseSGD(),
        {"w": jnp.ones((8 * len(configs) + 2, 1), jnp.float32) * 0.01},
        tx, jax.random.key(seed), mesh=mesh)
    sstate = init_streaming(de, scfg, mesh=mesh)

    def pred_fn(dp, outs, batch):
        x = jnp.concatenate(list(outs) + [batch], axis=-1)
        return jax.nn.sigmoid(x @ dp["w"])[:, 0]

    cfg = ServeConfig(max_batch=32, max_wait_ms=5, deadline_ms=4000,
                      max_queue=256, shed_frac=0.5)
    rng = np.random.default_rng(seed)
    tmpl = sv.synthetic_request(rng, sizes + [1], 2, numerical=2)
    return {
        "de": de, "pred_fn": pred_fn, "state": state, "mesh": mesh,
        "config": cfg, "streaming": (scfg, sstate),
        "template": (tmpl.cats, tmpl.batch),
        "sizes": sizes, "scfg": scfg,
    }


def worker_factory(world: int = 8, seed: int = 0):
    """The :class:`~distributed_embeddings_tpu.parallel.supervisor
    .Supervisor` factory entry point (``"tools.isolation_common:
    worker_factory"``): the worker's own model, ladder config, and
    warmup template."""
    built = build(world=world, seed=seed)
    return {k: built[k] for k in
            ("de", "pred_fn", "state", "mesh", "config", "streaming",
             "template")}


def make_request_fn(seed: int = 1):
    """Seeded Zipfian request factory over the drill model's tables
    (one external-id streaming input appended, like the serving drill);
    deterministic per index via a per-request generator."""
    import numpy as np

    from distributed_embeddings_tpu.parallel import serving as sv

    sizes = list(SIZES)

    def make_request(i: int):
        rng = np.random.default_rng(seed * 1_000_003 + i)
        n = int(rng.integers(1, 5))
        req = sv.synthetic_request(rng, sizes, n, numerical=2)
        req.cats = list(req.cats) + [np.asarray(
            rng.integers(0, 1 << 30, size=(n,)), np.int32)]
        req.priority = 1 if i % 8 == 0 else 0
        return req

    return make_request

#!/usr/bin/env python
"""Verify gate for cross-process request tracing (run by ``make
check-tracing`` inside ``make verify``) — the outage-spanning trace
drill.

CPU end-to-end, one child on the 8-virtual-device mesh that spawns a
REAL world-8 serving worker through the supervisor and drives a
wall-clock request stream with a 4x burst into a ``DETPU_FAULT=die@150``
crash. The gate asserts the tracing plane's four contracts:

1. **one trace crosses the restart**: a retained supervisor-side trace
   carries the outage — submit, ``outage`` mark, typed ``Unavailable``
   — AND the ``worker_restarted`` / ``served_after_restart`` marks the
   reborn worker's first Served appends (``restart_crossed`` attr);
2. **the span partition is exact**: every retained trace's stage spans
   sum to its ``latency_ms`` within ``SPAN_SUM_TOL_MS`` (1e-6 ms) —
   including the five-stage partitions the worker pickled back over the
   supervisor socket;
3. **the federated scrape is one view**: the supervisor's ``/metrics``
   endpoint (scraped over HTTP mid-drill, after the restart) serves the
   WORKER's families (``detpu_serve_*`` — arrived on pong heartbeats,
   sketch-merged across the dead and reborn incarnations) next to its
   own (``detpu_supervisor_*``);
4. **tracing is free at steady state**: the reborn worker reports 0
   steady-state recompiles, and the Chrome export round-trips through
   the jax-free ``utils/traceparse.py`` reader.

Exit 0 when the drill passes; 1 with a readable reason otherwise.
"""

from __future__ import annotations

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

WORLD = 8
QPS = 120.0       # normal arrival rate against the worker
BURST_AT = 1      # second of the stream the 4x spike hits
BURST_X = 4.0
DIE_AT = 150      # global request ordinal that os._exit()s the worker

_CHILD = """
import sys, time, urllib.request
sys.path.insert(0, {repo!r})
import jax
jax.config.update('jax_platforms', 'cpu')
from distributed_embeddings_tpu.parallel import (
    RealtimeDriver, Served, Supervisor, SuperviseConfig, Unavailable)
from distributed_embeddings_tpu.utils import mplane, reqtrace, traceparse
from tools import isolation_common as ic

world = {world}
sup = Supervisor(
    "tools.isolation_common:worker_factory", {{"world": world}},
    config=SuperviseConfig(
        env={{"DETPU_FAULT": "die@{die_at}", "DETPU_METRICS_PORT": ""}}))
sup.start()
built = ic.build(world=world)
sup.install_snapshot(built["state"], built["streaming"][1],
                     version=1, train_step=0)
exp = mplane.start_http_exporter(sup.metrics, port=0)

driver = RealtimeDriver(sup, ic.make_request_fn(seed=3), {qps},
                        duration_s=None, burst_positions={{{burst_at}}},
                        burst_x={burst_x}, drain_s=60.0)
driver.start()
deadline = time.monotonic() + 180
while time.monotonic() < deadline:
    blk = sup.stats(sync=False)["supervisor"]
    if blk["worker_alive"] and blk["restarts"] >= 1:
        break
    time.sleep(0.2)
driver.stop()
driver.join(timeout=120)

# post-restart tail: the reborn worker serves, its first Served stamps
# the restart-crossing marks onto the outage trace
tail = RealtimeDriver(sup, ic.make_request_fn(seed=4), 60.0,
                      duration_s=1.0, burst_positions=(), drain_s=60.0)
tail.start()
tail.join(timeout=120)

# give the reborn worker's federation document a pong cycle to arrive,
# then scrape the merged /metrics view over HTTP mid-load
time.sleep(1.2)
scrape = urllib.request.urlopen(exp.url(), timeout=30).read().decode()

st = sup.stats(sync=True)
snap = sup.traces.snapshot()
export_path = {export!r}
sup.traces.export(export_path)
exp.stop()
sup.close()

results = driver.results() + tail.results()
served = sum(1 for r in results if isinstance(r, Served))
unavailable = sum(1 for r in results if isinstance(r, Unavailable))

crossing = [t for t in snap if t["attrs"].get("restart_crossed")]
cross_marks = 0
for t in crossing:
    names = {{e["name"] for e in t["events"]}}
    if {{"worker_restarted", "served_after_restart"}} <= names:
        cross_marks += 1
span_bad = sum(
    1 for t in snap
    if abs(sum(t["stages_ms"].values()) - t["latency_ms"])
    > reqtrace.SPAN_SUM_TOL_MS)
served_full = sum(1 for t in snap if t["outcome"] == "served"
                  and len(t["stages_ms"]) == 5)

parsed = traceparse.parse_request_traces(export_path)
parse_ok = int(len(parsed) == len(snap) and any(
    p["attrs"].get("restart_crossed") for p in parsed))
fed_ok = int("detpu_serve_latency_ms" in scrape
             and "detpu_serve_total" in scrape
             and "detpu_supervisor_restarts" in scrape
             and "detpu_supervisor_worker_alive 1" in scrape)
blk = st["supervisor"]
exemplars = blk["p99_exemplars"]

print("FINAL",
      "SERVED", served, "UNAVAILABLE", unavailable,
      "CRASHES", blk["crashes"], "RESTARTS", blk["restarts"],
      "RETAINED", len(snap),
      "RING_OK", int(len(snap) <= sup.traces.stats()["capacity"]),
      "CROSS", len(crossing), "CROSS_MARKS", cross_marks,
      "SPAN_BAD", span_bad, "SERVED_FULL", served_full,
      "PARSE_OK", parse_ok, "FED_OK", fed_ok,
      "EXEMPLARS", len(exemplars),
      "STEADY", st.get("steady_state_recompiles", -1),
      flush=True)
"""


def main() -> int:
    import tempfile

    with tempfile.TemporaryDirectory(prefix="detpu_tracing_") as td:
        export = os.path.join(td, "req.trace.json.gz")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        for k in ("DETPU_FAULT", "DETPU_OBS", "DETPU_TELEMETRY",
                  "DETPU_METRICS_PORT", "DETPU_TRACE",
                  "DETPU_TRACE_RING", "DETPU_TRACE_SAMPLE",
                  "DETPU_TRACE_SEED"):
            env.pop(k, None)
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={WORLD}")
        code = _CHILD.format(repo=REPO, world=WORLD, qps=QPS,
                             burst_at=BURST_AT, burst_x=BURST_X,
                             die_at=DIE_AT, export=export)
        p = subprocess.run([sys.executable, "-c", code], env=env,
                           capture_output=True, text=True, timeout=900)
        if p.returncode != 0:
            return _fail([f"drill child failed rc={p.returncode}: "
                          f"{(p.stderr or p.stdout).strip()[-1500:]}"])
        got = None
        for line in reversed(p.stdout.strip().splitlines()):
            if line.startswith("FINAL"):
                parts = line.split()
                got = dict(zip(parts[1::2], parts[2::2]))
                break
        if got is None:
            return _fail(["drill child printed no FINAL line: "
                          f"{p.stdout.strip()[-800:]}"])
        errors = []
        if int(got.get("CRASHES", 0)) < 1 or int(got.get("RESTARTS", 0)) < 1:
            errors.append(
                f"no crash/restart (crashes={got.get('CRASHES')}, "
                f"restarts={got.get('RESTARTS')}) — die@{DIE_AT} never "
                "fired; the drill tested nothing")
        if int(got.get("UNAVAILABLE", 0)) < 1:
            errors.append("no Unavailable responses — the outage window "
                          "was empty, nothing for a trace to cross")
        if int(got.get("RETAINED", 0)) < 1 or got.get("RING_OK") != "1":
            errors.append(
                f"trace ring bad (retained={got.get('RETAINED')}, "
                f"ring_ok={got.get('RING_OK')}) — retention is either "
                "empty or unbounded")
        if int(got.get("CROSS", 0)) < 1 or int(got.get("CROSS_MARKS", 0)) < 1:
            errors.append(
                f"no restart-crossing trace (crossed={got.get('CROSS')}, "
                f"with_marks={got.get('CROSS_MARKS')}) — the outage trace "
                "must carry worker_restarted + served_after_restart marks "
                "from the reborn worker's first Served")
        if got.get("SPAN_BAD") != "0":
            errors.append(
                f"{got.get('SPAN_BAD')} trace(s) break the span "
                "partition: sum(stages_ms) != latency_ms within "
                f"{_tol()} ms")
        if int(got.get("SERVED_FULL", 0)) < 1:
            errors.append(
                "no retained served trace carries the full five-stage "
                "partition — the worker's spans did not survive the "
                "supervisor boundary")
        if got.get("PARSE_OK") != "1":
            errors.append(
                "Chrome export did not round-trip through "
                "utils/traceparse.parse_request_traces (count mismatch "
                "or the restart-crossing trace vanished)")
        if got.get("FED_OK") != "1":
            errors.append(
                "federated scrape incomplete — /metrics must serve the "
                "worker's detpu_serve_* families (pong-federated) next "
                "to the supervisor's own")
        if int(got.get("EXEMPLARS", 0)) < 1:
            errors.append("stats() returned no p99 exemplars despite a "
                          "retained tail")
        if got.get("STEADY") != "0":
            errors.append(
                f"{got.get('STEADY')} steady-state recompile(s) — "
                "tracing must not perturb the serve ladder's compile "
                "cache")
        if errors:
            return _fail(errors)
        print(f"check_tracing: OK (die@{DIE_AT} mid-burst: "
              f"{got['CROSS']} trace(s) crossed the restart with marks, "
              f"{got['RETAINED']} retained / ring bounded, 0 span-sum "
              f"violations ({got['SERVED_FULL']} five-stage served "
              f"partitions over the boundary), federated scrape serves "
              f"worker + supervisor families, {got['EXEMPLARS']} p99 "
              f"exemplars, export round-trips, {got['STEADY']} "
              "steady-state recompiles)")
        return 0


def _tol() -> float:
    from distributed_embeddings_tpu.utils import reqtrace
    return reqtrace.SPAN_SUM_TOL_MS


def _fail(errors) -> int:
    for e in errors:
        print(f"check_tracing: {e}", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())

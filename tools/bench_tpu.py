#!/usr/bin/env python
"""One-command real-TPU bench capture (``make bench-tpu``).

ROADMAP standing note ii: the pipelined step, the serving runtime and
the online train-and-serve loop are all landed and gated, but their
remaining debt is a REAL-TPU capture — the tunnel has been down since
BENCH_r05 died rc=124 to a pre-probe backend touch. This wrapper makes
the capture a single command that can be retried cheaply until the
tunnel returns:

1. probe the backend FIRST (``utils.runtime.probe_backend`` — a watched
   subprocess with a hard timeout, the r5 fix), and fail FAST with the
   probe's verdict when the tunnel is down or the backend resolves to
   anything but TPU (a CPU-proxy record must never be mistaken for the
   real capture — the BENCH_r04-vs-r05 confusion trap);
2. only then run the full ``bench.py`` (headline + pipelined + serving
   + online sections) in a child, stream its progress through, and
   write the final JSON record — backend stamped by bench itself — to
   ``--out``.

Exit codes: 0 captured; 2 probe failed (tunnel verdict printed);
3 backend is not TPU; 1 bench child failed or produced no record.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="BENCH_tpu.json",
                    help="where to write the captured record "
                         "(default: %(default)s)")
    ap.add_argument("--probe-timeout-s", type=float,
                    default=float(os.environ.get("DETPU_PROBE_TIMEOUT_S",
                                                 "120")),
                    help="hard deadline for the first backend touch")
    ap.add_argument("--smoke", action="store_true",
                    help="run the bench in smoke shapes (wrapper "
                         "self-test; the record is NOT a capture)")
    args = ap.parse_args(argv)

    from distributed_embeddings_tpu.utils.runtime import probe_backend

    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        # an inherited CPU pin would make the probe "succeed" on the
        # wrong backend — surface the real cause instead
        print("bench_tpu: JAX_PLATFORMS=cpu is set in this environment "
              "— unset it to reach the TPU", file=sys.stderr)
        return 3

    probe = probe_backend(timeout_s=args.probe_timeout_s)
    print(f"bench_tpu: probe verdict: {json.dumps(probe.to_json())}")
    if not probe.ok:
        print(f"bench_tpu: backend probe FAILED after "
              f"{probe.elapsed_s:.1f}s: {probe.error} — the tunnel is "
              "down; nothing was benched", file=sys.stderr)
        return 2
    if probe.platform != "tpu":
        print(f"bench_tpu: backend resolved to {probe.platform!r} "
              f"({probe.device_count} device(s)), not TPU — refusing to "
              "capture a CPU-proxy record under a TPU filename "
              "(run plain `make bench` for a proxy run)", file=sys.stderr)
        return 3

    env = dict(os.environ)
    if args.smoke:
        env["DETPU_BENCH_SMOKE"] = "1"
    print(f"bench_tpu: TPU backend up ({probe.device_count} device(s), "
          f"probe {probe.elapsed_s:.1f}s) — running the full bench")
    proc = subprocess.Popen([sys.executable, os.path.join(REPO, "bench.py")],
                            env=env, cwd=REPO, text=True,
                            stdout=subprocess.PIPE)
    record = None
    assert proc.stdout is not None
    for line in proc.stdout:  # stream progress, remember the JSON line
        sys.stdout.write(line)
        sys.stdout.flush()
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if isinstance(obj, dict) and "metric" in obj:
            record = obj
    rc = proc.wait()
    if rc != 0 or record is None:
        print(f"bench_tpu: bench child rc={rc}, "
              f"record={'present' if record else 'MISSING'} — no capture "
              "written", file=sys.stderr)
        return 1
    if record.get("backend") != "tpu":
        # the child re-probes; a tunnel that died between the probe and
        # the run yields a record stamped with the wrong backend
        print(f"bench_tpu: record is stamped backend="
              f"{record.get('backend')!r} — the tunnel dropped mid-run; "
              "not writing a TPU capture", file=sys.stderr)
        return 1
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(record, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"bench_tpu: captured {args.out} "
          f"(backend=tpu, devices={record.get('device_count')}, "
          f"headline {record.get('value')} {record.get('unit')})")
    return 0


if __name__ == "__main__":
    sys.exit(main())

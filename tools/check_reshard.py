#!/usr/bin/env python
"""Verify gate for elastic topology resume (run by ``make verify``).

CPU end-to-end mesh-shrink drill:

1. spawn a child training driver on an 8-virtual-device mesh
   (``run_resilient`` with periodic checkpointing) with a
   ``DETPU_FAULT=preempt@<step>`` self-SIGTERM — it must checkpoint and
   exit with ``PREEMPT_EXIT_CODE``;
2. relaunch the SAME model on a 4-virtual-device mesh — auto-resume must
   detect the plan/world mismatch, re-shard the checkpoint in place
   (``on_mismatch='reshard'``, the driver default), log the degradation
   into its metrics sidecar, and run to completion (exit 0, no manual
   intervention);
3. a second 4-device resume from a pristine copy of the same preempted
   checkpoint must end CRC-identical to the first — the re-shard point
   starts a deterministic trajectory;
4. an uninterrupted 8-device reference run must end with the same
   per-table LOGICAL state (within float tolerance: world size changes
   the reduction order, never the math).

Exit 0 when the drill passes; 1 with a readable reason otherwise.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

STEPS = 8
PREEMPT_AT = 4

_CHILD = """
import sys
sys.path.insert(0, {repo!r})
import jax, optax, numpy as np, jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from distributed_embeddings_tpu.parallel import (
    DistributedEmbedding, SparseSGD, init_hybrid_state,
    make_hybrid_train_step, run_resilient)
from distributed_embeddings_tpu.utils import obs

world = {world}
configs = [{{"input_dim": 24 + 3 * i, "output_dim": 8}} for i in range(8)]
de = DistributedEmbedding(configs, world_size=world,
                          strategy="memory_balanced")
mesh = Mesh(np.array(jax.devices()[:world]), ("data",)) \
    if world > 1 else None
emb_opt = SparseSGD()
tx = optax.sgd(0.1)
dp = {{"w": jnp.ones((8 * 8, 1), jnp.float32) * 0.05}}
state = init_hybrid_state(de, emb_opt, dp, tx, jax.random.key(0),
                          mesh=mesh)
def loss_fn(dparams, outs, batch):
    x = jnp.concatenate([o.reshape(o.shape[0], -1) for o in outs], axis=1)
    return jnp.mean((x @ dparams["w"] - batch) ** 2)
B = 16
def data(start):
    for i in range(start, {steps}):
        rng = np.random.default_rng(900 + i)
        cats = [jnp.asarray(rng.integers(0, c["input_dim"], B), jnp.int32)
                for c in configs]
        y = jnp.asarray(rng.normal(size=(B, 1)), jnp.float32)
        if mesh is not None:
            y = jax.device_put(y, NamedSharding(mesh, P("data")))
        yield cats, y
step = make_hybrid_train_step(de, loss_fn, tx, emb_opt, mesh=mesh,
                              lr_schedule=0.2, with_metrics=False,
                              nan_guard=True)
logger = obs.MetricsLogger({metrics!r}) if {metrics!r} else None
r = run_resilient(step, state, data, de=de, checkpoint_dir={ckpt!r},
                  checkpoint_every_steps=2, resume=True,
                  emb_optimizer=emb_opt, dense_tx=tx, mesh=mesh,
                  metrics_logger=logger, exit_on_preempt=True)
tables = de.get_weights(r.state.emb_params)
np.savez({tables_out!r}, **{{f"t{{i}}": t for i, t in enumerate(tables)}})
print("FINAL", r.step, flush=True)
"""


def _run(world, ckpt, tables_out, metrics="", preempt_at=None,
         timeout=600):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + f" --xla_force_host_platform_device_count={world}")
    if preempt_at is not None:
        env["DETPU_FAULT"] = f"preempt@{preempt_at}"
    else:
        env.pop("DETPU_FAULT", None)
    # the drill TESTS the elastic default; an operator's exported
    # DETPU_ON_MISMATCH=error must not make make verify fail spuriously
    env["DETPU_ON_MISMATCH"] = "reshard"
    code = _CHILD.format(repo=REPO, world=world, steps=STEPS, ckpt=ckpt,
                         tables_out=tables_out, metrics=metrics)
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                          text=True, timeout=timeout)
    return proc.returncode, proc.stdout


def _final_crcs(ckpt):
    with open(os.path.join(ckpt, "meta.json"), encoding="utf-8") as f:
        return json.load(f)["files"]


def _fail(errors) -> int:
    for e in errors:
        print(f"check_reshard: {e}", file=sys.stderr)
    return 1


def main() -> int:
    from distributed_embeddings_tpu.parallel.resilient import (
        PREEMPT_EXIT_CODE)

    errors = []
    with tempfile.TemporaryDirectory(prefix="detpu_reshard_") as tmp:
        ckpt = os.path.join(tmp, "ck")
        metrics = os.path.join(tmp, "metrics.jsonl")

        # 1: preempt an 8-device run mid-flight
        rc, out = _run(8, ckpt, os.path.join(tmp, "t_pre.npz"),
                       preempt_at=PREEMPT_AT)
        if rc != PREEMPT_EXIT_CODE:
            return _fail([f"preempted 8-dev child exited rc={rc} (want "
                          f"{PREEMPT_EXIT_CODE}): {out.strip()[-500:]}"])

        # pristine copy for the determinism resume (3)
        ckpt2 = os.path.join(tmp, "ck2")
        shutil.copytree(ckpt, ckpt2)

        # 2: auto-resume the SAME model on 4 devices — must re-shard and
        # complete without manual intervention
        rc, out = _run(4, ckpt, os.path.join(tmp, "t4.npz"),
                       metrics=metrics)
        if rc != 0:
            return _fail([f"4-dev resume failed rc={rc}: "
                          f"{out.strip()[-800:]}"])
        if f"FINAL {STEPS}" not in out:
            errors.append(f"4-dev resume did not reach step {STEPS}: "
                          f"{out.splitlines()[-3:]}")
        recs = []
        if os.path.exists(metrics):
            with open(metrics, encoding="utf-8") as f:
                recs = [json.loads(line) for line in f if line.strip()]
        reshard_recs = [r for r in recs
                        if r.get("section") == "checkpoint_reshard"]
        if not reshard_recs:
            errors.append("no checkpoint_reshard degradation record in "
                          "the resumed run's metrics sidecar")
        else:
            diff = reshard_recs[0].get("diff", {})
            if diff.get("world_size") != [8, 4]:
                errors.append(f"degradation record has wrong world sizes: "
                              f"{diff.get('world_size')}")
            if not diff.get("per_rank_byte_deltas"):
                errors.append("degradation record missing per-rank byte "
                              "deltas")

        # 3: resuming the pristine copy again must be deterministic —
        # CRC-identical final checkpoint
        rc, out = _run(4, ckpt2, os.path.join(tmp, "t4b.npz"))
        if rc != 0:
            return _fail([f"second 4-dev resume failed rc={rc}: "
                          f"{out.strip()[-500:]}"])
        if _final_crcs(ckpt) != _final_crcs(ckpt2):
            errors.append("two resumes onto the same shrunken mesh wrote "
                          "different final checkpoints — the re-shard "
                          "point is not deterministic")

        # 4: uninterrupted 8-device reference — same logical state
        rc, out = _run(8, os.path.join(tmp, "ref"),
                       os.path.join(tmp, "t8.npz"))
        if rc != 0:
            return _fail([f"8-dev reference failed rc={rc}: "
                          f"{out.strip()[-500:]}"])
        got = np.load(os.path.join(tmp, "t4.npz"))
        ref = np.load(os.path.join(tmp, "t8.npz"))
        for k in ref.files:
            if not np.allclose(ref[k], got[k], rtol=1e-5, atol=1e-6):
                errors.append(
                    f"logical table {k} differs between the shrunken "
                    "resume and the uninterrupted 8-dev run (max delta "
                    f"{np.abs(ref[k] - got[k]).max():.3e})")
    if errors:
        return _fail(errors)
    print("check_reshard: OK (preempted 8-dev run exited "
          f"{PREEMPT_EXIT_CODE}, auto-resumed on 4 devices via in-place "
          "re-shard, degradation logged, resume deterministic, final "
          "logical state matches the uninterrupted run)")
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Pipelined-vs-serialized hybrid-step A/B on the virtual-device CPU mesh.

The pipelined step (``parallel/schedule.py::pipelined_schedule``) exists
to hide the all-to-all exchanges under dense compute — a property that
only *exists* at world > 1 (the single-chip headline step has no
exchange to hide, so bench.py's world-1 sections are structurally unable
to show it). This tool is the bench's ``pipeline`` section body, run in
a CHILD process so the 8-virtual-device CPU mesh never touches the bench
process's accelerator tunnel:

* builds the capped Criteo-Kaggle DLRM shapes on a world-8 CPU mesh,
* times the SAME model/config under the serialized baseline schedule and
  under ``pipelined_schedule(K)`` (``DETPU_MICROBATCH_BENCH``, default
  2),
* rides the steady-state recompile gate (a pipelined step that retraces
  per step poisons its own numbers exactly like any other section),
* emits one JSON record: both ms/step figures, the speedup fraction, and
  the recompile count.

Honesty note (docs/perf_tpu.md Round 14): on THIS proxy the exchange is
a shared-memory copy priced at ~nothing and the CPU thunk scheduler does
not overlap across chains, so the wall-clock delta is noise-level; the
certified wins are the schedule auditor's modeled fraction (0.99 → 0.00)
and critical path. The record exists so the REAL capture lands in the
same slot the moment the TPU tunnel returns — and so compare_bench can
ratchet the pipelined variant's numbers like any other section.

    python tools/pipeline_bench.py --json -          # the bench child
    python tools/pipeline_bench.py --iters 4 --batch 4096

Exit codes: 0 ok; 2 usable-environment failure.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

try:  # imported as tools.pipeline_bench (tests)
    from tools._profcommon import (CAP_SIZES, cpu_mesh,  # noqa: F401
                                   force_cpu)
except ImportError:  # run as a script: tools/ itself is sys.path[0]
    from _profcommon import CAP_SIZES, cpu_mesh, force_cpu  # noqa: F401

WORLD = 8
#: vocab cap of the A/B tables — the capped Criteo-Kaggle vector shrunk
#: so a world-8 CPU host holds both variants' slabs comfortably; the
#: shapes stay 26-table/dim-128 DLRM-like so the exchange layout (and
#: therefore what the pipeline hides) matches the headline's structure
TABLE_CAP = 200_000


def run_ab(batch: int, iters: int, k: int):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from distributed_embeddings_tpu.models.dlrm import (DLRMConfig,
                                                        DLRMDense,
                                                        bce_with_logits)
    from distributed_embeddings_tpu.parallel import (
        DistributedEmbedding, SparseSGD, init_hybrid_state,
        make_hybrid_train_step)
    from distributed_embeddings_tpu.parallel.schedule import (
        pipelined_schedule)
    from distributed_embeddings_tpu.utils import obs, power_law_ids

    sizes = [min(s, TABLE_CAP) for s in CAP_SIZES]
    mesh = cpu_mesh(WORLD)
    cfg = DLRMConfig(table_sizes=sizes, embedding_dim=128,
                     num_numerical_features=13,
                     bottom_mlp_dims=(512, 256, 128),
                     top_mlp_dims=(1024, 1024, 512, 256, 1),
                     compute_dtype=jnp.bfloat16)
    obs.install_compile_listener()

    def time_variant(schedule):
        de = DistributedEmbedding(cfg.embedding_configs(),
                                  world_size=WORLD,
                                  compute_dtype=jnp.bfloat16,
                                  schedule=schedule)
        dense = DLRMDense(cfg)
        emb_opt = SparseSGD()
        tx = optax.sgd(0.005)
        rng = np.random.default_rng(0)
        cats = [jnp.asarray(power_law_ids(rng, s, (batch,)), jnp.int32)
                for s in sizes]
        num = jnp.asarray(rng.normal(size=(batch, 13)), jnp.float32)
        labels = jnp.asarray(rng.integers(0, 2, size=(batch, 1)),
                             jnp.float32)
        dense_params = dense.init(
            jax.random.key(0), num[:2],
            [jnp.zeros((2, 128), jnp.float32) for _ in sizes])

        def loss_fn(dp, emb_outs, b):
            n, y = b
            return bce_with_logits(dense.apply(dp, n, emb_outs), y)

        state = init_hybrid_state(de, emb_opt, dense_params, tx,
                                  jax.random.key(1), mesh=mesh,
                                  dtype=jnp.bfloat16)
        step = make_hybrid_train_step(de, loss_fn, tx, emb_opt, mesh=mesh,
                                      lr_schedule=0.005,
                                      with_metrics=False, nan_guard=False,
                                      telemetry=False)
        loss = None
        for _ in range(2):
            loss, state = step(state, cats, (num, labels))
        float(jnp.asarray(loss).reshape(-1)[-1])
        compiles0 = obs.counters().get("recompiles", 0)
        t0 = time.perf_counter()
        for _ in range(iters):
            loss, state = step(state, cats, (num, labels))
        float(jnp.asarray(loss).reshape(-1)[-1])
        dt = (time.perf_counter() - t0) / iters
        recompiles = obs.counters().get("recompiles", 0) - compiles0
        del state
        return dt, recompiles

    ser_s, ser_rc = time_variant(None)
    pip_s, pip_rc = time_variant(pipelined_schedule(k))
    return {
        "world": WORLD,
        "batch": batch,
        "iters": iters,
        "microbatches": k,
        "table_cap": TABLE_CAP,
        "serialized_ms_per_step": round(ser_s * 1e3, 3),
        "pipelined_ms_per_step": round(pip_s * 1e3, 3),
        "serialized_samples_per_sec": round(batch / ser_s, 1),
        "pipeline_samples_per_sec": round(batch / pip_s, 1),
        "pipeline_speedup_frac": round(ser_s / pip_s - 1.0, 4),
        "steady_state_recompiles": ser_rc + pip_rc,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--batch", type=int, default=8192,
                    help="global batch of the A/B (default 8192)")
    ap.add_argument("--iters", type=int, default=8,
                    help="timed steps per variant (default 8)")
    ap.add_argument("--microbatches", type=int, default=None,
                    help="pipeline K (default DETPU_MICROBATCH_BENCH)")
    ap.add_argument("--json", metavar="PATH",
                    help="write the record as JSON (- for stdout)")
    args = ap.parse_args(argv)

    force_cpu(WORLD)
    sys.path.insert(0, REPO)
    from distributed_embeddings_tpu.utils import envvars

    k = (args.microbatches if args.microbatches is not None
         else envvars.get_int("DETPU_MICROBATCH_BENCH"))
    if k < 1:
        print(f"pipeline_bench: microbatches must be >= 1, got {k}",
              file=sys.stderr)
        return 2
    try:
        rec = run_ab(args.batch, args.iters, k)
    except Exception as e:  # noqa: BLE001 - child tool: readable env-fail
        print(f"pipeline_bench: errored: {e}", file=sys.stderr)
        return 2
    print(f"pipeline_bench: world={rec['world']} K={k} "
          f"serialized {rec['serialized_ms_per_step']:.1f} ms/step vs "
          f"pipelined {rec['pipelined_ms_per_step']:.1f} ms/step "
          f"({rec['pipeline_speedup_frac']:+.1%}); recompiles="
          f"{rec['steady_state_recompiles']}")
    if args.json:
        payload = json.dumps(rec, indent=2)
        if args.json == "-":
            print(payload)
        else:
            with open(args.json, "w", encoding="utf-8") as fh:
                fh.write(payload + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())

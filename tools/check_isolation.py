#!/usr/bin/env python
"""Verify gate for process-isolated serving (run by ``make
check-isolation`` inside ``make verify``) — the crash-containment drill.

CPU end-to-end, one trainer child on the 8-virtual-device mesh which
spawns a REAL world-8 serving worker through the supervisor:

1. the child first runs the serving-free reference: the same training
   stream under ``run_resilient`` with checkpointing, no supervisor at
   all — its final checkpoint CRCs are the trajectory contract;
2. then the supervised run: a spawned worker (``DETPU_FAULT=die@<rid>``
   injected into the WORKER's env only) serves a wall-clock open-loop
   request stream — with a 4x burst in second 1 — while the trainer
   trains and publishes snapshots through shared memory. Request
   ``<rid>`` executes ``os._exit`` inside the worker mid-burst: the
   supervisor must detect the death, answer every in-flight and
   outage-window request with typed ``Unavailable`` (zero lost, zero
   hung futures), dump a CRC-stamped blackbox naming
   ``serve_worker_crash``, and restart the worker within the backoff
   budget while training never blocks;
3. after the restart a fresh tail of normal-rate requests must be
   served IN FULL from the reborn worker (which re-ingested the latest
   snapshot from shm before answering) at ZERO steady-state recompiles,
   request rids must be conserved across the whole drill (every
   submission answered exactly once, rids contiguous), and the
   supervised run's final checkpoints must be CRC-IDENTICAL to the
   serving-free reference — the worker's death never touched training.

Exit 0 when the drill passes; 1 with a readable reason otherwise.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

WORLD = 8
STEPS = 24
QPS = 120.0       # normal arrival rate against the worker
BURST_AT = 1      # second of the stream the 4x spike hits
BURST_X = 4.0
DIE_AT = 150      # global request ordinal that os._exit()s the worker

_CHILD = """
import sys, time
sys.path.insert(0, {repo!r})
import numpy as np, jax, jax.numpy as jnp, optax
jax.config.update('jax_platforms', 'cpu')
from distributed_embeddings_tpu.parallel import (
    RealtimeDriver, Served, SparseSGD, SuperviseConfig, Supervisor,
    Unavailable, make_hybrid_train_step, run_resilient)
from distributed_embeddings_tpu.utils import mplane
from tools import isolation_common as ic

world = {world}
STEPS = {steps}

def loss_fn(dp, outs, batch):
    return sum(batch[:, i % 2].mean() * jnp.mean(o)
               for i, o in enumerate(outs)) * jnp.mean(dp["w"])

def make_batch(i):
    rng = np.random.default_rng(900 + i)
    cats = [np.asarray(rng.integers(0, s, 8), np.int32)
            for s in ic.SIZES]
    cats.append(np.asarray(rng.integers(i, i + 6, 8) * 7 + 10_000_000,
                           np.int32))
    return cats, np.asarray(rng.normal(size=(8, 2)), np.float32)

def data(start):
    for i in range(start, STEPS):
        yield make_batch(i)

def train_once(ckpt, pump=None):
    built = ic.build(world=world)
    step = make_hybrid_train_step(built["de"], loss_fn, optax.sgd(0.05),
                                  SparseSGD(), mesh=built["mesh"],
                                  with_metrics=True, nan_guard=True,
                                  dynamic=built["scfg"])
    return built, run_resilient(
        step, built["state"], data, de=built["de"], checkpoint_dir=ckpt,
        checkpoint_every_steps=4, resume=True, emb_optimizer=SparseSGD(),
        dense_tx=optax.sgd(0.05),
        streaming_state=built["streaming"][1], metrics_interval=0,
        on_step_aux=pump)

# ---- 1. serving-free reference -------------------------------------
_, ref = train_once({ref_ckpt!r})
assert ref.step == STEPS and not ref.preempted

# ---- 2. supervised run: train + publish + serve + crash ------------
blackbox = {blackbox!r}
sup = Supervisor(
    "tools.isolation_common:worker_factory", {{"world": world}},
    config=SuperviseConfig(
        blackbox_path=blackbox,
        env={{"DETPU_FAULT": "die@{die_at}", "DETPU_METRICS_PORT": ""}}))
sup.start()
built0 = ic.build(world=world)
sup.install_snapshot(built0["state"], built0["streaming"][1],
                     version=1, train_step=0)
driver = RealtimeDriver(sup, ic.make_request_fn(seed=3), {qps},
                        duration_s=None, burst_positions={{{burst_at}}},
                        burst_x={burst_x}, drain_s=60.0)
driver.start()

vc = {{"v": 1}}
def pump(cur, loss, metrics, state_now, telem, stream):
    if cur % 2 == 0:
        vc["v"] += 1
        sup.install_snapshot(state_now, stream, version=vc["v"],
                             train_step=cur)
    sup.note_train_step(cur)

t_train0 = time.monotonic()
_, res = train_once({sup_ckpt!r}, pump=pump)
train_s = time.monotonic() - t_train0
assert res.step == STEPS and not res.preempted

# training must not have blocked on the worker: wait out the crash +
# restart AFTER training returned (the driver keeps the stream open)
deadline = time.monotonic() + 180
while time.monotonic() < deadline:
    blk = sup.stats(sync=False)["supervisor"]
    if blk["worker_alive"] and blk["restarts"] >= 1:
        break
    time.sleep(0.2)
driver.stop()
driver.join(timeout=120)
results = driver.results()

# ---- 3. post-restart tail: fully served from the reborn worker -----
sup.install_snapshot(res.state, res.streaming, version=vc["v"] + 1,
                     train_step=res.step)
tail_drv = RealtimeDriver(sup, ic.make_request_fn(seed=4), 60.0,
                          duration_s=1.0, burst_positions=(),
                          drain_s=60.0)
tail_drv.start()
tail_drv.join(timeout=120)
tail = tail_drv.results()

st = sup.stats(sync=True)
blk = st["supervisor"]
sup.close()

allr = results + tail
rids = sorted(r.rid for r in allr)
unavailable = [r for r in allr if isinstance(r, Unavailable)]
tail_served = sum(1 for r in tail if isinstance(r, Served))
bb_trigger, bb_crc_ok = "", 0
try:
    payload = mplane.verify_blackbox(blackbox)
    bb_trigger, bb_crc_ok = payload.get("trigger", ""), 1
except Exception as e:
    bb_trigger = "ERROR:" + type(e).__name__

print("FINAL",
      "SUBMITTED", driver.submitted + tail_drv.submitted,
      "ANSWERED", len(allr),
      "CONSERVED", int(rids == list(range(len(rids)))),
      "UNAVAILABLE", len(unavailable),
      "UNAVAIL_TYPED", int(all(r.status == "unavailable"
                               and r.reason for r in unavailable)),
      "CRASHES", blk["crashes"], "RESTARTS", blk["restarts"],
      "BUDGET_OK", int(not blk["restart_budget_exhausted"]),
      "ALIVE", int(blk["worker_alive"]),
      "TAIL_SERVED", tail_served, "TAIL_TOTAL", len(tail),
      "STEADY", st.get("steady_state_recompiles", -1),
      "RTFS_MS", round(blk.get("restart_to_first_served_ms") or -1, 1),
      "TRAIN_S", round(train_s, 2),
      "BB_CRC", bb_crc_ok, "BB_TRIGGER", bb_trigger,
      flush=True)
"""


def _final_crcs(ckpt):
    with open(os.path.join(ckpt, "meta.json"), encoding="utf-8") as f:
        return json.load(f)["files"]


def main() -> int:
    import tempfile

    with tempfile.TemporaryDirectory(prefix="detpu_isolation_") as td:
        ref_ckpt = os.path.join(td, "ref")
        sup_ckpt = os.path.join(td, "sup")
        blackbox = os.path.join(td, "sup.blackbox.json")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        for k in ("DETPU_FAULT", "DETPU_OBS", "DETPU_TELEMETRY",
                  "DETPU_METRICS_PORT"):
            env.pop(k, None)
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={WORLD}")
        env["DETPU_CKPT_RING"] = "2"
        code = _CHILD.format(repo=REPO, world=WORLD, steps=STEPS,
                             qps=QPS, burst_at=BURST_AT, burst_x=BURST_X,
                             die_at=DIE_AT, ref_ckpt=ref_ckpt,
                             sup_ckpt=sup_ckpt, blackbox=blackbox)
        p = subprocess.run([sys.executable, "-c", code], env=env,
                           capture_output=True, text=True, timeout=900)
        if p.returncode != 0:
            return _fail([f"drill child failed rc={p.returncode}: "
                          f"{(p.stderr or p.stdout).strip()[-1500:]}"])
        got = None
        for line in reversed(p.stdout.strip().splitlines()):
            if line.startswith("FINAL"):
                parts = line.split()
                got = dict(zip(parts[1::2], parts[2::2]))
                break
        if got is None:
            return _fail(["drill child printed no FINAL line: "
                          f"{p.stdout.strip()[-800:]}"])
        errors = []
        if int(got.get("CRASHES", 0)) < 1:
            errors.append(
                f"the worker never crashed (die@{DIE_AT} never fired) — "
                "the drill tested nothing")
        if int(got.get("RESTARTS", 0)) < 1 or got.get("BUDGET_OK") != "1":
            errors.append(
                f"restart failed (restarts={got.get('RESTARTS')}, "
                f"budget_ok={got.get('BUDGET_OK')}) — the supervisor "
                "must restart a crashed worker within the backoff budget")
        if got.get("ALIVE") != "1":
            errors.append("the worker is not alive at drill end — no "
                          "recovery from the crash")
        if got.get("CONSERVED") != "1":
            errors.append(
                "request conservation broken: rids are not contiguous — "
                "a future was lost, duplicated, or left hanging across "
                "the crash")
        if int(got.get("UNAVAILABLE", 0)) < 1:
            errors.append(
                "no Unavailable responses — either the outage window was "
                "empty (kill did not land mid-stream) or outage requests "
                "were silently dropped")
        if got.get("UNAVAIL_TYPED") != "1":
            errors.append("an outage response was not a typed "
                          "Unavailable with a reason")
        if got.get("TAIL_SERVED") != got.get("TAIL_TOTAL", "-1"):
            errors.append(
                f"post-restart tail served {got.get('TAIL_SERVED')}/"
                f"{got.get('TAIL_TOTAL')} — the reborn worker did not "
                "resume full service")
        if got.get("STEADY") != "0":
            errors.append(
                f"{got.get('STEADY')} steady-state recompile(s) in the "
                "reborn worker — the restart retraced the serve ladder")
        if got.get("BB_CRC") != "1" or got.get("BB_TRIGGER") \
                != "serve_worker_crash":
            errors.append(
                f"blackbox bad (crc_ok={got.get('BB_CRC')}, trigger="
                f"{got.get('BB_TRIGGER')!r}) — the supervisor must dump "
                "a CRC-intact post-mortem naming serve_worker_crash on "
                "behalf of the SIGKILLed child")
        crcs, ref_crcs = _final_crcs(sup_ckpt), _final_crcs(ref_ckpt)
        if crcs != ref_crcs:
            diff = sorted(k for k in set(crcs) | set(ref_crcs)
                          if crcs.get(k) != ref_crcs.get(k))
            errors.append(
                "supervised training diverged from the serving-free "
                f"reference (checkpoint CRC mismatch in {diff}) — the "
                "worker's crash leaked into the training trajectory")
        if errors:
            return _fail(errors)
        print(f"check_isolation: OK (die@{DIE_AT} mid-burst: "
              f"{got['CRASHES']} crash / {got['RESTARTS']} restart within "
              f"budget, {got['UNAVAILABLE']} outage requests all typed "
              f"Unavailable, {got['ANSWERED']}/{got['SUBMITTED']} futures "
              f"conserved, post-restart tail {got['TAIL_SERVED']}/"
              f"{got['TAIL_TOTAL']} served at 0 steady-state recompiles, "
              f"restart-to-first-served {got['RTFS_MS']} ms, training "
              "CRC-identical to the serving-free reference, blackbox "
              "CRC-intact)")
        return 0


def _fail(errors) -> int:
    for e in errors:
        print(f"check_isolation: {e}", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())

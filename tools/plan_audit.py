#!/usr/bin/env python
"""Enforce the plan-time HBM/comms capacity contracts — before anything
is built, traced, or compiled.

The third static gate (jaxpr auditor → what we ask for; HLO census →
what XLA emits; THIS → what the plan costs before either exists): for
every shared reference configuration (``tools/_profcommon.build_case``)
**plus the real Criteo-1TB vocab vector** it prices the placement plan
with :mod:`distributed_embeddings_tpu.analysis.plan_audit` — per-rank
param+optimizer+exchange-buffer bytes, per-step all-to-all payloads,
apply-slab sizes against the measured 2.7→8.65 GB scatter cliff, padded
group-shape count — and enforces the default :class:`PlanContract`.

Strict mode additionally

* calibrates the jax-free byte model against
  ``analysis.memory.table_memory_report``'s ``eval_shape`` accounting
  (drift beyond ``--calibration-tol`` fails: the mirror broke);
* runs two seeded NEGATIVE drills — an over-HBM plan (Criteo-1TB fp32 +
  Adam on 8 ranks) and a past-cliff slab (Criteo-1TB bf16 unsliced on
  16 ranks) — and fails unless each is rejected with a violation naming
  the offending rank / slab (a gate that cannot catch a seeded
  violation is not a gate).

Nothing executes on any backend: plans are host metadata, inputs are
``ShapeDtypeStruct``s, and the only jax use is ``eval_shape`` inside the
calibration target.

    python tools/plan_audit.py --strict           # make verify's gate
    python tools/plan_audit.py --case criteo1tb --markdown
    python tools/plan_audit.py --json report.json

Exit codes: 0 clean; 1 violations / calibration drift / failed drill
(only with ``--strict``); 2 unusable environment.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

try:  # imported as tools.plan_audit (tests)
    from tools import _profcommon as pc
except ImportError:  # run as a script: tools/ itself is sys.path[0]
    import _profcommon as pc

#: (case, world, global batch, param dtype, optimizer, dp_input) —
#: the tier-1 shapes at the 8-position mesh the other static gates use,
#: plus the Criteo-1TB deployment shape (world 16, bf16, mp input: the
#: dlrm example's defaults at the north-star scale).
CASES = (
    ("dense", 8, 16, "float32", "adagrad", True),
    ("ragged", 8, 16, "float32", "adagrad", True),
    ("row_sliced", 8, 16, "float32", "adagrad", True),
    ("bigvocab", 8, 16, "float32", "sgd", True),
    ("criteo1tb", pc.CRITEO1TB_WORLD, pc.CRITEO1TB_BATCH, "bfloat16",
     "sgd", False),
)


def audit_case(name, world, batch, param_dtype, opt_name, dp_input,
               chip="v5e"):
    """Build one shared reference case and audit its plan + calibration."""
    from distributed_embeddings_tpu.analysis import (
        compare_with_memory, default_contract, memory as dmem, plan_audit)
    from distributed_embeddings_tpu.parallel import (
        SparseAdagrad, SparseAdam, SparseMomentum, SparseSGD)

    opt = {"sgd": SparseSGD, "adagrad": SparseAdagrad,
           "momentum": SparseMomentum, "adam": SparseAdam}[opt_name]()
    de, cats, _batch_tree, _dp, _loss = pc.build_case(name, world, batch)
    rep = plan_audit.audit_plan(
        de, batch, optimizer=opt, param_dtype=param_dtype,
        cat_inputs=cats, dp_input=dp_input, chip=chip,
        label=f"{name}/world{world}/{opt_name}/{param_dtype}",
        contract=default_contract(chip))
    mem = dmem.table_memory_report(de, opt, param_dtype=param_dtype)
    calib = compare_with_memory(rep, mem)
    return rep, calib


def seeded_drills():
    """The negative self-tests: each returns ``(label, violations,
    expect_substring)`` and MUST produce at least one violation whose
    text names the offending rank / slab."""
    from distributed_embeddings_tpu.analysis import (default_contract,
                                                     plan_audit)
    from distributed_embeddings_tpu.parallel.strategy import (
        DistEmbeddingStrategy)

    configs = [{"input_dim": int(s), "output_dim": pc.CRITEO1TB_DIM,
                "combiner": None} for s in pc.CRITEO_1TB_SIZES]
    # drill 1: fp32 + Adam (2 state slots) on 8 ranks — ~57 GB/rank,
    # nearly 4x over the v5e budget; must fail naming a rank
    st8 = DistEmbeddingStrategy(configs, 8, strategy="memory_balanced")
    over = plan_audit.audit_plan(
        st8, pc.CRITEO1TB_BATCH, optimizer="adam", param_dtype="float32",
        label="drill_over_hbm", contract=default_contract())
    # drill 2: bf16 on 16 ranks WITHOUT column slicing — the ~40M-row
    # tables stack into a 9.5 GB apply slab, past the measured cliff;
    # must fail naming the slab
    st16 = DistEmbeddingStrategy(configs, pc.CRITEO1TB_WORLD,
                                 strategy="comm_balanced")
    cliff = plan_audit.audit_plan(
        st16, pc.CRITEO1TB_BATCH, optimizer="sgd", param_dtype="bfloat16",
        dp_input=False, label="drill_past_cliff",
        contract=default_contract())
    return [("over_hbm", over.violations, "rank "),
            ("past_cliff", cliff.violations, "slab w")]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--case",
                    choices=("dense", "ragged", "row_sliced", "bigvocab",
                             "criteo1tb", "all"),
                    default="all")
    ap.add_argument("--chip", default="v5e",
                    help="capacity-registry chip the contracts bind to")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 on any violation, calibration drift, or "
                         "failed seeded drill (the make verify gate)")
    ap.add_argument("--calibration-tol", type=float, default=0.001,
                    help="max |drift| of the jax-free byte model vs the "
                         "eval_shape accounting (default 0.1%%)")
    ap.add_argument("--markdown", action="store_true",
                    help="print each case's per-rank budget table")
    ap.add_argument("--json", metavar="PATH",
                    help="dump the full reports as JSON (- for stdout)")
    args = ap.parse_args(argv)

    # pure-host tool: pin an inert CPU backend exactly like the other
    # static auditors (nothing is dispatched, but the jax import — for
    # eval_shape calibration — must never wait on an accelerator tunnel)
    pc.force_cpu(1)
    sys.path.insert(0, REPO)

    cases = [c for c in CASES
             if args.case in ("all", c[0])]
    failed = 0
    reports = []
    for name, world, batch, dt, opt_name, dp in cases:
        try:
            rep, calib = audit_case(name, world, batch, dt, opt_name, dp,
                                    chip=args.chip)
        except Exception as e:  # noqa: BLE001 - report, then fail the gate
            print(f"plan_audit: {name}: audit errored: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            return 2
        reports.append(rep)
        status = "OK" if rep.ok else "FAIL"
        print(f"plan_audit: {rep.label}: {status} "
              f"max_rank={rep.max_rank_bytes / 2**30:.2f}GB "
              f"a2a={rep.total_a2a_bytes_per_step / 1e6:.1f}MB/step "
              f"groups={rep.n_groups} imbalance={rep.imbalance_ratio:.2f} "
              f"calib_drift={calib['max_abs_drift']:.2e}")
        if args.markdown:
            print(rep.markdown())
        for v in rep.violations:
            print(f"plan_audit:   violation: {v}", file=sys.stderr)
            failed += 1
        if calib["max_abs_drift"] > args.calibration_tol:
            print(f"plan_audit:   CALIBRATION DRIFT {calib} — the jax-free "
                  "byte model disagrees with analysis.memory's eval_shape "
                  "accounting; one of the two mirrors broke",
                  file=sys.stderr)
            failed += 1

    # the negative self-test runs for the full sweep AND for any strict
    # invocation — a strict gate that skipped its seeded drills because
    # the case list was narrowed would no longer prove it can reject
    if args.case == "all" or args.strict:
        for label, violations, expect in seeded_drills():
            if any(expect in v for v in violations):
                print(f"plan_audit: drill {label}: correctly rejected "
                      f"({len(violations)} violation(s))")
            else:
                print(f"plan_audit: drill {label}: NOT rejected — the "
                      f"contract failed to catch a seeded violation "
                      f"(wanted {expect!r} in {violations})",
                      file=sys.stderr)
                failed += 1

    if args.json:
        payload = json.dumps([r.to_json() for r in reports], indent=2)
        if args.json == "-":
            print(payload)
        else:
            with open(args.json, "w", encoding="utf-8") as fh:
                fh.write(payload + "\n")
    if failed and args.strict:
        print(f"plan_audit: {failed} failure(s)", file=sys.stderr)
        return 1
    if not failed:
        print(f"plan_audit: OK ({len(reports)} case(s) hold their capacity "
              "contracts; byte model calibrated)")
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Verify gate for the streaming-vocab (dynamic-table) mode (run by
``make check-streaming`` inside ``make verify``) — the non-stationary-
traffic drill.

CPU end-to-end, deterministic, no backend required beyond the CPU one:

1. spawn a child training driver (one static + one streaming table, 12
   batches of drifting never-in-vocab external ids through
   ``parallel.resilient.run_resilient`` with the jit-carried slot map)
   under ``DETPU_FAULT=oovflood@3,preempt@6`` — batch 3 floods the
   stream with a burst of never-before-seen ids (the admission/bucket
   machinery must absorb it: no crash, ids served from the shared
   buckets) and at step 6 the driver self-SIGTERMs, checkpoints
   (slot map + sketch riding INSIDE the checkpoint as
   ``aux/streaming.npz``), and exits preempted;
2. re-run the same child (auto-resume): it must restore the slot-map
   state from the checkpoint and run to clean completion with real
   ADMISSIONS having happened and ZERO steady-state recompiles (three
   extra manual steps of novel ids after the run re-use the compiled
   step — slot-map churn must never retrace);
3. run the identical stream uninterrupted in a fresh directory and
   assert both final checkpoints are CRC-identical, ``aux/streaming.npz``
   included — the interrupted+resumed streaming run reproduces the
   uninterrupted trajectory (params AND slot map) bit for bit.

Exit 0 when the drill passes; 1 with a readable reason otherwise.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

STEPS = 12
FLOOD = 3    # stream position the oovflood@ drill floods
PREEMPT = 6  # step the preempt@ drill SIGTERMs at

_CHILD = """
import sys
sys.path.insert(0, {repo!r})
import jax, optax, numpy as np, jax.numpy as jnp
jax.config.update('jax_platforms', 'cpu')
from distributed_embeddings_tpu.parallel import (
    DistributedEmbedding, SparseAdagrad, StreamingConfig,
    init_hybrid_state, init_streaming, make_hybrid_train_step,
    run_resilient)
from distributed_embeddings_tpu.parallel import streaming as smod
from distributed_embeddings_tpu.utils import obs
obs.install_compile_listener()
configs = [
    {{"input_dim": 20, "output_dim": 4}},
    {{"input_dim": 32 + 8, "output_dim": 4,
      "streaming": {{"capacity": 32, "buckets": 8}}}},
]
de = DistributedEmbedding(configs, world_size=1)
cfg = StreamingConfig(admit_min_count=2, evict_margin=1,
                      depth=2, buckets=256)
emb_opt = SparseAdagrad()
tx = optax.sgd(0.05)
state = init_hybrid_state(de, emb_opt,
                          {{"w": jnp.ones((4, 1), jnp.float32)}},
                          tx, jax.random.key(0))
sstate = init_streaming(de, cfg)
def loss_fn(dp, outs, batch):
    return sum(batch[:, i].mean() * jnp.mean(o)
               for i, o in enumerate(outs)) * jnp.mean(dp["w"])
def make_batch(i):
    rng = np.random.default_rng(900 + i)
    # a slowly drifting external-id distribution: day-k ids give way to
    # day-k+1 ids, far outside any static vocab
    cats = [jnp.asarray(rng.integers(0, 20, 8), jnp.int32),
            jnp.asarray(rng.integers(i, i + 6, 8) * 7 + 10_000_000,
                        jnp.int32)]
    return cats, jnp.asarray(rng.normal(size=(8, 2)), jnp.float32)
def data(start):
    for i in range(start, {steps}):
        yield make_batch(i)
step = make_hybrid_train_step(de, loss_fn, tx, emb_opt,
                              with_metrics=True, nan_guard=True,
                              dynamic=cfg)
r = run_resilient(step, state, data, de=de, checkpoint_dir={ckpt!r},
                  checkpoint_every_steps=2, resume=True,
                  emb_optimizer=emb_opt, dense_tx=tx,
                  streaming_state=sstate, metrics_interval=0)
occ = smod.occupancy(de, r.streaming)
steady = 0
if not r.preempted:
    # steady-state recompile proof: more steps of NOVEL ids against the
    # already-compiled step — slot-map churn must not retrace
    c0 = obs.counters().get("recompiles", 0)
    st, ss = r.state, r.streaming
    for j in range(3):
        cats, b = make_batch(1000 + j)
        _, st, _, ss = step(st, cats, b, ss)
    jax.block_until_ready(jax.tree.leaves(ss))
    steady = obs.counters().get("recompiles", 0) - c0
print("FINAL", r.step, "PREEMPTED", int(r.preempted),
      "ADMITTED", int(occ["admitted"]), "EVICTED", int(occ["evicted"]),
      "BUCKET", int(occ["bucket_ids"]), "STEADY", steady, flush=True)
"""


def _run_child(ckpt, fault=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    for k in ("DETPU_FAULT", "DETPU_OBS", "DETPU_TELEMETRY"):
        env.pop(k, None)
    env["DETPU_CKPT_RING"] = "2"
    if fault:
        env["DETPU_FAULT"] = fault
    code = _CHILD.format(repo=REPO, ckpt=ckpt, steps=STEPS)
    return subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=600)


def _final_crcs(ckpt):
    with open(os.path.join(ckpt, "meta.json"), encoding="utf-8") as f:
        return json.load(f)["files"]


def _parse(stdout):
    for line in reversed(stdout.strip().splitlines()):
        if line.startswith("FINAL"):
            parts = line.split()
            return dict(zip(parts[::2], parts[1::2]))
    return None


def main() -> int:
    errors = []
    with tempfile.TemporaryDirectory(prefix="detpu_streaming_") as tmp:
        ckpt = os.path.join(tmp, "ck")

        # 1: flood + preempt — must checkpoint (slot map inside) and exit
        p = _run_child(ckpt, fault=f"oovflood@{FLOOD},preempt@{PREEMPT}")
        if p.returncode != 0:
            return _fail([f"preempt child failed rc={p.returncode}: "
                          f"{(p.stderr or p.stdout).strip()[-800:]}"])
        got = _parse(p.stdout)
        if not got or got.get("PREEMPTED") != "1":
            errors.append(f"child did not report a preemption: {got}")
        if not os.path.isfile(os.path.join(ckpt, "aux", "streaming.npz")):
            errors.append("preemption checkpoint carries no "
                          "aux/streaming.npz slot-map snapshot")

        # 2: resume — clean completion, admissions happened, 0 recompiles
        p2 = _run_child(ckpt, fault=f"oovflood@{FLOOD}")
        if p2.returncode != 0:
            return _fail([f"resume child failed rc={p2.returncode}: "
                          f"{(p2.stderr or p2.stdout).strip()[-800:]}"])
        got2 = _parse(p2.stdout)
        if not got2 or got2.get("FINAL") != str(STEPS):
            errors.append(f"resume child ended at {got2} — want FINAL "
                          f"{STEPS}")
        elif got2.get("PREEMPTED") != "0":
            errors.append("resume child reported preempted")
        elif int(got2.get("ADMITTED", 0)) <= 0:
            errors.append("no slot admissions happened across the run — "
                          "the frequency gate never fired")
        elif int(got2.get("STEADY", 1)) != 0:
            errors.append(
                f"{got2['STEADY']} steady-state recompile(s): slot-map "
                "churn retraces the compiled step")
        if errors:
            return _fail(errors)

        # 3: CRC-identity vs the uninterrupted run (aux included)
        ref = os.path.join(tmp, "ref")
        p3 = _run_child(ref, fault=f"oovflood@{FLOOD}")
        if p3.returncode != 0:
            return _fail([f"reference child failed rc={p3.returncode}: "
                          f"{(p3.stderr or p3.stdout).strip()[-800:]}"])
        crcs, ref_crcs = _final_crcs(ckpt), _final_crcs(ref)
        if crcs != ref_crcs:
            diff = sorted(k for k in set(crcs) | set(ref_crcs)
                          if crcs.get(k) != ref_crcs.get(k))
            errors.append(
                "final checkpoints differ between the interrupted+resumed "
                f"run and the uninterrupted run (files {diff}) — the "
                "streaming trajectory (params and/or slot map) is not "
                "preemption-deterministic")
    if errors:
        return _fail(errors)
    print(f"check_streaming: OK (oovflood@{FLOOD} absorbed into the "
          f"shared buckets, admissions happened, preempt@{PREEMPT} -> "
          f"resume reached step {STEPS} with 0 steady-state recompiles "
          "and a final checkpoint CRC-identical — aux/streaming.npz "
          "included — to the uninterrupted run)")
    return 0


def _fail(errors) -> int:
    for e in errors:
        print(f"check_streaming: {e}", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())

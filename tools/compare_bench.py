#!/usr/bin/env python
"""Diff two BENCH records and fail on throughput regressions.

The repo accumulates one BENCH JSON per round (``BENCH_r*.json``), but
nothing ever *compared* them — a 15% throughput slide between rounds was
only caught by a human reading numbers. This tool is the regression gate:

    python tools/compare_bench.py OLD.json NEW.json [--threshold 0.10]

Accepts either the driver's wrapper format (``{"rc": ..., "parsed":
{...}}``) or bench.py's raw one-line JSON. Exit codes:

* 0 — every comparable metric within the threshold;
* 1 — at least one regression beyond the threshold (throughput metrics
  dropping, or ms-per-iter metrics rising, by more than ``--threshold``,
  default 10%), a nonzero steady-state recompile count, a per-phase
  HLO pass-count regression / contract violation in the candidate's
  ``phase_budget`` census (:func:`check_phase_budget`), a
  ``plan_audit`` capacity failure — contract violation or a
  predicted-vs-measured byte drift beyond ±15%
  (:func:`check_plan_audit`) — a ``schedule`` overlap regression:
  ``serialized_collective_fraction`` or modeled critical-path bytes
  growing versus the baseline (:func:`check_schedule`) — or a MEASURED
  overlap regression: the trace-parsed ``phase_profile`` section's
  measured serialized fraction growing, or its measured-vs-modeled
  classification disagreeing (:func:`check_phase_profile`) — both the
  schedule and phase-profile gates run twice, once for the serialized
  headline and once for the pipelined twins (``schedule_pipelined`` /
  ``phase_profile_pipelined``), so the K-microbatch step's won overlap
  ratchets independently — or a ``serving`` regression: fixed-QPS p95
  latency growing beyond 10%, a recompiling padded-batch ladder, or
  the section missing versus the baseline (:func:`check_serving`);
* 2 — unusable inputs (missing file, no parseable payload).

Metrics present in only one record are reported but never fail the gate
(rounds legitimately add sections). Records from DIFFERENT backends or
device counts (the top-level probe stamp, falling back to the PR 2
``env`` block) are REFUSED outright — the BENCH_r04-vs-r05 CPU/TPU
confusion trap; ``--allow-env-mismatch`` downgrades that to a loud
warning when cross-backend reading is deliberate. Wired as ``make
bench-diff`` (``OLD=... NEW=... make bench-diff``).

No jax import: this must run anywhere, instantly.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, Optional

# higher is better
THROUGHPUT_KEYS = (
    "value",
    "fp32_samples_per_sec",
    "bf16_samples_per_sec",
    "bf16_params_samples_per_sec",
    "bf16_per_dispatch_samples_per_sec",
    "uncapped_bf16_samples_per_sec",
    "multihot_ragged_samples_per_sec",
    "criteo1tb_shard_samples_per_sec",
    "input_pipeline_samples_per_sec",
    "nanguard_samples_per_sec",
    "resilient_samples_per_sec",
    "sentinel_samples_per_sec",
    "telemetry_samples_per_sec",
    "streaming_samples_per_sec",
    "pipeline_samples_per_sec",
    "online_train_samples_per_sec",
)
# lower is better (ms-per-iter timings and byte budgets: a >threshold
# rise in per-step peak HBM is a regression exactly like a slower step)
MS_KEYS = (
    "tiny_zoo_adagrad_ms_per_iter",
    "tiny_zoo_sgd_ms_per_iter",
    "tiny_zoo_adagrad_bf16_ms_per_iter",
    "criteo1tb_v5e16_step_ms",
    "peak_hbm_mb",
)
ENV_KEYS = ("backend", "device_count", "jax_version", "smoke")
# per-phase HLO pass kinds gated round over round (keep in sync with
# analysis/hlo_census.py GATED_KINDS; convert/transpose counts are
# reported in the record but move with benign layout choices)
PHASE_GATE_KINDS = ("gather", "scatter", "sort", "cumsum", "all_to_all")


def load_bench(path: str) -> Optional[Dict[str, Any]]:
    """Extract the bench payload from either the driver wrapper or a raw
    bench.py JSON line (last parseable JSON object wins for line files)."""
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except OSError as e:
        print(f"compare_bench: cannot read {path}: {e}", file=sys.stderr)
        return None
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        # maybe a JSONL tail (e.g. a sidecar) — take the last object line
        doc = None
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                doc = json.loads(line)
            except json.JSONDecodeError:
                continue
        if doc is None:
            print(f"compare_bench: no JSON payload in {path}",
                  file=sys.stderr)
            return None
    if isinstance(doc, dict) and "parsed" in doc and "rc" in doc:
        if doc["parsed"] is None:
            print(f"compare_bench: {path} is a driver record whose bench "
                  f"run failed (rc={doc.get('rc')}); nothing to compare",
                  file=sys.stderr)
            return None
        doc = doc["parsed"]
    if isinstance(doc, dict) and "section" in doc:
        # SectionRecorder sidecar (BENCH.partial.jsonl): the bench payload
        # of the "final" record is nested under "value"
        if doc.get("section") == "final" and isinstance(doc.get("value"),
                                                        dict):
            doc = doc["value"]
        else:
            print(f"compare_bench: {path} is a sidecar without a completed "
                  "'final' record (run killed mid-way?); nothing to compare",
                  file=sys.stderr)
            return None
    if not isinstance(doc, dict) or "metric" not in doc:
        print(f"compare_bench: {path} does not look like a bench record",
              file=sys.stderr)
        return None
    return doc


def _stamp(rec: Dict[str, Any], key: str):
    """A record's backend-identity field: the top-level probe verdict
    (stamped since the phase-profile round), falling back to the PR 2
    ``env`` block for older records."""
    if key in rec:
        return rec[key]
    env = rec.get("env")
    return env.get(key) if isinstance(env, dict) else None


def check_env(old: Dict[str, Any], new: Dict[str, Any],
              allow_mismatch: bool = False) -> int:
    """Backend honesty gate: records from DIFFERENT backends or device
    counts are REFUSED, not silently diffed — the BENCH_r04-vs-r05
    CPU/TPU confusion trap (a tunnel that quietly fell back to the CPU
    proxy must never pass a gate calibrated on TPU numbers, nor vice
    versa). ``--allow-env-mismatch`` downgrades the refusal to the old
    loud warning for deliberate cross-backend reading. Softer stamps
    (jax version, smoke flag) always warn only. Records carrying no
    stamp on either side (pre-PR-2) compare as before."""
    failures = 0
    for k in ("backend", "device_count"):
        ov, nv = _stamp(old, k), _stamp(new, k)
        if ov is not None and nv is not None and ov != nv:
            if allow_mismatch:
                print(f"compare_bench: WARNING {k} mismatch "
                      f"({ov!r} vs {nv!r}) overridden by "
                      "--allow-env-mismatch — numbers are not "
                      "apples-to-apples", file=sys.stderr)
            else:
                print(f"compare_bench: REFUSING to compare: {k} "
                      f"{ov!r} (baseline) vs {nv!r} (candidate) — "
                      "records from different backends measure "
                      "different machines; pass --allow-env-mismatch "
                      "to diff them anyway", file=sys.stderr)
                failures += 1
    oenv, nenv = old.get("env"), new.get("env")
    if isinstance(oenv, dict) and isinstance(nenv, dict):
        for k in ENV_KEYS:
            if k in ("backend", "device_count"):
                continue  # hard-gated above
            if k in oenv and k in nenv and oenv[k] != nenv[k]:
                print(f"compare_bench: WARNING env mismatch on {k!r}: "
                      f"{oenv[k]!r} vs {nenv[k]!r} — numbers are not "
                      "apples-to-apples", file=sys.stderr)
    return failures


def check_steady_state(new: Dict[str, Any]) -> int:
    """The recompile gate: a candidate record carrying the PR 4
    ``steady_state_recompiles`` field (compiles observed inside bench.py's
    TIMED loops, warmup excluded; obs compile-listener counter) must show
    zero — a nonzero count means some section retraces per step, which
    poisons every throughput number in the same record. Absolute property
    of the NEW record, no baseline needed; absent field (pre-PR-4 records,
    runs without DETPU_OBS) passes."""
    n = new.get("steady_state_recompiles")
    if isinstance(n, (int, float)) and n > 0:
        print(f"compare_bench: steady_state_recompiles={int(n)} — the "
              "candidate bench retraced inside a timed loop; its "
              "throughput numbers measure compiles, not steps",
              file=sys.stderr)
        return 1
    return 0


def check_phase_budget(old: Dict[str, Any], new: Dict[str, Any]) -> int:
    """The PR 7 pass-budget gate, the static analogue of the recompile
    gate: the bench record embeds the per-phase HLO pass census of the
    headline step (``phase_budget.phases``: gather/scatter/sort/cumsum/
    all-to-all passes per ``obs.scope`` phase). Two absolute checks and
    one diff:

    * a candidate whose census VIOLATES its own contracts (e.g. a dedup
      pass compiled into the SparseSGD headline) fails outright;
    * a candidate whose gated pass count GROWS in any phase both records
      share fails — an extra gather/sort in the hot path is a regression
      even before it shows up as milliseconds. Counts dropping, phases
      disappearing, or brand-new phases are fine (pass cuts and new
      instrumentation are the point).

    Records without a ``phase_budget`` section (pre-PR-7) pass the diff.
    """
    failures = 0
    nb = new.get("phase_budget")
    if not isinstance(nb, dict):
        if isinstance(old.get("phase_budget"), dict):
            # the baseline proves the section used to exist: a candidate
            # without one means the census crashed or was skipped, and a
            # silent pass here would hide exactly the regressions the
            # gate exists to catch
            print("compare_bench: candidate record has no phase_budget "
                  "section but the baseline does — the census failed or "
                  "was skipped; the pass-budget gate cannot run",
                  file=sys.stderr)
            return 1
        return 0  # both pre-PR-7 records: nothing to compare
    for v in nb.get("violations") or []:
        print(f"compare_bench: phase_budget contract violation in the "
              f"candidate record: {v}", file=sys.stderr)
        failures += 1
    ob = old.get("phase_budget")
    ophases = ob.get("phases") if isinstance(ob, dict) else None
    nphases = nb.get("phases")
    if not isinstance(ophases, dict) or not isinstance(nphases, dict):
        return failures
    for phase, orow in ophases.items():
        nrow = nphases.get(phase)
        if not isinstance(orow, dict) or not isinstance(nrow, dict):
            continue
        for kind in PHASE_GATE_KINDS:
            ov, nv = orow.get(kind, 0) or 0, nrow.get(kind, 0) or 0
            if nv > ov:
                print(f"compare_bench: phase_budget REGRESSION: phase "
                      f"{phase!r} {kind} passes {ov} -> {nv} — a new "
                      "row-op pass entered the hot path",
                      file=sys.stderr)
                failures += 1
    return failures


#: max tolerated |predicted - measured| / measured byte drift of the
#: bench's plan_audit section (the plan-time capacity model must stay
#: validated against XLA's own accounting, not decorative)
PLAN_AUDIT_DRIFT_TOL = 0.15


def check_plan_audit(old: Dict[str, Any], new: Dict[str, Any]) -> int:
    """The PR 8 capacity gate: the bench record embeds the plan-time
    byte model's self-check (``plan_audit``: predicted argument bytes of
    the compiled headline step vs XLA ``memory_analysis``, plus the
    contract audit of the headline and Criteo-1TB plans). Three absolute
    checks on the candidate:

    * any contract violation (headline or the criteo1tb deployment
      plan) fails outright — an over-HBM or past-cliff plan must never
      ride a green bench record;
    * ``byte_drift_frac`` beyond ±15% fails — the predictor drifted
      from what XLA actually allocates and can no longer be trusted as
      a pre-pod gate;
    * a candidate missing the section while the baseline has it fails
      (the audit crashed or was skipped — silence would hide exactly
      the regressions the gate exists to catch).
    """
    nb = new.get("plan_audit")
    if not isinstance(nb, dict):
        if isinstance(old.get("plan_audit"), dict):
            print("compare_bench: candidate record has no plan_audit "
                  "section but the baseline does — the capacity audit "
                  "failed or was skipped; the plan gate cannot run",
                  file=sys.stderr)
            return 1
        return 0
    failures = 0
    for v in nb.get("violations") or []:
        print(f"compare_bench: plan_audit contract violation in the "
              f"candidate record: {v}", file=sys.stderr)
        failures += 1
    c1tb = nb.get("criteo1tb")
    if isinstance(c1tb, dict):
        for v in c1tb.get("violations") or []:
            print(f"compare_bench: plan_audit criteo1tb violation in the "
                  f"candidate record: {v}", file=sys.stderr)
            failures += 1
    drift = nb.get("byte_drift_frac")
    if drift is None:
        # the predictor was never validated this round (compile or
        # memory_analysis failed) — that is a gate failure whenever the
        # baseline shows validation used to work, not a silent pass
        ob = old.get("plan_audit")
        if isinstance(ob, dict) and ob.get("byte_drift_frac") is not None:
            print("compare_bench: plan_audit byte_drift_frac is null in "
                  "the candidate (compile_error="
                  f"{nb.get('compile_error')!r}) but the baseline had a "
                  "measured drift — the capacity predictor went "
                  "unvalidated", file=sys.stderr)
            failures += 1
    elif isinstance(drift, (int, float)) and abs(drift) > PLAN_AUDIT_DRIFT_TOL:
        print(f"compare_bench: plan_audit byte drift {drift:+.1%} exceeds "
              f"±{PLAN_AUDIT_DRIFT_TOL:.0%}: predicted "
              f"{nb.get('predicted_argument_mb')} MB vs measured "
              f"{nb.get('measured_argument_mb')} MB — the plan-time "
              "capacity model no longer matches XLA's accounting",
              file=sys.stderr)
        failures += 1
    return failures


#: tolerated growth of the schedule section's modeled critical-path
#: bytes (layout jitter between jax/XLA versions moves a few operand
#: shapes; structural regressions move megabytes)
SCHEDULE_BYTES_TOL = 0.02
#: tolerated growth of serialized_collective_fraction (float noise only
#: — any real re-serialization moves whole collectives, not epsilons)
SCHEDULE_FRACTION_TOL = 0.005


def check_schedule(old: Dict[str, Any], new: Dict[str, Any],
                   key: str = "schedule") -> int:
    """The schedule-graph gate (the overlap ratchet): the bench record
    embeds the schedule auditor's baseline report (``schedule``:
    serialized_collective_fraction, modeled critical-path bytes, and the
    per-collective classification of the headline step's dependency
    DAG) — and, since the pipelined round, the K=2 pipelined twin
    (``schedule_pipelined``, checked by a second call with ``key=``).
    Four checks:

    * any contract / declaration violation in the candidate's own
      report fails outright;
    * ``serialized_collective_fraction`` GROWING beyond float tolerance
      fails — overlap, once won, can never silently regress back to a
      serialized exchange;
    * a collective PHASE the baseline classified overlappable that the
      candidate classifies serialized fails, even when the fraction
      math would forgive it (one re-serialized exchange among many
      cheap ones moves the fraction little but loses the win);
    * modeled ``critical_path_bytes`` growing beyond
      :data:`SCHEDULE_BYTES_TOL` fails — a longer dependency chain is a
      structural regression even before it shows up as milliseconds;
    * a candidate missing the section while the baseline has it fails
      (the audit crashed or was skipped — silence would hide exactly
      the regressions the gate exists to catch).
    """
    sec = new.get(key)
    if not isinstance(sec, dict):
        if isinstance(old.get(key), dict):
            print(f"compare_bench: candidate record has no {key} "
                  "section but the baseline does — the schedule audit "
                  "failed or was skipped; the overlap gate cannot run",
                  file=sys.stderr)
            return 1
        return 0
    failures = 0
    for v in sec.get("violations") or []:
        print(f"compare_bench: {key} contract violation in the "
              f"candidate record: {v}", file=sys.stderr)
        failures += 1
    osec = old.get(key)
    if not isinstance(osec, dict):
        return failures
    of = osec.get("serialized_collective_fraction")
    nf = sec.get("serialized_collective_fraction")
    if isinstance(of, (int, float)) and isinstance(nf, (int, float)) \
            and nf > of + SCHEDULE_FRACTION_TOL:
        print(f"compare_bench: {key} REGRESSION: "
              f"serialized_collective_fraction {of:.3f} -> {nf:.3f} — "
              "a collective that used to overlap dense compute is "
              "serialized again", file=sys.stderr)
        failures += 1
    def classifications(s):
        """(scope, phase) -> classification over the section's own
        collectives list AND every per-case list (the headline section
        keeps its lists under ``cases``; the pipelined twin is flat)."""
        out = {}
        for c in s.get("collectives") or []:
            if isinstance(c, dict):
                out[("", c.get("phase"))] = c.get("classification")
        cases = s.get("cases")
        if isinstance(cases, dict):
            for label, case in cases.items():
                if not isinstance(case, dict):
                    continue
                for c in case.get("collectives") or []:
                    if isinstance(c, dict):
                        out[(label, c.get("phase"))] = c.get(
                            "classification")
        return out

    ocls = classifications(osec)
    for (scope, phase), cls in classifications(sec).items():
        if ocls.get((scope, phase)) == "overlappable" \
                and cls == "serialized":
            where = f" (case {scope!r})" if scope else ""
            print(f"compare_bench: {key} REGRESSION: collective phase "
                  f"{phase!r}{where} was overlappable in the baseline "
                  "but the candidate serializes it — an exchange lost "
                  "its independent compute", file=sys.stderr)
            failures += 1
    ob = osec.get("critical_path_bytes")
    nb2 = sec.get("critical_path_bytes")
    if isinstance(ob, (int, float)) and isinstance(nb2, (int, float)) \
            and ob > 0 and nb2 > ob * (1.0 + SCHEDULE_BYTES_TOL):
        print(f"compare_bench: {key} REGRESSION: modeled "
              f"critical-path bytes {int(ob)} -> {int(nb2)} "
              f"(+{(nb2 / ob - 1) * 100:.1f}%) — the step's dependency "
              "chain got longer", file=sys.stderr)
        failures += 1
    return failures


#: tolerated growth of the MEASURED serialized-collective fraction
#: (trace captures are noisier than the static model: thread scheduling
#: moves a few percent between runs; a real re-serialization moves the
#: whole collective, i.e. tens of points)
PHASE_PROFILE_FRACTION_TOL = 0.10


def check_phase_profile(old: Dict[str, Any], new: Dict[str, Any],
                        key: str = "phase_profile") -> int:
    """The measured half of the overlap ratchet: the bench record embeds
    the trace-parsed phase profile of the headline step
    (``phase_profile``: per-phase measured ms, measured a2a fraction,
    measured serialized-collective fraction, capture overhead,
    measured-vs-modeled agreement) — and, since the pipelined round,
    the K=2 pipelined twin (``phase_profile_pipelined``, checked by a
    second call with ``key=``). Three checks:

    * any agreement violation in the candidate (a modeled-serialized
      exchange that MEASURED overlapped, or a join failure) fails
      outright — the cost model and the clock disagree;
    * ``measured_serialized_fraction`` GROWING beyond
      :data:`PHASE_PROFILE_FRACTION_TOL` fails — measured overlap, once
      won by the pipelined step, can never silently regress (the
      measured twin of :func:`check_schedule`'s modeled ratchet);
    * a candidate missing the section while the baseline has it fails
      (the capture crashed or was skipped — silence would hide exactly
      the regressions the gate exists to catch).
    """
    sec = new.get(key)
    if not isinstance(sec, dict):
        if isinstance(old.get(key), dict):
            print(f"compare_bench: candidate record has no {key} "
                  "section but the baseline does — the measured capture "
                  "failed or was skipped; the measured overlap gate "
                  "cannot run", file=sys.stderr)
            return 1
        return 0
    failures = 0
    for v in sec.get("violations") or []:
        print(f"compare_bench: {key} agreement violation in the "
              f"candidate record: {v}", file=sys.stderr)
        failures += 1
    osec = old.get(key)
    if not isinstance(osec, dict):
        return failures
    of = osec.get("measured_serialized_fraction")
    nf = sec.get("measured_serialized_fraction")
    if isinstance(of, (int, float)) and isinstance(nf, (int, float)) \
            and nf > of + PHASE_PROFILE_FRACTION_TOL:
        print(f"compare_bench: {key} REGRESSION: measured "
              f"serialized fraction {of:.3f} -> {nf:.3f} — an exchange "
              "that used to measure hidden under compute is exposed "
              "again on the clock", file=sys.stderr)
        failures += 1
    return failures


#: streaming section contract: the capacity-bounded dynamic table must
#: keep TRACKING the static-vocab AUC on the day-k/day-k+1 replay (and
#: actually exercise its admission machinery) — the scenario's whole
#: point is matching quality at a fraction of the HBM
STREAMING_MAX_AUC_DROP = 0.02


def check_streaming(old: Dict[str, Any], new: Dict[str, Any]) -> int:
    """Gate the ``streaming`` section: a candidate carrying it must show
    a dynamic-vs-static AUC delta within :data:`STREAMING_MAX_AUC_DROP`,
    nonzero admissions, and a dynamic HBM footprint genuinely below the
    static plan's; a candidate MISSING the section while the baseline
    has it fails (the scenario silently disappeared)."""
    sec = new.get("streaming")
    if not isinstance(sec, dict):
        if isinstance(old.get("streaming"), dict):
            print("compare_bench: candidate has no 'streaming' section "
                  "but the baseline does — the streaming scenario failed "
                  "or was dropped", file=sys.stderr)
            return 1
        return 0
    failures = 0
    delta = sec.get("auc_delta_vs_static")
    if isinstance(delta, (int, float)) and delta < -STREAMING_MAX_AUC_DROP:
        print(f"compare_bench: streaming dynamic table trails the static "
              f"vocab by {-delta:.4f} AUC on the day-k+1 eval (> "
              f"{STREAMING_MAX_AUC_DROP} allowed)", file=sys.stderr)
        failures += 1
    if not sec.get("admitted"):
        print("compare_bench: streaming section reports zero admissions "
              "— the frequency gate never fired", file=sys.stderr)
        failures += 1
    frac = sec.get("hbm_frac_of_static")
    if isinstance(frac, (int, float)) and frac >= 1.0:
        print(f"compare_bench: streaming plan prices at {frac:.2f}x the "
              "static plan's HBM — the capacity bound is not bounding",
              file=sys.stderr)
        failures += 1
    return failures


#: max tolerated growth of the serving section's p95 latency (the
#: latency twin of the 10% throughput gate: at a FIXED target QPS and
#: fixed shapes, p95 rising faster than this is a served-path
#: regression, not load)
SERVING_P95_TOL = 0.10


def check_serving(old: Dict[str, Any], new: Dict[str, Any]) -> int:
    """Gate the ``serving`` section (ISSUE 15): three checks.

    * a nonzero ``steady_state_recompiles`` inside the section fails
      outright — a padded-batch ladder that retraces per request mix
      measures compiles, not latencies (the section's count also folds
      into the record-wide recompile gate, but a candidate diffed
      against a pre-serving baseline must not escape it);
    * ``latency_p95_ms`` growing beyond :data:`SERVING_P95_TOL` versus
      the baseline fails — the fixed-QPS latency ratchet;
    * a candidate missing the section while the baseline has it fails
      (the serving scenario failed or was dropped — silence would hide
      exactly the regressions the gate exists to catch).
    """
    sec = new.get("serving")
    if not isinstance(sec, dict):
        if isinstance(old.get("serving"), dict):
            print("compare_bench: candidate has no 'serving' section "
                  "but the baseline does — the serving scenario failed "
                  "or was dropped", file=sys.stderr)
            return 1
        return 0
    failures = 0
    rc = sec.get("steady_state_recompiles")
    if isinstance(rc, (int, float)) and rc > 0:
        print(f"compare_bench: serving section recompiled {int(rc)} "
              "time(s) at steady state — the compiled ladder retraces "
              "under the benched request mix; its latencies measure "
              "compiles", file=sys.stderr)
        failures += 1
    osec = old.get("serving")
    if isinstance(osec, dict):
        op, np_ = osec.get("latency_p95_ms"), sec.get("latency_p95_ms")
        if isinstance(op, (int, float)) and isinstance(np_, (int, float)) \
                and op > 0 and np_ > op * (1.0 + SERVING_P95_TOL):
            print(f"compare_bench: serving REGRESSION: p95 latency "
                  f"{op:.1f} -> {np_:.1f} ms "
                  f"(+{(np_ / op - 1) * 100:.1f}%) at fixed QPS — the "
                  "served path got slower", file=sys.stderr)
            failures += 1
    return failures


#: max tolerated growth of the online section's serve p95 (same latency
#: ratchet as the standalone serving section — fixed step-paced load)
ONLINE_P95_TOL = 0.10
#: max tolerated |AUC(online) - AUC(offline replay)|: the RCU snapshots
#: are COPIES, so concurrent serving must not move the trajectory — a
#: nonzero delta here means the publisher leaked aliased buffers or the
#: serve path wrote into live tables (the statistical twin of
#: check_online's checkpoint-CRC identity)
ONLINE_MAX_AUC_DELTA = 0.002


def check_online(old: Dict[str, Any], new: Dict[str, Any]) -> int:
    """Gate the ``online`` section (ISSUE 16): concurrent train-and-serve
    at fixed staleness.

    * nonzero ``steady_state_recompiles`` fails outright — any mix of
      training, publication and serving that retraces poisons both the
      joint throughput and the latencies;
    * ``freshness_p95_steps`` above the section's own
      ``freshness_slo_steps`` fails — the publisher fell behind the
      staleness budget the section claims to hold;
    * ``auc_delta_vs_replay`` beyond :data:`ONLINE_MAX_AUC_DELTA` fails
      — serving perturbed the training trajectory;
    * serve ``latency_p95_ms`` growing beyond :data:`ONLINE_P95_TOL`
      versus the baseline fails;
    * a candidate missing the section while the baseline has it fails
      (the online scenario crashed or was dropped — absence would hide
      exactly what this gate watches).
    """
    sec = new.get("online")
    if not isinstance(sec, dict):
        if isinstance(old.get("online"), dict):
            print("compare_bench: candidate has no 'online' section "
                  "but the baseline does — the online train-and-serve "
                  "scenario failed or was dropped", file=sys.stderr)
            return 1
        return 0
    failures = 0
    rc = sec.get("steady_state_recompiles")
    if isinstance(rc, (int, float)) and rc > 0:
        print(f"compare_bench: online section recompiled {int(rc)} "
              "time(s) at steady state — training, publication or "
              "serving retraced under the fixed joint load",
              file=sys.stderr)
        failures += 1
    fresh = sec.get("freshness_p95_steps")
    slo = sec.get("freshness_slo_steps")
    if isinstance(fresh, (int, float)) and isinstance(slo, (int, float)) \
            and fresh > slo:
        print(f"compare_bench: online freshness p95 {fresh} steps "
              f"exceeds the section's own SLO {slo} — snapshot "
              "publication fell behind training", file=sys.stderr)
        failures += 1
    delta = sec.get("auc_delta_vs_replay")
    if isinstance(delta, (int, float)) \
            and abs(delta) > ONLINE_MAX_AUC_DELTA:
        print(f"compare_bench: online AUC is {delta:+.4f} off the "
              "offline replay of the identical stream (tolerance "
              f"{ONLINE_MAX_AUC_DELTA}) — concurrent serving moved the "
              "training trajectory", file=sys.stderr)
        failures += 1
    osec = old.get("online")
    if isinstance(osec, dict):
        op, np_ = osec.get("latency_p95_ms"), sec.get("latency_p95_ms")
        if isinstance(op, (int, float)) and isinstance(np_, (int, float)) \
                and op > 0 and np_ > op * (1.0 + ONLINE_P95_TOL):
            print(f"compare_bench: online serve REGRESSION: p95 latency "
                  f"{op:.1f} -> {np_:.1f} ms "
                  f"(+{(np_ / op - 1) * 100:.1f}%) at fixed step-paced "
                  "load — the snapshot-serving path got slower",
                  file=sys.stderr)
            failures += 1
    return failures


#: out-of-process serve latency may cost a socket + pickle round-trip
#: over the in-process floor, but not a structural multiple of it: p99
#: beyond FACTOR x inproc + SLACK ms means the boundary grew a stall
#: (lock convoy, nagle, shm retry storm), not just overhead
ISOLATED_OOP_FACTOR = 5.0
ISOLATED_OOP_SLACK_MS = 10.0
#: ceiling on restart-to-first-served — a reborn worker re-ingests the
#: latest shm snapshot BEFORE answering, so first service after the
#: ready handshake is bounded host work, not a recompile
ISOLATED_RESTART_MS = 30_000.0


def check_isolated_serving(old: Dict[str, Any],
                           new: Dict[str, Any]) -> int:
    """Gate the ``isolated_serving`` section (ISSUE 18): process-isolated
    serving with crash containment.

    * a record whose worker never crashed+restarted fails — the section
      EXISTS to measure supervision under a real kill; zero restarts
      means the drill fizzled;
    * ``budget_ok`` != 1 fails — the restart exhausted its backoff
      budget;
    * ``conserved`` != 1 fails — a request future was lost, duplicated
      or left hanging across the crash;
    * nonzero ``steady_state_recompiles`` fails — the reborn worker
      retraced its serve ladder;
    * out-of-process p99 beyond :data:`ISOLATED_OOP_FACTOR` x the
      in-process floor (+ :data:`ISOLATED_OOP_SLACK_MS`) fails — the
      boundary grew a structural stall;
    * ``restart_to_first_served_ms`` beyond
      :data:`ISOLATED_RESTART_MS` fails;
    * a candidate missing the section while the baseline has it fails.
    """
    sec = new.get("isolated_serving")
    if not isinstance(sec, dict):
        if isinstance(old.get("isolated_serving"), dict):
            print("compare_bench: candidate has no 'isolated_serving' "
                  "section but the baseline does — the process-isolation "
                  "scenario failed or was dropped", file=sys.stderr)
            return 1
        return 0
    failures = 0
    if not sec.get("crashes") or not sec.get("restarts"):
        print(f"compare_bench: isolated_serving recorded crashes="
              f"{sec.get('crashes')} restarts={sec.get('restarts')} — "
              "the mid-stream kill never happened or the supervisor "
              "never restarted the worker", file=sys.stderr)
        failures += 1
    if sec.get("budget_ok") != 1:
        print("compare_bench: isolated_serving restart budget exhausted "
              "— the worker could not be brought back within the "
              "backoff budget", file=sys.stderr)
        failures += 1
    if sec.get("conserved") != 1:
        print("compare_bench: isolated_serving request conservation "
              "broken — a future was lost, duplicated or left hanging "
              "across the worker crash", file=sys.stderr)
        failures += 1
    rc = sec.get("steady_state_recompiles")
    if isinstance(rc, (int, float)) and rc > 0:
        print(f"compare_bench: isolated_serving recompiled {int(rc)} "
              "time(s) at steady state — the reborn worker retraced its "
              "serve ladder", file=sys.stderr)
        failures += 1
    ip, op = sec.get("inproc_p99_ms"), sec.get("oop_p99_ms")
    if isinstance(ip, (int, float)) and isinstance(op, (int, float)) \
            and op > ip * ISOLATED_OOP_FACTOR + ISOLATED_OOP_SLACK_MS:
        print(f"compare_bench: isolated_serving boundary overhead: "
              f"out-of-process p99 {op:.1f} ms vs in-process floor "
              f"{ip:.1f} ms — beyond {ISOLATED_OOP_FACTOR:.0f}x + "
              f"{ISOLATED_OOP_SLACK_MS:.0f} ms, the socket/shm path "
              "grew a structural stall", file=sys.stderr)
        failures += 1
    rtfs = sec.get("restart_to_first_served_ms")
    if isinstance(rtfs, (int, float)) and rtfs > ISOLATED_RESTART_MS:
        print(f"compare_bench: isolated_serving restart-to-first-served "
              f"{rtfs:.0f} ms exceeds {ISOLATED_RESTART_MS:.0f} ms — "
              "the reborn worker did not resume service promptly",
              file=sys.stderr)
        failures += 1
    return failures


#: max tolerated growth of the observability plane's own costs
#: (stats() wall time, HTTP scrape round-trip, black-box dump). These
#: are microsecond/millisecond-scale host measurements with real
#: scheduler noise, so the ratchet is deliberately looser than the 10%
#: throughput gate: a 2x jump is a structural regression (the plane
#: grew a sort, a lock convoy, or an O(window) path back), not jitter.
OBS_PLANE_COST_TOL = 1.0
#: per-metric noise floors: below these absolute baselines the ratchet
#: is skipped — doubling a 3us stats() call is timer noise, doubling a
#: 300us one is a regression
OBS_PLANE_FLOORS = {
    "stats_wall_us": 20.0,
    "scrape_ms": 0.25,
    "dump_ms": 0.25,
}


def check_obs_plane(old: Dict[str, Any], new: Dict[str, Any]) -> int:
    """Gate the ``obs_plane`` section (ISSUE 17): the observability
    plane must stay an instrument, not a workload.

    * a failed scrape (``scrape_ok`` != 1) fails outright — the
      Prometheus endpoint served garbage or nothing while the section
      ran;
    * nonzero ``steady_state_recompiles`` fails — observing a warmed
      serving ladder must never retrace it;
    * each cost in :data:`OBS_PLANE_FLOORS` growing beyond
      :data:`OBS_PLANE_COST_TOL` versus a baseline above its noise
      floor fails — the cost ratchet on the plane's own read, scrape,
      and crash-dump paths;
    * a candidate missing the section while the baseline has it fails
      (the cost measurement crashed or was dropped — absence would hide
      exactly the regressions this gate watches).

    The serving latencies the plane *measures* are gated separately by
    :func:`check_serving`; this gate prices the measuring itself.
    """
    sec = new.get("obs_plane")
    if not isinstance(sec, dict):
        if isinstance(old.get("obs_plane"), dict):
            print("compare_bench: candidate has no 'obs_plane' section "
                  "but the baseline does — the observability-plane cost "
                  "measurement failed or was dropped", file=sys.stderr)
            return 1
        return 0
    failures = 0
    if sec.get("scrape_ok") != 1:
        print("compare_bench: obs_plane scrape_ok != 1 — the Prometheus "
              "scrape endpoint failed while the section ran",
              file=sys.stderr)
        failures += 1
    rc = sec.get("steady_state_recompiles")
    if isinstance(rc, (int, float)) and rc > 0:
        print(f"compare_bench: obs_plane section recompiled {int(rc)} "
              "time(s) at steady state — observing the serving ladder "
              "retraced it", file=sys.stderr)
        failures += 1
    osec = old.get("obs_plane")
    if isinstance(osec, dict):
        for key, floor in OBS_PLANE_FLOORS.items():
            ov, nv = osec.get(key), sec.get(key)
            if not isinstance(ov, (int, float)) \
                    or not isinstance(nv, (int, float)):
                continue
            if ov >= floor and nv > ov * (1.0 + OBS_PLANE_COST_TOL):
                print(f"compare_bench: obs_plane REGRESSION: {key} "
                      f"{ov:.2f} -> {nv:.2f} "
                      f"(+{(nv / ov - 1) * 100:.0f}%) — the plane's own "
                      "cost grew past the "
                      f"{OBS_PLANE_COST_TOL * 100:.0f}% ratchet",
                      file=sys.stderr)
                failures += 1
    return failures


#: tracing-on throughput must stay within this fraction of tracing-off
#: WITHIN the same record (retain-everything is the tracer's worst case)
TRACING_ON_MIN_FRAC = 0.7

#: tracing-off throughput may not drop below this fraction of the
#: baseline's (the "tracing off costs nothing" ratchet; loose enough
#: for shared-CPU noise, tight enough to catch a hot-path tax)
TRACING_OFF_MIN_FRAC = 0.6


def check_tracing(old: Dict[str, Any], new: Dict[str, Any]) -> int:
    """Gate the ``tracing`` section (ISSUE 20): request tracing must be
    free when off, bounded when on, and exact always.

    * ``span_sum_ok`` != 1 fails — a retained trace whose stage spans
      do not sum to its ``latency_ms`` is a lying instrument;
    * ``trace_off_disabled`` != 1 fails — the off run actually traced;
    * nonzero ``steady_state_recompiles`` fails — tracing perturbed the
      serve ladder's compile cache;
    * ``retained`` must be positive and bounded by ``ring_capacity``;
    * ``tracing_on_rps`` below :data:`TRACING_ON_MIN_FRAC` x
      ``tracing_off_rps`` (same record) fails — the tracer's
      retain-everything worst case grew into a workload;
    * ``tracing_off_rps`` below :data:`TRACING_OFF_MIN_FRAC` x the
      baseline's fails — the disabled path grew a tax;
    * a candidate missing the section while the baseline has it fails.
    """
    sec = new.get("tracing")
    if not isinstance(sec, dict):
        if isinstance(old.get("tracing"), dict):
            print("compare_bench: candidate has no 'tracing' section "
                  "but the baseline does — the tracing cost measurement "
                  "failed or was dropped", file=sys.stderr)
            return 1
        return 0
    failures = 0
    if sec.get("span_sum_ok") != 1:
        print("compare_bench: tracing span_sum_ok != 1 — a retained "
              "trace's stage spans do not sum to its latency_ms within "
              "tolerance", file=sys.stderr)
        failures += 1
    if sec.get("trace_off_disabled") != 1:
        print("compare_bench: tracing trace_off_disabled != 1 — the "
              "tracing-off baseline run was actually tracing",
              file=sys.stderr)
        failures += 1
    rc = sec.get("steady_state_recompiles")
    if isinstance(rc, (int, float)) and rc > 0:
        print(f"compare_bench: tracing section recompiled {int(rc)} "
              "time(s) at steady state — tracing perturbed the serve "
              "ladder", file=sys.stderr)
        failures += 1
    retained, cap = sec.get("retained"), sec.get("ring_capacity")
    if not isinstance(retained, (int, float)) or retained < 1 \
            or (isinstance(cap, (int, float)) and retained > cap):
        print(f"compare_bench: tracing retained={retained!r} of "
              f"capacity={cap!r} — retention is empty or unbounded",
              file=sys.stderr)
        failures += 1
    off, on = sec.get("tracing_off_rps"), sec.get("tracing_on_rps")
    if isinstance(off, (int, float)) and isinstance(on, (int, float)) \
            and off > 0 and on < off * TRACING_ON_MIN_FRAC:
        print(f"compare_bench: tracing-on throughput {on:.0f} rps < "
              f"{TRACING_ON_MIN_FRAC:.0%} of tracing-off {off:.0f} rps "
              "— the retain-everything worst case costs too much",
              file=sys.stderr)
        failures += 1
    osec = old.get("tracing")
    if isinstance(osec, dict):
        o_off = osec.get("tracing_off_rps")
        if isinstance(o_off, (int, float)) and o_off > 0 \
                and isinstance(off, (int, float)) \
                and off < o_off * TRACING_OFF_MIN_FRAC:
            print(f"compare_bench: tracing-off throughput REGRESSION: "
                  f"{o_off:.0f} -> {off:.0f} rps (below the "
                  f"{TRACING_OFF_MIN_FRAC:.0%} ratchet) — the disabled "
                  "tracer grew a hot-path tax", file=sys.stderr)
            failures += 1
    return failures


def compare(old: Dict[str, Any], new: Dict[str, Any],
            threshold: float) -> int:
    steady_failures = check_steady_state(new)
    steady_failures += check_phase_budget(old, new)
    steady_failures += check_plan_audit(old, new)
    steady_failures += check_schedule(old, new)
    steady_failures += check_schedule(old, new, key="schedule_pipelined")
    steady_failures += check_phase_profile(old, new)
    steady_failures += check_phase_profile(old, new,
                                           key="phase_profile_pipelined")
    steady_failures += check_streaming(old, new)
    steady_failures += check_serving(old, new)
    steady_failures += check_online(old, new)
    steady_failures += check_isolated_serving(old, new)
    steady_failures += check_obs_plane(old, new)
    steady_failures += check_tracing(old, new)
    regressions = 0
    rows = []
    for keys, higher_better in ((THROUGHPUT_KEYS, True), (MS_KEYS, False)):
        for k in keys:
            ov, nv = old.get(k), new.get(k)
            if not isinstance(ov, (int, float)) or not isinstance(
                    nv, (int, float)):
                if (ov is None) != (nv is None):
                    rows.append((k, ov, nv, None, "only-one-side"))
                continue
            if not ov:
                # a failed section records 0.0 (bench _guard default):
                # not comparable, but NEVER silently dropped — a section
                # flipping between failed and healthy must stay visible
                rows.append((k, ov, nv, None, "baseline-zero"))
                continue
            change = (nv - ov) / ov
            regressed = (change < -threshold if higher_better
                         else change > threshold)
            rows.append((k, ov, nv, change,
                         "REGRESSION" if regressed else "ok"))
            regressions += bool(regressed)
    width = max((len(r[0]) for r in rows), default=10)
    for k, ov, nv, change, verdict in rows:
        pct = "" if change is None else f"{change * 100:+7.1f}%  "
        print(f"{k:<{width}}  {ov!s:>12} -> {nv!s:>12}  {pct}{verdict}")
    if regressions:
        print(f"compare_bench: {regressions} metric(s) regressed beyond "
              f"{threshold * 100:.0f}%", file=sys.stderr)
    if regressions or steady_failures:
        return 1
    print(f"compare_bench: OK ({len(rows)} metric(s) compared, none beyond "
          f"{threshold * 100:.0f}%; steady-state recompiles clean)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("old", help="baseline BENCH json (driver wrapper or "
                                "raw bench.py line)")
    ap.add_argument("new", help="candidate BENCH json")
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="max tolerated fractional regression "
                         "(default 0.10 = 10%%)")
    ap.add_argument("--allow-env-mismatch", action="store_true",
                    help="downgrade the cross-backend refusal to a "
                         "warning (deliberate CPU-vs-TPU reading only)")
    args = ap.parse_args(argv)
    old, new = load_bench(args.old), load_bench(args.new)
    if old is None or new is None:
        return 2
    if check_env(old, new, allow_mismatch=args.allow_env_mismatch):
        return 1
    return compare(old, new, args.threshold)


if __name__ == "__main__":
    sys.exit(main())

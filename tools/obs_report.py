#!/usr/bin/env python
"""The embedding telemetry observatory report: fuse step-metrics
sidecars, jit-carried access telemetry, and static HBM/FLOP accounting
into one run summary.

Three ways in:

* ``python tools/obs_report.py`` (= ``make obs-report``) — the live
  demo/acceptance run: an 8-virtual-device CPU mesh trains a small
  hybrid model on Zipfian synthetic inputs with PLANTED heavy hitters
  and an engineered per-rank load skew, with metrics + telemetry on.
  The report must recover the planted hot rows in the per-table top-k,
  show the planted imbalance in the per-rank load ratios, and carry the
  abstract-lowering HBM/FLOP budget — and the run verifies the
  telemetry is genuinely jit-carried: zero steady-state recompiles
  (``obs.install_compile_listener`` delta over the post-warmup steps)
  and zero host callbacks in the audited jaxpr. Nonzero exit when any
  of that fails, so the target doubles as a gate.
* ``python tools/obs_report.py --metrics BENCH.metrics.jsonl
  [--telemetry run.telemetry.json] [--phases phase_profile.json]`` —
  fuse existing artifacts (a bench sidecar, a resilient run's
  checkpoint-side telemetry flush, a ``tools/phase_profile.py --json``
  measured-phase artifact — or a raw ``DETPU_PROFILE_DIR`` trace
  capture, parsed jax-free) without running anything.
* ``python tools/obs_report.py --selftest`` (wired into ``make
  verify``) — synthetic metrics JSONL + telemetry summary + the
  checked-in miniature trace (``tests/data/mini.trace.json.gz``)
  through the full fusion + render path, no jax, sub-second.

Output: a human-readable report on stdout (``--json PATH`` for the
machine-readable version): per-table top-k hot rows with Zipf-skew
exponents, per-rank routed-id imbalance ratio time series, the a2a byte
breakdown, and the per-table/slab HBM budget table.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
from typing import Any, Dict, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DEMO_WORLD = 8
DEMO_TABLES = 16
DEMO_VOCAB = 1000
DEMO_BATCH = 128
#: (table, row, fraction-of-batch) heavy hitters the demo plants — and
#: the acceptance check then requires in the per-table top-k
PLANTED = ((0, 5, 0.25), (3, 17, 0.20), (9, 250, 0.15))
#: the demo's skewed ragged feature rides table 15, whose owning rank
#: receives ~RAGGED_HOT x the dense per-slot load
RAGGED_TABLE = 15
RAGGED_HOT = 12


def _force_cpu(devices: int) -> None:
    """Before the first jax import: the observatory's live demo is a CPU
    harness tool and must never wait on an accelerator backend."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={devices}")
    os.environ.pop("DETPU_OBS", None)


# ------------------------------------------------------------------ fusion


def load_metrics(path: str) -> List[Dict[str, Any]]:
    """step_metrics records of a MetricsLogger sidecar (rotated ``.1``
    generation included, oldest first; torn lines tolerated)."""
    from distributed_embeddings_tpu.utils.obs import MetricsLogger

    recs: List[Dict[str, Any]] = []
    for p in (path + ".1", path):
        if os.path.exists(p):
            recs.extend(r for r in MetricsLogger.load(p)
                        if r.get("section") == "step_metrics")
    return recs


def metrics_digest(records: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Fold step-metrics records into the observatory's exchange view:
    per-record per-rank imbalance ratios (the time series), a2a byte
    breakdown, overflow/invalid totals."""
    if not records:
        return None
    series = []
    a2a = {"id_a2a_bytes": 0.0, "out_a2a_bytes": 0.0, "grad_a2a_bytes": 0.0}
    overflow = invalid = 0.0
    for rec in records:
        m = rec.get("metrics", {})
        ids = m.get("ids_routed")
        flat = _flatten(ids) if ids is not None else []
        if flat:
            mean = sum(flat) / len(flat)
            series.append({
                "step": rec.get("step"),
                "ratio": (max(flat) / mean) if mean > 0 else 1.0,
            })
        for k in a2a:
            v = m.get(k)
            if v is not None:
                a2a[k] += sum(_flatten(v))
        for k, acc in (("id_overflow", "o"), ("invalid_id_count", "i")):
            v = m.get(k)
            if v is None:
                continue
            s = sum(_flatten(v))
            if acc == "o":
                overflow += s
            else:
                invalid += s
    ratios = [s["ratio"] for s in series]
    return {
        "records": len(records),
        "imbalance_series": series,
        "imbalance_max": max(ratios) if ratios else None,
        "a2a_bytes": dict(a2a, total=sum(a2a.values())),
        "id_overflow_total": overflow,
        "invalid_id_total": invalid,
    }


def _flatten(v) -> List[float]:
    if hasattr(v, "tolist"):  # numpy / jax arrays (fetch_metrics output)
        v = v.tolist()
    if isinstance(v, (list, tuple)):
        out: List[float] = []
        for x in v:
            out.extend(_flatten(x))
        return out
    return [float(v)]


def fuse_report(metrics: Optional[Dict[str, Any]],
                telemetry: Optional[Dict[str, Any]],
                hbm: Optional[Dict[str, Any]],
                verified: Optional[Dict[str, Any]] = None,
                phases: Optional[List[Dict[str, Any]]] = None,
                traces: Optional[Dict[str, Any]] = None
                ) -> Dict[str, Any]:
    """One observatory record from whichever inputs exist."""
    return {"metric": "obs_report", "metrics": metrics,
            "telemetry": telemetry, "hbm": hbm, "verified": verified,
            "phases": phases, "traces": traces}


def load_request_traces(path: str) -> Dict[str, Any]:
    """A request-trace export (``TraceBuffer.export``'s Chrome trace
    document, ``.gz`` fine) -> the report's trace digest: outcome
    histogram, span-partition violations (``sum(stages) != latency``
    beyond the writer's tolerance), restart-crossing traces, and the
    slowest retained requests with their dominant stage."""
    from distributed_embeddings_tpu.utils import reqtrace, traceparse

    recs = traceparse.parse_request_traces(path)
    outcomes: Dict[str, int] = {}
    bad_sum = 0
    crossing: List[str] = []
    for t in recs:
        outcomes[t["outcome"]] = outcomes.get(t["outcome"], 0) + 1
        lat = t.get("latency_ms")
        if isinstance(lat, (int, float)) and t["stages_ms"] and \
                abs(sum(t["stages_ms"].values()) - lat) \
                > reqtrace.SPAN_SUM_TOL_MS:
            bad_sum += 1
        if t["attrs"].get("restart_crossed"):
            crossing.append(t["trace_id"])
    slow = sorted(
        (t for t in recs if isinstance(t.get("latency_ms"), (int, float))),
        key=lambda t: -t["latency_ms"])[:5]
    return {
        "traces": len(recs),
        "outcomes": dict(sorted(outcomes.items())),
        "span_sum_violations": bad_sum,
        "restart_crossing": crossing,
        "slowest": [{
            "trace_id": t["trace_id"], "outcome": t["outcome"],
            "latency_ms": round(t["latency_ms"], 3),
            "dominant_stage": (max(t["stages_ms"],
                                   key=t["stages_ms"].get)
                               if t["stages_ms"] else None),
            "marks": [e["name"] for e in t["events"]],
        } for t in slow],
    }


def load_phases(path: str) -> List[Dict[str, Any]]:
    """Measured-phase cases from either artifact shape:

    * a ``tools/phase_profile.py --json`` dump (list of case records) —
      passed through with calibration/violations intact;
    * a raw trace capture — a ``DETPU_PROFILE_DIR`` directory or one
      ``.trace.json[.gz]`` file — parsed with the jax-free
      ``utils/traceparse.py`` (metadata-tier attribution only: no
      compiled HLO to join bare names against) and reduced to the same
      summary shape.
    """
    from distributed_embeddings_tpu.utils import traceparse

    if os.path.isdir(path) or ".trace.json" in os.path.basename(path):
        events = traceparse.parse_capture(path)
        if not events:
            raise ValueError(f"no op events parsed from trace {path!r}")
        m = traceparse.measure_events(events)
        return [{
            "label": os.path.basename(path.rstrip(os.sep)),
            "profile": {
                "step_wall_ms_p50": m["wall_ms"],
                "group_ms": m["group_ms"],
                "a2a_frac": m["a2a_frac"],
                "concurrency": m["concurrency"],
                "measured_serialized_fraction":
                    m["measured_serialized_fraction"],
                "collectives": m["collectives"],
                "resolved_frac": (m["events_resolved"] / m["events"]
                                  if m["events"] else 0.0),
            },
            "phase_ms": {k: {"p50": v} for k, v in m["phase_ms"].items()},
        }]
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if isinstance(doc, dict):
        doc = [doc]
    return doc


def _fmt_bytes(n: Optional[float]) -> str:
    if n is None:
        return "?"
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f} {unit}"
        n /= 1024
    return f"{n:.1f} GiB"


def render(report: Dict[str, Any]) -> str:
    """Human-readable observatory report."""
    lines: List[str] = ["== embedding telemetry observatory =="]
    tel = report.get("telemetry")
    if tel:
        lines.append(f"-- access telemetry ({tel.get('steps', '?')} steps)")
        lines.append(
            "   per-rank routed ids: "
            + ", ".join(f"{x:.0f}" for x in tel.get("per_rank_ids", []))
            + f"  (imbalance ratio {tel.get('imbalance_ratio', 0):.3f})")
        for t in tel.get("tables", []):
            alpha = t.get("zipf_alpha")
            top = ", ".join(f"row {r}~{c}" for r, c in t["top_rows"][:5])
            lines.append(
                f"   table {t['table_id']:>3} ({t['rows']}x{t['width']}): "
                f"{top}"
                + (f"  zipf~{alpha:.2f}" if alpha is not None else ""))
    m = report.get("metrics")
    if m:
        lines.append(f"-- step metrics ({m['records']} records)")
        a2a = m["a2a_bytes"]
        lines.append(
            f"   a2a bytes: id {_fmt_bytes(a2a['id_a2a_bytes'])} | out "
            f"{_fmt_bytes(a2a['out_a2a_bytes'])} | grad "
            f"{_fmt_bytes(a2a['grad_a2a_bytes'])} | total "
            f"{_fmt_bytes(a2a['total'])}")
        if m.get("imbalance_max") is not None:
            lines.append(
                f"   routed-id imbalance ratio: max {m['imbalance_max']:.3f}"
                f" over {len(m['imbalance_series'])} sampled steps")
        lines.append(
            f"   overflow ids {m['id_overflow_total']:.0f} | invalid ids "
            f"{m['invalid_id_total']:.0f}")
    hbm = report.get("hbm")
    if hbm:
        tot = hbm["layout"]["totals"]
        lines.append("-- HBM budget (static, abstract lowering)")
        lines.append(
            f"   params {_fmt_bytes(tot['param_bytes_allocated'])} "
            f"allocated / {_fmt_bytes(tot['param_bytes_live'])} live "
            f"(padding {tot['padding_frac'] * 100:.1f}%) | opt state "
            f"{_fmt_bytes(tot['opt_state_bytes'])}")
        for key, slab in sorted(hbm["layout"]["slabs"].items()):
            lines.append(
                f"   slab {key}: {slab['shape']} "
                f"{_fmt_bytes(slab['param_bytes'])} "
                f"(live {_fmt_bytes(slab['live_bytes'])}, opt "
                f"{_fmt_bytes(slab['opt_state_bytes'])})")
        comp = hbm.get("compiled") or {}
        if comp.get("error"):
            lines.append(f"   compiled-step analysis unavailable: "
                         f"{comp['error']}")
        else:
            lines.append(
                f"   compiled step [{comp.get('backend')}]: peak est "
                f"{_fmt_bytes(comp.get('peak_bytes_est'))} (args "
                f"{_fmt_bytes(comp.get('argument_bytes'))}, temps "
                f"{_fmt_bytes(comp.get('temp_bytes'))}, aliased "
                f"{_fmt_bytes(comp.get('alias_bytes'))}) | "
                f"flops {comp.get('flops')} | bytes accessed "
                f"{_fmt_bytes(comp.get('bytes_accessed'))}")
        traffic = hbm.get("per_table_traffic") or []
        heavy = sorted(traffic, key=lambda t: -t["est_hbm_bytes_per_step"])
        for t in heavy[:5]:
            lines.append(
                f"   table {t['table_id']:>3}: ~{t['ids_per_step']} "
                f"ids/step, est {_fmt_bytes(t['est_hbm_bytes_per_step'])}"
                f"/step, {t['est_flops_per_step']} flops/step")
    phases = report.get("phases")
    if phases:
        lines.append(f"-- measured phase profile ({len(phases)} case(s))")
        for case in phases:
            prof = case.get("profile") or {}
            frac = prof.get("measured_serialized_fraction")
            lines.append(
                f"   {case.get('label', '?')}: wall p50 "
                f"{prof.get('step_wall_ms_p50', 0):.2f} ms | a2a in "
                f"flight {prof.get('a2a_frac', 0) * 100:.1f}% | "
                f"concurrency x{prof.get('concurrency', 0):.2f} | "
                "measured serialized frac "
                + (f"{frac:.3f}" if isinstance(frac, (int, float))
                   else "n/a"))
            groups = prof.get("group_ms") or {}
            if groups:
                lines.append("      breakdown ms: " + " | ".join(
                    f"{g} {groups[g]:.2f}" for g in
                    ("exchange", "lookup", "dense", "apply", "streaming",
                     "other") if g in groups))
            for c in prof.get("collectives") or []:
                lines.append(
                    f"      {c['phase']}: {c['classification']} "
                    f"(hidden {c.get('hidden_frac', 0) * 100:.0f}%)")
            calib = case.get("calibration") or {}
            flagged = calib.get("flagged")
            if flagged is not None:
                lines.append(
                    f"      calibration: x"
                    f"{calib.get('scale_measured_over_modeled', 0):.0f} "
                    "backend scale, "
                    + (f"{len(flagged)} phase(s) DRIFT beyond "
                       f"{calib.get('drift_max')}x" if flagged
                       else "no phase drifts beyond "
                            f"{calib.get('drift_max')}x"))
            for v in case.get("agreement_violations") or []:
                lines.append(f"      VIOLATION: {v}")
    tr = report.get("traces")
    if tr:
        lines.append(f"-- request traces ({tr['traces']} retained)")
        lines.append("   outcomes: " + (", ".join(
            f"{k} {v}" for k, v in tr["outcomes"].items()) or "none"))
        lines.append(
            f"   span-partition violations: {tr['span_sum_violations']}"
            + ("  !!" if tr["span_sum_violations"] else ""))
        if tr["restart_crossing"]:
            lines.append("   restart-crossing: "
                         + ", ".join(tr["restart_crossing"]))
        for s in tr["slowest"]:
            marks = f"  [{', '.join(s['marks'])}]" if s["marks"] else ""
            lines.append(
                f"   {s['trace_id']}: {s['outcome']} "
                f"{s['latency_ms']:.3f} ms, dominant stage "
                f"{s['dominant_stage'] or 'n/a'}{marks}")
    ver = report.get("verified")
    if ver:
        lines.append("-- verification")
        for k, v in ver.items():
            lines.append(f"   {k}: {v}")
    return "\n".join(lines)


# ---------------------------------------------------------------- demo run


def run_demo(world: int, steps: int, batch: int,
             metrics_path: Optional[str] = None) -> Dict[str, Any]:
    """The acceptance run (see module docstring): train `steps` steps of
    a small hybrid model with planted heavy hitters + skewed ragged
    load, metrics and telemetry on, then fuse + verify."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh

    from distributed_embeddings_tpu.analysis import (
        audit_step_fn, step_memory_report, telemetry as tel)
    from distributed_embeddings_tpu.ops.embedding_lookup import Ragged
    from distributed_embeddings_tpu.parallel import (
        DistributedEmbedding, SparseAdagrad, init_hybrid_state,
        make_hybrid_train_step)
    from distributed_embeddings_tpu.utils import obs, power_law_ids

    devs = jax.devices()  # backend-ok: _force_cpu ran before jax import
    if len(devs) < world:
        raise RuntimeError(
            f"host platform exposes {len(devs)} devices < {world}")
    mesh = Mesh(np.array(devs[:world]), ("data",))

    configs = [{"input_dim": DEMO_VOCAB, "output_dim": 8,
                "combiner": "sum" if i == RAGGED_TABLE else None}
               for i in range(DEMO_TABLES)]
    de = DistributedEmbedding(configs, world_size=world)
    tx = optax.sgd(0.01)
    emb_opt = SparseAdagrad()

    def loss_fn(dp, outs, _batch):
        x = jnp.concatenate([o.reshape(o.shape[0], -1) for o in outs],
                            axis=1)
        return jnp.mean((x @ dp["w"]) ** 2)

    dense_params = {"w": jnp.full((8 * DEMO_TABLES, 1), 0.1, jnp.float32)}
    state = init_hybrid_state(de, emb_opt, dense_params, tx,
                              jax.random.key(0), mesh=mesh)
    tel_cfg = tel.config_from_env()
    telem = tel.init_telemetry(de, tel_cfg, mesh=mesh)
    step = make_hybrid_train_step(de, loss_fn, tx, emb_opt, mesh=mesh,
                                  with_metrics=True, nan_guard=False,
                                  telemetry=tel_cfg)

    obs.install_compile_listener()
    logger = obs.MetricsLogger(metrics_path) if metrics_path else None
    rng = np.random.default_rng(0)
    local_b = batch // world
    cap = local_b * RAGGED_HOT  # per-shard static capacity

    def make_batch():
        cats: List[Any] = []
        for t in range(DEMO_TABLES):
            if t == RAGGED_TABLE:
                # the skew plant: every row of the ragged feature claims
                # RAGGED_HOT ids, so table 15's rank routes ~12x the ids
                # of a 1-hot dense slot. dp-sharded ragged layout: one
                # (values[cap], row_splits[local_b+1]) block per shard
                values = power_law_ids(rng, DEMO_VOCAB, (world * cap,))
                splits = np.tile(
                    np.arange(local_b + 1, dtype=np.int32) * RAGGED_HOT,
                    world)
                cats.append(Ragged(values=jnp.asarray(values, jnp.int32),
                                   row_splits=jnp.asarray(splits)))
                continue
            ids = power_law_ids(rng, DEMO_VOCAB, (batch,)).astype(np.int32)
            for tid, row, frac in PLANTED:
                if tid == t:
                    k = int(batch * frac)
                    pos = rng.permutation(batch)[:k]
                    ids[pos] = row
            cats.append(jnp.asarray(ids))
        return cats

    from distributed_embeddings_tpu.utils import envvars

    warmup = 2
    # metrics-log cadence (DETPU_TELEMETRY_INTERVAL, scaled down to the
    # demo's short run so a default-100 interval still samples it)
    interval = max(1, min(envvars.get_int("DETPU_TELEMETRY_INTERVAL"),
                          max(steps // 4, 1)))
    compiles_after_warmup = None
    loss = metrics = None
    for i in range(steps):
        loss, state, metrics, telem = step(state, make_batch(), None, telem)
        if i == warmup - 1:
            float(np.asarray(loss))  # drain, then mark the steady state
            compiles_after_warmup = obs.counters().get("recompiles", 0)
        if logger is not None and i % interval == 0:
            logger.log_step(obs.fetch_metrics(metrics), step=i,
                            summary=obs.summarize(metrics))
    float(np.asarray(loss))
    steady_recompiles = (obs.counters().get("recompiles", 0)
                         - (compiles_after_warmup or 0))

    # host-interop audit of the exact program (abstract, no execution)
    abs_args = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype)
        if hasattr(a, "shape") else a,
        (state, make_batch(), None, telem))
    audit = audit_step_fn(step, abs_args, world=world,
                          label="obs_report_demo")

    summary = tel.summarize_telemetry(de, telem, topk=tel_cfg.topk)

    # planted-heavy-hitter recovery check
    recovered = {}
    for tid, row, _frac in PLANTED:
        tab = next((t for t in summary["tables"]
                    if t["table_id"] == tid), None)
        recovered[f"table{tid}/row{row}"] = bool(
            tab and any(r == row for r, _ in tab["top_rows"]))

    hbm = step_memory_report(
        de, loss_fn, tx, emb_opt,
        jax.tree.map(lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype)
                     if hasattr(a, "shape") else a, make_batch()),
        None, mesh=mesh, with_metrics=True, nan_guard=False,
        telemetry=tel_cfg, dense_params=dense_params)

    verified = {
        "steady_state_recompiles": int(steady_recompiles),
        "host_interop_in_step": list(audit.host_interop),
        "planted_hot_rows_recovered": recovered,
        "imbalance_ratio": summary["imbalance_ratio"],
        "imbalance_skew_detected": summary["imbalance_ratio"] > 1.5,
    }
    metrics_digest_v = (metrics_digest(load_metrics(metrics_path))
                        if metrics_path else
                        metrics_digest([{"metrics": obs.fetch_metrics(
                            metrics), "step": steps - 1}]))
    return fuse_report(metrics_digest_v, summary, hbm, verified)


def demo_ok(report: Dict[str, Any]) -> bool:
    ver = report.get("verified") or {}
    return (ver.get("steady_state_recompiles") == 0
            and not ver.get("host_interop_in_step")
            and all((ver.get("planted_hot_rows_recovered") or {}).values())
            and bool(ver.get("imbalance_skew_detected")))


# ---------------------------------------------------------------- selftest


def _synth_metrics(path: str, steps: int = 6, world: int = 8) -> None:
    """Synthetic step-metrics JSONL in MetricsLogger's exact schema."""
    from distributed_embeddings_tpu.utils.obs import MetricsLogger

    logger = MetricsLogger(path)
    for s in range(steps):
        per_rank = [100.0 + 40.0 * (r == 0) + s for r in range(world)]
        logger.log_step({
            "ids_routed": per_rank,
            "id_overflow": [0.0] * world,
            "invalid_id_count": [0.0] * world,
            "id_a2a_bytes": [4096.0] * world,
            "out_a2a_bytes": [65536.0] * world,
            "grad_a2a_bytes": [65536.0] * world,
            "out_pad_frac": [0.1] * world,
            "loss": [0.5] * world,
        }, step=s)


#: the checked-in miniature TPU-style trace the no-jax selftest parses
#: (2 device lanes, metadata-embedded op_names, one fused event, one
#: event missing op_name, one host frame that must be dropped)
MINI_TRACE = os.path.join(REPO, "tests", "data", "mini.trace.json.gz")


def _synth_request_trace(tmp: str) -> str:
    """A two-trace request export through the REAL writer (one served
    with the full stage partition, one unavailable crossing a restart)
    — exercises the export -> parse -> digest path end to end."""
    from distributed_embeddings_tpu.utils import reqtrace

    buf = reqtrace.TraceBuffer(capacity=16, sample=1.0, seed=7,
                               enabled=True, process="selftest")
    buf.begin(0, 100.0)
    buf.finish(0, "served", 5.0, 100.005,
               {"queue_wait": 1.0, "coalesce": 0.5, "dispatch": 0.5,
                "device_compute": 2.5, "reply_slice": 0.5},
               flush=1, coalesced=2, flush_t0=100.001)
    buf.begin(1, 100.1)
    buf.event(1, "outage", t=100.2, reason="worker_crash")
    tr = buf.finish(1, "unavailable", 100.0, 100.2,
                    {"queue_wait": 100.0}, stranded=True)
    buf.append_event(tr["trace_id"], "worker_restarted", t=100.9)
    buf.annotate(tr["trace_id"], restart_crossed=True)
    path = os.path.join(tmp, "req.trace.json.gz")
    buf.export(path)
    return path


def _selftest_phases() -> List[str]:
    """Parse the checked-in miniature trace through the jax-free parser
    and check the hand-computable numbers; returns failure strings."""
    from distributed_embeddings_tpu.utils import traceparse

    bad: List[str] = []
    events = traceparse.parse_events(traceparse.load_trace(MINI_TRACE))
    if len(events) != 8:  # 9 X events minus the $python host frame
        bad.append(f"mini trace: expected 8 op events, got {len(events)}")
    m = traceparse.measure_events(events)
    want_phases = {
        "embedding_forward/id_all_to_all",
        "embedding_forward/lookup_w8_d/packed_gather",
        "sparse_apply/sparse_apply_w8",
    }
    missing_ph = want_phases - set(m["phase_ms"])
    if missing_ph:
        bad.append(f"mini trace: phases not recovered: {missing_ph}")
    # a2a spans [0,100)+[10,110) us -> union exactly 110 us
    if abs(m["a2a_union_ms"] - 0.11) > 1e-9:
        bad.append(f"mini trace: a2a union {m['a2a_union_ms']} != 0.11")
    # compute in flight during the a2a: [50,60) copy + [95,110) of the
    # pid2 gather/dot chain = 25 us -> serialized frac (110-25)/110
    frac = m["measured_serialized_fraction"]
    if frac is None or abs(frac - 85.0 / 110.0) > 1e-3:
        bad.append(f"mini trace: serialized fraction {frac} != "
                   f"{85.0 / 110.0:.4f}")
    if m["events_resolved"] != 7:  # copy.3 carries no op_name anywhere
        bad.append(f"mini trace: resolved {m['events_resolved']} != 7")
    if not any(c["classification"] == "serialized"
               for c in m["collectives"]):
        bad.append("mini trace: a2a not classified serialized")
    return bad


def selftest() -> int:
    """Synthetic metrics JSONL + telemetry summary + the checked-in
    miniature trace -> full fusion + render; asserts every report
    section materializes. No jax."""
    with tempfile.TemporaryDirectory(prefix="detpu_obs_report_") as tmp:
        side = os.path.join(tmp, "metrics.jsonl")
        _synth_metrics(side)
        m = metrics_digest(load_metrics(side))
        telemetry = {
            "steps": 6, "per_rank_ids": [840.0] + [600.0] * 7,
            "imbalance_ratio": 840.0 / 630.0,
            "tables": [{"table_id": 0, "rows": 1000, "width": 8,
                        "top_rows": [[5, 150], [17, 90], [2, 30],
                                     [40, 12]],
                        "zipf_alpha": 1.2}],
            "per_width_ids": {"w8": [840.0] + [600.0] * 7},
        }
        hbm = {
            "layout": {
                "totals": {"param_bytes_allocated": 1 << 20,
                           "param_bytes_live": 900 * 1024,
                           "padding_frac": 0.12,
                           "opt_state_bytes": 1 << 20},
                "slabs": {"w8": {"shape": [8, 1024, 128],
                                 "param_bytes": 1 << 20,
                                 "live_bytes": 900 * 1024,
                                 "opt_state_bytes": 1 << 20}},
            },
            "compiled": {"backend": "cpu", "peak_bytes_est": 5 << 20,
                         "argument_bytes": 4 << 20, "temp_bytes": 1 << 20,
                         "alias_bytes": 3 << 20, "flops": 1e6,
                         "bytes_accessed": 8e6, "error": None},
            "per_table_traffic": [{"table_id": 0, "ids_per_step": 128,
                                   "est_hbm_bytes_per_step": 12288,
                                   "est_flops_per_step": 4096}],
        }
        phases = load_phases(MINI_TRACE)
        req_traces = load_request_traces(_synth_request_trace(tmp))
        report = fuse_report(m, telemetry, hbm,
                             {"selftest": True}, phases=phases,
                             traces=req_traces)
        text = render(report)
        required = ("access telemetry", "step metrics", "HBM budget",
                    "imbalance ratio", "a2a bytes", "zipf", "slab w8",
                    "compiled step", "measured phase profile",
                    "id_all_to_all: serialized",
                    "request traces (2 retained)", "restart-crossing",
                    "span-partition violations: 0")
        missing = [r for r in required if r not in text]
        json.dumps(report)  # must round-trip
        if m is None or m["records"] != 6:
            missing.append("metrics records")
        # per-rank loads at step 0 are [140, 100 x7]: mean 105, max 140
        elif abs(m["imbalance_max"] - 140.0 / 105.0) > 1e-9:
            missing.append("imbalance math")
        missing.extend(_selftest_phases())
        if missing:
            print(text)
            for x in missing:
                print(f"obs_report selftest: missing {x!r}",
                      file=sys.stderr)
            return 1
    print("obs_report selftest: OK (synthetic metrics + telemetry + HBM "
          "budget + miniature measured trace fused and rendered)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--metrics", metavar="PATH",
                    help="fuse an existing step-metrics JSONL sidecar")
    ap.add_argument("--telemetry", metavar="PATH",
                    help="fuse an existing telemetry summary JSON (e.g. "
                         "a resilient run's <ckpt>.telemetry.json)")
    ap.add_argument("--phases", metavar="PATH",
                    help="fuse a measured phase-profile artifact: a "
                         "tools/phase_profile.py --json dump, or a raw "
                         "DETPU_PROFILE_DIR trace capture (dir or "
                         ".trace.json[.gz] file, parsed jax-free)")
    ap.add_argument("--traces", metavar="PATH",
                    help="fuse a request-trace export (the Chrome trace "
                         "document utils/reqtrace.py TraceBuffer.export "
                         "writes, .gz fine)")
    ap.add_argument("--run", action="store_true",
                    help="force the live demo run even with --metrics")
    ap.add_argument("--world", type=int, default=DEMO_WORLD)
    ap.add_argument("--steps", type=int, default=24)
    ap.add_argument("--batch", type=int, default=DEMO_BATCH)
    ap.add_argument("--json", metavar="PATH",
                    help="also dump the fused report as JSON (- = stdout)")
    ap.add_argument("--selftest", action="store_true",
                    help="synthetic end-to-end render check (make verify)")
    args = ap.parse_args(argv)

    sys.path.insert(0, REPO)
    if args.selftest:
        return selftest()

    if args.metrics or args.telemetry or args.phases or args.traces:
        if not args.run:
            metrics = telemetry = phases = req_traces = None
            if args.metrics:
                if not os.path.exists(args.metrics) and \
                        not os.path.exists(args.metrics + ".1"):
                    print(f"obs_report: no metrics sidecar at "
                          f"{args.metrics}", file=sys.stderr)
                    return 2
                metrics = metrics_digest(load_metrics(args.metrics))
            if args.telemetry:
                try:
                    with open(args.telemetry, encoding="utf-8") as f:
                        telemetry = json.load(f)
                except (OSError, json.JSONDecodeError) as e:
                    print(f"obs_report: cannot read {args.telemetry}: {e}",
                          file=sys.stderr)
                    return 2
            if args.phases:
                try:
                    phases = load_phases(args.phases)
                except (OSError, ValueError,
                        json.JSONDecodeError) as e:
                    print(f"obs_report: cannot read {args.phases}: {e}",
                          file=sys.stderr)
                    return 2
            if args.traces:
                try:
                    req_traces = load_request_traces(args.traces)
                except (OSError, ValueError,
                        json.JSONDecodeError) as e:
                    print(f"obs_report: cannot read {args.traces}: {e}",
                          file=sys.stderr)
                    return 2
            report = fuse_report(metrics, telemetry, None, phases=phases,
                                 traces=req_traces)
            print(render(report))
            _maybe_json(report, args.json)
            return 0

    _force_cpu(max(args.world, 1))
    with tempfile.TemporaryDirectory(prefix="detpu_obs_demo_") as tmp:
        report = run_demo(args.world, args.steps, args.batch,
                          metrics_path=os.path.join(tmp, "metrics.jsonl"))
    print(render(report))
    _maybe_json(report, args.json)
    if not demo_ok(report):
        print("obs_report: verification FAILED (see the verification "
              "section above)", file=sys.stderr)
        return 1
    print("obs_report: OK (planted hot rows recovered, skew detected, "
          "telemetry jit-carried: 0 steady-state recompiles, no host "
          "callbacks)")
    return 0


def _maybe_json(report: Dict[str, Any], path: Optional[str]) -> None:
    if not path:
        return
    payload = json.dumps(report, indent=2)
    if path == "-":
        print(payload)
    else:
        with open(path, "w", encoding="utf-8") as f:
            f.write(payload + "\n")


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Verify gate for the online learning runtime (run by
``make check-online`` inside ``make verify``) — concurrent
train-and-serve under the combined chaos drill.

CPU end-to-end, deterministic, no backend required beyond the CPU one:

1. spawn a child running ``parallel.online.OnlineRuntime`` — the
   resilient streaming-vocab training loop and the serving coalescer in
   ONE process against ONE set of tables, RCU snapshots published every
   2 steps — under ``DETPU_FAULT=oovflood@3,burst@5`` (step 3 floods
   the TRAINING stream with never-seen ids while step 5 multiplies the
   SERVE arrivals 8x). The child must reach the final step with real
   slot admissions, real serves, only TYPED sheds, versions that only
   move forward (no torn snapshot reads — the bitwise pin lives in
   ``tests/test_online.py``), bounded staleness
   (``freshness_p95_steps`` within the SLO), bounded p99, and ZERO
   steady-state recompiles across any mix of training, publication and
   serving;
2. run the IDENTICAL training stream withOUT serving (plain
   ``run_resilient``, same fault env) in a fresh directory and assert
   both final checkpoints are CRC-identical — concurrent serving must
   not perturb the training trajectory by a single bit (the publisher
   copies, serves read copies, and the version record lives in a
   sidecar BESIDE the checkpoint).

Exit 0 when the drill passes; 1 with a readable reason otherwise.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

STEPS = 12
FLOOD = 3      # training-stream position oovflood@ floods
BURST = 5      # train-step ordinal burst@ multiplies serve arrivals at
SLO_STEPS = 4  # freshness SLO the drill must hold (publish cadence 2)
P99_MS = 5000.0  # sanity ceiling for CPU flushes, not a perf ratchet

_COMMON = """
import sys
sys.path.insert(0, {repo!r})
import jax, optax, numpy as np, jax.numpy as jnp
jax.config.update('jax_platforms', 'cpu')
from distributed_embeddings_tpu.parallel import (
    DistributedEmbedding, SparseAdagrad, StreamingConfig,
    init_hybrid_state, init_streaming, make_hybrid_train_step)
from distributed_embeddings_tpu.parallel import streaming as smod
from distributed_embeddings_tpu.utils import obs
obs.install_compile_listener()
configs = [
    {{"input_dim": 20, "output_dim": 4}},
    {{"input_dim": 32 + 8, "output_dim": 4,
      "streaming": {{"capacity": 32, "buckets": 8}}}},
]
de = DistributedEmbedding(configs, world_size=1)
cfg = StreamingConfig(admit_min_count=2, evict_margin=1,
                      depth=2, buckets=256)
emb_opt = SparseAdagrad()
tx = optax.sgd(0.05)
state = init_hybrid_state(de, emb_opt,
                          {{"w": jnp.ones((4, 1), jnp.float32)}},
                          tx, jax.random.key(0))
sstate = init_streaming(de, cfg)
def loss_fn(dp, outs, batch):
    return sum(batch[:, i].mean() * jnp.mean(o)
               for i, o in enumerate(outs)) * jnp.mean(dp["w"])
def make_batch(i):
    rng = np.random.default_rng(900 + i)
    cats = [jnp.asarray(rng.integers(0, 20, 8), jnp.int32),
            jnp.asarray(rng.integers(i, i + 6, 8) * 7 + 10_000_000,
                        jnp.int32)]
    return cats, jnp.asarray(rng.normal(size=(8, 2)), jnp.float32)
def data(start):
    for i in range(start, {steps}):
        yield make_batch(i)
step = make_hybrid_train_step(de, loss_fn, tx, emb_opt,
                              with_metrics=True, nan_guard=True,
                              dynamic=cfg)
"""

# the online child: train + publish + serve in one process
_CHILD_ONLINE = _COMMON + """
from distributed_embeddings_tpu.parallel import (
    OnlineConfig, OnlineRuntime, Overloaded, ServeConfig, Served,
    ServingRuntime)
from distributed_embeddings_tpu.parallel import serving as sv
rt = ServingRuntime(de, lambda dp, outs, b:
                        sum(jnp.sum(o, -1) for o in outs)
                        + jnp.sum(b, -1),
                    state,
                    config=ServeConfig(max_batch=16, max_wait_ms=0,
                                       deadline_ms=10_000, max_queue=16),
                    streaming=(cfg, sstate))
rng = np.random.default_rng(7)
online = OnlineRuntime(rt, config=OnlineConfig(publish_every_steps=2,
                                               freshness_max_steps={slo}),
                       checkpoint_dir={ckpt!r})
res = online.run(
    step, state, data, de=de,
    warmup_template=([np.zeros(2, np.int32), np.zeros(2, np.int32)],
                     np.zeros((2, 2), np.float32)),
    make_request=lambda i: sv.synthetic_request(rng, [20, 40], 2,
                                                numerical=2),
    requests_per_step=2, streaming_state=sstate, emb_optimizer=emb_opt,
    dense_tx=tx, checkpoint_every_steps=2, metrics_interval=0)
occ = smod.occupancy(de, res.train.streaming)
served = [r for r in res.serve_results if isinstance(r, Served)]
others = [r for r in res.serve_results if not isinstance(r, Served)]
untyped = sum(1 for r in others if not isinstance(r, Overloaded))
vs = [r.version for r in served]
torn = int(vs != sorted(vs) or any(v < 1 for v in vs))
s = res.serve_stats
print("FINAL", res.train.step, "PREEMPTED", int(res.train.preempted),
      "ADMITTED", int(occ["admitted"]), "SERVED", len(served),
      "SHED", len(others), "UNTYPED", untyped,
      "STEADY", s["steady_state_recompiles"], "TORN", torn,
      "FRESHP95", s["freshness_p95_steps"],
      "P99", round(s["latency_p99_ms"], 3),
      "LEVEL", rt.level, "VERSION", res.published_version, flush=True)
"""

# the offline reference: the SAME training stream, no serving at all
_CHILD_OFFLINE = _COMMON + """
from distributed_embeddings_tpu.parallel import run_resilient
r = run_resilient(step, state, data, de=de, checkpoint_dir={ckpt!r},
                  checkpoint_every_steps=2, resume=True,
                  emb_optimizer=emb_opt, dense_tx=tx,
                  streaming_state=sstate, metrics_interval=0)
print("FINAL", r.step, "PREEMPTED", int(r.preempted), flush=True)
"""


def _run_child(code, fault=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    for k in ("DETPU_FAULT", "DETPU_OBS", "DETPU_TELEMETRY"):
        env.pop(k, None)
    env["DETPU_CKPT_RING"] = "2"
    if fault:
        env["DETPU_FAULT"] = fault
    return subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=600)


def _final_crcs(ckpt):
    with open(os.path.join(ckpt, "meta.json"), encoding="utf-8") as f:
        return json.load(f)["files"]


def _parse(stdout):
    for line in reversed(stdout.strip().splitlines()):
        if line.startswith("FINAL"):
            parts = line.split()
            return dict(zip(parts[::2], parts[1::2]))
    return None


def main() -> int:
    errors = []
    with tempfile.TemporaryDirectory(prefix="detpu_online_") as tmp:
        ckpt = os.path.join(tmp, "ck")
        fault = f"oovflood@{FLOOD},burst@{BURST}"
        code = _CHILD_ONLINE.format(repo=REPO, ckpt=ckpt, steps=STEPS,
                                    slo=SLO_STEPS)
        p = _run_child(code, fault=fault)
        if p.returncode != 0:
            return _fail([f"online child failed rc={p.returncode}: "
                          f"{(p.stderr or p.stdout).strip()[-800:]}"])
        got = _parse(p.stdout)
        if not got or got.get("FINAL") != str(STEPS) \
                or got.get("PREEMPTED") != "0":
            errors.append(f"online child ended at {got} — want FINAL "
                          f"{STEPS}, PREEMPTED 0")
        else:
            if int(got["ADMITTED"]) <= 0:
                errors.append("no slot admissions under the oovflood — "
                              "the admission gate never fired")
            if int(got["SERVED"]) <= 0:
                errors.append("no request was ever served")
            if int(got["UNTYPED"]) != 0:
                errors.append(f"{got['UNTYPED']} refusal(s) were not "
                              "typed Overloaded — the burst leaked "
                              "exceptions or losses")
            if int(got["SHED"]) <= 0:
                errors.append("the 8x burst shed nothing — the drill "
                              "never pressured admission control")
            if int(got["STEADY"]) != 0:
                errors.append(
                    f"{got['STEADY']} steady-state recompile(s): some "
                    "mix of publication/serving/training retraced")
            if int(got["TORN"]) != 0:
                errors.append("served versions regressed or preceded "
                              "the first publication — torn or stale "
                              "snapshot reads")
            if float(got["FRESHP95"]) > SLO_STEPS:
                errors.append(
                    f"freshness_p95_steps {got['FRESHP95']} exceeds the "
                    f"SLO {SLO_STEPS} — publication fell behind")
            if float(got["P99"]) > P99_MS:
                errors.append(f"latency p99 {got['P99']} ms is unbounded "
                              f"(ceiling {P99_MS})")
            if got.get("LEVEL") != "0":
                errors.append(f"ladder level {got['LEVEL']} at exit — "
                              "no post-burst recovery")
        if errors:
            return _fail(errors)

        # 2: CRC identity — the same stream without serving
        ref = os.path.join(tmp, "ref")
        code = _CHILD_OFFLINE.format(repo=REPO, ckpt=ref, steps=STEPS)
        p2 = _run_child(code, fault=fault)
        if p2.returncode != 0:
            return _fail([f"offline reference child failed "
                          f"rc={p2.returncode}: "
                          f"{(p2.stderr or p2.stdout).strip()[-800:]}"])
        crcs, ref_crcs = _final_crcs(ckpt), _final_crcs(ref)
        if crcs != ref_crcs:
            diff = sorted(k for k in set(crcs) | set(ref_crcs)
                          if crcs.get(k) != ref_crcs.get(k))
            errors.append(
                "final checkpoints differ between the train-and-serve "
                f"run and the train-only run (files {diff}) — concurrent "
                "serving perturbed the training trajectory")
        if not os.path.isfile(ckpt + ".online.json"):
            errors.append("the online run left no version sidecar "
                          "(<ckpt>.online.json)")
    if errors:
        return _fail(errors)
    print(f"check_online: OK (oovflood@{FLOOD}+burst@{BURST}: "
          f"{got['SERVED']} served / {got['SHED']} typed sheds, "
          f"admissions happened, freshness p95 {got['FRESHP95']} steps "
          f"<= SLO {SLO_STEPS}, p99 {got['P99']} ms, 0 steady-state "
          "recompiles, versions monotone, and the training trajectory "
          "is checkpoint-CRC-identical to the run without serving)")
    return 0


def _fail(errors) -> int:
    for e in errors:
        print(f"check_online: {e}", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())

"""Full DLRMDense fwd/bwd/SGD step at bench shapes: current dot_interact
(gram[:, li, lj] static gather) vs a select-matmul lower-triangle
extraction (MXU-friendly [F*F, P] 0/1 matmul).

Usage: python tools/profile_dense.py [current|matmul]
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

import _profcommon as pc  # repo on sys.path + probe-first backend gate
import distributed_embeddings_tpu.models.dlrm as dlrm_mod
from bench import BATCH, make_cfg, timed_loop


def dot_interact_mm(emb_outs, bottom_mlp_out):
    feats = jnp.stack([bottom_mlp_out] + list(emb_outs), axis=1)
    gram = jnp.einsum("bfd,bgd->bfg", feats, feats)
    f = feats.shape[1]
    li, lj = np.tril_indices(f, k=-1)
    sel = np.zeros((f * f, len(li)), np.float32)
    sel[li * f + lj, np.arange(len(li))] = 1.0
    lower = gram.reshape(gram.shape[0], f * f) @ jnp.asarray(sel, gram.dtype)
    return jnp.concatenate([lower, bottom_mlp_out], axis=1)


def run(batch):
    cfg = make_cfg([100] * 26, jnp.bfloat16)
    dense = dlrm_mod.DLRMDense(cfg)
    tx = optax.sgd(0.005)
    rng = np.random.default_rng(0)
    num = jnp.asarray(rng.normal(size=(batch, 13)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 2, size=(batch, 1)), jnp.float32)
    embs = [jnp.asarray(rng.normal(size=(batch, 128)), jnp.bfloat16)
            for _ in range(26)]
    params = dense.init(jax.random.key(0), num[:2], [e[:2] for e in embs])
    opt_state = tx.init(params)

    def step(state, embs_, batch_):
        params, opt_state = state
        n, y = batch_

        def loss_fn(p):
            return dlrm_mod.bce_with_logits(dense.apply(p, n, embs_), y)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return loss, (optax.apply_updates(params, updates), opt_state)

    dt = timed_loop(jax.jit(step, donate_argnums=(0,)),
                    (params, opt_state), (embs, (num, labels)), iters=20)
    return dt * 1e3


if __name__ == "__main__":
    pc.ensure_backend()  # probe-first: a stalled tunnel must not hang us
    which = sys.argv[1] if len(sys.argv) > 1 else "current"
    if which == "matmul":
        dlrm_mod.dot_interact = dot_interact_mm
    t0 = time.time()
    print(f"{which} dot_interact dense step: {run(BATCH):.1f} ms "
          f"(compile+run {time.time()-t0:.0f}s)", flush=True)

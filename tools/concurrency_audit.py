#!/usr/bin/env python
"""Audit the serving plane's concurrency: lock discipline + protocol proofs.

The fifth static gate (``make concurrency-audit``). The other four see
only the jitted step; this one covers the host-side control plane that
surrounds it — the ``RealtimeDriver`` arrival thread, the
``Supervisor``'s monitor/sender/accept threads, the ``utils/shm.py``
seqlock and the thread-shared ``mplane`` registry
(:mod:`distributed_embeddings_tpu.analysis.concurrency_audit`):

* **Half 1 — lock-discipline analysis** (pure AST): scans every package
  module, discovers its threads of control, and reports unguarded
  shared-attribute mutations, lock-acquisition-order cycles, blocking
  calls under a held lock, unguarded shared module globals and any
  drift against the declared per-module ``ConcurrencyContract``s.
  Deliberate lock-free sites carry ``# thread-local-ok:`` /
  ``# lock-order-ok:`` / ``# blocking-ok:`` line waivers.
* **Half 2 — interleaving model checker**: exhaustively explores the
  seqlock writer/reader and supervisor-heartbeat transition systems
  (virtual clock, bounded depth, zero wall time) proving torn-read
  detection, stamp honesty, publish-never-blocks, rid monotonicity,
  hang-detection-within-deadline and the restart budget over the FULL
  bounded interleaving space.
* **Self-drills**: three seeded-broken sources must each fire their
  Half-1 finding, and three seeded protocol mutants (CRC check removed,
  stamps swapped, heartbeat deadline off-by-one) must each be REFUTED
  with a counterexample trace — a gate that cannot catch its own
  seeded bugs gates nothing.

No jax tracing, no backend, no wall-clock dependence.

    python tools/concurrency_audit.py --strict      # make verify's gate
    python tools/concurrency_audit.py --json report.json
    python tools/concurrency_audit.py --no-drill    # repo audit only

Exit codes: 0 clean; 1 findings / failed proof / drill not caught
(only with ``--strict``); 2 usable-environment failure.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # run as a script: tools/ itself is sys.path[0]
    sys.path.insert(0, REPO)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 on any finding, failed proof or "
                         "missed drill")
    ap.add_argument("--json", metavar="PATH",
                    help="write the full machine-readable report")
    ap.add_argument("--no-drill", action="store_true",
                    help="skip the seeded self-drills (repo audit + "
                         "proofs only)")
    ap.add_argument("--max-states", type=int, default=500_000,
                    help="state-count ceiling per model exploration "
                         "(default %(default)s)")
    args = ap.parse_args(argv)

    try:
        from distributed_embeddings_tpu.analysis import (concurrency_audit
                                                         as ca)
        from distributed_embeddings_tpu.utils import envvars
    except Exception as e:  # pragma: no cover - environment failure
        print(f"concurrency_audit: cannot import the auditor: {e}",
              file=sys.stderr)
        return 2

    depth = envvars.get_int("DETPU_CONCURRENCY_DEPTH")
    words = envvars.get_int("DETPU_CONCURRENCY_WORDS")
    failed = False
    report = {"findings": [], "proofs": [], "refutations": [],
              "drills": "skipped" if args.no_drill else "pending"}

    # ---- Half 1: the repo-wide lock-discipline audit -----------------
    rep = ca.audit_repo()
    report["modules"] = rep.modules
    report["inventory"] = rep.inventory
    report["findings"] = [
        {"kind": f.kind, "path": f.path, "line": f.line,
         "message": f.message} for f in rep.findings]
    for f in rep.findings:
        print(f"concurrency_audit: {f}")
        failed = True
    n_threads = sum(len(v) for v in rep.inventory.values())
    print(f"concurrency_audit: scanned {rep.modules} modules, "
          f"{n_threads} threads of control across "
          f"{len(rep.inventory)} concurrent modules, "
          f"{len(rep.lock_edges)} lock-order edges "
          f"({len(rep.cycles)} cycles), "
          f"{len(rep.findings)} unwaived findings")

    # ---- Half 2: exhaustive interleaving proofs ----------------------
    try:
        for model in (ca.seqlock_model(words=words),
                      ca.supervisor_model(ticks=depth)):
            res = ca.prove(model, args.max_states)
            report["proofs"].append({
                "model": res.model, "ok": res.ok, "states": res.states,
                "transitions": res.transitions,
                "violated": res.violated, "trace": list(res.trace)})
            print(f"concurrency_audit: {res}")
            if not res.ok:
                failed = True
        for name, build in ca.MUTANTS.items():
            kw = ({"words": words} if name.startswith("seqlock")
                  else {"ticks": depth})
            res = ca.refute(build(**kw), args.max_states)
            refuted = not res.ok
            report["refutations"].append({
                "mutant": name, "refuted": refuted,
                "states": res.states, "violated": res.violated,
                "trace": list(res.trace)})
            if refuted:
                print(f"concurrency_audit: mutant '{name}' refuted — "
                      f"'{res.violated}' violated after "
                      f"{len(res.trace)} steps: "
                      f"{' -> '.join(res.trace)}")
            else:
                print(f"concurrency_audit: MUTANT NOT REFUTED: '{name}' "
                      f"passed all invariants over {res.states} states "
                      f"— the explorer cannot distinguish a broken "
                      f"protocol", file=sys.stderr)
                failed = True
    except RuntimeError as e:     # state-space blowup = authoring bug
        print(f"concurrency_audit: {e}", file=sys.stderr)
        failed = True

    # ---- the seeded self-drills --------------------------------------
    if not args.no_drill:
        drill_failures = ca.run_drills(args.max_states)
        report["drills"] = drill_failures or "ok"
        for msg in drill_failures:
            print(f"concurrency_audit: DRILL FAILED: {msg}",
                  file=sys.stderr)
            failed = True
        if not drill_failures:
            print("concurrency_audit: drills OK (unguarded-attribute, "
                  "lock-order-cycle and blocking-under-lock fire; "
                  "faithful models prove; all 3 protocol mutants "
                  "refuted)")

    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=2, sort_keys=True)
        print(f"concurrency_audit: wrote {args.json}")

    if failed:
        print("concurrency_audit: FAILED", file=sys.stderr)
        return 1 if args.strict else 0
    print("concurrency_audit: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())

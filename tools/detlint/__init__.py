"""detlint — the repo's pluggable AST lint framework.

The repo accumulated ad-hoc static checkers (``check_no_eager_backend``,
the AST half of ``check_obs``) that each reimplemented file walking and
reporting. detlint replaces that with one rule framework:

* a **rule** is a module in :mod:`tools.detlint.rules` exposing ``NAME``
  (kebab-case id), ``SCOPE`` (repo-relative glob patterns of the files it
  applies to), optional ``EXCLUDE`` globs, and
  ``check(tree, path, src, ctx) -> [Finding]`` where ``tree`` is the
  parsed ``ast`` module, ``path`` the repo-relative posix path, ``src``
  the file text, and ``ctx`` a per-run scratch dict (rules cache things
  like the env-var registry there);
* the runner walks the repo once, parses each file once, and hands every
  rule the files its scope matches;
* ``python -m tools.detlint`` (wired as ``make lint``) prints findings as
  ``detlint: <path>:<line>: [<rule>] <message>`` and exits nonzero when
  anything fired.

Pure stdlib + AST: no jax import, runs anywhere, instantly.
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import importlib
import json
import os
import pkgutil
import re
import sys
from typing import Any, Dict, Iterable, List, Optional, Sequence

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: directories never walked (build junk, vendored native code, VCS)
SKIP_DIRS = {".git", "__pycache__", "build", "dist", ".claude", "cc",
             ".pytest_cache"}


@dataclasses.dataclass
class Finding:
    """One lint finding, pointing at a repo-relative line."""
    rule: str
    path: str
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def to_json(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


def discover_rules() -> Dict[str, Any]:
    """Import every module in :mod:`tools.detlint.rules` exposing a
    ``NAME`` + ``check`` pair — dropping a new rule module in the package
    is the whole registration story."""
    from . import rules as rules_pkg

    out: Dict[str, Any] = {}
    for info in pkgutil.iter_modules(rules_pkg.__path__):
        if info.name.startswith("_"):
            continue
        mod = importlib.import_module(f"{rules_pkg.__name__}.{info.name}")
        name = getattr(mod, "NAME", None)
        if name and callable(getattr(mod, "check", None)):
            out[name] = mod
    return out


def iter_py_files(repo: str = REPO) -> Iterable[str]:
    """Every checkable ``*.py`` as a repo-relative posix path."""
    for base, dirs, files in os.walk(repo):
        dirs[:] = sorted(d for d in dirs
                         if d not in SKIP_DIRS and not d.endswith(".egg-info"))
        for f in sorted(files):
            if f.endswith(".py"):
                rel = os.path.relpath(os.path.join(base, f), repo)
                yield rel.replace(os.sep, "/")


_glob_cache: Dict[str, "re.Pattern[str]"] = {}


def _compile_glob(pat: str) -> "re.Pattern[str]":
    """Path-aware glob -> regex: ``*``/``?`` stay within one path segment
    (fnmatch's ``*`` crosses ``/``, which makes scopes mean more than they
    read); ``**`` crosses segments."""
    rx = _glob_cache.get(pat)
    if rx is None:
        parts, i = [], 0
        while i < len(pat):
            if pat.startswith("**", i):
                parts.append(".*")
                i += 2
            elif pat[i] == "*":
                parts.append("[^/]*")
                i += 1
            elif pat[i] == "?":
                parts.append("[^/]")
                i += 1
            else:
                parts.append(re.escape(pat[i]))
                i += 1
        rx = _glob_cache[pat] = re.compile("^" + "".join(parts) + "$")
    return rx


def _matches(path: str, patterns: Sequence[str]) -> bool:
    return any(_compile_glob(p).match(path) for p in patterns)


def run(repo: str = REPO,
        rule_names: Optional[Sequence[str]] = None,
        files: Optional[Sequence[str]] = None) -> List[Finding]:
    """Run rules over the repo (or an explicit file list); returns every
    finding. Unknown rule names raise — a gate that silently skips a
    misspelled rule is worse than no gate."""
    rules = discover_rules()
    if rule_names:
        unknown = sorted(set(rule_names) - set(rules))
        if unknown:
            raise ValueError(f"unknown detlint rule(s): {', '.join(unknown)} "
                             f"(have: {', '.join(sorted(rules))})")
        rules = {k: rules[k] for k in rule_names}

    if files is not None:
        # normalize explicit args (absolute, ./-prefixed, OS separators) to
        # repo-relative posix form — SCOPE globs only speak that dialect,
        # and an unmatchable path would silently lint as "clean"
        paths = []
        for f in files:
            if not os.path.isabs(f) and os.path.exists(os.path.join(repo, f)):
                rel = f  # already repo-relative
            else:
                rel = os.path.relpath(os.path.abspath(f), repo)
            if rel.startswith(".."):
                raise ValueError(f"{f!r} lies outside the repo {repo!r}")
            paths.append(rel.replace(os.sep, "/"))
    else:
        paths = list(iter_py_files(repo))
    ctx: Dict[str, Any] = {"repo": repo}
    findings: List[Finding] = []
    for rel in paths:
        full = os.path.join(repo, rel)
        applicable = [m for m in rules.values()
                      if _matches(rel, getattr(m, "SCOPE", ("**",)))
                      and not _matches(rel, getattr(m, "EXCLUDE", ()))]
        if not applicable:
            continue
        try:
            with open(full, encoding="utf-8") as fh:
                src = fh.read()
            tree = ast.parse(src, filename=rel)
        except (OSError, SyntaxError) as e:
            findings.append(Finding("parse", rel, getattr(e, "lineno", 0) or 0,
                                    f"unparseable: {e}"))
            continue
        for mod in applicable:
            findings.extend(mod.check(tree, rel, src, ctx))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="detlint", description=__doc__.splitlines()[0])
    ap.add_argument("files", nargs="*",
                    help="repo-relative files to check (default: whole repo)")
    ap.add_argument("--rule", action="append", dest="rules", metavar="NAME",
                    help="run only this rule (repeatable)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--json", action="store_true",
                    help="emit findings as a JSON array")
    args = ap.parse_args(argv)

    if args.list_rules:
        for name, mod in sorted(discover_rules().items()):
            doc = (mod.__doc__ or "").strip().splitlines()
            print(f"{name}: {doc[0] if doc else ''}")
        return 0

    try:
        findings = run(rule_names=args.rules, files=args.files or None)
    except ValueError as e:
        print(f"detlint: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps([f.to_json() for f in findings], indent=2))
    else:
        for f in findings:
            print(f"detlint: {f}", file=sys.stderr)
    if findings:
        print(f"detlint: {len(findings)} finding(s)", file=sys.stderr)
        return 1
    n_rules = len(args.rules) if args.rules else len(discover_rules())
    print(f"detlint: OK ({n_rules} rule(s), no findings)")
    return 0

"""No ``jnp.unique``/``jnp.nonzero`` without ``size=`` in package code.

The dynamic-shape family (``unique``, ``nonzero``, ``argwhere``,
``flatnonzero``, ``unique_values``/``unique_counts``/...) returns a
data-dependent shape. Under jit that either fails outright or — worse,
via ``jax.ensure_compile_time_eval`` / host staging — silently retraces
per distinct count, the exact recompile poison the steady-state gate
exists to catch; ``size=`` pins the static capacity (the repo-wide
convention: ``analysis/telemetry.py`` candidate extraction,
``unique_ids_static``'s sort-based equivalent). Host-side numpy
(``np.unique``) is untouched — this rule only matches the ``jnp`` /
``jax.numpy`` spellings inside ``distributed_embeddings_tpu/``. A
genuinely eager call site can annotate the line with
``# unsized-ok: <reason>``.
"""

from __future__ import annotations

import ast

from .. import Finding

NAME = "unsized-unique"
SCOPE = ("distributed_embeddings_tpu/**",)
MARKER = "unsized-ok:"

#: jnp callables whose output shape depends on the data unless size= pins it
DYNAMIC_FNS = frozenset({
    "unique", "unique_values", "unique_counts", "unique_inverse",
    "unique_all", "nonzero", "flatnonzero", "argwhere",
})


def _is_jnp(node: ast.expr) -> bool:
    """``jnp.foo`` or ``jax.numpy.foo`` (the package's two spellings)."""
    if isinstance(node, ast.Name):
        return node.id == "jnp"
    return (isinstance(node, ast.Attribute) and node.attr == "numpy"
            and isinstance(node.value, ast.Name) and node.value.id == "jax")


def check(tree: ast.Module, path: str, src: str, ctx) -> list:
    lines = src.splitlines()
    findings = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not (isinstance(f, ast.Attribute) and f.attr in DYNAMIC_FNS
                and _is_jnp(f.value)):
            continue
        if any(kw.arg == "size" for kw in node.keywords):
            continue
        if MARKER in lines[node.lineno - 1]:
            continue
        findings.append(Finding(
            NAME, path, node.lineno,
            f"jnp.{f.attr}() without size= — a data-dependent shape is a "
            "TPU recompile/correctness hazard under jit; pin the static "
            f"capacity with size= (or annotate '# {MARKER} <reason>' for "
            "a genuinely eager call site)"))
    return findings

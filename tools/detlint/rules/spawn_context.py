"""multiprocessing must request the ``spawn`` start method explicitly.

The default start method on Linux is ``fork``, and forking a process
that has initialised the jax backend deadlocks: XLA's runtime threads
and locks are duplicated mid-state into a child that will never run
them (the supervisor's worker processes exist precisely because of
this). Package code therefore never uses the default context:

* ``from multiprocessing import Process/Pool/Manager`` (or the
  ``multiprocessing.pool`` / ``multiprocessing.managers`` modules)
  binds the DEFAULT context — a finding at the import;
* ``<mp>.Process(...)`` / ``<mp>.Pool(...)`` / ``<mp>.Manager(...)``
  on the raw module is the same thing at the call site;
* ``get_context()`` / ``get_context("fork")`` / ``set_start_method``
  with anything but the literal ``"spawn"`` asks for the hazard by
  name.

The blessed idiom is ``parallel/supervisor.py``'s module policy::

    _SPAWN = multiprocessing.get_context("spawn")
    ...
    _SPAWN.Process(target=_worker_main, args=(spec,))

Process-free corners of the package (``multiprocessing.shared_memory``,
``.connection``, ``.resource_tracker``) start nothing and stay quiet.
A site that genuinely needs fork (no jax in the process, ever)
annotates ``# spawn-ok: <reason>`` on the line.
"""

from __future__ import annotations

import ast

from .. import Finding

NAME = "spawn-context"
SCOPE = ("distributed_embeddings_tpu/**", "tools/**", "bench.py",
         "__graft_entry__.py")

MARKER = "spawn-ok:"

#: names that bind the default (fork) context when taken off the raw
#: module or imported directly
DEFAULT_CTX_FACTORIES = {"Process", "Pool", "Manager"}
#: submodules that are nothing but default-context factories
DEFAULT_CTX_MODULES = {"multiprocessing.pool", "multiprocessing.managers"}


def _first_arg_literal(call: ast.Call):
    if call.args and isinstance(call.args[0], ast.Constant):
        return call.args[0].value
    for kw in call.keywords:
        if kw.arg == "method" and isinstance(kw.value, ast.Constant):
            return kw.value.value
    return None


def check(tree: ast.Module, path: str, src: str, ctx) -> list:
    lines = src.splitlines()
    findings = []
    mp_aliases = set()      # names bound to the multiprocessing module
    ctx_getters = set()     # bare names bound to get_context/set_start_method

    def _waived(lineno: int) -> bool:
        return MARKER in lines[lineno - 1]

    def _finding(lineno: int, what: str):
        if not _waived(lineno):
            findings.append(Finding(
                NAME, path, lineno,
                f"{what} uses the default (fork) start method — fork "
                "after jax backend init deadlocks; request spawn "
                'explicitly (multiprocessing.get_context("spawn"), the '
                "supervisor's _SPAWN idiom) or annotate "
                f"'# {MARKER} <reason>'"))

    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "multiprocessing":
                    mp_aliases.add(a.asname or a.name)
                elif a.name in DEFAULT_CTX_MODULES:
                    _finding(node.lineno, f"import {a.name}")
        elif isinstance(node, ast.ImportFrom) and node.level == 0:
            mod = node.module or ""
            if mod == "multiprocessing":
                for a in node.names:
                    if a.name in DEFAULT_CTX_FACTORIES:
                        _finding(node.lineno,
                                 f"from multiprocessing import {a.name}")
                    elif a.name in ("get_context", "set_start_method"):
                        ctx_getters.add(a.asname or a.name)
            elif mod in DEFAULT_CTX_MODULES:
                _finding(node.lineno, f"from {mod} import ...")

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
                and f.value.id in mp_aliases):
            if f.attr in DEFAULT_CTX_FACTORIES:
                _finding(node.lineno, f"{f.value.id}.{f.attr}()")
            elif f.attr in ("get_context", "set_start_method"):
                if _first_arg_literal(node) != "spawn":
                    _finding(node.lineno,
                             f"{f.value.id}.{f.attr}(...) without the "
                             'literal "spawn"')
        elif isinstance(f, ast.Name) and f.id in ctx_getters:
            if _first_arg_literal(node) != "spawn":
                _finding(node.lineno,
                         f'{f.id}(...) without the literal "spawn"')
    findings.sort(key=lambda x: x.line)
    return findings

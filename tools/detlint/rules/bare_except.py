"""No bare ``except:`` anywhere in the repo.

A bare except swallows ``KeyboardInterrupt`` and ``SystemExit`` — in this
codebase that means a preemption SIGTERM-turned-exit or a deadline
``SIGALRM`` escalation can be silently eaten by an over-broad handler,
exactly the failure the resilient driver exists to surface. Catch
``Exception`` (and say why) instead.
"""

from __future__ import annotations

import ast

from .. import Finding

NAME = "bare-except"
SCOPE = ("**",)


def check(tree: ast.Module, path: str, src: str, ctx) -> list:
    findings = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            findings.append(Finding(
                NAME, path, node.lineno,
                "bare 'except:' swallows KeyboardInterrupt/SystemExit "
                "(preemption + deadline escalation paths); catch "
                "'Exception' at most, and name why"))
    return findings

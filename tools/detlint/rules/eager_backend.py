"""No eager jax backend touch in driver entry points and tools.

Round 5's artifacts died rc=124 because ``__graft_entry__.py`` called
``jax.device_count()`` in the parent process before deciding anything — a
>2 min hang when the TPU tunnel stalls (VERDICT r5). Entry points decide
purely from ``utils.runtime.probe_backend`` (a watched subprocess with a
timeout); this rule keeps the bare calls from creeping back in:

* a backend-touching call (``jax.devices``, ``jax.device_count``,
  ``jax.local_devices``, ``jax.local_device_count``,
  ``jax.default_backend``) at MODULE scope (incl. the ``__main__`` block)
  always fails — it runs before any probe can;
* inside a function it must carry a ``# backend-ok: <reason>`` annotation
  on the same line, asserting the call only executes in a probe-cleared
  context (e.g. the dryrun child process).
"""

from __future__ import annotations

import ast

from .. import Finding

NAME = "eager-backend"
SCOPE = ("__graft_entry__.py", "bench.py", "tools/*.py",
         "tools/detlint/*.py", "tools/detlint/rules/*.py")

BACKEND_ATTRS = {"devices", "device_count", "local_devices",
                 "local_device_count", "default_backend"}
MARKER = "backend-ok:"


def _is_backend_call(node: ast.Call) -> bool:
    f = node.func
    return (isinstance(f, ast.Attribute) and f.attr in BACKEND_ATTRS
            and isinstance(f.value, ast.Name) and f.value.id == "jax")


def check(tree: ast.Module, path: str, src: str, ctx) -> list:
    lines = src.splitlines()
    findings = []

    def walk(node, in_function):
        for child in ast.iter_child_nodes(node):
            child_in_fn = in_function or isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda))
            if isinstance(child, ast.Call) and _is_backend_call(child):
                line = lines[child.lineno - 1]
                if not in_function:
                    findings.append(Finding(
                        NAME, path, child.lineno,
                        f"module-scope jax.{child.func.attr}() — runs "
                        "before any backend probe and hangs the process on "
                        "a stalled tunnel; route through "
                        "utils.runtime.probe_backend/require_devices"))
                elif MARKER not in line:
                    findings.append(Finding(
                        NAME, path, child.lineno,
                        f"jax.{child.func.attr}() without a "
                        f"'# {MARKER} <reason>' annotation — either probe "
                        "first (utils.runtime) or annotate why this only "
                        "executes in a probe-cleared context"))
            walk(child, child_in_fn)

    walk(tree, False)
    return findings

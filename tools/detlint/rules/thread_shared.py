"""Classes that spawn threads must declare their shared mutable state.

A class that starts a ``threading.Thread`` (constructor call, a
``Thread`` subclass instantiating itself, or a target handed to an
executor) has — by construction — at least two threads of control
touching its instance. Which attributes those threads share is the
single most load-bearing fact about the class, and the one Python
gives you no syntax for. This rule makes it declarative:

* any class whose body contains a ``threading.Thread(...)`` /
  ``Thread(...)`` spawn (or subclasses ``threading.Thread``) must
  define a ``_THREAD_SHARED`` class attribute: a tuple of the
  instance-attribute names that are mutated after construction and
  visible from more than one thread of control. An empty tuple is a
  legitimate declaration ("the spawned thread touches only closure
  locals / synchronized channels") — the point is that the author
  *said so*;
* the deeper question — is every name in that tuple actually guarded
  or waived? — belongs to the concurrency auditor
  (``analysis/concurrency_audit.py``), which cross-checks the declared
  tuple against its thread-of-control discovery (``make
  concurrency-audit``). This rule is the cheap structural gate that
  makes the declaration exist at all;
* a spawn site that genuinely needs no declaration (e.g. a throwaway
  script-level helper) annotates ``# thread-shared-ok: <reason>`` on
  the spawning line.

The blessed idiom is ``parallel/supervisor.py``::

    class Supervisor:
        _THREAD_SHARED = ("_alive", "_closing", ...)
"""

from __future__ import annotations

import ast

from .. import Finding

NAME = "thread-shared"
SCOPE = ("distributed_embeddings_tpu/**",)

MARKER = "thread-shared-ok:"

DECL = "_THREAD_SHARED"


def _is_thread_ctor(func: ast.expr, thread_names: set) -> bool:
    """``Thread(...)`` via an imported name or ``<mod>.Thread(...)``."""
    if isinstance(func, ast.Name):
        return func.id in thread_names
    if isinstance(func, ast.Attribute):
        return func.attr == "Thread"
    return False


def _spawn_lines(cls: ast.ClassDef, thread_names: set) -> list:
    """Line numbers of every thread spawn lexically inside the class."""
    lines = []
    for node in ast.walk(cls):
        if isinstance(node, ast.Call) and _is_thread_ctor(node.func,
                                                          thread_names):
            lines.append(node.lineno)
    return lines


def _subclasses_thread(cls: ast.ClassDef) -> bool:
    for base in cls.bases:
        if isinstance(base, ast.Name) and base.id == "Thread":
            return True
        if isinstance(base, ast.Attribute) and base.attr == "Thread":
            return True
    return False


def _declares(cls: ast.ClassDef) -> "ast.stmt | None":
    """The class-body ``_THREAD_SHARED = (...)`` assignment, if any."""
    for stmt in cls.body:
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                if isinstance(t, ast.Name) and t.id == DECL:
                    return stmt
        elif isinstance(stmt, ast.AnnAssign):
            if isinstance(stmt.target, ast.Name) and stmt.target.id == DECL:
                return stmt
    return None


def _decl_is_str_tuple(stmt: ast.stmt) -> bool:
    value = getattr(stmt, "value", None)
    if not isinstance(value, ast.Tuple):
        return False
    return all(isinstance(e, ast.Constant) and isinstance(e.value, str)
               for e in value.elts)


def check(tree: ast.Module, path: str, src: str, ctx) -> list:
    lines = src.splitlines()
    thread_names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "threading":
            for a in node.names:
                if a.name == "Thread":
                    thread_names.add(a.asname or a.name)

    findings = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        spawns = _spawn_lines(node, thread_names)
        if _subclasses_thread(node):
            spawns.append(node.lineno)
        if not spawns:
            continue
        unwaived = [ln for ln in spawns
                    if ln <= len(lines) and MARKER not in lines[ln - 1]]
        if not unwaived:
            continue
        decl = _declares(node)
        if decl is None:
            findings.append(Finding(
                NAME, path, node.lineno,
                f"class {node.name} spawns a thread (line"
                f"{'s' if len(unwaived) > 1 else ''} "
                f"{', '.join(map(str, sorted(unwaived)))}) but declares no "
                f"{DECL} tuple of shared mutable attributes — declare one "
                "(an empty tuple is a valid declaration) or annotate the "
                f"spawn line '# {MARKER} <reason>'; the concurrency "
                "auditor cross-checks the declared names"))
        elif not _decl_is_str_tuple(decl):
            findings.append(Finding(
                NAME, path, decl.lineno,
                f"class {node.name}: {DECL} must be a literal tuple of "
                "attribute-name strings — the concurrency auditor parses "
                "it statically"))
    findings.sort(key=lambda x: x.line)
    return findings

"""Every collective exchange site runs under an ``obs.scope`` phase.

The step auditor (``analysis/audit.py``) attributes each collective to the
``jax.named_scope`` phase it was traced under — that is how an audit
report can say *which* exchange broke the census, and how an XLA profile
attributes device time to phases. A ``lax.all_to_all`` added outside a
``with obs.scope(...)`` block would audit as an "unscoped" collective and
profile as anonymous time; this rule makes the omission a lint error at
review time. Annotate ``# scope-ok: <reason>`` for a site that genuinely
cannot take a scope.
"""

from __future__ import annotations

import ast

from .. import Finding

NAME = "named-scope-exchange"
SCOPE = ("distributed_embeddings_tpu/**",)
MARKER = "scope-ok:"

EXCHANGE_ATTRS = {"all_to_all", "all_gather", "reduce_scatter",
                  "ppermute"}


def _is_exchange_call(node: ast.Call) -> bool:
    f = node.func
    if not isinstance(f, ast.Attribute) or f.attr not in EXCHANGE_ATTRS:
        return False
    v = f.value
    # lax.all_to_all(...) / jax.lax.all_to_all(...)
    if isinstance(v, ast.Name) and v.id == "lax":
        return True
    return (isinstance(v, ast.Attribute) and v.attr == "lax"
            and isinstance(v.value, ast.Name) and v.value.id == "jax")


def _is_scope_with(node: ast.With) -> bool:
    for item in node.items:
        call = item.context_expr
        if not isinstance(call, ast.Call):
            continue
        f = call.func
        if isinstance(f, ast.Attribute) and f.attr == "scope":
            return True
        if isinstance(f, ast.Name) and f.id == "scope":
            return True
    return False


def check(tree: ast.Module, path: str, src: str, ctx) -> list:
    lines = src.splitlines()
    findings = []

    def walk(node, scoped):
        for child in ast.iter_child_nodes(node):
            child_scoped = scoped or (isinstance(child, ast.With)
                                      and _is_scope_with(child))
            if (isinstance(child, ast.Call) and _is_exchange_call(child)
                    and not scoped
                    and MARKER not in lines[child.lineno - 1]):
                findings.append(Finding(
                    NAME, path, child.lineno,
                    f"{child.func.attr} outside a 'with obs.scope(...)' "
                    "block — the step auditor and XLA profiles cannot "
                    "attribute this exchange to a phase (or annotate "
                    f"'# {MARKER} <reason>')"))
            walk(child, child_scoped)

    walk(tree, False)
    return findings

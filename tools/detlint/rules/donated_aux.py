"""Jit-carried trailing aux step args follow the single ordering registry.

The hybrid step builders thread optional jit-carried aux states
(telemetry sketches, streaming slot maps, future schedule state) as
TRAILING positional arguments after the fixed ``(state, cat_inputs,
batch)`` prefix. Donation indices, shard_map in/out specs, checkpoint
aux manifests and the resilient driver's generalized rewind all address
those trailing slots POSITIONALLY — so their order is load-bearing, and
it is declared exactly once:
``distributed_embeddings_tpu/parallel/trainer.py::AUX_ARG_REGISTRY``.

This rule resolves the registry by AST (no import) and checks every
step-builder-shaped function definition in scope — positional params
beginning ``state, cat_*, batch*`` — requiring each trailing param to be
a registered aux name, appearing in registry order. An undeclared
trailing arg ships a donated buffer nothing rewinds; a re-ordered pair
donates/rewinds the WRONG buffer. Register the kind first, then thread
it.

``aux`` itself is exempt: it is the PACKED tuple form the internal
``core(state, cat_inputs, batch, aux)`` helpers take — not a jit
boundary (the unpacked ``step`` wrappers are).
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional, Tuple

from .. import Finding

NAME = "donated-aux"
SCOPE = ("distributed_embeddings_tpu/parallel/**",
         "distributed_embeddings_tpu/analysis/**")

REGISTRY_PATH = "distributed_embeddings_tpu/parallel/trainer.py"
#: internal packed-tuple carriers, not jit boundaries
EXEMPT_TRAILING = {"aux"}
#: leading-prefix spellings of a step-builder signature: (state-ish,
#: categorical-inputs-ish, batch-ish)
_STATEISH = ("state", "carry")
_CATISH = ("cat_inputs", "cat_stacks", "cats")
_BATCHISH = ("batch", "batch_stacks", "batch_tree")


def registered_aux(repo: str, ctx: Optional[dict] = None
                   ) -> List[Tuple[str, str]]:
    """The ordered ``(kind, param_name)`` registry, extracted from
    trainer.py's ``AUX_ARG_REGISTRY`` tuple literal by AST. Cached per
    run in ``ctx``."""
    if ctx is not None and "donated_aux_registry" in ctx:
        return ctx["donated_aux_registry"]
    out: List[Tuple[str, str]] = []
    path = os.path.join(repo, REGISTRY_PATH)
    try:
        tree = ast.parse(open(path, encoding="utf-8").read(), path)
    except (OSError, SyntaxError):
        tree = None
    if tree is not None:
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Assign)
                    and any(isinstance(t, ast.Name)
                            and t.id == "AUX_ARG_REGISTRY"
                            for t in node.targets)):
                continue
            if isinstance(node.value, (ast.Tuple, ast.List)):
                for elt in node.value.elts:
                    if (isinstance(elt, (ast.Tuple, ast.List))
                            and len(elt.elts) == 2
                            and all(isinstance(e, ast.Constant)
                                    and isinstance(e.value, str)
                                    for e in elt.elts)):
                        out.append((elt.elts[0].value, elt.elts[1].value))
    if ctx is not None:
        ctx["donated_aux_registry"] = out
    return out


def _is_step_builder_sig(args: ast.arguments) -> bool:
    pos = [a.arg for a in args.posonlyargs + args.args]
    if len(pos) >= 1 and pos[0] == "self":
        pos = pos[1:]
    if len(pos) < 4:  # no trailing aux -> nothing to check
        return False
    return (pos[0] in _STATEISH and pos[1] in _CATISH
            and pos[2] in _BATCHISH)


def check(tree: ast.Module, path: str, src: str, ctx) -> list:
    registry = registered_aux(ctx.get("repo", "."), ctx)
    order = {name: i for i, (_, name) in enumerate(registry)}
    findings = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if not _is_step_builder_sig(node.args):
            continue
        pos = [a.arg for a in node.args.posonlyargs + node.args.args]
        if pos and pos[0] == "self":
            pos = pos[1:]
        trailing = [p for p in pos[3:] if p not in EXEMPT_TRAILING]
        last = -1
        for p in trailing:
            if p not in order:
                findings.append(Finding(
                    NAME, path, node.lineno,
                    f"step builder {node.name!r} threads undeclared aux "
                    f"arg {p!r} — declare it in "
                    f"{REGISTRY_PATH}::AUX_ARG_REGISTRY first (donation "
                    "indices, shard_map specs and the resilient rewind "
                    "address trailing aux POSITIONALLY)"))
                continue
            if order[p] < last:
                findings.append(Finding(
                    NAME, path, node.lineno,
                    f"step builder {node.name!r} threads aux arg {p!r} "
                    f"out of registry order (expected the "
                    f"AUX_ARG_REGISTRY order "
                    f"{[n for _, n in registry]}) — a re-ordered pair "
                    "donates/rewinds the WRONG buffer"))
                continue
            last = order[p]
    return findings

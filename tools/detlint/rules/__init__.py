"""detlint rule modules. A rule is any module here exposing ``NAME``,
``SCOPE`` (glob patterns), optional ``EXCLUDE``, and
``check(tree, path, src, ctx) -> [Finding]`` — discovery is automatic
(``tools.detlint.discover_rules`` walks this package)."""

"""Every ``DETPU_*`` env var read goes through the single registry.

``distributed_embeddings_tpu/utils/envvars.py`` declares every knob (name,
default, meaning). This rule resolves each ``DETPU_*`` env *read* —
``os.environ.get(...)``, ``os.getenv(...)``, ``os.environ[...]``,
``envvars.get/enabled/get_float/get_int(...)`` — to its variable name
(string literals and module-level ``X_ENV = "DETPU_X"`` constants) and
fails on any name the registry does not declare: a typo'd or undeclared
knob ships as a silently-dead env var otherwise. Writes and deletes are
not reads and are ignored.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Optional, Set

from .. import Finding

NAME = "env-registry"
SCOPE = ("distributed_embeddings_tpu/**", "tools/**", "examples/**",
         "bench.py", "__graft_entry__.py", "setup.py")
EXCLUDE = ("distributed_embeddings_tpu/utils/envvars.py",)

REGISTRY_PATH = "distributed_embeddings_tpu/utils/envvars.py"
ENV_READ_HELPERS = {"get", "enabled", "get_float", "get_int"}


def _is_detpu(name: str) -> bool:
    return name.startswith("DETPU_") or name.startswith("_DETPU")


def registered_names(repo: str, ctx: Optional[dict] = None) -> Set[str]:
    """The declared set, extracted from envvars.py's ``declare("...")``
    calls by AST (no import — the registry must be readable by pure
    tooling). Cached per run in ``ctx``."""
    if ctx is not None and "env_registry_names" in ctx:
        return ctx["env_registry_names"]
    names: Set[str] = set()
    path = os.path.join(repo, REGISTRY_PATH)
    try:
        tree = ast.parse(open(path, encoding="utf-8").read(), path)
    except (OSError, SyntaxError):
        tree = None
    if tree is not None:
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "declare"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                names.add(node.args[0].value)
    if ctx is not None:
        ctx["env_registry_names"] = names
    return names


def _module_str_consts(tree: ast.Module) -> Dict[str, str]:
    """Module-level ``NAME = "literal"`` assignments (the ``FAULT_ENV =
    "DETPU_FAULT"`` indirection pattern)."""
    out: Dict[str, str] = {}
    for node in ast.iter_child_nodes(tree):
        if (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out[tgt.id] = node.value.value
    return out


def _resolve(node: ast.AST, consts: Dict[str, str]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        return consts.get(node.id)
    return None


def _is_environ(node: ast.AST) -> bool:
    """``os.environ`` (or a bare ``environ`` from ``from os import
    environ``)."""
    if (isinstance(node, ast.Attribute) and node.attr == "environ"
            and isinstance(node.value, ast.Name) and node.value.id == "os"):
        return True
    return isinstance(node, ast.Name) and node.id == "environ"


def check(tree: ast.Module, path: str, src: str, ctx) -> list:
    registry = registered_names(ctx.get("repo", "."), ctx)
    consts = _module_str_consts(tree)
    findings = []

    def flag(node: ast.AST, arg: ast.AST) -> None:
        name = _resolve(arg, consts)
        if name is None or not _is_detpu(name) or name in registry:
            return
        findings.append(Finding(
            NAME, path, node.lineno,
            f"env read of unregistered {name!r} — declare it in "
            f"{REGISTRY_PATH} (default + one-line meaning) so the knob "
            "surface stays discoverable"))

    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and node.args:
            f = node.func
            # os.environ.get(...) / environ.get(...)
            if (isinstance(f, ast.Attribute) and f.attr == "get"
                    and _is_environ(f.value)):
                flag(node, node.args[0])
            # os.getenv(...)
            elif (isinstance(f, ast.Attribute) and f.attr == "getenv"
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "os"):
                flag(node, node.args[0])
            # envvars.get/enabled/get_float/get_int(...) — run-time checked
            # too, but catching a typo at lint beats catching it in prod
            elif (isinstance(f, ast.Attribute)
                    and f.attr in ENV_READ_HELPERS
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "envvars"):
                flag(node, node.args[0])
        elif (isinstance(node, ast.Subscript)
                and isinstance(node.ctx, ast.Load)
                and _is_environ(node.value)):
            flag(node, node.slice)
    return findings

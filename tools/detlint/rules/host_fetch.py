"""No host fetches (``jax.device_get`` / ``.item()``) in ``parallel/``.

The modules under ``distributed_embeddings_tpu/parallel/`` hold the code
that runs inside (or builds) the jitted SPMD step; a ``.item()`` or
``jax.device_get`` there is a device->host sync — under jit it inserts a
callback-shaped stall, and in builder code it blocks the dispatch
pipeline. Host-side driver code that legitimately reads back (the
resilient driver's loss escalation) uses ``float(np.asarray(...))`` at
clearly-host points; anything that truly needs the fetch can annotate the
line with ``# host-ok: <reason>``.
"""

from __future__ import annotations

import ast

from .. import Finding

NAME = "host-fetch"
SCOPE = ("distributed_embeddings_tpu/parallel/*.py",)
MARKER = "host-ok:"


def check(tree: ast.Module, path: str, src: str, ctx) -> list:
    lines = src.splitlines()
    findings = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        what = None
        if isinstance(f, ast.Attribute) and f.attr == "item" \
                and not node.args and not node.keywords:
            what = ".item()"
        elif (isinstance(f, ast.Attribute) and f.attr == "device_get"
                and isinstance(f.value, ast.Name) and f.value.id == "jax"):
            what = "jax.device_get()"
        if what is None:
            continue
        if MARKER in lines[node.lineno - 1]:
            continue
        findings.append(Finding(
            NAME, path, node.lineno,
            f"{what} in parallel/ — a device->host sync in step/builder "
            "code; keep readbacks in the host driver "
            f"(or annotate '# {MARKER} <reason>')"))
    return findings

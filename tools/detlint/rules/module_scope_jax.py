"""No module-scope jax import in host-side infrastructure modules.

``utils.runtime`` / ``utils.obs`` / ``utils.envvars`` hold the
never-touch-a-backend-at-import contract: their counter/registry halves
must work in processes that never load jax at all, and importing them must
never risk initializing an accelerator backend. ``tools/compare_bench.py``
and detlint itself promise the same ("runs anywhere, instantly"). This
rule pins the contract: any module-scope ``import jax`` /
``from jax... import`` in a scoped file is a finding — import it inside
the function that needs it.
"""

from __future__ import annotations

import ast

from .. import Finding

NAME = "module-scope-jax"
SCOPE = ("distributed_embeddings_tpu/utils/obs.py",
         "distributed_embeddings_tpu/utils/runtime.py",
         "distributed_embeddings_tpu/utils/envvars.py",
         "distributed_embeddings_tpu/utils/traceparse.py",
         "tools/compare_bench.py",
         "tools/detlint/**")


def check(tree: ast.Module, path: str, src: str, ctx) -> list:
    findings = []
    for node in ast.iter_child_nodes(tree):
        names = []
        if isinstance(node, ast.Import):
            names = [a.name for a in node.names]
        elif isinstance(node, ast.ImportFrom) and node.level == 0:
            names = [node.module or ""]
        if any(n == "jax" or n.startswith("jax.") for n in names):
            findings.append(Finding(
                NAME, path, node.lineno,
                "module-scope jax import — this module must stay "
                "importable without jax (the runtime-layer contract); "
                "import it inside the function that needs it"))
    return findings

"""Capacity numbers must come from the plan-audit registry, not call sites.

Device counts, HBM sizes, bandwidth figures, and byte-scale limit
literals inlined in package code drift silently when hardware
assumptions change — the scatter-cliff threshold measured on v5e, a
16 GiB HBM figure, an ICI bandwidth — and a stale copy turns the
capacity contracts into fiction. PR 8 made
``analysis/plan_audit.py`` the single registry (``ChipSpec`` /
``CHIP_SPECS``, ``SCATTER_CLIFF_*``, ``LANES``): everything else in
``distributed_embeddings_tpu/`` must import from it.

Two triggers:

* any numeric literal >= 2**30 (byte-scale magnitudes; 1 GiB and up) —
  model data that legitimately carries such numbers (e.g. the reference
  zoo's 2e9-row synthetic vocab) annotates the line with
  ``# capacity-ok: <reason>``;
* any assignment whose target name sounds like a hardware capability
  (``*_HBM_*``, ``*GBPS*``, ``*FLOPS*``, ``*CLIFF*``,
  ``*DEVICE_COUNT*``, ...) with a numeric literal on the right-hand
  side, regardless of magnitude.

The registry module itself is excluded (it IS the single home), and the
marker escapes genuinely non-capacity data.
"""

from __future__ import annotations

import ast
import re

from .. import Finding

NAME = "hardcoded-capacity"
SCOPE = ("distributed_embeddings_tpu/**",)
EXCLUDE = ("distributed_embeddings_tpu/analysis/plan_audit.py",)
MARKER = "capacity-ok:"

#: 1 GiB — numeric literals at byte-scale magnitude and above
BYTE_SCALE = 2**30

_CAP_NAME_RE = re.compile(
    r"(HBM|ICI|GBPS|GB_PER_S|TFLOP|FLOPS|CLIFF|DEVICE_COUNT|NUM_DEVICES|"
    r"HBM_HEADROOM)", re.IGNORECASE)


def _num_literals(node: ast.AST):
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value,
                                                        (int, float)) \
                and not isinstance(sub.value, bool):
            yield sub


def _targets(node) -> list:
    if isinstance(node, ast.Assign):
        return node.targets
    if isinstance(node, (ast.AnnAssign, ast.AugAssign)):
        return [node.target]
    return []


def _name_of(t) -> str:
    if isinstance(t, ast.Name):
        return t.id
    if isinstance(t, ast.Attribute):
        return t.attr
    return ""


def check(tree: ast.Module, path: str, src: str, ctx) -> list:
    lines = src.splitlines()

    def marked(lineno: int) -> bool:
        return MARKER in lines[lineno - 1]

    findings = []
    flagged_lines = set()
    # trigger 2: capacity-named assignments with numeric literals
    for node in ast.walk(tree):
        values = getattr(node, "value", None)
        if values is None or not _targets(node):
            continue
        names = [_name_of(t) for t in _targets(node)]
        if not any(n and _CAP_NAME_RE.search(n) for n in names):
            continue
        lits = list(_num_literals(values))
        if not lits or marked(node.lineno):
            continue
        flagged_lines.add(node.lineno)
        findings.append(Finding(
            NAME, path, node.lineno,
            f"capacity-named constant {'/'.join(n for n in names if n)!r} "
            "assigned from a literal — hardware capability numbers live in "
            "the capacity registry (analysis/plan_audit.py: CHIP_SPECS / "
            "SCATTER_CLIFF_* / LANES); import from there (or annotate "
            f"'# {MARKER} <reason>' if this is genuinely not a hardware "
            "number)"))
    # trigger 1: byte-scale magnitudes anywhere. Hex/binary spellings are
    # exempt: hash multipliers and bit masks live in hex, capacity
    # numbers in decimal — the spelling encodes the intent.
    for lit in _num_literals(tree):
        if abs(lit.value) < BYTE_SCALE:
            continue
        if marked(lit.lineno) or lit.lineno in flagged_lines:
            continue
        seg = lines[lit.lineno - 1][lit.col_offset:lit.col_offset + 2]
        if seg.lower() in ("0x", "0b", "0o"):
            continue
        flagged_lines.add(lit.lineno)
        findings.append(Finding(
            NAME, path, lit.lineno,
            f"byte-scale literal {lit.value!r} (>= 2**30) — HBM sizes and "
            "byte limits come from the capacity registry "
            "(analysis/plan_audit.py); import from there, or annotate "
            f"'# {MARKER} <reason>' for non-capacity data (e.g. model "
            "vocab sizes)"))
    return findings

#!/usr/bin/env python
"""Verify gate for the deadline-bounded serving runtime (run by ``make
check-serving`` inside ``make verify``) — the overload drill.

CPU end-to-end, one child process on the 8-virtual-device mesh:

1. the child builds an 8-table model (one STREAMING table serving
   read-only — cold external ids degrade to their shared buckets while
   being served, nothing about the slot map may change), warms the
   padded-batch ladder, audits the compiled serve program (forward-only
   collective contract, no host interop), and drives a seeded Zipfian
   request stream under ``DETPU_FAULT=slow:serve_step:<s>,burst@<pos>``:
   every flush is injected slow (the degraded-backend drill) and during
   second ``<pos>`` the arrival rate spikes ``DETPU_SERVE_BURST_X``-fold
   (the QPS-spike drill);
2. the burst must drive the admission controller up its degradation
   ladder: the queue stays bounded, low-priority requests are shed with
   typed ``Overloaded`` responses (no crash, no exception, no recompile
   storm), ``serve_degraded``/``serve_recovered`` events fire, and
   HIGH-priority requests submitted during the burst keep being served;
3. after the burst the runtime must RECOVER: the ladder returns to
   healthy, a fresh tail of normal-rate requests is served in full, the
   p99 over all served requests stays under ``DETPU_SERVE_SLO_MS``, the
   streaming state is bitwise-unchanged, and the steady-state recompile
   count is ZERO across the whole drill.

Exit 0 when the drill passes; 1 with a readable reason otherwise.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

WORLD = 8
BURST_AT = 2      # second of the stream the QPS spike hits
BURST_X = 16      # arrival-rate multiplier during the burst
SLOW_S = 0.05     # injected per-flush latency (the degraded backend)
QPS = 40.0        # normal arrival rate (well within capacity)
DURATION_S = 4.0

_CHILD = """
import sys
sys.path.insert(0, {repo!r})
import numpy as np, jax, jax.numpy as jnp, optax
jax.config.update('jax_platforms', 'cpu')
from jax.sharding import Mesh
from distributed_embeddings_tpu.parallel import (
    DistributedEmbedding, ServeConfig, ServingRuntime, SparseSGD,
    StreamingConfig, init_hybrid_state, init_streaming)
from distributed_embeddings_tpu.parallel import serving as sv
from distributed_embeddings_tpu.utils import obs

world = {world}
mesh = Mesh(np.array(jax.devices()[:world]), ("data",))
sizes = [20000, 10000, 10000, 5000, 5000, 2000, 2000]
configs = ([{{"input_dim": v, "output_dim": 8}} for v in sizes]
           + [{{"input_dim": 64 + 16, "output_dim": 8,
                "streaming": {{"capacity": 64, "buckets": 16}}}}])
de = DistributedEmbedding(configs, world_size=world)
scfg = StreamingConfig(admit_min_count=2, evict_margin=1, depth=2,
                       buckets=256)
tx = optax.sgd(0.05)
state = init_hybrid_state(de, SparseSGD(),
                          {{"w": jnp.ones((8 * len(configs) + 2, 1),
                                          jnp.float32) * 0.01}},
                          tx, jax.random.key(0), mesh=mesh)
sstate = init_streaming(de, scfg, mesh=mesh)

def pred_fn(dp, outs, batch):
    x = jnp.concatenate(list(outs) + [batch], axis=-1)
    return jax.nn.sigmoid(x @ dp["w"])[:, 0]

cfg = ServeConfig(max_batch=32, max_wait_ms=5, deadline_ms=2000,
                  max_queue=64, shed_frac=0.5)
rt = ServingRuntime(de, pred_fn, state, mesh=mesh, config=cfg,
                    streaming=(scfg, sstate))
rng = np.random.default_rng(0)
table_sizes = sizes + [1]  # streaming input draws external ids below
tmpl = sv.synthetic_request(rng, table_sizes, 2, numerical=2)
rt.warmup((tmpl.cats, tmpl.batch))
stream_before = jax.tree.map(np.asarray, rt.streaming_state)

rep = sv.audit_serve_program(rt)
if rep.violations:
    print("AUDIT_FAIL", "; ".join(rep.violations), flush=True)
    sys.exit(3)

def make_request(i):
    n = int(rng.integers(1, 5))
    req = sv.synthetic_request(rng, sizes, n, numerical=2)
    # streaming table input: EXTERNAL ids far outside any vocab — the
    # read-only remap must serve them from the shared buckets
    req.cats = list(req.cats) + [np.asarray(
        rng.integers(0, 1 << 30, size=(n,)), np.int32)]
    # every 8th request is high-priority: it must survive the shed level
    req.priority = 1 if i % 8 == 0 else 0
    return req

results = sv.drive(rt, make_request, {qps}, {duration},
                   burst_x={burst_x})

# recovery tail: fresh normal-rate requests after the burst must ALL be
# served from a healthy ladder
tail = sv.drive(rt, make_request, {qps}, 1.0, burst_positions=())
tail_served = sum(1 for r in tail if isinstance(r, sv.Served))
tail_total = len(tail)

stream_after = jax.tree.map(np.asarray, rt.streaming_state)
stream_clean = all(
    np.array_equal(a, b)
    for a, b in zip(jax.tree.leaves(stream_before),
                    jax.tree.leaves(stream_after)))
ev_deg = obs.counters().get("event_serve_degraded", 0)
ev_rec = obs.counters().get("event_serve_recovered", 0)
s2 = rt.stats()
print("FINAL",
      "SERVED", s2["served"], "SHED", s2["shed"],
      "EXPIRED", s2["expired"],
      "DEADLINE_MISSED", s2["deadline_missed"],
      "P99", round(s2["latency_p99_ms"] or -1, 1),
      "PAD", round(s2["pad_fraction"], 3),
      "DEGRADED", ev_deg, "RECOVERED", ev_rec,
      "LEVEL", s2["level"],
      "TAIL_SERVED", tail_served, "TAIL_TOTAL", tail_total,
      "STREAM_CLEAN", int(stream_clean),
      "STEADY", s2["steady_state_recompiles"], flush=True)
"""


def main() -> int:
    from distributed_embeddings_tpu.utils import envvars

    slo_ms = envvars.get_float("DETPU_SERVE_SLO_MS")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    for k in ("DETPU_OBS", "DETPU_TELEMETRY"):
        env.pop(k, None)
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + f" --xla_force_host_platform_device_count={WORLD}")
    env["DETPU_FAULT"] = f"slow:serve_step:{SLOW_S},burst@{BURST_AT}"
    env["DETPU_SERVE_BURST_X"] = str(BURST_X)
    code = _CHILD.format(repo=REPO, world=WORLD, qps=QPS,
                         duration=DURATION_S, burst_x=BURST_X)
    p = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=900)
    if p.returncode != 0:
        return _fail([f"drill child failed rc={p.returncode}: "
                      f"{(p.stderr or p.stdout).strip()[-1200:]}"])
    got = None
    for line in reversed(p.stdout.strip().splitlines()):
        if line.startswith("FINAL"):
            parts = line.split()
            got = dict(zip(parts[1::2], parts[2::2]))
            break
    if got is None:
        return _fail(["drill child printed no FINAL line: "
                      f"{p.stdout.strip()[-800:]}"])
    errors = []
    if int(got.get("SERVED", 0)) <= 0:
        errors.append("no requests were served at all")
    if int(got.get("SHED", 0)) <= 0:
        errors.append(
            "the burst shed nothing — the admission controller never "
            "engaged (queue growth was unbounded or the spike fizzled)")
    if int(got.get("DEGRADED", 0)) < 1 or int(got.get("RECOVERED", 0)) < 1:
        errors.append(
            f"degradation ladder events missing (degraded="
            f"{got.get('DEGRADED')}, recovered={got.get('RECOVERED')}) — "
            "transitions must be observable, not silent")
    p99 = float(got.get("P99", -1))
    if not (0 <= p99 <= slo_ms):
        errors.append(
            f"p99 over served requests is {p99} ms — outside the "
            f"{slo_ms:.0f} ms bound (DETPU_SERVE_SLO_MS): shedding did "
            "not keep the served path's latency bounded")
    if int(got.get("LEVEL", 1)) != 0:
        errors.append(
            f"runtime ended at level {got.get('LEVEL')} — no post-burst "
            "recovery to healthy")
    if int(got.get("TAIL_SERVED", 0)) != int(got.get("TAIL_TOTAL", -1)):
        errors.append(
            f"post-burst tail served {got.get('TAIL_SERVED')}/"
            f"{got.get('TAIL_TOTAL')} — normal service did not resume "
            "after the burst")
    if got.get("STREAM_CLEAN") != "1":
        errors.append(
            "the read-only streaming state CHANGED during serving — "
            "slot map/sketch must be bitwise-unchanged by any traffic")
    if got.get("STEADY") != "0":
        errors.append(
            f"{got.get('STEADY')} steady-state recompile(s) — the "
            "request mix retraced the compiled ladder (recompile storm)")
    if errors:
        return _fail(errors)
    print(f"check_serving: OK (burst@{BURST_AT}s x{BURST_X} under "
          f"slow:serve_step:{SLOW_S}: served {got['SERVED']}, shed "
          f"{got['SHED']} typed, p99 {got['P99']} ms <= {slo_ms:.0f} ms, "
          f"{got['DEGRADED']} degraded/{got['RECOVERED']} recovered "
          f"events, post-burst tail {got['TAIL_SERVED']}/"
          f"{got['TAIL_TOTAL']} served, streaming state bitwise clean, "
          "0 steady-state recompiles)")
    return 0


def _fail(errors) -> int:
    for e in errors:
        print(f"check_serving: {e}", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())

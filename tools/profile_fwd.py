"""Bisect the ragged forward chain (gather -> scatter combine -> postprocess)
to find where the 10x-over-op-model time goes (VERDICT r3 Weak #2).

Each stage is timed as one jitted program at the bench's exact shapes, with
the slab passed as an argument. Stages:

  g        : gather only
  gs       : gather + sentinel scatter-add combine (fused as XLA likes)
  gs_bar   : same with an optimization_barrier between gather and scatter
  gs_where : gs + mean-where + counts divide
  full     : gs_where + transpose + astype(bf16)  (the real forward tail)
  send     : _build_send_blocks-style concat + slice + decode in front

Usage: python tools/profile_fwd.py [stage ...]
"""

import sys

import jax
import jax.numpy as jnp
import numpy as np

import _profcommon as pc
from _profcommon import readback, slope

CAP_SIZES = pc.CAP_SIZES
B = 16384
N = 26
W = 128


def main(stages):
    rng = np.random.default_rng(0)
    rows_total = sum(CAP_SIZES)

    hots = rng.integers(1, 31, size=(N, B))
    splits = np.zeros((N, B + 1), np.int64)
    np.cumsum(hots, axis=1, out=splits[:, 1:])
    cap = int(splits[:, -1].max())
    print(f"cap={cap} stream={N*cap}", flush=True)

    vals_np = np.zeros((N, cap), np.int32)
    offs = np.zeros(N, np.int64)
    o = 0
    for i, s in enumerate(CAP_SIZES):
        nnz = int(splits[i, -1])
        u = rng.random(nnz)
        vals_np[i, :nnz] = np.minimum((u ** 3 * s).astype(np.int64), s - 1)
        offs[i] = o
        o += s

    grows = jnp.asarray(vals_np) + jnp.asarray(
        offs.astype(np.int32))[:, None]
    lens = jnp.asarray((splits[:, 1:] - splits[:, :-1]).astype(np.int32))
    slab = jnp.zeros((rows_total, W), jnp.float32) + 0.5

    def seg_of(lens_):
        zero = jnp.zeros((N, 1), lens_.dtype)
        sp = jnp.concatenate([zero, jnp.cumsum(lens_, axis=1)], axis=1)
        return jax.vmap(lambda s: jnp.searchsorted(
            s, jnp.arange(cap, dtype=s.dtype), side="right") - 1)(sp)

    seg_const = seg_of(lens)
    sidx_const = jnp.arange(N)[:, None] * (B + 1) + seg_const

    def combine(gath, sidx):
        buf = jnp.zeros((N * (B + 1), W), gath.dtype)
        buf = buf.at[sidx.reshape(-1)].add(
            gath.reshape(-1, W), indices_are_sorted=True)
        return buf.reshape(N, B + 1, W)[:, :B, :]

    def want(s):
        return not stages or s in stages

    if want("g"):
        def mk(k):
            def f(sl, ids):
                acc = jnp.float32(0)
                for _ in range(k):
                    g = jnp.take(sl, ids.reshape(-1), axis=0, mode="clip")
                    acc = acc + g[0, 0] + g[-1, -1]
                    ids = ids + jnp.int32(acc - acc)
                return acc
            return f
        print(f"g: {slope(mk, (slab, grows)):.1f} ms", flush=True)

    if want("gs"):
        def mk(k):
            def f(sl, ids, sidx):
                acc = jnp.float32(0)
                for _ in range(k):
                    g = jnp.take(sl, ids.reshape(-1), axis=0,
                                 mode="clip").reshape(N, cap, W)
                    red = combine(g, sidx)
                    acc = acc + red[0, 0, 0] + red[-1, -1, -1]
                    ids = ids + jnp.int32(acc - acc)
                return acc
            return f
        print(f"gs (fused): {slope(mk, (slab, grows, sidx_const)):.1f} ms",
              flush=True)

    if want("gs_bar"):
        def mk(k):
            def f(sl, ids, sidx):
                acc = jnp.float32(0)
                for _ in range(k):
                    g = jnp.take(sl, ids.reshape(-1), axis=0,
                                 mode="clip").reshape(N, cap, W)
                    g = jax.lax.optimization_barrier(g)
                    red = combine(g, sidx)
                    acc = acc + red[0, 0, 0] + red[-1, -1, -1]
                    ids = ids + jnp.int32(acc - acc)
                return acc
            return f
        print(f"gs_bar (barrier): {slope(mk, (slab, grows, sidx_const)):.1f} "
              "ms", flush=True)

    if want("gs_where"):
        counts = jnp.maximum(lens, 1)

        def mk(k):
            def f(sl, ids, sidx, cnt):
                acc = jnp.float32(0)
                mean = jnp.zeros((N,), jnp.float32)
                for _ in range(k):
                    g = jnp.take(sl, ids.reshape(-1), axis=0,
                                 mode="clip").reshape(N, cap, W)
                    red = combine(g, sidx)
                    red = jnp.where(mean[:, None, None] > 0,
                                    red / cnt[..., None].astype(red.dtype),
                                    red)
                    acc = acc + red[0, 0, 0] + red[-1, -1, -1]
                    ids = ids + jnp.int32(acc - acc)
                return acc
            return f
        print(f"gs_where: {slope(mk, (slab, grows, sidx_const, counts)):.1f} "
              "ms", flush=True)

    if want("full"):
        counts = jnp.maximum(lens, 1)

        def mk(k):
            def f(sl, ids, sidx, cnt):
                acc = jnp.float32(0)
                mean = jnp.zeros((N,), jnp.float32)
                for _ in range(k):
                    g = jnp.take(sl, ids.reshape(-1), axis=0,
                                 mode="clip").reshape(1, N, cap, W)
                    red = combine(g.reshape(N, cap, W), sidx)
                    red = red.reshape(1, N, B, W)
                    red = jnp.where(mean[None, :, None, None] > 0,
                                    red / cnt[None, ..., None].astype(
                                        red.dtype), red)
                    out = red.transpose(0, 2, 1, 3).reshape(
                        1, B, N * W).astype(jnp.bfloat16)
                    acc = acc + out[0, 0, 0].astype(jnp.float32)
                    ids = ids + jnp.int32(acc - acc)
                return acc
            return f
        print(f"full tail: {slope(mk, (slab, grows, sidx_const, counts)):.1f}"
              " ms", flush=True)

    if want("send_cs"):
        # candidate fix: seg via scatter-ones + cumsum instead of searchsorted
        blen = cap + B

        def seg_cs(lens_):
            ends = jnp.cumsum(lens_, axis=1)  # [N, B] ascending
            marks = jnp.zeros((N, cap + 1), jnp.int32)
            marks = marks.at[
                jnp.arange(N, dtype=jnp.int32)[:, None],
                jnp.clip(ends, 0, cap)].add(1, indices_are_sorted=True)
            return jnp.cumsum(marks[:, :cap], axis=1)

        def mk(k):
            def f(sl, ids, lens_):
                acc = jnp.float32(0)
                for _ in range(k):
                    parts = []
                    for i in range(N):
                        parts.append(ids[i])
                        parts.append(lens_[i])
                    blk = jnp.concatenate(parts).reshape(1, N * blen)
                    r3 = blk.reshape(1, N, blen)
                    values = r3[0, :, :cap]
                    ln = r3[0, :, cap:]
                    seg = seg_cs(ln)
                    sidx = jnp.arange(N)[:, None] * (B + 1) + seg
                    g = jnp.take(sl, values.reshape(-1), axis=0,
                                 mode="clip").reshape(N, cap, W)
                    red = combine(g, sidx)
                    acc = acc + red[0, 0, 0] + red[-1, -1, -1]
                    ids = ids + jnp.int32(acc - acc)
                return acc
            return f
        print(f"send_cs (cumsum seg): {slope(mk, (slab, grows, lens)):.1f} "
              "ms", flush=True)

    if want("send"):
        # the real front: concat values+lengths into [1, l_max] then decode
        blen = cap + B

        def mk(k):
            def f(sl, ids, lens_):
                acc = jnp.float32(0)
                for _ in range(k):
                    parts = []
                    for i in range(N):
                        parts.append(ids[i])
                        parts.append(lens_[i])
                    blk = jnp.concatenate(parts).reshape(1, N * blen)
                    r3 = blk.reshape(1, N, blen)
                    values = r3[0, :, :cap]
                    ln = r3[0, :, cap:]
                    seg = seg_of(ln)
                    sidx = jnp.arange(N)[:, None] * (B + 1) + seg
                    g = jnp.take(sl, values.reshape(-1), axis=0,
                                 mode="clip").reshape(N, cap, W)
                    red = combine(g, sidx)
                    acc = acc + red[0, 0, 0] + red[-1, -1, -1]
                    ids = ids + jnp.int32(acc - acc)
                return acc
            return f
        print(f"send+decode+gs: {slope(mk, (slab, grows, lens)):.1f} ms",
              flush=True)


if __name__ == "__main__":
    pc.ensure_backend()  # probe-first: a stalled tunnel must not hang us
    main(sys.argv[1:])

#!/usr/bin/env python
"""Fixed-QPS Zipfian serving benchmark on the world-8 virtual CPU mesh.

The bench ``serving`` section's body, run in a CHILD process so the
8-virtual-device mesh never touches the bench process's accelerator
tunnel (like ``schedule`` / ``phase_profile`` / ``pipeline``):

* builds an 8-table DLRM-shaped model on a world-8 CPU mesh and a
  :class:`~distributed_embeddings_tpu.parallel.serving.ServingRuntime`
  around the donated-input no-grad forward (padded-batch ladder warmed
  up front),
* drives a seeded Zipfian request stream (variable 1..max samples per
  request, power-law ids) at a FIXED target QPS through the shared
  :func:`~distributed_embeddings_tpu.parallel.serving.drive` loop,
* reports p50/p95/p99 latency over served requests, the shed and
  deadline-missed counts, the aggregate padding fraction, the achieved
  QPS, and the steady-state recompile count (0 required — a ladder that
  retraces per request mix poisons its own latencies),
* embeds the jax-free int8-rows-with-per-row-scales serving-table
  pricing (``analysis.plan_audit.price_int8_serving``) — the capacity
  case for the future quantized-serving PR, recorded next to the
  latencies it would improve.

``tools/compare_bench.py::check_serving`` gates the section: p95
regression beyond 10%, a nonzero recompile count, or the section
disappearing versus the baseline fails the diff.

    python tools/serve_bench.py --json -          # the bench child
    python tools/serve_bench.py --qps 100 --duration 5

Exit codes: 0 ok; 2 usable-environment failure.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

try:  # imported as tools.serve_bench (tests)
    from tools._profcommon import cpu_mesh, force_cpu  # noqa: F401
except ImportError:  # run as a script: tools/ itself is sys.path[0]
    from _profcommon import cpu_mesh, force_cpu  # noqa: F401

WORLD = 8
#: 8 tables (>= world), DLRM-ish widths — big enough that the forward
#: is a real exchange+gather program, small enough that the whole
#: ladder compiles in seconds on the CPU proxy
TABLE_SIZES = (100_000, 50_000, 50_000, 20_000, 20_000, 10_000, 10_000,
               5_000)
DIM = 32
NUMERICAL = 4


def run_qps(qps: float, duration_s: float, max_batch: int,
            max_samples: int, seed: int):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from distributed_embeddings_tpu.analysis.plan_audit import (
        price_int8_serving)
    from distributed_embeddings_tpu.parallel import (
        DistributedEmbedding, ServeConfig, ServingRuntime, SparseSGD,
        init_hybrid_state)
    from distributed_embeddings_tpu.parallel import serving as sv

    mesh = cpu_mesh(WORLD)
    de = DistributedEmbedding(
        [{"input_dim": v, "output_dim": DIM} for v in TABLE_SIZES],
        world_size=WORLD)
    tx = optax.sgd(0.05)
    dense_params = {"w": jnp.asarray(
        np.random.default_rng(0).normal(
            size=(len(TABLE_SIZES) * DIM + NUMERICAL, 1)) * 0.05,
        jnp.float32)}
    state = init_hybrid_state(de, SparseSGD(), dense_params, tx,
                              jax.random.key(1), mesh=mesh)

    def pred_fn(dp, outs, batch):
        x = jnp.concatenate(list(outs) + [batch], axis=-1)
        return jax.nn.sigmoid(x @ dp["w"])[:, 0]

    cfg = ServeConfig(max_batch=max_batch)
    rt = ServingRuntime(de, pred_fn, state, mesh=mesh, config=cfg)
    tmpl_rng = np.random.default_rng(seed)
    tmpl = sv.synthetic_request(tmpl_rng, TABLE_SIZES, 2,
                                numerical=NUMERICAL)
    rt.warmup((tmpl.cats, tmpl.batch))

    rng = np.random.default_rng(seed + 1)

    def make_request(i):
        n = int(rng.integers(1, max_samples + 1))
        return sv.synthetic_request(rng, TABLE_SIZES, n,
                                    numerical=NUMERICAL)

    results = sv.drive(rt, make_request, qps, duration_s,
                       burst_positions=())
    s = rt.stats()
    served = [r for r in results if isinstance(r, sv.Served)]
    rec = {
        "world": WORLD,
        "tables": len(TABLE_SIZES),
        "dim": DIM,
        "qps_target": qps,
        "duration_s": duration_s,
        "rungs": list(rt.rungs),
        "requests_submitted": s["served"] + s["shed"] + s["expired"],
        "served": s["served"],
        "served_samples": s["served_samples"],
        "qps_achieved": round(len(served) / duration_s, 1),
        "latency_p50_ms": round(s["latency_p50_ms"] or 0.0, 3),
        "latency_p95_ms": round(s["latency_p95_ms"] or 0.0, 3),
        "latency_p99_ms": round(s["latency_p99_ms"] or 0.0, 3),
        "shed": s["shed"],
        "shed_frac": round(s["shed_frac_of_submitted"], 4),
        "deadline_missed": s["deadline_missed"],
        "pad_fraction": round(s["pad_fraction"], 4),
        "queue_depth_p95": round(s["queue_depth_p95"], 1),
        "flushes": s["flushes"],
        "warmup_compiles": s["warmup_compiles"],
        "steady_state_recompiles": s["steady_state_recompiles"],
        # pricing only: the int8 serving-table variant this latency
        # record would ride (future quantized-serving PR; also feeds
        # the ROADMAP-1 hot-row cache sizing)
        "int8_serving": price_int8_serving(
            de, rt.rungs[-1], param_dtype="float32",
            label=f"serving/world{WORLD}"),
    }
    return rec


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--qps", type=float, default=150.0,
                    help="target request arrival rate (default 150)")
    ap.add_argument("--duration", type=float, default=10.0,
                    help="seconds of load (default 10)")
    ap.add_argument("--max-batch", type=int, default=64,
                    help="largest padded-batch rung (default 64)")
    ap.add_argument("--max-samples", type=int, default=8,
                    help="largest request size in samples (default 8)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--smoke", action="store_true",
                    help="3s at 60 QPS (the DETPU_BENCH_SMOKE shape)")
    ap.add_argument("--json", metavar="PATH",
                    help="write the record as JSON (- for stdout)")
    args = ap.parse_args(argv)

    force_cpu(WORLD)
    sys.path.insert(0, REPO)
    if args.smoke:
        args.qps, args.duration = 60.0, 3.0
    try:
        rec = run_qps(args.qps, args.duration, args.max_batch,
                      args.max_samples, args.seed)
    except Exception as e:  # noqa: BLE001 - child tool: readable env-fail
        print(f"serve_bench: errored: {e}", file=sys.stderr)
        return 2
    print(f"serve_bench: world={rec['world']} qps={rec['qps_target']:.0f} "
          f"(achieved {rec['qps_achieved']:.0f}) p50/p95/p99 = "
          f"{rec['latency_p50_ms']:.1f}/{rec['latency_p95_ms']:.1f}/"
          f"{rec['latency_p99_ms']:.1f} ms, shed={rec['shed']}, "
          f"pad={rec['pad_fraction']:.2f}, recompiles="
          f"{rec['steady_state_recompiles']}")
    if args.json:
        payload = json.dumps(rec, indent=2)
        if args.json == "-":
            print(payload)
        else:
            with open(args.json, "w", encoding="utf-8") as fh:
                fh.write(payload + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())

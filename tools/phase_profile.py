#!/usr/bin/env python
"""Measure where the compiled hybrid step's milliseconds actually go —
and calibrate the schedule auditor's cost model against the clock.

``make schedule-audit`` proves the step's dependency STRUCTURE and
prices it from CHIP_SPECS byte arithmetic; this gate is its measured
twin (= ``make phase-profile``). For each reference case it

1. builds the hybrid train step EXACTLY as shipped (default metrics /
   nan-guard policy, the program the static gates audit) on the
   8-virtual-device CPU mesh, with concrete inputs;
2. times N unprofiled steps, then N steps each under its own
   ``jax.profiler.trace`` capture into a temp ``DETPU_PROFILE_DIR``-style
   directory (``DETPU_PHASE_PROFILE_DIR`` keeps the captures);
3. parses every capture (``utils/traceparse.py``), joins bare-name
   events against the compiled module's own ``metadata.op_name`` text
   (:class:`~distributed_embeddings_tpu.analysis.phase_profile.HloPhaseIndex`),
   and reduces them to a ``PhaseProfile``: per-phase p50/p95 ms, the
   exchange/lookup/apply/dense breakdown, measured a2a fraction,
   measured overlap, and a measured serialized/overlapped verdict per
   exchange — where "overlap" only credits DAG-independent compute, so
   lockstep skew across virtual devices cannot fake a win;
4. audits the SAME compiled text with ``analysis/schedule_audit.py`` and
   (a) cross-checks measured vs modeled classification
   (:func:`check_agreement` — the strict gate: a modeled-serialized
   exchange that measures overlapped means the model lies), and
   (b) renders the calibration drift table (:func:`calibrate`:
   measured/modeled ratio per phase, normalized so the CPU-proxy-vs-v5e
   speed factor cancels; >2x relative drift is flagged).

Profiling is strictly opt-in: the step program is untouched, unprofiled
steps are bitwise the shipped program, and the reported
``profile_overhead_frac`` prices what turning the profiler on costs.

    python tools/phase_profile.py --strict            # the full gate
    python tools/phase_profile.py --smoke --strict    # make verify's smoke
    python tools/phase_profile.py --json out.json --case dense

Exit codes: 0 clean; 1 agreement violations or unusable captures (with
``--strict``; add ``--fail-on-drift`` to also fail on calibration
flags); 2 usable-environment failure.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

try:  # imported as tools.phase_profile (tests)
    from tools._profcommon import build_case, cpu_mesh, force_cpu  # noqa: F401
except ImportError:  # run as a script: tools/ itself is sys.path[0]
    from _profcommon import build_case, cpu_mesh, force_cpu  # noqa: F401

#: (case, world, global batch, optimizer) — the measured twin of the
#: schedule auditor's sweep, restricted to the two cases the acceptance
#: pins: the serialized dense baseline and the streaming case whose
#: out/grad exchanges the auditor already classifies overlappable
CASES = (
    ("dense", 8, 256, "adagrad"),
    ("pipelined", 8, 256, "adagrad"),
    ("streaming", 8, 256, "adagrad"),
)
SMOKE_STEPS = 2


def concretize_case(name, world, batch):
    """``build_case``'s abstract shapes -> concrete arrays: categorical
    ids drawn inside each table's vocab (the streaming table draws from
    a 16x-capacity external space so admissions genuinely fire), floats
    from a fixed-seed normal."""
    import jax.numpy as jnp
    import numpy as np

    de, cats_abs, batch_abs, dp_abs, loss_fn = build_case(
        name, world, batch)
    rng = np.random.default_rng(0)
    configs = de.strategy.global_configs
    cats = []
    for cfg, a in zip(configs, cats_abs):
        stream = cfg.get("streaming")
        hi = (16 * int(stream["capacity"]) if stream
              else int(cfg["input_dim"]))
        cats.append(jnp.asarray(rng.integers(0, hi, size=a.shape),
                                jnp.int32))
    def conc(a):
        return jnp.asarray(rng.normal(size=a.shape), a.dtype)
    batch_tree = (conc(batch_abs[0]), conc(batch_abs[1]))
    dense_params = {k: conc(v) for k, v in dp_abs.items()}
    return de, cats, batch_tree, dense_params, loss_fn


def run_case(name: str, world: int, batch: int, opt_name: str,
             steps: int):
    """Profile one case; returns the JSON-able case record."""
    import optax

    from distributed_embeddings_tpu.analysis import (
        phase_profile as pp, schedule_audit as sa)
    from distributed_embeddings_tpu.parallel import (
        SparseAdagrad, SparseSGD, StreamingConfig, init_hybrid_state,
        init_streaming, make_hybrid_train_step)
    import jax

    emb_opt = SparseSGD() if opt_name == "sgd" else SparseAdagrad()
    tx = optax.sgd(0.5)
    de, cats, batch_tree, dense_params, loss_fn = concretize_case(
        name, world, batch)
    mesh = cpu_mesh(world)
    dynamic = StreamingConfig() if name == "streaming" else None
    state = init_hybrid_state(de, emb_opt, dense_params, tx,
                              jax.random.key(0), mesh=mesh)
    # the SHIPPED program: default metrics policy (env popped by
    # force_cpu -> off) and default nan-guard — the same defaults
    # build_abstract_step gives the static gates
    step = make_hybrid_train_step(de, loss_fn, tx, emb_opt, mesh=mesh,
                                  lr_schedule=0.3, dynamic=dynamic)
    sstate = init_streaming(de, dynamic) if dynamic else None
    args = (state, cats, batch_tree) + ((sstate,) if dynamic else ())
    txt = step.lower(*args).compile().as_text()
    index = pp.HloPhaseIndex(txt, world=world)
    label = f"{name}/world{world}/{opt_name}"
    sched = sa.audit_text(
        txt, label=label, world=world,
        backend=jax.default_backend())  # backend-ok: force_cpu ran first

    holder = {"state": state, "sstate": sstate}

    def run_one():
        if dynamic:
            loss, s, ss = step(holder["state"], cats, batch_tree,
                               holder["sstate"])
            holder["state"], holder["sstate"] = s, ss
        else:
            loss, s = step(holder["state"], cats, batch_tree)
            holder["state"] = s
        float(loss)  # force completion through the tunnel

    for _ in range(2):  # compile + reach steady state before any clock
        run_one()
    t0 = time.perf_counter()
    for _ in range(steps):
        run_one()
    plain_s = (time.perf_counter() - t0) / steps

    profile = pp.profile_steps(run_one, steps=steps, index=index,
                               world=world, label=label)
    # the profiler's cost ON the step (capture only; parsing happens off
    # the training path and is priced separately as parse_s)
    profiled_s = profile.capture_s or plain_s

    calib = pp.calibrate(profile, sched)
    agreement = pp.check_agreement(profile, sched)
    return {
        "label": label,
        "profile": profile.summary(),
        "phase_ms": profile.phase_ms,
        "modeled": {
            "serialized_collective_fraction":
                sched.serialized_collective_fraction,
            "collectives": [
                {"phase": c.phase, "classification": c.classification}
                for c in sched.collectives],
        },
        "calibration": calib.to_json(),
        "agreement_violations": agreement,
        "plain_step_ms": round(plain_s * 1e3, 3),
        "profiled_step_ms": round(profiled_s * 1e3, 3),
        "parse_ms_per_step": (round(profile.parse_s * 1e3, 3)
                              if profile.parse_s else None),
        "profile_overhead_frac": round(profiled_s / plain_s - 1.0, 4)
        if plain_s > 0 else None,
        "steps": steps,
    }, profile, calib


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--case",
                    choices=("dense", "pipelined", "streaming", "all"),
                    default="all")
    ap.add_argument("--steps", type=int, default=None,
                    help="profiled steps per case (default "
                         "DETPU_PHASE_PROFILE_STEPS)")
    ap.add_argument("--smoke", action="store_true",
                    help="dense case only, 2 steps — the make verify "
                         "smoke")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 on measured-vs-modeled classification "
                         "disagreement (the gate)")
    ap.add_argument("--fail-on-drift", action="store_true",
                    help="with --strict, also fail on calibration drift "
                         "flags (off by default: the CPU proxy "
                         "legitimately misprices phases the v5e model "
                         "prices for ICI)")
    ap.add_argument("--markdown", action="store_true",
                    help="print the full per-phase tables")
    ap.add_argument("--json", metavar="PATH",
                    help="dump the case records as JSON (- for stdout)")
    args = ap.parse_args(argv)

    cases = [c for c in CASES
             if args.case == "all" or c[0] == args.case]
    if args.smoke and args.case == "all":
        # smoke narrows the DEFAULT sweep to the dense case; an explicit
        # --case selection is honored (smoke then only shrinks steps)
        cases = [c for c in CASES if c[0] == "dense"]
    force_cpu(max(c[1] for c in cases))
    sys.path.insert(0, REPO)

    from distributed_embeddings_tpu.analysis.phase_profile import (
        PhaseProfileError, default_profile_steps)

    steps = args.steps or (SMOKE_STEPS if args.smoke
                           else default_profile_steps())
    records = []
    failed = 0
    for name, world, batch, opt_name in cases:
        try:
            rec, profile, calib = run_case(name, world, batch, opt_name,
                                           steps)
        except PhaseProfileError as e:
            print(f"phase_profile: {name}: {e}", file=sys.stderr)
            failed += 1
            continue
        except Exception as e:  # noqa: BLE001 - report, then env-fail
            print(f"phase_profile: {name}: errored: {e}", file=sys.stderr)
            return 2
        records.append(rec)
        prof = rec["profile"]
        print(f"phase_profile: {rec['label']}: wall p50 "
              f"{prof['step_wall_ms_p50']:.1f} ms | a2a in flight "
              f"{prof['a2a_frac'] * 100:.1f}% | concurrency "
              f"x{prof['concurrency']:.2f} | measured serialized frac "
              f"{prof['measured_serialized_fraction']} (modeled "
              f"{rec['modeled']['serialized_collective_fraction']:.3f}) | "
              f"overhead {rec['profile_overhead_frac']:+.1%} | "
              f"attribution {prof['resolved_frac'] * 100:.1f}%")
        if args.markdown:
            print(profile.markdown())
            print()
        print(calib.markdown())
        for v in rec["agreement_violations"]:
            print(f"phase_profile:   violation: {v}", file=sys.stderr)
            failed += 1
        if args.fail_on_drift:
            for f in rec["calibration"]["flagged"]:
                print(f"phase_profile:   drift: {f}", file=sys.stderr)
                failed += 1
    if args.json:
        payload = json.dumps(records, indent=2)
        if args.json == "-":
            print(payload)
        else:
            with open(args.json, "w", encoding="utf-8") as fh:
                fh.write(payload + "\n")
    if failed and args.strict:
        print(f"phase_profile: {failed} violation(s)", file=sys.stderr)
        return 1
    if not failed:
        print(f"phase_profile: OK ({len(records)} case(s): measured "
              "classification agrees with the schedule auditor's model)")
    return 0


if __name__ == "__main__":
    sys.exit(main())

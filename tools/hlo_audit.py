#!/usr/bin/env python
"""Audit the compiled hybrid step's per-phase HLO pass budget on a CPU mesh.

The jaxpr-level auditor (``tools/audit_step.py``) checks what we ASK the
compiler for; this gate checks what the compiler EMITS. It builds the
shared reference configurations (``tools/_profcommon.build_case`` — the
same shapes the profile tools and the SPMD auditor use), compiles the
hybrid train step abstractly on an N-virtual-device CPU mesh, parses the
optimized HLO (``metadata.op_name`` carries the ``obs.scope`` phases),
and enforces the declarative pass budgets of
:mod:`distributed_embeddings_tpu.analysis.hlo_census`:

* the ``dedup`` phase compiles to ZERO sort/segment-sum/scatter/gather
  passes whenever the sparse optimizer declares ``needs_dedup=False``
  (SparseSGD — the ROADMAP 3(a) pass cut), and is PRESENT (>= 1 sort)
  for stateful optimizers on the dedup-regime ``bigvocab`` shapes;
* at most 2 gather passes per (width, kind) lookup group (the packed
  gather plus its lane-extract companion);
* no float convert round-trips anywhere in the fp32 reference steps
  (an f32->bf16->f32 squeeze inside a phase silently drops mantissa).

Nothing executes on any backend — ``lower().compile()`` only.

    python tools/hlo_audit.py --strict            # make verify's gate
    python tools/hlo_audit.py --json report.json --config bigvocab
    python tools/hlo_audit.py --markdown          # per-phase budget tables

Exit codes: 0 clean; 1 violations found (only with ``--strict``);
2 usable-environment failure.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

try:  # imported as tools.hlo_audit (tests)
    from tools._profcommon import build_case, cpu_mesh, force_cpu  # noqa: F401
except ImportError:  # run as a script: tools/ itself is sys.path[0]
    from _profcommon import build_case, cpu_mesh, force_cpu  # noqa: F401


def shared_contracts():
    """Budgets every fp32 reference configuration must hold."""
    from distributed_embeddings_tpu.analysis import PassBudget

    return [
        PassBudget("*lookup_*", "gather", max_passes=2, per_path=True,
                   reason="one gather pass per (width, kind) lookup group "
                          "(+1 for the packed lane extract)"),
        PassBudget("*", "convert_roundtrip", max_passes=0,
                   reason="float round-trip converts squeeze mantissa; the "
                          "fp32 reference steps must have none"),
    ]


def census_case(name: str, world: int, batch: int, opt_name: str):
    """Census one (config, optimizer) pair against its contracts."""
    import optax

    from distributed_embeddings_tpu.analysis import (
        PassBudget, census_train_step, default_contracts)
    from distributed_embeddings_tpu.parallel import SparseAdagrad, SparseSGD

    opt = SparseSGD() if opt_name == "sgd" else SparseAdagrad()
    de, cats, batch_tree, dense_params, loss_fn = build_case(
        name, world, batch)
    contracts = list(default_contracts(opt)) + shared_contracts()
    if name == "pipelined":
        # the pipelined twin of the exchange budget: every
        # per-microbatch exchange phase compiles to EXACTLY one
        # all-to-all — per-microbatch op counts may not grow (a K=2
        # step is 2x the serialized per-phase budget, never more), and
        # a microbatch losing its exchange means the pipeline collapsed
        contracts.append(PassBudget(
            "*all_to_all_mb*", "all_to_all", max_passes=1, min_passes=1,
            per_path=True,
            reason="pipelined step: one exchange per microbatch phase — "
                   "per-microbatch op counts may not grow"))
    if name == "bigvocab" and opt_name != "sgd":
        # the dedup-regime shapes with a stateful optimizer: the pass must
        # EXIST (its disappearance would mean duplicates silently corrupt
        # the accumulator read-modify-write)
        contracts.append(PassBudget(
            "dedup", "sort", max_passes=8, min_passes=1,
            reason="stateful optimizer on dedup-regime shapes must compile "
                   "the sort-dedup pass"))
    return census_train_step(
        de, loss_fn, optax.sgd(0.5), opt, cats, batch_tree,
        mesh=cpu_mesh(world), lr_schedule=0.3,
        dense_params=dense_params, contracts=contracts,
        label=f"{name}/world{world}/{opt_name}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--config",
                    choices=("dense", "pipelined", "ragged", "row_sliced",
                             "bigvocab", "all"),
                    default="all")
    ap.add_argument("--world", type=int, default=8,
                    help="mesh positions (CPU virtual devices; default 8)")
    ap.add_argument("--batch", type=int, default=16, help="global batch")
    ap.add_argument("--sgd-dedup", action="store_true",
                    help="audit the DETPU_SGD_DEDUP=1 A/B variant (forces "
                         "the dedup pass back into the SGD build)")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 on any violation (the make verify gate)")
    ap.add_argument("--markdown", action="store_true",
                    help="print each case's per-phase budget table")
    ap.add_argument("--json", metavar="PATH",
                    help="dump the full reports as JSON (- for stdout)")
    args = ap.parse_args(argv)

    force_cpu(max(args.world, 1))
    if args.sgd_dedup:
        # unconditionally "1": preserving an inherited value would let an
        # exported DETPU_SGD_DEDUP=0 silently audit the default build
        # under the flag that promises the forced-dedup A/B variant
        os.environ["DETPU_SGD_DEDUP"] = "1"
    sys.path.insert(0, REPO)

    # (config, optimizer) sweep: the tier-1 shapes under the stateful
    # optimizer the SPMD auditor uses, plus the dedup-regime shapes under
    # BOTH families — the SGD build must be dedup-free, the Adagrad build
    # must not lose its dedup pass
    if args.config == "all":
        cases = [("dense", "adagrad"), ("pipelined", "adagrad"),
                 ("ragged", "adagrad"), ("row_sliced", "adagrad"),
                 ("bigvocab", "sgd"), ("bigvocab", "adagrad")]
    elif args.config == "bigvocab":
        cases = [("bigvocab", "sgd"), ("bigvocab", "adagrad")]
    else:
        cases = [(args.config, "adagrad")]

    reports = []
    failed = 0
    for name, opt_name in cases:
        if name == "pipelined" and (args.batch // max(args.world, 1)) % 2:
            print(f"hlo_audit: pipelined: skipped — per-device batch "
                  f"{args.batch // max(args.world, 1)} does not divide "
                  "into the case's K=2 microbatches (pick --batch "
                  "divisible by 2*world)")
            continue
        try:
            rep = census_case(name, args.world, args.batch, opt_name)
        except Exception as e:  # noqa: BLE001 - report, then fail the gate
            print(f"hlo_audit: {name}/{opt_name}: census errored: {e}",
                  file=sys.stderr)
            return 2
        reports.append(rep)
        status = "OK" if rep.ok else "FAIL"
        print(f"hlo_audit: {rep.label}: {status} "
              f"phases={len(rep.phases)} "
              f"dedup_sort={rep.passes('dedup', 'sort')} "
              f"dedup_scatter={rep.passes('dedup', 'scatter')} "
              f"lookup_gathers={rep.passes('*lookup_*', 'gather')} "
              f"a2a={rep.passes('*', 'all_to_all')}")
        if args.markdown:
            print(rep.markdown())
        for v in rep.violations:
            print(f"hlo_audit:   violation: {v}", file=sys.stderr)
            failed += 1
    if args.json:
        payload = json.dumps([r.to_json() for r in reports], indent=2)
        if args.json == "-":
            print(payload)
        else:
            with open(args.json, "w", encoding="utf-8") as fh:
                fh.write(payload + "\n")
    if failed and args.strict:
        print(f"hlo_audit: {failed} violation(s)", file=sys.stderr)
        return 1
    if not failed:
        print(f"hlo_audit: OK ({len(reports)} case(s) hold their compiled "
              "pass budgets)")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""End-to-end phase split of the ragged DLRM step (VERDICT r3 Weak #2/#4).

Splits the bench's ragged variant into dispatch overhead / embedding fwd /
dense fwd+bwd / sparse apply by timing nested subsets with the threaded-
state + readback methodology of bench.py.

Usage: python tools/profile_step.py [ragged|dense] [batch]
"""

import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax

import _profcommon as pc  # repo on sys.path + probe-first backend gate
from bench import BATCH, build_state, make_cfg, timed_loop
from _profcommon import CAP, CRITEO_KAGGLE_SIZES
from distributed_embeddings_tpu.models.dlrm import DLRMDense, bce_with_logits
from distributed_embeddings_tpu.ops.embedding_lookup import Ragged
from distributed_embeddings_tpu.parallel import (
    DistributedEmbedding, SparseSGD, make_hybrid_train_step)
from distributed_embeddings_tpu.utils import power_law_ids


def main():
    variant = sys.argv[1] if len(sys.argv) > 1 else "ragged"
    batch = int(sys.argv[2]) if len(sys.argv) > 2 else (
        16384 if variant == "ragged" else BATCH)
    # optional 3rd arg: parameter dtype (the bench headline is bf16 params)
    param_dtype = (jnp.bfloat16 if len(sys.argv) > 3
                   and sys.argv[3] == "bf16" else jnp.float32)
    # optional 4th arg: "uncapped" profiles the full Criteo-Kaggle vocabs
    uncapped = len(sys.argv) > 4 and sys.argv[4] == "uncapped"
    table_sizes = (list(CRITEO_KAGGLE_SIZES) if uncapped
                   else [min(s, CAP) for s in CRITEO_KAGGLE_SIZES])
    cfg = make_cfg(table_sizes, jnp.bfloat16)
    combiner = "sum" if variant == "ragged" else None
    de = DistributedEmbedding(cfg.embedding_configs(combiner=combiner),
                              world_size=1, compute_dtype=jnp.bfloat16)
    dense = DLRMDense(cfg)
    emb_opt = SparseSGD()
    tx = optax.sgd(0.005)

    rng = np.random.default_rng(0)
    if variant == "ragged":
        draws = []
        for s in table_sizes:
            hots = rng.integers(1, 31, size=batch)
            splits = np.zeros(batch + 1, np.int32)
            np.cumsum(hots, out=splits[1:])
            draws.append((s, splits))
        cap = max(int(sp[-1]) for _, sp in draws)
        cats = []
        for s, splits in draws:
            nnz = int(splits[-1])
            vals = np.zeros(cap, np.int32)
            vals[:nnz] = power_law_ids(rng, s, (nnz,))
            cats.append(Ragged(values=jnp.asarray(vals),
                               row_splits=jnp.asarray(splits)))
    else:
        cats = [jnp.asarray(power_law_ids(rng, s, (batch,)), jnp.int32)
                for s in table_sizes]

    state, num, labels = build_state(de, dense, cfg, emb_opt, tx,
                                     table_sizes, param_dtype, batch=batch)

    def loss_fn(dp, emb_outs, batch_):
        n, y = batch_
        return bce_with_logits(dense.apply(dp, n, emb_outs), y)

    # --- 0: dispatch floor (trivial jitted fn, threaded) ------------------
    @jax.jit
    def trivial(s, cats_, b_):
        return s.reshape(-1)[0] * 1.0001, s

    # a SMALL threaded state: threading a full slab would allocate a
    # second slab-sized output per call (no donation here) and OOM the
    # uncapped variant
    dt0 = timed_loop(trivial, jnp.zeros((128,), jnp.float32),
                     (cats, (num, labels)), iters=12)
    print(f"dispatch floor: {dt0*1e3:.1f} ms", flush=True)

    # Phases 1-2 thread a small token through the *inputs* (ids depend on
    # the previous iteration's output scalar) so dispatches can't
    # short-circuit, while params stay read-only — threading the params
    # themselves (v + bump) was measured to distort the phase by seconds.
    def _dep_cats(cats_, tok):
        bump = (tok * 0).astype(jnp.int32)

        def dep(c):
            if hasattr(c, "values"):  # Ragged
                return type(c)(values=c.values + bump,
                               row_splits=c.row_splits)
            return c + bump
        return [dep(c) for c in cats_]

    # --- 1: embedding forward only ---------------------------------------
    @jax.jit
    def fwd_only(tok, emb_params, cats_):
        outs, _ = de.forward_with_residuals(emb_params,
                                            _dep_cats(cats_, tok))
        tok2 = outs[0].astype(jnp.float32)[0, 0]
        return tok2, tok2

    # params are read-only in phases 1-2: reuse state's slabs (a second
    # de.init copy would double embedding HBM and can OOM)
    emb_params = state.emb_params
    dt1 = timed_loop(fwd_only, jnp.float32(0), (emb_params, cats), iters=8)
    print(f"embedding fwd: {dt1*1e3:.1f} ms (minus dispatch "
          f"{dt0*1e3:.0f})", flush=True)

    # --- 2: fwd + dense fwd/bwd (no sparse apply) -------------------------
    @jax.jit
    def fwd_dense(tok, emb_params, dp, cats_, batch_):
        outs, _ = de.forward_with_residuals(emb_params,
                                            _dep_cats(cats_, tok))
        loss, (dg, og) = jax.value_and_grad(loss_fn, argnums=(0, 1))(
            dp, outs, batch_)
        # the backward must feed the output or XLA dead-code-eliminates it
        gsum = sum(jnp.sum(g.astype(jnp.float32)) for g in og)
        gsum = gsum + jax.tree.reduce(
            lambda a, g: a + jnp.sum(g.astype(jnp.float32)), dg, 0.0)
        tok2 = loss + gsum * 1e-12
        return tok2, tok2

    dt2 = timed_loop(fwd_dense, jnp.float32(0),
                     (emb_params, state.dense_params, cats, (num, labels)),
                     iters=8)
    print(f"fwd + dense f/b: {dt2*1e3:.1f} ms", flush=True)

    # --- 3: full step -----------------------------------------------------
    step_fn = make_hybrid_train_step(de, loss_fn, tx, emb_opt,
                                     lr_schedule=0.005,
                                     with_metrics=False)
    dt3 = timed_loop(step_fn, state, (cats, (num, labels)), iters=8)
    print(f"full step: {dt3*1e3:.1f} ms -> {batch/dt3:.0f} samples/s",
          flush=True)
    print(f"phases: dispatch {dt0*1e3:.0f} | emb fwd {(dt1-dt0)*1e3:.0f} | "
          f"dense f/b {(dt2-dt1)*1e3:.0f} | sparse apply "
          f"{(dt3-dt2)*1e3:.0f}", flush=True)


if __name__ == "__main__":
    pc.ensure_backend()  # probe-first: a stalled tunnel must not hang us
    main()

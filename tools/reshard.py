#!/usr/bin/env python
"""Offline checkpoint re-shard: rewrite a train-state checkpoint to a new
sharding plan / world size.

The checkpoint format stores full LOGICAL tables (see
``utils/checkpoint.py``), so re-sharding never touches a device and never
rewrites table bytes — it re-fingerprints the plan in ``meta.json`` and
rebuilds the plan-dependent optimizer aux leaves, streamed file by file.
A v5e-16 checkpoint becomes an 8-chip checkpoint (or a
``telemetry_balanced`` one driven by measured traffic) in seconds, and a
round trip back to the original plan reproduces every array bit for bit.

Examples::

    # shrink a 16-way checkpoint to 8 ranks, same strategy
    python tools/reshard.py ckpt ckpt8 --world-size 8

    # what would moving to a row-sliced plan change? (no writes)
    python tools/reshard.py ckpt ckpt_rs --world-size 8 \\
        --row-slice 4000000 --dry-run

    # adopt a telemetry-balanced plan from the summary the resilient
    # driver flushes beside every checkpoint
    python tools/reshard.py ckpt ckpt_bal --world-size 8 \\
        --strategy telemetry_balanced --telemetry ckpt.telemetry.json

Exit codes: 0 = re-sharded (or dry run printed), 1 = failure (corrupt /
mismatched checkpoint, bad plan), 2 = usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _mib(b):
    return f"{b / 2**20:.2f} MiB"


def _print_diff(diff, verbose_tables=True):
    old_w, new_w = diff["world_size"]
    print(f"plan: world {old_w} -> {new_w}, strategy "
          f"{diff['strategy'][0]} -> {diff['strategy'][1]}")
    old_b = diff.get("per_rank_bytes_old")
    new_b = diff.get("per_rank_bytes_new")
    if new_b:
        print("per-rank parameter bytes:")
        for r in range(max(len(old_b or []), len(new_b))):
            o = old_b[r] if old_b and r < len(old_b) else None
            n = new_b[r] if r < len(new_b) else None
            delta = ""
            if o is not None and n is not None:
                delta = f"  (delta {n - o:+d} B)"
            print(f"  rank {r}: "
                  f"{_mib(o) if o is not None else '--':>12} -> "
                  f"{_mib(n) if n is not None else '--':>12}{delta}")
    moved = diff.get("moved_tables", [])
    if verbose_tables and moved:
        print(f"tables changing rank assignment: {moved}")
    else:
        print(f"{len(moved)} table(s) change rank assignment")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Rewrite a checkpoint to a new sharding plan / world "
                    "size (offline, host-only).")
    ap.add_argument("src", help="source checkpoint directory")
    ap.add_argument("dst", help="destination checkpoint directory")
    ap.add_argument("--world-size", type=int, required=True,
                    help="target number of model-parallel ranks")
    ap.add_argument("--strategy", default="basic",
                    choices=["basic", "memory_balanced", "memory_optimized",
                             "comm_balanced", "telemetry_balanced"],
                    help="target placement strategy (default: basic)")
    ap.add_argument("--column-slice-threshold", type=int, default=None,
                    help="max elements per slice before width-wise split")
    ap.add_argument("--row-slice", type=int, default=None,
                    help="max elements per slice before row-range split")
    ap.add_argument("--telemetry", default=None,
                    help="telemetry summary JSON feeding table loads to "
                         "the telemetry_balanced strategy (default: "
                         "<src>.telemetry.json when it exists)")
    ap.add_argument("--dry-run", action="store_true",
                    help="print the placement diff and per-rank byte "
                         "deltas; write nothing")
    args = ap.parse_args(argv)

    # jax-free planning: strategy.py and the checkpoint meta are all the
    # CLI needs to PLAN; the rewrite itself is file streaming
    from distributed_embeddings_tpu.parallel.strategy import (
        DistEmbeddingStrategy)
    from distributed_embeddings_tpu.utils import runtime
    from distributed_embeddings_tpu.utils.checkpoint import (
        reshard_checkpoint)

    # planning needs only meta.json; reshard_checkpoint CRC-verifies the
    # source before any rewrite, so table bytes are hashed exactly once
    try:
        with open(os.path.join(args.src, "meta.json"),
                  encoding="utf-8") as f:
            meta = json.load(f)
    except (OSError, ValueError) as e:
        print(f"reshard: source checkpoint invalid: {e}", file=sys.stderr)
        return 1
    tables = meta.get("tables")
    if tables is None:
        print("reshard: source meta.json has no table shapes — re-save "
              "the checkpoint with current code first", file=sys.stderr)
        return 1
    configs = [{"input_dim": int(v), "output_dim": int(d)}
               for v, d in tables]

    table_loads = None
    if args.strategy == "telemetry_balanced":
        tel_path = args.telemetry
        if tel_path is None:
            cand = args.src.rstrip(os.sep) + ".telemetry.json"
            if os.path.isfile(cand):
                tel_path = cand
        if tel_path is None:
            print("reshard: --strategy telemetry_balanced needs a "
                  "telemetry summary (--telemetry PATH, or a "
                  "<src>.telemetry.json beside the checkpoint)",
                  file=sys.stderr)
            return 2
        from distributed_embeddings_tpu.analysis.telemetry import (
            table_loads_from_summary)
        with open(tel_path, encoding="utf-8") as f:
            summary = json.load(f)
        table_loads = table_loads_from_summary(summary, len(configs))
        print(f"telemetry: table loads from {tel_path}: "
              f"{[int(x) for x in table_loads]}")

    try:
        strat = DistEmbeddingStrategy(
            configs, args.world_size, strategy=args.strategy,
            column_slice_threshold=args.column_slice_threshold,
            row_slice_threshold=args.row_slice,
            table_loads=table_loads)
        diff = reshard_checkpoint(args.src, args.dst, strat,
                                  dry_run=args.dry_run)
    except (runtime.RuntimeFault, ValueError) as e:
        print(f"reshard: {e}", file=sys.stderr)
        return 1
    _print_diff(diff)
    if args.dry_run:
        print("dry run: nothing written")
    else:
        print(f"re-sharded {args.src} -> {args.dst}")
    return 0


if __name__ == "__main__":
    sys.exit(main())

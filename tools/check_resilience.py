#!/usr/bin/env python
"""Verify gate for the self-healing driver (run by ``make verify``).

CPU end-to-end preemption drill, with a REAL external SIGTERM (not just
the in-process ``preempt@`` drill the unit tests use):

1. spawn a child training driver (tiny model, 12 slow-ish steps through
   ``parallel.resilient.run_resilient`` with checkpointing);
2. once the child reports a few completed steps, send it SIGTERM — the
   child must finish the in-flight step, checkpoint atomically, write the
   resume sentinel, and exit with ``PREEMPT_EXIT_CODE``;
3. relaunch the same command — it must auto-resume from the checkpoint
   (no batch replayed or skipped) and run to completion;
4. run the identical, uninterrupted driver in a fresh directory and
   assert both end at the same final step with CRC-identical final
   checkpoints (tables, optimizer components, dense state incl. the step
   counter) — the interrupted-run-equivalence acceptance criterion.

Exit 0 when the drill passes; 1 with a readable reason otherwise.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# the children template their own sys.path insert; main() imports the
# package too (PREEMPT_EXIT_CODE), so running as `python tools/...` from
# anywhere must work without a PYTHONPATH
sys.path.insert(0, REPO)

STEPS = 12
SIGTERM_AFTER_STEP = 3  # parent fires once the child reports this step

_CHILD = """
import sys, time
sys.path.insert(0, {repo!r})
import jax, optax, numpy as np, jax.numpy as jnp
jax.config.update('jax_platforms', 'cpu')
from distributed_embeddings_tpu.parallel import (
    DistributedEmbedding, SparseAdagrad, init_hybrid_state,
    make_hybrid_train_step, run_resilient)
configs = [{{"input_dim": 16 + 3 * i, "output_dim": 4}} for i in range(4)]
de = DistributedEmbedding(configs, world_size=1)
emb_opt = SparseAdagrad()
tx = optax.sgd(0.1)
state = init_hybrid_state(de, emb_opt,
                          {{"w": jnp.ones((4, 1), jnp.float32)}},
                          tx, jax.random.key(0))
def loss_fn(dp, outs, batch):
    x = sum(jnp.mean(o) for o in outs) * jnp.mean(dp["w"])
    return (x - jnp.mean(batch)) ** 2
def data(start):
    for i in range(start, {steps}):
        rng = np.random.default_rng(500 + i)
        cats = [jnp.asarray(rng.integers(0, c["input_dim"], 8), jnp.int32)
                for c in configs]
        yield cats, jnp.asarray(rng.normal(size=(8,)), jnp.float32)
step = make_hybrid_train_step(de, loss_fn, tx, emb_opt,
                              with_metrics=False, nan_guard=True)
def on_step(s, loss, metrics, st):
    print("RSTEP", s, flush=True)
    time.sleep({sleep})  # widen the SIGTERM window; a real step is not 0ms
    return False
r = run_resilient(step, state, data, de=de, checkpoint_dir={ckpt!r},
                  checkpoint_every_steps=2, resume=True,
                  emb_optimizer=emb_opt, dense_tx=tx, on_step=on_step,
                  exit_on_preempt=True)
print("FINAL", r.step, flush=True)
"""


def _spawn(ckpt, sleep=0.0):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("DETPU_FAULT", None)
    code = _CHILD.format(repo=REPO, ckpt=ckpt, steps=STEPS, sleep=sleep)
    # stderr merged into stdout: phase 1 reads stdout line-by-line, and a
    # separate never-drained stderr pipe could fill and deadlock a
    # stderr-heavy child
    return subprocess.Popen([sys.executable, "-c", code], env=env,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)


def _drain(proc, timeout=600):
    try:
        out, _ = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        out, _ = proc.communicate()
        return None, out
    return proc.returncode, out


def _final_crcs(ckpt):
    with open(os.path.join(ckpt, "meta.json"), encoding="utf-8") as f:
        return json.load(f)["files"]


def main() -> int:
    from distributed_embeddings_tpu.parallel.resilient import (
        PREEMPT_EXIT_CODE)

    errors = []
    with tempfile.TemporaryDirectory(prefix="detpu_resilience_") as tmp:
        ckpt = os.path.join(tmp, "ck")
        ref_ckpt = os.path.join(tmp, "ref")

        # 1+2: spawn, SIGTERM once a few steps completed. The line reader
        # runs under a SIGALRM watchdog: a wedged child must fail the
        # gate with a diagnostic, not hang `make verify` forever.
        proc = _spawn(ckpt, sleep=0.2)
        fired = False

        def _watchdog(signum, frame):
            raise TimeoutError

        old = signal.signal(signal.SIGALRM, _watchdog)
        signal.alarm(600)
        try:
            for line in proc.stdout:
                if line.startswith("RSTEP"):
                    step = int(line.split()[1])
                    if step >= SIGTERM_AFTER_STEP and not fired:
                        proc.send_signal(signal.SIGTERM)
                        fired = True
                if line.startswith("FINAL"):
                    break
        except TimeoutError:
            proc.kill()
            _drain(proc, timeout=10)
            return _fail(["phase-1 child produced no progress for 600s "
                          "(wedged step?) — killed"])
        finally:
            signal.alarm(0)
            signal.signal(signal.SIGALRM, old)
        rc, out = _drain(proc)
        if not fired:
            return _fail(["child finished before the SIGTERM window — "
                          "raise STEPS or the per-step sleep"])
        if rc != PREEMPT_EXIT_CODE:
            return _fail([f"preempted child exited rc={rc} (want "
                          f"{PREEMPT_EXIT_CODE}): {out.strip()[-500:]}"])
        if not os.path.exists(ckpt + ".resume.json"):
            return _fail(["preempted child left no resume sentinel"])

        # 3: relaunch -> auto-resume -> completion
        rc, out = _drain(_spawn(ckpt))
        if rc != 0:
            return _fail([f"resumed child failed rc={rc}: "
                          f"{out.strip()[-500:]}"])
        if f"FINAL {STEPS}" not in out:
            errors.append(f"resumed child did not reach step {STEPS}: "
                          f"{out.splitlines()[-3:]}")
        resumed_first = [int(line.split()[1]) for line in out.splitlines()
                         if line.startswith("RSTEP")][:1]
        if resumed_first and resumed_first[0] <= SIGTERM_AFTER_STEP:
            errors.append(
                f"resume replayed step {resumed_first[0]} — the "
                "checkpointed steps must not re-train")
        if os.path.exists(ckpt + ".resume.json"):
            errors.append("completed run left the resume sentinel behind")

        # 4: uninterrupted reference must match bit for bit
        rc, out = _drain(_spawn(ref_ckpt))
        if rc != 0:
            return _fail([f"reference child failed rc={rc}: "
                          f"{out.strip()[-500:]}"])
        if not errors and _final_crcs(ckpt) != _final_crcs(ref_ckpt):
            errors.append(
                "final checkpoints differ between the interrupted+resumed "
                "run and the uninterrupted run (CRC manifests unequal) — "
                "resume is not trajectory-exact")
    if errors:
        return _fail(errors)
    print("check_resilience: OK (SIGTERM'd child checkpointed + exited "
          f"{PREEMPT_EXIT_CODE}, resumed to step {STEPS}, final state "
          "CRC-identical to the uninterrupted run)")
    return 0


def _fail(errors) -> int:
    for e in errors:
        print(f"check_resilience: {e}", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())

# makes `python -m tools.detlint` work from the repo root; the individual
# tools stay directly runnable as scripts

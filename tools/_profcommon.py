"""Shared setup for the ``tools/profile_*.py`` microbenchmarks.

Every profile tool used to open with the same boilerplate: a ``sys.path``
insert, the capped Criteo-Kaggle vocab table, and copies of the
readback-forced repetition-slope timing helpers (``docs/perf_tpu.md``
"Methodology") — and, critically, a bare first backend touch. The latter
is the exact bug that motivated PR 1: a stalled device tunnel turns the
first ``jit`` dispatch into a silent multi-minute hang. :func:`ensure_backend`
routes every tool through ``utils.runtime.probe_backend`` (a watched
subprocess with a hard timeout) so a dead backend fails in seconds with a
clear message instead.

Usage, at the top of a tool::

    import _profcommon as pc
    ...
    if __name__ == "__main__":
        pc.ensure_backend()   # probe-first; exits 2 if unavailable
        main(sys.argv[1:])
"""

from __future__ import annotations

import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# the bench's capped Criteo-Kaggle vocabs — the shapes every profile tool
# times (kept here so the five tools cannot drift apart)
CAP = 2_000_000
CRITEO_KAGGLE_SIZES = [
    1460, 583, 10131227, 2202608, 305, 24, 12517, 633, 3, 93145, 5683,
    8351593, 3194, 27, 14992, 5461306, 10, 5652, 2173, 4, 7046547, 18, 15,
    286181, 105, 142572,
]
CAP_SIZES = [min(s, CAP) for s in CRITEO_KAGGLE_SIZES]


def ensure_backend(timeout_s: float | None = None):
    """Probe the backend BEFORE this process's first jax touch.

    Runs ``utils.runtime.probe_backend`` (subprocess + hard timeout, the
    PR 1 mechanism) and exits 2 with a readable message when the backend
    is unavailable — a profile tool must never hang on a stalled tunnel.
    On success also arms the observability hooks (recompile counter,
    ``DETPU_PROFILE_PORT`` server) so captured profiles carry the named
    scopes this repo's step is annotated with. Returns the
    ``BackendProbe``.
    """
    from distributed_embeddings_tpu.utils import obs, runtime

    if timeout_s is None:
        timeout_s = float(os.environ.get("DETPU_PROBE_TIMEOUT_S", "120"))
    probe = runtime.probe_backend(timeout_s=timeout_s)
    if not probe.ok:
        print(f"profile tool: backend unavailable ({probe.error}); "
              "fix the tunnel or set JAX_PLATFORMS=cpu to profile the CPU "
              "lowering", file=sys.stderr)
        sys.exit(2)
    print(f"backend: {probe.platform} x{probe.device_count} "
          f"(probed in {probe.elapsed_s:.1f}s)", flush=True)
    obs.install_compile_listener()
    obs.maybe_start_server()
    return probe


def readback(x) -> float:
    """Force completion through the device tunnel with a one-element host
    fetch (``block_until_ready`` can be a no-op through remote tunnels —
    ``docs/perf_tpu.md``)."""
    import jax.numpy as jnp

    return float(jnp.asarray(x).reshape(-1)[0])


def slope(make_fn, args, iters_hi: int = 3) -> float:
    """Repetition-slope timing in ms: jit ``make_fn(1)`` and
    ``make_fn(iters_hi)`` (K in-jit repetitions of the phase under test),
    time both after compile, report the per-repetition slope — dispatch
    constants and readback cost cancel."""
    import jax

    f1 = jax.jit(make_fn(1))
    fh = jax.jit(make_fn(iters_hi))
    readback(f1(*args))  # compile
    readback(fh(*args))
    t0 = time.perf_counter(); readback(f1(*args)); t1 = time.perf_counter()
    readback(fh(*args)); t2 = time.perf_counter()
    return ((t2 - t1) - (t1 - t0)) / (iters_hi - 1) * 1e3


def slope_donate(make_fn, args, iters_hi: int = 3) -> float:
    """:func:`slope` with the FIRST argument donated and re-threaded
    between calls — for phases that update a multi-GB slab in place
    (without donation XLA copies the slab and the program OOMs). The
    ``make_fn(k)`` body must return ``(scalar, slab)``."""
    import jax

    f1 = jax.jit(make_fn(1), donate_argnums=(0,))
    fh = jax.jit(make_fn(iters_hi), donate_argnums=(0,))
    state = {"args": args}

    def run(f):
        s, sl = f(*state["args"])
        state["args"] = (sl,) + state["args"][1:]
        return readback(s)

    run(f1); run(fh)
    t0 = time.perf_counter(); run(f1); t1 = time.perf_counter()
    run(fh); t2 = time.perf_counter()
    return ((t2 - t1) - (t1 - t0)) / (iters_hi - 1) * 1e3

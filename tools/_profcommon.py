"""Shared setup for the ``tools/profile_*.py`` microbenchmarks.

Every profile tool used to open with the same boilerplate: a ``sys.path``
insert, the capped Criteo-Kaggle vocab table, and copies of the
readback-forced repetition-slope timing helpers (``docs/perf_tpu.md``
"Methodology") — and, critically, a bare first backend touch. The latter
is the exact bug that motivated PR 1: a stalled device tunnel turns the
first ``jit`` dispatch into a silent multi-minute hang. :func:`ensure_backend`
routes every tool through ``utils.runtime.probe_backend`` (a watched
subprocess with a hard timeout) so a dead backend fails in seconds with a
clear message instead.

Usage, at the top of a tool::

    import _profcommon as pc
    ...
    if __name__ == "__main__":
        pc.ensure_backend()   # probe-first; exits 2 if unavailable
        main(sys.argv[1:])
"""

from __future__ import annotations

import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# the bench's capped Criteo-Kaggle vocabs — the shapes every profile tool
# times (kept here so the five tools cannot drift apart)
CAP = 2_000_000
CRITEO_KAGGLE_SIZES = [
    1460, 583, 10131227, 2202608, 305, 24, 12517, 633, 3, 93145, 5683,
    8351593, 3194, 27, 14992, 5461306, 10, 5652, 2173, 4, 7046547, 18, 15,
    286181, 105, 142572,
]
CAP_SIZES = [min(s, CAP) for s in CRITEO_KAGGLE_SIZES]

# Criteo-1TB (MLPerf DLRM) vocab sizes + the reference's "+1" convention
# (its examples/dlrm/main.py loads model_size.json and adds 1): 26 tables,
# ~187.8M rows total — the real shapes behind the ≥2M samples/s v5e-16
# north star. Shared here so bench.py, the capacity auditor, and the
# dress-rehearsal tooling price the SAME vector (they used to drift).
CRITEO_1TB_SIZES = [s + 1 for s in [
    39884406, 39043, 17289, 7420, 20263, 3, 7120, 1543, 63, 38532951,
    2953546, 403346, 10, 2208, 11938, 155, 4, 976, 14, 39979771, 25641295,
    39664984, 585935, 12972, 108, 36,
]]
# Column-slice threshold (elements) of the criteo1tb reference case: the
# five ~25-40M-row tables (3.3-5.1e9 elements at dim 128) split 4-way into
# width-32 slices, putting every per-rank apply slab under the measured
# scatter cliff at world=16 bf16 (analysis/plan_audit.py enforces this);
# the <=1.4e9-element tables stay whole.
CRITEO1TB_COL_SLICE = 1_400_000_000
CRITEO1TB_DIM = 128
CRITEO1TB_BATCH = 65536
CRITEO1TB_WORLD = 16


def build_case(name: str, world: int, batch: int):
    """One reference DistributedEmbedding configuration: ``(de, cat_inputs,
    batch_tree, dense_params, loss_fn)`` with abstract (ShapeDtypeStruct)
    inputs — the shapes the static tools audit. Shared by
    ``tools/audit_step.py`` (jaxpr-level SPMD contract) and
    ``tools/hlo_audit.py`` (optimized-HLO pass budgets) so both gates and
    the profile tools cannot drift apart.

    Cases: ``dense`` / ``ragged`` / ``row_sliced`` (the tier-1 shapes),
    ``pipelined`` — the dense shapes under ``pipelined_schedule(2)``,
    the K-microbatch case the schedule auditor certifies declared
    overlaps on and the phase profiler measures —
    ``bigvocab`` — vocab rows >> the id stream, so stateful sparse
    optimizers compile their sort-dedup path instead of the dense-apply
    regime (the configuration the dedup pass budget is pinned on) —
    ``streaming`` — the dense shapes plus one dynamic-vocab table
    (``"streaming"`` config entry), so the static gates can audit the
    slot-map remap/commit phases too (build its step with
    ``dynamic=StreamingConfig(...)``) — and
    ``criteo1tb`` — the REAL 26-table Criteo-1TB vocab vector at dim 128
    with the reference column-slice threshold (``CRITEO1TB_COL_SLICE``),
    the shapes the plan-time capacity auditor (``tools/plan_audit.py``)
    enforces its HBM/cliff contracts at. Building it materializes
    nothing (plans are host metadata; inputs are ShapeDtypeStructs), but
    only the static tools should ask for it — ``de.init`` at these
    shapes is 48 GB of bf16.
    """
    import jax
    import jax.numpy as jnp

    from distributed_embeddings_tpu.ops.embedding_lookup import Ragged
    from distributed_embeddings_tpu.parallel import DistributedEmbedding

    def loss_fn(dp, emb_outs, b):
        n, y = b
        x = jnp.concatenate([e.reshape(e.shape[0], -1) for e in emb_outs],
                            axis=1)
        return jnp.mean((x @ dp["w"] + n @ dp["v"] - y) ** 2)

    def dense_cats(configs):
        cats = []
        for cfg in configs:
            hot = 1 if cfg["combiner"] is None else 3
            shape = (batch,) if hot == 1 else (batch, hot)
            cats.append(jax.ShapeDtypeStruct(shape, jnp.int32))
        return cats

    if name == "dense":
        configs = [{"input_dim": 20 + 6 * i, "output_dim": 4,
                    "combiner": ["sum", None, "mean"][i % 3]}
                   for i in range(10)]
        de = DistributedEmbedding(configs, world_size=world)
        cats = dense_cats(configs)
    elif name == "pipelined":
        # the dense shapes under the K=2 software-pipelined schedule
        # (parallel/schedule.py): the case the schedule auditor certifies
        # the DECLARED microbatch overlaps on, the HLO census pins the
        # per-microbatch pass budgets on, and the measured phase profile
        # confirms on the clock (ROADMAP item 2)
        from distributed_embeddings_tpu.parallel.schedule import (
            pipelined_schedule)

        configs = [{"input_dim": 20 + 6 * i, "output_dim": 4,
                    "combiner": ["sum", None, "mean"][i % 3]}
                   for i in range(10)]
        de = DistributedEmbedding(configs, world_size=world,
                                  schedule=pipelined_schedule(2))
        cats = dense_cats(configs)
    elif name == "bigvocab":
        # stream << rows: SparseAdagrad's dense_apply_ratio cost model
        # (stream * ratio > slab rows) cannot trigger, so the compiled
        # program holds the sort + segment-sum dedup passes the census
        # budgets; under SparseSGD the same shapes must compile dedup-free
        configs = [{"input_dim": 5000 + 400 * i, "output_dim": 8,
                    "combiner": ["sum", None, "mean"][i % 3]}
                   for i in range(10)]
        de = DistributedEmbedding(configs, world_size=world)
        cats = dense_cats(configs)
    elif name == "streaming":
        # the dense shapes plus ONE dynamic-vocab table: capacity slots +
        # shared bucket rows (input_dim = capacity + buckets, the
        # streaming slab contract) — the shapes the schedule auditor's
        # streaming case certifies
        configs = [{"input_dim": 20 + 6 * i, "output_dim": 4,
                    "combiner": ["sum", None, "mean"][i % 3]}
                   for i in range(9)]
        configs.append({"input_dim": 4096 + 64, "output_dim": 4,
                        "combiner": "sum",
                        "streaming": {"capacity": 4096, "buckets": 64}})
        de = DistributedEmbedding(configs, world_size=world)
        cats = dense_cats(configs)
    elif name == "criteo1tb":
        # mp input + comm_balanced: the ROADMAP item-4 deployment shape
        # (the dlrm example's defaults at scale). dp_input stays True in
        # the returned layer so the case also traces on the generic
        # harnesses; the capacity audit prices the mp-input variant via
        # audit_plan(dp_input=False).
        configs = [{"input_dim": int(s), "output_dim": CRITEO1TB_DIM,
                    "combiner": None} for s in CRITEO_1TB_SIZES]
        de = DistributedEmbedding(configs, world_size=world,
                                  strategy="comm_balanced",
                                  column_slice_threshold=CRITEO1TB_COL_SLICE)
        cats = dense_cats(configs)
    elif name == "ragged":
        configs = [{"input_dim": 40 + 7 * i, "output_dim": 8,
                    "combiner": "sum" if i % 2 else "mean"}
                   for i in range(8)]
        de = DistributedEmbedding(configs, world_size=world)
        local_b = batch // max(world, 1)
        cap = local_b * 4
        cats = [Ragged(values=jax.ShapeDtypeStruct((world * cap,),
                                                   jnp.int32),
                       row_splits=jax.ShapeDtypeStruct(
                           (world * (local_b + 1),), jnp.int32))
                for _ in configs]
    elif name == "row_sliced":
        configs = [
            {"input_dim": 100, "output_dim": 8, "combiner": None},
            {"input_dim": 30, "output_dim": 8, "combiner": "sum"},
            {"input_dim": 100, "output_dim": 8, "combiner": "mean"},
            {"input_dim": 40, "output_dim": 8, "combiner": None},
            {"input_dim": 26, "output_dim": 8, "combiner": "sum"},
            {"input_dim": 100, "output_dim": 4, "combiner": "sum"},
            {"input_dim": 22, "output_dim": 8, "combiner": None},
            {"input_dim": 24, "output_dim": 8, "combiner": None},
        ]
        # the 100-row tables split into 4 row-range slices
        de = DistributedEmbedding(configs, world_size=world,
                                  row_slice=100 * 8 // 4 + 1)
        cats = dense_cats(configs)
    else:
        raise ValueError(f"unknown config {name!r}")

    cols = sum(int(c["output_dim"]) for c in configs)
    dense_params = {"w": jax.ShapeDtypeStruct((cols, 1), jnp.float32),
                    "v": jax.ShapeDtypeStruct((3, 1), jnp.float32)}
    batch_tree = (jax.ShapeDtypeStruct((batch, 3), jnp.float32),
                  jax.ShapeDtypeStruct((batch, 1), jnp.float32))
    return de, cats, batch_tree, dense_params, loss_fn


def force_cpu(devices: int) -> None:
    """Pin the static audit tools to an N-virtual-device CPU backend.

    Must run before the process's first jax import: the auditors are pure
    static tools and must never touch (or wait on) an accelerator
    backend. Shared by ``tools/audit_step.py`` and ``tools/hlo_audit.py``
    so the two gates cannot drift in WHICH program they audit: an
    inherited ``DETPU_OBS=1`` / ``DETPU_TELEMETRY=1`` would flip the
    audited step to an instrumented variant, and an exported
    ``DETPU_SGD_DEDUP=1`` would force the dedup pass back into the SGD
    builds — both gates audit the default program; the variants are
    audited explicitly (``--with-metrics``/``--with-telemetry``,
    ``--sgd-dedup``, tests)."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={devices}")
    for knob in ("DETPU_OBS", "DETPU_TELEMETRY", "DETPU_SGD_DEDUP"):
        os.environ.pop(knob, None)


def cpu_mesh(world: int):
    """A ``("data",)`` Mesh over the first ``world`` host-platform devices
    (``None`` for world <= 1). :func:`force_cpu` must have run first."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    if world <= 1:
        return None
    devs = jax.devices()  # backend-ok: force_cpu ran before jax import
    if len(devs) < world:
        raise RuntimeError(
            f"host platform exposes {len(devs)} devices < {world}")
    return Mesh(np.array(devs[:world]), ("data",))


def ensure_backend(timeout_s: float | None = None):
    """Probe the backend BEFORE this process's first jax touch.

    Runs ``utils.runtime.probe_backend`` (subprocess + hard timeout, the
    PR 1 mechanism) and exits 2 with a readable message when the backend
    is unavailable — a profile tool must never hang on a stalled tunnel.
    On success also arms the observability hooks (recompile counter,
    ``DETPU_PROFILE_PORT`` server) so captured profiles carry the named
    scopes this repo's step is annotated with. Returns the
    ``BackendProbe``.
    """
    from distributed_embeddings_tpu.utils import obs, runtime

    if timeout_s is None:
        timeout_s = float(os.environ.get("DETPU_PROBE_TIMEOUT_S", "120"))
    probe = runtime.probe_backend(timeout_s=timeout_s)
    if not probe.ok:
        print(f"profile tool: backend unavailable ({probe.error}); "
              "fix the tunnel or set JAX_PLATFORMS=cpu to profile the CPU "
              "lowering", file=sys.stderr)
        sys.exit(2)
    print(f"backend: {probe.platform} x{probe.device_count} "
          f"(probed in {probe.elapsed_s:.1f}s)", flush=True)
    obs.install_compile_listener()
    obs.maybe_start_server()
    return probe


def readback(x) -> float:
    """Force completion through the device tunnel with a one-element host
    fetch (``block_until_ready`` can be a no-op through remote tunnels —
    ``docs/perf_tpu.md``)."""
    import jax.numpy as jnp

    return float(jnp.asarray(x).reshape(-1)[0])


def slope(make_fn, args, iters_hi: int = 3) -> float:
    """Repetition-slope timing in ms: jit ``make_fn(1)`` and
    ``make_fn(iters_hi)`` (K in-jit repetitions of the phase under test),
    time both after compile, report the per-repetition slope — dispatch
    constants and readback cost cancel."""
    import jax

    f1 = jax.jit(make_fn(1))
    fh = jax.jit(make_fn(iters_hi))
    readback(f1(*args))  # compile
    readback(fh(*args))
    t0 = time.perf_counter(); readback(f1(*args)); t1 = time.perf_counter()
    readback(fh(*args)); t2 = time.perf_counter()
    return ((t2 - t1) - (t1 - t0)) / (iters_hi - 1) * 1e3


def slope_donate(make_fn, args, iters_hi: int = 3) -> float:
    """:func:`slope` with the FIRST argument donated and re-threaded
    between calls — for phases that update a multi-GB slab in place
    (without donation XLA copies the slab and the program OOMs). The
    ``make_fn(k)`` body must return ``(scalar, slab)``."""
    import jax

    f1 = jax.jit(make_fn(1), donate_argnums=(0,))
    fh = jax.jit(make_fn(iters_hi), donate_argnums=(0,))
    state = {"args": args}

    def run(f):
        s, sl = f(*state["args"])
        state["args"] = (sl,) + state["args"][1:]
        return readback(s)

    run(f1); run(fh)
    t0 = time.perf_counter(); run(f1); t1 = time.perf_counter()
    run(fh); t2 = time.perf_counter()
    return ((t2 - t1) - (t1 - t0)) / (iters_hi - 1) * 1e3

#!/usr/bin/env python
"""Thin shim: the no-eager-backend gate now lives in detlint.

The original AST walker moved verbatim into
``tools/detlint/rules/eager_backend.py`` (one rule of the unified lint
framework, run by ``make lint`` / ``python -m tools.detlint``). This shim
keeps the historical ``make verify`` entry point green while callers
migrate: it runs exactly that one rule and reports in the old format.

Rules (see the rule module's docstring): backend-touching jax calls
(``jax.devices``/``device_count``/...) at module scope always fail;
inside a function they need a same-line ``# backend-ok: <reason>``
annotation. No jax import needed — pure AST.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools import detlint  # noqa: E402


# the rule walks whatever exists; the gate additionally pins that the two
# historical entry points are PRESENT — a renamed __graft_entry__.py (the
# r5 rc=124 file) must not make the protection vanish vacuously
REQUIRED_FILES = ("__graft_entry__.py", "bench.py")


def main() -> int:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    missing = [name for name in REQUIRED_FILES
               if not os.path.exists(os.path.join(repo, name))]
    for name in missing:
        print(f"check_no_eager_backend: {name}: checked file missing",
              file=sys.stderr)
    if missing:
        return 1
    findings = detlint.run(rule_names=["eager-backend"])
    for f in findings:
        print(f"check_no_eager_backend: {f.path}:{f.line}: {f.message}",
              file=sys.stderr)
    if not findings:
        print("check_no_eager_backend: OK (detlint rule 'eager-backend' "
              "clean over __graft_entry__.py, bench.py, tools/**)")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())

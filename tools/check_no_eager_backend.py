#!/usr/bin/env python
"""Static gate: no eager jax backend touch in the driver entry points.

Round 5's artifacts died rc=124 because ``__graft_entry__.py`` called
``jax.device_count()`` in the parent process before deciding anything —
a >2 min hang when the TPU tunnel stalls (VERDICT r5). The entry points
were rewired to decide purely from ``utils.runtime.probe_backend`` (a
watched subprocess with a timeout); this check keeps the bare calls from
creeping back in.

Rules, per checked file (``__graft_entry__.py``, ``bench.py``, and — since
the observability PR routed them through ``probe_backend`` — every
``tools/*.py``):

* a backend-touching call (``jax.devices``, ``jax.device_count``,
  ``jax.local_devices``, ``jax.local_device_count``,
  ``jax.default_backend``) at MODULE scope (incl. the ``__main__`` block)
  always fails — it runs before any probe can;
* inside a function it must carry a ``# backend-ok: <reason>`` annotation
  on the same line, asserting the call only executes in a probe-cleared
  context (e.g. the dryrun child process).

Runs from ``make verify``. No jax import needed — pure AST.
"""

from __future__ import annotations

import ast
import os
import sys

BACKEND_ATTRS = {"devices", "device_count", "local_devices",
                 "local_device_count", "default_backend"}
MARKER = "backend-ok:"
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHECKED_FILES = ("__graft_entry__.py", "bench.py")


def _tool_files():
    """Every ``tools/*.py`` (this checker included — it holds itself to
    its own rule; trivially, since it never imports jax)."""
    d = os.path.join(REPO, "tools")
    return tuple(os.path.join("tools", name) for name in sorted(
        os.listdir(d)) if name.endswith(".py"))


def _is_backend_call(node: ast.Call) -> bool:
    f = node.func
    return (isinstance(f, ast.Attribute) and f.attr in BACKEND_ATTRS
            and isinstance(f.value, ast.Name) and f.value.id == "jax")


def check_file(path: str) -> list:
    with open(path, encoding="utf-8") as fh:
        src = fh.read()
    lines = src.splitlines()
    errors = []

    def walk(node, in_function):
        for child in ast.iter_child_nodes(node):
            child_in_fn = in_function or isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda))
            if isinstance(child, ast.Call) and _is_backend_call(child):
                where = f"{os.path.relpath(path, REPO)}:{child.lineno}"
                line = lines[child.lineno - 1]
                if not in_function:
                    errors.append(
                        f"{where}: module-scope jax.{child.func.attr}() — "
                        "runs before any backend probe and hangs the "
                        "process on a stalled tunnel; route through "
                        "utils.runtime.probe_backend/require_devices")
                elif MARKER not in line:
                    errors.append(
                        f"{where}: jax.{child.func.attr}() without a "
                        f"'# {MARKER} <reason>' annotation — either probe "
                        "first (utils.runtime) or annotate why this only "
                        "executes in a probe-cleared context")
            walk(child, child_in_fn)

    walk(ast.parse(src, path), False)
    return errors


def main() -> int:
    errors = []
    checked = CHECKED_FILES + _tool_files()
    for name in checked:
        path = os.path.join(REPO, name)
        if not os.path.exists(path):
            errors.append(f"{name}: checked file missing")
            continue
        errors.extend(check_file(path))
    for e in errors:
        print(f"check_no_eager_backend: {e}", file=sys.stderr)
    if not errors:
        print(f"check_no_eager_backend: OK ({len(checked)} files clean: "
              f"{', '.join(CHECKED_FILES)} + tools/*.py)")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Verify gate for the observability layer (run by ``make verify``).

Two checks, both in clean subprocesses so they test what a user's process
actually does:

1. ``utils.obs`` imports cleanly under ``JAX_PLATFORMS=cpu`` and — like
   ``utils.runtime`` — without pulling jax in at module scope (importing
   the obs/counters half must never risk a backend touch).
2. ``DETPU_OBS=1 DETPU_BENCH_SMOKE=1 python bench.py`` emits a parseable
   step-metrics sidecar containing the acceptance fields: exchange bytes,
   per-rank routed-id counts, capacity-overflow counters, and a recompile
   count (the ISSUE 2 acceptance criterion, kept green by CI).

Exit 0 when both pass; 1 with a readable reason otherwise.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

REQUIRED_METRIC_FIELDS = ("id_a2a_bytes", "ids_routed", "id_overflow")


def check_import() -> list:
    """obs must import (and count) cleanly under ``JAX_PLATFORMS=cpu`` in
    a fresh process, and the module source must not import jax at module
    scope (the :mod:`utils.runtime` never-touch-a-backend-at-import
    contract; the *package* path unavoidably imports jax via compat). The
    static half is the detlint ``module-scope-jax`` rule — shared here so
    the AST walking lives in exactly one place."""
    sys.path.insert(0, REPO)
    from tools import detlint

    errors = [
        f"{f.path}:{f.line}: {f.message}"
        for f in detlint.run(rule_names=["module-scope-jax"])]
    code = (
        "import distributed_embeddings_tpu.utils.obs as obs\n"
        "obs.counter_inc('selftest'); assert obs.counters()['selftest'] == 1\n"
        "print('obs import OK')\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    env.pop("DETPU_OBS", None)
    try:
        r = subprocess.run([sys.executable, "-c", code], env=env, cwd=REPO,
                           capture_output=True, text=True, timeout=120)
    except subprocess.TimeoutExpired:
        errors.append("obs import check timed out after 120s")
        return errors
    if r.returncode != 0:
        errors.append(f"obs import check failed (rc={r.returncode}): "
                      f"{(r.stderr or r.stdout).strip()[-500:]}")
    return errors


def check_smoke_sidecar() -> list:
    """The DETPU_OBS=1 smoke bench must write a metrics sidecar whose
    records carry the acceptance fields."""
    errors = []
    with tempfile.TemporaryDirectory(prefix="detpu_check_obs_") as tmp:
        side = os.path.join(tmp, "metrics.jsonl")
        env = dict(os.environ, JAX_PLATFORMS="cpu", DETPU_OBS="1",
                   DETPU_BENCH_SMOKE="1", DETPU_OBS_SIDECAR=side,
                   DETPU_BENCH_SIDECAR=os.path.join(tmp, "partial.jsonl"))
        try:
            r = subprocess.run(
                [sys.executable, os.path.join(REPO, "bench.py")],
                env=env, cwd=tmp, capture_output=True, text=True,
                timeout=1200)
        except subprocess.TimeoutExpired:
            return ["smoke bench timed out after 1200s — wedged backend or "
                    "grossly overloaded machine; re-run `DETPU_OBS=1 "
                    "DETPU_BENCH_SMOKE=1 python bench.py` to see where"]
        if r.returncode != 0:
            return [f"smoke bench failed (rc={r.returncode}): "
                    f"{(r.stderr or r.stdout).strip()[-500:]}"]
        try:
            recs = [json.loads(line) for line in open(side, encoding="utf-8")
                    if line.strip()]
        except (OSError, json.JSONDecodeError) as e:
            return [f"metrics sidecar unreadable: {e}"]
        steps = [x for x in recs if x.get("section") == "step_metrics"]
        if not steps:
            errors.append("sidecar has no step_metrics record")
        for field in REQUIRED_METRIC_FIELDS:
            if not any(field in x.get("metrics", {}) for x in steps):
                errors.append(f"no step_metrics record carries {field!r}")
        counter_recs = [x for x in recs if x.get("section") == "counters"]
        if not any("recompiles" in x.get("counters", {})
                   for x in counter_recs):
            errors.append("sidecar has no recompile count")
    return errors


def main() -> int:
    errors = check_import()
    if not errors:  # a broken import would make the bench check noise
        errors += check_smoke_sidecar()
    for e in errors:
        print(f"check_obs: {e}", file=sys.stderr)
    if not errors:
        print("check_obs: OK (obs imports cleanly; DETPU_OBS=1 smoke bench "
              "emits a parseable metrics sidecar with "
              f"{', '.join(REQUIRED_METRIC_FIELDS)} + recompiles)")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
